// Native WAL key-value engine behind the Store actor.
//
// TPU-native counterpart of the reference's RocksDB storage layer
// (reference store/src/lib.rs:15-92, store/Cargo.toml:9).  RocksDB is a
// poor fit here: the consensus store holds kilobyte-scale protocol
// objects with a working set that always fits in memory, and the only
// durability requirement is crash-recovery replay (SURVEY.md §5 "the
// store IS the checkpoint").  So the engine is an append-only WAL with
// an in-memory open-addressing index — O(1) gets with zero read
// amplification, one sequential write per put.
//
// WAL record format (little-endian), shared bit-for-bit with the Python
// WalEngine (hotstuff_tpu/store/engine.py) so either implementation can
// recover the other's files:
//   u32 klen | u32 vlen | key bytes | value bytes
//   vlen == 0xFFFFFFFF marks a tombstone (delete; no value bytes).
//
// Durability modes (hs_open's fsync_mode):
//   0 = flush to the OS page cache per put (survives process death)
//   1 = fdatasync per put               (survives OS/power loss)
//   2 = fdatasync on close only
//
// Compaction: on open, after replay, if the log carries more than
// COMPACT_RATIO x live bytes (and is at least COMPACT_MIN bytes), live
// records are rewritten to a fresh log which atomically replaces the old
// one — bounding disk growth across restarts without a background
// thread racing the single writer.
//
// C ABI (consumed via ctypes from hotstuff_tpu/store/native.py):
//   hs_open / hs_put / hs_get / hs_delete / hs_keys_blob / hs_count /
//   hs_compact / hs_wal_bytes / hs_free / hs_close

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint32_t kTombstone = 0xFFFFFFFFu;
constexpr double kCompactRatio = 2.0;
constexpr uint64_t kCompactMin = 1 << 20;  // 1 MiB

struct Engine {
  std::string dir;
  std::string wal_path;
  int fd = -1;
  int fsync_mode = 0;
  uint64_t wal_bytes = 0;   // current log size
  uint64_t live_bytes = 0;  // bytes a compacted log would occupy
  std::unordered_map<std::string, std::string> index;
};

uint64_t record_size(size_t klen, size_t vlen) {
  return 8 + klen + vlen;
}

bool write_all(int fd, const uint8_t* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool append_record(Engine* e, const uint8_t* k, uint32_t klen,
                   const uint8_t* v, uint32_t vlen, bool tombstone) {
  uint8_t hdr[8];
  uint32_t vfield = tombstone ? kTombstone : vlen;
  std::memcpy(hdr, &klen, 4);
  std::memcpy(hdr + 4, &vfield, 4);
  std::vector<uint8_t> buf;
  buf.reserve(8 + klen + (tombstone ? 0 : vlen));
  buf.insert(buf.end(), hdr, hdr + 8);
  buf.insert(buf.end(), k, k + klen);
  if (!tombstone && vlen > 0) buf.insert(buf.end(), v, v + vlen);
  if (!write_all(e->fd, buf.data(), buf.size())) return false;
  e->wal_bytes += buf.size();
  if (e->fsync_mode == 1) {
    if (::fdatasync(e->fd) != 0) return false;
  }
  return true;
}

// Replay the WAL into the index; truncate any torn tail.  Returns false
// only on I/O errors (a missing file is fine).
bool replay(Engine* e) {
  FILE* f = std::fopen(e->wal_path.c_str(), "rb");
  if (f == nullptr) return errno == ENOENT;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> data(static_cast<size_t>(size));
  if (size > 0 && std::fread(data.data(), 1, data.size(), f) != data.size()) {
    std::fclose(f);
    return false;
  }
  std::fclose(f);

  size_t off = 0, n = data.size(), valid_end = 0;
  while (off + 8 <= n) {
    uint32_t klen, vfield;
    std::memcpy(&klen, data.data() + off, 4);
    std::memcpy(&vfield, data.data() + off + 4, 4);
    off += 8;
    if (vfield == kTombstone) {
      if (off + klen > n) break;  // torn tail
      std::string key(reinterpret_cast<char*>(data.data() + off), klen);
      off += klen;
      auto it = e->index.find(key);
      if (it != e->index.end()) {
        e->live_bytes -= record_size(it->first.size(), it->second.size());
        e->index.erase(it);
      }
    } else {
      if (off + klen + static_cast<uint64_t>(vfield) > n) break;  // torn tail
      std::string key(reinterpret_cast<char*>(data.data() + off), klen);
      off += klen;
      std::string val(reinterpret_cast<char*>(data.data() + off), vfield);
      off += vfield;
      auto it = e->index.find(key);
      if (it != e->index.end()) {
        e->live_bytes -= record_size(it->first.size(), it->second.size());
      }
      e->live_bytes += record_size(key.size(), val.size());
      e->index[std::move(key)] = std::move(val);
    }
    valid_end = off;
  }
  e->wal_bytes = valid_end;
  if (valid_end < n) {
    if (::truncate(e->wal_path.c_str(), static_cast<off_t>(valid_end)) != 0) {
      return false;
    }
  }
  return true;
}

// Rewrite live records to a fresh log and atomically swap it in.
bool compact(Engine* e) {
  std::string tmp = e->wal_path + ".compact";
  int tfd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (tfd < 0) return false;
  uint64_t written = 0;
  for (const auto& [key, val] : e->index) {
    uint8_t hdr[8];
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t vlen = static_cast<uint32_t>(val.size());
    std::memcpy(hdr, &klen, 4);
    std::memcpy(hdr + 4, &vlen, 4);
    if (!write_all(tfd, hdr, 8) ||
        !write_all(tfd, reinterpret_cast<const uint8_t*>(key.data()), klen) ||
        !write_all(tfd, reinterpret_cast<const uint8_t*>(val.data()), vlen)) {
      ::close(tfd);
      ::unlink(tmp.c_str());
      return false;
    }
    written += record_size(klen, vlen);
  }
  if (::fdatasync(tfd) != 0 || ::close(tfd) != 0) {
    ::unlink(tmp.c_str());
    return false;
  }
  if (e->fd >= 0) ::close(e->fd);
  if (::rename(tmp.c_str(), e->wal_path.c_str()) != 0) {
    e->fd = ::open(e->wal_path.c_str(), O_WRONLY | O_APPEND | O_CREAT, 0644);
    return false;
  }
  e->fd = ::open(e->wal_path.c_str(), O_WRONLY | O_APPEND, 0644);
  e->wal_bytes = written;
  e->live_bytes = written;
  return e->fd >= 0;
}

}  // namespace

extern "C" {

void* hs_open(const char* path, int fsync_mode) {
  auto* e = new Engine();
  e->dir = path;
  e->fsync_mode = fsync_mode;
  ::mkdir(path, 0755);  // EEXIST is fine
  e->wal_path = e->dir + "/wal.log";
  if (!replay(e)) {
    delete e;
    return nullptr;
  }
  if (e->wal_bytes >= kCompactMin &&
      static_cast<double>(e->wal_bytes) >
          kCompactRatio * static_cast<double>(e->live_bytes)) {
    if (!compact(e)) {
      delete e;
      return nullptr;
    }
  } else {
    e->fd = ::open(e->wal_path.c_str(), O_WRONLY | O_APPEND | O_CREAT, 0644);
    if (e->fd < 0) {
      delete e;
      return nullptr;
    }
  }
  return e;
}

int hs_put(void* h, const uint8_t* k, uint32_t klen, const uint8_t* v,
           uint32_t vlen) {
  auto* e = static_cast<Engine*>(h);
  if (vlen == kTombstone) return -1;  // reserved
  if (!append_record(e, k, klen, v, vlen, false)) return -1;
  std::string key(reinterpret_cast<const char*>(k), klen);
  auto it = e->index.find(key);
  if (it != e->index.end()) {
    e->live_bytes -= record_size(it->first.size(), it->second.size());
  }
  e->live_bytes += record_size(klen, vlen);
  e->index[std::move(key)].assign(reinterpret_cast<const char*>(v), vlen);
  return 0;
}

int hs_get(void* h, const uint8_t* k, uint32_t klen, uint8_t** out,
           uint32_t* outlen) {
  auto* e = static_cast<Engine*>(h);
  auto it = e->index.find(std::string(reinterpret_cast<const char*>(k), klen));
  if (it == e->index.end()) return -1;
  *outlen = static_cast<uint32_t>(it->second.size());
  *out = static_cast<uint8_t*>(std::malloc(it->second.size() ? it->second.size() : 1));
  if (*out == nullptr) return -2;
  std::memcpy(*out, it->second.data(), it->second.size());
  return 0;
}

int hs_delete(void* h, const uint8_t* k, uint32_t klen) {
  auto* e = static_cast<Engine*>(h);
  if (!append_record(e, k, klen, nullptr, 0, true)) return -1;
  std::string key(reinterpret_cast<const char*>(k), klen);
  auto it = e->index.find(key);
  if (it != e->index.end()) {
    e->live_bytes -= record_size(it->first.size(), it->second.size());
    e->index.erase(it);
  }
  return 0;
}

// All keys as one blob: u32 count | (u32 klen | key bytes)*
int hs_keys_blob(void* h, uint8_t** out, uint64_t* outlen) {
  auto* e = static_cast<Engine*>(h);
  uint64_t total = 4;
  for (const auto& [key, _] : e->index) total += 4 + key.size();
  auto* buf = static_cast<uint8_t*>(std::malloc(total));
  if (buf == nullptr) return -2;
  uint32_t count = static_cast<uint32_t>(e->index.size());
  std::memcpy(buf, &count, 4);
  uint64_t off = 4;
  for (const auto& [key, _] : e->index) {
    uint32_t klen = static_cast<uint32_t>(key.size());
    std::memcpy(buf + off, &klen, 4);
    off += 4;
    std::memcpy(buf + off, key.data(), key.size());
    off += key.size();
  }
  *out = buf;
  *outlen = total;
  return 0;
}

uint64_t hs_count(void* h) {
  return static_cast<Engine*>(h)->index.size();
}

uint64_t hs_wal_bytes(void* h) {
  return static_cast<Engine*>(h)->wal_bytes;
}

int hs_compact(void* h) {
  return compact(static_cast<Engine*>(h)) ? 0 : -1;
}

void hs_free(uint8_t* p) { std::free(p); }

void hs_close(void* h) {
  auto* e = static_cast<Engine*>(h);
  if (e->fd >= 0) {
    if (e->fsync_mode != 0) ::fdatasync(e->fd);
    ::close(e->fd);
  }
  delete e;
}

}  // extern "C"
