// Batched Ed25519 verification over the random-linear-combination
// equation — the dalek-parity CPU baseline the framework benchmarks
// against, and the CpuVerifier's fast path for QC-shaped verification.
//
// Parity target: the reference's `Signature::verify_batch`
// (crypto/src/lib.rs:213-226) delegates to ed25519-dalek's batch
// verification: sample random 128-bit z_i and accept iff
//
//     [8] ( (sum z_i s_i) B  -  sum z_i R_i  -  sum (z_i h_i) A_i ) == O
//
// where h_i = SHA-512(R_i || A_i || M_i) mod L.  One multiscalar
// multiplication (Pippenger) replaces n independent double-scalar
// multiplications.  This file implements the whole stack from the
// published math (RFC 8032 + the curve25519 51-bit-limb field
// formulation); no code is taken from dalek/ref10.
//
// Semantics notes (documented divergences, all STRICTER than dalek):
//   - non-canonical point encodings (y >= p) and non-canonical scalars
//     (s >= L) are rejected up front;
//   - acceptance is cofactored (the [8] above), matching dalek's batch
//     semantics; the per-signature OpenSSL path used for failure
//     attribution is cofactorless — honestly-generated signatures pass
//     both, and the reference itself mixes the two the same way
//     (verify_strict singles + batch QCs).
//
// API (ctypes, GIL released for the whole call):
//   hs_ed25519_batch_verify(msgs, pks, sigs, n, shared_msg) -> 1/0/-1
//     msgs: n*32 bytes (or 32 bytes if shared_msg), pks n*32, sigs n*64.
//     1 = every signature valid; 0 = batch rejected; -1 = malformed
//     input (caller should fall back to per-item attribution).

#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <sys/random.h>

// ---------------------------------------------------------------- SHA-512
// FIPS 180-4, written out directly from the standard.

namespace {

struct Sha512 {
  uint64_t h[8];
  uint8_t buf[128];
  uint64_t len_lo;  // total bytes
  size_t fill;

  Sha512() { init(); }

  void init() {
    static const uint64_t iv[8] = {
        0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
        0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
        0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
    memcpy(h, iv, sizeof h);
    len_lo = 0;
    fill = 0;
  }

  static uint64_t rotr(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

  void block(const uint8_t* p) {
    static const uint64_t K[80] = {
        0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
        0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
        0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
        0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
        0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
        0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
        0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
        0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
        0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
        0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
        0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
        0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
        0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
        0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
        0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
        0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
        0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
        0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
        0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
        0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
        0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
        0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
        0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
        0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
        0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
        0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
        0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};
    uint64_t w[80];
    for (int i = 0; i < 16; i++) {
      w[i] = 0;
      for (int j = 0; j < 8; j++) w[i] = (w[i] << 8) | p[i * 8 + j];
    }
    for (int i = 16; i < 80; i++) {
      uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
      uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
      uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
      uint64_t ch = (e & f) ^ (~e & g);
      uint64_t t1 = hh + S1 + ch + K[i] + w[i];
      uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
      uint64_t mj = (a & b) ^ (a & c) ^ (b & c);
      uint64_t t2 = S0 + mj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    len_lo += n;
    while (n) {
      size_t take = 128 - fill;
      if (take > n) take = n;
      memcpy(buf + fill, p, take);
      fill += take;
      p += take;
      n -= take;
      if (fill == 128) {
        block(buf);
        fill = 0;
      }
    }
  }

  void final(uint8_t out[64]) {
    uint64_t bits = len_lo * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (fill != 112) update(&z, 1);
    uint8_t lenb[16] = {0};
    for (int i = 0; i < 8; i++) lenb[15 - i] = (uint8_t)(bits >> (8 * i));
    update(lenb, 16);
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < 8; j++) out[i * 8 + j] = (uint8_t)(h[i] >> (56 - 8 * j));
  }
};

// ------------------------------------------------------- field mod 2^255-19
// 5 x 51-bit limbs; products via unsigned __int128.

typedef unsigned __int128 u128;

struct fe {
  uint64_t v[5];
};

static const uint64_t MASK51 = (1ULL << 51) - 1;

static const fe FE_D = {{0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL, 0x739c663a03cbbULL, 0x52036cee2b6ffULL}};
static const fe FE_2D = {{0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL, 0x6738cc7407977ULL, 0x2406d9dc56dffULL}};
static const fe FE_SQRTM1 = {{0x61b274a0ea0b0ULL, 0xd5a5fc8f189dULL, 0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL, 0x2b8324804fc1dULL}};
static const fe FE_BX = {{0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL, 0x1ff60527118feULL, 0x216936d3cd6e5ULL}};
static const fe FE_BY = {{0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL, 0x3333333333333ULL, 0x6666666666666ULL}};

static fe fe_zero() { return fe{{0, 0, 0, 0, 0}}; }
static fe fe_one() { return fe{{1, 0, 0, 0, 0}}; }

static fe fe_add(const fe& a, const fe& b) {
  fe r;
  for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
  return r;
}

// a - b, biased by 2p so limbs never go negative (inputs reduced-ish)
static fe fe_sub(const fe& a, const fe& b) {
  fe r;
  r.v[0] = a.v[0] + 0xFFFFFFFFFFFDAULL - b.v[0];
  r.v[1] = a.v[1] + 0xFFFFFFFFFFFFEULL - b.v[1];
  r.v[2] = a.v[2] + 0xFFFFFFFFFFFFEULL - b.v[2];
  r.v[3] = a.v[3] + 0xFFFFFFFFFFFFEULL - b.v[3];
  r.v[4] = a.v[4] + 0xFFFFFFFFFFFFEULL - b.v[4];
  return r;
}

static void fe_carry(fe& r) {
  // one full carry chain: limbs back under 2^51 (+ epsilon)
  uint64_t c;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
  c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
  c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
  c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
  c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += c * 19;
  c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
}

static fe fe_mul(const fe& f, const fe& g) {
  u128 f0 = f.v[0], f1 = f.v[1], f2 = f.v[2], f3 = f.v[3], f4 = f.v[4];
  uint64_t g0 = g.v[0], g1 = g.v[1], g2 = g.v[2], g3 = g.v[3], g4 = g.v[4];
  uint64_t g1_19 = g1 * 19, g2_19 = g2 * 19, g3_19 = g3 * 19, g4_19 = g4 * 19;

  u128 r0 = f0 * g0 + f1 * g4_19 + f2 * g3_19 + f3 * g2_19 + f4 * g1_19;
  u128 r1 = f0 * g1 + f1 * g0 + f2 * g4_19 + f3 * g3_19 + f4 * g2_19;
  u128 r2 = f0 * g2 + f1 * g1 + f2 * g0 + f3 * g4_19 + f4 * g3_19;
  u128 r3 = f0 * g3 + f1 * g2 + f2 * g1 + f3 * g0 + f4 * g4_19;
  u128 r4 = f0 * g4 + f1 * g3 + f2 * g2 + f3 * g1 + f4 * g0;

  fe out;
  uint64_t c;
  c = (uint64_t)(r0 >> 51); out.v[0] = (uint64_t)r0 & MASK51; r1 += c;
  c = (uint64_t)(r1 >> 51); out.v[1] = (uint64_t)r1 & MASK51; r2 += c;
  c = (uint64_t)(r2 >> 51); out.v[2] = (uint64_t)r2 & MASK51; r3 += c;
  c = (uint64_t)(r3 >> 51); out.v[3] = (uint64_t)r3 & MASK51; r4 += c;
  c = (uint64_t)(r4 >> 51); out.v[4] = (uint64_t)r4 & MASK51;
  out.v[0] += c * 19;
  c = out.v[0] >> 51; out.v[0] &= MASK51; out.v[1] += c;
  return out;
}

static fe fe_sq(const fe& f) { return fe_mul(f, f); }

static fe fe_frombytes(const uint8_t s[32]) {
  // little-endian, top bit masked off by the caller where relevant
  uint64_t w[4];
  for (int i = 0; i < 4; i++) {
    w[i] = 0;
    for (int j = 7; j >= 0; j--) w[i] = (w[i] << 8) | s[i * 8 + j];
  }
  fe r;
  r.v[0] = w[0] & MASK51;
  r.v[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
  r.v[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
  r.v[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
  r.v[4] = (w[3] >> 12) & MASK51;
  return r;
}

static void fe_tobytes(uint8_t s[32], const fe& a) {
  fe t = a;
  fe_carry(t);
  fe_carry(t);
  // final conditional subtraction of p
  uint64_t q = (t.v[0] + 19) >> 51;
  q = (t.v[1] + q) >> 51;
  q = (t.v[2] + q) >> 51;
  q = (t.v[3] + q) >> 51;
  q = (t.v[4] + q) >> 51;
  t.v[0] += 19 * q;
  uint64_t c;
  c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
  c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
  c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
  c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
  t.v[4] &= MASK51;
  uint64_t w0 = t.v[0] | (t.v[1] << 51);
  uint64_t w1 = (t.v[1] >> 13) | (t.v[2] << 38);
  uint64_t w2 = (t.v[2] >> 26) | (t.v[3] << 25);
  uint64_t w3 = (t.v[3] >> 39) | (t.v[4] << 12);
  uint64_t w[4] = {w0, w1, w2, w3};
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) s[i * 8 + j] = (uint8_t)(w[i] >> (8 * j));
}

static bool fe_iszero(const fe& a) {
  uint8_t s[32];
  fe_tobytes(s, a);
  uint8_t r = 0;
  for (int i = 0; i < 32; i++) r |= s[i];
  return r == 0;
}

static bool fe_isneg(const fe& a) {
  uint8_t s[32];
  fe_tobytes(s, a);
  return s[0] & 1;
}

static fe fe_neg(const fe& a) { return fe_sub(fe_zero(), a); }

// a^(2^250-1) helper chain, then finish for (p-5)/8 or p-2 exponents.
static fe fe_pow_core(const fe& z, fe& t_out_z11) {
  // standard curve25519 addition chain (public formulation)
  fe z2 = fe_sq(z);                       // 2
  fe z8 = fe_sq(fe_sq(z2));               // 8
  fe z9 = fe_mul(z, z8);                  // 9
  fe z11 = fe_mul(z2, z9);                // 11
  fe z22 = fe_sq(z11);                    // 22
  fe z_5_0 = fe_mul(z9, z22);             // 2^5 - 2^0
  fe t = fe_sq(z_5_0);
  for (int i = 0; i < 4; i++) t = fe_sq(t);
  fe z_10_0 = fe_mul(t, z_5_0);           // 2^10 - 2^0
  t = fe_sq(z_10_0);
  for (int i = 0; i < 9; i++) t = fe_sq(t);
  fe z_20_0 = fe_mul(t, z_10_0);          // 2^20 - 2^0
  t = fe_sq(z_20_0);
  for (int i = 0; i < 19; i++) t = fe_sq(t);
  fe z_40_0 = fe_mul(t, z_20_0);          // 2^40 - 2^0
  t = fe_sq(z_40_0);
  for (int i = 0; i < 9; i++) t = fe_sq(t);
  fe z_50_0 = fe_mul(t, z_10_0);          // 2^50 - 2^0
  t = fe_sq(z_50_0);
  for (int i = 0; i < 49; i++) t = fe_sq(t);
  fe z_100_0 = fe_mul(t, z_50_0);         // 2^100 - 2^0
  t = fe_sq(z_100_0);
  for (int i = 0; i < 99; i++) t = fe_sq(t);
  fe z_200_0 = fe_mul(t, z_100_0);        // 2^200 - 2^0
  t = fe_sq(z_200_0);
  for (int i = 0; i < 49; i++) t = fe_sq(t);
  fe z_250_0 = fe_mul(t, z_50_0);         // 2^250 - 2^0
  t_out_z11 = z11;
  return z_250_0;
}

static fe fe_pow22523(const fe& z) {
  // z^((p-5)/8) = z^(2^252 - 3)
  fe z11;
  fe t = fe_pow_core(z, z11);  // 2^250 - 1
  t = fe_sq(t);
  t = fe_sq(t);                // 2^252 - 4
  return fe_mul(t, z);         // 2^252 - 3
}

// ------------------------------------------------------------ group element
// Extended coordinates (X : Y : Z : T), x = X/Z, y = Y/Z, T = XY/Z.
// Unified addition (complete for a = -1 twisted Edwards) used for both
// add and double: simple and exception-free at a ~20% doubling cost —
// acceptable, the multiscalar is bucket-add dominated.

struct ge {
  fe X, Y, Z, T;
};

static ge ge_identity() { return ge{fe_zero(), fe_one(), fe_one(), fe_zero()}; }

static ge ge_add(const ge& P, const ge& Q) {
  fe A = fe_mul(fe_sub(P.Y, P.X), fe_sub(Q.Y, Q.X));
  fe B = fe_mul(fe_add(P.Y, P.X), fe_add(Q.Y, Q.X));
  fe C = fe_mul(fe_mul(P.T, FE_2D), Q.T);
  fe D = fe_mul(fe_add(P.Z, P.Z), Q.Z);
  fe E = fe_sub(B, A);
  fe F = fe_sub(D, C);
  fe G = fe_add(D, C);
  fe H = fe_add(B, A);
  ge R;
  R.X = fe_mul(E, F);
  R.Y = fe_mul(G, H);
  R.T = fe_mul(E, H);
  R.Z = fe_mul(F, G);
  return R;
}

static ge ge_double(const ge& P) { return ge_add(P, P); }

static ge ge_neg(const ge& P) {
  return ge{fe_neg(P.X), P.Y, P.Z, fe_neg(P.T)};
}

static bool ge_is_identity(const ge& P) {
  return fe_iszero(P.X) && fe_iszero(fe_sub(P.Y, P.Z));
}

// Decompress a 32-byte point.  Rejects non-canonical y (stricter than
// dalek, see header) and invalid x^2 = (y^2-1)/(dy^2+1).
static bool ge_frombytes(ge& out, const uint8_t s[32]) {
  uint8_t yb[32];
  memcpy(yb, s, 32);
  int sign = yb[31] >> 7;
  yb[31] &= 0x7f;
  fe y = fe_frombytes(yb);
  // canonicality: re-serialize and compare
  uint8_t chk[32];
  fe_tobytes(chk, y);
  if (memcmp(chk, yb, 32) != 0) return false;

  fe y2 = fe_sq(y);
  fe u = fe_sub(y2, fe_one());           // y^2 - 1
  fe v = fe_add(fe_mul(y2, FE_D), fe_one());  // d y^2 + 1
  // x = u v^3 (u v^7)^((p-5)/8)
  fe v3 = fe_mul(fe_sq(v), v);
  fe v7 = fe_mul(fe_sq(v3), v);
  fe x = fe_mul(fe_mul(u, v3), fe_pow22523(fe_mul(u, v7)));
  fe vx2 = fe_mul(v, fe_sq(x));
  if (!fe_iszero(fe_sub(vx2, u))) {
    if (!fe_iszero(fe_add(vx2, u))) return false;
    x = fe_mul(x, FE_SQRTM1);
  }
  if (fe_iszero(x) && sign) return false;  // -0 encoding
  if (fe_isneg(x) != (bool)sign) x = fe_neg(x);
  out.X = x;
  out.Y = y;
  out.Z = fe_one();
  out.T = fe_mul(x, y);
  return true;
}

// ------------------------------------------------------------- scalars mod L
// L = 2^252 + 27742317777372353535851937790883648493.

struct sc {
  uint64_t v[4];  // little-endian
};

static const uint64_t SC_L[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                                 0x0ULL, 0x1000000000000000ULL};

static int sc_cmp_l(const uint64_t a[4]) {
  for (int i = 3; i >= 0; i--) {
    if (a[i] > SC_L[i]) return 1;
    if (a[i] < SC_L[i]) return -1;
  }
  return 0;
}

static void sc_sub_l(uint64_t a[4]) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    unsigned __int128 d = (unsigned __int128)a[i] - SC_L[i] - borrow;
    a[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
}

// reduce an n-limb (<= 8) little-endian value mod L, bit by bit from the
// top.  ~n*64 iterations of shift/compare — microseconds, and scalar
// work is noise next to the point arithmetic.
static sc sc_reduce(const uint64_t* limbs, int n) {
  uint64_t r[4] = {0, 0, 0, 0};
  for (int i = n * 64 - 1; i >= 0; i--) {
    // r = 2r + bit
    uint64_t carry = 0;
    for (int j = 0; j < 4; j++) {
      uint64_t nc = r[j] >> 63;
      r[j] = (r[j] << 1) | carry;
      carry = nc;
    }
    r[0] |= (limbs[i / 64] >> (i % 64)) & 1;
    // carry can only be set transiently if r >= 2^255; L > 2^252 keeps
    // r < 2L < 2^253 after the subtraction below, so carry stays 0.
    if (carry || sc_cmp_l(r) >= 0) sc_sub_l(r);
  }
  sc out;
  memcpy(out.v, r, sizeof r);
  return out;
}

static sc sc_frombytes64(const uint8_t s[64]) {
  uint64_t limbs[8];
  for (int i = 0; i < 8; i++) {
    limbs[i] = 0;
    for (int j = 7; j >= 0; j--) limbs[i] = (limbs[i] << 8) | s[i * 8 + j];
  }
  return sc_reduce(limbs, 8);
}

// canonical 32-byte scalar; false if s >= L
static bool sc_frombytes32_canonical(sc& out, const uint8_t s[32]) {
  for (int i = 0; i < 4; i++) {
    out.v[i] = 0;
    for (int j = 7; j >= 0; j--) out.v[i] = (out.v[i] << 8) | s[i * 8 + j];
  }
  return sc_cmp_l(out.v) < 0;
}

static sc sc_mul(const sc& a, const sc& b) {
  uint64_t prod[8] = {0};
  for (int i = 0; i < 4; i++) {
    u128 carry = 0;
    for (int j = 0; j < 4; j++) {
      u128 t = (u128)a.v[i] * b.v[j] + prod[i + j] + carry;
      prod[i + j] = (uint64_t)t;
      carry = t >> 64;
    }
    prod[i + 4] = (uint64_t)carry;
  }
  return sc_reduce(prod, 8);
}

static sc sc_add(const sc& a, const sc& b) {
  uint64_t r[5] = {0};
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 t = (u128)a.v[i] + b.v[i] + carry;
    r[i] = (uint64_t)t;
    carry = t >> 64;
  }
  r[4] = (uint64_t)carry;
  return sc_reduce(r, 5);
}

static bool sc_iszero(const sc& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

// -------------------------------------------------- multiscalar (Pippenger)

static unsigned sc_window(const sc& s, int w, int c) {
  // digit w of width c (bits [w*c, w*c+c))
  int bit = w * c;
  int limb = bit / 64, off = bit % 64;
  uint64_t d = s.v[limb] >> off;
  if (off + c > 64 && limb + 1 < 4) d |= s.v[limb + 1] << (64 - off);
  return (unsigned)(d & ((1u << c) - 1));
}

// Straus (simultaneous windows, per-point tables) for small point sets.
// Pippenger's bucket reduction costs 2*(2^c-1) adds per window whatever
// k is — for a handful of points that fixed cost dominates (a 3-point
// MSM spent ~2k adds reducing 15 buckets 64 times).  Straus instead
// pays 15 adds per point ONCE (the d*P_i table, d=1..15) and then one
// add per nonzero digit: ~253 doubles + ~74 adds per point, no
// reduction term.  In add-units: Straus(4) = 253 + 74.3k vs
// Pippenger(4) = 253 + 59.3k + 1898, so Straus wins below k ~ 127; vs
// Pippenger(6) = 258 + 42.3k + 5418 the model crossover is k ~ 169 and
// the measured one ~200-257 (head-to-head sweep, docs/ROUND5.md).
static ge ge_msm_straus(const std::vector<sc>& scalars,
                        const std::vector<ge>& points) {
  size_t k = points.size();
  std::vector<ge> table(k * 15);  // table[i*15 + (d-1)] = d * P_i
  for (size_t i = 0; i < k; i++) {
    table[i * 15] = points[i];
    for (int d = 1; d < 15; d++)
      table[i * 15 + d] = ge_add(table[i * 15 + d - 1], points[i]);
  }
  ge result = ge_identity();
  for (int w = 63; w >= 0; w--) {  // 64 4-bit windows cover bits 0..255
    if (w != 63)
      for (int i = 0; i < 4; i++) result = ge_double(result);
    for (size_t i = 0; i < k; i++) {
      unsigned d = sc_window(scalars[i], w, 4);
      if (d) result = ge_add(result, table[i * 15 + d - 1]);
    }
  }
  return result;
}

// Table-based Straus: same walk as ge_msm_straus but over caller-built
// 15-entry tables (table[d-1] = d*P), so fixed points — committee keys
// and the basepoint — can reuse PRECOMPUTED tables across calls instead
// of paying decompression + 15 table adds per verification.  In a
// QC-shaped batch every A point is a committee key; only the R points
// are per-signature.
struct StrausTable {
  ge t[15];
};

static ge ge_msm_straus_tables(const std::vector<sc>& scalars,
                               const std::vector<const StrausTable*>& tables) {
  ge result = ge_identity();
  size_t k = scalars.size();
  for (int w = 63; w >= 0; w--) {
    if (w != 63)
      for (int i = 0; i < 4; i++) result = ge_double(result);
    for (size_t i = 0; i < k; i++) {
      unsigned d = sc_window(scalars[i], w, 4);
      if (d) result = ge_add(result, tables[i]->t[d - 1]);
    }
  }
  return result;
}

static void straus_fill(StrausTable& out, const ge& P) {
  out.t[0] = P;
  for (int d = 1; d < 15; d++) out.t[d] = ge_add(out.t[d - 1], P);
}

// Committee-key table cache: pk bytes -> Straus table of the NEGATED
// point (the batch equation always subtracts A).  Entries are
// node-based (unordered_map), so held pointers stay valid across
// inserts; the map is never cleared (insertion stops at the cap
// instead) so verify threads can hold entry pointers without a lock.
struct PkTableEntry {
  StrausTable neg_table;
  bool on_curve;
};

static std::unordered_map<std::string, PkTableEntry> g_pk_tables;
static std::mutex g_pk_mu;

extern "C" int hs_ed25519_precompute(const uint8_t* pks, uint32_t n) {
  int ok = 0;
  for (uint32_t i = 0; i < n; i++) {
    std::string key(reinterpret_cast<const char*>(pks + 32 * (size_t)i), 32);
    {
      std::lock_guard<std::mutex> g(g_pk_mu);
      if (g_pk_tables.count(key)) {
        ok++;
        continue;
      }
      if (g_pk_tables.size() >= 4096) break;  // cap: skip, never clear
    }
    PkTableEntry e;
    ge A;
    e.on_curve = ge_frombytes(A, pks + 32 * (size_t)i);
    if (e.on_curve) straus_fill(e.neg_table, ge_neg(A));
    std::lock_guard<std::mutex> g(g_pk_mu);
    if (g_pk_tables.size() < 4096) {
      g_pk_tables.emplace(std::move(key), e);
      if (e.on_curve) ok++;
    }
  }
  return ok;
}

// nullptr = not cached; otherwise a stable pointer (map is node-based
// and never cleared) to the cached entry.
static const PkTableEntry* pk_table_lookup(const uint8_t pk[32]) {
  std::string key(reinterpret_cast<const char*>(pk), 32);
  std::lock_guard<std::mutex> g(g_pk_mu);
  auto it = g_pk_tables.find(key);
  return it == g_pk_tables.end() ? nullptr : &it->second;
}

// Static basepoint table (positive B — its scalar coefficient is the
// only non-negated term in the equation), built once.
static const StrausTable* basepoint_table() {
  static StrausTable tbl;
  static std::once_flag once;
  std::call_once(once, [] {
    ge B;
    B.X = FE_BX;
    B.Y = FE_BY;
    B.Z = fe_one();
    B.T = fe_mul(FE_BX, FE_BY);
    straus_fill(tbl, B);
  });
  return &tbl;
}

static ge ge_msm(const std::vector<sc>& scalars, const std::vector<ge>& points) {
  size_t k = scalars.size();
  if (k < 200) return ge_msm_straus(scalars, points);
  // Bucket thresholds from the cost model windows*(c + k + 2*2^c):
  // c=6 beats c=4 above k ~ 207 (moot — Straus owns that range) and
  // c=8 beats c=6 above k ~ 1050.  The old thresholds (c=6 from k=16)
  // made an 8-signature batch SLOWER than 6 (the n=8 step measured in
  // docs/ROUND5.md).
  int c = k < 1024 ? 6 : k < 8192 ? 8 : 10;
  int windows = (253 + c - 1) / c;
  std::vector<ge> buckets((size_t)1 << c);
  ge result = ge_identity();
  for (int w = windows - 1; w >= 0; w--) {
    for (int i = 0; i < c; i++) result = ge_double(result);
    for (auto& b : buckets) b = ge_identity();
    bool any = false;
    for (size_t i = 0; i < k; i++) {
      unsigned d = sc_window(scalars[i], w, c);
      if (d) {
        buckets[d] = ge_add(buckets[d], points[i]);
        any = true;
      }
    }
    if (!any) continue;
    ge run = ge_identity(), acc = ge_identity();
    for (int d = (1 << c) - 1; d >= 1; d--) {
      run = ge_add(run, buckets[d]);
      acc = ge_add(acc, run);
    }
    result = ge_add(result, acc);
  }
  return result;
}

// ---------------------------------------------------------------- randomness

static bool fill_random(uint8_t* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = getrandom(buf + got, n - got, 0);
    if (r <= 0) return false;
    got += (size_t)r;
  }
  return true;
}

}  // namespace

// --------------------------------------------------------------------- API

extern "C" int hs_ed25519_batch_verify(const uint8_t* msgs, uint32_t msg_len,
                                       const uint8_t* pks, const uint8_t* sigs,
                                       uint32_t n, int shared_msg) {
  if (n == 0) return 1;
  const uint32_t k_expected = 2 * n + 1;
  // Small batches run the table-based Straus MSM, which lets committee
  // keys (hs_ed25519_precompute) and the basepoint reuse precomputed
  // tables; large batches keep the Pippenger path on raw points.
  const bool small = k_expected < 200;

  std::vector<sc> scalars;
  std::vector<ge> points;                  // Pippenger path
  std::vector<const StrausTable*> tables;  // Straus path
  std::deque<StrausTable> scratch;         // owns per-call tables
  scalars.reserve(k_expected);
  if (small)
    tables.reserve(k_expected);
  else
    points.reserve(k_expected);

  std::vector<uint8_t> zbytes(16 * (size_t)n);
  if (!fill_random(zbytes.data(), zbytes.size())) return -1;

  sc b_coeff = {{0, 0, 0, 0}};
  for (uint32_t i = 0; i < n; i++) {
    const uint8_t* sig = sigs + (size_t)i * 64;
    const uint8_t* pk = pks + (size_t)i * 32;
    const uint8_t* msg = shared_msg ? msgs : msgs + (size_t)i * msg_len;

    ge R;
    if (!ge_frombytes(R, sig)) return -1;
    // A: cached committee-key table when available (skips the point
    // decompression — an Fq sqrt — and the 15 table adds)
    const PkTableEntry* cached = small ? pk_table_lookup(pk) : nullptr;
    ge A;  // set iff !cached — the cached branch only touches neg_table
    if (cached != nullptr) {
      if (!cached->on_curve) return -1;
    } else {
      if (!ge_frombytes(A, pk)) return -1;
    }
    sc s;
    if (!sc_frombytes32_canonical(s, sig + 32)) return -1;

    uint8_t h64[64];
    Sha512 hash;
    hash.update(sig, 32);
    hash.update(pk, 32);
    hash.update(msg, msg_len);
    hash.final(h64);
    sc h = sc_frombytes64(h64);

    sc z = {{0, 0, 0, 0}};
    memcpy(&z.v[0], &zbytes[16 * (size_t)i], 8);
    memcpy(&z.v[1], &zbytes[16 * (size_t)i + 8], 8);
    if (sc_iszero(z)) z.v[0] = 1;

    b_coeff = sc_add(b_coeff, sc_mul(z, s));
    if (small) {
      scratch.emplace_back();
      straus_fill(scratch.back(), ge_neg(R));
      tables.push_back(&scratch.back());
      scalars.push_back(z);
      if (cached != nullptr) {
        tables.push_back(&cached->neg_table);
      } else {
        scratch.emplace_back();
        straus_fill(scratch.back(), ge_neg(A));
        tables.push_back(&scratch.back());
      }
      scalars.push_back(sc_mul(z, h));
    } else {
      scalars.push_back(z);
      points.push_back(ge_neg(R));
      scalars.push_back(sc_mul(z, h));
      points.push_back(ge_neg(A));
    }
  }
  scalars.push_back(b_coeff);

  ge P;
  if (small) {
    tables.push_back(basepoint_table());
    P = ge_msm_straus_tables(scalars, tables);
  } else {
    ge B;
    B.X = FE_BX;
    B.Y = FE_BY;
    B.Z = fe_one();
    B.T = fe_mul(FE_BX, FE_BY);
    points.push_back(B);
    P = ge_msm(scalars, points);
  }
  // cofactored acceptance: [8]P == O
  P = ge_double(ge_double(ge_double(P)));
  return ge_is_identity(P) ? 1 : 0;
}

// Single-signature cofactored verify via the same machinery (used by
// tests to cross-check the batch path; production singles stay on the
// OpenSSL path).
extern "C" int hs_ed25519_verify_one(const uint8_t* msg, uint32_t msg_len,
                                     const uint8_t* pk, const uint8_t* sig) {
  return hs_ed25519_batch_verify(msg, msg_len, pk, sig, 1, 1);
}
