// BLS12-381 signature verification — native path.
//
// A direct C++ port of the framework's OWN Python implementation
// (hotstuff_tpu/crypto/bls/{fields,curve,pairing}.py — which is the
// correctness oracle it is tested against): same tower (Fq2 = Fq[u]/(u²+1),
// Fq6 = Fq2[v]/(v³−(u+1)), Fq12 = Fq6[w]/(w²−v)), same Jacobian-twist
// Miller loop with w³-scaled lines, same easy-part + BLS12 parameter-chain
// final exponentiation (the computed value is e(P,Q)³ — a fixed cube,
// bilinear and non-degenerate; only equalities are consumed).  Fq is
// 6×64-bit Montgomery (CIOS with unsigned __int128).
//
// Purpose: the pure-Python pairing equality costs ~40 ms — fine for one
// aggregate check per certificate, unusable for per-message
// authentication (timeout floods).  This path brings verify-one to
// ~1-2 ms.  Exposed via ctypes (hotstuff_tpu/crypto/bls/native.py) with
// graceful fallback to the Python backend.
//
// Reference boundary being accelerated: the SignatureService / verify
// path of the reference's crypto crate (crypto/src/lib.rs:186-257),
// BASELINE config 5.

#include <chrono>
#include <memory>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "bls_constants.h"

namespace {

constexpr int L = 6;  // 64-bit limbs in Fq

// ---------------------------------------------------------------- fp core
struct Fp {
  uint64_t v[L];
};

inline bool fp_is_zero(const Fp &a) {
  uint64_t acc = 0;
  for (int i = 0; i < L; i++) acc |= a.v[i];
  return acc == 0;
}

inline bool fp_eq(const Fp &a, const Fp &b) {
  uint64_t acc = 0;
  for (int i = 0; i < L; i++) acc |= a.v[i] ^ b.v[i];
  return acc == 0;
}

// a >= b on raw limb values
inline bool fp_geq(const uint64_t *a, const uint64_t *b) {
  for (int i = L - 1; i >= 0; i--) {
    if (a[i] > b[i]) return true;
    if (a[i] < b[i]) return false;
  }
  return true;  // equal
}

inline void fp_sub_raw(uint64_t *r, const uint64_t *a, const uint64_t *b) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < L; i++) {
    unsigned __int128 d =
        (unsigned __int128)a[i] - b[i] - (uint64_t)borrow;
    r[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

inline void fp_add(Fp &r, const Fp &a, const Fp &b) {
  unsigned __int128 carry = 0;
  for (int i = 0; i < L; i++) {
    unsigned __int128 s = (unsigned __int128)a.v[i] + b.v[i] + (uint64_t)carry;
    r.v[i] = (uint64_t)s;
    carry = s >> 64;
  }
  if (carry || fp_geq(r.v, BLS_Q)) fp_sub_raw(r.v, r.v, BLS_Q);
}

inline void fp_sub(Fp &r, const Fp &a, const Fp &b) {
  unsigned __int128 borrow = 0;
  uint64_t t[L];
  for (int i = 0; i < L; i++) {
    unsigned __int128 d =
        (unsigned __int128)a.v[i] - b.v[i] - (uint64_t)borrow;
    t[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {
    unsigned __int128 carry = 0;
    for (int i = 0; i < L; i++) {
      unsigned __int128 s = (unsigned __int128)t[i] + BLS_Q[i] + (uint64_t)carry;
      t[i] = (uint64_t)s;
      carry = s >> 64;
    }
  }
  std::memcpy(r.v, t, sizeof t);
}

inline void fp_neg(Fp &r, const Fp &a) {
  if (fp_is_zero(a)) {
    r = a;
    return;
  }
  fp_sub_raw(r.v, BLS_Q, a.v);
}

// Montgomery CIOS multiply: r = a*b*R^{-1} mod q
inline void fp_mul(Fp &r, const Fp &a, const Fp &b) {
  uint64_t t[L + 1] = {0};
  for (int i = 0; i < L; i++) {
    // t += a[i] * b
    unsigned __int128 carry = 0;
    for (int j = 0; j < L; j++) {
      unsigned __int128 s =
          (unsigned __int128)a.v[i] * b.v[j] + t[j] + (uint64_t)carry;
      t[j] = (uint64_t)s;
      carry = s >> 64;
    }
    uint64_t t_extra = (uint64_t)carry;
    // m = t[0] * n0 mod 2^64 ; t += m*q; t >>= 64
    uint64_t m = t[0] * BLS_N0;
    carry = 0;
    for (int j = 0; j < L; j++) {
      unsigned __int128 s =
          (unsigned __int128)m * BLS_Q[j] + t[j] + (uint64_t)carry;
      t[j] = (uint64_t)s;
      carry = s >> 64;
    }
    unsigned __int128 s = (unsigned __int128)t[L] + t_extra + (uint64_t)carry;
    // shift down one limb
    for (int j = 0; j < L - 1; j++) t[j] = t[j + 1];
    t[L - 1] = (uint64_t)s;
    t[L] = (uint64_t)(s >> 64);
  }
  // t[L] is 0 or 1; conditional subtract
  if (t[L] || fp_geq(t, BLS_Q)) fp_sub_raw(t, t, BLS_Q);
  std::memcpy(r.v, t, sizeof(uint64_t) * L);
}

inline void fp_sqr(Fp &r, const Fp &a) { fp_mul(r, a, a); }

inline void fp_set(Fp &r, const uint64_t *src) {
  std::memcpy(r.v, src, sizeof(uint64_t) * L);
}

inline Fp fp_one() {
  Fp r;
  fp_set(r, BLS_ONE_M);
  return r;
}

inline Fp fp_zero() {
  Fp r{};
  return r;
}

// pow by a little-endian limb exponent (not Montgomery exponent)
inline void fp_pow(Fp &r, const Fp &base, const uint64_t *e, int elimbs) {
  Fp acc = fp_one();
  Fp b = base;
  bool started = false;
  // MSB-first over all bits
  for (int i = elimbs - 1; i >= 0; i--) {
    for (int bit = 63; bit >= 0; bit--) {
      if (started) fp_sqr(acc, acc);
      if ((e[i] >> bit) & 1) {
        if (started)
          fp_mul(acc, acc, b);
        else {
          acc = b;
          started = true;
        }
      }
    }
  }
  r = started ? acc : fp_one();
}

inline void fp_inv(Fp &r, const Fp &a) { fp_pow(r, a, BLS_Q_M2, L); }

// canonical (non-Montgomery) value, for serialization / comparisons
inline void fp_from_mont(uint64_t out[L], const Fp &a) {
  // multiply by 1 (non-Montgomery) via CIOS == divide by R
  Fp one_raw{};
  one_raw.v[0] = 1;
  Fp t;
  fp_mul(t, a, one_raw);
  std::memcpy(out, t.v, sizeof(uint64_t) * L);
}

inline void fp_to_mont(Fp &r, const uint64_t raw[L]) {
  Fp a;
  std::memcpy(a.v, raw, sizeof(uint64_t) * L);
  Fp r2;
  fp_set(r2, BLS_R2);
  fp_mul(r, a, r2);
}

// 48-byte big-endian -> raw limbs; returns false if >= q
inline bool fp_raw_from_be48(uint64_t out[L], const uint8_t *be) {
  for (int i = 0; i < L; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | be[(L - 1 - i) * 8 + j];
    out[i] = w;
  }
  return !fp_geq(out, BLS_Q);
}

// canonical value comparison with (q-1)/2 ("is y lexicographically large")
inline bool fp_canon_gt_half(const Fp &a) {
  uint64_t raw[L];
  fp_from_mont(raw, a);
  // raw > (q-1)/2  <=>  raw >= (q-1)/2 + 1
  uint64_t half[L];
  std::memcpy(half, BLS_QM1_2, sizeof half);
  // compare raw > half
  for (int i = L - 1; i >= 0; i--) {
    if (raw[i] > half[i]) return true;
    if (raw[i] < half[i]) return false;
  }
  return false;
}

// ---------------------------------------------------------------- fp2
struct Fp2 {
  Fp c0, c1;
};

inline Fp2 fp2_zero() { return {fp_zero(), fp_zero()}; }
inline Fp2 fp2_one() { return {fp_one(), fp_zero()}; }

inline bool fp2_is_zero(const Fp2 &a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}

inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
  return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

inline void fp2_add(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  fp_add(r.c0, a.c0, b.c0);
  fp_add(r.c1, a.c1, b.c1);
}

inline void fp2_sub(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  fp_sub(r.c0, a.c0, b.c0);
  fp_sub(r.c1, a.c1, b.c1);
}

inline void fp2_neg(Fp2 &r, const Fp2 &a) {
  fp_neg(r.c0, a.c0);
  fp_neg(r.c1, a.c1);
}

inline void fp2_mul(Fp2 &r, const Fp2 &a, const Fp2 &b) {
  // Karatsuba: (a0+a1u)(b0+b1u) = a0b0 - a1b1 + ((a0+a1)(b0+b1) - a0b0 - a1b1)u
  Fp t0, t1, t2, s0, s1;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(s0, a.c0, a.c1);
  fp_add(s1, b.c0, b.c1);
  fp_mul(t2, s0, s1);
  fp_sub(r.c0, t0, t1);
  fp_sub(t2, t2, t0);
  fp_sub(r.c1, t2, t1);
}

inline void fp2_sqr(Fp2 &r, const Fp2 &a) {
  // (a+bu)^2 = (a+b)(a-b) + 2ab u
  Fp s, d, m;
  fp_add(s, a.c0, a.c1);
  fp_sub(d, a.c0, a.c1);
  fp_mul(m, a.c0, a.c1);
  fp_mul(r.c0, s, d);
  fp_add(r.c1, m, m);
}

inline void fp2_conj(Fp2 &r, const Fp2 &a) {
  r.c0 = a.c0;
  fp_neg(r.c1, a.c1);
}

inline void fp2_mul_nonres(Fp2 &r, const Fp2 &a) {
  // * (u + 1): (c0 - c1) + (c0 + c1) u
  Fp t0, t1;
  fp_sub(t0, a.c0, a.c1);
  fp_add(t1, a.c0, a.c1);
  r.c0 = t0;
  r.c1 = t1;
}

inline void fp2_inv(Fp2 &r, const Fp2 &a) {
  // 1/(a+bu) = (a - bu)/(a^2 + b^2)
  Fp n, t, inv;
  fp_sqr(n, a.c0);
  fp_sqr(t, a.c1);
  fp_add(n, n, t);
  fp_inv(inv, n);
  fp_mul(r.c0, a.c0, inv);
  Fp negb;
  fp_neg(negb, a.c1);
  fp_mul(r.c1, negb, inv);
}

inline void fp2_mul_fp(Fp2 &r, const Fp2 &a, const Fp &k) {
  fp_mul(r.c0, a.c0, k);
  fp_mul(r.c1, a.c1, k);
}

inline void fp2_pow(Fp2 &r, const Fp2 &base, const uint64_t *e, int elimbs) {
  Fp2 acc = fp2_one();
  Fp2 b = base;
  bool started = false;
  for (int i = elimbs - 1; i >= 0; i--) {
    for (int bit = 63; bit >= 0; bit--) {
      if (started) fp2_sqr(acc, acc);
      if ((e[i] >> bit) & 1) {
        if (started)
          fp2_mul(acc, acc, b);
        else {
          acc = b;
          started = true;
        }
      }
    }
  }
  r = started ? acc : fp2_one();
}

// sqrt in Fq2 (Adj/Rodríguez-Henríquez, q ≡ 3 mod 4) — port of
// fields.py::Fq2.sqrt.  Returns false if no root.
inline bool fp2_sqrt(Fp2 &r, const Fp2 &a) {
  if (fp2_is_zero(a)) {
    r = fp2_zero();
    return true;
  }
  Fp2 a1, alpha, x0;
  fp2_pow(a1, a, BLS_QM3_4, L);
  fp2_sqr(alpha, a1);
  fp2_mul(alpha, alpha, a);
  fp2_mul(x0, a1, a);
  Fp2 neg_one = fp2_one();
  fp_neg(neg_one.c0, neg_one.c0);
  if (fp2_eq(alpha, neg_one)) {
    // (-x0.c1, x0.c0)
    Fp t;
    fp_neg(t, x0.c1);
    r.c1 = x0.c0;
    r.c0 = t;
    return true;
  }
  Fp2 b, cand, chk;
  fp2_add(b, alpha, fp2_one());
  fp2_pow(b, b, BLS_QM1_2_FULL, L);
  fp2_mul(cand, b, x0);
  fp2_sqr(chk, cand);
  if (!fp2_eq(chk, a)) return false;
  r = cand;
  return true;
}

// ---------------------------------------------------------------- fp6
struct Fp6 {
  Fp2 c0, c1, c2;
};

inline Fp6 fp6_zero() { return {fp2_zero(), fp2_zero(), fp2_zero()}; }
inline Fp6 fp6_one() { return {fp2_one(), fp2_zero(), fp2_zero()}; }

inline bool fp6_eq(const Fp6 &a, const Fp6 &b) {
  return fp2_eq(a.c0, b.c0) && fp2_eq(a.c1, b.c1) && fp2_eq(a.c2, b.c2);
}

inline void fp6_add(Fp6 &r, const Fp6 &a, const Fp6 &b) {
  fp2_add(r.c0, a.c0, b.c0);
  fp2_add(r.c1, a.c1, b.c1);
  fp2_add(r.c2, a.c2, b.c2);
}

inline void fp6_sub(Fp6 &r, const Fp6 &a, const Fp6 &b) {
  fp2_sub(r.c0, a.c0, b.c0);
  fp2_sub(r.c1, a.c1, b.c1);
  fp2_sub(r.c2, a.c2, b.c2);
}

inline void fp6_neg(Fp6 &r, const Fp6 &a) {
  fp2_neg(r.c0, a.c0);
  fp2_neg(r.c1, a.c1);
  fp2_neg(r.c2, a.c2);
}

inline void fp6_mul(Fp6 &r, const Fp6 &a, const Fp6 &b) {
  // port of fields.py::Fq6.__mul__ (Karatsuba-style with nonresidue folds)
  Fp2 t0, t1, t2, s, u, c0, c1, c2;
  fp2_mul(t0, a.c0, b.c0);
  fp2_mul(t1, a.c1, b.c1);
  fp2_mul(t2, a.c2, b.c2);
  // c0 = ((a1 + a2)(b1 + b2) - t1 - t2) * nonres + t0
  fp2_add(s, a.c1, a.c2);
  fp2_add(u, b.c1, b.c2);
  fp2_mul(c0, s, u);
  fp2_sub(c0, c0, t1);
  fp2_sub(c0, c0, t2);
  fp2_mul_nonres(c0, c0);
  fp2_add(c0, c0, t0);
  // c1 = (a0 + a1)(b0 + b1) - t0 - t1 + t2 * nonres
  fp2_add(s, a.c0, a.c1);
  fp2_add(u, b.c0, b.c1);
  fp2_mul(c1, s, u);
  fp2_sub(c1, c1, t0);
  fp2_sub(c1, c1, t1);
  Fp2 t2n;
  fp2_mul_nonres(t2n, t2);
  fp2_add(c1, c1, t2n);
  // c2 = (a0 + a2)(b0 + b2) - t0 - t2 + t1
  fp2_add(s, a.c0, a.c2);
  fp2_add(u, b.c0, b.c2);
  fp2_mul(c2, s, u);
  fp2_sub(c2, c2, t0);
  fp2_sub(c2, c2, t2);
  fp2_add(c2, c2, t1);
  r.c0 = c0;
  r.c1 = c1;
  r.c2 = c2;
}

inline void fp6_mul_nonres(Fp6 &r, const Fp6 &a) {
  // * v : (c2 * (u+1), c0, c1)
  Fp2 t;
  fp2_mul_nonres(t, a.c2);
  Fp2 old0 = a.c0, old1 = a.c1;
  r.c0 = t;
  r.c1 = old0;
  r.c2 = old1;
}

inline void fp6_inv(Fp6 &r, const Fp6 &x) {
  // port of fields.py::Fq6.inverse
  Fp2 a = x.c0, b = x.c1, c = x.c2;
  Fp2 t0, t1, t2, bc, cs, as_, denom, tmp;
  fp2_sqr(t0, a);
  fp2_mul(bc, b, c);
  fp2_mul_nonres(tmp, bc);
  fp2_sub(t0, t0, tmp);  // t0 = a^2 - (b c) nonres
  fp2_sqr(cs, c);
  fp2_mul_nonres(t1, cs);
  fp2_mul(tmp, a, b);
  fp2_sub(t1, t1, tmp);  // t1 = c^2 nonres - a b
  fp2_sqr(t2, b);
  fp2_mul(as_, a, c);
  fp2_sub(t2, t2, as_);  // t2 = b^2 - a c
  // denom = a t0 + (c t1 + b t2) nonres
  Fp2 u, v;
  fp2_mul(u, c, t1);
  fp2_mul(v, b, t2);
  fp2_add(u, u, v);
  fp2_mul_nonres(u, u);
  fp2_mul(v, a, t0);
  fp2_add(denom, v, u);
  Fp2 dinv;
  fp2_inv(dinv, denom);
  fp2_mul(r.c0, t0, dinv);
  fp2_mul(r.c1, t1, dinv);
  fp2_mul(r.c2, t2, dinv);
}

// ---------------------------------------------------------------- fp12
struct Fp12 {
  Fp6 c0, c1;
};

inline Fp12 fp12_one() { return {fp6_one(), fp6_zero()}; }

inline bool fp12_eq(const Fp12 &a, const Fp12 &b) {
  return fp6_eq(a.c0, b.c0) && fp6_eq(a.c1, b.c1);
}

inline void fp12_mul(Fp12 &r, const Fp12 &a, const Fp12 &b) {
  Fp6 t0, t1, s, u, c0, c1;
  fp6_mul(t0, a.c0, b.c0);
  fp6_mul(t1, a.c1, b.c1);
  Fp6 t1n;
  fp6_mul_nonres(t1n, t1);
  fp6_add(c0, t0, t1n);
  fp6_add(s, a.c0, a.c1);
  fp6_add(u, b.c0, b.c1);
  fp6_mul(c1, s, u);
  fp6_sub(c1, c1, t0);
  fp6_sub(c1, c1, t1);
  r.c0 = c0;
  r.c1 = c1;
}

inline void fp12_sqr(Fp12 &r, const Fp12 &a) {
  // complex squaring (port of fields.py::Fq12.square)
  Fp6 t, m, s, u;
  fp6_mul(t, a.c0, a.c1);
  fp6_add(s, a.c0, a.c1);
  Fp6 c1n;
  fp6_mul_nonres(c1n, a.c1);
  fp6_add(u, a.c0, c1n);
  fp6_mul(m, s, u);
  fp6_sub(m, m, t);
  Fp6 tn;
  fp6_mul_nonres(tn, t);
  fp6_sub(r.c0, m, tn);
  fp6_add(r.c1, t, t);
}

inline void fp12_conj(Fp12 &r, const Fp12 &a) {
  r.c0 = a.c0;
  fp6_neg(r.c1, a.c1);
}

inline void fp12_inv(Fp12 &r, const Fp12 &a) {
  // port of fields.py::Fq12.inverse
  Fp6 t0, t1, denom, dinv;
  fp6_mul(t0, a.c0, a.c0);
  fp6_mul(t1, a.c1, a.c1);
  fp6_mul_nonres(t1, t1);
  fp6_sub(denom, t0, t1);
  fp6_inv(dinv, denom);
  fp6_mul(r.c0, a.c0, dinv);
  Fp6 n;
  fp6_neg(n, a.c1);
  fp6_mul(r.c1, n, dinv);
}

inline Fp2 frob_coeff(const uint64_t *c0m, const uint64_t *c1m) {
  Fp2 r;
  fp_set(r.c0, c0m);
  fp_set(r.c1, c1m);
  return r;
}

inline void fp12_frobenius(Fp12 &r, const Fp12 &a) {
  // one application of x -> x^q (port of fields.py::Fq12._frobenius_once)
  Fp2 f6c1 = frob_coeff(BLS_FROB6_C1_C0_M, BLS_FROB6_C1_C1_M);
  Fp2 f6c2 = frob_coeff(BLS_FROB6_C2_C0_M, BLS_FROB6_C2_C1_M);
  Fp2 f12 = frob_coeff(BLS_FROB12_C1_C0_M, BLS_FROB12_C1_C1_M);
  Fp6 c0, c1;
  fp2_conj(c0.c0, a.c0.c0);
  fp2_conj(c0.c1, a.c0.c1);
  fp2_mul(c0.c1, c0.c1, f6c1);
  fp2_conj(c0.c2, a.c0.c2);
  fp2_mul(c0.c2, c0.c2, f6c2);
  fp2_conj(c1.c0, a.c1.c0);
  fp2_mul(c1.c0, c1.c0, f12);
  fp2_conj(c1.c1, a.c1.c1);
  fp2_mul(c1.c1, c1.c1, f6c1);
  fp2_mul(c1.c1, c1.c1, f12);
  fp2_conj(c1.c2, a.c1.c2);
  fp2_mul(c1.c2, c1.c2, f6c2);
  fp2_mul(c1.c2, c1.c2, f12);
  r.c0 = c0;
  r.c1 = c1;
}

inline void fp12_cyclotomic_sqr(Fp12 &r, const Fp12 &f) {
  // Granger-Scott (port of fields.py::Fq12.cyclotomic_square)
  Fp2 z0 = f.c0.c0, z4 = f.c0.c1, z3 = f.c0.c2;
  Fp2 z2 = f.c1.c0, z1 = f.c1.c1, z5 = f.c1.c2;
  auto fp4_sq = [](Fp2 &o0, Fp2 &o1, const Fp2 &a0, const Fp2 &a1) {
    Fp2 t, s, u, sq;
    fp2_mul(t, a0, a1);
    fp2_add(s, a0, a1);
    fp2_mul_nonres(u, a1);
    fp2_add(u, a0, u);
    fp2_mul(sq, s, u);
    fp2_sub(sq, sq, t);
    Fp2 tn;
    fp2_mul_nonres(tn, t);
    fp2_sub(o0, sq, tn);
    fp2_add(o1, t, t);
  };
  Fp2 t0, t1, t2, t3, t4, t5;
  fp4_sq(t0, t1, z0, z1);
  fp4_sq(t2, t3, z2, z3);
  fp4_sq(t4, t5, z4, z5);
  auto three_minus_two = [](Fp2 &out, const Fp2 &t, const Fp2 &z) {
    // out = t + 2*(t - z)
    Fp2 d;
    fp2_sub(d, t, z);
    fp2_add(d, d, d);
    fp2_add(out, t, d);
  };
  auto three_plus_two = [](Fp2 &out, const Fp2 &t, const Fp2 &z) {
    // out = t + 2*(t + z)
    Fp2 d;
    fp2_add(d, t, z);
    fp2_add(d, d, d);
    fp2_add(out, t, d);
  };
  Fp2 nz0, nz1, nz2, nz3, nz4, nz5, nrt5;
  three_minus_two(nz0, t0, z0);
  three_plus_two(nz1, t1, z1);
  fp2_mul_nonres(nrt5, t5);
  three_plus_two(nz2, nrt5, z2);
  three_minus_two(nz3, t4, z3);
  three_minus_two(nz4, t2, z4);
  three_plus_two(nz5, t3, z5);
  r.c0.c0 = nz0;
  r.c0.c1 = nz4;
  r.c0.c2 = nz3;
  r.c1.c0 = nz2;
  r.c1.c1 = nz1;
  r.c1.c2 = nz5;
}

// ------------------------------------------------------------- G1 points
struct G1 {
  Fp x, y;  // affine, Montgomery form
  bool inf;
};

struct G1Jac {
  Fp x, y, z;  // z == 0 -> infinity
};

inline G1Jac g1_to_jac(const G1 &p) {
  if (p.inf) return {fp_one(), fp_one(), fp_zero()};
  return {p.x, p.y, fp_one()};
}

inline void g1_jac_dbl(G1Jac &r, const G1Jac &p) {
  if (fp_is_zero(p.z) || fp_is_zero(p.y)) {
    r = {fp_one(), fp_one(), fp_zero()};
    if (fp_is_zero(p.z)) r = p;
    return;
  }
  // dbl-2009-l (port of curve.py::_jac_double)
  Fp A, B, C, t, D, E, F, X3, Y3, Z3;
  fp_sqr(A, p.x);
  fp_sqr(B, p.y);
  fp_sqr(C, B);
  fp_add(t, p.x, B);
  fp_sqr(t, t);
  fp_sub(t, t, A);
  fp_sub(t, t, C);
  fp_add(D, t, t);
  fp_add(E, A, A);
  fp_add(E, E, A);
  fp_sqr(F, E);
  fp_sub(X3, F, D);
  fp_sub(X3, X3, D);
  Fp c8;
  fp_add(c8, C, C);
  fp_add(c8, c8, c8);
  fp_add(c8, c8, c8);
  fp_sub(t, D, X3);
  fp_mul(Y3, E, t);
  fp_sub(Y3, Y3, c8);
  fp_mul(Z3, p.y, p.z);
  fp_add(Z3, Z3, Z3);
  r = {X3, Y3, Z3};
}

inline void g1_jac_add(G1Jac &r, const G1Jac &p, const G1Jac &q) {
  if (fp_is_zero(p.z)) {
    r = q;
    return;
  }
  if (fp_is_zero(q.z)) {
    r = p;
    return;
  }
  // add-2007-bl (port of curve.py::_jac_add)
  Fp Z1Z1, Z2Z2, U1, U2, S1, S2, H, rr, I, J, V, X3, Y3, Z3, t;
  fp_sqr(Z1Z1, p.z);
  fp_sqr(Z2Z2, q.z);
  fp_mul(U1, p.x, Z2Z2);
  fp_mul(U2, q.x, Z1Z1);
  fp_mul(S1, p.y, q.z);
  fp_mul(S1, S1, Z2Z2);
  fp_mul(S2, q.y, p.z);
  fp_mul(S2, S2, Z1Z1);
  fp_sub(H, U2, U1);
  fp_sub(rr, S2, S1);
  if (fp_is_zero(H)) {
    if (fp_is_zero(rr)) {
      g1_jac_dbl(r, p);
      return;
    }
    r = {fp_one(), fp_one(), fp_zero()};
    return;
  }
  fp_add(I, H, H);
  fp_sqr(I, I);
  fp_mul(J, H, I);
  fp_add(rr, rr, rr);
  fp_mul(V, U1, I);
  fp_sqr(X3, rr);
  fp_sub(X3, X3, J);
  fp_sub(X3, X3, V);
  fp_sub(X3, X3, V);
  fp_sub(t, V, X3);
  fp_mul(Y3, rr, t);
  Fp S1J;
  fp_mul(S1J, S1, J);
  fp_sub(Y3, Y3, S1J);
  fp_sub(Y3, Y3, S1J);
  fp_add(Z3, p.z, q.z);
  fp_sqr(Z3, Z3);
  fp_sub(Z3, Z3, Z1Z1);
  fp_sub(Z3, Z3, Z2Z2);
  fp_mul(Z3, Z3, H);
  r = {X3, Y3, Z3};
}

inline void g1_jac_mul_jacbase(G1Jac &r, const G1Jac &b, const uint64_t *k,
                               int klimbs) {
  // Jacobian-base ladder: the membership test chains two ladders and
  // normalizing between them would cost a full Fermat inversion
  G1Jac acc = {fp_one(), fp_one(), fp_zero()};
  bool started = false;
  for (int i = klimbs - 1; i >= 0; i--) {
    for (int bit = 63; bit >= 0; bit--) {
      if (started) g1_jac_dbl(acc, acc);
      if ((k[i] >> bit) & 1) {
        g1_jac_add(acc, acc, b);
        started = true;
      }
    }
  }
  r = acc;
}

inline void g1_jac_mul(G1Jac &r, const G1 &base, const uint64_t *k, int klimbs) {
  g1_jac_mul_jacbase(r, g1_to_jac(base), k, klimbs);
}

inline G1 g1_from_jac(const G1Jac &p) {
  if (fp_is_zero(p.z)) return {fp_zero(), fp_zero(), true};
  Fp zi, zi2, zi3;
  fp_inv(zi, p.z);
  fp_sqr(zi2, zi);
  fp_mul(zi3, zi2, zi);
  G1 r;
  fp_mul(r.x, p.x, zi2);
  fp_mul(r.y, p.y, zi3);
  r.inf = false;
  return r;
}

// Full r-order ladder membership (the oracle the endomorphism test is
// parity-pinned against in tests; ~255 doubles + ~127 adds).
inline bool g1_in_subgroup_ladder(const G1 &p) {
  if (p.inf) return true;
  G1Jac t;
  g1_jac_mul(t, p, BLS_ORDER, 4);
  return fp_is_zero(t.z);
}

// GLV-endomorphism membership test: P in G1  <=>  phi(P) == -[x^2]P,
// where phi(x,y) = (beta*x, y) with beta the cube root of unity whose
// G1 eigenvalue is -x^2 mod r (x = the BLS parameter; beta derived
// from the framework's Python field oracle — see bls_constants.h).
// On G1 the identity holds because phi acts as an eigenvalue; for the
// cofactor torsion it fails (checked against the r-ladder oracle over
// raw curve / pure-cofactor / mixed / order-3 points — 3 divides the
// cofactor but x^2+1 = 2 mod 3, so order-3 components are rejected).
// Cost: two sparse |x|-ladders (~64 doubles + ~6 adds each) + 3 muls,
// vs the 255-bit order ladder — measured ~3x faster, and it runs per
// SIGNATURE in the distinct-digest storm path.
inline bool g1_in_subgroup(const G1 &p) {
  if (p.inf) return true;
  G1Jac q1;
  g1_jac_mul(q1, p, &BLS_X_ABS, 1);  // [|x|]P
  if (fp_is_zero(q1.z)) return false;  // ord(P) | |x|: phi(P) != O
  G1Jac q2;
  // chain in Jacobian coords — normalizing q1 would cost a Fermat
  // inversion, ~a third ladder's worth, per signature
  g1_jac_mul_jacbase(q2, q1, &BLS_X_ABS, 1);  // [x^2]P (x neg, squared)
  if (fp_is_zero(q2.z)) return false;
  // phi(P) == -q2, compared in Jacobian coords (no inversion):
  // beta*px * Z^2 == X2  and  py * Z^3 == -Y2
  Fp beta;
  fp_set(beta, BLS_BETA_TEST_M);
  Fp bx;
  fp_mul(bx, p.x, beta);
  Fp z2, z3, lhs;
  fp_sqr(z2, q2.z);
  fp_mul(z3, z2, q2.z);
  fp_mul(lhs, bx, z2);
  if (!fp_eq(lhs, q2.x)) return false;
  fp_mul(lhs, p.y, z3);
  Fp negy;
  fp_neg(negy, q2.y);
  return fp_eq(lhs, negy);
}

// decompress a 48-byte zcash-format G1 point; subgroup check optional
inline bool g1_from_bytes(G1 &out, const uint8_t *data, bool subgroup) {
  if (!(data[0] & 0x80)) return false;
  if (data[0] & 0x40) {  // infinity
    if (data[0] != 0xc0) return false;
    for (int i = 1; i < 48; i++)
      if (data[i]) return false;
    out = {fp_zero(), fp_zero(), true};
    return true;
  }
  bool sign = data[0] & 0x20;
  uint8_t buf[48];
  std::memcpy(buf, data, 48);
  buf[0] &= 0x1f;
  uint64_t raw[L];
  if (!fp_raw_from_be48(raw, buf)) return false;
  Fp x;
  fp_to_mont(x, raw);
  // y^2 = x^3 + 4
  Fp y2, t, b;
  fp_sqr(t, x);
  fp_mul(y2, t, x);
  fp_set(b, BLS_G1B_M);
  fp_add(y2, y2, b);
  Fp y;
  fp_pow(y, y2, BLS_QP1_4, L);
  Fp chk;
  fp_sqr(chk, y);
  if (!fp_eq(chk, y2)) return false;
  if (fp_canon_gt_half(y) != sign) fp_neg(y, y);
  out = {x, y, false};
  if (subgroup && !g1_in_subgroup(out)) return false;
  return true;
}

// ------------------------------------------------------------- G2 points
struct G2 {
  Fp2 x, y;
  bool inf;
};

struct G2Jac {
  Fp2 x, y, z;
};

inline void g2_jac_dbl(G2Jac &r, const G2Jac &p) {
  if (fp2_is_zero(p.z) || fp2_is_zero(p.y)) {
    if (fp2_is_zero(p.z)) {
      r = p;
      return;
    }
    r = {fp2_one(), fp2_one(), fp2_zero()};
    return;
  }
  Fp2 A, B, C, t, D, E, F, X3, Y3, Z3;
  fp2_sqr(A, p.x);
  fp2_sqr(B, p.y);
  fp2_sqr(C, B);
  fp2_add(t, p.x, B);
  fp2_sqr(t, t);
  fp2_sub(t, t, A);
  fp2_sub(t, t, C);
  fp2_add(D, t, t);
  fp2_add(E, A, A);
  fp2_add(E, E, A);
  fp2_sqr(F, E);
  fp2_sub(X3, F, D);
  fp2_sub(X3, X3, D);
  Fp2 c8;
  fp2_add(c8, C, C);
  fp2_add(c8, c8, c8);
  fp2_add(c8, c8, c8);
  fp2_sub(t, D, X3);
  fp2_mul(Y3, E, t);
  fp2_sub(Y3, Y3, c8);
  fp2_mul(Z3, p.y, p.z);
  fp2_add(Z3, Z3, Z3);
  r = {X3, Y3, Z3};
}

inline void g2_jac_add(G2Jac &r, const G2Jac &p, const G2Jac &q) {
  if (fp2_is_zero(p.z)) {
    r = q;
    return;
  }
  if (fp2_is_zero(q.z)) {
    r = p;
    return;
  }
  Fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, H, rr, I, J, V, X3, Y3, Z3, t;
  fp2_sqr(Z1Z1, p.z);
  fp2_sqr(Z2Z2, q.z);
  fp2_mul(U1, p.x, Z2Z2);
  fp2_mul(U2, q.x, Z1Z1);
  fp2_mul(S1, p.y, q.z);
  fp2_mul(S1, S1, Z2Z2);
  fp2_mul(S2, q.y, p.z);
  fp2_mul(S2, S2, Z1Z1);
  fp2_sub(H, U2, U1);
  fp2_sub(rr, S2, S1);
  if (fp2_is_zero(H)) {
    if (fp2_is_zero(rr)) {
      g2_jac_dbl(r, p);
      return;
    }
    r = {fp2_one(), fp2_one(), fp2_zero()};
    return;
  }
  fp2_add(I, H, H);
  fp2_sqr(I, I);
  fp2_mul(J, H, I);
  fp2_add(rr, rr, rr);
  fp2_mul(V, U1, I);
  fp2_sqr(X3, rr);
  fp2_sub(X3, X3, J);
  fp2_sub(X3, X3, V);
  fp2_sub(X3, X3, V);
  fp2_sub(t, V, X3);
  fp2_mul(Y3, rr, t);
  Fp2 S1J;
  fp2_mul(S1J, S1, J);
  fp2_sub(Y3, Y3, S1J);
  fp2_sub(Y3, Y3, S1J);
  fp2_add(Z3, p.z, q.z);
  fp2_sqr(Z3, Z3);
  fp2_sub(Z3, Z3, Z1Z1);
  fp2_sub(Z3, Z3, Z2Z2);
  fp2_mul(Z3, Z3, H);
  r = {X3, Y3, Z3};
}

inline void g2_jac_mul(G2Jac &r, const G2 &base, const uint64_t *k, int klimbs) {
  G2Jac acc = {fp2_one(), fp2_one(), fp2_zero()};
  G2Jac b = {base.x, base.y, fp2_one()};
  bool started = false;
  for (int i = klimbs - 1; i >= 0; i--) {
    for (int bit = 63; bit >= 0; bit--) {
      if (started) g2_jac_dbl(acc, acc);
      if ((k[i] >> bit) & 1) {
        g2_jac_add(acc, acc, b);
        started = true;
      }
    }
  }
  r = acc;
}

inline bool g2_in_subgroup(const G2 &p) {
  if (p.inf) return true;
  G2Jac t;
  g2_jac_mul(t, p, BLS_ORDER, 4);
  return fp2_is_zero(t.z);
}

// "lexicographically large" for Fq2: c1 > half, or c1 == 0 and c0 > half
inline bool fp2_canon_gt_half(const Fp2 &a) {
  uint64_t raw1[L];
  fp_from_mont(raw1, a.c1);
  uint64_t zero1 = 0;
  for (int i = 0; i < L; i++) zero1 |= raw1[i];
  if (zero1 != 0) return fp_canon_gt_half(a.c1);
  return fp_canon_gt_half(a.c0);
}

inline bool g2_from_bytes(G2 &out, const uint8_t *data, bool subgroup) {
  if (!(data[0] & 0x80)) return false;
  if (data[0] & 0x40) {
    if (data[0] != 0xc0) return false;
    for (int i = 1; i < 96; i++)
      if (data[i]) return false;
    out = {fp2_zero(), fp2_zero(), true};
    return true;
  }
  bool sign = data[0] & 0x20;
  uint8_t buf[48];
  std::memcpy(buf, data, 48);
  buf[0] &= 0x1f;
  uint64_t raw1[L], raw0[L];
  if (!fp_raw_from_be48(raw1, buf)) return false;      // x.c1 (first 48)
  if (!fp_raw_from_be48(raw0, data + 48)) return false;  // x.c0
  Fp2 x;
  fp_to_mont(x.c1, raw1);
  fp_to_mont(x.c0, raw0);
  // y^2 = x^3 + 4(u+1)
  Fp2 y2, t, b2;
  fp2_sqr(t, x);
  fp2_mul(y2, t, x);
  Fp four;
  fp_set(four, BLS_G1B_M);  // Montgomery 4
  b2.c0 = four;
  b2.c1 = four;
  fp2_add(y2, y2, b2);
  Fp2 y;
  if (!fp2_sqrt(y, y2)) return false;
  if (fp2_canon_gt_half(y) != sign) fp2_neg(y, y);
  out = {x, y, false};
  if (subgroup && !g2_in_subgroup(out)) return false;
  return true;
}

// ------------------------------------------------------------ Miller loop
// Port of pairing.py::miller_loop with FULL fp12 line multiplication
// (the line value a + b*v + c*v*w embedded into Fp12 — simplicity over
// the 18-mul sparse product; C is fast enough).

inline Fp12 line_to_fp12(const Fp2 &a, const Fp2 &b, const Fp2 &c) {
  Fp12 r;
  r.c0.c0 = a;
  r.c0.c1 = b;
  r.c0.c2 = fp2_zero();
  r.c1.c0 = fp2_zero();
  r.c1.c1 = c;
  r.c1.c2 = fp2_zero();
  return r;
}

inline void miller_loop(Fp12 &f_out, const G1 &p, const G2 &q) {
  if (p.inf || q.inf) {
    f_out = fp12_one();
    return;
  }
  Fp2 xq = q.x, yq = q.y;
  G2Jac T = {xq, yq, fp2_one()};
  Fp12 f = fp12_one();
  // bits of |x| MSB-first, skipping the leading 1
  bool started = false;
  for (int bit = 63; bit >= 0; bit--) {
    bool one = (BLS_X_ABS >> bit) & 1;
    if (!started) {
      if (one) started = true;
      continue;
    }
    // tangent line at T, scaled by 2YZ^3:
    //   a = 3X^3 - 2Y^2, b = -3X^2 Z^2 xP, c = 2YZ^3 yP
    Fp2 X2, Y2, Z2, Z3, X3c, la, lb, lc, t;
    fp2_sqr(X2, T.x);
    fp2_sqr(Y2, T.y);
    fp2_sqr(Z2, T.z);
    fp2_mul(Z3, T.z, Z2);
    fp2_mul(X3c, T.x, X2);
    fp2_add(la, X3c, X3c);
    fp2_add(la, la, X3c);
    fp2_sub(la, la, Y2);
    fp2_sub(la, la, Y2);
    Fp2 x2_3;
    fp2_add(x2_3, X2, X2);
    fp2_add(x2_3, x2_3, X2);
    fp2_mul(lb, x2_3, Z2);
    fp2_mul_fp(lb, lb, p.x);
    fp2_neg(lb, lb);
    fp2_add(t, T.y, T.y);
    fp2_mul(lc, t, Z3);
    fp2_mul_fp(lc, lc, p.y);
    fp12_sqr(f, f);
    Fp12 lf = line_to_fp12(la, lb, lc);
    fp12_mul(f, f, lf);
    g2_jac_dbl(T, T);
    if (one) {
      // chord through T and Q, scaled by Z^3 * D
      Fp2 n, d;
      fp2_sqr(Z2, T.z);
      fp2_mul(Z3, T.z, Z2);
      fp2_mul(n, yq, Z3);
      fp2_sub(n, n, T.y);
      fp2_mul(d, xq, Z2);
      fp2_sub(d, d, T.x);
      Fp2 yd;
      fp2_mul(la, n, T.x);
      fp2_mul(yd, T.y, d);
      fp2_sub(la, la, yd);
      fp2_mul(lb, n, Z2);
      fp2_mul_fp(lb, lb, p.x);
      fp2_neg(lb, lb);
      fp2_mul(lc, Z3, d);
      fp2_mul_fp(lc, lc, p.y);
      Fp12 lf2 = line_to_fp12(la, lb, lc);
      fp12_mul(f, f, lf2);
      G2Jac qj = {xq, yq, fp2_one()};
      g2_jac_add(T, T, qj);
    }
  }
  // X < 0: conjugate
  fp12_conj(f, f);
  f_out = f;
}

// ------------------------------------------------- prepared Miller loop
// Committee public keys are FIXED per epoch, so the G2-side work of
// every Miller loop — tangent/chord line coefficients and the T-point
// ladder — can be computed once per key and cached (the standard
// "prepared pairing" decomposition).  Evaluation then only scales each
// step's (b, c) coefficients by the G1 point's affine coordinates and
// folds the sparse line into the accumulator.  Measured on this rig it
// takes the per-entry Miller cost from ~1.5 ms to ~0.8 ms, which is
// what makes the distinct-digest TC storm target reachable
// (VERDICT r5 item 8).

struct LineCoeff {
  Fp2 a, b, c;  // unscaled: evaluation multiplies b by xP and c by yP
};

struct G2Prepared {
  bool inf = false;
  std::vector<LineCoeff> coeffs;
};

inline void g2_prepare(G2Prepared &out, const G2 &q) {
  out.inf = q.inf;
  out.coeffs.clear();
  if (q.inf) return;
  Fp2 xq = q.x, yq = q.y;
  G2Jac T = {xq, yq, fp2_one()};
  bool started = false;
  for (int bit = 63; bit >= 0; bit--) {
    bool one = (BLS_X_ABS >> bit) & 1;
    if (!started) {
      if (one) started = true;
      continue;
    }
    // tangent line at T (same algebra as miller_loop, px/py unscaled)
    Fp2 X2, Y2, Z2, Z3, X3c, t;
    LineCoeff L;
    fp2_sqr(X2, T.x);
    fp2_sqr(Y2, T.y);
    fp2_sqr(Z2, T.z);
    fp2_mul(Z3, T.z, Z2);
    fp2_mul(X3c, T.x, X2);
    fp2_add(L.a, X3c, X3c);
    fp2_add(L.a, L.a, X3c);
    fp2_sub(L.a, L.a, Y2);
    fp2_sub(L.a, L.a, Y2);
    Fp2 x2_3;
    fp2_add(x2_3, X2, X2);
    fp2_add(x2_3, x2_3, X2);
    fp2_mul(L.b, x2_3, Z2);
    fp2_neg(L.b, L.b);
    fp2_add(t, T.y, T.y);
    fp2_mul(L.c, t, Z3);
    out.coeffs.push_back(L);
    g2_jac_dbl(T, T);
    if (one) {
      // chord through T and Q
      Fp2 n, d, yd;
      LineCoeff M;
      fp2_sqr(Z2, T.z);
      fp2_mul(Z3, T.z, Z2);
      fp2_mul(n, yq, Z3);
      fp2_sub(n, n, T.y);
      fp2_mul(d, xq, Z2);
      fp2_sub(d, d, T.x);
      fp2_mul(M.a, n, T.x);
      fp2_mul(yd, T.y, d);
      fp2_sub(M.a, M.a, yd);
      fp2_mul(M.b, n, Z2);
      fp2_neg(M.b, M.b);
      fp2_mul(M.c, Z3, d);
      out.coeffs.push_back(M);
      G2Jac qj = {xq, yq, fp2_one()};
      g2_jac_add(T, T, qj);
    }
  }
}

// f *= line, exploiting the line's sparsity: c0 = (a, b, 0), c1 =
// (0, c, 0).  13 fp2 multiplications instead of fp12_mul's 18.
inline void fp12_mul_by_line(Fp12 &f, const Fp2 &a, const Fp2 &b,
                             const Fp2 &c) {
  const Fp6 &f0 = f.c0;
  const Fp6 &f1 = f.c1;
  // t0 = f0 * (a, b, 0)
  Fp6 t0;
  {
    Fp2 xa, yb, zb, za, k, s, u;
    fp2_mul(xa, f0.c0, a);
    fp2_mul(yb, f0.c1, b);
    fp2_mul(zb, f0.c2, b);
    fp2_mul(za, f0.c2, a);
    fp2_add(s, f0.c0, f0.c1);
    fp2_add(u, a, b);
    fp2_mul(k, s, u);  // (x+y)(a+b)
    fp2_mul_nonres(t0.c0, zb);
    fp2_add(t0.c0, t0.c0, xa);
    fp2_sub(t0.c1, k, xa);
    fp2_sub(t0.c1, t0.c1, yb);
    fp2_add(t0.c2, za, yb);
  }
  // t1 = f1 * (0, c, 0)
  Fp6 t1;
  {
    Fp2 yc, zc, xc;
    fp2_mul(xc, f1.c0, c);
    fp2_mul(yc, f1.c1, c);
    fp2_mul(zc, f1.c2, c);
    fp2_mul_nonres(t1.c0, zc);
    t1.c1 = xc;
    t1.c2 = yc;
  }
  // c1 = (f0 + f1) * (a, b + c, 0) - t0 - t1
  Fp6 c1;
  {
    Fp6 s6;
    fp6_add(s6, f0, f1);
    Fp2 bc;
    fp2_add(bc, b, c);
    Fp2 xa, ybc, zbc, za, k, s, u;
    fp2_mul(xa, s6.c0, a);
    fp2_mul(ybc, s6.c1, bc);
    fp2_mul(zbc, s6.c2, bc);
    fp2_mul(za, s6.c2, a);
    fp2_add(s, s6.c0, s6.c1);
    fp2_add(u, a, bc);
    fp2_mul(k, s, u);
    fp6_sub(c1, fp6_zero(), t0);  // start at -t0
    Fp6 prod;
    fp2_mul_nonres(prod.c0, zbc);
    fp2_add(prod.c0, prod.c0, xa);
    fp2_sub(prod.c1, k, xa);
    fp2_sub(prod.c1, prod.c1, ybc);
    fp2_add(prod.c2, za, ybc);
    fp6_add(c1, c1, prod);
    fp6_sub(c1, c1, t1);
  }
  // c0 = t0 + nonres(t1)
  Fp6 t1n;
  fp6_mul_nonres(t1n, t1);
  fp6_add(f.c0, t0, t1n);
  f.c1 = c1;
}

inline void miller_loop_prepared(Fp12 &f_out, const G1 &p,
                                 const G2Prepared &q) {
  if (p.inf || q.inf) {
    f_out = fp12_one();
    return;
  }
  Fp12 f = fp12_one();
  size_t idx = 0;
  bool started = false;
  for (int bit = 63; bit >= 0; bit--) {
    bool one = (BLS_X_ABS >> bit) & 1;
    if (!started) {
      if (one) started = true;
      continue;
    }
    fp12_sqr(f, f);
    {
      const LineCoeff &L = q.coeffs[idx++];
      Fp2 lb, lc;
      fp2_mul_fp(lb, L.b, p.x);
      fp2_mul_fp(lc, L.c, p.y);
      fp12_mul_by_line(f, L.a, lb, lc);
    }
    if (one) {
      const LineCoeff &M = q.coeffs[idx++];
      Fp2 lb, lc;
      fp2_mul_fp(lb, M.b, p.x);
      fp2_mul_fp(lc, M.c, p.y);
      fp12_mul_by_line(f, M.a, lb, lc);
    }
  }
  fp12_conj(f, f);
  f_out = f;
}

// per-epoch cache: compressed pk bytes -> prepared line coefficients.
// Entries are shared_ptr so eviction can clear the map while another
// verifier thread (AsyncVerifyService executor) is still mid-loop on a
// previously returned entry — the in-flight reference keeps it alive
// (returning raw pointers here would be a use-after-free on eviction).
inline std::shared_ptr<const G2Prepared> g2_prepared_cached(
    const uint8_t *pk96, const G2 &q) {
  static std::unordered_map<std::string, std::shared_ptr<const G2Prepared>>
      cache;
  static std::mutex mu;
  std::string key(reinterpret_cast<const char *>(pk96), 96);
  {
    std::lock_guard<std::mutex> g(mu);
    auto it = cache.find(key);
    if (it != cache.end()) return it->second;
  }
  auto prep = std::make_shared<G2Prepared>();
  g2_prepare(*prep, q);
  {
    std::lock_guard<std::mutex> g(mu);
    auto it = cache.find(key);
    if (it != cache.end()) return it->second;
    if (cache.size() > 8192) cache.clear();  // epoch churn bound
    cache.emplace(std::move(key), prep);
  }
  return prep;
}

inline G2 g2_generator();  // defined below

inline const G2Prepared &g2_generator_prepared() {
  static G2Prepared prep;
  static std::once_flag once;
  std::call_once(once, [] { g2_prepare(prep, g2_generator()); });
  return prep;
}

// f^|x| on cyclotomic elements (Granger-Scott squarings)
inline void pow_abs_x(Fp12 &r, const Fp12 &f) {
  Fp12 acc = f;
  bool started = false;
  for (int bit = 63; bit >= 0; bit--) {
    bool one = (BLS_X_ABS >> bit) & 1;
    if (!started) {
      if (one) started = true;
      continue;
    }
    fp12_cyclotomic_sqr(acc, acc);
    if (one) fp12_mul(acc, acc, f);
  }
  r = acc;
}

inline void pow_x(Fp12 &r, const Fp12 &f) {
  Fp12 t;
  pow_abs_x(t, f);
  fp12_conj(r, t);  // X < 0: conjugate = inverse in cyclotomic subgroup
}

inline void final_exponentiation(Fp12 &r, const Fp12 &f_in) {
  // easy part: f^((q^6-1)(q^2+1))
  Fp12 fc, fi, t, f;
  fp12_conj(fc, f_in);
  fp12_inv(fi, f_in);
  fp12_mul(t, fc, fi);  // f^(q^6 - 1)
  Fp12 tf;
  fp12_frobenius(tf, t);
  fp12_frobenius(tf, tf);
  fp12_mul(f, tf, t);  // ^(q^2 + 1)
  // hard part: ^((x-1)^2 (x+q) (x^2+q^2-1)) * f^3
  Fp12 t1, t2, t3, tmp;
  pow_x(t1, f);
  fp12_conj(tmp, f);
  fp12_mul(t1, t1, tmp);  // f^(x-1)
  pow_x(tmp, t1);
  Fp12 t1c;
  fp12_conj(t1c, t1);
  fp12_mul(t1, tmp, t1c);  // ^(x-1)^2
  pow_x(t2, t1);
  fp12_frobenius(tmp, t1);
  fp12_mul(t2, t2, tmp);  // ^(x+q)
  pow_x(t3, t2);
  pow_x(t3, t3);  // ^x^2
  fp12_frobenius(tmp, t2);
  fp12_frobenius(tmp, tmp);
  fp12_mul(t3, t3, tmp);
  Fp12 t2c;
  fp12_conj(t2c, t2);
  fp12_mul(t3, t3, t2c);  // ^(x^2+q^2-1)
  Fp12 f2;
  fp12_sqr(f2, f);
  fp12_mul(f2, f2, f);  // f^3
  fp12_mul(r, t3, f2);
}

inline bool pairings_equal(const G1 &p1, const G2 &q1, const G1 &p2,
                           const G2 &q2) {
  // e(P1,Q1) == e(P2,Q2)  via  e(P1,Q1) * e(-P2,Q2) == 1
  G1 np2 = p2;
  if (!np2.inf) fp_neg(np2.y, np2.y);
  Fp12 f1, f2, f, out;
  miller_loop(f1, p1, q1);
  miller_loop(f2, np2, q2);
  fp12_mul(f, f1, f2);
  final_exponentiation(out, f);
  return fp12_eq(out, fp12_one());
}

// ---------------------------------------------------------------- SHA-256
struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len;
  size_t fill;
};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline void sha256_init(Sha256 &s) {
  static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  std::memcpy(s.h, H0, sizeof H0);
  s.len = 0;
  s.fill = 0;
}

inline void sha256_block(Sha256 &s, const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = s.h[0], b = s.h[1], c = s.h[2], d = s.h[3], e = s.h[4],
           f = s.h[5], g = s.h[6], h = s.h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t temp1 = h + S1 + ch + K256[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t temp2 = S0 + maj;
    h = g;
    g = f;
    f = e;
    e = d + temp1;
    d = c;
    c = b;
    b = a;
    a = temp1 + temp2;
  }
  s.h[0] += a;
  s.h[1] += b;
  s.h[2] += c;
  s.h[3] += d;
  s.h[4] += e;
  s.h[5] += f;
  s.h[6] += g;
  s.h[7] += h;
}

inline void sha256_update(Sha256 &s, const uint8_t *data, size_t n) {
  s.len += n;
  while (n) {
    size_t take = 64 - s.fill;
    if (take > n) take = n;
    std::memcpy(s.buf + s.fill, data, take);
    s.fill += take;
    data += take;
    n -= take;
    if (s.fill == 64) {
      sha256_block(s, s.buf);
      s.fill = 0;
    }
  }
}

inline void sha256_final(Sha256 &s, uint8_t out[32]) {
  uint64_t bitlen = s.len * 8;
  uint8_t pad = 0x80;
  sha256_update(s, &pad, 1);
  uint8_t z = 0;
  while (s.fill != 56) sha256_update(s, &z, 1);
  uint8_t lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = (uint8_t)(bitlen >> (8 * (7 - i)));
  sha256_update(s, lenb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (uint8_t)(s.h[i] >> 24);
    out[4 * i + 1] = (uint8_t)(s.h[i] >> 16);
    out[4 * i + 2] = (uint8_t)(s.h[i] >> 8);
    out[4 * i + 3] = (uint8_t)s.h[i];
  }
}

// ------------------------------------------------------------- hash_to_g1
// Port of curve.py::hash_to_g1 (framework-internal deterministic map —
// NOT RFC 9380; both sides must match bit for bit).

inline void be48_mod_q(uint64_t out[L], const uint8_t be[48]) {
  for (int i = 0; i < L; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | be[(L - 1 - i) * 8 + j];
    out[i] = w;
  }
  // value < 2^384, q ~ 2^381.6 -> at most ~6 subtractions
  while (fp_geq(out, BLS_Q)) fp_sub_raw(out, out, BLS_Q);
}

inline void hash_to_g1_base(G1 &out, const uint8_t *msg, size_t msg_len,
                            const uint8_t *dst, size_t dst_len) {
  // the pre-cofactor map: device offload clears the cofactor inside
  // its combined (weight x h_eff) ladder
  for (uint32_t counter = 0;; counter++) {
    uint8_t ctr[4] = {(uint8_t)(counter >> 24), (uint8_t)(counter >> 16),
                      (uint8_t)(counter >> 8), (uint8_t)counter};
    uint8_t h[32], h2[32];
    Sha256 s;
    sha256_init(s);
    sha256_update(s, dst, dst_len);
    sha256_update(s, ctr, 4);
    sha256_update(s, msg, msg_len);
    sha256_final(s, h);
    Sha256 s2;
    sha256_init(s2);
    const uint8_t tag[2] = {'x', '2'};
    sha256_update(s2, tag, 2);
    sha256_update(s2, h, 32);
    sha256_final(s2, h2);
    uint8_t xbe[48];
    std::memcpy(xbe, h, 32);
    std::memcpy(xbe + 32, h2, 16);
    uint64_t raw[L];
    be48_mod_q(raw, xbe);
    Fp x;
    fp_to_mont(x, raw);
    Fp y2, t, b;
    fp_sqr(t, x);
    fp_mul(y2, t, x);
    fp_set(b, BLS_G1B_M);
    fp_add(y2, y2, b);
    Fp y, chk;
    fp_pow(y, y2, BLS_QP1_4, L);
    fp_sqr(chk, y);
    if (!fp_eq(chk, y2)) continue;
    // pick the "even" root: NOT lexicographically large
    if (fp_canon_gt_half(y)) fp_neg(y, y);
    out = {x, y, false};
    return;
  }
}

inline void hash_to_g1(G1 &out, const uint8_t *msg, size_t msg_len,
                       const uint8_t *dst, size_t dst_len) {
  G1 base;
  hash_to_g1_base(base, msg, msg_len, dst, dst_len);
  G1Jac cleared;
  g1_jac_mul(cleared, base, BLS_H1, 2);
  out = g1_from_jac(cleared);
}

// Decompressed-pk cache: committee keys repeat across every verify
// call, and G2 decompression costs an Fq2 sqrt (~0.3 ms) plus an
// optional subgroup ladder.  Keyed by the raw 96 compressed bytes;
// entries are stored SUBGROUP-CHECKED so a hit satisfies the strictest
// caller.  Bounded; cleared when full (worst case = re-decompression).
struct PkCacheEntry {
  G2 point;
  bool on_curve;
  bool in_subgroup;
};

inline bool g2_from_bytes_cached(G2 &out, const uint8_t *data,
                                 bool subgroup) {
  static std::unordered_map<std::string, PkCacheEntry> cache;
  static std::mutex mu;
  std::string key(reinterpret_cast<const char *>(data), 96);
  {
    std::lock_guard<std::mutex> g(mu);
    auto it = cache.find(key);
    if (it != cache.end()) {
      const PkCacheEntry &e = it->second;
      if (!e.on_curve) return false;
      if (subgroup && !e.in_subgroup) return false;
      out = e.point;
      return true;
    }
  }
  G2 p;
  bool on_curve = g2_from_bytes(p, data, /*subgroup=*/false);
  bool in_sub = on_curve && g2_in_subgroup(p);
  {
    std::lock_guard<std::mutex> g(mu);
    if (cache.size() > 8192) cache.clear();
    cache.emplace(std::move(key), PkCacheEntry{p, on_curve, in_sub});
  }
  if (!on_curve) return false;
  if (subgroup && !in_sub) return false;
  out = p;
  return true;
}

inline G2 g2_generator() {
  G2 g;
  fp_set(g.x.c0, BLS_G2X0_M);
  fp_set(g.x.c1, BLS_G2X1_M);
  fp_set(g.y.c0, BLS_G2Y0_M);
  fp_set(g.y.c1, BLS_G2Y1_M);
  g.inf = false;
  return g;
}

}  // namespace

namespace {

// canonical compressed encodings (zcash format, matching curve.py)
inline void g1_to_bytes(uint8_t out[48], const G1 &p) {
  if (p.inf) {
    std::memset(out, 0, 48);
    out[0] = 0xc0;
    return;
  }
  uint64_t raw[L];
  fp_from_mont(raw, p.x);
  for (int i = 0; i < L; i++)
    for (int j = 0; j < 8; j++)
      out[(L - 1 - i) * 8 + j] = (uint8_t)(raw[i] >> (8 * (7 - j)));
  out[0] |= 0x80;
  if (fp_canon_gt_half(p.y)) out[0] |= 0x20;
}

// uncompressed affine (x||y, 48 B big-endian each) — the exchange
// format between this library and the TPU G1 ladder (tpu/bls.py):
// decompression/hashing happens here, scalar ladders on device, and
// the resulting points come back for the pairing product.
inline void fp_to_be48(uint8_t out[48], const Fp &a) {
  uint64_t raw[L];
  fp_from_mont(raw, a);
  for (int i = 0; i < L; i++)
    for (int j = 0; j < 8; j++)
      out[(L - 1 - i) * 8 + j] = (uint8_t)(raw[i] >> (8 * (7 - j)));
}

inline bool fp_from_be48(Fp &out, const uint8_t in[48]) {
  uint64_t raw[L];
  for (int i = 0; i < L; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[(L - 1 - i) * 8 + j];
    raw[i] = w;
  }
  if (fp_geq(raw, BLS_Q)) return false;
  fp_to_mont(out, raw);
  return true;
}

inline void g1_to_uncompressed(uint8_t out[96], const G1 &p) {
  if (p.inf) {
    std::memset(out, 0, 96);
    return;  // (0, 0) is not on the curve (b=4): unambiguous infinity
  }
  fp_to_be48(out, p.x);
  fp_to_be48(out + 48, p.y);
}

inline bool g1_from_uncompressed(G1 &out, const uint8_t in[96]) {
  bool all_zero = true;
  for (int i = 0; i < 96; i++)
    if (in[i]) {
      all_zero = false;
      break;
    }
  if (all_zero) {
    out = {fp_zero(), fp_zero(), true};
    return true;
  }
  if (!fp_from_be48(out.x, in) || !fp_from_be48(out.y, in + 48))
    return false;
  out.inf = false;
  // on-curve check: y^2 == x^3 + 4
  Fp y2, x3, b;
  fp_sqr(y2, out.y);
  fp_sqr(x3, out.x);
  fp_mul(x3, x3, out.x);
  fp_set(b, BLS_G1B_M);
  fp_add(x3, x3, b);
  return fp_eq(y2, x3);
}

}  // namespace

// ----------------------------------------------------------------- C API
extern "C" {

// Sum n compressed G1 signatures (48 B each, contiguous) into out48.
// Decompression checks on-curve only — callers subgroup-check the
// AGGREGATE (hs_bls_verify_one_ex does).  Returns 1 ok / 0 malformed.
int hs_bls_aggregate_sigs(const uint8_t *sigs, size_t n, uint8_t *out48) {
  G1Jac acc = {fp_one(), fp_one(), fp_zero()};
  for (size_t i = 0; i < n; i++) {
    G1 p;
    if (!g1_from_bytes(p, sigs + 48 * i, /*subgroup=*/false)) return 0;
    if (p.inf) continue;
    G1Jac pj = g1_to_jac(p);
    g1_jac_add(acc, acc, pj);
  }
  G1 aff = g1_from_jac(acc);
  g1_to_bytes(out48, aff);
  return 1;
}

// NOTE: a native G2 public-key aggregate was tried and REMOVED — it
// lost to summing the verifier's cached decoded Python points, because
// the native path must re-run the expensive Fq2 sqrt per key that the
// cache pays once per epoch (docs/ROUND2.md records the experiment).

// Batched distinct-message verification (the TC / view-change-storm
// shape) by the random-weight small-exponents technique:
//   e(Σ rᵢ·sigᵢ, G2) == Π e(rᵢ·H(mᵢ), pkᵢ)
// — n+1 Miller loops sharing ONE final exponentiation instead of n
// full pairing equalities.  msgs32: n contiguous 32-byte digests;
// weights16: n contiguous 16-byte little-endian nonzero random weights
// (HOST-generated — they are what makes cross-entry cancellation
// infeasible); check_pk_subgroup = 0 only for keys the caller already
// validated (committee cache).  Every signature is individually
// subgroup-checked (see the in-loop comment).  Returns 1 = every entry valid; 0 = at
// least one invalid/malformed (caller re-checks per item to pinpoint).
int hs_bls_verify_batch(const uint8_t *msgs32, const uint8_t *pks96,
                        const uint8_t *sigs48, size_t n,
                        const uint8_t *weights16, int check_pk_subgroup) {
  // check_pk_subgroup == 0 marks per-batch AGGREGATE keys (the grouped
  // TC path): they never repeat, so caching their ~20 KB prepared line
  // coefficients would only pollute (and eventually flush) the
  // committee-key cache — prepare them on the stack instead
  const bool cache_pks = check_pk_subgroup != 0;
  if (n == 0) return 0;
  static const uint8_t DST[] = "HOTSTUFF_TPU_BLS_G1";
  G1Jac sig_acc = {fp_one(), fp_one(), fp_zero()};
  Fp12 f = fp12_one();
  for (size_t i = 0; i < n; i++) {
    G2 pk;
    if (cache_pks) {
      if (!g2_from_bytes_cached(pk, pks96 + 96 * i, /*subgroup=*/true))
        return 0;
    } else {
      // one-shot aggregate keys: plain decode, no subgroup ladder (the
      // flag's contract), and no decode-cache insertion — the cached
      // path would run the ladder on every miss anyway and grow the
      // cache toward the clear() that evicts the real committee keys
      if (!g2_from_bytes(pk, pks96 + 96 * i, /*subgroup=*/false)) return 0;
    }
    if (pk.inf) return 0;
    G1 sig;
    // per-signature subgroup check: the G1 cofactor has SMALL factors
    // (3, 11, ...), so a small-order component T on one signature
    // survives the weighted-aggregate ladder whenever the random
    // weight is divisible by ord(T) (probability 1/3 for order 3) —
    // an aggregate-only check is NOT sound here, unlike the
    // shared-message path where failures fall back to per-item checks
    if (!g1_from_bytes(sig, sigs48 + 48 * i, /*subgroup=*/true)) return 0;
    if (sig.inf) return 0;
    uint64_t w[2];
    w[0] = w[1] = 0;
    for (int b = 0; b < 8; b++) {
      w[0] |= (uint64_t)weights16[16 * i + b] << (8 * b);
      w[1] |= (uint64_t)weights16[16 * i + 8 + b] << (8 * b);
    }
    if ((w[0] | w[1]) == 0) return 0;  // zero weight defeats the check
    G1Jac wsig;
    g1_jac_mul(wsig, sig, w, 2);
    g1_jac_add(sig_acc, sig_acc, wsig);
    G1 hm;
    hash_to_g1(hm, msgs32 + 32 * i, 32, DST, sizeof(DST) - 1);
    G1Jac whm_j;
    g1_jac_mul(whm_j, hm, w, 2);
    G1 whm = g1_from_jac(whm_j);
    Fp12 fi;
    // committee keys are fixed per epoch: cached line coefficients
    // halve the per-entry Miller cost
    if (cache_pks) {
      miller_loop_prepared(fi, whm, *g2_prepared_cached(pks96 + 96 * i, pk));
    } else {
      G2Prepared prep;
      g2_prepare(prep, pk);
      miller_loop_prepared(fi, whm, prep);
    }
    fp12_mul(f, f, fi);
  }
  G1 agg = g1_from_jac(sig_acc);
  if (agg.inf) return 0;  // subgroup membership: per-signature above
  fp_neg(agg.y, agg.y);
  Fp12 fs, out;
  miller_loop_prepared(fs, agg, g2_generator_prepared());
  fp12_mul(f, f, fs);
  final_exponentiation(out, f);
  return fp12_eq(out, fp12_one()) ? 1 : 0;
}

// verify sig48 (compressed G1) by pk96 (compressed G2) over msg with the
// framework's hash-to-curve + DST.  Returns 1 valid / 0 invalid.
// check_pk_subgroup = 0 skips the pk r-torsion ladder — ONLY for keys
// whose membership the caller already established (e.g. an aggregate of
// individually subgroup-checked committee keys).
int hs_bls_verify_one_ex(const uint8_t *msg, size_t msg_len,
                         const uint8_t *pk96, const uint8_t *sig48,
                         int check_pk_subgroup) {
  G2 pk;
  // check_pk_subgroup==0 callers pass per-QC AGGREGATE keys: always a
  // cache miss (pure pollution) and the miss path runs the very ladder
  // the flag skips — bypass the cache for them
  if (check_pk_subgroup != 0) {
    if (!g2_from_bytes_cached(pk, pk96, true)) return 0;
  } else {
    if (!g2_from_bytes(pk, pk96, /*subgroup=*/false)) return 0;
  }
  if (pk.inf) return 0;
  G1 sig;
  if (!g1_from_bytes(sig, sig48, /*subgroup=*/true)) return 0;
  if (sig.inf) return 0;
  static const uint8_t DST[] = "HOTSTUFF_TPU_BLS_G1";
  G1 hm;
  hash_to_g1(hm, msg, msg_len, DST, sizeof(DST) - 1);
  // e(sig, G2) == e(hm, pk) via e(sig, G2) * e(-hm, pk) == 1, with
  // cached line coefficients on both fixed-G2 sides where possible
  G1 nhm = hm;
  if (!nhm.inf) fp_neg(nhm.y, nhm.y);
  Fp12 f1, f2, f, out;
  miller_loop_prepared(f1, sig, g2_generator_prepared());
  if (check_pk_subgroup != 0) {
    miller_loop_prepared(f2, nhm, *g2_prepared_cached(pk96, pk));
  } else {
    miller_loop(f2, nhm, pk);  // aggregate pk: never cache-worthy
  }
  fp12_mul(f, f1, f2);
  final_exponentiation(out, f);
  return fp12_eq(out, fp12_one()) ? 1 : 0;
}

int hs_bls_verify_one(const uint8_t *msg, size_t msg_len, const uint8_t *pk96,
                      const uint8_t *sig48) {
  return hs_bls_verify_one_ex(msg, msg_len, pk96, sig48, 1);
}

// pairing equality on uncompressed-style operands is not exposed; the
// aggregate paths reuse hs_bls_verify_one with aggregate pk/sig bytes.

// self-test hook used by the ctypes bridge at import: e(aP, bQ) == e(abP, Q)
int hs_bls_selftest(void) {
  // generator of G1 (Montgomery constants)
  G1 g1;
  fp_set(g1.x, BLS_G1X_M);
  fp_set(g1.y, BLS_G1Y_M);
  g1.inf = false;
  G2 g2 = g2_generator();
  // 5*G1, 7*G2, 35*G1
  uint64_t k5[1] = {5}, k7[1] = {7}, k35[1] = {35};
  G1Jac j5, j35;
  g1_jac_mul(j5, g1, k5, 1);
  g1_jac_mul(j35, g1, k35, 1);
  G1 p5 = g1_from_jac(j5), p35 = g1_from_jac(j35);
  G2Jac j7;
  g2_jac_mul(j7, g2, k7, 1);
  Fp2 zi, zi2, zi3;
  fp2_inv(zi, j7.z);
  fp2_sqr(zi2, zi);
  fp2_mul(zi3, zi2, zi);
  G2 q7;
  fp2_mul(q7.x, j7.x, zi2);
  fp2_mul(q7.y, j7.y, zi3);
  q7.inf = false;
  if (!pairings_equal(p5, q7, p35, g2)) return 0;
  if (pairings_equal(p5, q7, p5, g2)) return 0;  // 5*7 != 5
  return 1;
}

// ---- TPU-offload split of the distinct-digest batch (VERDICT r5 item
// 8).  The per-entry G1 scalar ladders (signature subgroup checks,
// weight multiplications, cofactor clearing) run on the TPU
// (tpu/bls.py TpuG1ScalarMul); this library provides the host ends:
// decompression/hash-to-base out, pairing product over the returned
// points back in.

// n compressed sigs -> uncompressed affine points (on-curve check
// only; subgroup membership is the DEVICE ladder's job).  1 ok.
int hs_bls_g1_decompress_many(const uint8_t *sigs48, size_t n,
                              uint8_t *out96) {
  for (size_t i = 0; i < n; i++) {
    G1 p;
    if (!g1_from_bytes(p, sigs48 + 48 * i, /*subgroup=*/false)) return 0;
    if (p.inf) return 0;  // an infinity signature proves nothing
    g1_to_uncompressed(out96 + 96 * i, p);
  }
  return 1;
}

// n 32-byte digests -> PRE-COFACTOR hash base points (the map only).
int hs_bls_hash_base_many(const uint8_t *msgs32, size_t n,
                          uint8_t *out96) {
  static const uint8_t DST[] = "HOTSTUFF_TPU_BLS_G1";
  for (size_t i = 0; i < n; i++) {
    G1 base;
    hash_to_g1_base(base, msgs32 + 32 * i, 32, DST, sizeof(DST) - 1);
    g1_to_uncompressed(out96 + 96 * i, base);
  }
  return 1;
}

// The pairing product over externally computed points: whm96[i] must be
// (r_i * h_eff) * H_base(m_i) and agg96 the sum of r_i * sig_i, both
// uncompressed affine from the device ladder (same process — the
// caller's own arithmetic, not untrusted input; on-curve is still
// checked).  Runs G + 1 prepared Miller loops + one final exp.  1 =
// accept.
int hs_bls_verify_batch_points(const uint8_t *whm96, const uint8_t *pks96,
                               size_t n, const uint8_t *agg96,
                               int check_pk_subgroup) {
  // same cache discipline as hs_bls_verify_batch: check_pk_subgroup==0
  // marks caller-validated one-shot keys that must stay out of both the
  // decode cache and the prepared-coefficient cache
  const bool cache_pks = check_pk_subgroup != 0;
  if (n == 0) return 0;
  Fp12 f = fp12_one();
  for (size_t i = 0; i < n; i++) {
    G2 pk;
    if (cache_pks) {
      if (!g2_from_bytes_cached(pk, pks96 + 96 * i, /*subgroup=*/true))
        return 0;
    } else {
      if (!g2_from_bytes(pk, pks96 + 96 * i, /*subgroup=*/false)) return 0;
    }
    if (pk.inf) return 0;
    G1 whm;
    if (!g1_from_uncompressed(whm, whm96 + 96 * i)) return 0;
    if (whm.inf) return 0;  // zero weight/hash defeats the check
    Fp12 fi;
    if (cache_pks) {
      miller_loop_prepared(fi, whm, *g2_prepared_cached(pks96 + 96 * i, pk));
    } else {
      G2Prepared prep;
      g2_prepare(prep, pk);
      miller_loop_prepared(fi, whm, prep);
    }
    fp12_mul(f, f, fi);
  }
  G1 agg;
  if (!g1_from_uncompressed(agg, agg96)) return 0;
  if (agg.inf) return 0;
  fp_neg(agg.y, agg.y);
  Fp12 fs, out;
  miller_loop_prepared(fs, agg, g2_generator_prepared());
  fp12_mul(f, f, fs);
  final_exponentiation(out, f);
  return fp12_eq(out, fp12_one()) ? 1 : 0;
}

// Stage profiler for the distinct-digest batch path (VERDICT r4 weak
// #5 / item 8): times each per-entry stage of hs_bls_verify_batch over
// `iters` synthetic entries and writes mean nanoseconds per stage to
// out_ns[5]: [0]=sig decompress+subgroup ladder, [1]=hash_to_g1,
// [2]=128-bit G1 weight mul, [3]=miller_loop, [4]=final_exponentiation
// (one-off, NOT per entry).  Committee pks are cache-decoded once per
// epoch, so g2 decompression is not a per-entry stage.
void hs_bls_profile(int iters, double *out_ns) {
  static const uint8_t DST[] = "HOTSTUFF_TPU_BLS_G1";
  using clk = std::chrono::steady_clock;
  G1 g1;
  fp_set(g1.x, BLS_G1X_M);
  fp_set(g1.y, BLS_G1Y_M);
  g1.inf = false;
  G2 g2 = g2_generator();
  uint8_t sig48[48];
  g1_to_bytes(sig48, g1);

  auto t0 = clk::now();
  for (int i = 0; i < iters; i++) {
    G1 p;
    g1_from_bytes(p, sig48, /*subgroup=*/true);
  }
  out_ns[0] = std::chrono::duration<double, std::nano>(clk::now() - t0)
                  .count() / iters;

  t0 = clk::now();
  for (int i = 0; i < iters; i++) {
    uint8_t msg[32] = {0};
    msg[0] = (uint8_t)i;
    msg[1] = (uint8_t)(i >> 8);
    G1 hm;
    hash_to_g1(hm, msg, 32, DST, sizeof(DST) - 1);
  }
  out_ns[1] = std::chrono::duration<double, std::nano>(clk::now() - t0)
                  .count() / iters;

  uint64_t w[2] = {0x0123456789abcdefULL, 0xfedcba9876543210ULL};
  t0 = clk::now();
  for (int i = 0; i < iters; i++) {
    G1Jac r;
    w[0] ^= (uint64_t)i;
    g1_jac_mul(r, g1, w, 2);
  }
  out_ns[2] = std::chrono::duration<double, std::nano>(clk::now() - t0)
                  .count() / iters;

  // the production batch path runs the PREPARED loop (cached per-epoch
  // line coefficients) — profile that, after a one-off prepare
  G2Prepared prep;
  g2_prepare(prep, g2);
  t0 = clk::now();
  Fp12 f = fp12_one();
  for (int i = 0; i < iters; i++) {
    Fp12 fi;
    miller_loop_prepared(fi, g1, prep);
    fp12_mul(f, f, fi);
  }
  out_ns[3] = std::chrono::duration<double, std::nano>(clk::now() - t0)
                  .count() / iters;

  t0 = clk::now();
  Fp12 out;
  final_exponentiation(out, f);
  out_ns[4] = std::chrono::duration<double, std::nano>(clk::now() - t0)
                  .count();
}
}

// Membership-test parity hook (tests only): xy96 = uncompressed
// big-endian affine x||y (all-zero = infinity).  use_ladder selects
// the full r-order ladder oracle vs the production endomorphism test.
// Returns 1 in-subgroup, 0 not, -1 not on the curve.
extern "C" int hs_bls_g1_membership(const uint8_t *xy96, int use_ladder) {
  G1 p;
  if (!g1_from_uncompressed(p, xy96)) return -1;
  return (use_ladder ? g1_in_subgroup_ladder(p) : g1_in_subgroup(p)) ? 1 : 0;
}
