// Zero-copy wire→device ingest: native frame parsing straight into
// wave-shaped staging arenas (ISSUE 20).
//
// The Python hot path used to be: reactor frame → Decoder → Vote object
// → claim tuple → flatten_claims (fresh bytes per claim) → prepare
// (another copy into staging arrays). This file moves the parse+pack
// onto the native side: vote frames are validated with EXACTLY the
// bounds the Python Decoder enforces (tests/test_wire_fuzz.py holds a
// differential harness to that contract) and their digest/pk/sig
// columns are scattered straight into a ring of preallocated,
// bucket-shaped staging arenas. The async verify service then *adopts*
// an arena (NumPy frombuffer views over the columns) instead of
// flattening claim objects — see crypto/async_service.py.
//
// Wire contracts mirrored here (consensus/wire.py, scheme=ed25519):
//
//   vote frame (TAG_VOTE=1), accepted iff EXACTLY 145 bytes:
//     [u8 tag=1][32B block hash][u64 LE round]
//     [u32 LE pk_len==32][32B pk][u32 LE sig_len==64][64B sig]
//   claim digest = SHA-512(hash || round_le8)[:32]  (messages.py
//   Vote.digest) — hash and round are adjacent on the wire, so the
//   digest input is simply frame[1:41].
//
//   producer batch v2 (TAG_PRODUCER_V2=6):
//     [u8 tag=6][u8 version==2][u32 LE count, 1..512]
//     count x ([32B digest][u32 LE len<=65536][len bytes body])
//   with no trailing bytes (Decoder.finish()).
//
// Arena ring lifecycle (all transitions under one mutex — pack runs on
// the event-loop thread, recycle on verifier slot threads):
//
//   FREE --wp_seal promotes--> OPEN --wp_pack_vote fills rows-->
//   OPEN --wp_seal(n_take)--> SEALED (surplus rows move to the next
//   FREE arena, which becomes OPEN) --wp_recycle--> FREE
//
// Every arena is pre-filled with a VALID pad claim (wp_set_pad), and
// recycle/discard re-pad only the dirtied rows — so a sealed arena is
// always a full, valid, fixed-shape wave: rows [0,n) are real claims,
// rows [n,capacity) are the pad claim. Fixed-shape bucket padding
// therefore costs nothing at dispatch time.
//
// Exposed through the same dlopen handle as transport.cpp's ht_* ABI
// (both compile into libhs_transport.so).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>
#include <vector>

namespace {

// ---- SHA-512 (single block, messages <= 111 bytes) -------------------------
// The only digest this file needs is SHA-512(hash32 || round8)[:32] for
// the vote claim column — a fixed 40-byte message, so one 128-byte
// block always suffices. Verified byte-for-byte against hashlib by
// tests/test_wire_fuzz.py.

constexpr uint64_t kShaK[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

// digest of a message that fits one padded block (len <= 111)
void sha512_single_block(const uint8_t* msg, size_t len, uint8_t out[64]) {
  uint8_t block[128];
  std::memset(block, 0, sizeof block);
  std::memcpy(block, msg, len);
  block[len] = 0x80;
  uint64_t bits = (uint64_t)len * 8;
  for (int i = 0; i < 8; i++)
    block[127 - i] = (uint8_t)(bits >> (8 * i));

  uint64_t h[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                   0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                   0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                   0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    uint64_t v = 0;
    for (int b = 0; b < 8; b++) v = (v << 8) | block[i * 8 + b];
    w[i] = v;
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = h[0], b = h[1], c = h[2], d = h[3];
  uint64_t e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = hh + S1 + ch + kShaK[i] + w[i];
    uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  for (int i = 0; i < 8; i++)
    for (int b2 = 0; b2 < 8; b2++)
      out[i * 8 + b2] = (uint8_t)(h[i] >> (56 - 8 * b2));
}

// ---- wire parsing (Decoder-parity) -----------------------------------------

constexpr int kTagVote = 1;
constexpr int kTagProducerV2 = 6;
constexpr int kProducerVersion = 2;
constexpr long kMaxProducerBatch = 512;   // wire.py MAX_PRODUCER_BATCH
constexpr long kMaxPayloadBody = 65536;   // wire.py MAX_PAYLOAD_BODY
constexpr int kVoteFrameLen = 145;        // tag + <32sQI32sI64s>
constexpr int kDigSize = 32;
constexpr int kPkSize = 32;
constexpr int kSigSize = 64;

inline uint32_t le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

// Accept iff the Python Decoder (scheme=ed25519) accepts: the struct
// fast path in messages.py reads a fixed 144-byte layout after the tag
// (truncation -> CodecError), rejects pk_len/sig_len field mismatches,
// and decode_message's finish() rejects trailing bytes — net: exactly
// 145 bytes with the two length fields pinned to 32/64.
inline bool vote_ok(const uint8_t* frame, long n) {
  return n == kVoteFrameLen && frame[0] == kTagVote &&
         le32(frame + 41) == kPkSize && le32(frame + 77) == kSigSize;
}

// ---- staging arena ring ----------------------------------------------------

enum ArenaState { kFree = 0, kOpen = 1, kSealed = 2 };

struct Arena {
  std::vector<uint8_t> dig, pk, sig;
  int count = 0;   // rows packed (OPEN) / exposed (SEALED)
  int dirty = 0;   // high-water of rows written since the last pad fill
  int state = kFree;
};

struct Packer {
  std::mutex mu;
  int capacity = 0;
  int depth = 0;
  int open = -1;
  bool pad_set = false;
  uint8_t pad_dig[kDigSize];
  uint8_t pad_pk[kPkSize];
  uint8_t pad_sig[kSigSize];
  std::vector<Arena> ring;
  // counters: packed, reject, full, seal, discard, recycle, moved rows
  uint64_t c_packed = 0, c_reject = 0, c_full = 0, c_seal = 0;
  uint64_t c_discard = 0, c_recycle = 0, c_moved = 0;
};

void pad_rows(Packer* p, Arena& a, int lo, int hi) {
  for (int r = lo; r < hi; r++) {
    std::memcpy(a.dig.data() + (size_t)r * kDigSize, p->pad_dig, kDigSize);
    std::memcpy(a.pk.data() + (size_t)r * kPkSize, p->pad_pk, kPkSize);
    std::memcpy(a.sig.data() + (size_t)r * kSigSize, p->pad_sig, kSigSize);
  }
}

}  // namespace

extern "C" {

// Ring of `ring_depth` arenas, each `capacity` rows (capacity should be
// the LARGEST wave bucket so any smaller bucket is a prefix view).
// Returns an opaque handle, or null on bad args / alloc failure.
void* wp_create(int capacity, int ring_depth) {
  if (capacity <= 0 || ring_depth < 2) return nullptr;
  Packer* p = new (std::nothrow) Packer();
  if (!p) return nullptr;
  p->capacity = capacity;
  p->depth = ring_depth;
  p->ring.resize(ring_depth);
  for (auto& a : p->ring) {
    a.dig.resize((size_t)capacity * kDigSize);
    a.pk.resize((size_t)capacity * kPkSize);
    a.sig.resize((size_t)capacity * kSigSize);
  }
  p->ring[0].state = kOpen;
  p->open = 0;
  return p;
}

void wp_destroy(void* h) { delete static_cast<Packer*>(h); }

// Install the pad claim and pre-fill EVERY arena with it. Must run
// before the first pack (packing without a pad would leave unsealed
// rows garbage instead of valid claims). Rejected once any row has
// been written.
int wp_set_pad(void* h, const uint8_t* dig, const uint8_t* pk,
               const uint8_t* sig) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  for (auto& a : p->ring)
    if (a.dirty > 0) return -1;
  std::memcpy(p->pad_dig, dig, kDigSize);
  std::memcpy(p->pad_pk, pk, kPkSize);
  std::memcpy(p->pad_sig, sig, kSigSize);
  for (auto& a : p->ring) pad_rows(p, a, 0, p->capacity);
  p->pad_set = true;
  return 0;
}

// Stateless accept/reject probe with Decoder parity — the differential
// fuzz harness drives this over the same corpus as decode_message.
int wp_probe_vote(const uint8_t* frame, long n) {
  return vote_ok(frame, n) ? 1 : 0;
}

// Parse a vote frame into the open arena. Returns the row slot (>= 0)
// and writes the 32-byte claim digest to digest_out (also column 0 of
// the row), or: -1 malformed frame, -2 arena full (caller falls back
// for this wave), -3 no pad installed / no open arena.
long wp_pack_vote(void* h, const uint8_t* frame, long n, uint8_t* digest_out) {
  Packer* p = static_cast<Packer*>(h);
  if (!vote_ok(frame, n)) {
    std::lock_guard<std::mutex> g(p->mu);
    p->c_reject++;
    return -1;
  }
  std::lock_guard<std::mutex> g(p->mu);
  if (!p->pad_set || p->open < 0) return -3;
  Arena& a = p->ring[p->open];
  if (a.count >= p->capacity) {
    p->c_full++;
    return -2;
  }
  int row = a.count;
  uint8_t full[64];
  // Vote.digest(): sha512_trunc(hash || round_le8) — the wire already
  // holds hash and LE round adjacent at frame[1:41]
  sha512_single_block(frame + 1, 40, full);
  std::memcpy(a.dig.data() + (size_t)row * kDigSize, full, kDigSize);
  std::memcpy(a.pk.data() + (size_t)row * kPkSize, frame + 45, kPkSize);
  std::memcpy(a.sig.data() + (size_t)row * kSigSize, frame + 81, kSigSize);
  a.count = row + 1;
  if (a.count > a.dirty) a.dirty = a.count;
  p->c_packed++;
  if (digest_out) std::memcpy(digest_out, full, kDigSize);
  return row;
}

// Rows currently packed in the open arena (debug/ingest accounting).
long wp_count(void* h) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  return p->open < 0 ? -1 : p->ring[p->open].count;
}

// Seal the open arena, exposing its first n_take rows as a wave. Any
// surplus rows (claims packed after the dispatcher snapshot) move to
// the head of the next FREE arena, which becomes the new OPEN arena —
// so the pack stream stays aligned with the claim stream. Returns the
// sealed arena index, or: -1 bad n_take, -2 no FREE arena available
// (caller should discard + fall back).
long wp_seal(void* h, long n_take) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  if (p->open < 0) return -1;
  Arena& a = p->ring[p->open];
  if (n_take < 0 || n_take > a.count) return -1;
  int next = -1;
  for (int i = 0; i < p->depth; i++) {
    int j = (p->open + 1 + i) % p->depth;
    if (p->ring[j].state == kFree) {
      next = j;
      break;
    }
  }
  if (next < 0) return -2;
  Arena& f = p->ring[next];
  long surplus = a.count - n_take;
  if (surplus > 0) {
    std::memcpy(f.dig.data(), a.dig.data() + (size_t)n_take * kDigSize,
                (size_t)surplus * kDigSize);
    std::memcpy(f.pk.data(), a.pk.data() + (size_t)n_take * kPkSize,
                (size_t)surplus * kPkSize);
    std::memcpy(f.sig.data(), a.sig.data() + (size_t)n_take * kSigSize,
                (size_t)surplus * kSigSize);
    p->c_moved += (uint64_t)surplus;
  }
  f.count = (int)surplus;
  if (f.count > f.dirty) f.dirty = f.count;
  f.state = kOpen;
  long sealed = p->open;
  a.count = (int)n_take;
  a.state = kSealed;
  p->open = next;
  p->c_seal++;
  return sealed;
}

// Column addresses + shape of a sealed arena, for NumPy frombuffer
// adoption: out = {dig_ptr, pk_ptr, sig_ptr, exposed_rows, capacity}.
int wp_arena_info(void* h, long arena, uint64_t out[5]) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  if (arena < 0 || arena >= p->depth) return -1;
  Arena& a = p->ring[arena];
  if (a.state != kSealed) return -1;
  out[0] = (uint64_t)(uintptr_t)a.dig.data();
  out[1] = (uint64_t)(uintptr_t)a.pk.data();
  out[2] = (uint64_t)(uintptr_t)a.sig.data();
  out[3] = (uint64_t)a.count;
  out[4] = (uint64_t)p->capacity;
  return 0;
}

// Return a sealed arena to the FREE pool: re-pad its dirtied rows so
// the next seal exposes a fully valid fixed-shape wave again. Called
// from verifier slot threads once the adopted views are consumed.
int wp_recycle(void* h, long arena) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  if (arena < 0 || arena >= p->depth) return -1;
  Arena& a = p->ring[arena];
  if (a.state != kSealed) return -1;
  pad_rows(p, a, 0, a.dirty);
  a.count = 0;
  a.dirty = 0;
  a.state = kFree;
  p->c_recycle++;
  return 0;
}

// Drop everything packed into the open arena (pack/claim streams went
// out of sync — e.g. a deduped duplicate vote): re-pad and start over.
int wp_discard(void* h) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  if (p->open < 0) return -1;
  Arena& a = p->ring[p->open];
  pad_rows(p, a, 0, a.dirty);
  a.count = 0;
  a.dirty = 0;
  p->c_discard++;
  return 0;
}

// counters: {packed, reject, full, seal, discard, recycle, moved}
int wp_counters(void* h, uint64_t* out, int cap) {
  Packer* p = static_cast<Packer*>(h);
  std::lock_guard<std::mutex> g(p->mu);
  uint64_t vals[7] = {p->c_packed, p->c_reject,  p->c_full, p->c_seal,
                      p->c_discard, p->c_recycle, p->c_moved};
  int n = cap < 7 ? cap : 7;
  for (int i = 0; i < n; i++) out[i] = vals[i];
  return n;
}

// Stateless producer-v2 batch parse with Decoder parity. On accept,
// writes the digest column (count x 32B) to digests_out and
// (offset, len) body spans into spans_out (count x 2 u64) — bodies
// stay in the caller's frame buffer as memoryview slices, no copies.
// Returns the item count, or -1 on any frame the Python Decoder
// rejects. Output buffers must hold MAX_PRODUCER_BATCH entries.
long wp_parse_producer(const uint8_t* frame, long n, uint8_t* digests_out,
                       uint64_t* spans_out) {
  if (n < 2 || frame[0] != kTagProducerV2) return -1;
  if (frame[1] != kProducerVersion) return -1;
  if (n < 6) return -1;  // truncated count field
  long count = (long)le32(frame + 2);
  if (count < 1 || count > kMaxProducerBatch) return -1;
  long off = 6;
  for (long i = 0; i < count; i++) {
    if (off + kDigSize > n) return -1;  // truncated digest
    if (digests_out)
      std::memcpy(digests_out + i * kDigSize, frame + off, kDigSize);
    off += kDigSize;
    if (off + 4 > n) return -1;  // truncated body length
    long blen = (long)le32(frame + off);
    if (blen > kMaxPayloadBody) return -1;
    off += 4;
    if (off + blen > n) return -1;  // truncated body
    if (spans_out) {
      spans_out[i * 2] = (uint64_t)off;
      spans_out[i * 2 + 1] = (uint64_t)blen;
    }
    off += blen;
  }
  return off == n ? count : -1;  // Decoder.finish(): no trailing bytes
}

}  // extern "C"
