// Native TCP transport reactor for hotstuff_tpu.
//
// The reference's network crate is native (tokio TCP with
// LengthDelimitedCodec framing, network/src/receiver.rs:70); this is the
// framework's native equivalent: a single epoll reactor thread owning
// every socket, with a C API consumed through ctypes
// (hotstuff_tpu/network/native.py).  Semantics mirrored:
//
// - length-delimited framing: u32 big-endian prefix, 64 MB cap
//   (framing.py / reference receiver.rs:70);
// - outbound peers (SimpleSender, simple_sender.rs:22-143): one
//   persistent connection per peer, bounded queue of 1000 frames,
//   frames dropped when the peer is down (reconnect attempted on the
//   next send), inbound frames on the same socket (ACKs) surfaced to
//   the caller;
// - inbound listener (Receiver, receiver.rs:31-89): accepted
//   connections deliver frames to the caller, which may write replies
//   (ACKs) back on the same connection.
//
// Bridge to asyncio: a notify pipe becomes readable whenever the event
// queue transitions from empty to non-empty; the Python side registers
// it with loop.add_reader and drains ht_next() without blocking.
//
// Thread model: the reactor thread owns all sockets.  ht_send/ht_reply
// only take a lock and append to an outbox, then wake the reactor via
// a second (wake) pipe.  No socket syscall ever happens off-thread.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <atomic>
#include <string>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kMaxFrame = 64u * 1024u * 1024u;
constexpr size_t kQueueCap = 1000;  // per-peer outbox (reference cap)

enum EventKind : int {
  kFrameFromAccepted = 1,
  kFrameFromPeer = 2,
  kAcceptedClosed = 3,
  kPeerClosed = 4,
};

struct Event {
  long src;
  int kind;
  std::string payload;
};

struct Conn {
  int fd = -1;
  bool outbound = false;     // outbound peer (reconnects) vs accepted
  long listener = -1;        // owning listener id (accepted conns)
  bool connecting = false;   // nonblocking connect in flight
  std::string host;          // outbound only
  int port = 0;              // outbound only
  std::string rbuf;          // partial inbound bytes
  std::string wbuf;          // bytes queued on the socket
  std::deque<std::string> outbox;  // framed messages not yet in wbuf
  bool closed = false;
  bool pending_close = false;  // Python asked; reactor thread executes
  // Flow control: Python pauses reads when its dispatch queue for this
  // connection crosses the high-water mark, so TCP backpressure reaches
  // the sender instead of frames piling up in unbounded Python queues
  // (measured: an 8k tx/s overload collapsed throughput 30x without it).
  bool read_paused = false;
  bool pending_rearm = false;  // pause state changed off-thread
};

int set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

void frame_into(std::string& out, const uint8_t* data, int len) {
  uint32_t be = htonl(static_cast<uint32_t>(len));
  out.append(reinterpret_cast<const char*>(&be), 4);
  out.append(reinterpret_cast<const char*>(data), static_cast<size_t>(len));
}

struct Reactor {
  int epfd = -1;
  int notify_r = -1, notify_w = -1;  // events pending -> readable
  int wake_r = -1, wake_w = -1;      // off-thread poke of the reactor
  std::thread thread;
  std::atomic<bool> running{false};

  // Wire-level flow accounting (ISSUE 19): cumulative counters over
  // every socket the reactor owns, read back via ht_counters.  tx_bytes
  // counts bytes ::send actually accepted (length prefixes included);
  // rx_bytes counts 4+len per extracted frame; tx_frames counts frames
  // framed into an outbox (a best-effort drop of a queued frame on
  // disconnect can leave tx_bytes below tx_frames' framed total).
  // Atomics: bumped on the reactor thread, read from Python threads.
  std::atomic<unsigned long long> tx_bytes{0}, tx_frames{0};
  std::atomic<unsigned long long> rx_bytes{0}, rx_frames{0};

  std::mutex mu;  // guards events, conns map mutation, outboxes, next_id
  std::deque<Event> events;
  std::map<long, Conn> conns;
  std::map<int, long> fd_to_id;
  std::map<int, long> listeners;  // listener fd -> id
  long next_id = 1;

  void wake() {
    char b = 1;
    (void)!write(wake_w, &b, 1);
  }

  void push_event(long src, int kind, std::string payload) {
    bool was_empty;
    {
      std::lock_guard<std::mutex> g(mu);
      was_empty = events.empty();
      events.push_back(Event{src, kind, std::move(payload)});
    }
    if (was_empty) {
      char b = 1;
      (void)!write(notify_w, &b, 1);
    }
  }

  void arm(int fd, bool want_write, bool want_read = true) {
    epoll_event ev{};
    ev.events = (want_read ? EPOLLIN : 0u) | (want_write ? EPOLLOUT : 0u);
    ev.data.fd = fd;
    epoll_ctl(epfd, EPOLL_CTL_MOD, fd, &ev);
  }

  void add_fd(int fd, bool want_write) {
    epoll_event ev{};
    ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0u);
    ev.data.fd = fd;
    epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
  }

  void close_conn(long id, bool notify) {
    bool was_accepted = false;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = conns.find(id);
      if (it == conns.end()) return;
      Conn& c = it->second;
      if (c.fd >= 0) {
        epoll_ctl(epfd, EPOLL_CTL_DEL, c.fd, nullptr);
        fd_to_id.erase(c.fd);
        ::close(c.fd);
        c.fd = -1;
      }
      c.connecting = false;
      c.rbuf.clear();
      c.wbuf.clear();
      if (!c.outbound) {
        c.closed = true;
        was_accepted = true;
      } else {
        // best-effort semantics: frames queued while down are dropped
        c.outbox.clear();
      }
    }
    if (notify) push_event(id, was_accepted ? kAcceptedClosed : kPeerClosed, "");
  }

  // try to open the outbound connection for peer `id` (reactor thread)
  void start_connect(long id) {
    std::string host;
    int port;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = conns.find(id);
      if (it == conns.end() || it->second.fd >= 0 || it->second.connecting)
        return;
      host = it->second.host;
      port = it->second.port;
    }
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return;
    set_nonblock(fd);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1) {
      ::close(fd);
      return;
    }
    int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa));
    bool failed = false;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = conns.find(id);
      if (it == conns.end()) {
        ::close(fd);
        return;
      }
      if (rc == 0 || errno == EINPROGRESS) {
        it->second.fd = fd;
        it->second.connecting = (rc != 0);
        fd_to_id[fd] = id;
        add_fd(fd, true);  // EPOLLOUT signals connect completion
      } else {
        ::close(fd);
        it->second.outbox.clear();  // drop (peer down)
        failed = true;
      }
    }
    if (failed) push_event(id, kPeerClosed, "");
  }

  void flush_outbox_locked(Conn& c) {
    while (!c.outbox.empty() && c.wbuf.size() < (1u << 20)) {
      c.wbuf += c.outbox.front();
      c.outbox.pop_front();
    }
  }

  void handle_writable(long id) {
    bool broken = false;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = conns.find(id);
      if (it == conns.end() || it->second.fd < 0) return;
      Conn& c = it->second;
      if (c.connecting) {
        int err = 0;
        socklen_t len = sizeof(err);
        getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) {
          // connect failed: drop queued frames (best-effort)
          epoll_ctl(epfd, EPOLL_CTL_DEL, c.fd, nullptr);
          fd_to_id.erase(c.fd);
          ::close(c.fd);
          c.fd = -1;
          c.connecting = false;
          c.outbox.clear();
          broken = true;  // emits kPeerClosed below
        } else {
          c.connecting = false;
        }
      }
      if (!broken) {
        flush_outbox_locked(c);
        while (!c.wbuf.empty()) {
          ssize_t n = ::send(c.fd, c.wbuf.data(), c.wbuf.size(), MSG_NOSIGNAL);
          if (n > 0) {
            tx_bytes.fetch_add(static_cast<unsigned long long>(n),
                               std::memory_order_relaxed);
            c.wbuf.erase(0, static_cast<size_t>(n));
            flush_outbox_locked(c);
          } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            break;
          } else {
            broken = true;
            break;
          }
        }
      }
      if (!broken)
        arm(c.fd, !c.wbuf.empty() || !c.outbox.empty(), !c.read_paused);
    }
    if (broken) close_conn(id, true);
  }

  void handle_readable(long id) {
    int fd;
    bool outbound;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = conns.find(id);
      if (it == conns.end() || it->second.fd < 0) return;
      fd = it->second.fd;
      outbound = it->second.outbound;
    }
    char buf[64 * 1024];
    while (true) {
      ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
      if (n > 0) {
        std::string* rbuf;
        {
          std::lock_guard<std::mutex> g(mu);
          auto it = conns.find(id);
          if (it == conns.end()) return;
          rbuf = &it->second.rbuf;
          rbuf->append(buf, static_cast<size_t>(n));
        }
        // extract complete frames
        bool violation = false;
        while (true) {
          std::string payload;
          bool have = false;
          {
            std::lock_guard<std::mutex> g(mu);
            auto it = conns.find(id);
            if (it == conns.end()) return;
            std::string& r = it->second.rbuf;
            if (r.size() >= 4) {
              uint32_t be;
              memcpy(&be, r.data(), 4);
              uint32_t len = ntohl(be);
              if (len > kMaxFrame) {
                violation = true;  // protocol violation: drop the conn
              } else if (r.size() >= 4 + len) {
                payload = r.substr(4, len);
                r.erase(0, 4 + static_cast<size_t>(len));
                rx_bytes.fetch_add(4ull + len, std::memory_order_relaxed);
                rx_frames.fetch_add(1, std::memory_order_relaxed);
                have = true;
              }
            }
          }
          if (violation) {
            close_conn(id, true);
            return;
          }
          if (!have) break;
          push_event(id, outbound ? kFrameFromPeer : kFrameFromAccepted,
                     std::move(payload));
        }
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        return;
      } else {
        close_conn(id, true);
        return;
      }
    }
  }

  void handle_accept(int lfd) {
    while (true) {
      int fd = ::accept(lfd, nullptr, nullptr);
      if (fd < 0) return;
      set_nonblock(fd);
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      long id;
      {
        std::lock_guard<std::mutex> g(mu);
        id = next_id++;
        Conn c;
        c.fd = fd;
        c.outbound = false;
        auto lit = listeners.find(lfd);
        c.listener = lit != listeners.end() ? lit->second : -1;
        conns[id] = std::move(c);
        fd_to_id[fd] = id;
      }
      add_fd(fd, false);
    }
  }

  void run() {
    epoll_event evs[64];
    while (running) {
      int n = epoll_wait(epfd, evs, 64, 200);
      for (int i = 0; i < n; i++) {
        int fd = evs[i].data.fd;
        if (fd == wake_r) {
          char tmp[256];
          while (read(wake_r, tmp, sizeof(tmp)) > 0) {
          }
          // execute closes requested off-thread
          std::vector<long> doomed;
          {
            std::lock_guard<std::mutex> g(mu);
            for (auto& [id, c] : conns) {
              if (c.pending_close) doomed.push_back(id);
            }
          }
          for (long id : doomed) {
            // notify: Python cleans up per-connection workers / pending
            // ACK futures off the close event
            close_conn(id, true);
            std::lock_guard<std::mutex> g(mu);
            auto it = conns.find(id);
            // accepted conns are reaped when the close event is
            // consumed; outbound handles are being discarded entirely
            if (it != conns.end() && it->second.outbound) conns.erase(it);
          }
          // apply read-pause changes requested off-thread: snapshot
          // (fd, want_write, want_read) under the lock, re-arm after
          {
            struct Rearm { int fd; bool w; bool r; };
            std::vector<Rearm> rearm;
            {
              std::lock_guard<std::mutex> g(mu);
              for (auto& [id, c] : conns) {
                (void)id;
                if (c.pending_rearm && c.fd >= 0 && !c.connecting) {
                  c.pending_rearm = false;
                  rearm.push_back(Rearm{
                      c.fd,
                      !c.wbuf.empty() || !c.outbox.empty(),
                      !c.read_paused});
                }
              }
            }
            for (const Rearm& a : rearm) arm(a.fd, a.w, a.r);
          }
          // flush every outbound conn with pending frames; start
          // connections for peers that are down
          std::vector<long> want;
          {
            std::lock_guard<std::mutex> g(mu);
            for (auto& [id, c] : conns) {
              if (!c.outbox.empty() || !c.wbuf.empty()) want.push_back(id);
            }
          }
          for (long id : want) {
            bool need_connect = false;
            {
              std::lock_guard<std::mutex> g(mu);
              auto it = conns.find(id);
              if (it == conns.end()) continue;
              need_connect =
                  it->second.outbound && it->second.fd < 0 &&
                  !it->second.connecting;
            }
            if (need_connect) start_connect(id);
            std::lock_guard<std::mutex> g(mu);
            auto it = conns.find(id);
            if (it != conns.end() && it->second.fd >= 0 &&
                !it->second.connecting) {
              arm(it->second.fd, true, !it->second.read_paused);
            }
          }
          continue;
        }
        bool is_listener;
        long id = -1;
        {
          std::lock_guard<std::mutex> g(mu);
          auto lit = listeners.find(fd);
          is_listener = lit != listeners.end();
          if (!is_listener) {
            auto fit = fd_to_id.find(fd);
            if (fit == fd_to_id.end()) continue;
            id = fit->second;
          }
        }
        if (is_listener) {
          handle_accept(fd);
          continue;
        }
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
          // treat as readable first (drain), then close
          handle_readable(id);
          continue;
        }
        if (evs[i].events & EPOLLOUT) handle_writable(id);
        if (evs[i].events & EPOLLIN) handle_readable(id);
      }
    }
  }
};

}  // namespace

extern "C" {

void* ht_start() {
  auto* r = new Reactor();
  r->epfd = epoll_create1(0);
  int p1[2], p2[2];
  if (pipe(p1) != 0 || pipe(p2) != 0) {
    delete r;
    return nullptr;
  }
  r->notify_r = p1[0];
  r->notify_w = p1[1];
  r->wake_r = p2[0];
  r->wake_w = p2[1];
  set_nonblock(r->notify_r);
  set_nonblock(r->notify_w);
  set_nonblock(r->wake_r);
  set_nonblock(r->wake_w);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = r->wake_r;
  epoll_ctl(r->epfd, EPOLL_CTL_ADD, r->wake_r, &ev);
  r->running = true;
  r->thread = std::thread([r] { r->run(); });
  return r;
}

int ht_notify_fd(void* rp) {
  return static_cast<Reactor*>(rp)->notify_r;
}

long ht_listen(void* rp, const char* ip, int port) {
  auto* r = static_cast<Reactor*>(rp);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, ip, &sa.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
      listen(fd, 128) != 0) {
    ::close(fd);
    return -1;
  }
  set_nonblock(fd);
  long id;
  {
    std::lock_guard<std::mutex> g(r->mu);
    id = r->next_id++;
    r->listeners[fd] = id;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = fd;
  epoll_ctl(r->epfd, EPOLL_CTL_ADD, fd, &ev);
  return id;
}

long ht_connect(void* rp, const char* ip, int port) {
  auto* r = static_cast<Reactor*>(rp);
  std::lock_guard<std::mutex> g(r->mu);
  long id = r->next_id++;
  Conn c;
  c.outbound = true;
  c.host = ip;
  c.port = port;
  r->conns[id] = std::move(c);
  return id;
}

int ht_send(void* rp, long peer, const uint8_t* data, int len) {
  auto* r = static_cast<Reactor*>(rp);
  if (len < 0 || static_cast<uint32_t>(len) > kMaxFrame) return -1;
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->conns.find(peer);
    if (it == r->conns.end() || !it->second.outbound) return -1;
    if (it->second.outbox.size() >= kQueueCap) return -1;  // drop
    std::string framed;
    frame_into(framed, data, len);
    it->second.outbox.push_back(std::move(framed));
    r->tx_frames.fetch_add(1, std::memory_order_relaxed);
  }
  char b = 1;
  (void)!write(r->wake_w, &b, 1);
  return 0;
}

int ht_reply(void* rp, long conn, const uint8_t* data, int len) {
  auto* r = static_cast<Reactor*>(rp);
  if (len < 0 || static_cast<uint32_t>(len) > kMaxFrame) return -1;
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->conns.find(conn);
    if (it == r->conns.end() || it->second.outbound || it->second.closed)
      return -1;
    if (it->second.outbox.size() >= kQueueCap) {
      // peer not reading its replies: close the connection rather than
      // silently dropping an ACK (a dropped ACK on a live connection
      // would permanently desync the sender's FIFO ACK pairing; a
      // close makes the peer reconnect and retransmit)
      it->second.pending_close = true;
      r->wake();
      return -1;
    }
    std::string framed;
    frame_into(framed, data, len);
    it->second.outbox.push_back(std::move(framed));
    r->tx_frames.fetch_add(1, std::memory_order_relaxed);
  }
  char b = 1;
  (void)!write(r->wake_w, &b, 1);
  return 0;
}

// Cumulative wire counters (ISSUE 19): out[0]=tx_bytes (accepted by
// ::send, prefixes included), out[1]=tx_frames (framed into outboxes),
// out[2]=rx_bytes (4+len per extracted frame), out[3]=rx_frames.
void ht_counters(void* rp, unsigned long long out[4]) {
  auto* r = static_cast<Reactor*>(rp);
  out[0] = r->tx_bytes.load(std::memory_order_relaxed);
  out[1] = r->tx_frames.load(std::memory_order_relaxed);
  out[2] = r->rx_bytes.load(std::memory_order_relaxed);
  out[3] = r->rx_frames.load(std::memory_order_relaxed);
}

// Drain one event.  Returns payload length (>= 0) with *src/*kind set,
// -1 when the queue is empty, -2 when the buffer is too small (event
// stays queued; call again with a bigger buffer of at least the
// returned-in-*kind size... simpler: capacity >= 64 MB never triggers).
int ht_next(void* rp, long* src, int* kind, uint8_t* buf, int cap) {
  auto* r = static_cast<Reactor*>(rp);
  std::lock_guard<std::mutex> g(r->mu);
  if (r->events.empty()) {
    // drain the notify pipe only when empty so the fd stays readable
    // while events remain
    char tmp[256];
    while (read(r->notify_r, tmp, sizeof(tmp)) > 0) {
    }
    return -1;
  }
  Event& e = r->events.front();
  if (static_cast<int>(e.payload.size()) > cap) return -2;
  *src = e.src;
  *kind = e.kind;
  int n = static_cast<int>(e.payload.size());
  memcpy(buf, e.payload.data(), e.payload.size());
  if (e.kind == kAcceptedClosed) {
    // reap: the consumer has now seen the close — the entry is dead
    // (outbound peers are NOT reaped: their ids are stable handles that
    // reconnect on the next send)
    r->conns.erase(e.src);
  }
  r->events.pop_front();
  return n;
}

// Ask the reactor thread to close a connection (accepted or outbound)
// and forget it.  Deferred to the reactor: only it may ::close() an fd
// it could concurrently be reading/writing (an off-thread close would
// race with recv/send and could hit a recycled fd number).
// Flow control from Python: pause/resume reading a connection.  The
// reactor re-arms the fd on the next wake; while paused, the kernel
// receive buffer fills and TCP backpressure reaches the sender.
int ht_set_read_paused(void* rp, long conn, int paused) {
  auto* r = static_cast<Reactor*>(rp);
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->conns.find(conn);
    if (it == r->conns.end()) return -1;
    if (it->second.read_paused == static_cast<bool>(paused)) return 0;
    it->second.read_paused = paused;
    it->second.pending_rearm = true;
  }
  r->wake();
  return 0;
}

int ht_close_conn(void* rp, long conn) {
  auto* r = static_cast<Reactor*>(rp);
  {
    std::lock_guard<std::mutex> g(r->mu);
    auto it = r->conns.find(conn);
    if (it == r->conns.end()) return -1;
    it->second.pending_close = true;
  }
  r->wake();
  return 0;
}

// Close a listener: stop accepting; existing connections are unaffected.
int ht_close_listener(void* rp, long listener_id) {
  auto* r = static_cast<Reactor*>(rp);
  std::lock_guard<std::mutex> g(r->mu);
  for (auto it = r->listeners.begin(); it != r->listeners.end(); ++it) {
    if (it->second == listener_id) {
      epoll_ctl(r->epfd, EPOLL_CTL_DEL, it->first, nullptr);
      ::close(it->first);
      r->listeners.erase(it);
      return 0;
    }
  }
  return -1;
}

// Owning listener id of an accepted connection (-1 if unknown) — the
// Python side routes frames to the right receiver with this.
long ht_conn_listener(void* rp, long conn) {
  auto* r = static_cast<Reactor*>(rp);
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->conns.find(conn);
  if (it == r->conns.end() || it->second.outbound) return -1;
  return it->second.listener;
}

void ht_stop(void* rp) {
  auto* r = static_cast<Reactor*>(rp);
  r->running = false;
  char b = 1;
  (void)!write(r->wake_w, &b, 1);
  if (r->thread.joinable()) r->thread.join();
  {
    // scope the guard: the lock_guard must release r->mu BEFORE
    // delete r, or its destructor unlocks a destroyed mutex inside
    // freed memory (caught by the TSan stress harness).  The reactor
    // thread is already joined, so nothing else can take the mutex.
    std::lock_guard<std::mutex> g(r->mu);
    for (auto& [id, c] : r->conns) {
      if (c.fd >= 0) ::close(c.fd);
    }
    for (auto& [fd, id] : r->listeners) ::close(fd);
    ::close(r->epfd);
    ::close(r->notify_r);
    ::close(r->notify_w);
    ::close(r->wake_r);
    ::close(r->wake_w);
  }
  delete r;
}

}  // extern "C"
