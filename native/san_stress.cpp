// Sanitizer stress harness for the native layer (ISSUE 12).
//
// Compiled TOGETHER with transport.cpp and store_engine.cpp into a
// standalone executable (build/san_stress_{tsan,asan}) — a sanitized
// .so dlopened into an uninstrumented Python would miss the runtime
// interceptors, so the stress drives the C ABI directly:
//
//   store:     per-thread WAL engines (put/get/delete/compact/replay
//              round-trips) plus one SHARED engine serialized by an
//              external mutex — the engine is single-writer by design
//              (hotstuff_tpu/store owns one per node), so the shared
//              mode models the documented discipline, not free-for-all
//              concurrency.
//   transport: one reactor, multi-threaded ht_send/ht_reply against the
//              reactor thread's epoll loop and the ht_next drain —
//              every mutex-protected queue handoff in transport.cpp
//              under genuine cross-thread fire.
//   wavepack:  one wave packer ring (wave_pack.cpp), four packer
//              threads racing wp_pack_vote against a sealer thread
//              doing wp_seal/wp_arena_info/column reads/wp_recycle and
//              periodic wp_discard — the production topology (reactor
//              thread packs, verifier slot threads seal and recycle)
//              with the thread count turned up.
//
// Exit 0 and "SAN_STRESS OK" on success; any sanitizer report fails
// the process via halt_on_error=1 (set by scripts/san_check.py).

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

extern "C" {
// store_engine.cpp
void* hs_open(const char* path, int fsync_mode);
int hs_put(void* h, const uint8_t* k, uint32_t klen, const uint8_t* v,
           uint32_t vlen);
int hs_get(void* h, const uint8_t* k, uint32_t klen, uint8_t** out,
           uint32_t* outlen);
int hs_delete(void* h, const uint8_t* k, uint32_t klen);
uint64_t hs_count(void* h);
int hs_compact(void* h);
void hs_free(uint8_t* p);
void hs_close(void* h);
// transport.cpp
void* ht_start();
long ht_listen(void* rp, const char* ip, int port);
long ht_connect(void* rp, const char* ip, int port);
int ht_send(void* rp, long peer, const uint8_t* data, int len);
int ht_reply(void* rp, long conn, const uint8_t* data, int len);
int ht_next(void* rp, long* src, int* kind, uint8_t* buf, int cap);
int ht_set_read_paused(void* rp, long conn, int paused);
int ht_close_conn(void* rp, long conn);
void ht_stop(void* rp);
// wave_pack.cpp
void* wp_create(int capacity, int ring_depth);
void wp_destroy(void* h);
int wp_set_pad(void* h, const uint8_t* dig, const uint8_t* pk,
               const uint8_t* sig);
int wp_probe_vote(const uint8_t* frame, long n);
long wp_pack_vote(void* h, const uint8_t* frame, long n, uint8_t* digest_out);
long wp_count(void* h);
long wp_seal(void* h, long n_take);
int wp_arena_info(void* h, long arena, uint64_t out[5]);
int wp_recycle(void* h, long arena);
int wp_discard(void* h);
int wp_counters(void* h, uint64_t* out, int cap);
long wp_parse_producer(const uint8_t* frame, long n, uint8_t* digests_out,
                       uint64_t* spans_out);
}

namespace {

constexpr int kStoreThreads = 4;
constexpr int kStoreOps = 400;
constexpr int kSendThreads = 4;
constexpr int kSendsPerThread = 250;

bool g_failed = false;

void fail(const char* what) {
  std::fprintf(stderr, "SAN_STRESS FAIL: %s\n", what);
  g_failed = true;
}

// ---- store stress ----------------------------------------------------------

std::string key_of(int t, int i) {
  char buf[32];
  std::snprintf(buf, sizeof buf, "k/%d/%d", t, i % 37);
  return buf;
}

void store_worker(const std::string& dir, int t) {
  std::string path = dir + "/own_" + std::to_string(t) + ".wal";
  void* h = hs_open(path.c_str(), 0);
  if (!h) return fail("hs_open(per-thread)");
  for (int i = 0; i < kStoreOps; i++) {
    std::string k = key_of(t, i);
    std::string v(1 + (i * 7) % 96, char('a' + t));
    if (hs_put(h, (const uint8_t*)k.data(), k.size(),
               (const uint8_t*)v.data(), v.size()) != 0)
      return fail("hs_put");
    uint8_t* out = nullptr;
    uint32_t outlen = 0;
    if (hs_get(h, (const uint8_t*)k.data(), k.size(), &out, &outlen) != 0 ||
        outlen != v.size() || std::memcmp(out, v.data(), outlen) != 0) {
      hs_free(out);
      return fail("hs_get round-trip");
    }
    hs_free(out);
    if (i % 11 == 3)
      hs_delete(h, (const uint8_t*)k.data(), k.size());
    if (i % 97 == 50) hs_compact(h);
    if (i % 151 == 100) {
      // close/reopen exercises WAL replay + compaction-on-open
      hs_close(h);
      h = hs_open(path.c_str(), 0);
      if (!h) return fail("hs_open(reopen)");
    }
  }
  hs_close(h);
}

void store_stress(const std::string& dir) {
  // per-thread engines: the production topology (one engine per node)
  std::vector<std::thread> ts;
  for (int t = 0; t < kStoreThreads; t++)
    ts.emplace_back(store_worker, dir, t);
  for (auto& th : ts) th.join();

  // one shared engine behind an external mutex: the documented
  // discipline when an engine must cross threads
  std::string path = dir + "/shared.wal";
  void* h = hs_open(path.c_str(), 0);
  if (!h) return fail("hs_open(shared)");
  std::mutex mu;
  std::vector<std::thread> ss;
  for (int t = 0; t < kStoreThreads; t++) {
    ss.emplace_back([&, t] {
      for (int i = 0; i < kStoreOps; i++) {
        std::string k = key_of(t, i);
        std::string v(1 + i % 64, char('A' + t));
        std::lock_guard<std::mutex> g(mu);
        if (hs_put(h, (const uint8_t*)k.data(), k.size(),
                   (const uint8_t*)v.data(), v.size()) != 0)
          return fail("hs_put(shared)");
        if (i % 13 == 7)
          hs_delete(h, (const uint8_t*)k.data(), k.size());
      }
    });
  }
  for (auto& th : ss) th.join();
  {
    std::lock_guard<std::mutex> g(mu);
    hs_compact(h);
    if (hs_count(h) == 0) fail("shared engine lost every key");
    hs_close(h);
  }
  std::printf("store stress done\n");
}

// ---- transport stress ------------------------------------------------------

void transport_stress() {
  void* rp = ht_start();
  if (!rp) return fail("ht_start");
  long listener = -1;
  int port = 0;
  for (int attempt = 0; attempt < 100 && listener < 0; attempt++) {
    port = 36000 + (int)((getpid() + attempt * 7) % 20000);
    listener = ht_listen(rp, "127.0.0.1", port);
  }
  if (listener < 0) {
    ht_stop(rp);
    return fail("ht_listen");
  }

  std::vector<long> peers;
  for (int i = 0; i < kSendThreads; i++) {
    long p = ht_connect(rp, "127.0.0.1", port);
    if (p < 0) {
      ht_stop(rp);
      return fail("ht_connect");
    }
    peers.push_back(p);
  }

  std::atomic<long> sent{0}, replied{0};
  std::atomic<long> got_accepted{0}, got_peer{0};
  std::atomic<bool> done_sending{false};

  // drain thread: the single ht_next consumer; replies to every 3rd
  // accepted frame so the reply path runs concurrently with senders
  std::thread drain([&] {
    std::vector<uint8_t> buf(1 << 16);
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(30);
    long pauses = 0;
    while (std::chrono::steady_clock::now() < deadline) {
      long src = 0;
      int kind = 0;
      int n = ht_next(rp, &src, &kind, buf.data(), (int)buf.size());
      if (n == -1) {
        if (done_sending.load() &&
            got_accepted.load() >= sent.load() &&
            got_peer.load() >= replied.load())
          break;
        usleep(200);
        continue;
      }
      if (n < 0) {
        fail("ht_next buffer too small");
        break;
      }
      if (kind == 1) {  // frame from an accepted conn
        long c = got_accepted.fetch_add(1) + 1;
        if (c % 3 == 0) {
          if (ht_reply(rp, src, buf.data(), n > 64 ? 64 : n) == 0)
            replied.fetch_add(1);
        }
        if (c % 101 == 50 && pauses < 8) {
          // flow-control churn against the reactor thread
          ht_set_read_paused(rp, src, 1);
          ht_set_read_paused(rp, src, 0);
          pauses++;
        }
      } else if (kind == 2) {  // frame from a connected peer (reply)
        got_peer.fetch_add(1);
      }
      // kinds 3/4 (closes) just drain
    }
  });

  std::vector<std::thread> senders;
  for (int t = 0; t < kSendThreads; t++) {
    senders.emplace_back([&, t] {
      std::vector<uint8_t> payload(16 + 97 * t, (uint8_t)t);
      for (int i = 0; i < kSendsPerThread; i++) {
        int len = 1 + (int)((i * 131 + t) % payload.size());
        if (ht_send(rp, peers[t], payload.data(), len) == 0)
          sent.fetch_add(1);
        else
          usleep(100);  // connect still in flight: retry cadence
        if (i % 50 == 49) usleep(500);  // let the reactor breathe
      }
    });
  }
  for (auto& th : senders) th.join();
  done_sending.store(true);
  drain.join();

  if (got_accepted.load() < sent.load())
    fail("transport dropped accepted-side frames");
  if (got_peer.load() < replied.load())
    fail("transport dropped reply frames");

  for (long p : peers) ht_close_conn(rp, p);
  ht_stop(rp);
  std::printf("transport stress done: sent=%ld delivered=%ld replies=%ld\n",
              sent.load(), got_accepted.load(), got_peer.load());
}

// ---- wave-pack stress ------------------------------------------------------

constexpr int kPackThreads = 4;
constexpr int kPacksPerThread = 2000;
constexpr int kArenaCap = 64;
constexpr int kRingDepth = 4;

// Valid 145-byte ed25519 vote frame with deterministic junk contents —
// the packer checks wire shape, not signatures.
void make_vote_frame(uint8_t out[145], int t, int i) {
  std::memset(out, 0, 145);
  out[0] = 1;  // TAG_VOTE
  for (int k = 0; k < 32; k++) out[1 + k] = (uint8_t)(t * 37 + i + k);
  uint64_t rnd = (uint64_t)t << 32 | (uint32_t)i;
  std::memcpy(out + 33, &rnd, 8);  // round (LE on every target we build)
  out[41] = 32;                    // pk_len LE
  for (int k = 0; k < 32; k++) out[45 + k] = (uint8_t)(t + k);
  out[77] = 64;  // sig_len LE
  for (int k = 0; k < 64; k++) out[81 + k] = (uint8_t)(i + k);
}

void wavepack_stress() {
  void* wp = wp_create(kArenaCap, kRingDepth);
  if (!wp) return fail("wp_create");
  uint8_t pad_dig[32], pad_pk[32], pad_sig[64];
  std::memset(pad_dig, 0xA5, sizeof pad_dig);
  std::memset(pad_pk, 0x5A, sizeof pad_pk);
  std::memset(pad_sig, 0x3C, sizeof pad_sig);
  if (wp_set_pad(wp, pad_dig, pad_pk, pad_sig) != 0) {
    wp_destroy(wp);
    return fail("wp_set_pad");
  }

  std::atomic<long> packed{0}, dropped{0};
  std::atomic<bool> done_packing{false};

  // sealer: the verifier-slot role — seal whatever is packed, adopt the
  // column views (read every exposed byte: ASan bounds + TSan ordering
  // vs. the packers), recycle; periodic discard models an ingest resync
  std::thread sealer([&] {
    std::vector<uint8_t> sink(1, 0);
    uint64_t info[5];
    long seals = 0;
    while (true) {
      long c = wp_count(wp);
      if (c <= 0) {
        if (done_packing.load() && wp_count(wp) <= 0) break;
        usleep(100);
        continue;
      }
      long take = c > 16 ? 16 : c;
      long arena = wp_seal(wp, take);
      if (arena == -2) {  // every arena busy: shed like the real plane
        wp_discard(wp);
        continue;
      }
      if (arena < 0) continue;  // packer raced the count snapshot
      if (wp_arena_info(wp, arena, info) != 0) {
        fail("wp_arena_info on sealed arena");
        break;
      }
      if ((long)info[3] != take || (long)info[4] != kArenaCap) {
        fail("wp_arena_info shape mismatch");
        break;
      }
      const uint8_t* dig = (const uint8_t*)(uintptr_t)info[0];
      const uint8_t* pk = (const uint8_t*)(uintptr_t)info[1];
      const uint8_t* sig = (const uint8_t*)(uintptr_t)info[2];
      uint8_t acc = 0;
      for (long r = 0; r < kArenaCap; r++) {  // full fixed shape, pads too
        for (int k = 0; k < 32; k++) acc ^= dig[r * 32 + k];
        for (int k = 0; k < 32; k++) acc ^= pk[r * 32 + k];
        for (int k = 0; k < 64; k++) acc ^= sig[r * 64 + k];
      }
      sink[0] ^= acc;
      if (wp_recycle(wp, arena) != 0) {
        fail("wp_recycle");
        break;
      }
      if (++seals % 97 == 0) wp_discard(wp);
    }
    if (sink[0] == 0xFF) std::printf("(sink)\n");  // keep the reads live
  });

  std::vector<std::thread> packers;
  for (int t = 0; t < kPackThreads; t++) {
    packers.emplace_back([&, t] {
      uint8_t frame[145], digest[32];
      uint64_t spans[8 * 2];
      uint8_t digs[8 * 32];
      for (int i = 0; i < kPacksPerThread; i++) {
        make_vote_frame(frame, t, i);
        if (wp_probe_vote(frame, sizeof frame) != 1) {
          fail("wp_probe_vote rejected a valid frame");
          return;
        }
        long slot = wp_pack_vote(wp, frame, sizeof frame, digest);
        if (slot == -2) {
          dropped.fetch_add(1);  // open arena full: real plane resyncs
          usleep(50);
        } else if (slot >= 0) {
          packed.fetch_add(1);
        } else {
          fail("wp_pack_vote rejected a valid frame");
          return;
        }
        if (i % 53 == 17) {
          // stateless producer parse races the stateful ring paths
          uint8_t pf[6 + 2 * (32 + 4 + 3)];
          pf[0] = 6;  // TAG_PRODUCER_V2
          pf[1] = 2;  // version
          pf[2] = 2; pf[3] = 0; pf[4] = 0; pf[5] = 0;  // count LE
          size_t off = 6;
          for (int item = 0; item < 2; item++) {
            std::memset(pf + off, (uint8_t)(t + item), 32);
            off += 32;
            pf[off] = 3; pf[off + 1] = 0; pf[off + 2] = 0; pf[off + 3] = 0;
            off += 4;
            std::memset(pf + off, 0x42, 3);
            off += 3;
          }
          if (wp_parse_producer(pf, (long)off, digs, spans) != 2) {
            fail("wp_parse_producer rejected a valid frame");
            return;
          }
        }
      }
    });
  }
  for (auto& th : packers) th.join();
  done_packing.store(true);
  sealer.join();

  uint64_t ctr[7] = {0};
  wp_counters(wp, ctr, 7);
  long expect = (long)kPackThreads * kPacksPerThread - dropped.load();
  if ((long)ctr[0] != packed.load() || packed.load() != expect)
    fail("wave-pack lost packed rows");
  if (ctr[3] == 0) fail("wave-pack sealer never sealed");
  if (ctr[3] != ctr[5]) fail("seal/recycle imbalance");
  wp_destroy(wp);
  std::printf("wavepack stress done: packed=%llu seals=%llu moved=%llu "
              "discards=%llu dropped=%ld\n",
              (unsigned long long)ctr[0], (unsigned long long)ctr[3],
              (unsigned long long)ctr[6], (unsigned long long)ctr[4],
              dropped.load());
}

}  // namespace

int main(int argc, char** argv) {
  const char* which = argc > 1 ? argv[1] : "all";
  char tmpl[] = "/tmp/hs_san_XXXXXX";
  char* dir = mkdtemp(tmpl);
  if (!dir) {
    std::fprintf(stderr, "SAN_STRESS FAIL: mkdtemp\n");
    return 1;
  }
  bool all = std::strcmp(which, "all") == 0;
  if (all || std::strcmp(which, "store") == 0) store_stress(dir);
  if (all || std::strcmp(which, "transport") == 0) transport_stress();
  if (all || std::strcmp(which, "wavepack") == 0) wavepack_stress();
  if (g_failed) return 1;
  std::printf("SAN_STRESS OK\n");
  return 0;
}
