// Sanitizer stress harness for the native layer (ISSUE 12).
//
// Compiled TOGETHER with transport.cpp and store_engine.cpp into a
// standalone executable (build/san_stress_{tsan,asan}) — a sanitized
// .so dlopened into an uninstrumented Python would miss the runtime
// interceptors, so the stress drives the C ABI directly:
//
//   store:     per-thread WAL engines (put/get/delete/compact/replay
//              round-trips) plus one SHARED engine serialized by an
//              external mutex — the engine is single-writer by design
//              (hotstuff_tpu/store owns one per node), so the shared
//              mode models the documented discipline, not free-for-all
//              concurrency.
//   transport: one reactor, multi-threaded ht_send/ht_reply against the
//              reactor thread's epoll loop and the ht_next drain —
//              every mutex-protected queue handoff in transport.cpp
//              under genuine cross-thread fire.
//
// Exit 0 and "SAN_STRESS OK" on success; any sanitizer report fails
// the process via halt_on_error=1 (set by scripts/san_check.py).

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

extern "C" {
// store_engine.cpp
void* hs_open(const char* path, int fsync_mode);
int hs_put(void* h, const uint8_t* k, uint32_t klen, const uint8_t* v,
           uint32_t vlen);
int hs_get(void* h, const uint8_t* k, uint32_t klen, uint8_t** out,
           uint32_t* outlen);
int hs_delete(void* h, const uint8_t* k, uint32_t klen);
uint64_t hs_count(void* h);
int hs_compact(void* h);
void hs_free(uint8_t* p);
void hs_close(void* h);
// transport.cpp
void* ht_start();
long ht_listen(void* rp, const char* ip, int port);
long ht_connect(void* rp, const char* ip, int port);
int ht_send(void* rp, long peer, const uint8_t* data, int len);
int ht_reply(void* rp, long conn, const uint8_t* data, int len);
int ht_next(void* rp, long* src, int* kind, uint8_t* buf, int cap);
int ht_set_read_paused(void* rp, long conn, int paused);
int ht_close_conn(void* rp, long conn);
void ht_stop(void* rp);
}

namespace {

constexpr int kStoreThreads = 4;
constexpr int kStoreOps = 400;
constexpr int kSendThreads = 4;
constexpr int kSendsPerThread = 250;

bool g_failed = false;

void fail(const char* what) {
  std::fprintf(stderr, "SAN_STRESS FAIL: %s\n", what);
  g_failed = true;
}

// ---- store stress ----------------------------------------------------------

std::string key_of(int t, int i) {
  char buf[32];
  std::snprintf(buf, sizeof buf, "k/%d/%d", t, i % 37);
  return buf;
}

void store_worker(const std::string& dir, int t) {
  std::string path = dir + "/own_" + std::to_string(t) + ".wal";
  void* h = hs_open(path.c_str(), 0);
  if (!h) return fail("hs_open(per-thread)");
  for (int i = 0; i < kStoreOps; i++) {
    std::string k = key_of(t, i);
    std::string v(1 + (i * 7) % 96, char('a' + t));
    if (hs_put(h, (const uint8_t*)k.data(), k.size(),
               (const uint8_t*)v.data(), v.size()) != 0)
      return fail("hs_put");
    uint8_t* out = nullptr;
    uint32_t outlen = 0;
    if (hs_get(h, (const uint8_t*)k.data(), k.size(), &out, &outlen) != 0 ||
        outlen != v.size() || std::memcmp(out, v.data(), outlen) != 0) {
      hs_free(out);
      return fail("hs_get round-trip");
    }
    hs_free(out);
    if (i % 11 == 3)
      hs_delete(h, (const uint8_t*)k.data(), k.size());
    if (i % 97 == 50) hs_compact(h);
    if (i % 151 == 100) {
      // close/reopen exercises WAL replay + compaction-on-open
      hs_close(h);
      h = hs_open(path.c_str(), 0);
      if (!h) return fail("hs_open(reopen)");
    }
  }
  hs_close(h);
}

void store_stress(const std::string& dir) {
  // per-thread engines: the production topology (one engine per node)
  std::vector<std::thread> ts;
  for (int t = 0; t < kStoreThreads; t++)
    ts.emplace_back(store_worker, dir, t);
  for (auto& th : ts) th.join();

  // one shared engine behind an external mutex: the documented
  // discipline when an engine must cross threads
  std::string path = dir + "/shared.wal";
  void* h = hs_open(path.c_str(), 0);
  if (!h) return fail("hs_open(shared)");
  std::mutex mu;
  std::vector<std::thread> ss;
  for (int t = 0; t < kStoreThreads; t++) {
    ss.emplace_back([&, t] {
      for (int i = 0; i < kStoreOps; i++) {
        std::string k = key_of(t, i);
        std::string v(1 + i % 64, char('A' + t));
        std::lock_guard<std::mutex> g(mu);
        if (hs_put(h, (const uint8_t*)k.data(), k.size(),
                   (const uint8_t*)v.data(), v.size()) != 0)
          return fail("hs_put(shared)");
        if (i % 13 == 7)
          hs_delete(h, (const uint8_t*)k.data(), k.size());
      }
    });
  }
  for (auto& th : ss) th.join();
  {
    std::lock_guard<std::mutex> g(mu);
    hs_compact(h);
    if (hs_count(h) == 0) fail("shared engine lost every key");
    hs_close(h);
  }
  std::printf("store stress done\n");
}

// ---- transport stress ------------------------------------------------------

void transport_stress() {
  void* rp = ht_start();
  if (!rp) return fail("ht_start");
  long listener = -1;
  int port = 0;
  for (int attempt = 0; attempt < 100 && listener < 0; attempt++) {
    port = 36000 + (int)((getpid() + attempt * 7) % 20000);
    listener = ht_listen(rp, "127.0.0.1", port);
  }
  if (listener < 0) {
    ht_stop(rp);
    return fail("ht_listen");
  }

  std::vector<long> peers;
  for (int i = 0; i < kSendThreads; i++) {
    long p = ht_connect(rp, "127.0.0.1", port);
    if (p < 0) {
      ht_stop(rp);
      return fail("ht_connect");
    }
    peers.push_back(p);
  }

  std::atomic<long> sent{0}, replied{0};
  std::atomic<long> got_accepted{0}, got_peer{0};
  std::atomic<bool> done_sending{false};

  // drain thread: the single ht_next consumer; replies to every 3rd
  // accepted frame so the reply path runs concurrently with senders
  std::thread drain([&] {
    std::vector<uint8_t> buf(1 << 16);
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(30);
    long pauses = 0;
    while (std::chrono::steady_clock::now() < deadline) {
      long src = 0;
      int kind = 0;
      int n = ht_next(rp, &src, &kind, buf.data(), (int)buf.size());
      if (n == -1) {
        if (done_sending.load() &&
            got_accepted.load() >= sent.load() &&
            got_peer.load() >= replied.load())
          break;
        usleep(200);
        continue;
      }
      if (n < 0) {
        fail("ht_next buffer too small");
        break;
      }
      if (kind == 1) {  // frame from an accepted conn
        long c = got_accepted.fetch_add(1) + 1;
        if (c % 3 == 0) {
          if (ht_reply(rp, src, buf.data(), n > 64 ? 64 : n) == 0)
            replied.fetch_add(1);
        }
        if (c % 101 == 50 && pauses < 8) {
          // flow-control churn against the reactor thread
          ht_set_read_paused(rp, src, 1);
          ht_set_read_paused(rp, src, 0);
          pauses++;
        }
      } else if (kind == 2) {  // frame from a connected peer (reply)
        got_peer.fetch_add(1);
      }
      // kinds 3/4 (closes) just drain
    }
  });

  std::vector<std::thread> senders;
  for (int t = 0; t < kSendThreads; t++) {
    senders.emplace_back([&, t] {
      std::vector<uint8_t> payload(16 + 97 * t, (uint8_t)t);
      for (int i = 0; i < kSendsPerThread; i++) {
        int len = 1 + (int)((i * 131 + t) % payload.size());
        if (ht_send(rp, peers[t], payload.data(), len) == 0)
          sent.fetch_add(1);
        else
          usleep(100);  // connect still in flight: retry cadence
        if (i % 50 == 49) usleep(500);  // let the reactor breathe
      }
    });
  }
  for (auto& th : senders) th.join();
  done_sending.store(true);
  drain.join();

  if (got_accepted.load() < sent.load())
    fail("transport dropped accepted-side frames");
  if (got_peer.load() < replied.load())
    fail("transport dropped reply frames");

  for (long p : peers) ht_close_conn(rp, p);
  ht_stop(rp);
  std::printf("transport stress done: sent=%ld delivered=%ld replies=%ld\n",
              sent.load(), got_accepted.load(), got_peer.load());
}

}  // namespace

int main(int argc, char** argv) {
  const char* which = argc > 1 ? argv[1] : "all";
  char tmpl[] = "/tmp/hs_san_XXXXXX";
  char* dir = mkdtemp(tmpl);
  if (!dir) {
    std::fprintf(stderr, "SAN_STRESS FAIL: mkdtemp\n");
    return 1;
  }
  if (std::strcmp(which, "transport") != 0) store_stress(dir);
  if (std::strcmp(which, "store") != 0) transport_stress();
  if (g_failed) return 1;
  std::printf("SAN_STRESS OK\n");
  return 0;
}
