#!/usr/bin/env python3
"""Admission-plane load check (ISSUE 10): does backpressure hold past
saturation?

Runs the open-loop client-fleet saturation sweep (benchmark/loadgen.py)
against a live local committee, then the 2x-saturation overload run with
a deliberately small proposer buffer, and asserts end to end:

  * SWEEP — the sweep completes and commits payloads (goodput > 0) with
    client-observed p50/p99 latency measured through the real
    submit->commit path;
  * TELEMETRY — every node published the ``ingest`` telemetry section
    (the admission story is observable, not inferred);
  * BACKPRESSURE — at 2x the measured saturation rate with
    ``HOTSTUFF_MAX_PENDING`` squeezed, overload is SHED (typed BUSY
    replies and/or client-side credit starvation), never silently
    dropped: ``proposer drop_newest`` must be exactly 0 while
    ``shed_server + shed_client`` is nonzero.

Usage:
    python scripts/load_check.py           # 4 nodes, short sweep
    LOAD=1 scripts/trace.sh                # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--start-rate", type=int, default=500)
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--max-steps", type=int, default=4)
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--conns", type=int, default=2)
    ap.add_argument(
        "--overload-max-pending", type=int, default=300,
        help="HOTSTUFF_MAX_PENDING for the 2x-saturation overload run "
        "(small so the buffer WOULD fill if credits failed)",
    )
    args = ap.parse_args(argv)

    from benchmark.loadgen import format_load_block, run_sweep

    print(" LOAD CHECK — admission-controlled payload plane under an "
          "open-loop client fleet")
    result = run_sweep(
        nodes=args.nodes,
        start_rate=args.start_rate,
        duration=args.duration,
        max_steps=args.max_steps,
        clients=args.clients,
        conns_per_node=args.conns,
        overload_max_pending=args.overload_max_pending,
    )
    print(format_load_block(result))

    fails: list[str] = []
    if result["goodput_tx_s"] <= 0:
        fails.append("sweep committed nothing (goodput 0 tx/s)")
    rows = result.get("rows") or []
    if not all(r.get("telemetry_present") for r in rows):
        fails.append(
            "ingest telemetry section missing from some node snapshots"
        )
    over = result.get("overload") or {}
    drops = over.get("drop_newest", 0)
    sheds = over.get("shed_server", 0) + over.get("shed_client", 0)
    if drops:
        fails.append(
            f"overload run SILENTLY dropped {drops} payload(s) at the "
            f"proposer buffer — admission credits failed to hold "
            f"occupancy below HOTSTUFF_MAX_PENDING="
            f"{args.overload_max_pending}"
        )
    if not sheds:
        fails.append(
            "overload run at 2x saturation shed nothing — either the "
            "rate never exceeded capacity (raise --max-steps) or the "
            "admission plane is not engaging"
        )

    if fails:
        print("load_check: FAIL")
        for msg in fails:
            print(f"  - {msg}")
        return 1
    print(
        f"load_check: OK (saturation {result['saturation_tx_s']} tx/s, "
        f"goodput {result['goodput_tx_s']} tx/s, overload shed {sheds} "
        f"with zero silent drops)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
