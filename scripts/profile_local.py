"""Profile an in-process committee run (VERDICT r3 item 6: decompose the
~20 ms local consensus-latency floor at 4 nodes / 1k tx/s).

Runs the whole committee (run-many shape) under cProfile in THIS process
while a client subprocess drives load, then prints the top functions by
cumulative and total time, plus a bucketed per-stage summary (crypto,
store, framing/network, asyncio machinery, serialization).

    python scripts/profile_local.py [--nodes 4] [--rate 1000] [--duration 10]
"""

import argparse
import asyncio
import cProfile
import os
import pstats
import subprocess
import sys
import time

sys.path.insert(0, ".")

BUCKETS = {
    # NB: patterns match against full file paths; "hotstuff_tpu/tpu/"
    # (not "tpu/") — a bare "tpu/" matches every hotstuff_tpu/ path and
    # swallows all buckets into crypto.
    "crypto": (
        "hotstuff_tpu/crypto/",
        "hotstuff_tpu/tpu/",
        "hashlib",
        "_hashlib",
        "openssl",  # cryptography's Ed25519 verify/sign builtins
    ),
    "store": ("hotstuff_tpu/store/",),
    "network": ("hotstuff_tpu/network/", "streams.py", "selector_events"),
    "serialization": ("utils/codec", "consensus/wire.py", "consensus/messages.py"),
    "consensus": (
        "consensus/core.py",
        "consensus/proposer.py",
        "consensus/aggregator.py",
        "consensus/synchronizer",
        "consensus/helper.py",
        "consensus/consensus.py",
        "consensus/leader.py",
        "consensus/timer.py",
        "consensus/config.py",
    ),
    "logging": ("logging/",),
    "asyncio": ("asyncio/", "selectors.py"),
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=int, default=1000)
    ap.add_argument("--duration", type=float, default=10.0)
    args = ap.parse_args()

    from benchmark.local import LocalBench
    from benchmark.logs import LogParser
    from benchmark.utils import PathMaker
    from hotstuff_tpu.node.main import setup_logging
    from hotstuff_tpu.node.node import Node

    bench = LocalBench(nodes=args.nodes, rate=args.rate, duration=args.duration)
    bench._cleanup_files()
    bench._config()
    setup_logging(2)
    # route node logs to the log file the parser expects
    import logging

    handler = logging.FileHandler(PathMaker.node_log_file(0))
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s.%(msecs)03dZ [%(levelname)s] %(name)s %(message)s",
            datefmt="%Y-%m-%dT%H:%M:%S",
        )
    )
    logging.getLogger().addHandler(handler)

    client = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "hotstuff_tpu.node.client",
            "--committee",
            PathMaker.committee_file(),
            "--rate",
            str(args.rate),
            "--duration",
            str(args.duration),
            "--warmup",
            "1",
        ],
        stdout=open(PathMaker.client_log_file(), "w"),
        stderr=subprocess.STDOUT,
        env={**os.environ, "PYTHONPATH": "."},
    )

    async def committee():
        nodes = []
        for i in range(args.nodes):
            nodes.append(
                await Node.new(
                    committee_file=PathMaker.committee_file(),
                    key_file=PathMaker.key_file(i),
                    store_path=PathMaker.db_path(i),
                    parameters_file=PathMaker.parameters_file(),
                    bind_host="127.0.0.1",
                )
            )
        from hotstuff_tpu.node.main import _freeze_boot_objects

        _freeze_boot_objects()  # match the production run-many GC shape
        drain = asyncio.gather(*(n.analyze_block() for n in nodes))
        await asyncio.sleep(args.duration + 3)
        drain.cancel()
        for n in nodes:
            try:
                await n.shutdown()
            except Exception:
                pass

    prof = cProfile.Profile()
    t0 = time.time()
    prof.enable()
    asyncio.run(committee())
    prof.disable()
    wall = time.time() - t0
    client.wait(timeout=10)

    stats = pstats.Stats(prof)
    stats.sort_stats("cumulative")
    print(f"=== wall: {wall:.1f}s ===")
    stats.print_stats(25)
    stats.sort_stats("tottime")
    print("=== top self time ===")
    stats.print_stats(30)

    # bucket tottime by module
    totals: dict[str, float] = {k: 0.0 for k in BUCKETS}
    other = 0.0
    grand = 0.0
    for (file, _line, fn), (_cc, _nc, tt, _ct, _callers) in stats.stats.items():
        grand += tt
        # built-in methods are keyed under file '~' with the detail in
        # the function-name field (e.g. "<method 'update' of
        # '_hashlib.HASH' objects>") — match both fields or C digest
        # time silently lands in 'other'
        where = file + " " + fn
        for bucket, pats in BUCKETS.items():
            if any(p in where for p in pats):
                totals[bucket] += tt
                break
        else:
            other = other + tt
    print("\n=== tottime buckets (s) ===")
    for k, v in sorted(totals.items(), key=lambda kv: -kv[1]):
        print(f"  {k:14s} {v:7.2f}  ({100*v/max(grand,1e-9):.0f}%)")
    print(f"  {'other':14s} {other:7.2f}  ({100*other/max(grand,1e-9):.0f}%)")
    print(f"  {'total':14s} {grand:7.2f}  (wall {wall:.1f}s)")

    parser = LogParser.process(PathMaker.logs_path())
    print(parser.result(faults=0, nodes=args.nodes, verifier="cpu-profiled"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
