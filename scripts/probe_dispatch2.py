"""Second dispatch probe: is the ~125 ms per-dispatch cost a fixed
tunnel RTT, or load-state-dependent (fast when idle, slow under
sustained dispatch)?  Measures the same resident-arg exec at different
points and paces.
"""

import time

import numpy as np

import jax


def q(xs):
    xs = sorted(xs)
    return {
        "p50": round(xs[len(xs) // 2] * 1000, 2),
        "min": round(xs[0] * 1000, 2),
        "max": round(xs[-1] * 1000, 2),
    }


def main():
    dev = jax.devices()[0]

    @jax.jit
    def f(x):
        return (x * 2 + 1).sum(axis=1)

    x_dev = jax.device_put(np.ones((256, 20), np.int32), dev)
    jax.block_until_ready(f(x_dev))

    def burst(n, sleep=0.0, label=""):
        ts = []
        for _ in range(n):
            t = time.perf_counter()
            jax.block_until_ready(f(x_dev))
            ts.append(time.perf_counter() - t)
            if sleep:
                time.sleep(sleep)
        print(f"{label}: {q(ts)}  (n={n}, sleep={sleep})")
        return ts

    burst(20, 0, "cold-ish back-to-back")
    time.sleep(2)
    burst(20, 0, "after 2s idle, back-to-back")
    burst(20, 0.1, "paced 100ms")
    burst(20, 0.02, "paced 20ms")
    time.sleep(2)
    # async issue then single wait: measure issue cost vs wait cost
    for k in (8,):
        t0 = time.perf_counter()
        outs = [f(x_dev) for _ in range(k)]
        t1 = time.perf_counter()
        jax.block_until_ready(outs)
        t2 = time.perf_counter()
        print(
            f"async x{k}: issue={round((t1-t0)*1e3,2)}ms "
            f"wait={round((t2-t1)*1e3,2)}ms"
        )


if __name__ == "__main__":
    main()
