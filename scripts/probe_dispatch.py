"""Probe the rig's per-dispatch cost anatomy (VERDICT r3 item 1 groundwork).

The round-3 bench recorded a flat ~112 ms rig p50 per QC-verify dispatch
regardless of batch size, while the in-dispatch device time is 0.2-0.5 ms.
Before redesigning the consensus integration, decompose that fixed cost:

  - h2d: host->device transfer round trip (jax.device_put + wait)
  - exec: dispatch of an already-resident computation (args on device)
  - d2h: result fetch (np.asarray on a device array)
  - e2e: the production-shaped call (numpy args in, bool out)
  - pipelined: N async dispatches issued back-to-back, one final block —
    does the tunnel pipeline them (cost ~1 RTT) or serialize (~N RTT)?

Run:  python scripts/probe_dispatch.py
"""

import time

import numpy as np

import jax
import jax.numpy as jnp


def q(xs):
    xs = sorted(xs)
    return {
        "p50": round(xs[len(xs) // 2] * 1000, 2),
        "min": round(xs[0] * 1000, 2),
        "max": round(xs[-1] * 1000, 2),
    }


def main():
    print("backend:", jax.default_backend(), jax.devices())
    dev = jax.devices()[0]

    @jax.jit
    def f(x):
        return (x * 2 + 1).sum(axis=1)

    x_host = np.ones((256, 20), np.int32)
    x_dev = jax.device_put(x_host, dev)
    jax.block_until_ready(f(x_dev))  # compile

    N = 15

    h2d = []
    for _ in range(N):
        t = time.perf_counter()
        jax.block_until_ready(jax.device_put(x_host, dev))
        h2d.append(time.perf_counter() - t)

    ex = []
    for _ in range(N):
        t = time.perf_counter()
        jax.block_until_ready(f(x_dev))
        ex.append(time.perf_counter() - t)

    y = f(x_dev)
    jax.block_until_ready(y)
    d2h = []
    for _ in range(N):
        t = time.perf_counter()
        np.asarray(y)
        d2h.append(time.perf_counter() - t)

    e2e = []
    for _ in range(N):
        t = time.perf_counter()
        np.asarray(f(x_host))
        e2e.append(time.perf_counter() - t)

    # pipelining: issue K dispatches without blocking, then block once
    pipe = {}
    for k in (1, 4, 16):
        ts = []
        for _ in range(N):
            t = time.perf_counter()
            outs = [f(x_dev) for _ in range(k)]
            jax.block_until_ready(outs)
            ts.append(time.perf_counter() - t)
        pipe[k] = q(ts)

    # many-arg dispatch (the production kernel takes 8 arrays): does each
    # host numpy arg add a separate transfer round trip?
    @jax.jit
    def g(a, b, c, d, e, f_, g_, h):
        return (a + b + c + d + e + f_ + g_ + h).sum(axis=1)

    args = [np.ones((256, 20), np.int32) for _ in range(8)]
    jax.block_until_ready(g(*args))
    many = []
    for _ in range(N):
        t = time.perf_counter()
        np.asarray(g(*args))
        many.append(time.perf_counter() - t)

    print("h2d (device_put 20KB):", q(h2d))
    print("exec (resident args):", q(ex))
    print("d2h (np.asarray 1KB):", q(d2h))
    print("e2e 1-arg (numpy in, numpy out):", q(e2e))
    print("e2e 8-arg:", q(many))
    print("pipelined exec:", pipe)


if __name__ == "__main__":
    main()
