#!/usr/bin/env python3
"""Commit critical-path attribution check (docs/TELEMETRY.md, ISSUE 17).

Drives the whole observability loop end-to-end against a real 4-node
committee and exits non-zero when ANY contract breaks:

1. **Journaled run #1** — ``benchmark local --nodes 4 --journal``: the
   run must PASS, print the ``+ CRITPATH`` SUMMARY block, and the merged
   journals must attribute with coverage >= 90% (the acceptance floor:
   less means the causal chain reconstruction is dropping edges).
2. **Attribution-diff gate** — ``benchmark critpath --diff`` against the
   run's own attribution document must exit 0 (unchanged re-run), and
   against a PLANTED reference (the dominant stage's share shifted past
   the tolerance) must exit non-zero — the shape gate catches a stage
   regression even when the scalar latency holds.
3. **Journaled run #2** — a second identical run: the regime
   classification (network-/verify-/aggregation-/ingest-bound) must
   match run #1 — same committee, same load, same verdict.

The default rate (2000 tx/s, past this rig's admission knee) pins the
committee firmly inside ONE regime (ingest-bound: payload queueing
dominates, ~7pp ahead of the network group).  At moderate rates a
localhost committee sits ON the ingest/network boundary — payload wait
is structurally about half a round — and the argmax regime legitimately
coin-flips between runs, which is a property of the operating point,
not an attribution bug.

Usage:
    python scripts/critpath_check.py [--rate R] [--duration D]
    CRIT=1 scripts/trace.sh               # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: acceptance floor for causal-chain attribution coverage (ISSUE 17)
MIN_COVERAGE_PCT = 90.0


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {label}"
          + (f" — {detail}" if detail and not ok else ""))
    return ok


def _run_local(rate: int, duration: int) -> tuple[int, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "benchmark", "local",
         "--nodes", "4", "--rate", str(rate),
         "--duration", str(duration), "--journal"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    return proc.returncode, proc.stdout + proc.stderr


def _run_critpath_cli(diff_path: str | None = None) -> int:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "benchmark", "critpath"]
    if diff_path is not None:
        cmd += ["--diff", diff_path]
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    return proc.returncode


def _analyze() -> dict | None:
    """Attribution document for the journals the last run left behind."""
    from benchmark.critpath import analyze_dir
    from benchmark.utils import PathMaker

    traces, report = analyze_dir(PathMaker.journals_path())
    if not traces.journals or not report.commits:
        return None
    return report.attribution()


def _plant_regression(att: dict, pp: float) -> dict:
    """A reference in which the CURRENT dominant stage's share reads as
    having grown by ``pp + 5`` percentage points — i.e. shrink it in the
    reference so the diff against the live document must fail."""
    planted = json.loads(json.dumps(att))  # deep copy
    stages = planted.get("stages", {})
    top = max(stages, key=lambda s: stages[s].get("share", 0.0))
    shift = (pp + 5.0) / 100.0
    stages[top]["share"] = max(0.0, stages[top]["share"] - shift)
    return planted


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=int, default=2000)
    ap.add_argument("--duration", type=int, default=15)
    args = ap.parse_args(argv)

    os.chdir(REPO)
    from benchmark.critpath import diff_share_pp

    failed = False

    print("=== phase 1: journaled 4-node run, attribution coverage ===")
    rc, out = _run_local(args.rate, args.duration)
    failed |= not check("run #1 PASSes (exit 0)", rc == 0, f"exit {rc}")
    failed |= not check("+ CRITPATH block in SUMMARY", "+ CRITPATH" in out)
    att1 = _analyze()
    failed |= not check("journals attribute commits", att1 is not None)
    if att1 is None:
        print("critpath check: FAIL")
        return 1
    failed |= not check(
        f"attribution coverage >= {MIN_COVERAGE_PCT:.0f}%",
        att1["coverage_pct"] >= MIN_COVERAGE_PCT,
        f"coverage {att1['coverage_pct']:.1f}%",
    )
    failed |= not check(
        "regime classified", att1["regime"] != "unknown", att1["regime"]
    )
    print(f"  (run #1: {att1['commits']} commits, p50 "
          f"{att1['p50_ms']:.1f} ms, regime {att1['regime']}, coverage "
          f"{att1['coverage_pct']:.1f}%)")

    print("=== phase 2: attribution-diff gate ===")
    with tempfile.TemporaryDirectory(prefix="critpath-check-") as tmp:
        ref_same = os.path.join(tmp, "ref-same.json")
        with open(ref_same, "w") as f:
            json.dump(att1, f)
        rc = _run_critpath_cli(diff_path=ref_same)
        failed |= not check("unchanged re-run passes --diff", rc == 0,
                            f"exit {rc}")
        ref_planted = os.path.join(tmp, "ref-planted.json")
        with open(ref_planted, "w") as f:
            json.dump(_plant_regression(att1, diff_share_pp()), f)
        rc = _run_critpath_cli(diff_path=ref_planted)
        failed |= not check("planted share regression FAILS --diff",
                            rc != 0, f"exit {rc}")

    print("=== phase 3: regime stable across two runs ===")
    rc, out = _run_local(args.rate, args.duration)
    failed |= not check("run #2 PASSes (exit 0)", rc == 0, f"exit {rc}")
    att2 = _analyze()
    failed |= not check("run #2 attributes commits", att2 is not None)
    if att2 is not None:
        failed |= not check(
            "regime stable across runs",
            att2["regime"] == att1["regime"],
            f"run #1 {att1['regime']} vs run #2 {att2['regime']}",
        )
        print(f"  (run #2: {att2['commits']} commits, regime "
              f"{att2['regime']}, coverage {att2['coverage_pct']:.1f}%)")

    print("critpath check:", "FAIL" if failed else "PASS")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
