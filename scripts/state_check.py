#!/usr/bin/env python3
"""Replicated execution-layer check (docs/STATE.md).

Runs two canned scenarios through the production chaos runner
(``python -m benchmark chaos``) and asserts the state-root contracts
each one exists to prove:

- ``rolling-crash-restart`` — a SIGKILLed node rejoins through
  snapshot state-sync (no history replay) and its incremental state
  root converges with the committee: run PASSes (exit 0), the
  ``+ CHAOS`` block reports state-root agreement PASS, and the node
  logs carry the ``Adopted state snapshot`` / ``history replay
  skipped`` evidence.
- ``byz-collude`` — a shadow-committing colluding pair reports roots
  chained over its shadow history: full-history state-root agreement
  must FAIL with the divergence attributed to the colluders, while the
  trusted-subset re-check over honest nodes still PASSes.

Exit non-zero when ANY contract breaks — including byz-collude's
state roots "agreeing", which would mean the execution layer stopped
folding what nodes actually commit.

Usage:
    python scripts/state_check.py [--seed N] [--rate R] [--duration S]
    STATE=1 scripts/trace.sh              # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RE_STATE_ROOT = re.compile(r"State root (\d+) -> (\S+) \(round (\d+)\)")
RE_ADOPTED = re.compile(r"Adopted state snapshot version (\d+)")
RE_CURSOR = re.compile(
    r"State sync advanced commit cursor (\d+) -> (\d+) "
    r"\(history replay skipped\)"
)


def run_scenario(name: str, seed: int, rate: int, duration: int,
                 extra_env: dict | None = None) -> tuple[int, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [
            sys.executable, "-m", "benchmark", "chaos",
            "--scenario", name, "--seed", str(seed),
            "--rate", str(rate), "--duration", str(duration),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=duration + 240,
    )
    return proc.returncode, proc.stdout + proc.stderr


def node_logs() -> dict[str, str]:
    out = {}
    for path in sorted(glob.glob(os.path.join(REPO, "logs", "node-*.log"))):
        with open(path, errors="replace") as f:
            out[os.path.basename(path)] = f.read()
    return out


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {label}"
          + (f" — {detail}" if detail and not ok else ""))
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=int, default=400)
    ap.add_argument("--duration", type=int, default=30,
                    help="per-run seconds (rolling-crash-restart's last "
                    "restart is at t=15, so keep >= 30)")
    args = ap.parse_args(argv)

    failed = False

    print(f"=== rolling-crash-restart (seed {args.seed}) ===")
    # lag threshold 2 so even a short outage is rejoined via snapshot
    # instead of per-block sync (the default 8-round threshold would
    # make the test depend on round cadence)
    rc, out = run_scenario(
        "rolling-crash-restart", args.seed, args.rate, args.duration,
        extra_env={"HOTSTUFF_STATE_SYNC_LAG": "2"},
    )
    failed |= not check("run PASSes (exit 0)", rc == 0, f"exit {rc}")
    failed |= not check("+ CHAOS block rendered", "+ CHAOS:" in out)
    failed |= not check(
        "state-root agreement verdict is PASS",
        "State-root agreement: PASS" in out,
    )
    logs = node_logs()
    adopted = {n for n, text in logs.items() if RE_ADOPTED.search(text)}
    failed |= not check(
        "a restarted node adopted a snapshot",
        bool(adopted),
        "no 'Adopted state snapshot' line in any node log",
    )
    failed |= not check(
        "snapshot rejoin skipped history replay",
        any(RE_CURSOR.search(text) for text in logs.values()),
        "no 'history replay skipped' cursor advance in any node log",
    )
    reporting = {n for n, text in logs.items() if RE_STATE_ROOT.search(text)}
    failed |= not check(
        "every node reports state roots",
        len(reporting) == len(logs) and bool(logs),
        f"{sorted(reporting)} of {len(logs)} logs report roots",
    )

    print(f"=== byz-collude (seed {args.seed}) ===")
    rc, out = run_scenario("byz-collude", args.seed, args.rate,
                           args.duration)
    failed |= not check("run FAILs (non-zero exit)", rc != 0, f"exit {rc}")
    failed |= not check(
        "full-history state-root agreement is FAIL",
        "State-root agreement: FAIL" in out,
    )
    failed |= not check(
        "state-root divergence names a version",
        "state-root divergence at version" in out,
    )
    failed |= not check(
        "trusted-subset state roots still agree (honest nodes consistent)",
        "Trusted-subset state roots (adversaries excluded): PASS" in out,
    )

    print("state matrix:", "FAIL" if failed else "ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
