"""Compile-probe: which fused-kernel tile shapes fit the scoped VMEM
limit on the real TPU (the wave-batched kernel's transients tripled the
per-tile footprint: batch-1024 @ bt=256 OOMed at 21.7M vs the 16M cap).

Tries the fused unsplit kernel at bt=128/256 and the fused split kernel
at tile 256, reporting compile success/OOM + a quick slope timing for
the ones that fit.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hotstuff_tpu  # noqa: F401,E402


def main() -> int:
    import jax

    from hotstuff_tpu.crypto import ed25519_ref as ref
    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    print("platform:", jax.devices()[0].platform, flush=True)

    def items(n):
        seed = b"\x5a" * 32
        msg = b"probe"
        pk = ref.public_from_seed(seed)
        sig = ref.sign(seed, msg)
        return [msg] * n, [pk] * n, [sig] * n

    v = BatchVerifier(min_device_batch=0)

    # split kernel shape: n <= SPLIT_MAX -> rows 2n, tile 256
    for label, n in (("split/tile256 (64 sigs)", 64),
                     ("unsplit/bt256 (256 sigs)", 256),
                     ("unsplit/bt256 (1024 batch)", 1024)):
        t0 = time.perf_counter()
        try:
            out = v.verify(*items(n))
            ok = bool(np.asarray(out).all())
            print(f"{label}: OK valid={ok} "
                  f"({time.perf_counter() - t0:.1f}s)", flush=True)
        except Exception as e:
            msg = str(e)
            brief = "VMEM OOM" if "vmem" in msg.lower() else msg[:160]
            print(f"{label}: FAIL {brief} "
                  f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
