#!/usr/bin/env python3
"""Mesh scale-out check (ISSUE 7): does the sharded mesh backend still
scale under the production dispatch pipeline?

Drives the mesh wave-train (``benchmark/meshtrain.py``) at mesh sizes
1 and 8 on the virtual 8-device CPU mesh — each size in its own child
process with ``HOTSTUFF_MESH_DEVICES`` set before jax loads, exactly
the node CLI's ``--mesh-devices`` path — prints the per-mesh sustained
train rates, and exits non-zero when the mesh-8 scaling efficiency
falls below the floor.

The floor is self-calibrating: half the efficiency recorded in the
committed reference round's ``mesh_train`` block (``--ref``, default
the latest BENCH_r*.json carrying one), overridable with
``MESH_EFF_FLOOR``; with no reference the absolute default floor is
0.02 (the virtual mesh shares one socket — the check catches the
sharded path COLLAPSING, not sub-linear CPU scaling).

Usage:
    python scripts/mesh_check.py          # train + compare
    MESH=1 scripts/trace.sh               # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MESH_SIZES = (1, 8)
ABS_FLOOR = 0.02
REF_SHARE = 0.5


def load_ref_efficiency(ref: str | None) -> tuple[float, str] | None:
    """mesh_scaling_efficiency from the committed reference: an explicit
    --ref file, else the newest BENCH_r*.json that carries one."""
    paths = (
        [ref]
        if ref
        else sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")), reverse=True)
    )
    for path in paths:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        doc = rec.get("parsed") if isinstance(rec.get("parsed"), dict) else rec
        eff = ((doc or {}).get("mesh_train") or {}).get(
            "mesh_scaling_efficiency"
        )
        if isinstance(eff, (int, float)) and eff > 0:
            return float(eff), os.path.basename(path)
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ref", default=None,
                    help="reference BENCH round (default: newest "
                    "BENCH_r*.json with a mesh_train block)")
    ap.add_argument("--batches", default="256,1024",
                    help="train batch sizes (default 256,1024 — smaller "
                    "than bench.py's sweep to keep the check fast)")
    ap.add_argument("--train", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args(argv)

    from benchmark.meshtrain import run_mesh_train

    batches = tuple(int(x) for x in args.batches.split(",") if x)
    result = run_mesh_train(
        mesh_sizes=MESH_SIZES,
        batches=batches,
        train=args.train,
        reps=args.reps,
        force_virtual=True,
    )

    print(" MESH CHECK — sustained train sigs/s per mesh size "
          "(virtual CPU mesh)")
    for m_str, doc in sorted(
        result.get("per_mesh", {}).items(), key=lambda kv: int(kv[0])
    ):
        rates = ", ".join(
            f"{b}: {v['train_sigs_per_s']}"
            for b, v in sorted(
                doc["per_batch"].items(), key=lambda kv: int(kv[0])
            )
        )
        print(f"   mesh {m_str}: {rates}  (devices {doc['mesh_devices']})")
    for m_str, err in (result.get("errors") or {}).items():
        print(f"   mesh {m_str}: CHILD FAILED — {err}")

    eff = result.get("mesh_scaling_efficiency")
    if eff is None:
        print("mesh_check: FAIL — no mesh-8 efficiency "
              "(a child died or mesh 1 is missing)")
        return 1

    env_floor = os.environ.get("MESH_EFF_FLOOR")
    if env_floor:
        floor, provenance = float(env_floor), "MESH_EFF_FLOOR"
    else:
        ref = load_ref_efficiency(args.ref)
        if ref:
            floor = ref[0] * REF_SHARE
            provenance = f"{ref[1]} x {REF_SHARE:g}"
        else:
            floor, provenance = ABS_FLOOR, "absolute default"
    print(f"   mesh-8 scaling efficiency {eff:.4f} "
          f"(floor {floor:.4f} from {provenance})")
    if eff < floor:
        print("mesh_check: FAIL — mesh-8 efficiency below the floor; "
              "the sharded dispatch path has collapsed")
        return 1
    print("mesh_check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
