#!/usr/bin/env python3
"""Wire-level flow accounting check (docs/TELEMETRY.md, ISSUE 19).

Drives the flow-accounting plane end-to-end and exits non-zero when ANY
contract breaks:

1. **Journaled run #1** — ``benchmark local --nodes 4 --journal``: the
   run must PASS, print the ``+ NET`` SUMMARY block, and the parsed
   flow ledgers must satisfy the acceptance floors: median propose
   amplification within 20% of n-1 (round-robin leaders broadcast every
   proposal to the other n-1 peers), per-class byte shares summing to
   >= 95% of accounted egress (less means frames are being charged to
   thin air), compact QCs cheaper on the wire than the quorum-sized
   vote list they replace, and ZERO retransmitted bytes on clean
   localhost links.
2. **Determinism** — the same honest sim schedule run twice must
   produce byte-identical per-node flow tables (the accounting rides
   the deterministic plane: same seed, same ledger, to the byte).
3. **Flapping-link chaos** — a sim schedule with sustained lossy links
   must still land propose amplification in a sane band (>= 1, and
   bounded by retransmit inflation); a lossy link CAN legitimately
   retransmit, so retx is reported, not gated, here.

Usage:
    python scripts/net_check.py [--rate R] [--duration D]
    NET=1 scripts/trace.sh                # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: acceptance: median propose amplification within this fraction of n-1
AMP_TOLERANCE = 0.20

#: acceptance: per-class shares must cover this much of accounted egress
MIN_CLASS_COVERAGE = 0.95


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {label}"
          + (f" — {detail}" if detail and not ok else ""))
    return ok


def _run_local(rate: int, duration: int) -> tuple[int, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["HOTSTUFF_NET"] = "1"  # the plane under test must be on
    proc = subprocess.run(
        [sys.executable, "-m", "benchmark", "local",
         "--nodes", "4", "--rate", str(rate),
         "--duration", str(duration), "--journal"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    return proc.returncode, proc.stdout + proc.stderr


def _amp_from_tables(flows: dict) -> list[float]:
    """Per-node propose amplification (wire/logical egress) from the
    sim verdict's flow tables — the same rollup bench.py publishes."""
    amps = []
    for tables in flows.values():
        wire = logical = 0
        for table in tables:
            for key, row in (table.get("flows") or {}).items():
                _peer, d, cls = key.rsplit("|", 2)
                if d == "tx" and cls == "propose":
                    wire += row[0]
            row = (table.get("logical") or {}).get("propose")
            if row:
                logical += row[0]
        if logical:
            amps.append(wire / logical)
    return sorted(amps)


def _retx_from_tables(flows: dict) -> int:
    total = 0
    for tables in flows.values():
        for table in tables:
            for row in (table.get("flows") or {}).values():
                total += row[2]
    return total


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=int, default=500)
    ap.add_argument("--duration", type=int, default=10)
    args = ap.parse_args(argv)

    os.chdir(REPO)
    failed = False

    print("=== phase 1: journaled 4-node run, flow ledger floors ===")
    rc, out = _run_local(args.rate, args.duration)
    failed |= not check("run #1 PASSes (exit 0)", rc == 0, f"exit {rc}")
    failed |= not check("+ NET block in SUMMARY", "+ NET" in out)

    from benchmark.logs import LogParser
    from benchmark.utils import PathMaker

    parser = LogParser.process(PathMaker.logs_path())
    net = parser.net_summary()
    failed |= not check("flow accounting enabled on all nodes",
                        net is not None and net["nodes"] > 0)
    if net is None:
        print("net check: FAIL")
        return 1

    n = parser.num_node_logs
    amp, target = net["leader_amp_p50"], float(n - 1)
    failed |= not check(
        f"propose amp p50 within {AMP_TOLERANCE:.0%} of n-1={target:g}",
        amp is not None and abs(amp - target) <= AMP_TOLERANCE * target,
        f"amp p50 {amp}",
    )
    covered = sum(net["class_tx_bytes"].values())
    failed |= not check(
        f"class shares cover >= {MIN_CLASS_COVERAGE:.0%} of egress",
        net["tx_bytes"] > 0
        and covered >= MIN_CLASS_COVERAGE * net["tx_bytes"],
        f"{covered:,} of {net['tx_bytes']:,} B",
    )
    vote_b = net["class_tx_bytes"].get("vote", 0)
    vote_f = net["class_tx_frames"].get("vote", 0)
    quorum = n - (n - 1) // 3
    votelist = round(quorum * vote_b / vote_f) if vote_f else 0
    failed |= not check(
        "compact QC cheaper on the wire than the vote list it replaces",
        0 < parser.qc_wire_bytes < votelist,
        f"qc {parser.qc_wire_bytes:,} B vs vote list ~{votelist:,} B",
    )
    failed |= not check(
        "zero retransmitted bytes on clean localhost links",
        net["retx_bytes"] == 0,
        f"{net['retx_bytes']:,} retx B",
    )
    print(f"  (run #1: {net['tx_bytes']:,} B egress across {net['nodes']} "
          f"nodes, amp p50 {amp}, "
          f"{net['wire_bytes_per_commit']:,} B/commit)")

    print("=== phase 2: same-seed sim runs are byte-identical ===")
    from hotstuff_tpu.sim import draw_schedule, run_schedule

    schedule = draw_schedule(3, nodes=4, profile="honest")
    v1 = run_schedule(schedule)
    v2 = run_schedule(schedule)
    failed |= not check("sim run #1 PASSes", v1.ok)
    failed |= not check("flow tables harvested", bool(v1.flows))
    failed |= not check(
        "double-run flow tables byte-identical",
        json.dumps(v1.flows, sort_keys=True)
        == json.dumps(v2.flows, sort_keys=True),
    )

    print("=== phase 3: amp sanity under flapping-link chaos ===")
    flapping = {
        "version": schedule["version"],
        "seed": 11,
        "nodes": 4,
        "duration_s": 9.0,
        "profile": "honest",
        # two lossy links flapping across most of the run: enough to
        # force reconnect/retransmit churn without breaking liveness
        "events": [
            {"kind": "loss", "from": [0], "to": [1], "drop": 0.25,
             "at": 1.5, "until": 3.5},
            {"kind": "loss", "from": [2], "to": [3], "drop": 0.25,
             "at": 2.0, "until": 4.0},
            {"kind": "loss", "from": [0], "to": [1], "drop": 0.2,
             "at": 4.5, "until": 5.5},
        ],
    }
    v3 = run_schedule(flapping)
    failed |= not check("chaos run PASSes invariants", v3.ok)
    amps = _amp_from_tables(v3.flows)
    amp3 = amps[len(amps) // 2] if amps else None
    # retransmits inflate the wire side, never deflate it: sane means
    # at least broadcast-shaped and not runaway duplication
    failed |= not check(
        "propose amp sane under chaos (1 <= amp <= 3x(n-1))",
        amp3 is not None and 1.0 <= amp3 <= 3.0 * (4 - 1),
        f"amp p50 {amp3}",
    )
    retx = _retx_from_tables(v3.flows)
    print(f"  (chaos run: amp p50 {amp3 and round(amp3, 2)}, "
          f"{retx:,} retx B — informational)")

    print("net check:", "FAIL" if failed else "PASS")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
