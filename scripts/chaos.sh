#!/usr/bin/env bash
# Run the canned chaos scenarios (docs/FAULTS.md) against a local
# committee and check the safety/liveness invariants after each.
#
#   scripts/chaos.sh                    # all four scenarios, seed 7
#   scripts/chaos.sh --seed 3 split-brain flapping-link
#   scripts/chaos.sh --transport native # native reactor instead of asyncio
#
# Exits non-zero if ANY scenario fails an invariant.
set -u

cd "$(dirname "$0")/.."

SEED=7
TRANSPORT=asyncio
RATE=400
EXTRA=()
SCENARIOS=()
while [ $# -gt 0 ]; do
  case "$1" in
    --seed)      SEED=$2; shift 2 ;;
    --transport) TRANSPORT=$2; shift 2 ;;
    --rate)      RATE=$2; shift 2 ;;
    --journal)   EXTRA+=(--journal); shift ;;
    -h|--help)   sed -n '2,9p' "$0"; exit 0 ;;
    *)           SCENARIOS+=("$1"); shift ;;
  esac
done
if [ ${#SCENARIOS[@]} -eq 0 ]; then
  SCENARIOS=(split-brain leader-isolation flapping-link rolling-crash-restart)
fi

FAILED=0
for scenario in "${SCENARIOS[@]}"; do
  echo "=== chaos: $scenario (seed $SEED, $TRANSPORT) ==="
  JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmark chaos \
    --scenario "$scenario" --seed "$SEED" --transport "$TRANSPORT" \
    --rate "$RATE" ${EXTRA[@]+"${EXTRA[@]}"} || FAILED=1
done
exit $FAILED
