#!/usr/bin/env python3
"""Performance regression gate for the verify rig.

Compares a FRESH ``bench.py`` run against the committed reference
(latest ``BENCH_r*.json``, falling back to ``BASELINE.json``) and exits
non-zero when either guarded metric regresses past the threshold
(default 15%):

  * ``qc_verify_ms.256.rig_p50_ms``  — QC-256 end-to-end verify latency
    (the number the span waterfall decomposes; may not rise >15%)
  * ``value``                        — batch-1024 verify throughput in
    sigs/s (may not fall >15%)
  * ``pipeline.train_sigs_per_s``    — sustained QC-256 wave-train
    throughput through the depth-2 dispatch pipeline (ISSUE 5; may not
    fall >15%)
  * ``mesh_train.mesh_scaling_efficiency`` — per-mesh-size sustained
    train sigs/s at the largest mesh vs single-device (ISSUE 7; wide
    per-guard 50% gate — the virtual CPU mesh is noisy)
  * ``agg_qc.verify_p50_ms`` — compact-QC one-pairing verify at the
    largest benched committee (ISSUE 9; per-guard 75% gate — the value
    is a single host pairing, so only a structural regression such as
    losing the key-sum memo or the native pairing should trip it)
  * ``state.apply_tx_s`` / ``state.sync_catchup_s`` — replicated
    execution-layer apply throughput and snapshot serve+adopt wall cost
    (ISSUE 11; wide per-guard 50% gates, skip-if-missing)
  * ``sim.rounds_per_s`` / ``sim.seeds_per_min`` — deterministic
    simulator sweep throughput (ISSUE 15; wide per-guard 50% gates,
    skip-if-missing)
  * ``adapt.schedules_per_min`` / ``adapt.fitness_evals_per_s`` —
    adaptive-adversary guided-search throughput (ISSUE 18; wide
    per-guard 50% gates, skip-if-missing)
  * ``net.leader_amp_p50`` / ``net.wire_bytes_per_commit`` —
    wire-level flow accounting rollup: median propose-amplification
    factor (gated in both directions — a fall means lost charges, a
    rise means redundant sends) and committee wire egress per commit
    (ISSUE 19; wide per-guard 50% gates, skip-if-missing)

``tunnel_dispatch_p50_ms`` is gated as a RATCHET instead of a guard
(ISSUE 6): the fresh value must stay within ``--ratchet-slack``
(default 1.25x) of the BEST value anywhere in the committed BENCH
series — not the latest.  The old latest-reference guard silently
absorbed a slow drift (each round only had to beat the previous round's
weather); the ratchet pins the series' best as the floor, with the
slack absorbing tunnel weather.  ``--no-ratchet`` skips it (e.g. on a
known-degraded rig).

Guards missing from either side are skipped, so old references gate
only the metrics they carry.

Usage:

    python scripts/perfgate.py                 # runs bench.py itself
    python scripts/perfgate.py --fresh out.txt # pre-captured output
    python scripts/perfgate.py --fresh -       # ... from stdin
    PERFGATE=1 scripts/trace.sh                # opt-in after a trace run

The comparison logic is import-safe pure functions so tests can drive
it without spawning a benchmark.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (human name, extractor, direction[, threshold]) — direction +1 means
#: "higher is a regression" (latency), -1 means "lower is a regression"
#: (throughput).  An optional 4th element overrides the run's threshold
#: for THAT guard.  The tunnel dispatch cost is NOT in this table: it is
#: ratcheted against the best of the whole BENCH series (see below).
GUARDS = (
    (
        "qc_verify_ms.256.rig_p50_ms",
        lambda doc: doc.get("qc_verify_ms", {}).get("256", {}).get(
            "rig_p50_ms"
        ),
        +1,
    ),
    ("value (sigs/s)", lambda doc: doc.get("value"), -1),
    (
        "pipeline.train_sigs_per_s",
        lambda doc: (doc.get("pipeline") or {}).get("train_sigs_per_s"),
        -1,
    ),
    # mesh scale-out health (ISSUE 7): sustained-train efficiency at the
    # largest mesh vs single-device.  The virtual CPU mesh shares one
    # socket, so the absolute value is small and noisy — hence the wide
    # per-guard 50% gate; skip-if-missing covers references from before
    # the mesh_train block existed.
    (
        "mesh_train.mesh_scaling_efficiency",
        lambda doc: (doc.get("mesh_train") or {}).get(
            "mesh_scaling_efficiency"
        ),
        -1,
        0.5,
    ),
    # compact-QC verify (ISSUE 9): ONE pairing over the memoized key sum
    # at the largest benched committee.  Skip-if-missing covers
    # references predating the agg_qc block; the wide 75% per-guard gate
    # tolerates host pairing jitter while still catching a lost memo or
    # a fall off the native pairing path (both are >2x).
    (
        "agg_qc.verify_p50_ms",
        lambda doc: (doc.get("agg_qc") or {}).get("verify_p50_ms"),
        +1,
        0.75,
    ),
    # admission-controlled payload plane (ISSUE 10): committed goodput
    # and client-observed tail latency from a short loadgen run against
    # a live 4-node committee.  Both are end-to-end numbers through the
    # whole consensus stack on a shared single-core rig, so the
    # per-guard gates are wide; skip-if-missing covers references from
    # before the load block existed.
    (
        "load.goodput_tx_s",
        lambda doc: (doc.get("load") or {}).get("goodput_tx_s"),
        -1,
        0.5,
    ),
    (
        "load.client_p99_ms",
        lambda doc: (doc.get("load") or {}).get("client_p99_ms"),
        +1,
        0.75,
    ),
    # replicated execution layer (ISSUE 11): typed-op apply throughput
    # through StateMachine.apply_block and the wall cost of a full
    # snapshot serve+adopt cycle (the no-replay rejoin path).  Both run
    # on the WAL engine of a shared single-core rig, so the per-guard
    # gates are wide; skip-if-missing covers references from before the
    # state block existed.
    (
        "state.apply_tx_s",
        lambda doc: (doc.get("state") or {}).get("apply_tx_s"),
        -1,
        0.5,
    ),
    (
        "state.sync_catchup_s",
        lambda doc: (doc.get("state") or {}).get("sync_catchup_s"),
        +1,
        0.5,
    ),
    # deterministic simulator (ISSUE 15): how fast this host chews
    # through exploration seeds — consensus rounds simulated per wall
    # second and seeds per minute over a short sweep.  Whole-committee
    # Python on a shared single-core rig, so the per-guard gates are
    # wide; skip-if-missing covers references from before the sim block
    # existed.
    (
        "sim.rounds_per_s",
        lambda doc: (doc.get("sim") or {}).get("rounds_per_s"),
        -1,
        0.5,
    ),
    (
        "sim.seeds_per_min",
        lambda doc: (doc.get("sim") or {}).get("seeds_per_min"),
        -1,
        0.5,
    ),
    # commit critical-path attribution (ISSUE 17): end-to-end commit
    # latency p50 and attribution coverage from the journal-merged
    # critpath engine over a sim sweep.  Whole-committee Python on a
    # shared rig — wide gates; skip-if-missing covers references from
    # before the critpath block existed.  The attribution SHAPE (per
    # stage share) is gated separately by attribution_check() below —
    # a stage whose share of commit latency balloons fails the gate
    # even when these scalars hold.
    (
        "critpath.p50_ms",
        lambda doc: (doc.get("critpath") or {}).get("p50_ms"),
        +1,
        0.75,
    ),
    (
        "critpath.coverage_pct",
        lambda doc: (doc.get("critpath") or {}).get("coverage_pct"),
        -1,
        0.25,
    ),
    # adaptive-adversary guided search (ISSUE 18): candidate schedules
    # simulated per minute and fitness evaluations per second — the two
    # throughputs that bound how much schedule space a guided-search
    # budget actually covers.  Whole-committee Python on a shared
    # single-core rig, so the per-guard gates are wide; skip-if-missing
    # covers references from before the adapt block existed.
    (
        "adapt.schedules_per_min",
        lambda doc: (doc.get("adapt") or {}).get("schedules_per_min"),
        -1,
        0.5,
    ),
    (
        "adapt.fitness_evals_per_s",
        lambda doc: (doc.get("adapt") or {}).get("fitness_evals_per_s"),
        -1,
        0.5,
    ),
    # wire-level flow accounting (ISSUE 19): the median per-node
    # propose-amplification factor (wire/logical egress; exactly n-1
    # when every proposal is one broadcast — a FALL means charges went
    # missing, a RISE means redundant sends crept in, both regressions,
    # so the amp guard gates in both directions via two entries) and the
    # committee's wire egress per committed block.  Skip-if-missing
    # covers references from before the net block existed.
    (
        "net.leader_amp_p50",
        lambda doc: (doc.get("net") or {}).get("leader_amp_p50"),
        +1,
        0.5,
    ),
    (
        "net.leader_amp_p50 (floor)",
        lambda doc: (doc.get("net") or {}).get("leader_amp_p50"),
        -1,
        0.5,
    ),
    (
        "net.wire_bytes_per_commit",
        lambda doc: (doc.get("net") or {}).get("wire_bytes_per_commit"),
        +1,
        0.5,
    ),
    # zero-copy ingest throughput (ISSUE 20): sustained wire -> arena ->
    # device sigs/s through the native wave packer + verify_packed.
    # Skip-if-missing covers references from before the ingest block
    # existed and hosts without the native toolchain; the wide 50% gate
    # tolerates simulated-device weather while catching a fall off the
    # arena fast path (the flatten detour alone is >2x on large waves).
    (
        "ingest.zero_copy_sigs_per_s",
        lambda doc: (doc.get("ingest") or {}).get("zero_copy_sigs_per_s"),
        -1,
        0.5,
    ),
)

#: the ratcheted metric: lower is better, fresh must stay within
#: RATCHET_SLACK of the series-wide best
RATCHET_METRIC = "tunnel_dispatch_p50_ms"
RATCHET_SLACK = 1.25


def last_json_line(text: str) -> dict | None:
    """The bench contract: the result is the LAST parseable JSON object
    line of stdout (jax warnings etc. precede it)."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def load_reference(repo: str = REPO) -> tuple[dict, str] | None:
    """Latest ``BENCH_r*.json``'s metrics (its ``parsed`` dict, or the
    JSON line inside ``tail``), else ``BASELINE.json`` if it carries
    published numbers.  Returns (metrics, source-path) or None."""
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json")),
                       reverse=True):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        doc = rec.get("parsed") or last_json_line(rec.get("tail", ""))
        if isinstance(doc, dict) and any(
            fn(doc) is not None for _, fn, *_ in GUARDS
        ):
            return doc, path
    base = os.path.join(repo, "BASELINE.json")
    try:
        with open(base) as f:
            doc = json.load(f).get("published") or {}
    except (OSError, ValueError):
        return None
    if any(fn(doc) is not None for _, fn, *_ in GUARDS):
        return doc, base
    return None


def load_best(repo: str = REPO) -> tuple[float, str] | None:
    """The BEST (lowest) ``tunnel_dispatch_p50_ms`` anywhere in the
    committed BENCH series — the ratchet floor.  Scans EVERY
    ``BENCH_r*.json`` (not just the latest): the point of the ratchet is
    that one good round permanently raises the bar.  Returns
    (best-value, source-path) or None when no round carries the metric."""
    best: tuple[float, str] | None = None
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        doc = rec.get("parsed") or last_json_line(rec.get("tail", ""))
        if not isinstance(doc, dict):
            continue
        val = doc.get(RATCHET_METRIC)
        if isinstance(val, (int, float)) and val > 0:
            if best is None or val < best[0]:
                best = (float(val), path)
    return best


def ratchet_check(
    fresh: dict, best: tuple[float, str] | None, slack: float = RATCHET_SLACK
) -> list[str]:
    """Failure messages when the fresh ratcheted metric exceeds the
    series best by more than ``slack``.  Missing on either side skips
    (same philosophy as compare())."""
    if best is None:
        return []
    f = fresh.get(RATCHET_METRIC)
    if not isinstance(f, (int, float)):
        return []
    best_val, best_path = best
    limit = best_val * slack
    if f > limit:
        return [
            f"{RATCHET_METRIC} {f:g} ms exceeds the series-best ratchet "
            f"{best_val:g} ms x {slack:g} = {limit:g} ms "
            f"(best from {os.path.basename(best_path)})"
        ]
    return []


def attribution_check(fresh: dict, ref: dict) -> list[str]:
    """Attribution-shape gate: failure messages when any critical-path
    stage's SHARE of commit latency regressed past the engine tolerance
    (HOTSTUFF_CRITPATH_DIFF_PP) — the scalar-blind regression the plain
    guards cannot see.  Skip-if-missing on either side, and degrade to
    skip when the engine is unimportable (perfgate must run anywhere)."""
    f, r = fresh.get("critpath"), ref.get("critpath")
    if not isinstance(f, dict) or not isinstance(r, dict):
        return []
    try:
        sys.path.insert(0, REPO)
        from hotstuff_tpu.telemetry import critpath as engine

        from benchmark.critpath import diff_share_pp
    except Exception:  # noqa: BLE001 — shape gate is best-effort extra
        return []
    return [
        f"critpath attribution: {msg}"
        for msg in engine.diff(f, r, share_pp=diff_share_pp())
    ]


def compare(fresh: dict, ref: dict, threshold: float = 0.15) -> list[str]:
    """Failure messages for every guarded metric past the threshold.
    A metric missing on either side is skipped (a bench that stopped
    publishing a number is a review problem, not a perf gate's)."""
    failures = []
    for name, fn, direction, *rest in GUARDS:
        f, r = fn(fresh), fn(ref)
        if f is None or r is None or r <= 0:
            continue
        gate = rest[0] if rest else threshold
        delta = (f - r) / r * direction
        if delta > gate:
            word = "rose" if direction > 0 else "fell"
            failures.append(
                f"{name} {word} {abs(f - r) / r:.1%} past the "
                f"{gate:.0%} gate (fresh {f:g} vs reference {r:g})"
            )
    return failures


def run_bench(repo: str = REPO) -> str:
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench.py exited {proc.returncode}:\n{proc.stderr[-2000:]}"
        )
    return proc.stdout


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--fresh",
        default=None,
        metavar="FILE",
        help="pre-captured bench.py stdout ('-' for stdin) instead of "
        "running the benchmark",
    )
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="allowed relative regression (default 0.15)")
    ap.add_argument("--no-ratchet", action="store_true",
                    help="skip the tunnel_dispatch_p50_ms series-best "
                    "ratchet (e.g. on a known-degraded rig)")
    ap.add_argument("--ratchet-slack", type=float, default=RATCHET_SLACK,
                    help="allowed multiple of the series-best tunnel "
                    f"dispatch cost (default {RATCHET_SLACK})")
    args = ap.parse_args(argv)

    ref = load_reference()
    if ref is None:
        print("perfgate: no usable reference (BENCH_r*.json / "
              "BASELINE.json) — nothing to gate against")
        return 0
    ref_doc, ref_path = ref

    if args.fresh == "-":
        text = sys.stdin.read()
    elif args.fresh:
        with open(args.fresh) as f:
            text = f.read()
    else:
        print("perfgate: running bench.py ...")
        text = run_bench()
    fresh = last_json_line(text)
    if fresh is None:
        print("perfgate: FAIL — no JSON result line in the fresh bench "
              "output")
        return 1

    failures = compare(fresh, ref_doc, args.threshold)
    failures += attribution_check(fresh, ref_doc)
    ratcheted = ""
    if not args.no_ratchet:
        best = load_best()
        failures += ratchet_check(fresh, best, args.ratchet_slack)
        if best is not None and fresh.get(RATCHET_METRIC) is not None:
            ratcheted = (
                f"; {RATCHET_METRIC} within {args.ratchet_slack:g}x of "
                f"series best {best[0]:g} ms"
            )
    rel = os.path.relpath(ref_path, REPO)
    if failures:
        print(f"perfgate: FAIL vs {rel}")
        for msg in failures:
            print(f"  - {msg}")
        return 1
    checked = [n for n, fn, *_ in GUARDS
               if fn(fresh) is not None and fn(ref_doc) is not None]
    print(f"perfgate: OK vs {rel} ({', '.join(checked) or 'nothing'} "
          f"within {args.threshold:.0%}{ratcheted})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
