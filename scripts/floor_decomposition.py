"""Latency-floor decomposition at 4 nodes / 1k tx/s (VERDICT r3 item 6).

Runs the in-process committee three ways and reports consensus latency:
  1. normal CPU verification;
  2. null verification (every signature check monkeypatched to True —
     measurement only, never a production mode): bounds the crypto
     share of the round;
  3. null verification AND null codec digests... (skipped: digests are
     protocol-critical; crypto is the one cleanly removable stage).

    python scripts/floor_decomposition.py
"""

import asyncio
import os
import subprocess
import sys

sys.path.insert(0, ".")


async def run_committee(nodes: int, rate: int, duration: float) -> str:
    from benchmark.logs import LogParser
    from benchmark.utils import PathMaker
    from hotstuff_tpu.node.node import Node

    committee = []
    for i in range(nodes):
        committee.append(
            await Node.new(
                committee_file=PathMaker.committee_file(),
                key_file=PathMaker.key_file(i),
                store_path=PathMaker.db_path(i),
                parameters_file=PathMaker.parameters_file(),
                bind_host="127.0.0.1",
            )
        )
    from hotstuff_tpu.node.main import _freeze_boot_objects

    _freeze_boot_objects()  # match the production run-many GC shape
    drain = asyncio.gather(*(n.analyze_block() for n in committee))
    await asyncio.sleep(duration + 4)
    drain.cancel()
    for n in committee:
        try:
            await n.shutdown()
        except Exception:
            pass
    parser = LogParser.process(PathMaker.logs_path())
    tps, _ = parser.consensus_throughput()
    lat = parser.consensus_latency()
    return f"TPS={tps:.0f}/s latency={lat*1e3:.1f}ms blocks={len(parser.commits)}"


def drive(label: str, nodes: int, rate: int, duration: float) -> None:
    import logging

    from benchmark.local import LocalBench
    from benchmark.utils import PathMaker
    from hotstuff_tpu.node.main import setup_logging

    bench = LocalBench(nodes=nodes, rate=rate, duration=duration)
    bench._cleanup_files()
    bench._config()
    setup_logging(2)
    root = logging.getLogger()
    for h in list(root.handlers):
        if isinstance(h, logging.FileHandler):
            root.removeHandler(h)
    handler = logging.FileHandler(PathMaker.node_log_file(0))
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s.%(msecs)03dZ [%(levelname)s] %(name)s %(message)s",
            datefmt="%Y-%m-%dT%H:%M:%S",
        )
    )
    root.addHandler(handler)

    client = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "hotstuff_tpu.node.client",
            "--committee",
            PathMaker.committee_file(),
            "--rate",
            str(rate),
            "--duration",
            str(duration),
            "--warmup",
            "1",
        ],
        stdout=open(PathMaker.client_log_file(), "w"),
        stderr=subprocess.STDOUT,
        env={**os.environ, "PYTHONPATH": "."},
    )
    out = asyncio.run(run_committee(nodes, rate, duration))
    client.wait(timeout=15)
    print(f"{label}: {out}")


def main() -> int:
    nodes, rate, duration = 4, 1000, 12.0

    drive("cpu-verify ", nodes, rate, duration)

    # null verification: bound the crypto share of the round
    from hotstuff_tpu.crypto import service, signature

    service.CpuVerifier.verify_one = lambda self, d, pk, s: True
    service.CpuVerifier.verify_shared_msg = lambda self, d, v: True
    service.CpuVerifier.verify_many = (
        lambda self, d, p, s, aggregate_ok=False: [True] * len(d)
    )
    signature.batch_verify_arrays = lambda d, p, s: [True] * len(d)
    drive("null-verify", nodes, rate, duration)
    return 0


if __name__ == "__main__":
    sys.exit(main())
