#!/usr/bin/env python3
"""Deterministic-simulator sweep check (docs/SIM.md).

Runs a 500-seed schedule exploration at n=4 in-process (partitions,
lossy/slow links, crashes with torn WAL tails, reconfig ops and the
byz-collude family all mixed by the seeded drawer) and asserts the
contracts the sim plane exists to prove:

- every HONEST schedule passes every invariant (safety, state-root
  agreement, liveness-after-heal, epoch agreement, handoff gap) — any
  failure prints its repro seed, bundle path and shrunk minimal
  schedule and fails this check;
- the byz-collude family still behaves: enough byz seeds were drawn,
  each diverged full history (safety FAIL) AND was absolved by the
  trusted-subset recheck (PASS) — a byz schedule "passing" full
  history would mean the collusion plane went blind;
- determinism: a sample seed re-run in-process produces a
  byte-identical journal digest and the same verdict.

Exit non-zero when any contract breaks.

Usage:
    python scripts/sim_check.py [--seeds N] [--nodes N] [--start N]
    SIM=1 scripts/trace.sh               # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(
        f"  [{'ok' if ok else 'FAIL'}] {label}"
        + (f" — {detail}" if detail and not ok else "")
    )
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=500)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument(
        "--out",
        default=os.path.join(REPO, "logs", "sim-check"),
        help="failure repro-bundle directory",
    )
    args = ap.parse_args(argv)

    from hotstuff_tpu.sim import draw_schedule, explore, run_schedule

    print(
        f"=== explore: {args.seeds} seeds, {args.nodes} nodes "
        f"(start {args.start}) ==="
    )
    t0 = time.monotonic()
    result = explore(
        seeds=args.seeds,
        nodes=args.nodes,
        start_seed=args.start,
        out_dir=args.out,
        progress=lambda msg: print(msg, flush=True),
    )
    dt = time.monotonic() - t0
    print(
        f"  swept {result.seeds} seeds in {dt:.1f}s "
        f"({result.seeds * 60.0 / dt:.0f} seeds/min): "
        f"honest={result.honest} byz={result.byz} "
        f"findings={len(result.findings)}"
    )

    failed = False
    honest_failures = [
        f for f in result.findings if f.profile != "byz-collude"
    ]
    byz_failures = [
        f for f in result.findings if f.profile == "byz-collude"
    ]
    failed |= not check(
        "every honest schedule passes every invariant",
        not honest_failures,
        "; ".join(
            f"seed {f.seed}: {'; '.join(f.failures[:2])}"
            for f in honest_failures[:5]
        ),
    )
    failed |= not check(
        "byz-collude family drawn by the sweep",
        result.byz > 0,
        f"0 of {result.seeds} seeds drew byz-collude",
    )
    # a byz finding means either no divergence (checker blind) or a
    # divergence the trusted subset could not absolve — both break the
    # PR-8/11 contract the family exists to prove
    failed |= not check(
        "byz-collude seeds FAIL full-history / PASS trusted-subset",
        not byz_failures,
        "; ".join(
            f"seed {f.seed}: {'; '.join(f.failures[:2])}"
            for f in byz_failures[:5]
        ),
    )
    for f in result.findings:
        print(f"    repro: seed {f.seed} bundle={f.repro_dir}")
        if f.minimal_events is not None:
            kinds = ",".join(ev["kind"] for ev in f.minimal_events)
            print(
                f"    minimal schedule: {len(f.minimal_events)} "
                f"event(s) [{kinds}]"
            )

    print("=== determinism: double-run sample seed ===")
    sample = draw_schedule(args.start, nodes=args.nodes)
    a = run_schedule(sample)
    b = run_schedule(sample)
    failed |= not check(
        "same seed twice => identical journal digest",
        a.journal_digest == b.journal_digest,
        f"{a.journal_digest[:16]} != {b.journal_digest[:16]}",
    )
    failed |= not check(
        "same seed twice => identical verdict",
        (a.ok, a.all_ok, a.safety_ok, a.commits, a.rounds)
        == (b.ok, b.all_ok, b.safety_ok, b.commits, b.rounds),
    )

    print("sim sweep:", "FAIL" if failed else "ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
