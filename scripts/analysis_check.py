#!/usr/bin/env python
"""The LINT=1 gate: static analysis plane + native sanitizer smoke.

Three stages, all must pass:

1. ``python -m hotstuff_tpu.analysis check`` — every lint rule
   (no-blocking-in-async, wire-decoder-bounds, taxonomy-registry,
   env-knob-registry, guarded-by) over the tree, inline allows and the
   committed allowlist applied.
2. ``gen-knobs --check`` — docs/KNOBS.md freshness (also surfaced as a
   rule finding; repeated here so the failure message names the fix).
3. ``scripts/san_check.py`` — the TSan/ASan reactor + store stress,
   skip-if-unsupported.

Runs stdlib-only (no jax import), so the CI lint job needs no heavy
deps.  Invoked as ``LINT=1 scripts/trace.sh`` to mirror the BYZ=/
STATE=/TUNNEL= gate pattern.
"""

from __future__ import annotations

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def stage(title: str, argv: list) -> bool:
    print(f"== {title} ==")
    proc = subprocess.run(argv, cwd=ROOT)
    print()
    return proc.returncode == 0


def main() -> int:
    py = sys.executable
    ok = True
    ok &= stage(
        "static analysis rules",
        [py, "-m", "hotstuff_tpu.analysis", "check"],
    )
    ok &= stage(
        "env-knob registry freshness",
        [py, "-m", "hotstuff_tpu.analysis", "gen-knobs", "--check"],
    )
    ok &= stage(
        "native sanitizer smoke",
        [py, os.path.join(ROOT, "scripts", "san_check.py")],
    )
    if not ok:
        print("ANALYSIS CHECK FAIL")
        return 1
    print("ANALYSIS CHECK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
