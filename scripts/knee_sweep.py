"""Map the saturation knee per committee size (VERDICT r5 item 3).

Round 4 mapped the 4-node knee (~11k payloads/s) by sweeping input rate
to capacity; the 64/128-node rows instead carried pure-queueing latency
from a 2,000/s input against capacity.  This script replaces them: for
each committee size it

  1. doubles the input rate until achieved TPS PLATEAUS (gain below
     PLATEAU_GAIN per doubling) — the knee is the highest achieved TPS.
     Saturation must be detected as a plateau, NOT as achieved/input
     ratio: large in-process committees commit a near-constant ~85-90%
     of ANY sub-saturation input (payloads buffered at nodes awaiting
     their leadership turn are lost at window end — a fixed ~latency/
     window fraction), so a ratio test misfires at every rate;
  2. runs once more at ~80% of the knee and reports THAT latency — the
     sub-saturation operating point (reference methodology: the latency
     column of benchmark/data plots is always sub-saturation,
     /root/reference/benchmark/benchmark/logs.py:147-180).

Every individual run is appended to results/ via the same save_result
path as `python -m benchmark local`, so aggregates see them; the knee
summary lands in results/knee-<nodes>-<label>.txt.

    python scripts/knee_sweep.py --sizes 32,64,128 [--verifier tpu]
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from benchmark.local import LocalBench  # noqa: E402
from benchmark.utils import save_result  # noqa: E402

# A doubling of input that buys less than this TPS factor means the
# committee is on its plateau.
PLATEAU_GAIN = 1.3


def one_run(nodes: int, rate: int, args) -> dict:
    bench = LocalBench(
        nodes=nodes,
        rate=rate,
        duration=args.duration,
        verifier=args.verifier,
        in_process=True,
        tx_size=args.tx_size,
    )
    parser = bench.run()
    label = f"{args.verifier}-1proc"
    summary = parser.result(faults=0, nodes=nodes, verifier=label)
    print(summary)
    save_result(summary, 0, nodes, rate, label, ok=parser.has_window())
    tps, _ = parser.consensus_throughput()
    e2e = parser.end_to_end_latency()
    return {
        "consensus_tps": tps,
        "consensus_lat_ms": round(parser.consensus_latency() * 1000),
        "e2e_lat_ms": round(e2e * 1000) if e2e is not None else None,
    }


def sweep(nodes: int, args) -> None:
    """Double the rate until the TPS plateau, then measure latency at
    0.8 x knee."""
    rate = args.start_rate
    prev_tps = None
    history = []
    for _ in range(args.max_runs):
        m = one_run(nodes, rate, args)
        tps = m.get("consensus_tps", 0)
        plateaued = prev_tps is not None and tps < PLATEAU_GAIN * prev_tps
        history.append((rate, tps, m.get("consensus_lat_ms"), plateaued))
        print(
            f"[knee {nodes}] rate={rate} tps={tps:.0f} "
            f"lat={m.get('consensus_lat_ms')} plateaued={plateaued}",
            flush=True,
        )
        if plateaued:
            break
        prev_tps = tps
        rate *= 2
    knee_tps = max(t for _, t, _, _ in history)
    op_rate = max(args.min_rate, int(0.8 * knee_tps))
    m = one_run(nodes, op_rate, args)
    lines = [
        f"SATURATION KNEE: {nodes} nodes, verifier={args.verifier}, "
        f"in-process, tx {args.tx_size} B, {args.duration:.0f}s windows",
        "",
        " rate_in   tps  lat_ms  plateaued",
    ]
    for r, t, lat, s in history:
        lines.append(f"{r:8d} {t:5.0f}  {lat}  {s}")
    lines += [
        "",
        f"knee (plateau tps): {knee_tps:.0f} payloads/s",
        f"operating point at ~80% knee ({op_rate}/s input): "
        f"tps {m.get('consensus_tps', 0):.0f}, "
        f"consensus latency {m.get('consensus_lat_ms')} ms, "
        f"e2e latency {m.get('e2e_lat_ms')} ms",
        time.strftime("measured %Y-%m-%d %H:%MZ", time.gmtime()),
        "",
    ]
    out = f"results/knee-{nodes}-{args.verifier}-1proc.txt"
    with open(out, "a") as f:
        f.write("\n".join(lines))
    print("\n".join(lines), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="32,64,128")
    ap.add_argument("--verifier", default="tpu")
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--start-rate", type=int, default=1000)
    ap.add_argument("--min-rate", type=int, default=100)
    ap.add_argument("--max-runs", type=int, default=6)
    args = ap.parse_args()
    for nodes in (int(s) for s in args.sizes.split(",")):
        sweep(nodes, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
