"""One-line tunnel weather check: median dispatch+fetch time of a tiny
resident-arg jit call.  <5 ms = good window (device routing will win);
>50 ms = degraded (the adaptive service will serve waves from the CPU).

    python scripts/probe_weather.py
"""

import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    import numpy as np

    import jax

    @jax.jit
    def f(x):
        return (x * 2 + 1).sum()

    x = jax.device_put(np.ones((128, 20), np.int32))
    jax.block_until_ready(f(x))
    times = []
    for _ in range(9):
        t0 = time.perf_counter()
        np.asarray(f(x))
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2] * 1e3
    verdict = "good" if p50 < 5 else ("fair" if p50 < 50 else "degraded")
    print(f"tunnel dispatch p50 {p50:.2f} ms ({verdict})")
    # machine-readable exit for the harness weather gate
    # (benchmark/local.py --wait-weather): 0 good, 3 fair, 4 degraded
    return 0 if p50 < 5 else (3 if p50 < 50 else 4)


if __name__ == "__main__":
    sys.exit(main())
