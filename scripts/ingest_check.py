#!/usr/bin/env python3
"""Zero-copy ingest check (ISSUE 20): do vote frames arriving on the
native transport actually verify from the staging arenas?

End-to-end harness over the production pieces: a native reactor
listener (``network/native.py`` -> ``dispatch_ingest`` packing tag-1
frames into the wave arenas), a vote-decoding handler submitting claim
waves to the device ``AsyncVerifyService``, and real signed votes sent
open-loop through ``NativeSimpleSender``.  Every wave the service
serves should adopt its columns straight from the arena the reactor
packed — the flatten/prepare copies the zero-copy path exists to erase.

Asserts:
  - every verdict is True (adoption must not corrupt columns),
  - the zero-copy hit rate (adopted waves / submitted vote waves) is
    >= ``--min-hit`` (default 0.90) — below that the pack stream is
    desyncing from the claim stream and the fast path is decorative,
  - reports end-to-end sigs/s (wire -> verdict) for the bench record.

Skip-if-unsupported: without the native toolchain (libhs_transport.so
unbuildable) there is nothing to check — prints SKIP and exits 0, same
contract as scripts/san_check.py.

Usage:
    python scripts/ingest_check.py               # default 24 x 256
    INGEST=1 scripts/trace.sh                    # via the trace wrapper
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the check IS the zero-copy plane: force it on regardless of caller env
os.environ["HOTSTUFF_ZERO_COPY"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")


class WaveHandler:
    """Decodes vote frames, submits fixed-size claim waves."""

    def __init__(self, svc, wave_size: int):
        self.svc = svc
        self.wave_size = wave_size
        self.claims: list = []
        self.tasks: list = []
        self.waves = 0
        self.warmed = asyncio.Event()

    async def dispatch(self, writer, message: bytes) -> None:
        from hotstuff_tpu.consensus.wire import TAG_VOTE, decode_message

        tag, payload = decode_message(bytes(message), scheme="ed25519")
        if tag != TAG_VOTE:
            # the producer-v2 handshake frame: proves the sender's
            # connection is live before the open-loop vote stream starts
            self.warmed.set()
            return
        self.claims.append(payload.claim())
        if len(self.claims) >= self.wave_size:
            wave, self.claims = self.claims, []
            self.waves += 1
            self.tasks.append(
                asyncio.ensure_future(self.svc.verify_claims(wave))
            )


def make_votes(count: int, signers: int):
    """``count`` distinct signed votes round-robined over ``signers``
    keypairs; returns (wire frames, signer pubkey bytes)."""
    from hotstuff_tpu.consensus.messages import Vote
    from hotstuff_tpu.consensus.wire import encode_vote
    from hotstuff_tpu.crypto import Digest, Signature, generate_keypair

    keys = [
        generate_keypair(bytes([7 + i]) * 32, i) for i in range(signers)
    ]
    frames = []
    for i in range(count):
        pk, sk = keys[i % signers]
        vote = Vote(
            hash=Digest.of(b"ingest_check block %d" % i),
            round=i + 1,
            author=pk,
        )
        vote.signature = Signature.new(vote.digest(), sk)
        frames.append(encode_vote(vote))
    return frames, [pk.to_bytes() for pk, _ in keys]


async def run(args) -> int:
    from hotstuff_tpu.consensus.wire import encode_producer_batch
    from hotstuff_tpu.crypto.async_service import AsyncVerifyService
    from hotstuff_tpu.crypto.digest import Digest
    from hotstuff_tpu.network import native
    from hotstuff_tpu.node.node import LazyDeviceVerifier

    from tests.common import fresh_base_port

    total = args.waves * args.wave_size
    print(
        f" building {total} signed votes "
        f"({args.waves} waves x {args.wave_size})..."
    )
    frames, pubkeys = make_votes(total, signers=4)

    backend = LazyDeviceVerifier("tpu")
    backend.precompute(pubkeys)
    backend.warmup(batch=args.wave_size)
    # the simulated device (JAX_PLATFORMS=cpu) is slow but must stay
    # measured, not deadline-demoted mid-check
    backend.dispatch_deadline_s = 30.0
    svc = AsyncVerifyService(backend, device=True)
    svc.warm_buckets()

    handler = WaveHandler(svc, args.wave_size)
    port = fresh_base_port()
    recv = native.NativeReceiver("127.0.0.1", port, handler)
    await recv.spawn()
    sender = native.NativeSimpleSender()
    addr = ("127.0.0.1", port)

    try:
        # connect handshake: the native sender drops frames while the
        # connection is still in flight, and a dropped VOTE would desync
        # pack and claim streams — so prove liveness with a frame the
        # packer ignores (tag 6) before any vote leaves
        ping = encode_producer_batch([(Digest.of(b"ingest ping"), b"")])
        for _ in range(100):
            await sender.send(addr, ping)
            try:
                await asyncio.wait_for(handler.warmed.wait(), timeout=0.1)
                break
            except asyncio.TimeoutError:
                continue
        if not handler.warmed.is_set():
            print("ingest_check: FAIL (native sender never connected)")
            return 1

        # paced open loop: at most two waves outstanding, like a real
        # committee where vote arrival tracks commit rate.  A flat-out
        # flood would just overflow the staging arena (capacity
        # HOTSTUFF_INGEST_ARENA_ROWS) and measure the resync path, not
        # the steady state.
        t0 = time.perf_counter()
        deadline = time.monotonic() + args.timeout
        for w in range(args.waves):
            base = w * args.wave_size
            for frame in frames[base:base + args.wave_size]:
                await sender.send(addr, frame)
            while handler.waves <= w:
                if time.monotonic() > deadline:
                    print(
                        f"ingest_check: FAIL (only {handler.waves}/"
                        f"{args.waves} waves arrived before timeout)"
                    )
                    return 1
                await asyncio.sleep(0.005)
            if w >= 2:
                await asyncio.wait_for(
                    asyncio.shield(handler.tasks[w - 2]),
                    timeout=args.timeout,
                )
        results = await asyncio.wait_for(
            asyncio.gather(*handler.tasks), timeout=args.timeout
        )
        elapsed = time.perf_counter() - t0
    finally:
        sender.close()
        await recv.shutdown()
        svc.close()

    verdicts = [v for wave in results for v in wave]
    bad = verdicts.count(False)
    zc, fb = svc.zero_copy_waves, svc.fallback_waves
    # sig-based hit rate: the dispatcher may coalesce several submitted
    # waves into one adoption, so wave counts under-report coverage
    hit = svc.zero_copy_sigs / len(verdicts) if verdicts else 0.0
    sigs_per_s = len(verdicts) / elapsed if elapsed > 0 else 0.0

    print(" INGEST CHECK — wire -> arena -> device, no flatten copies")
    print(
        f"   waves: {handler.waves} submitted, {zc} adopted zero-copy, "
        f"{fb} fell back"
    )
    print(
        f"   sigs:  {svc.zero_copy_sigs}/{len(verdicts)} verified from "
        f"arenas ({100 * hit:.1f}% zero-copy hit rate)"
    )
    print(
        f"   rate:  {len(verdicts)} sigs in {elapsed:.2f} s "
        f"-> {sigs_per_s:,.0f} e2e sigs/s (simulated device)"
    )

    failures = []
    if bad:
        failures.append(f"{bad} valid votes got a False verdict")
    if hit < args.min_hit:
        failures.append(
            f"zero-copy hit rate {100 * hit:.1f}% < "
            f"{100 * args.min_hit:.0f}% — pack/claim streams desynced"
        )
    if failures:
        print("ingest_check: FAIL")
        for msg in failures:
            print(f"  - {msg}")
        return 1
    print("ingest_check: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--waves", type=int, default=24,
                    help="vote waves to send (default 24)")
    ap.add_argument("--wave-size", type=int, default=256,
                    help="votes per wave (default 256)")
    ap.add_argument("--min-hit", type=float, default=0.90,
                    help="minimum zero-copy hit rate (default 0.90)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="end-to-end deadline in seconds (default 120)")
    args = ap.parse_args(argv)

    from hotstuff_tpu.crypto import native_ed25519

    if not native_ed25519.wave_pack_available():
        print(
            "ingest_check: SKIP (native toolchain unavailable — "
            "cannot build libhs_transport.so)"
        )
        return 0
    try:
        from hotstuff_tpu.network import native  # noqa: F401
    except Exception as exc:  # pragma: no cover - same toolchain
        print(f"ingest_check: SKIP (native transport unavailable: {exc})")
        return 0

    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
