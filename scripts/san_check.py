#!/usr/bin/env python
"""Native sanitizer smoke: reactor + store engine under TSan/ASan.

Builds ``native/build/san_stress_{tsan,asan}`` (make tsan / make asan:
san_stress.cpp linked directly with transport.cpp and store_engine.cpp
— a sanitized .so inside an uninstrumented Python would miss the
runtime interceptors) and runs both stress binaries with
``halt_on_error=1``.  Any data race, use-after-free, overflow, or leak
the harness provokes fails the gate; the day-one catch was ht_stop
unlocking the reactor mutex after deleting the reactor.

Skip-if-unsupported: when the toolchain cannot link ``-fsanitize=X``
(missing libtsan/libasan, exotic cross compiler) or the sanitizer
runtime refuses to start (kernel ASLR layouts old TSan builds reject),
the affected mode SKIPs with an explicit message and the gate still
passes — sanitizer coverage is best-effort per machine, mandatory in
CI.

Exit codes: 0 = every supported mode passed (or everything skipped),
1 = a supported mode failed.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(ROOT, "native")

#: sanitizer-runtime startup failures that mean "unsupported here",
#: as opposed to reports about our code
_STARTUP_FAILURES = (
    "unexpected memory mapping",
    "failed to intercept",
    "incompatible with ASLR",
    "Sanitizer CHECK failed",
)

MODES = (
    ("tsan", "thread", {"TSAN_OPTIONS": "halt_on_error=1"}),
    (
        "asan",
        "address",
        {"ASAN_OPTIONS": "halt_on_error=1:detect_leaks=1"},
    ),
)


def toolchain_supports(flag: str) -> bool:
    """Can $CXX compile AND link a trivial program with -fsanitize=?"""
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None:
        return False
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "probe.cpp")
        with open(src, "w") as f:
            f.write("int main() { return 0; }\n")
        probe = subprocess.run(
            [cxx, f"-fsanitize={flag}", src, "-o", os.path.join(td, "p")],
            capture_output=True,
            text=True,
        )
        return probe.returncode == 0


def run_mode(name: str, flag: str, env_extra: dict) -> str:
    """'pass' | 'skip' | 'fail' for one sanitizer mode."""
    if not toolchain_supports(flag):
        print(
            f" [{name}] SKIP: toolchain cannot build -fsanitize={flag} "
            f"(unsupported toolchain on this machine)"
        )
        return "skip"
    build = subprocess.run(
        ["make", "-C", NATIVE, name],
        capture_output=True,
        text=True,
    )
    if build.returncode != 0:
        print(f" [{name}] FAIL: make {name} failed:\n{build.stderr[-2000:]}")
        return "fail"
    binary = os.path.join(NATIVE, "build", f"san_stress_{name}")
    env = dict(os.environ)
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [binary],
            capture_output=True,
            text=True,
            env=env,
            timeout=300,
        )
    except subprocess.TimeoutExpired:
        print(f" [{name}] FAIL: stress binary timed out (300 s)")
        return "fail"
    out = proc.stdout + proc.stderr
    if proc.returncode != 0 or "SAN_STRESS OK" not in out:
        if any(marker in out for marker in _STARTUP_FAILURES):
            print(
                f" [{name}] SKIP: sanitizer runtime failed to start on "
                f"this kernel/toolchain (unsupported environment)"
            )
            return "skip"
        print(f" [{name}] FAIL (rc={proc.returncode}):\n{out[-4000:]}")
        return "fail"
    summary = out.strip().splitlines()
    print(f" [{name}] PASS: {summary[-2] if len(summary) > 1 else ''}")
    return "pass"


def main() -> int:
    print("Native sanitizer smoke (reactor + store engine stress):")
    results = {name: run_mode(name, flag, env) for name, flag, env in MODES}
    failed = [n for n, r in results.items() if r == "fail"]
    if failed:
        print(f"SAN CHECK FAIL: {', '.join(failed)}")
        return 1
    if all(r == "skip" for r in results.values()):
        print("SAN CHECK SKIP: no sanitizer supported by this toolchain")
    else:
        print("SAN CHECK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
