#!/usr/bin/env python3
"""Tunnel-anatomy check (ISSUE 6): is the dispatch tunnel crushed, and
does the waterfall still account for where the time goes?

Drives ``benchmark profile`` waves at QC sizes 16/64/256 through the
production dispatch path (fixed-shape buckets, dispatch-loop slots,
donation), prints the per-stage p50 waterfall for each size, and
compares each size's e2e p50 against the committed reference round
(``--ref``, default BENCH_r05.json — the last round before the
fixed-shape dispatch loop landed, whose per-size ``rig_p50_ms`` were
fully serialized dispatches).

Exit status is non-zero when any size's leaf-span coverage drops below
``--min-coverage`` (default 95%): a stage missing its instrumentation
means the waterfall can no longer explain the wave, which is exactly
the failure mode that let the 91 ms rig gap hide pre-ISSUE-4.

Usage:
    python scripts/tunnel_check.py              # profile + compare
    TUNNEL=1 scripts/trace.sh                   # same, via the trace
                                                # wrapper's env switch
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SIZES = (16, 64, 256)


def load_ref(path: str) -> dict:
    """Per-size reference e2e ms from a BENCH round record: the
    serialized ``rig_p50_ms`` for old rounds, or ``blocking_p50_ms``
    once a round carries the ISSUE 6 split."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return {}
    doc = rec.get("parsed") or {}
    out = {}
    for size, entry in (doc.get("qc_verify_ms") or {}).items():
        val = entry.get("blocking_p50_ms", entry.get("rig_p50_ms"))
        if isinstance(val, (int, float)):
            out[int(size)] = float(val)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ref", default=os.path.join(REPO, "BENCH_r05.json"),
                    help="reference BENCH round (default BENCH_r05.json)")
    ap.add_argument("--waves", type=int, default=None,
                    help="waves per size (default: profile's own)")
    ap.add_argument("--min-coverage", type=float, default=95.0,
                    help="minimum leaf-span coverage %% (default 95)")
    args = ap.parse_args(argv)

    from benchmark.profile import format_waterfall, run_profile

    kwargs = {"sizes": SIZES, "verifier": "tpu", "route": "device"}
    if args.waves:
        kwargs["waves"] = args.waves
    result = run_profile(**kwargs)
    print(format_waterfall(result))

    ref = load_ref(args.ref)
    ref_name = os.path.basename(args.ref)
    failures = []
    print(f" TUNNEL CHECK — fresh e2e p50 vs {ref_name} (serialized)")
    for n in SIZES:
        res = result["sizes"].get(n)
        if res is None:
            failures.append(f"size {n}: no profile result")
            continue
        fresh = res["e2e_ms"]["p50"]
        cov = res["coverage_pct"]
        line = f"   QC {n:>4}: e2e p50 {fresh:8.3f} ms, coverage {cov:5.1f}%"
        base = ref.get(n)
        if base:
            line += (
                f"  (ref {base:.3f} ms, {base / fresh:.2f}x)"
                if fresh > 0
                else f"  (ref {base:.3f} ms)"
            )
        print(line)
        if cov < args.min_coverage:
            failures.append(
                f"size {n}: coverage {cov:.1f}% < {args.min_coverage:.0f}% "
                "— a pipeline stage is missing its instrumentation"
            )
    if failures:
        print("tunnel_check: FAIL")
        for msg in failures:
            print(f"  - {msg}")
        return 1
    print("tunnel_check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
