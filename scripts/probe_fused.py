"""Focused device timing: DSM-only vs fused verify at 128/256/1024
lanes, long-chain slope + median, one quiet process.

Separates per-tile scan cost from the fused epilogue cost and
cross-checks the grid scaling (batch 256 = 2 tiles must cost ~2x one
128-lane tile; divergence means the measurement, not the kernel)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hotstuff_tpu  # noqa: F401,E402


def main() -> int:
    import jax
    import jax.numpy as jnp

    from hotstuff_tpu.crypto import ed25519_ref as ref
    from hotstuff_tpu.tpu import curve
    from hotstuff_tpu.tpu import pallas_dsm
    from hotstuff_tpu.tpu.ed25519 import _bytes_to_windows_msb

    print("platform:", jax.devices()[0].platform, flush=True)
    rng = np.random.default_rng(11)
    pk = ref.public_from_seed(b"\x5a" * 32)
    pt = curve.point_to_limbs(ref.point_neg(ref.point_decompress(pk)))

    def inputs(batch):
        s_rows = rng.integers(0, 256, (batch, 32)).astype(np.uint8)
        s_rows[:, 31] &= 0x0F  # keep scalars < 2^252 (window form only)
        k_rows = rng.integers(0, 256, (batch, 32)).astype(np.uint8)
        k_rows[:, 31] &= 0x0F
        s_win = jnp.asarray(_bytes_to_windows_msb(s_rows).T)
        k_win = jnp.asarray(_bytes_to_windows_msb(k_rows).T)
        a = tuple(
            jnp.asarray(np.repeat(np.asarray(c)[None, :], batch, axis=0))
            for c in pt
        )
        r_y = jnp.asarray(rng.integers(0, 1 << 13, (batch, 20)).astype(np.int32))
        r_sign = jnp.asarray(rng.integers(0, 2, batch).astype(np.int32))
        return s_win, k_win, a, r_y, r_sign

    def slope_ms(fn, fetch, short=8, long=64, reps=7):
        out = fn()
        jax.block_until_ready(out)
        slopes = []
        for _ in range(reps):
            times = {}
            for n in (short, long):
                t0 = time.perf_counter()
                for _ in range(n):
                    out = fn()
                fetch(out)
                times[n] = time.perf_counter() - t0
            slopes.append((times[long] - times[short]) / (long - short))
        slopes.sort()
        return slopes[len(slopes) // 2] * 1e3

    for batch in (128, 256, 1024):
        s_win, k_win, a, r_y, r_sign = inputs(batch)
        dsm = slope_ms(
            lambda: pallas_dsm.dual_scalar_mult(s_win, k_win, a),
            lambda o: np.asarray(o[1]),
        )
        fused = slope_ms(
            lambda: pallas_dsm.verify_compressed(s_win, k_win, a, r_y, r_sign),
            lambda o: np.asarray(o),
        )
        print(
            f"batch {batch:4d}: dsm {dsm:7.3f} ms  fused {fused:7.3f} ms  "
            f"(epilogue {fused - dsm:+.3f})",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
