#!/usr/bin/env python3
"""Live health-plane check (docs/TELEMETRY.md, ISSUE 13).

Three phases, exit non-zero when ANY contract breaks:

1. **Healthy committee, live watch** — a 4-node ``benchmark local
   --health --journal`` run with the fleet watcher attached mid-run:
   every node must scrape (no STALE rows), the head round must
   advance, the anomaly detectors must stay quiet (zero crit
   incidents, nothing open at the end), and the SUMMARY must carry the
   ``+ HEALTH`` block with all four monitors announced.
2. **Leader isolation trips leader-stall** — the canned
   ``leader-isolation`` chaos scenario with the watcher attached: a
   ``leader_stall`` incident must appear in the LIVE view (scraped
   from the victim's own monitor) and in the ``+ HEALTH`` SUMMARY
   block, and the campaign rings must persist beside the journals.
3. **Perfgate ratchet with the plane on** — ``bench.probe_tunnel()``
   re-measured in a child with ``HOTSTUFF_TELEMETRY=1
   HOTSTUFF_HEALTH=1`` while a live HealthMonitor ticks at 4x the
   production cadence and a client scrapes ``/delta`` throughout: the
   recorder + export overhead must keep ``tunnel_dispatch_p50_ms``
   within the existing series-best ratchet (scripts/perfgate.py).
   Skip with ``--no-perfgate``.

Usage:
    python scripts/health_check.py [--rate R] [--no-perfgate]
    HEALTH=1 scripts/trace.sh             # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {label}"
          + (f" — {detail}" if detail and not ok else ""))
    return ok


def _launch(args: list[str]) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "benchmark", *args],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _attach(launched_at: float, boot_timeout: float = 60.0):
    """(targets, leader_order) once THIS run's committee files exist and
    the first node answers a /delta scrape."""
    from benchmark.utils import PathMaker
    from benchmark.watch import NodeFeed, fleet_targets

    deadline = time.time() + boot_timeout
    while time.time() < deadline:
        try:
            if os.path.getmtime(PathMaker.committee_file()) < launched_at:
                raise OSError("stale committee from a previous run")
            targets, order = fleet_targets()
            t = targets[0]
            probe = NodeFeed(t["name"], f"http://{t['host']}:{t['port']}")
            if probe.poll() is not None:
                return targets, order
        except (OSError, RuntimeError, ValueError):
            pass
        time.sleep(1.0)
    raise TimeoutError("committee metrics endpoints never came up")


def _watch(targets, order, timeout_s: float, duration: float):
    """Run the watcher for ``duration`` s; (final view, watcher)."""
    from benchmark.watch import FleetWatcher, run_watch

    frames: list[str] = []
    watcher = FleetWatcher(targets, order, timeout_s=timeout_s)
    view = run_watch(
        watcher, duration=duration, interval=1.0, out=frames.append
    )
    return view, watcher, frames


def phase_healthy(rate: int) -> bool:
    print("=== phase 1: healthy committee, live watch ===")
    failed = False
    launched_at = time.time()
    proc = _launch([
        "local", "--nodes", "4", "--rate", str(rate),
        "--duration", "25", "--health", "--journal",
    ])
    try:
        targets, order = _attach(launched_at)
        failed |= not check("watch attached to 4 nodes", len(targets) == 4,
                            f"found {len(targets)}")
        view, watcher, frames = _watch(
            targets, order, timeout_s=5.0, duration=10.0
        )
        live = [v for v in view["nodes"] if not v.get("stale")]
        failed |= not check("no STALE rows mid-run", len(live) == 4,
                            f"{4 - len(live)} stale")
        failed |= not check("head round advancing", view["head"] > 0,
                            f"head {view['head']}")
        rates = [v.get("commit_rate") for v in view["nodes"]]
        failed |= not check(
            "per-node commit rate measured",
            any(isinstance(r, float) and r > 0 for r in rates),
            f"rates {rates}",
        )
        crits = [i for _, i in watcher.incidents if i.severity == "crit"]
        failed |= not check("zero crit incidents on a healthy run",
                            not crits, f"{[(i.kind, i.node) for i in crits]}")
        failed |= not check("nothing open at watch end", not view["open"],
                            f"{view['open']}")
        if watcher.incidents:
            print(f"  (transient warns observed: "
                  f"{[(i.kind, i.node) for _, i in watcher.incidents]})")
        out, _ = proc.communicate(timeout=120)
        failed |= not check("run PASSes (exit 0)", proc.returncode == 0,
                            f"exit {proc.returncode}")
        failed |= not check("+ HEALTH block in SUMMARY", "+ HEALTH" in out)
        failed |= not check("all 4 monitors announced",
                            "Nodes monitored: 4" in out)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    return failed


def phase_isolation(rate: int) -> bool:
    print("=== phase 2: leader-isolation trips leader-stall ===")
    failed = False
    launched_at = time.time()
    proc = _launch([
        "chaos", "--scenario", "leader-isolation", "--seed", "7",
        "--rate", str(rate), "--duration", "10",
        "--timeout-delay", "1000", "--health", "--journal",
    ])
    try:
        targets, order = _attach(launched_at)
        # the scenario isolates one node for 7 s against a 1 s timeout:
        # its own monitor fires leader_stall (3 s threshold) and the
        # watcher must lift it into the live feed
        view, watcher, frames = _watch(
            targets, order, timeout_s=1.0, duration=45.0
        )
        live_kinds = {i.kind for _, i in watcher.incidents}
        failed |= not check("leader_stall in the LIVE view",
                            "leader_stall" in live_kinds,
                            f"live incidents {sorted(live_kinds)}")
        rendered = any("leader_stall" in f for f in frames)
        failed |= not check("incident rendered on the dashboard", rendered)
        out, _ = proc.communicate(timeout=120)
        failed |= not check("run PASSes (exit 0)", proc.returncode == 0,
                            f"exit {proc.returncode}")
        failed |= not check("+ HEALTH block in SUMMARY", "+ HEALTH" in out)
        failed |= not check("leader_stall in SUMMARY",
                            "leader_stall" in out)
        from benchmark.utils import PathMaker
        from hotstuff_tpu.telemetry.health import CAMPAIGN_SUFFIX

        rings = glob.glob(os.path.join(
            REPO, PathMaker.journals_path(), f"*{CAMPAIGN_SUFFIX}"))
        failed |= not check("campaign rings persisted", bool(rings))
        trace = os.path.join(REPO, PathMaker.trace_file())
        failed |= not check(
            "incidents track in the Chrome trace",
            os.path.exists(trace)
            and '"incidents"' in open(trace, errors="replace").read(),
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    return failed


def _probe_child() -> int:
    """Phase-3 child: measure the dispatch tunnel with the health plane
    LIVE in-process — a HealthMonitor ticking at 4x the production
    cadence (campaign ring included) and a client scraping ``/delta``
    for the whole measurement window — so the recorder + export
    overhead lands inside ``tunnel_dispatch_p50_ms``."""
    os.environ["HOTSTUFF_TELEMETRY"] = "1"
    os.environ["HOTSTUFF_HEALTH"] = "1"
    import asyncio
    import json
    import tempfile
    import threading
    import urllib.request

    from hotstuff_tpu import telemetry
    from hotstuff_tpu.telemetry.health import HealthMonitor

    import bench

    telemetry.enable()
    tel = telemetry.for_node("probe")
    ring = os.path.join(
        tempfile.mkdtemp(prefix="health-probe-"), "probe-campaign.json"
    )
    mon = HealthMonitor(
        tel, "probe", timeout_s=60.0, interval_s=0.25, campaign_path=ring
    )

    loop = asyncio.new_event_loop()
    ready = threading.Event()
    state: dict = {}

    async def _serve():
        state["server"] = await telemetry.maybe_start_server(
            0, host="127.0.0.1"
        )
        state["monitor"] = asyncio.ensure_future(mon.run())
        ready.set()

    def _loop_main():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(_serve())
        loop.run_forever()

    threading.Thread(target=_loop_main, daemon=True).start()
    if not ready.wait(10.0) or state.get("server") is None:
        print("probe child: metrics server never came up", file=sys.stderr)
        return 1
    port = state["server"].port

    stop = threading.Event()
    scrapes = [0]

    def _scrape():
        seq = -1
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/delta?since={seq}",
                    timeout=2.0,
                ) as resp:
                    seq = json.loads(resp.read()).get("seq", -1)
                    scrapes[0] += 1
            except (OSError, ValueError):
                pass
            stop.wait(0.25)

    scraper = threading.Thread(target=_scrape, daemon=True)
    scraper.start()
    try:
        out = bench.probe_tunnel()
    finally:
        stop.set()
        scraper.join(5.0)
    out["delta_scrapes"] = scrapes[0]
    print(json.dumps(out))
    return 0


def phase_perfgate() -> bool:
    print("=== phase 3: dispatch ratchet with the health plane on ===")
    import perfgate

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--probe-child"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    fresh = perfgate.last_json_line(proc.stdout)
    if not check("tunnel probe ran with the plane live",
                 proc.returncode == 0 and fresh is not None,
                 f"exit {proc.returncode}: {proc.stderr.strip()[-200:]}"):
        return True
    if not check("delta export scraped during the window",
                 fresh.get("delta_scrapes", 0) > 0):
        return True
    best = perfgate.load_best()
    if best is None:
        print("  [skip] no committed BENCH series carries the ratchet "
              "metric")
        return False
    failures = perfgate.ratchet_check(fresh, best)
    ok = check(
        "tunnel_dispatch_p50_ms within the series-best ratchet",
        not failures,
        "; ".join(failures),
    )
    if ok:
        print(f"  ({perfgate.RATCHET_METRIC} "
              f"{fresh.get(perfgate.RATCHET_METRIC)} ms vs best "
              f"{best[0]:g} ms x {perfgate.RATCHET_SLACK:g})")
    return not ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=int, default=400)
    ap.add_argument("--no-perfgate", action="store_true",
                    help="skip the dispatch-ratchet phase")
    ap.add_argument("--probe-child", action="store_true",
                    help=argparse.SUPPRESS)  # phase-3 internal re-exec
    args = ap.parse_args(argv)

    os.chdir(REPO)
    if args.probe_child:
        return _probe_child()
    failed = phase_healthy(args.rate)
    failed |= phase_isolation(args.rate)
    if not args.no_perfgate:
        failed |= phase_perfgate()
    else:
        print("=== phase 3 skipped (--no-perfgate) ===")
    print("health check:", "FAIL" if failed else "PASS")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
