#!/usr/bin/env python3
"""Compact-certificate sweep (ISSUE 9): does the aggregated QC stay
O(1) and agree with the vote-list baseline across committee sizes?

For each committee size the check builds a BLS quorum over one block
digest and asserts, end to end:

  * PARITY — the compact QC (one aggregate + signer bitmap) and the
    vote-list QC produce identical accept verdicts, and the adversary
    plane's forged certificates (garbage aggregate over a valid quorum
    bitmap) are REJECTED by the aggregate path exactly as the vote-list
    forgery is by the batch path;
  * WIRE — compact wire size is 48 + ceil(n/8) + framing, i.e. constant
    in committee size up to the bitmap byte, vs n x 144 for vote lists;
  * FLATNESS — compact verify p50 (one pairing over the memoized key
    sum) at the largest size stays within ``--flat-ratio`` (default
    2.0) of the smallest — the one-pairing promise;
  * HANDEL — the in-process two-level aggregation run covers the whole
    quorum with <= log2(n) leader-side merges.

At the smallest size the quorum additionally flows through the REAL
``Aggregator`` (consensus/aggregator.py) so the running-sum emission
path is exercised, not just hand-built certificates.

Usage:
    python scripts/agg_check.py            # sizes 16,64,256
    AGG=1 scripts/trace.sh                 # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_quorum(n: int, digest):
    """(sorted pks, quorum votes, running-sum aggregate bytes) with
    small-scalar secrets — fixture cost is O(n) cheap multiplies while
    verification cost is untouched."""
    from hotstuff_tpu.crypto import PublicKey, Signature
    from hotstuff_tpu.crypto.bls import BlsSecretKey
    from hotstuff_tpu.crypto.bls.curve import G1Point

    sks = [BlsSecretKey(i + 2) for i in range(n)]
    by_pk = {PublicKey(sk.public_key().to_bytes()): sk for sk in sks}
    pks = sorted(by_pk)
    quorum = 2 * n // 3 + 1
    msg = digest.to_bytes()
    votes = [
        (pk, Signature(by_pk[pk].sign(msg).to_bytes()))
        for pk in pks[:quorum]
    ]
    agg = G1Point.sum(
        [
            G1Point.from_bytes(sig.to_bytes(), subgroup_check=False)
            for _, sig in votes
        ]
    ).to_bytes()
    return pks, votes, agg


def check_size(n: int, reps: int) -> tuple[float, list[str]]:
    """(compact verify p50 ms, failure messages) for one committee."""
    from hotstuff_tpu.consensus.handel import HandelTopology, simulate
    from hotstuff_tpu.consensus.messages import QC, make_signer_bitmap
    from hotstuff_tpu.crypto import Digest, Signature
    from hotstuff_tpu.crypto.scheme import make_cpu_verifier

    fails: list[str] = []
    digest = Digest.of(f"agg-check-{n}".encode())
    pks, votes, agg = build_quorum(n, digest)
    signers = [pk for pk, _ in votes]
    pk_bytes = [pk.to_bytes() for pk in signers]
    verifier = make_cpu_verifier("bls")
    verifier.precompute(pk_bytes)

    compact = QC(
        hash=digest,
        round=3,
        votes=[],
        agg_sig=Signature(agg),
        signers=make_signer_bitmap(signers, pks),
    )
    votelist = QC(hash=digest, round=3, votes=list(votes))

    # parity: both forms accept the honest quorum
    ok_compact = bool(
        verifier.verify_aggregate_msg(digest, pk_bytes, agg)
    )
    ok_votelist = bool(verifier.verify_shared_msg(digest, votes))
    if not (ok_compact and ok_votelist):
        fails.append(
            f"n={n}: honest quorum verdicts diverge "
            f"(compact={ok_compact} votelist={ok_votelist})"
        )

    # parity: a garbage aggregate over the same valid bitmap must fail
    forged = bytearray(agg)
    forged[7] ^= 0xFF
    if verifier.verify_aggregate_msg(digest, pk_bytes, bytes(forged)):
        fails.append(f"n={n}: forged aggregate ACCEPTED")

    # wire: constant-size promise (agg sig + bitmap + fixed framing)
    cb, vb = compact.wire_size(), votelist.wire_size()
    bound = 48 + (len(pks) + 7) // 8 + 64  # framing slack
    if cb > bound:
        fails.append(f"n={n}: compact wire {cb}B exceeds bound {bound}B")
    if cb * 10 > vb and n >= 16:
        fails.append(
            f"n={n}: compact wire {cb}B not <10% of vote-list {vb}B"
        )

    # flatness sample: warm the key-sum memo, then p50 the pairing
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        assert verifier.verify_aggregate_msg(digest, pk_bytes, agg)
        samples.append((time.perf_counter() - t0) * 1e3)
    samples.sort()
    p50 = samples[len(samples) // 2]

    # Handel: full quorum coverage in <= log2(n) leader merges
    topo = HandelTopology.for_round(n, round_=3)
    index_of = {pk: i for i, pk in enumerate(pks)}
    final, top_merges, _ = simulate(
        topo, {index_of[pk]: sig.to_bytes() for pk, sig in votes}
    )
    if final.weight != len(votes):
        fails.append(
            f"n={n}: Handel coverage {final.weight} != quorum {len(votes)}"
        )
    if top_merges > topo.levels:
        fails.append(
            f"n={n}: Handel leader merged {top_merges} partials "
            f"(> {topo.levels} levels)"
        )

    print(
        f"   n={n:4d}: compact {cb}B vs vote-list {vb}B, "
        f"verify p50 {p50:.2f} ms, handel merges {top_merges}/"
        f"{topo.levels} levels"
    )
    return p50, fails


def check_aggregator_path(n: int) -> list[str]:
    """Drive the smallest committee through the REAL Aggregator: the
    running-sum compact emission, the claims plane, and the adversary
    plane's compact forgery."""
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.config import Committee
    from hotstuff_tpu.consensus.errors import ConsensusError
    from hotstuff_tpu.consensus.messages import Vote
    from hotstuff_tpu.crypto import Digest, PublicKey, Signature
    from hotstuff_tpu.crypto.bls import BlsSecretKey, prove_possession
    from hotstuff_tpu.crypto.scheme import make_cpu_verifier
    from hotstuff_tpu.faults.adversary import AdversaryPlane

    fails: list[str] = []
    sks = [BlsSecretKey(i + 2) for i in range(n)]
    by_pk = {PublicKey(sk.public_key().to_bytes()): sk for sk in sks}
    com = Committee.new(
        [
            (pk, 1, ("127.0.0.1", 21000 + i))
            for i, pk in enumerate(sorted(by_pk))
        ],
        scheme="bls",
        pops={
            pk: prove_possession(sk).to_bytes()
            for pk, sk in by_pk.items()
        },
    )
    verifier = make_cpu_verifier("bls")
    agg = Aggregator(com, verifier)
    bh = Digest.of(b"agg-check-aggregator-block")
    qc = None
    for pk in com.sorted_keys()[: com.quorum_threshold()]:
        vote = Vote(hash=bh, round=5, author=pk, signature=None)
        vote.signature = Signature(
            by_pk[pk].sign(vote.digest().to_bytes()).to_bytes()
        )
        qc = agg.add_vote(vote, current_round=5) or qc
    if qc is None or not qc.is_compact:
        fails.append(f"Aggregator did not emit a compact QC: {qc!r}")
        return fails
    try:
        qc.check_weight(com)
        qc.verify(com, verifier)
    except ConsensusError as e:
        fails.append(f"Aggregator-emitted compact QC rejected: {e}")

    plane = AdversaryPlane.__new__(AdversaryPlane)
    import random

    plane.seed = 7
    plane.rng = random.Random(7)
    forged = plane.forged_compact_qc(com, 6)
    try:
        forged.check_weight(com)  # structurally valid by design
        forged.verify(com, verifier)
        fails.append("forged compact QC ACCEPTED by verify")
    except ConsensusError:
        pass
    return fails


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sizes", default="16,64,256",
                    help="committee sizes (default 16,64,256)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--flat-ratio", type=float,
                    default=float(os.environ.get("AGG_FLAT_RATIO", "2.0")),
                    help="allowed compact verify p50 growth largest/"
                    "smallest (default 2.0, env AGG_FLAT_RATIO)")
    args = ap.parse_args(argv)
    sizes = tuple(int(x) for x in args.sizes.split(",") if x)

    print(" AGG CHECK — compact vs vote-list certificates per "
          "committee size")
    fails: list[str] = []
    p50s: dict[int, float] = {}
    for n in sizes:
        p50, f = check_size(n, args.reps)
        p50s[n] = p50
        fails += f
    fails += check_aggregator_path(min(sizes))

    lo, hi = min(sizes), max(sizes)
    ratio = p50s[hi] / max(p50s[lo], 1e-9)
    print(f"   flatness: p50 {p50s[lo]:.2f} ms @ {lo} -> "
          f"{p50s[hi]:.2f} ms @ {hi} (ratio {ratio:.2f}, "
          f"gate {args.flat_ratio:g})")
    if ratio > args.flat_ratio:
        fails.append(
            f"compact verify p50 grew {ratio:.2f}x from committee "
            f"{lo} to {hi} (gate {args.flat_ratio:g}) — the one-pairing "
            f"path has degraded"
        )

    if fails:
        print("agg_check: FAIL")
        for msg in fails:
            print(f"  - {msg}")
        return 1
    print("agg_check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
