"""Stage breakdown of the distinct-digest BLS batch path (VERDICT r5
item 8): where do the ~430 ms for a 171-entry all-distinct TC go?

Prints per-stage mean cost from the native profiler
(hs_bls_profile), the implied 171-entry wall decomposition, and a
measured end-to-end verify_many wall for cross-checking.  Then, if a
device is available, times the TPU batched ladder (TpuG1ScalarMul) on
the same shape for the offload comparison.
"""

import ctypes
import sys
import time

sys.path.insert(0, ".")

N = 171  # 2*256//3 + 1: the 256-committee storm quorum


def native_stages():
    from hotstuff_tpu.crypto.bls import native

    lib = native._lib  # loaded CDLL
    lib.hs_bls_profile.restype = None
    lib.hs_bls_profile.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
    out = (ctypes.c_double * 5)()
    lib.hs_bls_profile(64, out)
    names = [
        "sig decompress+subgroup ladder",
        "hash_to_g1 (sqrt + cofactor)",
        "128-bit G1 weight mul",
        "miller_loop",
        "final_exponentiation (once)",
    ]
    per_entry_ms = 0.0
    print(f"native per-stage cost (64-iter means):")
    for i, name in enumerate(names):
        ms = out[i] / 1e6
        print(f"  {name:34s} {ms:8.3f} ms")
        if i < 4:
            mult = 2 if i == 2 else 1  # weight mul runs twice per entry
            per_entry_ms += ms * mult
    wall = per_entry_ms * N + out[3] / 1e6 + out[4] / 1e6
    print(
        f"implied {N}-entry wall: {wall:.0f} ms "
        f"(= {per_entry_ms:.3f} ms/entry x {N} + final miller + final exp)"
    )
    return out


def measured_wall():
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.crypto.bls import keygen
    from hotstuff_tpu.crypto.bls.service import BlsSigningService, BlsVerifier

    v = BlsVerifier()
    db, pb, sb = [], [], []
    for i in range(N):
        pk, sk = keygen(bytes([7, i % 256, i // 256]) + b"\x00" * 29)
        svc = BlsSigningService(sk)
        d = Digest.of(bytes([i]) * 3)
        sig = svc.sign_sync(d)
        db.append(d.to_bytes())
        pb.append(pk.to_bytes())
        sb.append(sig.to_bytes())
    v.precompute(pb)
    t0 = time.perf_counter()
    ok = v.verify_many(db, pb, sb, aggregate_ok=True)
    cold = time.perf_counter() - t0
    assert all(ok), "valid batch rejected"
    # second call: the native pk/line-coefficient caches are warm — the
    # steady-state storm cost (committee keys warm once per epoch)
    t0 = time.perf_counter()
    ok = v.verify_many(db, pb, sb, aggregate_ok=True)
    warm = time.perf_counter() - t0
    assert all(ok)
    print(
        f"measured verify_many wall ({N} distinct): cold {cold * 1e3:.0f} ms"
        f" (epoch key-cache fill), warm {warm * 1e3:.0f} ms"
    )
    return warm


def device_ladder():
    from hotstuff_tpu.crypto.bls.curve import G1Point
    from hotstuff_tpu.tpu.bls import TpuG1ScalarMul

    import secrets

    g = G1Point.generator()
    pts = [g._mul_raw(i + 1) for i in range(N)]
    ks = [secrets.randbits(128) | 1 for _ in range(N)]
    m = TpuG1ScalarMul()
    t0 = time.perf_counter()
    out = m.mul(ks, pts)
    warm = time.perf_counter() - t0
    # correctness spot-check
    for i in (0, 7, N - 1):
        assert out[i] == pts[i]._mul_raw(ks[i]), f"ladder mismatch at {i}"
    t0 = time.perf_counter()
    m.mul(ks, pts)
    hot = time.perf_counter() - t0
    print(
        f"device ladder ({N} x 128-bit): warm-inclusive {warm * 1e3:.0f} ms, "
        f"hot {hot * 1e3:.0f} ms"
    )


if __name__ == "__main__":
    native_stages()
    measured_wall()
    if "--device" in sys.argv:
        device_ladder()
