"""Validate the per-shard Pallas dispatch of ShardedBatchVerifier on the
real chip (a 1-device TPU mesh — the code path is identical to a v5e-8
mesh; only the axis size differs).  Run manually on TPU hardware:

    python scripts/validate_sharded_device.py
"""

import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    import jax

    from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
    from hotstuff_tpu.parallel.mesh import ShardedBatchVerifier, default_mesh

    print("devices:", jax.devices())
    mesh = default_mesh()
    v = ShardedBatchVerifier(mesh=mesh, min_device_batch=0)
    print("verifier:", v.name, "per-shard pallas:", v._shard_pallas)

    shared = Digest.of(b"sharded pallas validation")
    msgs, pks, sigs = [], [], []
    for i in range(171):
        pk, sk = generate_keypair(b"\x88" * 32, i)
        msgs.append(shared.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(shared, sk).to_bytes())
    v.precompute(pks)

    t0 = time.time()
    out = v.verify(msgs, pks, sigs)
    print(
        "first sharded verify (incl compile): %.1f s, all valid: %s"
        % (time.time() - t0, bool(out.all()))
    )
    assert out.all()
    bad = list(sigs)
    bad[42] = bad[42][:40] + b"\x03" + bad[42][41:]
    out2 = v.verify(msgs, pks, bad)
    assert not out2[42] and out2[:42].all() and out2[43:].all()
    print("tamper detection OK")
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        v.verify(msgs, pks, sigs)
        times.append(time.perf_counter() - t0)
    times.sort()
    print("171-sig sharded verify rig p50: %.1f ms" % (times[5] * 1e3))
    return 0


if __name__ == "__main__":
    sys.exit(main())
