"""Third probe: isolate implicit-host-arg transfer vs resident args,
interleaved A/B/A/B so tunnel weather can't confound the comparison.
Also times explicit device_put of all args + call, and the production
BatchVerifier.verify path at QC shapes.
"""

import time

import numpy as np

import jax


def q(xs):
    xs = sorted(xs)
    return {
        "p50": round(xs[len(xs) // 2] * 1000, 2),
        "min": round(xs[0] * 1000, 2),
        "max": round(xs[-1] * 1000, 2),
    }


def main():
    dev = jax.devices()[0]

    @jax.jit
    def g(a, b, c, d, e, f_, g_, h):
        return (a + b + c + d + e + f_ + g_ + h).sum(axis=1)

    host_args = [np.ones((256, 20), np.int32) for _ in range(8)]
    dev_args = [jax.device_put(a, dev) for a in host_args]
    jax.block_until_ready(g(*dev_args))

    N = 12
    res, imp, put = [], [], []
    for _ in range(N):
        t = time.perf_counter()
        np.asarray(g(*dev_args))
        res.append(time.perf_counter() - t)

        t = time.perf_counter()
        np.asarray(g(*host_args))
        imp.append(time.perf_counter() - t)

        t = time.perf_counter()
        moved = [jax.device_put(a, dev) for a in host_args]
        np.asarray(g(*moved))
        put.append(time.perf_counter() - t)

    print("resident args:", q(res))
    print("implicit host args:", q(imp))
    print("explicit device_put then call:", q(put))

    # production path at QC shapes
    from hotstuff_tpu.crypto import ed25519_ref as ref
    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    seed = b"\x11" * 32
    msg = b"probe3"
    pk = ref.public_from_seed(seed)
    sig = ref.sign(seed, msg)
    v = BatchVerifier(min_device_batch=0)
    v.verify([msg] * 22, [pk] * 22, [sig] * 22)  # warm 128-pad shape
    prod = []
    for _ in range(N):
        t = time.perf_counter()
        out = v.verify([msg] * 22, [pk] * 22, [sig] * 22)
        prod.append(time.perf_counter() - t)
        assert out.all()
    print("BatchVerifier.verify 22 sigs (pad 128):", q(prod))


if __name__ == "__main__":
    main()
