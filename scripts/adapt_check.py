#!/usr/bin/env python3
"""Adaptive-adversary plane check (docs/FAULTS.md, ISSUE 18).

Runs the guided schedule search against a flat sweep at the SAME run
budget and asserts the contracts the adaptive plane exists to prove:

- honest seeds stay green: a flat sweep over the budget's seed range
  produces zero honest-profile findings with the adaptive plane wired
  in;
- guided search pays for itself: at equal budget it surfaces strictly
  more invariant-threatening schedules (full-history FAIL or liveness
  stall) than the flat sweep;
- containment: every full-history FAIL the search discovers is
  absolved by the trusted-subset regime (PASS) — an uncontained attack
  is a real bug and fails this check;
- promotion replays: every promoted corpus schedule (inline schedules
  in tests/data/sim_seeds.json) re-runs to the SAME verdict and a
  byte-identical journal digest.

Exit non-zero when any contract breaks.

Usage:
    python scripts/adapt_check.py [--budget N] [--nodes N] [--start N]
    ADAPT=1 scripts/trace.sh             # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CORPUS = os.path.join(REPO, "tests", "data", "sim_seeds.json")


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(
        f"  [{'ok' if ok else 'FAIL'}] {label}"
        + (f" — {detail}" if detail and not ok else "")
    )
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budget", type=int, default=18,
                    help="schedules per search mode (flat AND guided)")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--start", type=int, default=0)
    ap.add_argument(
        "--out",
        default=os.path.join(REPO, "logs", "adapt-check"),
        help="failure repro-bundle directory",
    )
    args = ap.parse_args(argv)

    from hotstuff_tpu.sim import explore, explore_guided, run_schedule

    say = lambda msg: print(msg, flush=True)  # noqa: E731

    print(
        f"=== flat sweep: {args.budget} seeds, {args.nodes} nodes "
        f"(start {args.start}) ==="
    )
    t0 = time.monotonic()
    flat = explore(
        seeds=args.budget,
        nodes=args.nodes,
        start_seed=args.start,
        out_dir=os.path.join(args.out, "flat"),
        progress=say,
    )
    dt_flat = time.monotonic() - t0
    print(
        f"  flat: {flat.passed}/{flat.seeds} passed, "
        f"{flat.threats} invariant-threatening, "
        f"{len(flat.findings)} findings ({dt_flat:.1f}s)"
    )

    print(f"=== guided search: same budget ({args.budget}) ===")
    t0 = time.monotonic()
    guided = explore_guided(
        budget=args.budget,
        nodes=args.nodes,
        start_seed=args.start,
        out_dir=os.path.join(args.out, "guided"),
        progress=say,
    )
    dt_guided = time.monotonic() - t0
    print(
        f"  guided: {guided.passed}/{guided.budget} passed, "
        f"{guided.threats} invariant-threatening "
        f"(best fitness {guided.best_fitness}), "
        f"{guided.generations} generations, "
        f"{len(guided.findings)} findings ({dt_guided:.1f}s)"
    )

    failed = False
    honest_failures = [
        f for f in flat.findings if f.profile == "honest"
    ]
    failed |= not check(
        "honest seeds stay green under the adaptive plane",
        not honest_failures,
        "; ".join(
            f"seed {f.seed}: {'; '.join(f.failures[:2])}"
            for f in honest_failures[:5]
        ),
    )
    failed |= not check(
        "guided search surfaces strictly more threats at equal budget",
        guided.threats > flat.threats,
        f"guided {guided.threats} <= flat {flat.threats}",
    )
    failed |= not check(
        "every discovered failure is a contained attack "
        "(trusted-subset PASS) or fixed",
        guided.ok,
        "; ".join(
            f"seed {f.seed} ({f.profile}): {'; '.join(f.failures[:2])}"
            for f in guided.findings[:5]
        ),
    )

    print("=== corpus replay: promoted schedules ===")
    with open(CORPUS) as f:
        corpus = json.load(f)
    promoted = [e for e in corpus["entries"] if "schedule" in e]
    print(f"  {len(promoted)} promoted entries in {CORPUS}")
    replayed = divergences = 0
    for entry in promoted:
        verdict = run_schedule(entry["schedule"])
        same_verdict = verdict.ok == entry["ok"] and (
            list(verdict.threats) == list(entry.get("threats", []))
        )
        same_digest = verdict.journal_digest == entry["journal_digest"]
        replayed += same_verdict and same_digest
        if not (same_verdict and same_digest):
            print(
                f"    seed {entry['seed']}: verdict "
                f"{'ok' if same_verdict else 'DIVERGED'}, digest "
                f"{'ok' if same_digest else 'DIVERGED'} "
                f"(threats {verdict.threats} vs {entry.get('threats')})"
            )
        # containment on replay: a full-history FAIL must come with a
        # trusted-subset PASS
        if not verdict.safety_ok:
            divergences += 1
            if verdict.trusted_ok is not True:
                failed |= not check(
                    f"promoted seed {entry['seed']} trusted-subset PASS",
                    False,
                    f"trusted_ok={verdict.trusted_ok}",
                )
    failed |= not check(
        "every promoted schedule replays deterministically "
        "(same verdict + byte-identical digest)",
        promoted and replayed == len(promoted) or not promoted,
        f"{replayed}/{len(promoted)} replayed clean",
    )
    if promoted:
        print(
            f"  replay: {replayed}/{len(promoted)} clean, "
            f"{divergences} full-history FAILs (all trusted-PASS "
            f"unless flagged above)"
        )

    print("adapt check:", "FAIL" if failed else "ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
