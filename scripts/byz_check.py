#!/usr/bin/env python3
"""Byzantine adversary matrix check (docs/FAULTS.md, Byzantine section).

Runs the canned ``byz-*`` scenarios through the production chaos
runner (``python -m benchmark chaos``) and asserts the contract each
one exists to prove:

- ``byz-equivocate`` — the attack is journaled/counted, the honest
  committee keeps committing one history: run PASSes (exit 0) and the
  ``+ BYZ`` block shows the attack contained.
- ``byz-withhold``  — a withholding node costs rounds, never safety:
  liveness recovers after the window closes and the run PASSes.
- ``byz-collude``   — a shadow-committing colluding pair produces a
  REAL divergent history: the run must FAIL (non-zero exit) with the
  violation attributed to the colluders, while the trusted-subset
  re-check still PASSes over the honest nodes.

Exit non-zero when ANY scenario breaks its contract — including
byz-collude "passing", which would mean the safety checker went blind.

Usage:
    python scripts/byz_check.py [--seed N] [--rate R] [--duration S]
    BYZ=1 scripts/trace.sh                # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_scenario(name: str, seed: int, rate: int, duration: int) -> tuple[int, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [
            sys.executable, "-m", "benchmark", "chaos",
            "--scenario", name, "--seed", str(seed),
            "--rate", str(rate), "--duration", str(duration),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=duration + 240,
    )
    return proc.returncode, proc.stdout + proc.stderr


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {label}" + (f" — {detail}" if detail and not ok else ""))
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=int, default=400)
    ap.add_argument("--duration", type=int, default=30,
                    help="per-run seconds (byz-withhold heals at t=12 "
                    "and must resume within its bound, so keep >= 30)")
    args = ap.parse_args(argv)

    failed = False

    print(f"=== byz-equivocate (seed {args.seed}) ===")
    rc, out = run_scenario("byz-equivocate", args.seed, args.rate, args.duration)
    failed |= not check("run PASSes (exit 0)", rc == 0, f"exit {rc}")
    failed |= not check("+ BYZ block rendered", "+ BYZ:" in out)
    failed |= not check(
        "equivocation counted and attributed to the adversary",
        bool(re.search(r"Adversary node-\d+ .*equivocate x\d+", out)),
    )
    failed |= not check(
        "attack contained on full history",
        "Attack contained (full-history safety): PASS" in out,
    )

    print(f"=== byz-withhold (seed {args.seed}) ===")
    rc, out = run_scenario("byz-withhold", args.seed, args.rate, args.duration)
    failed |= not check("run PASSes (exit 0)", rc == 0, f"exit {rc}")
    failed |= not check(
        "withholding journaled on the adversary",
        bool(re.search(r"Adversary node-\d+ .*withhold x\d+", out)),
    )
    failed |= not check(
        "liveness recovers after the withhold window closes",
        bool(re.search(r"Liveness .*: PASS", out)),
    )

    print(f"=== byz-collude (seed {args.seed}) ===")
    rc, out = run_scenario("byz-collude", args.seed, args.rate, args.duration)
    failed |= not check("run FAILs (non-zero exit)", rc != 0, f"exit {rc}")
    failed |= not check(
        "divergent commits detected",
        "conflicting commits" in out,
    )
    failed |= not check(
        "violation attributed to the colluders",
        "[adversary:" in out,
    )
    failed |= not check(
        "full-history safety verdict is FAIL",
        "Attack contained (full-history safety): FAIL" in out,
    )
    failed |= not check(
        "trusted-subset quorum still agrees (honest nodes consistent)",
        "Trusted-subset quorum (adversaries excluded): PASS" in out,
    )

    print("byz matrix:", "FAIL" if failed else "ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
