"""Smoke the remote harness end-to-end over a real transport (VERDICT
r5 item 9): install (real `git clone` of this repo) -> config keygen +
upload -> detached nohup node/client launch -> log download -> parsed
SUMMARY.  The gcloud CLI surface is served by scripts/fake_gcloud (a
localhost sandbox executor — no sshd exists in this image and nothing
may be installed; see that file's docstring), so every harness command
string, file transfer, and log artifact is real; only the SSH hop is a
local shell.

    python scripts/remote_smoke.py [--nodes 4] [--rate 500] [--duration 15]
"""

import argparse
import os
import shutil
import sys
import time

sys.path.insert(0, ".")

SMOKE_ROOT = "/tmp/hotstuff-remote-smoke"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=int, default=500)
    ap.add_argument("--duration", type=float, default=15.0)
    ap.add_argument("--watch", action="store_true",
                    help="health plane on in the sandbox nodes + live "
                    "fleet dashboard over the instance map for the "
                    "measurement window (remote --watch)")
    args = ap.parse_args()

    # one sandbox "host": the remote layout co-locates extra nodes on a
    # host with sequential ports, and distinct sandboxes share this
    # machine's loopback, so a single host is the collision-free shape
    shutil.rmtree(SMOKE_ROOT, ignore_errors=True)
    os.makedirs(os.path.join(SMOKE_ROOT, "smoke-0"))

    shim_dir = os.path.abspath("scripts/fake_gcloud")
    os.environ["PATH"] = shim_dir + os.pathsep + os.environ["PATH"]
    os.environ["GCLOUD_SHIM_ROOT"] = SMOKE_ROOT

    from benchmark.remote import RemoteBench
    from benchmark.settings import Settings

    settings = Settings(
        testbed="smoke",
        key_path="unused",
        consensus_port=27_100,
        repo_name="hotstuff_tpu_repo",
        repo_url=os.path.abspath("."),
        branch="main",
        zone="localhost-a",
        accelerator_type="local-sandbox",
        runtime_version="local",
        instances=1,
    )
    bench = RemoteBench(settings)

    print("== install (real git clone into the sandbox) ==", flush=True)
    bench.install()
    clone = os.path.join(SMOKE_ROOT, "smoke-0", settings.repo_name)
    assert os.path.isdir(os.path.join(clone, ".git")), "clone missing"
    # the sandbox runs nodes from the clone: build its native libs once
    # up front so first-use builds don't race inside the run window
    bench._ssh("smoke-0", f"make -C {settings.repo_name}/native || true")

    print("== kill + config + run + logs ==", flush=True)
    t0 = time.time()
    bench.run(
        nodes_list=[args.nodes],
        rate_list=[args.rate],
        duration=args.duration,
        watch=args.watch,
        runs=1,
        faults=0,
        verifier="cpu",
    )
    print(f"remote smoke completed in {time.time() - t0:.0f}s", flush=True)
    # relabel the results file so remote-smoke runs never mix into the
    # local-bench aggregates under the same name
    src = f"results/bench-0-{args.nodes}-{args.rate}-cpu.txt"
    dst = f"results/remote-smoke-0-{args.nodes}-{args.rate}-cpu.txt"
    if os.path.exists(src) and os.path.getmtime(src) >= t0:
        with open(src) as f:
            content = f.read()
        with open(dst, "a") as f:
            f.write(content)
        os.remove(src)
        print(f"summary moved to {dst}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
