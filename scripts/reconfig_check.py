#!/usr/bin/env python3
"""Live committee-reconfiguration check (docs/RECONFIG.md).

Runs three canned scenarios through the production chaos runner
(``python -m benchmark chaos``) and asserts the epoch-change contracts
each one exists to prove:

- ``reconfig-rotate`` — a live 4-node committee 2-chain commits a
  sponsored epoch change that rotates in a freshly keyed 5th member
  and retires member 0: run PASSes (exit 0), every node activates
  epoch 2 at the SAME round (epoch agreement PASS), commits never
  stall past the declared handoff bound, the joiner boots in join
  mode, verifies the certified schedule link, and commits in its
  first active epoch, and the retiree serves its grace window before
  a clean ``Retired`` shutdown.
- ``reconfig-retire-crash`` — the same rotation with a SIGKILL+rejoin
  of a SURVIVING member straddling the boundary: everything above
  must still hold (the restarted node replays its persisted schedule
  links and re-activates at the same round).
- ``byz-reconfig`` — an adversary forging unsponsored epoch changes
  and shadow-reporting a skewed activation history: full-history
  epoch agreement must FAIL with the divergence attributed to the
  adversary, while the trusted-subset re-check over honest nodes
  still PASSes and the forged ops die at validation on every honest
  node.

Exit non-zero when ANY contract breaks — including byz-reconfig's
epoch histories "agreeing", which would mean the invariant stopped
reading what nodes actually report.

Usage:
    python scripts/reconfig_check.py [--seed N] [--rate R] [--duration S]
    RECONFIG=1 scripts/trace.sh           # same, via the trace wrapper
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RE_ACTIVATED = re.compile(r"Epoch (\d+) activated at round (\d+)")
RE_LINK = re.compile(r"Verified schedule link: epoch (\d+)")
RE_COMMITTED = re.compile(r"Committed block (\d+)")
RE_RETIRED = re.compile(r"Retired at round (\d+) \(grace window complete\)")
RE_FORGE = re.compile(r"byz reconfig-forge round (\d+)")


def run_scenario(name: str, seed: int, rate: int, duration: int,
                 extra_env: dict | None = None) -> tuple[int, str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [
            sys.executable, "-m", "benchmark", "chaos",
            "--scenario", name, "--seed", str(seed),
            "--rate", str(rate), "--duration", str(duration),
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=duration + 240,
    )
    return proc.returncode, proc.stdout + proc.stderr


def node_logs() -> dict[str, str]:
    out = {}
    for path in sorted(glob.glob(os.path.join(REPO, "logs", "node-*.log"))):
        with open(path, errors="replace") as f:
            out[os.path.basename(path)] = f.read()
    return out


def check(label: str, ok: bool, detail: str = "") -> bool:
    print(f"  [{'ok' if ok else 'FAIL'}] {label}"
          + (f" — {detail}" if detail and not ok else ""))
    return ok


def check_rotation(name: str, rc: int, out: str) -> bool:
    """The shared rotation contract: run PASSes, epoch agreement and
    the handoff bound hold, the joiner joins, the retiree retires."""
    failed = False
    failed |= not check("run PASSes (exit 0)", rc == 0, f"exit {rc}")
    failed |= not check("+ RECONFIG block rendered", "+ RECONFIG:" in out)
    failed |= not check(
        "epoch agreement verdict is PASS", "Epoch agreement: PASS" in out
    )
    m = re.search(r"Handoff gap \(bound (\d+)\): (PASS|FAIL)", out)
    failed |= not check(
        "handoff gap within the declared bound",
        m is not None and m.group(2) == "PASS",
        "no handoff-gap line" if m is None else f"verdict {m.group(2)}",
    )
    logs = node_logs()
    joiner = logs.get("node-4.log", "")
    failed |= not check(
        "joiner booted in join mode",
        "Join mode: key not in the committee yet" in joiner,
    )
    failed |= not check(
        "joiner verified the certified schedule link",
        bool(RE_LINK.search(joiner)),
    )
    # the joiner must participate, not just observe: it commits inside
    # its first active epoch (all its commits are post-join by boot)
    failed |= not check(
        "joiner commits in its first active epoch",
        bool(RE_ACTIVATED.search(joiner)) and bool(RE_COMMITTED.search(joiner)),
        "no activation or no commit in node-4.log",
    )
    retiree = logs.get("node-0.log", "")
    failed |= not check(
        "retiree completed its grace window",
        bool(RE_RETIRED.search(retiree)),
        "no 'Retired at round' line in node-0.log",
    )
    # every node that reports the boundary reports the SAME round (the
    # SUMMARY verdict already asserts this; re-derive from raw logs so
    # the check does not trust its own renderer)
    rounds = {
        m.group(2)
        for text in logs.values()
        for m in RE_ACTIVATED.finditer(text)
    }
    failed |= not check(
        "raw logs agree on one activation round",
        len(rounds) == 1,
        f"activation rounds {sorted(rounds)}",
    )
    return failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rate", type=int, default=400)
    ap.add_argument("--duration", type=int, default=30,
                    help="per-run seconds (the runner extends past the "
                    "reconfig event's settle window automatically)")
    args = ap.parse_args(argv)

    failed = False

    print(f"=== reconfig-rotate (seed {args.seed}) ===")
    rc, out = run_scenario("reconfig-rotate", args.seed, args.rate,
                           args.duration)
    failed |= check_rotation("reconfig-rotate", rc, out)

    print(f"=== reconfig-retire-crash (seed {args.seed}) ===")
    rc, out = run_scenario("reconfig-retire-crash", args.seed, args.rate,
                           args.duration)
    failed |= check_rotation("reconfig-retire-crash", rc, out)
    failed |= not check(
        "crashed member recovered (liveness PASS after heal)",
        bool(re.search(r"Liveness \(recovery after heal.*: PASS", out)),
    )

    print(f"=== byz-reconfig (seed {args.seed}) ===")
    rc, out = run_scenario("byz-reconfig", args.seed, args.rate,
                           args.duration)
    failed |= not check("run FAILs (non-zero exit)", rc != 0, f"exit {rc}")
    failed |= not check(
        "full-history epoch agreement is FAIL",
        "Epoch agreement: FAIL" in out,
    )
    failed |= not check(
        "divergence attributed to the adversary",
        bool(re.search(r"epoch-activation divergence.*\[adversary:", out)),
    )
    failed |= not check(
        "trusted-subset epoch agreement still PASSes",
        "Trusted-subset epoch agreement (adversaries excluded): PASS" in out,
    )
    failed |= not check(
        "safety held under forged epoch changes",
        "Safety (no conflicting commits): PASS" in out,
    )
    logs = node_logs()
    forged = sum(len(RE_FORGE.findall(t)) for t in logs.values())
    failed |= not check(
        "adversary actually forged reconfig ops",
        forged > 0,
        "no 'byz reconfig-forge' line in any node log",
    )
    # forged ops died at validation: no honest node ever activated an
    # epoch past the one real rotation
    epochs = {
        int(m.group(1))
        for text in logs.values()
        for m in RE_ACTIVATED.finditer(text)
    }
    failed |= not check(
        "forged ops never activated an epoch",
        epochs <= {2},
        f"epochs activated: {sorted(epochs)}",
    )

    print("reconfig matrix:", "FAIL" if failed else "ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
