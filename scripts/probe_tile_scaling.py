"""Probe: is the Pallas DSM kernel latency-bound or throughput-bound in
the lane dimension?

Decides the fate of the 512-lane wide split tile (VERDICT r2 item 1c):
- If a 128-lane tile costs ~the same as a 256-lane tile (latency-bound),
  doubling lanes is ~free and the 512-lane 16-step scan should halve the
  256-vote QC time -> budget the one-time Mosaic compile.
- If cost scales ~linearly with lanes (throughput-bound), the wide tile
  cannot win -> delete it and spend the effort on signed-digit windows.

Method: slope timing (chained dispatches, (T_long-T_short)/delta) of
dual_scalar_mult at batch 128 (bt=128), 256 (bt=256), 512 (bt=256,
grid=2), repeated; reports the median slope per shape.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hotstuff_tpu  # noqa: F401,E402  (compilation cache)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from hotstuff_tpu.crypto import ed25519_ref as ref
    from hotstuff_tpu.tpu import curve
    from hotstuff_tpu.tpu.pallas_dsm import dual_scalar_mult

    print("platform:", jax.devices()[0].platform, flush=True)

    pk = ref.public_from_seed(b"\x5a" * 32)
    pt = curve.point_to_limbs(ref.point_neg(ref.point_decompress(pk)))
    rng = np.random.default_rng(7)

    def inputs(batch):
        s_win = rng.integers(0, 16, (curve.NWIN, batch)).astype(np.int32)
        k_win = rng.integers(0, 16, (curve.NWIN, batch)).astype(np.int32)
        a = tuple(
            jnp.asarray(np.repeat(np.asarray(c)[None, :], batch, axis=0))
            for c in pt
        )
        return jnp.asarray(s_win), jnp.asarray(k_win), a

    def slope_ms(batch, short=8, long=64, reps=7):
        # long chains: the tunnel's RTT variance (~±15 ms) must be small
        # against (long-short) dispatches of signal, or slopes go
        # negative (observed with 4-vs-16 chains)
        s, k, a = inputs(batch)
        out = dual_scalar_mult(s, k, a)
        jax.block_until_ready(out)  # compile/warm
        slopes = []
        for _ in range(reps):
            times = {}
            for n in (short, long):
                t0 = time.perf_counter()
                for _ in range(n):
                    out = dual_scalar_mult(s, k, a)
                np.asarray(out[1])
                times[n] = time.perf_counter() - t0
            slopes.append((times[long] - times[short]) / (long - short))
        slopes.sort()
        return slopes[len(slopes) // 2] * 1e3

    for batch in (128, 256, 512):
        t0 = time.perf_counter()
        ms = slope_ms(batch)
        print(
            f"batch {batch:4d}: {ms:7.3f} ms/dispatch "
            f"(total incl warm/compile {time.perf_counter() - t0:.1f}s)",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
