#!/usr/bin/env bash
# One-command flight-recorder run: journal-enabled local bench, merged
# cross-node trace in the SUMMARY, Chrome trace JSON for Perfetto.
#
#   scripts/trace.sh                         # 4 nodes, 500 tx/s, 10 s
#   scripts/trace.sh --nodes 8 --rate 1000   # extra args pass through
#
# Output: logs/journals/ (per-node JSONL ring segments) and
# logs/trace.json — open the latter at https://ui.perfetto.dev.
# Timeout-bounded so a hung committee cannot wedge a CI job.
#
#   PERFGATE=1 scripts/trace.sh   # also run the perf regression gate
#                                 # (scripts/perfgate.py) afterwards
#   TUNNEL=1 scripts/trace.sh     # ONLY the dispatch-tunnel anatomy
#                                 # check (scripts/tunnel_check.py):
#                                 # waterfall at QC 16/64/256, e2e
#                                 # delta vs the committed reference,
#                                 # non-zero exit if leaf-span coverage
#                                 # drops below 95%
#   BYZ=1 scripts/trace.sh        # ONLY the Byzantine adversary matrix
#                                 # (scripts/byz_check.py): equivocation
#                                 # caught-and-attributed, collusion
#                                 # FAILs with non-zero exit, withholding
#                                 # recovers liveness
#   MESH=1 scripts/trace.sh       # ONLY the mesh scale-out check
#                                 # (scripts/mesh_check.py): wave trains
#                                 # at mesh 1 and 8 on the virtual
#                                 # 8-device CPU mesh, non-zero exit if
#                                 # mesh-8 scaling efficiency falls
#                                 # below the committed-reference floor
#   AGG=1 scripts/trace.sh        # ONLY the compact-certificate sweep
#                                 # (scripts/agg_check.py): compact vs
#                                 # vote-list QC parity + one-pairing
#                                 # flatness across committee sizes,
#                                 # non-zero exit on any divergence
#   LOAD=1 scripts/trace.sh       # ONLY the admission-plane load check
#                                 # (scripts/load_check.py): open-loop
#                                 # saturation sweep + 2x-saturation
#                                 # overload with a squeezed proposer
#                                 # buffer, non-zero exit on any silent
#                                 # drop-newest
#   STATE=1 scripts/trace.sh      # ONLY the replicated execution-layer
#                                 # check (scripts/state_check.py):
#                                 # SIGKILLed node rejoins via snapshot
#                                 # state-sync with a converging root,
#                                 # byz-collude FAILs full-history root
#                                 # agreement while the trusted subset
#                                 # PASSes, non-zero exit on any break
#   HEALTH=1 scripts/trace.sh     # ONLY the live health-plane check
#                                 # (scripts/health_check.py): fleet
#                                 # watch attaches to a healthy 4-node
#                                 # committee with quiet detectors,
#                                 # leader-isolation trips leader_stall
#                                 # in the live view AND the + HEALTH
#                                 # SUMMARY, and the dispatch ratchet
#                                 # holds with the plane enabled
#   RECONFIG=1 scripts/trace.sh   # ONLY the live-reconfiguration check
#                                 # (scripts/reconfig_check.py): rotate
#                                 # joins node 4 / retires node 0 with
#                                 # epoch agreement + bounded handoff
#                                 # gap, the rotation survives a
#                                 # SIGKILL+rejoin across the boundary,
#                                 # and byz-reconfig FAILs full-history
#                                 # epoch agreement (trusted subset
#                                 # PASSes); non-zero exit on any break
#   SIM=1 scripts/trace.sh        # ONLY the deterministic-simulator
#                                 # sweep (scripts/sim_check.py): a
#                                 # 500-seed virtual-time explore at
#                                 # n=4 (faults+crashes+byz mix), zero
#                                 # honest invariant failures, the
#                                 # byz-collude family FAILs
#                                 # full-history / PASSes
#                                 # trusted-subset, and a double-run
#                                 # determinism probe; non-zero exit on
#                                 # any break
#   ADAPT=1 scripts/trace.sh      # ONLY the adaptive-adversary check
#                                 # (scripts/adapt_check.py): guided
#                                 # schedule search beats the flat sweep
#                                 # on invariant-threatening schedules
#                                 # at equal budget, honest seeds stay
#                                 # green, and every promoted corpus
#                                 # schedule replays to the same verdict
#                                 # with a byte-identical journal digest
#   CRIT=1 scripts/trace.sh       # ONLY the commit critical-path check
#                                 # (scripts/critpath_check.py): a
#                                 # journaled 4-node run must attribute
#                                 # with >= 90% coverage and print the
#                                 # + CRITPATH block, the --diff gate
#                                 # passes unchanged / fails a planted
#                                 # stage-share regression, and the
#                                 # regime classification is stable
#                                 # across two identical runs
#   NET=1 scripts/trace.sh        # ONLY the wire-level flow accounting
#                                 # check (scripts/net_check.py): a
#                                 # 4-node run must print + NET with
#                                 # propose amplification ~ n-1, class
#                                 # shares covering >= 95% of egress,
#                                 # compact QCs beating the vote list
#                                 # on the wire and zero clean-link
#                                 # retransmits; same-seed sim runs
#                                 # must produce byte-identical flow
#                                 # tables and amp stays sane under
#                                 # flapping-link chaos
#   INGEST=1 scripts/trace.sh     # ONLY the zero-copy ingest check
#                                 # (scripts/ingest_check.py): signed
#                                 # votes over the native reactor
#                                 # transport must verify straight from
#                                 # the staging arenas — every verdict
#                                 # True, zero-copy hit rate >= 90%,
#                                 # e2e sigs/s reported; non-zero exit
#                                 # if the pack/claim streams desync
#   LINT=1 scripts/trace.sh       # ONLY the static analysis plane
#                                 # (scripts/analysis_check.py): every
#                                 # hotstuff_tpu/analysis lint rule,
#                                 # docs/KNOBS.md freshness, and the
#                                 # native TSan/ASan reactor + store
#                                 # stress (skip-if-unsupported),
#                                 # non-zero exit on any finding
set -euo pipefail

cd "$(dirname "$0")/.."

if [ "${TUNNEL:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/tunnel_check.py "$@"
fi

if [ "${MESH:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/mesh_check.py "$@"
fi

if [ "${AGG:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/agg_check.py "$@"
fi

if [ "${BYZ:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/byz_check.py "$@"
fi

if [ "${LOAD:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/load_check.py "$@"
fi

if [ "${STATE:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/state_check.py "$@"
fi

if [ "${HEALTH:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/health_check.py "$@"
fi

if [ "${RECONFIG:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/reconfig_check.py "$@"
fi

if [ "${SIM:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/sim_check.py "$@"
fi

if [ "${ADAPT:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/adapt_check.py "$@"
fi

if [ "${CRIT:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/critpath_check.py "$@"
fi

if [ "${NET:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/net_check.py "$@"
fi

if [ "${INGEST:-0}" = "1" ]; then
    exec timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/ingest_check.py "$@"
fi

if [ "${LINT:-0}" = "1" ]; then
    # stdlib-only: the analysis plane never imports jax, so this gate
    # also runs in the bare CI lint venv
    exec timeout -k 10 1800 python scripts/analysis_check.py "$@"
fi

timeout -k 10 240 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m benchmark local \
    --nodes 4 --rate 500 --duration 10 --journal "$@"

if [ "${PERFGATE:-0}" = "1" ]; then
    timeout -k 10 1800 env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python scripts/perfgate.py
fi
