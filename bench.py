"""Benchmark: TPU Ed25519 batch-verify throughput + QC-verify latency.

Measures the framework's hot kernel — batched Ed25519 signature
verification (the QC-verify path: SURVEY.md §2.1 hot spots, BASELINE.json
north star) — against the CPU path (OpenSSL via `cryptography`, the
same backend the cpu verifier uses in production).

Methodology (r2, replacing r1's flattering pipeline math; tunnel/QC
latency views extended in ISSUE 6):
- throughput: 16 kernel dispatches on pre-staged device inputs, timed
  through a FULL result fetch of the final output (device->host), so the
  clock cannot stop before the device work is done.  Under the
  development tunnel block_until_ready() returns early, so fetch-based
  sync is the only honest stop condition.
- tunnel, two views: ``tunnel_rtt_p50_ms`` is the blocking round trip of
  one tiny dispatch+fetch (what a fully serialized caller pays);
  ``tunnel_dispatch_p50_ms`` is the AMORTIZED per-dispatch cost of a
  16-in-flight pipelined stream (total wall / 16) — the cost the
  production dispatch loop actually pays per crossing, since it never
  serializes on the tunnel (measured: 16 in flight costs about the same
  wall time as 1).
- QC latency, two views per size: ``blocking_p50/p99_ms`` is the old
  fully-serialized dispatch + full fetch (includes one whole tunnel RTT
  per wave — the pre-ISSUE-6 ``rig_*`` numbers); ``rig_p50/p99_ms`` is
  the sustained amortized per-wave latency of an 8-wave distinct-digest
  train driven through the PRODUCTION AsyncVerifyService dispatch
  pipeline (fixed-shape buckets + dispatch-loop slots + pipelining) —
  what a node under consensus load observes per QC.

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "qc_verify_ms": {...}}
vs_baseline > 1 means the TPU path beats the CPU baseline.

Baseline (r5, replacing r1-r4's derating footnote): the CPU number is
a TRUE dalek-parity batch verification — the random-linear-combination
equation over a Pippenger multiscalar, implemented in C++
(native/ed25519_batch.cpp) and measured directly on the same batches.
Provenance (backend + per-signature-loop rate for drift tracking) is
pinned in the "baseline" field of the output each run.
"""

from __future__ import annotations

import json
import sys
import time

import hotstuff_tpu  # noqa: F401  (sets the shared compilation-cache
# dir; must import before jax reads its config env vars)


BATCH = 1024  # four 256-vote QCs per dispatch (256-node committee shape)
WARMUP = 2
ROUNDS = 16  # dispatches per throughput measurement
LAT_REPS = 20


def make_qc_batch(n: int):
    """n committee signatures over ONE shared digest (the QC shape)."""
    from hotstuff_tpu.crypto import Digest, Signature, generate_keypair

    shared = Digest.of(b"bench block digest")
    msgs, pks, sigs = [], [], []
    for i in range(n):
        pk, sk = generate_keypair(b"\x33" * 32, i)
        msgs.append(shared.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(shared, sk).to_bytes())
    return msgs, pks, sigs


def _stage(verifier, msgs, pks, sigs):
    """(kernel_fn, device-staged arrays) via the production routing
    point (verifier.stage picks XLA / Pallas / Pallas-split)."""
    import jax
    import jax.numpy as jnp

    kernel, arrays, _ = verifier.stage(msgs, pks, sigs)
    staged = jax.device_put(tuple(jnp.asarray(a) for a in arrays))
    jax.block_until_ready(staged)
    return kernel, staged


def bench_tpu(msgs, pks, sigs) -> tuple[float, dict]:
    """(throughput sigs/s, {qc_size: {p50_ms, p99_ms}})."""
    import numpy as np

    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    verifier = BatchVerifier(min_device_batch=0)  # measure the kernel
    verifier.precompute(pks)  # epoch setup: committee keys decompressed once

    for _ in range(WARMUP):
        out = verifier.verify(msgs, pks, sigs)
        assert out.all(), "TPU verify returned invalid on a valid batch"

    _kernel, staged = _stage(verifier, msgs, pks, sigs)

    # throughput: FIFO dispatch stream, clock stopped by a full fetch of
    # the last result (the only sync the tunnel can't fake).  On this
    # rig the stream is TUNNEL-bound (per-dispatch enqueue ~4-10 ms >>
    # the ~2 ms kernel), so this is the honest end-to-end rate of THIS
    # rig; the co-located device rate is device_sigs_per_s below.
    t0 = time.perf_counter()
    outs = [_kernel(*staged) for _ in range(ROUNDS)]
    final = np.asarray(outs[-1])
    dt = time.perf_counter() - t0
    assert final.all()
    tput = ROUNDS * len(msgs) / dt

    # QC-verify latency, three views per QC-shaped size:
    # - blocking_p50/p99_ms: fully serialized dispatch + full result
    #   fetch (includes one whole tunnel round-trip per wave — the
    #   pre-ISSUE-6 rig_* numbers, kept for series comparability);
    # - rig_p50/p99_ms: merged in from bench_qc_pipelined() — sustained
    #   amortized per-wave latency through the production dispatch path;
    # - device_ms: dispatch-slope estimate over chained dispatch
    #   streams, which cancels fixed per-stream overhead and estimates
    #   the co-located per-QC device time.
    latencies: dict = {}
    for qc_size in (16, 64, 256):
        qc_kernel, sub = _stage(
            verifier, msgs[:qc_size], pks[:qc_size], sigs[:qc_size]
        )
        np.asarray(qc_kernel(*sub))  # warm this shape
        times = []
        for _ in range(LAT_REPS):
            t0 = time.perf_counter()
            ok = np.asarray(qc_kernel(*sub))
            times.append(time.perf_counter() - t0)
            assert ok.all()
        times.sort()
        latencies[str(qc_size)] = {
            "blocking_p50_ms": round(times[len(times) // 2] * 1e3, 3),
            "blocking_p99_ms": round(times[-1] * 1e3, 3),
            "device_ms": _device_slope_ms(qc_kernel, sub),
        }

    # co-located device rate: batch-1024 kernel time via the in-dispatch
    # loop slope (the dispatch-stream tput above is tunnel-bound)
    device_ms_1024 = _device_slope_ms(_kernel, staged)
    device_rate = round(BATCH / (device_ms_1024 / 1e3)) if device_ms_1024 > 0 else None
    return tput, latencies, {
        "batch": BATCH,
        "device_ms": device_ms_1024,
        "device_sigs_per_s": device_rate,
    }


def _device_slope_ms(kernel, staged) -> float:
    """In-dispatch loop slope: the per-call DEVICE time measured by
    running the kernel N times inside ONE dispatch (lax.fori_loop with a
    data-dependent carry — rolling the scalar windows each iteration
    defeats CSE/hoisting and forces sequential execution) and taking
    (T_long - T_short) / (long - short) over single dispatches.

    Why not chained host dispatches (r2's method): once the kernel
    dropped under ~2 ms the chain became TUNNEL-bound — the dev rig's
    per-dispatch enqueue cost (~4-10 ms, load-dependent) swamps the
    device time entirely and the 'slope' measures tunnel weather
    (observed: 0.7 ms and 4.5 ms for the SAME compiled shape in
    back-to-back runs).  One dispatch per sample amortizes the tunnel
    out of the slope."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def make(n):
        @jax.jit
        def run(args):
            def body(_i, carry):
                acc, s = carry
                out = kernel(
                    args[0], args[1], args[2], args[3],
                    s, args[5], args[6], args[7],
                )
                return (
                    acc + jnp.sum(out.astype(jnp.int32)),
                    jnp.roll(s, 1, axis=-1),
                )
            acc, _ = jax.lax.fori_loop(
                0, n, body, (jnp.int32(0), args[4])
            )
            return acc
        return run

    # 132 iterations of slope: the tunnel's ±15 ms single-dispatch RTT
    # variance divides down to ±0.11 ms — adequate for sub-ms kernels
    short, long = 4, 136
    run_short, run_long = make(short), make(long)
    np.asarray(run_short(staged))  # warm both loop shapes
    np.asarray(run_long(staged))
    slopes = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(run_short(staged))
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run_long(staged))
        t_long = time.perf_counter() - t0
        slopes.append((t_long - t_short) / (long - short))
    slopes.sort()
    return round(slopes[len(slopes) // 2] * 1e3, 3)


def make_tc_batch(n: int):
    """n committee signatures over n DISTINCT timeout digests — the TC /
    view-change-storm shape (BASELINE config 4; reference verifies these
    sequentially, messages.rs:305-311)."""
    from hotstuff_tpu.consensus.messages import timeout_digest
    from hotstuff_tpu.crypto import Signature, generate_keypair

    msgs, pks, sigs = [], [], []
    for i in range(n):
        pk, sk = generate_keypair(b"\x44" * 32, i)
        d = timeout_digest(10, i)  # one DISTINCT digest per entry
        msgs.append(d.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(d, sk).to_bytes())
    return msgs, pks, sigs


def bench_tc(verifier) -> dict:
    """TC-verify latency at the 256-committee storm quorum (171 distinct
    digests): p50/p99 of dispatch + full fetch, plus the device-slope
    line (VERDICT r2 weak #3 — the raw rig p50 is tunnel-RTT-dominated,
    so the TC kernel's actual device cost was unmeasured)."""
    import numpy as np

    n = 2 * 256 // 3 + 1  # 171
    msgs, pks, sigs = make_tc_batch(n)
    verifier.precompute(pks)
    kernel, staged = _stage(verifier, msgs, pks, sigs)
    np.asarray(kernel(*staged))  # warm the padded shape
    times = []
    for _ in range(LAT_REPS):
        t0 = time.perf_counter()
        ok = np.asarray(kernel(*staged))
        times.append(time.perf_counter() - t0)
        assert ok.all()
    times.sort()
    return {
        "quorum": n,
        "rig_p50_ms": round(times[len(times) // 2] * 1e3, 3),
        "rig_p99_ms": round(times[-1] * 1e3, 3),
        "device_ms": _device_slope_ms(kernel, staged),
    }


def bench_cpu(msgs, pks, sigs) -> tuple[float, dict]:
    """True batched CPU baseline (VERDICT r4 item 5).

    The reference's ``Signature::verify_batch`` is dalek batch
    verification (crypto/src/lib.rs:213-226); the parity implementation
    is native/ed25519_batch.cpp (random-linear-combination equation,
    Pippenger multiscalar).  vs_baseline is computed against it
    directly — no estimated derating.  Provenance is pinned in the
    output: which backend was measured, plus the per-signature-loop
    rate for drift tracking across rounds (the r3→r4 ratio drift came
    from an unpinned baseline)."""
    from hotstuff_tpu.crypto import native_ed25519
    from hotstuff_tpu.crypto.signature import batch_verify_arrays

    n = len(msgs)
    rounds = 3

    def timed(fn) -> float:
        assert fn()
        t0 = time.perf_counter()
        for _ in range(rounds):
            ok = fn()
        dt = time.perf_counter() - t0
        assert ok
        return rounds * n / dt

    loop_rate = timed(lambda: all(batch_verify_arrays(msgs, pks, sigs)))
    provenance = {
        "batch": n,
        "loop_sigs_per_s": round(loop_rate),
        "loop_backend": "openssl-per-signature",
    }
    if native_ed25519.available():
        shared, pkb, sgb = msgs[0], b"".join(pks), b"".join(sigs)
        batch_rate = timed(
            lambda: native_ed25519.batch_verify(
                shared, 32, pkb, sgb, n, shared=True
            )
        )
        provenance["backend"] = (
            "native-batch (dalek parity; straus<200<=pippenger)"
        )
        provenance["batch_sigs_per_s"] = round(batch_rate)
        baseline = max(batch_rate, loop_rate)
    else:
        provenance["backend"] = "openssl-per-signature (native batch unavailable)"
        baseline = loop_rate
    return baseline, provenance


def bench_sharded(msgs, pks, sigs) -> dict:
    """The PRODUCTION sharded route (shard_map + per-shard Pallas) on the
    real device mesh (VERDICT r3 item 7): a mesh of every visible device
    (1 on this rig — the code path is identical to a v5e-8's, only the
    axis size differs).  Records the 256-vote QC device slope for a
    parity check against the single-device kernel."""
    import numpy as np

    from hotstuff_tpu.parallel.mesh import ShardedBatchVerifier, default_mesh

    mesh = default_mesh()
    verifier = ShardedBatchVerifier(mesh=mesh, min_device_batch=0)
    verifier.precompute(pks)
    qc = 256
    out = verifier.verify(msgs[:qc], pks[:qc], sigs[:qc])
    assert out.all(), "sharded verify returned invalid on a valid batch"
    kernel, staged = _stage(verifier, msgs[:qc], pks[:qc], sigs[:qc])
    np.asarray(kernel(*staged))
    return {
        "mesh_devices": int(mesh.devices.size),
        "per_shard_pallas": bool(verifier._shard_pallas),
        "qc256_device_ms": _device_slope_ms(kernel, staged),
    }


def bench_verify_split(msgs, pks, sigs) -> dict:
    """Host-dispatch vs device wall split for QC verification, measured
    through the telemetry counters the async verify service exports
    (hotstuff_verify_host_wall_seconds / _device_wall_seconds on
    /metrics): QC-shaped claim waves driven through both the inline host
    route and the device dispatch route, so the reported split comes
    from the SAME instruments a production node publishes — not a
    bench-only stopwatch."""
    import asyncio

    from hotstuff_tpu import telemetry
    from hotstuff_tpu.crypto.async_service import AsyncVerifyService
    from hotstuff_tpu.crypto.service import CpuVerifier
    from hotstuff_tpu.node.node import LazyDeviceVerifier

    telemetry.enable()
    qc = 256
    claim = ("shared", msgs[0], tuple(zip(pks[:qc], sigs[:qc])))

    async def drive() -> dict:
        host = AsyncVerifyService(CpuVerifier())  # inline host route
        dev_backend = LazyDeviceVerifier("tpu")
        dev_backend.precompute(pks)
        dev_backend.warmup(batch=qc)
        device = AsyncVerifyService(dev_backend, device=True)
        try:
            for _ in range(8):
                assert (await host.verify_claims([claim])) == [True]
                assert (await device.verify_claims([claim])) == [True]
        finally:
            device.close()

        reg = telemetry.registry()

        def total(name: str) -> float:
            return sum(i.value for i in reg if i.name == f"hotstuff_{name}")

        return {
            "qc_size": qc,
            "host_wall_ms": round(total("verify_host_wall_seconds") * 1e3, 3),
            "device_wall_ms": round(
                total("verify_device_wall_seconds") * 1e3, 3
            ),
            "device_sigs": device.device_sigs,
            "cpu_fallback_sigs": device.cpu_sigs,
            "deadline_misses": device.deadline_misses,
            "claims_submitted": int(total("verify_claims_submitted")),
            "claims_unique": int(total("verify_claims_unique")),
        }

    return asyncio.run(drive())


def bench_pipeline() -> dict:
    """Sustained QC-256 wave-train through the dispatch pipeline
    (ISSUE 5): amortized per-wave latency and peak occupancy at depth 1
    (the old single-in-flight gate, the parity row) vs depth 2 (the
    default).  Distinct digests per wave defeat the claim dedup, so
    every wave is a real dispatch; depth 2's amortized wave must come in
    below depth 1's — that gap IS the staging/execute overlap, while
    device_ms elsewhere in this output stays unchanged (the kernel does
    the same work; only the host-side pipelining differs)."""
    from benchmark.profile import run_train

    r = run_train(size=256, train=8, reps=3, depth=2, verifier="tpu")
    depths = {str(d): res for d, res in r["depths"].items()}
    return {
        "qc_size": r["qc_size"],
        "train_waves": r["train_waves"],
        "depths": depths,
        "overlap_speedup": r.get("overlap_speedup"),
        "overlap_efficiency_pct": r.get("overlap_efficiency_pct"),
        # the perfgate throughput metric: depth-2 sustained train rate
        "train_sigs_per_s": depths.get("2", {}).get("train_sigs_per_s"),
    }


def bench_qc_pipelined(sizes=(16, 64, 256), train: int = 8, reps: int = 5) -> dict:
    """Per-size ``rig_p50/p99_ms`` — the sustained amortized per-wave QC
    latency through the PRODUCTION dispatch path (AsyncVerifyService:
    fixed-shape wave buckets, long-lived dispatch-loop slots, depth-K
    pipelining).  Each sample drives ``train`` distinct-digest QC waves
    back to back (dedup-defeating, single committee) and charges the
    train's wall clock per wave; p50/p99 over ``reps`` trains.  This is
    what a node under consensus load observes per QC — the serialized
    single-wave view is kept alongside as ``blocking_*`` (bench_tpu)."""
    import asyncio
    import os

    from benchmark.profile import make_train_claims
    from hotstuff_tpu.crypto.async_service import (
        AsyncVerifyService,
        eval_claims_sync,
    )
    from hotstuff_tpu.node.node import LazyDeviceVerifier

    os.environ["HOTSTUFF_FORCE_DEVICE_ROUTE"] = "1"
    out: dict = {}
    try:
        backend = LazyDeviceVerifier("tpu")
        for n in sizes:
            claims, pks = make_train_claims(n, train)
            backend.precompute(pks)
            backend.warmup(batch=n)
            # warm the padded shape through the real dispatch view so no
            # measured train pays a cold XLA compile
            assert eval_claims_sync(backend.async_backend, [claims[0]]) == [True]
            backend.dispatch_deadline_s = 30.0

            async def drive() -> list[float]:
                svc = AsyncVerifyService(backend, device=True)
                svc.warm_buckets()
                try:
                    for _ in range(WARMUP):
                        assert (await svc.verify_claims([claims[0]])) == [True]
                    samples: list[float] = []
                    for _ in range(reps):
                        t0 = time.perf_counter()
                        futs = []
                        for claim in claims:
                            futs.append(
                                asyncio.ensure_future(svc.verify_claims([claim]))
                            )
                            await asyncio.sleep(0)
                            while svc._pending:
                                await asyncio.sleep(0)
                        results = await asyncio.gather(*futs)
                        samples.append(
                            (time.perf_counter() - t0) * 1e3 / train
                        )
                        assert all(r == [True] for r in results)
                    samples.sort()
                    return samples
                finally:
                    svc.close()

            samples = asyncio.run(drive())
            out[str(n)] = {
                "rig_p50_ms": round(samples[len(samples) // 2], 3),
                "rig_p99_ms": round(samples[-1], 3),
                "train_waves": train,
            }
    finally:
        os.environ.pop("HOTSTUFF_FORCE_DEVICE_ROUTE", None)
    return out


def bench_agg_qc(sizes=(64, 256, 512), reps: int = 5) -> dict:
    """Compact (aggregated) QC vs the vote-list BLS baseline (ISSUE 9),
    per committee size: certificate wire bytes, QC formation p50 (build
    + encode from already-accumulated votes — the compact path snapshots
    a running G1 sum and emits ~50 wire bytes, the vote-list path copies
    and encodes n×144), and verify p50 — ``verify_aggregate_msg``'s one
    pairing over the memoized key sum vs ``verify_shared_msg``'s O(n)
    re-aggregation per certificate.  ``verify_cold_ms`` keeps the
    first-bitmap cost (one O(n) key sum) honest next to the steady-state
    p50.  Committee secrets are small scalars so fixture generation is
    O(n) cheap point multiplies — verification cost is unaffected.

    Headline scalars: ``verify_p50_ms`` (largest committee, the perfgate
    guard) and ``flat_ratio`` = compact verify p50 at max size / at min
    size — the acceptance bar is < 1.5 while the vote-list baseline
    grows with n."""
    from hotstuff_tpu.consensus.handel import HandelTopology, simulate
    from hotstuff_tpu.consensus.messages import QC, make_signer_bitmap
    from hotstuff_tpu.crypto import Digest, PublicKey, Signature
    from hotstuff_tpu.crypto.bls import BlsSecretKey
    from hotstuff_tpu.crypto.scheme import make_cpu_verifier

    digest = Digest.of(b"bench agg qc block digest")
    msg = digest.to_bytes()
    out: dict = {}
    p50s: dict[int, float] = {}
    for n in sizes:
        verifier = make_cpu_verifier("bls")  # fresh memo per size
        sks = [BlsSecretKey(i + 2) for i in range(n)]
        pks = sorted(
            PublicKey(sk.public_key().to_bytes()) for sk in sks
        )
        sk_by_pk = {
            PublicKey(sk.public_key().to_bytes()): sk for sk in sks
        }
        quorum = 2 * n // 3 + 1
        signers = pks[:quorum]
        votes = [
            (pk, Signature(sk_by_pk[pk].sign(msg).to_bytes()))
            for pk in signers
        ]
        verifier.precompute([pk.to_bytes() for pk in signers])

        from hotstuff_tpu.crypto.bls.curve import G1Point

        sig_points = [
            G1Point.from_bytes(sig.to_bytes(), subgroup_check=False)
            for _, sig in votes
        ]
        running_sum = G1Point.sum(sig_points)  # what the accumulator holds

        def timed(fn, count=reps):
            samples = []
            for _ in range(count):
                t0 = time.perf_counter()
                fn()
                samples.append((time.perf_counter() - t0) * 1e3)
            samples.sort()
            return samples

        # -- formation: votes already accumulated -> QC on the wire ----
        def form_compact():
            bitmap = make_signer_bitmap(signers, pks)
            qc = QC(
                hash=digest,
                round=3,
                votes=[],
                agg_sig=Signature(running_sum.to_bytes()),
                signers=bitmap,
            )
            return qc.wire_size()

        def form_votelist():
            return QC(hash=digest, round=3, votes=list(votes)).wire_size()

        compact_bytes = form_compact()
        votelist_bytes = form_votelist()
        form_c = timed(form_compact)
        form_v = timed(form_votelist)

        # -- verification ---------------------------------------------
        agg_bytes = running_sum.to_bytes()
        pk_bytes = [pk.to_bytes() for pk in signers]
        assert verifier.verify_aggregate_msg(digest, pk_bytes, agg_bytes)
        # genuinely cold verifier for the first-bitmap (key-sum) cost —
        # the warm ``verifier`` above now holds the memoized aggregate
        fresh = make_cpu_verifier("bls")
        fresh.precompute(pk_bytes)
        t0 = time.perf_counter()
        assert fresh.verify_aggregate_msg(digest, pk_bytes, agg_bytes)
        cold = [(time.perf_counter() - t0) * 1e3]
        verify_c = timed(
            lambda: verifier.verify_aggregate_msg(
                digest, pk_bytes, agg_bytes
            )
        )
        verify_v = timed(
            lambda: verifier.verify_shared_msg(digest, votes)
        )

        # -- Handel plane: leader-side merge count at this size --------
        topo = HandelTopology.for_round(n, round_=3)
        sigs_by_index = {
            pks.index(pk): sig.to_bytes() for pk, sig in votes
        }
        final, top_merges, _ = simulate(topo, sigs_by_index)
        assert final.weight == quorum

        p50s[n] = verify_c[len(verify_c) // 2]
        out[str(n)] = {
            "qc_bytes_compact": compact_bytes,
            "qc_bytes_votelist": votelist_bytes,
            "form_p50_ms": round(form_c[len(form_c) // 2], 3),
            "form_votelist_p50_ms": round(form_v[len(form_v) // 2], 3),
            "verify_p50_ms": round(verify_c[len(verify_c) // 2], 3),
            "verify_cold_ms": round(cold[0], 3),
            "verify_votelist_p50_ms": round(
                verify_v[len(verify_v) // 2], 3
            ),
            "handel_levels": topo.levels,
            "handel_leader_merges": top_merges,
        }
    lo, hi = min(sizes), max(sizes)
    out["verify_p50_ms"] = round(p50s[hi], 3)
    out["flat_ratio"] = round(p50s[hi] / max(p50s[lo], 1e-9), 3)
    return out


def bench_load() -> dict | None:
    """Admission-plane goodput probe (ISSUE 10): one short open-loop
    loadgen run (benchmark/loadgen.py) against a live 4-node local
    committee — committed goodput and client-observed p50/p99 through
    the REAL submit->commit path, the numbers scripts/perfgate.py
    guards (``load.goodput_tx_s`` must not fall, ``load.client_p99_ms``
    must not rise).  Returns None (key omitted, guards skip) when the
    committee cannot be spawned on this host — the kernel benchmarks
    above must still publish."""
    try:
        from benchmark.loadgen import quick_load

        return quick_load(nodes=4, rate=2_000, duration=10.0)
    except Exception as e:  # the bench must survive a failed committee
        print(f"bench_load skipped: {e!r}", file=sys.stderr)
        return None


def bench_state(blocks_n: int = 256, per_block: int = 8) -> dict | None:
    """Replicated execution-layer micro-bench (ISSUE 11): typed-op
    apply throughput through ``StateMachine.apply_block`` over a WAL
    store, then the wall cost of a full snapshot serve (manifest +
    chunks) + adopt cycle into a fresh store — the no-replay rejoin
    path a crash-recovered node takes.  Feeds the ``state.apply_tx_s``
    and ``state.sync_catchup_s`` perfgate guards; returns None (key
    omitted, guards skip) on any failure so the kernel benchmarks above
    still publish."""
    import os
    import tempfile

    try:
        from hotstuff_tpu.crypto import Digest
        from hotstuff_tpu.store import Store
        from hotstuff_tpu.store.state import (
            OP_BODY_OFFSET,
            StateMachine,
            encode_ops,
        )

        class _Committed:
            __slots__ = ("round", "payloads", "_digest")

            def __init__(self, round_, payloads):
                self.round = round_
                self.payloads = payloads
                self._digest = Digest.random()

            def digest(self):
                return self._digest

        with tempfile.TemporaryDirectory() as tmp:
            src_store = Store(os.path.join(tmp, "src"))
            blocks = []
            for r in range(1, blocks_n + 1):
                payloads = tuple(
                    Digest.random() for _ in range(per_block)
                )
                for d in payloads:
                    body = b"\x00" * OP_BODY_OFFSET + encode_ops(
                        [("put", b"bench/%d" % r, d.to_bytes())]
                    )
                    src_store.engine.put(b"p" + d.to_bytes(), body)
                blocks.append(_Committed(r, payloads))
            src = StateMachine(src_store)
            t0 = time.perf_counter()
            for block in blocks:
                src.apply_block(block)
            apply_s = time.perf_counter() - t0

            dst = StateMachine(Store(os.path.join(tmp, "dst")))
            t0 = time.perf_counter()
            manifest = src.manifest()
            entries = []
            for index in range(manifest.chunk_count):
                entries.extend(src.chunk(index))
            dst.adopt(manifest, entries)
            catchup_s = time.perf_counter() - t0
            if dst.root != src.root:
                raise RuntimeError("adopted root diverged from source")
            out = {
                "apply_tx_s": round(src.applied_payloads / apply_s),
                "applied_blocks": src.applied_blocks,
                "applied_payloads": src.applied_payloads,
                "typed_ops": src.typed_ops,
                "sync_catchup_s": round(catchup_s, 4),
                "snapshot_entries": len(entries),
            }
            src_store.engine.close()
            dst.store.engine.close()
            return out
    except Exception as e:  # the bench must survive a broken state layer
        print(f"bench_state skipped: {e!r}", file=sys.stderr)
        return None


def bench_sim(seeds: int = 16, nodes: int = 4) -> dict | None:
    """Deterministic-simulator throughput probe (docs/SIM.md): a short
    seeded schedule sweep through ``hotstuff_tpu.sim.run_schedule`` —
    whole committee in one process, virtual time — measuring how fast
    this host chews through exploration seeds.  Feeds the
    ``sim.rounds_per_s`` (consensus rounds simulated per wall second)
    and ``sim.seeds_per_min`` perfgate guards; returns None (key
    omitted, guards skip) on any failure so the kernel benchmarks above
    still publish."""
    try:
        from hotstuff_tpu.sim import draw_schedule, run_schedule

        rounds = 0
        t0 = time.perf_counter()
        for seed in range(seeds):
            verdict = run_schedule(draw_schedule(seed, nodes=nodes))
            if not verdict.ok:
                raise RuntimeError(
                    f"seed {seed} failed: {verdict.failures}"
                )
            rounds += verdict.rounds
        dt = time.perf_counter() - t0
        return {
            "seeds": seeds,
            "nodes": nodes,
            "rounds": rounds,
            "rounds_per_s": round(rounds / dt, 1),
            "seeds_per_min": round(seeds * 60.0 / dt, 1),
        }
    except Exception as e:  # the bench must survive a broken sim plane
        print(f"bench_sim skipped: {e!r}", file=sys.stderr)
        return None


def bench_critpath(seed: int = 1, nodes: int = 4) -> dict | None:
    """Commit critical-path attribution document (docs/TELEMETRY.md)
    from ONE deterministic sim schedule: per-stage latency shares,
    regime classification and attribution coverage, reproducible per
    seed because the sim journals carry virtual clocks.  Feeds the
    ``critpath.p50_ms`` / ``critpath.coverage_pct`` perfgate guards and
    the attribution-SHAPE gate (a stage whose share of commit latency
    balloons fails perfgate / `benchmark critpath --diff` even when the
    scalar holds).  Returns None (key omitted, guards skip) on any
    failure so the kernel benchmarks above still publish."""
    try:
        from hotstuff_tpu.sim import draw_schedule, run_schedule

        verdict = run_schedule(draw_schedule(seed, nodes=nodes))
        if verdict.attribution is None:
            raise RuntimeError("sim run committed nothing to attribute")
        return verdict.attribution
    except Exception as e:  # the bench must survive a broken critpath
        print(f"bench_critpath skipped: {e!r}", file=sys.stderr)
        return None


def bench_net(seed: int = 1, nodes: int = 4) -> dict | None:
    """Wire-level flow accounting probe (ISSUE 19): one deterministic
    sim schedule with the flow accountant on, read back through
    ``SimVerdict.flows`` (per-node flow tables, byte-identical across
    same-seed runs).  Reports the median per-node propose-amplification
    factor — wire propose egress / logical propose bytes, exactly n-1
    when every proposal is one broadcast — and the committee's wire
    egress per committed block.  Feeds the ``net.leader_amp_p50`` and
    ``net.wire_bytes_per_commit`` perfgate guards; returns None (key
    omitted, guards skip) when accounting is disabled or the sim plane
    fails, so the kernel benchmarks above still publish."""
    try:
        from hotstuff_tpu.sim import draw_schedule, run_schedule

        verdict = run_schedule(draw_schedule(seed, nodes=nodes))
        if not verdict.flows:
            raise RuntimeError(
                "no flow tables (HOTSTUFF_NET=0 or nothing sent)"
            )
        tx_total = 0
        amps = []
        for tables in verdict.flows.values():
            propose_tx = 0
            propose_logical = 0
            for table in tables:
                for key, row in (table.get("flows") or {}).items():
                    _peer, d, cls = key.rsplit("|", 2)
                    if d == "tx":
                        tx_total += row[0]
                        if cls == "propose":
                            propose_tx += row[0]
                logical = (table.get("logical") or {}).get("propose")
                if logical:
                    propose_logical += logical[0]
            if propose_logical:
                amps.append(propose_tx / propose_logical)
        amps.sort()
        # verdict.commits counts per-node observations; every node
        # observes every committed block, so unique blocks ~ commits/n
        unique = max(1, round(verdict.commits / max(nodes, 1)))
        return {
            "seed": seed,
            "nodes": nodes,
            "tx_bytes": tx_total,
            "commits": unique,
            "leader_amp_p50": (
                round(amps[len(amps) // 2], 3) if amps else None
            ),
            "wire_bytes_per_commit": round(tx_total / unique),
        }
    except Exception as e:  # the bench must survive a broken net plane
        print(f"bench_net skipped: {e!r}", file=sys.stderr)
        return None


def bench_ingest(waves: int = 8, wave_size: int = 1024) -> dict | None:
    """Zero-copy ingest throughput probe (ISSUE 20): sustained wire ->
    arena -> device sigs/s.  Packs encoded vote frames through the
    native wave packer exactly as the reactor read path does, adopts
    each arena, and verifies through ``BatchVerifier.verify_packed``
    (frombuffer column views, no flatten/prepare copies), against the
    same waves through the Python ``flatten_claims`` path for the
    speedup.  Feeds the ``ingest.zero_copy_sigs_per_s`` perfgate guard;
    returns None (key omitted, guard skips) when the native toolchain
    is unavailable so the kernel benchmarks above still publish."""
    try:
        from hotstuff_tpu.consensus.messages import Vote
        from hotstuff_tpu.consensus.wire import encode_vote
        from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
        from hotstuff_tpu.crypto import native_ed25519
        from hotstuff_tpu.crypto.async_service import (
            ZeroCopyIngest,
            eval_claims_arena,
            eval_claims_sync,
        )
        from hotstuff_tpu.tpu.ed25519 import BatchVerifier

        if not native_ed25519.wave_pack_available():
            raise RuntimeError("native wave packer unavailable")

        pk, sk = generate_keypair(b"\x44" * 32, 0)
        frames, claims = [], []
        for i in range(wave_size):
            vote = Vote(
                hash=Digest.of(b"ingest bench block %d" % i),
                round=i + 1,
                author=pk,
            )
            vote.signature = Signature.new(vote.digest(), sk)
            frames.append(encode_vote(vote))
            claims.append(vote.claim())

        backend = BatchVerifier(min_device_batch=0)
        backend.precompute([pk.to_bytes()])
        ingest = ZeroCopyIngest(capacity=wave_size, ring_depth=3)
        buckets = (wave_size,)

        def one_wave() -> list:
            for f in frames:
                ingest.note_vote_frame(f)
            wave = ingest.try_adopt(claims, buckets)
            if wave is None:
                raise RuntimeError("arena adoption missed")
            return eval_claims_arena(backend, wave, claims)

        if one_wave().count(True) != wave_size:  # warmup + compile
            raise RuntimeError("zero-copy wave returned bad verdicts")
        t0 = time.perf_counter()
        for _ in range(waves):
            one_wave()
        zc_s = time.perf_counter() - t0

        assert eval_claims_sync(backend, claims).count(True) == wave_size
        t0 = time.perf_counter()
        for _ in range(waves):
            eval_claims_sync(backend, claims)
        flat_s = time.perf_counter() - t0

        sigs = waves * wave_size
        return {
            "wave_size": wave_size,
            "waves": waves,
            "zero_copy_sigs_per_s": round(sigs / zc_s),
            "flatten_sigs_per_s": round(sigs / flat_s),
            "zero_copy_speedup": round(flat_s / zc_s, 3),
        }
    except Exception as e:  # the bench must survive a missing toolchain
        print(f"bench_ingest skipped: {e!r}", file=sys.stderr)
        return None


def bench_adapt(schedules: int = 6, nodes: int = 4) -> dict | None:
    """Adaptive-adversary search throughput probe (docs/FAULTS.md): a
    short sweep of adaptive-profile schedules — state-reactive byz
    policies live at the consensus seams — measuring how fast this host
    chews through guided-search candidates (``adapt.schedules_per_min``)
    and how fast the selection loop scores verdicts
    (``adapt.fitness_evals_per_s``; pure-Python fitness over the
    verdict, so it bounds the non-simulation overhead of a generation).
    Feeds the matching perfgate guards; returns None (key omitted,
    guards skip) on any failure so the kernel benchmarks above still
    publish."""
    try:
        from hotstuff_tpu.sim import draw_schedule, fitness, run_schedule

        verdicts = []
        t0 = time.perf_counter()
        for seed in range(schedules):
            verdicts.append(
                run_schedule(
                    draw_schedule(seed, nodes=nodes, profile="adaptive")
                )
            )
        sched_s = time.perf_counter() - t0

        evals = 2000
        t0 = time.perf_counter()
        for k in range(evals):
            fitness(verdicts[k % len(verdicts)])
        fit_s = time.perf_counter() - t0
        return {
            "schedules": schedules,
            "nodes": nodes,
            "threats": sum(1 for v in verdicts if v.threats),
            "schedules_per_min": round(schedules * 60.0 / sched_s, 1),
            "fitness_evals_per_s": round(evals / fit_s),
        }
    except Exception as e:  # the bench must survive a broken adapt plane
        print(f"bench_adapt skipped: {e!r}", file=sys.stderr)
        return None


def probe_tunnel(inflight: int = 16, reps: int = 7) -> dict:
    """Tunnel weather, two views over the same tiny resident-arg jit
    call, pinned in the output so end-to-end swings between rounds are
    attributable to the development tunnel:

    - ``tunnel_rtt_p50_ms``: median blocking dispatch + fetch — the
      round trip a fully serialized caller pays per crossing;
    - ``tunnel_dispatch_p50_ms``: median amortized per-dispatch cost of
      an ``inflight``-deep pipelined stream (one wall clock over
      ``inflight`` concurrent dispatches, synced by a fetch of the last
      result) — the per-crossing cost the production dispatch loop pays,
      since it keeps the tunnel full instead of serializing on it."""
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        return (x * 2 + 1).sum()

    x = jax.device_put(np.ones((128, 20), np.int32))
    np.asarray(f(x))
    rtt = []
    for _ in range(9):
        t0 = time.perf_counter()
        np.asarray(f(x))
        rtt.append(time.perf_counter() - t0)
    rtt.sort()
    amortized = []
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [f(x) for _ in range(inflight)]
        jax.block_until_ready(outs)
        np.asarray(outs[-1])
        amortized.append((time.perf_counter() - t0) / inflight)
    amortized.sort()
    return {
        "tunnel_rtt_p50_ms": round(rtt[len(rtt) // 2] * 1e3, 2),
        "tunnel_dispatch_p50_ms": round(
            amortized[len(amortized) // 2] * 1e3, 3
        ),
        "tunnel_inflight": inflight,
    }


def main() -> int:
    import jax

    msgs, pks, sigs = make_qc_batch(BATCH)
    platform = jax.devices()[0].platform

    tpu_tput, qc_latency, device_tput = bench_tpu(msgs, pks, sigs)
    cpu_tput, cpu_provenance = bench_cpu(msgs, pks, sigs)

    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    tc_latency = bench_tc(BatchVerifier(min_device_batch=0))
    sharded = bench_sharded(msgs, pks, sigs)
    if platform == "cpu" and sharded.get("mesh_devices", 0) <= 1:
        # CPU hosts see ONE XLA device unless the count is forced before
        # jax loads — re-measure the sharded route in a child on the
        # virtual 8-device mesh so this block stops reporting
        # mesh_devices: 1 (ISSUE 7 satellite); keep the in-process
        # number if the child fails
        from benchmark.meshtrain import run_sharded_virtual

        virtual = run_sharded_virtual()
        if virtual is not None:
            sharded = virtual

    # multi-chip wave-train scaling (ISSUE 7): per-mesh-size sustained
    # train sigs/s through the production dispatch pipeline, batches up
    # to 4096, on the virtual CPU mesh when no real multi-chip is present
    from benchmark.meshtrain import run_mesh_train

    mesh_train = run_mesh_train(force_virtual=(platform == "cpu"))

    # production-path amortized per-wave latency merged into the per-size
    # QC entries next to the serialized blocking_* and device_ms views
    for size, piped in bench_qc_pipelined().items():
        qc_latency.setdefault(size, {}).update(piped)

    # end-to-end payload-plane goodput through a live committee; the
    # key is omitted when the committee can't run here so the perfgate
    # load guards skip instead of failing the kernel bench
    load = bench_load()

    # replicated execution-layer apply/snapshot costs; key omitted on
    # failure so the perfgate state guards skip instead of failing
    state = bench_state()

    # deterministic-simulator sweep throughput; key omitted on failure
    # so the perfgate sim guards skip instead of failing
    sim = bench_sim()

    # commit critical-path attribution shape from one deterministic sim
    # seed; key omitted on failure so the critpath guards skip
    critpath = bench_critpath()

    # adaptive-adversary guided-search throughput; key omitted on
    # failure so the perfgate adapt guards skip instead of failing
    adapt = bench_adapt()

    # wire-level flow accounting rollup (propose amplification + wire
    # bytes per commit); key omitted on failure or with HOTSTUFF_NET=0
    # so the perfgate net guards skip instead of failing
    net = bench_net()

    # zero-copy ingest throughput (wire -> arena -> device); key omitted
    # without the native toolchain so the perfgate ingest guard skips
    ingest = bench_ingest()

    print(
        json.dumps(
            {
                "metric": f"ed25519_verify_throughput_{platform}_batch{BATCH}",
                "value": round(tpu_tput),
                "unit": "sigs/s",
                "vs_baseline": round(tpu_tput / cpu_tput, 3),
                "baseline": cpu_provenance,
                **probe_tunnel(),
                "device_throughput": device_tput,
                "qc_verify_ms": qc_latency,
                "tc_verify_ms": tc_latency,
                "sharded_route": sharded,
                "mesh_train": mesh_train,
                "verify_split": bench_verify_split(msgs, pks, sigs),
                "pipeline": bench_pipeline(),
                "agg_qc": bench_agg_qc(),
                **({"load": load} if load is not None else {}),
                **({"state": state} if state is not None else {}),
                **({"sim": sim} if sim is not None else {}),
                **({"critpath": critpath} if critpath is not None else {}),
                **({"adapt": adapt} if adapt is not None else {}),
                **({"net": net} if net is not None else {}),
                **({"ingest": ingest} if ingest is not None else {}),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
