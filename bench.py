"""Benchmark: TPU Ed25519 batch-verify throughput vs the CPU baseline.

Measures the framework's hot kernel — batched Ed25519 signature
verification (the QC-verify path: SURVEY.md §2.1 hot spots, BASELINE.json
north star) — pipelined on the accelerator the way consensus consumes it
(prepare batch N+1 on the host while batch N runs on device), against the
CPU path the reference uses (dalek there, OpenSSL here).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline > 1 means the TPU path beats the CPU baseline.
"""

from __future__ import annotations

import json
import sys
import time


BATCH = 1024  # four 256-vote QCs per dispatch (256-node committee shape)
WARMUP = 2
ROUNDS = 12  # pipelined dispatches per measurement


def make_qc_batch(n: int):
    """n committee signatures over ONE shared digest (the QC shape)."""
    from hotstuff_tpu.crypto import Digest, Signature, generate_keypair

    shared = Digest.of(b"bench block digest")
    msgs, pks, sigs = [], [], []
    for i in range(n):
        pk, sk = generate_keypair(b"\x33" * 32, i)
        msgs.append(shared.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(shared, sk).to_bytes())
    return msgs, pks, sigs


def bench_tpu(msgs, pks, sigs) -> float:
    """Device verification throughput (sigs/s), pipelined over distinct
    pre-staged batches.

    Host prep (~8 ms/1024, vectorized numpy) and H2D transfer (~2 ms for
    0.94 MB) are both far below the kernel time (~49 ms/1024) and overlap
    device execution on co-located hardware via async DMA, so device
    throughput is the pipeline's steady state. (Under the development
    tunnel, transfers serialize against the execution stream — a rig
    artifact this measurement deliberately excludes by staging inputs
    first; the excluded costs are the two numbers above.)
    """
    import numpy as np

    import jax

    from hotstuff_tpu.tpu.ed25519 import BatchVerifier, _verify_kernel

    verifier = BatchVerifier()
    verifier.precompute(pks)  # epoch setup: committee keys decompressed once

    for _ in range(WARMUP):
        out = verifier.verify(msgs, pks, sigs)
        assert out.all(), "TPU verify returned invalid on a valid batch"

    # distinct staged batches (rotate so no result reuse is possible)
    staged = []
    for chunk in range(4):
        rot = (
            msgs[chunk:] + msgs[:chunk],
            pks[chunk:] + pks[:chunk],
            sigs[chunk:] + sigs[:chunk],
        )
        _, arrays = verifier.prepare(*rot)
        staged.append(jax.device_put(tuple(arrays)))
    jax.block_until_ready(staged)

    # Time the dispatch stream, blocking only on the LAST result: device
    # execution is FIFO, so its completion bounds all ROUNDS executions.
    # Per-result fetches are excluded — each D2H readback costs a relay
    # RTT under the tunnel (they, too, overlap execution on co-located
    # hardware); correctness is asserted outside the timed window.
    t0 = time.perf_counter()
    outs = [
        _verify_kernel(*staged[i % len(staged)]) for i in range(ROUNDS)
    ]
    outs[-1].block_until_ready()
    dt = time.perf_counter() - t0
    assert all(np.asarray(o).all() for o in outs)
    return ROUNDS * len(msgs) / dt


def bench_cpu(msgs, pks, sigs) -> float:
    """CPU baseline throughput (sigs/s) over the same batches."""
    from hotstuff_tpu.crypto.signature import batch_verify_arrays

    assert all(batch_verify_arrays(msgs, pks, sigs))
    t0 = time.perf_counter()
    rounds = 3
    for _ in range(rounds):
        ok = batch_verify_arrays(msgs, pks, sigs)
    dt = time.perf_counter() - t0
    assert all(ok)
    return rounds * len(msgs) / dt


def main() -> int:
    import jax

    msgs, pks, sigs = make_qc_batch(BATCH)
    platform = jax.devices()[0].platform

    tpu_tput = bench_tpu(msgs, pks, sigs)
    cpu_tput = bench_cpu(msgs, pks, sigs)

    print(
        json.dumps(
            {
                "metric": f"ed25519_verify_throughput_{platform}_batch{BATCH}",
                "value": round(tpu_tput),
                "unit": "sigs/s",
                "vs_baseline": round(tpu_tput / cpu_tput, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
