"""TPU BLS12-381 G1 aggregation vs the pure-Python oracle
(crypto/bls/curve.py), incl. the adversarial edge cases the branchless
point addition must handle (equal points, opposite points, identity)."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from hotstuff_tpu.crypto.bls import (
    BlsSecretKey,
    aggregate_signatures,
)
from hotstuff_tpu.crypto.bls.curve import G1Point
from hotstuff_tpu.crypto.bls.fields import P as Q
from hotstuff_tpu.tpu import bls as T

rng = random.Random(4242)


def rand_fq() -> int:
    return rng.randrange(Q)


def rand_point() -> G1Point:
    return G1Point.generator().mul(rng.randrange(1, 2**64))


def to_dev(x: int):
    return jnp.asarray(T.to_mont_limbs(x))[None, :]


def test_mont_roundtrip_and_mul():
    for _ in range(10):
        a, b = rand_fq(), rand_fq()
        assert T.from_mont_int(T.to_mont_limbs(a)) == a
        out = T.mont_mul(to_dev(a), to_dev(b))
        assert T.from_mont_int(np.asarray(out)[0]) == a * b % Q


def test_mont_mul_edge_values():
    cases = [(0, 0), (0, 1), (1, 1), (Q - 1, Q - 1), (Q - 1, 1), (2, Q - 2)]
    a = jnp.stack([jnp.asarray(T.to_mont_limbs(x)) for x, _ in cases])
    b = jnp.stack([jnp.asarray(T.to_mont_limbs(y)) for _, y in cases])
    out = np.asarray(T.mont_mul(a, b))
    for i, (x, y) in enumerate(cases):
        assert T.from_mont_int(out[i]) == x * y % Q, (x, y)


def test_mont_add_sub():
    for _ in range(10):
        a, b = rand_fq(), rand_fq()
        s = np.asarray(T.madd(to_dev(a), to_dev(b)))[0]
        d = np.asarray(T.msub(to_dev(a), to_dev(b)))[0]
        # Montgomery form is linear, so add/sub stay in-form
        assert T.from_mont_int(s) == (a + b) % Q
        assert T.from_mont_int(d) == (a - b) % Q


def _dev_point(pt: G1Point):
    if pt.inf:
        one = to_dev(1)
        return (jnp.zeros_like(one), one, jnp.zeros_like(one))
    return (to_dev(pt.x), to_dev(pt.y), to_dev(1))


def _read_point(p) -> G1Point:
    x, y, z = (np.asarray(c)[0] for c in p)
    return T.TpuG1Aggregator._projective_to_affine(x, y, z)


@pytest.mark.parametrize(
    "case",
    ["distinct", "equal", "opposite", "p_inf", "q_inf", "both_inf"],
)
def test_point_add_unified(case):
    p = rand_point()
    if case == "distinct":
        q = rand_point()
    elif case == "equal":
        q = p
    elif case == "opposite":
        q = -p
    elif case == "p_inf":
        q, p = p, G1Point.identity()
    elif case == "q_inf":
        q = G1Point.identity()
    else:
        p = q = G1Point.identity()
    want = p + q
    got = _read_point(T.point_add(_dev_point(p), _dev_point(q)))
    assert got == want, case


def test_point_add_doubles():
    for _ in range(3):
        p = rand_point()
        got = _read_point(T.point_add(_dev_point(p), _dev_point(p)))
        assert got == p + p


def test_aggregate_matches_cpu_backend():
    """Device tree-reduce == CPU aggregate_signatures on real vote sets,
    including duplicate signatures (adversarial re-submission)."""
    agg = T.TpuG1Aggregator()
    digest = b"\x07" * 32
    sks = [BlsSecretKey(100 + i) for i in range(7)]
    sigs = [sk.sign(digest) for sk in sks]
    sigs.append(sigs[0])  # duplicate
    want = aggregate_signatures(sigs).point
    got = agg.aggregate([s.point for s in sigs])
    assert got == want


def test_aggregate_identity_and_empty():
    agg = T.TpuG1Aggregator()
    assert agg.aggregate([]) == G1Point.identity()
    assert agg.aggregate([G1Point.identity()]) == G1Point.identity()
    p = rand_point()
    assert agg.aggregate([p, G1Point.identity()]) == p


def test_bls_verifier_tpu_aggregation_end_to_end():
    """QC verify through BlsVerifier(aggregator='tpu') agrees with the
    CPU backend on valid and tampered vote sets."""
    from hotstuff_tpu.crypto.bls.service import BlsVerifier

    digest = b"\x21" * 32
    sks = [BlsSecretKey(7 + i) for i in range(4)]
    votes = [
        (sk.public_key().to_bytes(), sk.sign(digest).to_bytes())
        for sk in sks
    ]
    cpu, tpu = BlsVerifier(), BlsVerifier(aggregator="tpu")
    assert tpu.verify_shared_msg(digest, votes)
    assert cpu.verify_shared_msg(digest, votes)
    # tamper one signature: both backends must reject
    bad = votes[:2] + [(votes[2][0], votes[3][1])] + votes[3:]
    assert not tpu.verify_shared_msg(digest, bad)
    assert not cpu.verify_shared_msg(digest, bad)


def test_aggregate_deep_tree_stress():
    """40 points -> 64-pad, 6 tree levels of loose-on-loose additions:
    regression for the CIOS overflow-column fold (carry residue parked
    above limb 29 was silently dropped, shifting the value by k*R —
    only surfaced at tree depth >= 3 with particular carry patterns)."""
    agg = T.TpuG1Aggregator()
    pts = [rand_point() for _ in range(40)]
    want = pts[0]
    for p in pts[1:]:
        want = want + p
    assert agg.aggregate(pts) == want


def test_sharded_aggregate_matches_cpu_backend():
    """Cross-device G1 aggregation (design doc step 4): batch sharded
    over the 8-device CPU mesh, per-device tree reduce, all_gather of
    the partial points, replicated final tree — equals the CPU
    aggregate on random vote sets, pads included."""
    from hotstuff_tpu.crypto.bls import aggregate_signatures, BlsSignature, keygen
    from hotstuff_tpu.parallel.mesh import default_mesh
    from hotstuff_tpu.tpu.bls import TpuG1Aggregator

    mesh = default_mesh()
    assert mesh.devices.size == 8  # conftest forces the 8-device CPU mesh
    agg = TpuG1Aggregator(mesh=mesh)

    msg = b"sharded aggregate digest"
    pairs = [keygen(bytes([60 + i])) for i in range(11)]  # odd count -> pads
    sigs = [sk.sign(msg) for _, sk in pairs]
    want = aggregate_signatures(sigs).point

    got = agg.aggregate([s.point for s in sigs])
    assert got == want
    # degenerate shapes
    assert agg.aggregate([]).inf
    one = sigs[0].point
    assert agg.aggregate([one]) == one


def test_sharded_bls_verifier_end_to_end():
    """BlsVerifier(aggregator='tpu-sharded') — the product plug point —
    verifies a valid shared-message vote set and rejects a forgery."""
    from hotstuff_tpu.crypto.bls import keygen
    from hotstuff_tpu.crypto.bls.service import BlsVerifier

    v = BlsVerifier(aggregator="tpu-sharded")
    assert v.name == "bls-tpu-sharded"
    msg = b"sharded verifier digest"
    pairs = [keygen(bytes([80 + i])) for i in range(5)]
    votes = [(pk.to_bytes(), sk.sign(msg).to_bytes()) for pk, sk in pairs]
    assert v.verify_shared_msg(msg, votes)
    forged = votes[:4] + [(votes[4][0], votes[0][1])]
    assert not v.verify_shared_msg(msg, forged)


def test_scalar_mult_ladder_matches_oracle():
    """The batched variable-base ladder (TpuG1ScalarMul) against the
    Python oracle, including chain depths past the ~40-add magnitude
    drift the per-iteration freshen exists for (a 48-bit ladder runs 96
    sequential point adds)."""
    from hotstuff_tpu.crypto.bls.curve import G1Point
    from hotstuff_tpu.tpu.bls import TpuG1ScalarMul

    g = G1Point.generator()
    g2 = g + g
    m = TpuG1ScalarMul(nbits=48)
    ks = [5, (1 << 40) + 1, (1 << 47) + (1 << 23) + 9, 0]
    pts = [g, g, g2, g]
    out = m.mul(ks, pts)
    for k, p, r in zip(ks, pts, out):
        want = p._mul_raw(k)
        assert r == want or (r.inf and want.inf)


def test_native_offload_split_apis():
    """The host ends of the storm offload: hash_base_many gives the
    PRE-cofactor map (base * h_eff == hash_to_g1), g1_decompress_many
    round-trips signatures, and verify_batch_points accepts the pairing
    product over correctly weighted points and rejects a corruption."""
    import secrets

    pytest.importorskip("hotstuff_tpu.crypto.bls.native")
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.crypto.bls import keygen as bls_keygen, native
    from hotstuff_tpu.crypto.bls.curve import H1, G1Point, hash_to_g1
    from hotstuff_tpu.crypto.bls.service import BlsSigningService

    n = 6
    db, pb, sb = [], [], []
    for i in range(n):
        pk, sk = bls_keygen(bytes([77, i]) + b"\x00" * 30)
        svc = BlsSigningService(sk)
        d = Digest.of(bytes([i]) * 7)
        db.append(d.to_bytes())
        pb.append(pk.to_bytes())
        sb.append(svc.sign_sync(d).to_bytes())

    def parse(raw, count):
        return [
            G1Point(
                int.from_bytes(raw[96 * i : 96 * i + 48], "big"),
                int.from_bytes(raw[96 * i + 48 : 96 * i + 96], "big"),
            )
            for i in range(count)
        ]

    bases = parse(native.hash_base_many(db), n)
    for d, base in zip(db, bases):
        assert base._mul_raw(H1) == hash_to_g1(d)
    sigs = parse(native.g1_decompress_many(sb), n)

    ws = [secrets.randbits(128) | 1 for _ in range(n)]
    whm = [bases[i]._mul_raw(ws[i] * H1) for i in range(n)]
    agg = G1Point.identity()
    for i in range(n):
        agg = agg + sigs[i]._mul_raw(ws[i])

    def ser(pt):
        return (
            bytes(96)
            if pt.inf
            else pt.x.to_bytes(48, "big") + pt.y.to_bytes(48, "big")
        )

    whm_bytes = b"".join(ser(p) for p in whm)
    assert native.verify_batch_points(whm_bytes, pb, ser(agg))
    # corrupt one weighted-hash point: product must fail
    bad = bytearray(whm_bytes)
    bad[50] ^= 1
    assert not native.verify_batch_points(bytes(bad), pb, ser(agg))
