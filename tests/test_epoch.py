"""Epoch reconfiguration: committee handoff across a round boundary.

BEYOND reference parity (the reference has no reconfiguration at all,
SURVEY.md §2.7): a ``CommitteeSchedule`` maps round ranges to
committees; every verification/election call site routes through
``for_round``, so certificates formed under one epoch verify under that
epoch's validator set forever, and leaders rotate into the new set at
the boundary.  The e2e test rotates one member out (and a new one in)
without losing liveness.
"""

import asyncio

import pytest

from hotstuff_tpu.consensus import (
    Committee,
    CommitteeSchedule,
    Consensus,
    Parameters,
)
from hotstuff_tpu.consensus.config import InvalidCommittee
from hotstuff_tpu.consensus.leader import LeaderElector
from hotstuff_tpu.crypto import Digest, SignatureService, generate_keypair
from hotstuff_tpu.crypto.service import CpuVerifier
from hotstuff_tpu.node.config import read_committee, write_committee
from hotstuff_tpu.store import Store

from .common import SEED, async_test, fresh_base_port, signed_block

SWITCH_ROUND = 8


def five_keys():
    pairs = [generate_keypair(SEED, i) for i in range(5)]
    pairs.sort(key=lambda kp: kp[0])
    return pairs


def make_schedule(base_port):
    """Epoch 1 (rounds 1..SWITCH_ROUND-1): members 0-3; epoch 2
    (rounds >= SWITCH_ROUND): member 3 rotates out, member 4 in."""
    ks = five_keys()
    addr = lambda i: ("127.0.0.1", base_port + i)  # noqa: E731
    epoch1 = Committee.new(
        [(ks[i][0], 1, addr(i)) for i in range(4)], epoch=1
    )
    epoch2 = Committee.new(
        [(ks[i][0], 1, addr(i)) for i in (0, 1, 2, 4)], epoch=2
    )
    return CommitteeSchedule([(1, epoch1), (SWITCH_ROUND, epoch2)]), ks


def test_schedule_for_round_and_validation(tmp_path):
    schedule, ks = make_schedule(9_200)
    epoch1 = schedule.entries[0][1]
    epoch2 = schedule.entries[1][1]
    assert schedule.for_round(1) is epoch1
    assert schedule.for_round(SWITCH_ROUND - 1) is epoch1
    assert schedule.for_round(SWITCH_ROUND) is epoch2
    assert schedule.for_round(10_000) is epoch2
    # a bare Committee is its own one-epoch schedule
    assert epoch1.for_round(123) is epoch1

    with pytest.raises(InvalidCommittee):
        CommitteeSchedule([])
    with pytest.raises(InvalidCommittee):
        CommitteeSchedule([(5, epoch1)])  # round 1 uncovered
    with pytest.raises(InvalidCommittee):
        CommitteeSchedule([(1, epoch1), (1, epoch2)])  # duplicate

    # JSON round-trip through the node config files
    path = str(tmp_path / "committee.json")
    write_committee(schedule, path)
    again = read_committee(path)
    assert isinstance(again, CommitteeSchedule)
    assert [f for f, _ in again.entries] == [1, SWITCH_ROUND]
    assert again.for_round(1).sorted_keys() == epoch1.sorted_keys()
    assert again.for_round(SWITCH_ROUND).sorted_keys() == epoch2.sorted_keys()
    # plain committee files still load as Committee
    write_committee(epoch1, path)
    assert isinstance(read_committee(path), Committee)


def test_schedule_union_views():
    schedule, ks = make_schedule(9_210)
    # union membership: all five keys
    assert len(schedule.authorities) == 5
    # departing member's address still resolvable (sync/catch-up)
    assert schedule.address(ks[3][0]) == ("127.0.0.1", 9_213)
    assert schedule.address(ks[4][0]) == ("127.0.0.1", 9_214)
    # broadcast union excludes self, includes both epochs' members
    names = {n for n, _ in schedule.broadcast_addresses(ks[0][0])}
    assert names == {ks[i][0] for i in (1, 2, 3, 4)}
    assert schedule.scheme == "ed25519"
    assert schedule.wire_scheme() == "ed25519"


def test_leader_rotation_at_boundary():
    schedule, ks = make_schedule(9_220)
    elector = LeaderElector(schedule)
    epoch1_keys = schedule.for_round(1).sorted_keys()
    epoch2_keys = schedule.for_round(SWITCH_ROUND).sorted_keys()
    for r in range(1, SWITCH_ROUND):
        assert elector.get_leader(r) == epoch1_keys[r % 4]
    for r in range(SWITCH_ROUND, SWITCH_ROUND + 8):
        assert elector.get_leader(r) == epoch2_keys[r % 4]
    # the departing member leads no round past the boundary
    assert ks[3][0] not in {
        elector.get_leader(r)
        for r in range(SWITCH_ROUND, SWITCH_ROUND + 100)
    }


def test_cross_epoch_certificate_verification():
    """A QC formed by epoch-1 validators must verify under the schedule
    at ITS round forever — and must NOT verify as an epoch-2-round
    certificate when the signer set changed."""
    from hotstuff_tpu.consensus import QC, UnknownAuthority, Vote
    from hotstuff_tpu.crypto import Signature

    schedule, ks = make_schedule(9_230)
    verifier = CpuVerifier()
    epoch1 = schedule.for_round(1)

    author = ks[1][0]
    block = signed_block(author, ks[1][1], round_=3)
    # 3-of-4 epoch-1 quorum INCLUDING the departing member 3
    vote_digest = Vote.for_block(block, ks[0][0]).digest()
    qc = QC(
        hash=block.digest(),
        round=block.round,
        votes=[
            (pk, Signature.new(vote_digest, sk)) for pk, sk in ks[1:4]
        ],
    )
    # verifies under the schedule (routed to epoch 1)
    qc.verify(schedule, verifier)
    # the same vote set claimed for an epoch-2 round must fail: member 3
    # is not an epoch-2 authority
    forged = QC(hash=qc.hash, round=SWITCH_ROUND + 3, votes=qc.votes)
    with pytest.raises(UnknownAuthority):
        forged.verify(schedule, verifier)
    # sanity: direct epoch-1 verification agrees
    qc.verify(epoch1, verifier)


@async_test
async def test_epoch_handoff_e2e(tmp_path):
    """Five nodes share a schedule rotating member 3 out / member 4 in at
    SWITCH_ROUND.  The committee must keep committing across the
    boundary (liveness), the new member must commit the same chain, and
    post-boundary blocks must only be authored by epoch-2 members."""
    base = fresh_base_port()
    schedule, ks = make_schedule(base)

    nodes = []
    for i in range(5):
        name, secret = ks[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            schedule,
            Parameters(timeout_delay=1_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
        )
        nodes.append((stack, commit_q, store))

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.02)

    feeder = asyncio.ensure_future(feed())
    try:
        # collect commits on an always-member (0) and the NEW member (4)
        # until both are well past the boundary
        chains = {0: [], 4: []}
        for idx in (0, 4):
            commit_q = nodes[idx][1]
            while not chains[idx] or chains[idx][-1].round < SWITCH_ROUND + 6:
                block = await asyncio.wait_for(commit_q.get(), timeout=30.0)
                chains[idx].append(block)

        for idx, chain_blocks in chains.items():
            rounds = [b.round for b in chain_blocks]
            assert rounds == sorted(rounds), f"node {idx} rounds {rounds}"
            # liveness across the boundary: commits on both sides
            assert any(r < SWITCH_ROUND for r in rounds)
            assert any(r >= SWITCH_ROUND for r in rounds)
            epoch2_members = set(
                schedule.for_round(SWITCH_ROUND).authorities
            )
            for b in chain_blocks:
                if b.round >= SWITCH_ROUND:
                    assert b.author in epoch2_members
                    assert b.author != ks[3][0]

        # consistency: same digests at the same rounds on both nodes
        by_round_0 = {b.round: b.digest() for b in chains[0]}
        by_round_4 = {b.round: b.digest() for b in chains[4]}
        shared = set(by_round_0) & set(by_round_4)
        assert shared, "no common committed rounds"
        for r in shared:
            assert by_round_0[r] == by_round_4[r]
    finally:
        feeder.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()


@async_test
async def test_scheme_changeover_e2e(tmp_path):
    """SCHEME changeover at an epoch boundary: epoch 1 is a 4-member
    ed25519 committee, epoch 2 a 4-member BLS committee (identities are
    per-scheme, so every epoch-2 member is a fresh BLS keypair — the
    operational model for a changeover).  All eight stacks share the
    schedule and the dual-scheme verifier; commits must continue across
    the boundary, and the BLS members must commit the ed25519-era chain
    prefix too (old-epoch certificates keep verifying under their own
    scheme)."""
    from hotstuff_tpu.crypto.scheme import (
        bls_keygen,
        bls_pop,
        make_dual_verifier,
        make_cpu_verifier,
        make_signing_service,
    )
    from hotstuff_tpu.crypto.bls.service import BlsSigningService  # noqa: F401

    base = fresh_base_port()
    switch = 6
    ed = five_keys()[:4]
    bls_pairs = [bls_keygen(b"\x21" * 32, i) for i in range(4)]

    epoch1 = Committee.new(
        [(pk, 1, ("127.0.0.1", base + i)) for i, (pk, _) in enumerate(ed)],
        epoch=1,
    )
    epoch2 = Committee.new(
        [
            (pk, 1, ("127.0.0.1", base + 4 + i))
            for i, (pk, _) in enumerate(bls_pairs)
        ],
        epoch=2,
        scheme="bls",
        pops={pk: bls_pop(secret) for pk, secret in bls_pairs},
    )
    schedule = CommitteeSchedule([(1, epoch1), (switch, epoch2)])
    assert schedule.wire_scheme() is None  # mixed: wire accepts union

    async def spawn(name, service, store_dir):
        store = Store(str(tmp_path / store_dir))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            schedule,
            Parameters(timeout_delay=2_000, sync_retry_delay=5_000),
            service,
            store,
            commit_q,
            verifier=make_dual_verifier(make_cpu_verifier),
            bind_host="127.0.0.1",
        )
        return stack, commit_q, store

    nodes = []
    for i, (pk, sk) in enumerate(ed):
        nodes.append(
            await spawn(pk, make_signing_service("ed25519", sk), f"ed_{i}")
        )
    for i, (pk, secret) in enumerate(bls_pairs):
        from hotstuff_tpu.crypto.keys import WipeableSecret

        class _S(WipeableSecret):
            SIZE = None

        nodes.append(
            await spawn(
                pk, make_signing_service("bls", _S(secret)), f"bls_{i}"
            )
        )

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.02)

    feeder = asyncio.ensure_future(feed())
    try:
        # an epoch-1 member and an epoch-2 (BLS) member must both commit
        # past the boundary
        chains = {0: [], 5: []}
        for idx in chains:
            commit_q = nodes[idx][1]
            while not chains[idx] or chains[idx][-1].round < switch + 4:
                block = await asyncio.wait_for(commit_q.get(), timeout=45.0)
                chains[idx].append(block)
        epoch2_members = set(epoch2.authorities)
        for idx, chain_blocks in chains.items():
            rounds = [b.round for b in chain_blocks]
            assert rounds == sorted(rounds)
            assert any(r < switch for r in rounds)
            for b in chain_blocks:
                if b.round >= switch:
                    assert b.author in epoch2_members
        # consistency across schemes: identical digests per round
        by0 = {b.round: b.digest() for b in chains[0]}
        by5 = {b.round: b.digest() for b in chains[5]}
        for r in set(by0) & set(by5):
            assert by0[r] == by5[r]
    finally:
        feeder.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()


def test_departed_member_block_rejected_post_boundary():
    """A block authored by the rotated-out member for a post-boundary
    round must be rejected — by leader election (it never leads epoch-2
    rounds) and by verification (no epoch-2 stake)."""
    from hotstuff_tpu.consensus import UnknownAuthority

    schedule, ks = make_schedule(9_240)
    verifier = CpuVerifier()
    elector = LeaderElector(schedule)
    departed_pk, departed_sk = ks[3]

    forged = signed_block(departed_pk, departed_sk, round_=SWITCH_ROUND + 2)
    # never elected past the boundary
    assert elector.get_leader(forged.round) != departed_pk
    # and carries no stake under the round's committee
    with pytest.raises(UnknownAuthority):
        forged.verify(schedule, verifier)
    # the same author's PRE-boundary block still verifies (round routed
    # to epoch 1)
    ok_block = signed_block(departed_pk, departed_sk, round_=3)
    ok_block.verify(schedule, verifier)
