"""Async coalescing verification service + burst preverification tests
(VERDICT r3 item 1: QC/TC verification off the consensus critical path).
"""

import asyncio

from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
from hotstuff_tpu.crypto.async_service import (
    AsyncVerifyService,
    eval_claims_sync,
    flatten_claims,
)
from hotstuff_tpu.crypto.service import CpuVerifier

from .common import async_test


def _signed(seed: int, msg: bytes):
    """(pk, signature over the 32-byte msg treated as a digest)."""
    pk, sk = generate_keypair(bytes([seed]) * 32, 0)
    return pk, Signature.new(Digest(msg), sk)


def test_flatten_claims_spans():
    d1, d2 = b"\x01" * 32, b"\x02" * 32
    claims = [
        ("one", d1, b"pk1", b"s1"),
        ("shared", d2, ((b"pk2", b"s2"), (b"pk3", b"s3"))),
        ("one", d1, b"pk4", b"s4"),
    ]
    digests, pks, sigs, spans = flatten_claims(claims)
    assert digests == [d1, d2, d2, d1]
    assert pks == [b"pk1", b"pk2", b"pk3", b"pk4"]
    assert spans == [(0, 1), (1, 3), (3, 4)]


def test_eval_claims_mixed_validity():
    msg = b"m" * 32
    pk1, sig1 = _signed(1, msg)
    pk2, sig2 = _signed(2, msg)
    pk3, sig3 = _signed(3, msg)
    good_shared = (
        "shared",
        msg,
        (
            (pk1.to_bytes(), sig1.to_bytes()),
            (pk2.to_bytes(), sig2.to_bytes()),
        ),
    )
    bad_shared = (
        "shared",
        msg,
        (
            (pk1.to_bytes(), sig1.to_bytes()),
            (pk2.to_bytes(), sig1.to_bytes()),  # wrong sig for pk2
        ),
    )
    good_one = ("one", msg, pk3.to_bytes(), sig3.to_bytes())
    bad_one = ("one", msg, pk3.to_bytes(), sig1.to_bytes())
    out = eval_claims_sync(
        CpuVerifier(), [good_shared, bad_shared, good_one, bad_one]
    )
    assert out == [True, False, True, False]


def test_eval_claims_aggregate_preferring_backend():
    """prefers_aggregate backends see shared claims via verify_shared_msg
    (the BLS one-pairing path), singles via verify_many."""

    class Agg(CpuVerifier):
        prefers_aggregate = True
        shared_calls = 0
        many_calls = 0

        def verify_shared_msg(self, d, votes):
            Agg.shared_calls += 1
            return super().verify_shared_msg(d, votes)

        def verify_many(self, d, p, s, aggregate_ok=False):
            Agg.many_calls += 1
            return super().verify_many(d, p, s)

    msg = b"n" * 32
    pk1, sig1 = _signed(4, msg)
    pk2, sig2 = _signed(5, msg)
    claims = [
        ("shared", msg, ((pk1.to_bytes(), sig1.to_bytes()),
                         (pk2.to_bytes(), sig2.to_bytes()))),
        ("one", msg, pk1.to_bytes(), sig1.to_bytes()),
        ("one", msg, pk2.to_bytes(), sig1.to_bytes()),  # invalid
    ]
    out = eval_claims_sync(Agg(), claims)
    assert out == [True, True, False]
    assert Agg.shared_calls == 1
    assert Agg.many_calls == 1  # both singles in one batch


@async_test
async def test_inline_service_is_synchronous():
    msg = b"q" * 32
    pk, sig = _signed(6, msg)
    service = AsyncVerifyService.for_backend(CpuVerifier())
    assert not service.device
    out = await service.verify_claims(
        [("one", msg, pk.to_bytes(), sig.to_bytes())]
    )
    assert out == [True]


class _FakeDeviceHost:
    """A device host whose 'device' counts dispatches and records batch
    sizes — stands in for node.LazyDeviceVerifier + BatchVerifier."""

    def __init__(self, kind="fake", ready=True, delay=0.0):
        self.async_kind = kind
        self._ready = ready
        self.cpu_backend = CpuVerifier()
        self.dispatched_batches = []
        self._delay = delay
        host = self

        class _Dispatch:
            def verify_many(self, digests, pks, sigs, aggregate_ok=False):
                host.dispatched_batches.append(len(digests))
                if host._delay:
                    import time

                    time.sleep(host._delay)
                return CpuVerifier().verify_many(digests, pks, sigs)

        self.async_backend = _Dispatch()

    @property
    def device_ready(self):
        return self._ready


@async_test
async def test_device_service_coalesces_concurrent_submissions():
    """Claims submitted by many tasks in the same wave ride ONE device
    dispatch — the in-process committee coalescing that amortizes the
    tunnel round trip."""
    msg = b"w" * 32
    pairs = [_signed(10 + i, msg) for i in range(8)]
    host = _FakeDeviceHost(kind="coalesce-test")
    service = AsyncVerifyService.for_backend(host)
    assert service.device

    async def submit(pk, sig):
        return await service.verify_claims(
            [("one", msg, pk.to_bytes(), sig.to_bytes())]
        )

    outs = await asyncio.gather(*(submit(pk, sig) for pk, sig in pairs))
    assert all(o == [True] for o in outs)
    # every submission coalesced into one batch of 8
    assert host.dispatched_batches == [8]
    service.close()


@async_test
async def test_device_service_gates_on_readiness():
    """A device that is not warm must never be dispatched to (cold
    compile mid-consensus) — claims route to the CPU backend."""
    msg = b"r" * 32
    pk, sig = _signed(30, msg)
    host = _FakeDeviceHost(kind="gate-test", ready=False)
    service = AsyncVerifyService.for_backend(host)
    out = await service.verify_claims(
        [("one", msg, pk.to_bytes(), sig.to_bytes())]
    )
    assert out == [True]
    assert host.dispatched_batches == []  # CPU path took it
    service.close()


@async_test
async def test_device_service_adapts_to_slow_device():
    """A device dispatch that measures slower than the CPU estimate
    makes later small batches route to the CPU (the tunnel-weather
    fallback), with periodic probes keeping recovery possible."""
    import hotstuff_tpu.crypto.async_service as asv

    msg = b"s" * 32
    pk, sig = _signed(31, msg)
    host = _FakeDeviceHost(kind="adapt-test", delay=0.05)  # 50 ms "tunnel"
    service = AsyncVerifyService.for_backend(host)
    claim = ("one", msg, pk.to_bytes(), sig.to_bytes())
    # first dispatch probes the device optimistically and measures 50 ms
    await service.verify_claims([claim])
    assert host.dispatched_batches == [1]
    assert service._device_ewma_s > 0.04
    # ~1 sig -> CPU estimate ~130 us << 50 ms: next ones go to CPU
    service._last_probe = asv.time.monotonic()  # suppress the probe window
    await service.verify_claims([claim])
    await service.verify_claims([claim])
    assert host.dispatched_batches == [1]
    # a huge batch's CPU estimate exceeds the EWMA -> device again
    # (distinct claims — identical ones would dedup to a single check;
    # 1500 sigs x CPU_BATCH_US_PER_SIG 45 us = 67.5 ms > the 50 ms EWMA)
    big = [
        ("one", bytes([i % 256, i // 256]) + b"\x00" * 30,
         pk.to_bytes(), sig.to_bytes())
        for i in range(1500)
    ]
    out = await service.verify_claims(big)
    assert len(out) == 1500
    assert host.dispatched_batches == [1, 1500]
    service.close()


def test_empty_shared_claim_is_false():
    """A certificate with zero signatures proves nothing: vacuous truth
    over an empty span would verify a votes=[] forgery."""
    out = eval_claims_sync(CpuVerifier(), [("shared", b"\x01" * 32, ())])
    assert out == [False]

    class Agg(CpuVerifier):
        prefers_aggregate = True

    out = eval_claims_sync(Agg(), [("shared", b"\x01" * 32, ())])
    assert out == [False]


@async_test
async def test_subquorum_qc_never_memoized_via_preverify(tmp_path):
    """SAFETY (r4 review): a sub-quorum QC with one valid self-signature
    must not enter the verified-QC cache through the burst preverifier —
    the cache hit would skip QC.verify's quorum-weight check forever."""
    from hotstuff_tpu.consensus import QC
    from hotstuff_tpu.consensus.messages import Vote
    from hotstuff_tpu.consensus.wire import TAG_TIMEOUT

    from .common import chain, fresh_base_port, keys, signed_timeout
    from .test_core import make_core, teardown

    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=60_000)
    try:
        ks = keys()
        block = chain(1)[0]
        # a forged "QC": ONE valid vote signature, far below 2f+1
        attacker_pk, attacker_sk = ks[3]
        vote = Vote(hash=block.digest(), round=1, author=attacker_pk)
        vote.signature = Signature.new(vote.digest(), attacker_sk)
        forged = QC(hash=block.digest(), round=1, votes=[(attacker_pk, vote.signature)])
        evil_timeout = signed_timeout(forged, 2, ks[3][0], ks[3][1])

        pre = await h.core._preverify_burst([(TAG_TIMEOUT, evil_timeout)])
        # the message may have its AUTHOR sig preverified or not, but the
        # forged certificate must NOT be in the verified cache
        assert forged._cache_key() not in h.core._verified_qcs
        # and the full handler path rejects it
        from hotstuff_tpu.consensus.errors import ConsensusError

        try:
            await h.core._handle_timeout(
                evil_timeout, sig_verified=0 in pre
            )
            raise AssertionError("sub-quorum high_qc accepted")
        except ConsensusError:
            pass
        assert forged._cache_key() not in h.core._verified_qcs
        # a votes=[] forgery is equally rejected
        empty = QC(hash=block.digest(), round=1, votes=[])
        t2 = signed_timeout(empty, 2, ks[2][0], ks[2][1])
        await h.core._preverify_burst([(TAG_TIMEOUT, t2)])
        assert empty._cache_key() not in h.core._verified_qcs
    finally:
        teardown(h)


@async_test
async def test_identical_claims_deduplicate_across_submissions():
    """One broadcast message's claims arrive from every co-located core
    in the same wave — the service verifies each unique claim once
    (verdicts are pure functions of the claim bytes)."""
    msg = b"d" * 32
    pk, sig = _signed(50, msg)
    host = _FakeDeviceHost(kind="dedup-test")
    service = AsyncVerifyService.for_backend(host)
    claim = ("one", msg, pk.to_bytes(), sig.to_bytes())

    outs = await asyncio.gather(
        *(service.verify_claims([claim]) for _ in range(8))
    )
    assert all(o == [True] for o in outs)
    assert host.dispatched_batches == [1]  # 8 submissions, ONE evaluation
    service.close()


@async_test
async def test_stalled_device_dispatch_does_not_stall_later_waves():
    """A tunnel-stalled device dispatch must not queue later waves
    behind it: the deadline serves the stalled batch from the CPU, and
    while the device is busy new batches route to the CPU directly
    (measured failure mode: one stall collapsed a 32-node committee to
    a third of the CPU rate)."""
    import time as _time

    msg = b"t" * 32
    pk, sig = _signed(40, msg)
    host = _FakeDeviceHost(kind="stall-test", delay=0.5)  # 500 ms stall
    service = AsyncVerifyService.for_backend(host)
    claim = ("one", msg, pk.to_bytes(), sig.to_bytes())
    t0 = _time.perf_counter()
    out = await service.verify_claims([claim])
    first_wall = _time.perf_counter() - t0
    assert out == [True]
    # the deadline (100 ms floor, 4x EWMA) cut the wait well below the
    # 500 ms stall and the batch was served from the CPU
    assert first_wall < 0.45
    assert service.deadline_misses == 1
    # while the stalled dispatch is still in flight, new waves go
    # straight to the CPU (device busy)
    t0 = _time.perf_counter()
    out = await service.verify_claims([claim])
    assert out == [True]
    assert _time.perf_counter() - t0 < 0.2
    assert host.dispatched_batches == [1]  # no second device dispatch
    await asyncio.sleep(0.6)  # let the stalled dispatch land
    assert not service._device_busy
    service.close()


@async_test
async def test_qcmaker_skips_batch_when_all_preverified():
    """A cell whose every vote arrived pre-verified emits the QC with no
    quorum-time batch dispatch (the signatures are already proven)."""
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.messages import Vote

    from .common import committee, fresh_base_port, keys

    class Counting(CpuVerifier):
        shared = 0

        def verify_shared_msg(self, d, votes):
            Counting.shared += 1
            return super().verify_shared_msg(d, votes)

    com = committee(fresh_base_port())
    ks = keys()
    agg = Aggregator(com, Counting(), self_key=ks[0][0])
    block_hash = Digest(b"\x09" * 32)
    qc = None
    for pk, sk in ks[:3]:
        vote = Vote(hash=block_hash, round=1, author=pk)
        vote.signature = Signature.new(vote.digest(), sk)
        qc = agg.add_vote(vote, 1, sig_verified=True) or qc
    assert qc is not None and qc.round == 1
    assert Counting.shared == 0  # no quorum batch needed

    # mixed cell: one unverified entry forces the quorum batch
    Counting.shared = 0
    agg2 = Aggregator(com, Counting(), self_key=ks[0][0])
    for i, (pk, sk) in enumerate(ks[:3]):
        vote = Vote(hash=block_hash, round=2, author=pk)
        vote.signature = Signature.new(vote.digest(), sk)
        agg2.add_vote(vote, 2, sig_verified=i != 1)
    assert Counting.shared == 1


@async_test
async def test_preverified_proposal_skips_sync_crypto(tmp_path):
    """A proposal whose claims all pass arrives at the handler with
    sigs_verified=True: zero synchronous signature work on the loop."""
    from hotstuff_tpu.consensus.wire import TAG_PROPOSE

    from .common import chain, fresh_base_port
    from .test_core import make_core, teardown

    class Counting(CpuVerifier):
        ones = 0
        shared = 0

        def verify_one(self, d, pk, sig):
            Counting.ones += 1
            return super().verify_one(d, pk, sig)

        def verify_shared_msg(self, d, votes):
            Counting.shared += 1
            return super().verify_shared_msg(d, votes)

    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=60_000)
    try:
        blocks = chain(2)
        burst = [(TAG_PROPOSE, blocks[1])]
        pre = await h.core._preverify_burst(burst)
        assert pre == {0}
        # now swap in the counting verifier: the handler must not touch it
        h.core.verifier = Counting()
        h.core.aggregator.verifier = h.core.verifier
        await h.core._dispatch(burst[0], sig_verified=True)
        assert Counting.ones == 0
        assert Counting.shared == 0
        # and the embedded QC is memoized for future bursts
        assert blocks[1].qc._cache_key() in h.core._verified_qcs
    finally:
        teardown(h)


def test_registry_prunes_closed_loops():
    """Advisor r4: the per-(loop, kind) registry must not pin closed
    loops (and their idle executors) forever — stale entries are pruned
    on the next for_backend lookup."""

    class DeviceBackend(CpuVerifier):
        async_kind = "test-kind"
        device_ready = False

    backend = DeviceBackend()

    async def acquire():
        return AsyncVerifyService.for_backend(backend)

    loop1 = asyncio.new_event_loop()
    svc1 = loop1.run_until_complete(acquire())
    loop1.close()
    assert any(s is svc1 for _, s in AsyncVerifyService._registry.values())

    loop2 = asyncio.new_event_loop()
    svc2 = loop2.run_until_complete(acquire())
    try:
        # the closed loop's entry is gone; only the live one remains
        assert not any(
            s is svc1 for _, s in AsyncVerifyService._registry.values()
        )
        assert any(
            s is svc2 for _, s in AsyncVerifyService._registry.values()
        )
    finally:
        svc2.close()
        loop2.close()


class _GatedDeviceHost:
    """Device host whose every dispatch BLOCKS until its per-wave gate
    is released — drives out-of-order completion, per-wave failure
    injection, and in-flight concurrency tracking for the pipeline
    tests."""

    def __init__(self, kind):
        import threading

        self.async_kind = kind
        self.device_ready = True
        self.cpu_backend = CpuVerifier()
        # gates held open mid-test must not trip the dispatch deadline
        self.dispatch_deadline_s = 5.0
        self.gates: list = []
        self.fail_waves: set = set()
        self.concurrent = 0
        self.max_concurrent = 0
        self._lock = threading.Lock()
        host = self

        class _Dispatch:
            def verify_many(self, digests, pks, sigs, aggregate_ok=False):
                import threading as _threading

                with host._lock:
                    idx = len(host.gates)
                    gate = _threading.Event()
                    host.gates.append(gate)
                    host.concurrent += 1
                    host.max_concurrent = max(
                        host.max_concurrent, host.concurrent
                    )
                try:
                    assert gate.wait(5.0), "test gate never released"
                    if idx in host.fail_waves:
                        raise RuntimeError(f"wave {idx} failed")
                    return CpuVerifier().verify_many(digests, pks, sigs)
                finally:
                    with host._lock:
                        host.concurrent -= 1

        self.async_backend = _Dispatch()


async def _until(cond, timeout=2.0):
    import time as _time

    t0 = _time.perf_counter()
    while not cond():
        assert _time.perf_counter() - t0 < timeout, "condition not reached"
        await asyncio.sleep(0.005)


@async_test
async def test_out_of_order_completion_resolves_right_futures():
    """Two waves in flight at depth 2: the LATER wave lands first and
    resolves its own waiters with its own verdicts while the earlier
    wave is still on the device (async readback, ISSUE 5)."""
    msg_a, msg_b = b"a" * 32, b"b" * 32
    pk, sig_a = _signed(60, msg_a)
    claim_a = ("one", msg_a, pk.to_bytes(), sig_a.to_bytes())
    # sig_a over msg_b is INVALID — distinct verdicts prove the futures
    # were matched to the right waves
    claim_b = ("one", msg_b, pk.to_bytes(), sig_a.to_bytes())
    host = _GatedDeviceHost("ooo-test")
    service = AsyncVerifyService(host, device=True, pipeline_depth=2)
    task_a = asyncio.ensure_future(service.verify_claims([claim_a]))
    await _until(lambda: len(host.gates) == 1)
    task_b = asyncio.ensure_future(service.verify_claims([claim_b]))
    await _until(lambda: len(host.gates) == 2)
    assert service.peak_inflight == 2
    host.gates[1].set()  # wave B lands FIRST
    assert (await task_b) == [False]
    assert not task_a.done()  # A still parked on the device
    host.gates[0].set()
    assert (await task_a) == [True]
    service.close()


@async_test
async def test_failed_wave_poisons_only_its_own_futures():
    """A backend exception on wave N reaches wave N's waiters and ONLY
    wave N's — the in-flight wave behind it lands normally."""
    msg_a, msg_b = b"c" * 32, b"e" * 32
    pk_a, sig_a = _signed(61, msg_a)
    pk_b, sig_b = _signed(62, msg_b)
    host = _GatedDeviceHost("poison-test")
    host.fail_waves = {0}
    service = AsyncVerifyService(host, device=True, pipeline_depth=2)
    task_a = asyncio.ensure_future(
        service.verify_claims([("one", msg_a, pk_a.to_bytes(), sig_a.to_bytes())])
    )
    await _until(lambda: len(host.gates) == 1)
    task_b = asyncio.ensure_future(
        service.verify_claims([("one", msg_b, pk_b.to_bytes(), sig_b.to_bytes())])
    )
    await _until(lambda: len(host.gates) == 2)
    host.gates[0].set()
    try:
        await task_a
        raise AssertionError("poisoned wave returned a verdict")
    except RuntimeError:
        pass
    host.gates[1].set()
    assert (await task_b) == [True]
    service.close()


@async_test
async def test_depth_cap_backpressure_queues_next_wave(monkeypatch):
    """Wave K+1 QUEUES for a pipeline slot at full occupancy instead of
    dispatching past the depth cap (or spilling to the CPU when the
    device is the forced route), and dispatches as soon as a wave
    lands."""
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    claims = []
    for i in range(3):
        msg = bytes([100 + i]) * 32
        pk, sig = _signed(70 + i, msg)
        claims.append(("one", msg, pk.to_bytes(), sig.to_bytes()))
    host = _GatedDeviceHost("cap-test")
    service = AsyncVerifyService(host, device=True, pipeline_depth=2)
    tasks = []
    for i in range(2):
        tasks.append(asyncio.ensure_future(service.verify_claims([claims[i]])))
        await _until(lambda i=i: len(host.gates) == i + 1)
    tasks.append(asyncio.ensure_future(service.verify_claims([claims[2]])))
    await asyncio.sleep(0.05)
    # the third wave queued: never a third concurrent dispatch
    assert len(host.gates) == 2
    assert service.pipeline_waits == 1
    host.gates[0].set()  # a slot frees -> the queued wave dispatches
    await _until(lambda: len(host.gates) == 3)
    host.gates[1].set()
    host.gates[2].set()
    assert await asyncio.gather(*tasks) == [[True]] * 3
    assert host.max_concurrent <= 2
    assert service.peak_inflight == 2
    service.close()


@async_test
async def test_depth_one_preserves_single_inflight(monkeypatch):
    """pipeline_depth=1 restores the old single-in-flight dispatch gate:
    at no point are two device dispatches concurrent."""
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    msg_a, msg_b = b"f" * 32, b"g" * 32
    pk_a, sig_a = _signed(80, msg_a)
    pk_b, sig_b = _signed(81, msg_b)
    host = _GatedDeviceHost("depth1-test")
    service = AsyncVerifyService(host, device=True, pipeline_depth=1)
    task_a = asyncio.ensure_future(
        service.verify_claims([("one", msg_a, pk_a.to_bytes(), sig_a.to_bytes())])
    )
    await _until(lambda: len(host.gates) == 1)
    task_b = asyncio.ensure_future(
        service.verify_claims([("one", msg_b, pk_b.to_bytes(), sig_b.to_bytes())])
    )
    await asyncio.sleep(0.05)
    assert len(host.gates) == 1  # second wave queued behind the gate
    host.gates[0].set()
    await _until(lambda: len(host.gates) == 2)
    host.gates[1].set()
    assert await asyncio.gather(task_a, task_b) == [[True], [True]]
    assert host.max_concurrent == 1
    assert service.peak_inflight == 1
    service.close()


def test_route_under_full_occupancy(monkeypatch):
    """Routing at the depth cap: device-preferred waves queue ("wait"),
    device-losing waves spill to the CPU, a due probe NEVER fires (it
    would need the slot we don't have), and an overdue in-flight wave
    routes everything to the CPU."""
    import time as _time

    class DeviceBackend(CpuVerifier):
        async_kind = "occupancy-route-test"
        device_ready = True

    monkeypatch.delenv("HOTSTUFF_FORCE_DEVICE_ROUTE", raising=False)
    service = AsyncVerifyService(DeviceBackend(), device=True, pipeline_depth=2)
    now = _time.monotonic()
    service._inflight = {1: now + 10.0, 2: now + 10.0}
    service._last_probe = 0.0  # a probe is long overdue
    # device EWMA wins for this batch size -> queue for a slot
    service._device_ewma_s = 0.001
    assert service._route_device(256) == "wait"
    # device EWMA loses badly -> CPU, and the due probe must NOT fire
    service._device_ewma_s = 10.0
    assert service._route_device(1) == "cpu"
    # the forced route queues rather than spilling
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    assert service._route_device(1) == "wait"
    monkeypatch.delenv("HOTSTUFF_FORCE_DEVICE_ROUTE")
    # an OVERDUE in-flight wave routes everything to the CPU
    service._inflight[1] = now - 1.0
    service._device_ewma_s = 0.001
    assert service._route_device(256) == "cpu"
    service._inflight.clear()
    # below the cap the due probe finally fires on a losing EWMA
    service._device_ewma_s = 10.0
    assert service._route_device(1) == "probe"
    service.close()


def test_pipeline_depth_from_env(monkeypatch):
    from hotstuff_tpu.crypto.async_service import (
        DEFAULT_PIPELINE_DEPTH,
        pipeline_depth_from_env,
    )

    monkeypatch.delenv("HOTSTUFF_VERIFY_PIPELINE", raising=False)
    assert pipeline_depth_from_env() == DEFAULT_PIPELINE_DEPTH
    monkeypatch.setenv("HOTSTUFF_VERIFY_PIPELINE", "4")
    assert pipeline_depth_from_env() == 4
    monkeypatch.setenv("HOTSTUFF_VERIFY_PIPELINE", "0")
    assert pipeline_depth_from_env() == 1  # floor: depth 0 is depth 1


def test_wave_buckets_from_env(monkeypatch):
    from hotstuff_tpu.crypto.async_service import (
        DEFAULT_WAVE_BUCKETS,
        wave_buckets_from_env,
    )

    monkeypatch.delenv("HOTSTUFF_WAVE_BUCKETS", raising=False)
    assert wave_buckets_from_env() == DEFAULT_WAVE_BUCKETS
    monkeypatch.setenv("HOTSTUFF_WAVE_BUCKETS", "64,16,256")
    assert wave_buckets_from_env() == (16, 64, 256)  # sorted, deduped
    monkeypatch.setenv("HOTSTUFF_WAVE_BUCKETS", "off")
    assert wave_buckets_from_env() == ()
    monkeypatch.setenv("HOTSTUFF_WAVE_BUCKETS", "0")
    assert wave_buckets_from_env() == ()
    monkeypatch.setenv("HOTSTUFF_WAVE_BUCKETS", "bogus")
    assert wave_buckets_from_env() == DEFAULT_WAVE_BUCKETS


def test_pad_claim_is_a_valid_signature():
    """The fixed-shape filler claim must be VALID: an invalid pad would
    poison the CPU batch equation fallback for an otherwise all-valid
    packed wave (eval_claims_sync's flat fast path is all-or-nothing)."""
    service = AsyncVerifyService(CpuVerifier())
    pad = service._pad_claim_tuple()
    assert pad[0] == "one"
    assert eval_claims_sync(CpuVerifier(), [pad]) == [True]


@async_test
async def test_fixed_shape_padding_hits_bucket_and_preserves_verdicts(
    monkeypatch,
):
    """A device-routed wave on a padding-capable backend is padded to
    the smallest bucket (ISSUE 6) — and the pads can never flip a real
    claim's verdict, including an INVALID real claim's."""
    monkeypatch.delenv("HOTSTUFF_WAVE_BUCKETS", raising=False)
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    host = _FakeDeviceHost(kind="pack-test")
    host.supports_wave_padding = True
    service = AsyncVerifyService(host, device=True)
    claims = []
    for i in range(4):
        m = bytes([120 + i]) * 32
        pk, s = _signed(100 + i, m)
        claims.append(("one", m, pk.to_bytes(), s.to_bytes()))
    # claims[0]'s signature over a different digest is INVALID
    bad = ("one", b"k" * 32, claims[0][2], claims[0][3])
    out = await service.verify_claims(claims + [bad])
    assert out == [True] * 4 + [False]
    # 5 real sigs padded to the 16-bucket: the device saw EXACTLY 16
    assert host.dispatched_batches == [16]
    assert service.packed_waves == 1
    assert service.pad_sigs == 11
    # an exact-fit wave passes through unpadded
    fit = []
    for i in range(16):
        m = bytes([10, i]) + b"\x00" * 30
        pk, s = _signed(130, m)
        fit.append(("one", m, pk.to_bytes(), s.to_bytes()))
    out = await service.verify_claims(fit)
    assert len(out) == 16
    assert host.dispatched_batches[-1] == 16
    assert service.packed_waves == 1  # no pads added for the exact fit
    service.close()


@async_test
async def test_padding_needs_backend_opt_in(monkeypatch):
    """Backends that do NOT advertise supports_wave_padding see exactly
    the submitted claims (synthetic hosts, CPU fallback, aggregate
    backends) — no silent filler rides their dispatches."""
    monkeypatch.delenv("HOTSTUFF_WAVE_BUCKETS", raising=False)
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    msg = b"l" * 32
    pk, sig = _signed(105, msg)
    host = _FakeDeviceHost(kind="no-pack-test")  # no opt-in attribute
    service = AsyncVerifyService(host, device=True)
    out = await service.verify_claims(
        [("one", msg, pk.to_bytes(), sig.to_bytes())]
    )
    assert out == [True]
    assert host.dispatched_batches == [1]
    assert service.packed_waves == 0 and service.pad_sigs == 0
    service.close()


def test_warm_buckets_drives_every_bucket_shape(monkeypatch):
    """warm_buckets() pre-compiles each configured bucket size through
    the forced-device dispatch view, so the first real wave of any
    bucket never pays a cold compile mid-consensus."""
    monkeypatch.setenv("HOTSTUFF_WAVE_BUCKETS", "4,8")
    host = _FakeDeviceHost(kind="warm-test")
    host.supports_wave_padding = True
    service = AsyncVerifyService(host, device=True)
    service.warm_buckets()
    assert host.dispatched_batches == [4, 8]
    # non-padding backends and inline services are no-ops
    plain = AsyncVerifyService(CpuVerifier())
    plain.warm_buckets()
    service.close()
    plain.close()


@async_test
async def test_round_window_coalesces_qc_and_tc_into_one_wave(monkeypatch):
    """HOTSTUFF_COALESCE_WINDOW_MS holds the wave open so the QC and TC
    claims of one round merge into ONE tunnel crossing, with the claim
    table fanning each submitter its own verdicts on readback."""
    monkeypatch.setenv("HOTSTUFF_COALESCE_WINDOW_MS", "80")
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    msg = b"i" * 32
    qc_pairs = [_signed(91 + i, msg) for i in range(4)]
    qc_claim = (
        "shared",
        msg,
        tuple((pk.to_bytes(), s.to_bytes()) for pk, s in qc_pairs),
    )
    tc_claims = []
    for i in range(3):
        m = bytes([110 + i]) * 32
        pk, s = _signed(95 + i, m)
        tc_claims.append(("one", m, pk.to_bytes(), s.to_bytes()))
    # one INVALID TC entry proves the merged wave's per-claim fanout
    bad = ("one", b"j" * 32, tc_claims[0][2], tc_claims[0][3])
    host = _FakeDeviceHost(kind="window-test")
    service = AsyncVerifyService(host, device=True)
    assert abs(service.coalesce_window_s - 0.08) < 1e-9
    qc_fut = asyncio.ensure_future(service.verify_claims([qc_claim]))
    await asyncio.sleep(0.02)  # well inside the window
    tc_fut = asyncio.ensure_future(
        service.verify_claims(tc_claims + [bad])
    )
    assert (await qc_fut) == [True]
    assert (await tc_fut) == [True, True, True, False]
    # 4 QC sigs + 4 TC sigs crossed the tunnel ONCE
    assert host.dispatched_batches == [8]
    assert service.device_dispatches == 1
    service.close()


@async_test
async def test_dispatch_loop_shuts_down_on_close():
    """Service close stops the dedicated dispatch loop's slot threads
    (and deregisters it from the atexit shutdown set) — no leaked
    thread outlives its service."""
    import hotstuff_tpu.crypto.async_service as asv

    msg = b"h" * 32
    pk, sig = _signed(90, msg)
    host = _FakeDeviceHost(kind="lifecycle-test")
    service = AsyncVerifyService.for_backend(host)
    out = await service.verify_claims(
        [("one", msg, pk.to_bytes(), sig.to_bytes())]
    )
    assert out == [True]
    dl = service._dispatch
    assert dl is not None and dl in asv._live_dispatch_loops
    threads = list(dl._threads)
    assert threads and all(t.is_alive() for t in threads)
    assert all(t.name.startswith("verify-slot-") for t in threads)
    assert len(threads) == service.pipeline_depth
    service.close()
    assert service._dispatch is None
    assert dl not in asv._live_dispatch_loops
    for t in threads:
        t.join(timeout=2.0)
    assert not any(t.is_alive() for t in threads)
    # a closed loop refuses new work instead of silently dropping it
    try:
        dl.submit(lambda: None, lambda r, e: None)
        raise AssertionError("closed dispatch loop accepted a submit")
    except RuntimeError:
        pass


def test_no_claim_dedup_gives_private_services(monkeypatch):
    """HOTSTUFF_NO_CLAIM_DEDUP=1 (the --no-claim-dedup harness knob)
    must give every core a private device service: no cross-core
    coalescing registry entry, distinct instances per acquisition."""

    class DeviceBackend(CpuVerifier):
        async_kind = "nodedup-test"
        device_ready = False

    backend = DeviceBackend()
    monkeypatch.setenv("HOTSTUFF_NO_CLAIM_DEDUP", "1")

    async def acquire_two():
        return (
            AsyncVerifyService.for_backend(backend),
            AsyncVerifyService.for_backend(backend),
        )

    loop = asyncio.new_event_loop()
    try:
        s1, s2 = loop.run_until_complete(acquire_two())
        assert s1 is not s2
        assert s1.device and s2.device
        assert not any(
            s in (s1, s2) for _, s in AsyncVerifyService._registry.values()
        )
    finally:
        s1.close()
        s2.close()
        loop.close()
