"""TPU ed25519 kernel tests: point ops and batch verification vs the
pure-Python oracle (crypto/ed25519_ref.py), incl. adversarial inputs."""

import hashlib
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hotstuff_tpu.crypto import ed25519_ref as ref
from hotstuff_tpu.tpu import curve, field as F
from hotstuff_tpu.tpu.ed25519 import BatchVerifier

rng = random.Random(99)

jadd_pt = jax.jit(curve.point_add)
jdbl_pt = jax.jit(curve.point_double)


def rand_point():
    """Random curve point = [r]B via the oracle."""
    return ref.point_mul(rng.randrange(1, ref.L), ref.B_POINT)


def to_dev_point(p):
    return tuple(jnp.asarray(v)[None, :] for v in curve.point_to_limbs(p))


def assert_same_point(dev_p, ref_p):
    x = F.int_from_limbs(jax.jit(F.canonical)(F.mul(dev_p[0], jax.jit(F.pow_inv)(dev_p[2])))[0])
    y = F.int_from_limbs(jax.jit(F.canonical)(F.mul(dev_p[1], jax.jit(F.pow_inv)(dev_p[2])))[0])
    rx, ry = ref.point_affine(ref_p)
    assert (x, y) == (rx, ry)


def test_point_add_double_matches_oracle():
    for _ in range(5):
        p, q = rand_point(), rand_point()
        assert_same_point(jadd_pt(to_dev_point(p), to_dev_point(q)), ref.point_add(p, q))
        assert_same_point(jdbl_pt(to_dev_point(p)), ref.point_double(p))
    # identity edge cases (unified formulas must handle them)
    ident = tuple(jnp.asarray(v)[None, :] for v in (
        F.limbs_from_int(0), F.limbs_from_int(1), F.limbs_from_int(1), F.limbs_from_int(0)))
    p = rand_point()
    assert_same_point(jadd_pt(to_dev_point(p), ident), p)
    assert_same_point(jadd_pt(ident, ident), ref.IDENTITY)


def _sign_many(n, msg_fn):
    items = []
    for i in range(n):
        seed = bytes([i]) * 32
        pk = ref.public_from_seed(seed)
        msg = msg_fn(i)
        items.append((msg, pk, ref.sign(seed, msg)))
    return items


@pytest.fixture(scope="module")
def verifier():
    return BatchVerifier(min_device_batch=0)  # force the kernel path


def test_batch_all_valid(verifier):
    items = _sign_many(5, lambda i: b"msg-%d" % i)
    out = verifier.verify(*map(list, zip(*items)))
    assert out.tolist() == [True] * 5


def test_batch_mixed_invalid(verifier):
    items = _sign_many(8, lambda i: b"payload-%d" % i)
    msgs, pks, sigs = map(list, zip(*items))
    expected = [True] * 8
    # corrupt signature R
    sigs[1] = bytes([sigs[1][0] ^ 1]) + sigs[1][1:]; expected[1] = False
    # corrupt s half
    sigs[2] = sigs[2][:40] + bytes([sigs[2][40] ^ 0x80]) + sigs[2][41:]; expected[2] = False
    # wrong message
    msgs[3] = b"tampered"; expected[3] = False
    # wrong key
    pks[4] = ref.public_from_seed(b"\xaa" * 32); expected[4] = False
    # non-canonical s (s + L)
    s_int = int.from_bytes(sigs[5][32:], "little") + ref.L
    sigs[5] = sigs[5][:32] + s_int.to_bytes(32, "little"); expected[5] = False
    # undecompressable pubkey (y >= p encodes no point)
    pks[6] = (ref.P + 1).to_bytes(32, "little"); expected[6] = False
    out = verifier.verify(msgs, pks, sigs)
    assert out.tolist() == expected
    # agreement with the oracle on every item
    for got, (m, pk, sig) in zip(out.tolist(), zip(msgs, pks, sigs)):
        assert got == ref.verify(sig, pk, m)


def test_rfc_vectors_on_device(verifier):
    vecs = [
        ("9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60", ""),
        ("4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb", "72"),
        ("c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7", "af82"),
    ]
    msgs, pks, sigs = [], [], []
    for seed_hex, msg_hex in vecs:
        seed, msg = bytes.fromhex(seed_hex), bytes.fromhex(msg_hex)
        msgs.append(msg)
        pks.append(ref.public_from_seed(seed))
        sigs.append(ref.sign(seed, msg))
    assert verifier.verify(msgs, pks, sigs).tolist() == [True] * 3


def test_qc_shape_shared_message(verifier):
    """The QC-verify shape: many signers, one digest."""
    digest = hashlib.sha512(b"block").digest()[:32]
    msgs, pks, sigs = [], [], []
    for i in range(7):
        seed = bytes([0x40 + i]) * 32
        msgs.append(digest)
        pks.append(ref.public_from_seed(seed))
        sigs.append(ref.sign(seed, digest))
    assert verifier.verify(msgs, pks, sigs).all()
    sigs[3] = sigs[3][:10] + b"\x00" + sigs[3][11:]
    out = verifier.verify(msgs, pks, sigs)
    assert out.tolist() == [True, True, True, False, True, True, True]


def test_committee_precompute_cache(verifier):
    pks = [ref.public_from_seed(bytes([i]) * 32) for i in range(4)]
    verifier.precompute(pks)
    assert all(pk in verifier._point_cache for pk in pks)


def test_pallas_dsm_parity_interpret():
    """The Pallas double-scalar-mult kernel (tpu/pallas_dsm.py) must agree
    with the XLA path bit-for-bit.  Runs in interpreter mode so the
    parity check works on the CPU test mesh; on-device coverage comes
    from the benchmark and the TPU rig."""
    from hotstuff_tpu.tpu import pallas_dsm
    from hotstuff_tpu.tpu.ed25519 import _bytes_to_windows_msb

    B = pallas_dsm.LANE_TILE  # minimum lane-aligned batch
    s_rows = np.stack(
        [
            np.frombuffer(
                rng.randrange(ref.L).to_bytes(32, "little"), np.uint8
            )
            for _ in range(B)
        ]
    )
    k_rows = np.stack(
        [
            np.frombuffer(
                rng.randrange(ref.L).to_bytes(32, "little"), np.uint8
            )
            for _ in range(B)
        ]
    )
    s_win = jnp.asarray(_bytes_to_windows_msb(s_rows).T)
    k_win = jnp.asarray(_bytes_to_windows_msb(k_rows).T)
    pts = [rand_point() for _ in range(B)]
    a_point = tuple(
        jnp.asarray(np.stack([curve.point_to_limbs(p)[c] for p in pts]))
        for c in range(4)
    )

    x_out = curve.dual_scalar_mult(s_win, k_win, a_point)
    p_out = pallas_dsm.dual_scalar_mult(s_win, k_win, a_point, interpret=True)
    canon = jax.jit(F.canonical)
    # X, Y, Z only: the pallas kernel's need_t schedule leaves T
    # uncomputed (compressed_equals never reads it)
    for xla, pal in list(zip(x_out, p_out))[:3]:
        assert (np.asarray(canon(xla)) == np.asarray(canon(pal))).all()


def test_pallas_fused_epilogue_parity_interpret():
    """The in-kernel compressed-equality epilogue (limb-major ports of
    _chain/_strict/canonical/pow_inv) against the XLA field ops: encode
    the XLA scan's outputs host-side, corrupt the sign on some lanes and
    the y encoding on others, and check the fused unsplit kernel's
    verdict lane-by-lane."""
    import jax.numpy as jnp

    from hotstuff_tpu.tpu import pallas_dsm
    from hotstuff_tpu.tpu.ed25519 import _bytes_to_windows_msb

    B = pallas_dsm.LANE_TILE
    s_rows = np.stack(
        [
            np.frombuffer(
                rng.randrange(ref.L).to_bytes(32, "little"), np.uint8
            )
            for _ in range(B)
        ]
    )
    k_rows = np.stack(
        [
            np.frombuffer(
                rng.randrange(ref.L).to_bytes(32, "little"), np.uint8
            )
            for _ in range(B)
        ]
    )
    s_win = jnp.asarray(_bytes_to_windows_msb(s_rows).T)
    k_win = jnp.asarray(_bytes_to_windows_msb(k_rows).T)
    pts = [rand_point() for _ in range(B)]
    a_point = tuple(
        jnp.asarray(np.stack([curve.point_to_limbs(p)[c] for p in pts]))
        for c in range(4)
    )

    # the true compressed encodings, via the XLA path
    X, Y, Z, _ = curve.dual_scalar_mult(s_win, k_win, a_point)
    zinv = jax.jit(F.pow_inv)(Z)
    y_can = np.asarray(jax.jit(F.canonical)(F.mul(Y, zinv)))
    x_can = np.asarray(jax.jit(F.canonical)(F.mul(X, zinv)))
    r_y = y_can.copy()
    r_sign = (x_can[:, 0] & 1).astype(np.int32)
    expect = np.ones(B, bool)
    r_sign[:8] ^= 1  # wrong sign bit
    r_y[8:16, 0] ^= 1  # wrong y encoding
    expect[:16] = False

    ok = np.asarray(
        pallas_dsm.verify_compressed(
            s_win,
            k_win,
            a_point,
            jnp.asarray(r_y),
            jnp.asarray(r_sign),
            interpret=True,
        )
    )
    assert ok.tolist() == expect.tolist()


def test_donate_buffers_env_gate(monkeypatch):
    """HOTSTUFF_DONATE forces buffer donation on/off; unset defers to
    the backend platform (accelerators donate, CPU jax would warn)."""
    monkeypatch.setenv("HOTSTUFF_DONATE", "0")
    assert not BatchVerifier(min_device_batch=0).donate_buffers
    monkeypatch.setenv("HOTSTUFF_DONATE", "1")
    assert BatchVerifier(min_device_batch=0).donate_buffers
    monkeypatch.delenv("HOTSTUFF_DONATE")
    v = BatchVerifier(min_device_batch=0)
    assert v.donate_buffers == (jax.default_backend() in ("tpu", "gpu"))


def test_donated_dispatch_verdict_parity(monkeypatch):
    """With donation forced on, staging buffers are consumed per wave —
    and because verify() restages every wave, back-to-back waves of
    different shapes (and a repeat of the first) keep exact verdict
    parity.  The committee gather source (args 0-3) is NOT donated, so
    the epoch-static key tables survive every wave."""
    monkeypatch.setenv("HOTSTUFF_DONATE", "1")
    v = BatchVerifier(min_device_batch=0)
    assert v.donate_buffers
    items = _sign_many(6, lambda i: b"donate-%d" % i)
    msgs, pks, sigs = map(list, zip(*items))
    sigs[2] = bytes([sigs[2][0] ^ 1]) + sigs[2][1:]
    expected = [True, True, False, True, True, True]
    assert v.verify(msgs, pks, sigs).tolist() == expected
    # a different wave shape in between...
    items2 = _sign_many(3, lambda i: b"other-%d" % i)
    assert v.verify(*map(list, zip(*items2))).tolist() == [True] * 3
    # ...then the first wave again: donation corrupted nothing cached
    assert v.verify(msgs, pks, sigs).tolist() == expected


def test_challenge_hash_memo():
    """The per-(sig, pk, msg) challenge-hash memo serves repeated rows
    (pad claims, re-verified certificates) without re-hashing — and
    never changes a verdict."""
    v = BatchVerifier(min_device_batch=0)
    items = _sign_many(4, lambda i: b"memo-%d" % i)
    msgs, pks, sigs = map(list, zip(*items))
    assert v.verify(msgs, pks, sigs).all()
    assert len(v._challenge_memo) == 4
    assert v.verify(msgs, pks, sigs).all()  # served from the memo


def test_stage_routing_thresholds():
    """stage() contract after the split-kernel deletion: every batch
    goes through prepare() to _run_kernel (overridden by the
    mesh-sharded subclass); use_pallas only changes which kernel
    _run_kernel dispatches."""
    items = _sign_many(3, lambda i: b"route-%d" % i)
    msgs, pks, sigs = map(list, zip(*items))

    for use_pallas in (True, False):
        v = BatchVerifier(min_device_batch=0, use_pallas=use_pallas)
        kernel, arrays, valid = v.stage(msgs, pks, sigs)
        assert kernel == v._run_kernel
        assert valid.all() and len(arrays) == 8
