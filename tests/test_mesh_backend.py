"""Sharded-mesh production backend (ISSUE 7): padded-wave verdict
parity across virtual mesh sizes, the mesh-multiple bucket ladder, and
the shard-aligned committee gather surviving a rebuild.

All mesh sizes here run on the virtual 8-device CPU mesh (conftest sets
``--xla_force_host_platform_device_count=8``).
"""

import asyncio

import numpy as np
import pytest

from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
from hotstuff_tpu.crypto.async_service import AsyncVerifyService
from hotstuff_tpu.crypto.service import CpuVerifier
from hotstuff_tpu.node.node import _DeviceDispatch
from hotstuff_tpu.parallel.mesh import ShardedBatchVerifier, default_mesh

from .common import async_test


def _claims(n: int, seed: int, tamper=frozenset()):
    """n single-sig claims over DISTINCT digests; tampered indices sign
    the wrong digest (a well-formed signature that must fail on the
    device lanes, not in host pre-validation)."""
    wrong = Digest(b"\xee" * 32)
    claims, pks = [], []
    for i in range(n):
        msg = bytes([seed, i]) + b"\x00" * 30
        pk, sk = generate_keypair(bytes([seed]) * 32, i)
        sig = Signature.new(wrong if i in tamper else Digest(msg), sk)
        claims.append(("one", msg, pk.to_bytes(), sig.to_bytes()))
        pks.append(pk.to_bytes())
    return claims, pks


class _MeshHost:
    """LazyDeviceVerifier stand-in holding a REAL ShardedBatchVerifier.

    The lazy host materializes ONE shared device per kind per process,
    so cross-mesh-size tests build the verifier explicitly and expose
    the same capability surface the service consults (async_kind names
    the mesh so the service labels its dispatches "mesh")."""

    supports_wave_padding = True
    device_ready = True
    dispatch_deadline_s = 30.0

    def __init__(self, mesh_size: int):
        self.device = ShardedBatchVerifier(
            mesh=default_mesh(mesh_size), min_device_batch=0
        )
        self.async_kind = f"mesh-{mesh_size}-test"
        self.name = self.async_kind
        self.cpu_backend = CpuVerifier()
        self.dispatched_batches: list[int] = []
        inner = _DeviceDispatch(self.device)
        host = self

        class _Counted:
            supports_wave_padding = True

            def verify_many(self, digests, pks, sigs, aggregate_ok=False):
                host.dispatched_batches.append(len(digests))
                return inner.verify_many(digests, pks, sigs, aggregate_ok)

        self.async_backend = _Counted()
        self.wave_bucket_shapes = self.device.wave_bucket_shapes

    def precompute(self, pks) -> None:
        self.device.precompute(pks)


@pytest.mark.parametrize("m", [2, 4, 8])
def test_bucket_shapes_are_mesh_multiples(m):
    """Every advertised wave bucket is a pad-grid entry (== a kernel
    shape) with equal per-device slices, and the 4096 train bucket
    exists at every mesh size."""
    v = ShardedBatchVerifier(mesh=default_mesh(m), min_device_batch=0)
    shapes = v.wave_bucket_shapes
    assert shapes == tuple(sorted(set(shapes)))
    assert all(b % m == 0 for b in shapes)
    assert set(shapes) <= set(v.pad_sizes)
    assert 4096 in shapes
    # the canonical ladder survives snapping on small meshes: the
    # smallest bucket stays small enough that a QC-16 wave is not
    # padded past 2x
    assert shapes[0] <= 16


def test_service_resolves_buckets_from_backend(monkeypatch):
    """Without an explicit HOTSTUFF_WAVE_BUCKETS the service adopts the
    mesh backend's advertised ladder; an explicit env still wins."""
    monkeypatch.delenv("HOTSTUFF_WAVE_BUCKETS", raising=False)
    host = _MeshHost(2)
    service = AsyncVerifyService(host, device=True)
    try:
        assert service.wave_buckets == host.wave_bucket_shapes
        monkeypatch.setenv("HOTSTUFF_WAVE_BUCKETS", "8,32")
        assert service.wave_buckets == (8, 32)
    finally:
        service.close()


@pytest.mark.parametrize("m", [2, 4, 8])
@async_test
async def test_padded_wave_verdict_parity_across_mesh_sizes(m, monkeypatch):
    """One coalesced wave (two submitters, one tampered claim) through
    the production dispatch pipeline at each virtual mesh size: the
    wave pads to the mesh bucket, the pads stay valid through the
    sharded gather, the poisoned lane fails WITHOUT flipping its
    neighbors, and the claim table fans each submitter its own
    verdicts.  Dispatches carry the "mesh" route label."""
    monkeypatch.delenv("HOTSTUFF_WAVE_BUCKETS", raising=False)
    monkeypatch.setenv("HOTSTUFF_FORCE_DEVICE_ROUTE", "1")
    host = _MeshHost(m)
    a_claims, a_pks = _claims(3, seed=0x51)
    b_claims, b_pks = _claims(2, seed=0x52, tamper={1})
    host.precompute(a_pks + b_pks)
    service = AsyncVerifyService(host, device=True)
    try:
        task_a = asyncio.ensure_future(service.verify_claims(a_claims))
        task_b = asyncio.ensure_future(service.verify_claims(b_claims))
        out_a, out_b = await asyncio.gather(task_a, task_b)
        # per-submitter fanout with poison isolation
        assert out_a == [True, True, True]
        assert out_b == [True, False]
        # both submissions coalesced into ONE padded mesh dispatch at
        # the smallest bucket (5 real sigs -> bucket 16)
        assert host.dispatched_batches == [16]
        assert service.packed_waves == 1
        assert service.pad_sigs == 11
        # the dispatch rode the pipelined device path under the mesh
        # route label — no CPU spill, no unpadded fallback
        assert service.device_dispatches == 1
        assert service.mesh_dispatches == 1
        assert service.cpu_dispatches == 0
        assert service.peak_inflight <= service.pipeline_depth
    finally:
        service.close()


def test_sharded_gather_matches_in_specs_after_rebuild():
    """After a committee REBUILD the staged gather still produces
    coordinate rows sharded to match the shard_map in_specs (P('dp') on
    the batch axis) and numerically identical to the single-device
    verifier's rows for the new committee."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    def batch(seed):
        shared = Digest.of(bytes([seed]) * 16)
        msgs, pks, sigs = [], [], []
        for i in range(16):
            pk, sk = generate_keypair(bytes([seed]) * 32, i)
            msgs.append(shared.to_bytes())
            pks.append(pk.to_bytes())
            sigs.append(Signature.new(shared, sk).to_bytes())
        return msgs, pks, sigs

    v = ShardedBatchVerifier(mesh=default_mesh(4), min_device_batch=0)
    msgs_a, pks_a, sigs_a = batch(0x61)
    v.precompute(pks_a)
    v.prepare(msgs_a, pks_a, sigs_a)  # stage committee A's tables

    # rebuild: a NEW committee replaces the device-resident tables
    msgs_b, pks_b, sigs_b = batch(0x62)
    v.precompute(pks_b)
    valid_host, arrays = v.prepare(msgs_b, pks_b, sigs_b)
    assert valid_host.all()

    want = NamedSharding(v.mesh, P("dp"))
    for row in arrays[:4]:  # ax, ay, az, at — the gathered point rows
        assert row.sharding.is_equivalent_to(want, row.ndim)

    # numeric parity with the single-device verifier's prepare for the
    # same committee/batch (same 16-entry padded shape on both grids)
    base = BatchVerifier(min_device_batch=0, use_pallas=False)
    base.precompute(pks_b)
    _, base_arrays = base.prepare(msgs_b, pks_b, sigs_b)
    for got, ref in zip(arrays, base_arrays):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
