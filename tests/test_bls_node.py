"""BLS-committee integration: the BLS12-381 scheme driven through the
product surfaces — key files, committee config, wire format, and a full
4-node end-to-end commit over live TCP with aggregate QC verification.

This is BASELINE config 5 made product-reachable (reference boundary:
the SignatureService at crypto/src/lib.rs:232-257): ``keys --scheme
bls`` → committee file records the scheme → ``Node.new`` dispatches to
``BlsSigningService`` + ``BlsVerifier`` (one pairing equality per QC
however many votes it holds).
"""

from __future__ import annotations

import asyncio

import pytest

from hotstuff_tpu.consensus import Committee, Consensus, Parameters
from hotstuff_tpu.consensus.messages import QC, Vote
from hotstuff_tpu.crypto import Digest, PublicKey, Signature
from hotstuff_tpu.crypto.bls.service import BlsSigningService
from hotstuff_tpu.crypto.scheme import (
    bls_keygen,
    make_cpu_verifier,
    make_signing_service,
    read_secret,
)
from hotstuff_tpu.node.config import Secret, read_committee, write_committee
from hotstuff_tpu.node.node import make_verifier
from hotstuff_tpu.store import Store

from .common import async_test, fresh_base_port

SEED = b"\x07" * 32


def _bls_committee(base_port: int, n: int = 4):
    from hotstuff_tpu.crypto.scheme import bls_pop

    pairs = [bls_keygen(SEED, i) for i in range(n)]
    com = Committee.new(
        [
            (pk, 1, ("127.0.0.1", base_port + i))
            for i, (pk, _) in enumerate(pairs)
        ],
        scheme="bls",
        pops={pk: bls_pop(secret) for pk, secret in pairs},
    )
    return com, pairs


def test_key_and_committee_files_round_trip(tmp_path):
    """`keys --scheme bls` artifacts: secret file and committee file
    both record the scheme and survive the JSON round trip."""
    s = Secret.new("bls")
    path = str(tmp_path / "bls_key.json")
    s.write(path)
    back = Secret.read(path)
    assert back.scheme == "bls"
    assert back.name == s.name
    assert len(back.name.to_bytes()) == 96  # compressed G2
    assert back.secret.to_bytes() == s.secret.to_bytes()

    com, _ = _bls_committee(9_000)
    cpath = str(tmp_path / "committee.json")
    write_committee(com, cpath)
    loaded = read_committee(cpath)
    assert loaded.scheme == "bls"
    assert loaded.authorities.keys() == com.authorities.keys()


def test_scheme_mismatch_rejected(tmp_path):
    """A BLS key file cannot boot into an ed25519 committee (and vice
    versa) — Node.new refuses before any socket is bound."""
    from hotstuff_tpu.node.config import ConfigError, write_parameters
    from hotstuff_tpu.node.node import Node

    com, _ = _bls_committee(9_100)
    write_committee(com, str(tmp_path / "committee.json"))
    write_parameters(Parameters(), str(tmp_path / "parameters.json"))
    ed_secret = Secret.new("ed25519")
    ed_secret.write(str(tmp_path / "key.json"))

    async def run():
        with pytest.raises(ConfigError):
            await Node.new(
                committee_file=str(tmp_path / "committee.json"),
                key_file=str(tmp_path / "key.json"),
                store_path=str(tmp_path / "db"),
                parameters_file=str(tmp_path / "parameters.json"),
            )

    asyncio.run(run())


def test_bls_wire_round_trip_and_qc_verify():
    """Vote/QC with 96-byte keys and 48-byte signatures survive the
    length-prefixed wire codec, and QC.verify runs the ONE-pairing
    aggregate check through the VerifierBackend boundary."""
    com, pairs = _bls_committee(9_200)
    verifier = make_cpu_verifier("bls")
    block_digest = Digest.of(b"bls block")
    votes = []
    for pk, secret in pairs[:3]:  # 2f+1 = 3 of 4
        svc = BlsSigningService(secret)
        v = Vote(hash=block_digest, round=7, author=pk)
        v.signature = svc.sign_sync(v.digest())
        assert len(v.signature.to_bytes()) == 48
        votes.append(v)

    from hotstuff_tpu.consensus.wire import decode_message, encode_vote

    tag, decoded = decode_message(encode_vote(votes[0]))
    assert decoded.author == votes[0].author
    assert decoded.signature == votes[0].signature

    qc = QC(
        hash=block_digest,
        round=7,
        votes=[(v.author, v.signature) for v in votes],
    )
    qc.verify(com, verifier)  # must not raise
    # tamper: swap one signature for another author's
    bad = QC(
        hash=block_digest,
        round=7,
        votes=[
            (votes[0].author, votes[1].signature),
            (votes[1].author, votes[1].signature),
            (votes[2].author, votes[2].signature),
        ],
    )
    from hotstuff_tpu.consensus.errors import InvalidSignature

    with pytest.raises(InvalidSignature):
        bad.verify(com, verifier)


@async_test
async def test_rogue_key_committee_rejected(tmp_path):
    """Rogue-key defence: aggregate (sum-of-keys) QC verification lets a
    member who registers pk_m = a·G2 − Σ pk_honest forge QCs carrying
    honest authorities' names — possible only if the committee accepts
    keys without proof of possession.  Consensus.spawn must refuse (a) a
    PoP-less BLS committee and (b) a committee whose rogue member ships
    someone else's PoP."""
    from hotstuff_tpu.consensus.config import InvalidCommittee
    from hotstuff_tpu.crypto.bls import BlsPublicKey
    from hotstuff_tpu.crypto.bls.curve import G2Point
    from hotstuff_tpu.crypto.bls.fields import R as BLS_R
    from hotstuff_tpu.crypto.scheme import bls_pop

    base = fresh_base_port()
    pairs = [bls_keygen(SEED, 100 + i) for i in range(3)]
    # rogue key: a·G2 − (pk_0 + pk_1)
    a = 0xD15EA5E
    honest_sum = G2Point.sum(
        [BlsPublicKey.from_bytes(pk.to_bytes()).point for pk, _ in pairs[:2]]
    )
    rogue_point = G2Point.generator().mul(a) + (-honest_sum)
    rogue_pk = PublicKey(BlsPublicKey(rogue_point).to_bytes())

    async def try_spawn(com):
        store = Store(str(tmp_path / "db_rogue"))
        q: asyncio.Queue = asyncio.Queue()
        try:
            await Consensus.spawn(
                pairs[0][0],
                com,
                Parameters(),
                BlsSigningService(pairs[0][1]),
                store,
                q,
                verifier=make_cpu_verifier("bls"),
                bind_host="127.0.0.1",
            )
        finally:
            store.close()

    members = [
        (pk, 1, ("127.0.0.1", base + i)) for i, (pk, _) in enumerate(pairs)
    ] + [(rogue_pk, 1, ("127.0.0.1", base + 3))]
    # (a) no PoPs at all
    with pytest.raises(InvalidCommittee):
        await try_spawn(Committee.new(members, scheme="bls"))
    # (b) rogue member replays an honest member's PoP
    pops = {pk: bls_pop(secret) for pk, secret in pairs}
    pops[rogue_pk] = pops[pairs[0][0]]
    with pytest.raises(InvalidCommittee):
        await try_spawn(Committee.new(members, scheme="bls", pops=pops))


def test_make_verifier_scheme_dispatch():
    assert make_verifier("cpu", "bls").name == "bls-cpu"
    assert make_verifier("cpu", "ed25519").name == "cpu"
    svc = make_signing_service("bls", read_secret("bls", Secret.new("bls").secret.encode_base64()))
    assert isinstance(svc, BlsSigningService)


@async_test
async def test_bls_end_to_end_all_nodes_commit(tmp_path):
    """Four complete consensus stacks on localhost under the BLS scheme:
    every node commits a mutually consistent chain.  QC verification on
    this path is ONE pairing equality per certificate (~40 ms CPU)
    regardless of committee size — the aggregate-signature scaling
    argument (docs/BLS_TPU_DESIGN.md)."""
    base = fresh_base_port()
    com, pairs = _bls_committee(base)
    nodes = []
    for i, (name, secret) in enumerate(pairs):
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=5_000, sync_retry_delay=5_000),
            BlsSigningService(secret),
            store,
            commit_q,
            verifier=make_cpu_verifier("bls"),
            bind_host="127.0.0.1",
        )
        nodes.append((stack, commit_q, store))

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.05)

    feeder = asyncio.ensure_future(feed())
    try:
        chains = []
        for _, commit_q, _ in nodes:
            committed = [
                await asyncio.wait_for(commit_q.get(), timeout=60.0)
                for _ in range(2)
            ]
            chains.append(committed)
        digests = [[b.digest() for b in committed] for committed in chains]
        common_len = min(len(d) for d in digests)
        for d in digests[1:]:
            assert d[:common_len] == digests[0][:common_len]
    finally:
        feeder.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()
