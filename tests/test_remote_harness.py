"""Remote/cluster harness orchestration tests (reference
benchmark/benchmark/remote.py + instance.py, which ship untested).

A recording fake runner stands in for the gcloud CLI so the command
sequences — lifecycle, install/update fan-out, config upload, node/client
launch, log download — are pinned without any network access."""

from __future__ import annotations

import json

import pytest

from benchmark.instance import TpuVmManager
from benchmark.remote import RemoteBench
from benchmark.settings import DEFAULT_SETTINGS, Settings, SettingsError


def make_settings(tmp_path, count=2) -> Settings:
    cfg = json.loads(json.dumps(DEFAULT_SETTINGS))
    cfg["instances"]["count"] = count
    path = tmp_path / "settings.json"
    path.write_text(json.dumps(cfg))
    return Settings.load(str(path))


class FakeRunner:
    def __init__(self, hosts_json="[]"):
        self.commands: list[list[str]] = []
        self.hosts_json = hosts_json

    def __call__(self, cmd, timeout=600):
        self.commands.append(list(cmd))
        if "list" in cmd:
            return self.hosts_json
        return ""


def hosts_payload(n):
    return json.dumps(
        [
            {
                "name": f"projects/x/locations/y/nodes/hotstuff-tpu-{i}",
                "state": "READY",
                "networkEndpoints": [
                    {
                        "ipAddress": f"10.0.0.{i + 1}",
                        "accessConfig": {"externalIp": f"34.1.2.{i + 1}"},
                    }
                ],
            }
            for i in range(n)
        ]
    )


def test_settings_load_and_errors(tmp_path):
    s = make_settings(tmp_path)
    assert s.testbed == "hotstuff-tpu"
    assert s.accelerator_type == "v5litepod-8"
    with pytest.raises(SettingsError):
        Settings.load(str(tmp_path / "missing.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(SettingsError):
        Settings.load(str(bad))


def test_instance_lifecycle_commands(tmp_path):
    s = make_settings(tmp_path, count=2)
    runner = FakeRunner()
    mgr = TpuVmManager(s, runner=runner)
    mgr.create_instances()
    mgr.stop_instances()
    mgr.start_instances()
    mgr.terminate_instances()
    cmds = [" ".join(c) for c in runner.commands]
    assert sum("create hotstuff-tpu-" in c for c in cmds) == 2
    assert any("--accelerator-type=v5litepod-8" in c for c in cmds)
    assert sum(" stop hotstuff-tpu-" in c for c in cmds) == 2
    assert sum(" start hotstuff-tpu-" in c for c in cmds) == 2
    assert sum(" delete hotstuff-tpu-" in c for c in cmds) == 2


def test_hosts_parses_gcloud_json(tmp_path):
    s = make_settings(tmp_path, count=2)
    mgr = TpuVmManager(s, runner=FakeRunner(hosts_payload(2)))
    hosts = mgr.hosts()
    assert [h["name"] for h in hosts] == ["hotstuff-tpu-0", "hotstuff-tpu-1"]
    assert hosts[0]["internal_ip"] == "10.0.0.1"
    assert hosts[1]["external_ip"] == "34.1.2.2"
    assert all(h["state"] == "READY" for h in hosts)


def test_install_update_kill_fan_out(tmp_path):
    s = make_settings(tmp_path, count=3)
    runner = FakeRunner(hosts_payload(3))
    bench = RemoteBench(s, runner=runner)
    bench.install()
    bench.update()
    bench.kill()
    cmds = [" ".join(c) for c in runner.commands]
    assert sum("git clone" in c for c in cmds) == 3
    assert sum("git fetch origin && git checkout main" in c for c in cmds) == 3
    # bracketed pattern: must not match the remote shell running the pkill
    assert sum("pkill -f 'hotstuff_tpu[.]node'" in c for c in cmds) == 3


def test_config_generates_and_uploads(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    s = make_settings(tmp_path, count=2)
    runner = FakeRunner(hosts_payload(2))
    bench = RemoteBench(s, runner=runner)
    hosts = bench.manager.hosts()
    bench._config(hosts, nodes=4)
    # committee written locally with the hosts' internal IPs
    committee = json.loads((tmp_path / ".committee.json").read_text())
    addresses = str(committee)
    assert "10.0.0.1" in addresses and "10.0.0.2" in addresses
    # co-located nodes (2 per host) must get distinct ports per host
    ports = sorted(
        int(str(addr).rsplit(":", 1)[-1])
        for addr in json.dumps(committee).split('"')
        if str(addr).startswith("10.0.0.1:")
    )
    assert len(ports) == len(set(ports)) == 2
    # shared files once per host; key files once per node
    uploads = [c for c in runner.commands if ".committee.json" in " ".join(c)]
    assert len(uploads) == 2
    key_uploads = [c for c in runner.commands if ".node_" in " ".join(c)]
    assert len(key_uploads) == 4


class SweepRunner(FakeRunner):
    """Fake gcloud runner that synthesizes remote logs on scp download.

    Node/client log content is keyed by the rate of the most recent
    client launch so the sweep's per-config ``has_window`` gating can be
    exercised: configured 'dead' rates produce logs with no commits.
    """

    def __init__(self, hosts_json, dead_rates=()):
        super().__init__(hosts_json)
        self.dead_rates = set(dead_rates)
        self.current_rate = None

    def __call__(self, cmd, timeout=600):
        self.commands.append(list(cmd))
        if "list" in cmd:
            return self.hosts_json
        joined = " ".join(cmd)
        m = __import__("re").search(r"--rate (\d+)", joined)
        if m:
            self.current_rate = int(m.group(1))
        # scp download: first operand is "host:path", second is the
        # local destination (uploads are the reverse order)
        if "scp" in joined:
            operands = [a for a in cmd if not a.startswith("--")
                        and "scp" not in a and a not in ("gcloud", "compute",
                                                         "tpus", "tpu-vm")]
            if len(operands) == 2 and ":" in operands[0]:
                remote, local = operands
                dead = self.current_rate in self.dead_rates
                if "node-" in remote:
                    content = (
                        "2026-01-01T00:00:00.000Z INFO Timeout delay set to 5000 ms\n"
                        "2026-01-01T00:00:01.000Z INFO Created block 1 (payloads pA) -> B1\n"
                    )
                    if not dead:
                        content += (
                            "2026-01-01T00:00:01.100Z INFO Committed block 1 -> B1\n"
                        )
                else:
                    content = (
                        "2026-01-01T00:00:00.500Z INFO Transactions rate: "
                        f"{self.current_rate or 0} tx/s\n"
                        "2026-01-01T00:00:00.900Z INFO Sending sample payload pA\n"
                    )
                with open(local, "w") as f:
                    f.write(content)
        return ""


def test_remote_cli_sweep_end_to_end(tmp_path, monkeypatch):
    """Drive the PUBLIC seam — ``python -m benchmark remote`` — through
    main() with a fake runner.  Regression for the round-2 bug where
    ``self.run = runner`` in __init__ shadowed the run() sweep method and
    the CLI died with a TypeError on first use."""
    import time as _time

    from benchmark.__main__ import main

    monkeypatch.chdir(tmp_path)
    make_settings(tmp_path, count=2)  # writes tmp_path/settings.json
    runner = SweepRunner(hosts_payload(2), dead_rates={200})
    monkeypatch.setattr("benchmark.remote._default_runner", runner)
    monkeypatch.setattr(_time, "sleep", lambda s: None)

    rc = main([
        "remote", "--settings", str(tmp_path / "settings.json"),
        "--sizes", "4", "--rates", "100,200", "--duration", "1",
        "--runs", "2", "--verifier", "tpu",
    ])
    assert rc == 0

    cmds = [" ".join(c) for c in runner.commands]
    # sweep shape: 2 rates x 2 runs = 4 single runs, each with one
    # client launch and (nodes - faults) node launches
    client_launches = [c for c in cmds if "hotstuff_tpu.node.client" in c]
    assert len(client_launches) == 4
    node_launches = [c for c in cmds if "hotstuff_tpu.node -vv run" in c]
    assert len(node_launches) == 4 * 4
    # results-file discipline: rate 100 committed -> file with 2 runs;
    # rate 200 produced no commits -> has_window gating keeps it out
    ok_file = tmp_path / "results" / "bench-0-4-100-tpu.txt"
    assert ok_file.exists()
    assert ok_file.read_text().count("SUMMARY") == 2
    assert not (tmp_path / "results" / "bench-0-4-200-tpu.txt").exists()


def test_remote_run_is_a_method(tmp_path):
    """The run() sweep entry must be the class method, never an instance
    attribute (the shadowing-bug regression check at the API level)."""
    s = make_settings(tmp_path, count=1)
    bench = RemoteBench(s, runner=FakeRunner())
    assert callable(bench.run)
    assert bench.run.__func__ is RemoteBench.run


def test_run_single_boots_nodes_round_robin(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    s = make_settings(tmp_path, count=2)
    runner = FakeRunner(hosts_payload(2))
    bench = RemoteBench(s, runner=runner)
    hosts = bench.manager.hosts()
    bench._config(hosts, nodes=4)
    runner.commands.clear()
    bench._run_single(hosts, nodes=4, rate=1000, duration=30, faults=1,
                      verifier="tpu")
    cmds = [" ".join(c) for c in runner.commands]
    node_launches = [c for c in cmds if "hotstuff_tpu.node -vv run" in c]
    assert len(node_launches) == 3  # faults=1 -> one node not booted
    assert all("--verifier tpu" in c for c in node_launches)
    client_launches = [c for c in cmds if "hotstuff_tpu.node.client" in c]
    assert len(client_launches) == 1
    assert "--faults 1" in client_launches[0]
    # round-robin placement: node 0 and node 2 land on host 0
    assert "hotstuff-tpu-0" in node_launches[0]
    assert "hotstuff-tpu-1" in node_launches[1]
    assert "hotstuff-tpu-0" in node_launches[2]
