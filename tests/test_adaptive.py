"""Adaptive adversary policies (hotstuff_tpu/faults/adaptive.py).

The state-view seam: the view is READ-ONLY and deterministic, every
trigger is a pure predicate of (view, round) that fires on exactly the
protocol state it was designed to exploit and stays silent otherwise,
``wants()`` consumes ZERO rng draws on the trigger path (the fixed-draw
determinism contract), the rng checkpoint resumes the decision stream
across a restart, and ``mutate_schedule`` is a pure function of
(parent, salt) so guided-search generations are replayable.
"""

from __future__ import annotations

import time

import pytest

from hotstuff_tpu.faults.adaptive import (
    ADAPTIVE_POLICIES,
    ADAPTIVE_SHORT,
    ADAPTIVE_TRIGGERS,
    CountingRandom,
    StateView,
    ambush_trigger,
    load_rng_state,
    rng_state_path,
    save_rng_state,
    snipe_trigger,
    surf_trigger,
    sync_trigger,
)
from hotstuff_tpu.faults.adversary import POLICIES, AdversaryPlane


def _spec(policy, nodes=0, at=0.0, until=None, seed=3, base=9_940, n=4):
    return {
        "name": f"byz-{policy}",
        "seed": seed,
        "epoch_unix": time.time(),
        "nodes": {f"127.0.0.1:{base + i}": i for i in range(n)},
        "adversary": [
            {"policy": policy, "node": nodes, "at": at, "until": until}
        ],
    }


def _view(**over):
    """A hand-built fixture view: an attacker at node 0 in a 4-committee,
    round 10, no TC history, nobody syncing, static committee."""
    providers = {
        "round": lambda: 10,
        "leader": lambda r: f"auth-{r % 4}",
        "self": lambda: "auth-0",
        "last_tc_round": lambda: None,
        "timeout_ms": lambda: 5000.0,
        "credit": lambda: 32,
        "syncing": lambda: frozenset(),
        "boundaries": lambda: (),
        "incidents": lambda: 0,
    }
    providers.update(over)
    return StateView(providers)


# ---- the view is read-only and deterministic --------------------------------


def test_state_view_is_read_only():
    view = _view()
    with pytest.raises(AttributeError):
        view.round = 99
    with pytest.raises(AttributeError):
        view.extra = "steer"
    with pytest.raises(AttributeError):
        del view.round
    # nor can a policy reach the provider table to swap callbacks
    with pytest.raises(AttributeError):
        view._providers = {}


def test_state_view_reads_are_fresh_and_defaulted():
    state = {"round": 3}
    view = _view(**{"round": lambda: state["round"]})
    assert view.round == 3
    state["round"] = 7
    assert view.round == 7  # fresh pure read, no cached snapshot
    # missing providers degrade to inert defaults, never raise
    bare = StateView({})
    assert bare.round == 0
    assert bare.last_tc_round is None
    assert bare.timeout_ms == 0.0
    assert bare.credit is None
    assert bare.syncing_peers == frozenset()
    assert bare.epoch_boundaries == ()
    assert bare.incidents == 0
    assert not bare.is_leader(5)


# ---- each trigger fires on its fixture and stays silent otherwise -----------


def test_ambush_trigger_needs_fresh_tc_and_leadership():
    # round 12: auth-0 leads (12 % 4 == 0) and round 11 ended in a TC
    armed = _view(**{"last_tc_round": lambda: 11})
    assert ambush_trigger(armed, 12)
    # leading but the TC is stale
    assert not ambush_trigger(_view(**{"last_tc_round": lambda: 9}), 12)
    # fresh TC but someone else leads round 13
    assert not ambush_trigger(_view(**{"last_tc_round": lambda: 12}), 13)
    # no TC ever
    assert not ambush_trigger(_view(), 12)


def test_sync_trigger_needs_a_bootstrapping_peer():
    assert not sync_trigger(_view(), 10)
    prey = _view(**{"syncing": lambda: frozenset({"auth-2"})})
    assert sync_trigger(prey, 10)


def test_surf_trigger_spares_votes_we_collect_ourselves():
    # auth-0 collects round-12 votes (leads 12), so delaying the round-11
    # vote stalls nobody but us
    assert not surf_trigger(_view(), 11)
    assert surf_trigger(_view(), 10)  # round-11 collector is auth-3


def test_snipe_trigger_fires_only_inside_the_margin(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_ADAPT_SNIPE_MARGIN", "4")
    view = _view(**{"boundaries": lambda: (40,)})
    assert snipe_trigger(view, 36)
    assert snipe_trigger(view, 44)
    assert not snipe_trigger(view, 35)
    assert not snipe_trigger(view, 45)
    assert not snipe_trigger(_view(), 40)  # static committee: no window


# ---- wants(): the seam contract ---------------------------------------------


def _plane(policy, **kw):
    spec = _spec(policy, **kw)
    plane = AdversaryPlane(spec, ("127.0.0.1", 9_940))
    return plane, spec["epoch_unix"]


def test_adaptive_policies_ride_the_base_rule_table():
    assert set(ADAPTIVE_POLICIES) <= set(POLICIES)
    assert set(ADAPTIVE_SHORT) == set(ADAPTIVE_POLICIES)
    assert set(ADAPTIVE_TRIGGERS) == set(ADAPTIVE_POLICIES)


def test_wants_returns_token_when_trigger_fires():
    plane, epoch = _plane("ambush-leader")
    plane.bind_view({
        "round": lambda: 12,
        "leader": lambda r: f"auth-{r % 4}",
        "self": lambda: "auth-0",
        "last_tc_round": lambda: 11,
    })
    fired = plane.wants("equivocate", 12, now=epoch + 1.0)
    assert fired == "ambush"
    # silent outside the trigger state ...
    assert plane.wants("equivocate", 13, now=epoch + 1.0) is False
    # ... for other actions ...
    assert plane.wants("withhold", 12, now=epoch + 1.0) is False
    # ... and outside the policy window
    assert plane.wants("equivocate", 12, now=epoch - 1.0) is False


def test_wants_without_view_degrades_to_schedule_gating():
    plane, epoch = _plane("timeout-surfer")
    assert plane.view is None
    assert plane.wants("vote-delay", 5, now=epoch + 1.0) is False
    # a schedule-driven policy still answers plain True through wants()
    base, epoch2 = _plane("withhold")
    assert base.wants("withhold", 5, now=epoch2 + 1.0) is True


def test_trigger_evaluation_consumes_zero_rng_draws():
    """The determinism contract: the seeded decision stream is
    byte-for-byte the same whether adaptive triggers fire or not."""
    plane, epoch = _plane("reconfig-sniper")
    plane.bind_view({
        "round": lambda: 40,
        "boundaries": lambda: (40,),
    })
    before = plane.rng.draws
    assert plane.wants("reconfig", 40, now=epoch + 1.0) == "snipe"
    assert plane.wants("withhold", 40, now=epoch + 1.0) == "snipe"
    assert plane.wants("reconfig", 400, now=epoch + 1.0) is False
    assert plane.rng.draws == before == 0


def test_mark_adaptive_counts_and_ignores_schedule_true():
    plane, epoch = _plane("sync-predator")
    plane.bind_view({})
    plane.note_syncing("auth-3")
    fired = plane.wants("sync-withhold", now=epoch + 1.0)
    assert fired == "sync"
    plane.mark_adaptive(fired, 7)
    assert plane.counts["byz_adapt_sync"] == 1
    plane.mark_adaptive(True, 7)  # schedule-driven True: no-op
    assert plane.counts["byz_adapt_sync"] == 1


def test_surf_delay_stays_inside_the_timer(monkeypatch):
    plane, _ = _plane("timeout-surfer")
    assert 0.0 < plane.surf_delay_s(5.0) < 5.0
    monkeypatch.setenv("HOTSTUFF_ADAPT_SURF_FRACTION", "7.0")
    assert plane.surf_delay_s(5.0) <= 0.95 * 5.0  # clamp holds


# ---- rng continuity across restarts -----------------------------------------


def test_counting_random_checkpoint_resumes_the_stream(tmp_path):
    path = rng_state_path(str(tmp_path), 2)
    a = CountingRandom("3|adversary|2")
    reference = [a.random() for _ in range(10)]

    b = CountingRandom("3|adversary|2")
    assert [b.random() for _ in range(4)] == reference[:4]
    save_rng_state(path, b)
    assert b.draws == 4

    # "restart": a fresh generator restored from the checkpoint must
    # RESUME at draw 4, not replay from the top
    c = CountingRandom("3|adversary|2")
    assert load_rng_state(path, c) == 4
    assert [c.random() for _ in range(6)] == reference[4:]
    assert c.draws == 10
    # no checkpoint -> None, generator untouched
    assert load_rng_state(str(tmp_path / "missing.json"),
                          CountingRandom(0)) is None


def test_plane_restores_rng_from_env_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("HOTSTUFF_ADAPT_RNG_DIR", str(tmp_path))
    a, _ = _plane("timeout-surfer")
    reference = [a.rng.random() for _ in range(8)]

    b, _ = _plane("timeout-surfer")
    [b.rng.random() for _ in range(3)]
    b.count("byz_adapt_surf")  # decision boundary: checkpoints the rng

    restarted, _ = _plane("timeout-surfer")
    assert restarted.rng.draws == 3
    assert [restarted.rng.random() for _ in range(5)] == reference[3:]


# ---- mutate_schedule is a pure function of (parent, salt) -------------------


def test_mutate_schedule_deterministic_and_non_destructive():
    from hotstuff_tpu.sim import draw_schedule, mutate_schedule

    parent = draw_schedule(5, nodes=4, profile="adaptive")
    snapshot = __import__("copy").deepcopy(parent)
    a = mutate_schedule(parent, 1)
    b = mutate_schedule(parent, 1)
    assert a == b  # same salt, same child
    assert parent == snapshot  # the parent is never modified in place
    assert a["seed"] != parent["seed"]
    c = mutate_schedule(parent, 2)
    assert c != a  # different salt explores a different neighbor
    from hotstuff_tpu.sim import profile_of_events

    for child in (a, b, c):
        assert child["profile"] == profile_of_events(child["events"])
