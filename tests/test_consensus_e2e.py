"""Full-committee end-to-end tests: four complete consensus stacks on
localhost committing a mutually consistent chain (reference
consensus_tests.rs:49-102), plus a crash-fault run the reference only
exercises via the benchmark harness.
"""

import asyncio

from hotstuff_tpu.consensus import Consensus, Parameters
from hotstuff_tpu.crypto import Digest, SignatureService
from hotstuff_tpu.store import Store

from .common import async_test, committee, fresh_base_port, keys


async def _spawn_committee(
    tmp_path, base, indices, timeout_delay=1_000, transport="asyncio"
):
    com = committee(base)
    nodes = []
    for i in indices:
        name, secret = keys()[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=timeout_delay, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
            transport=transport,
        )
        nodes.append((stack, commit_q, store))
    return nodes


async def _feed_producers(nodes, interval=0.02):
    while True:
        digest = Digest.random()
        for stack, _, _ in nodes:
            await stack.tx_producer.put(digest)
        await asyncio.sleep(interval)


async def _shutdown(nodes, feeder):
    feeder.cancel()
    for stack, _, _ in nodes:
        await stack.shutdown()
    for _, _, store in nodes:
        store.close()


@async_test
async def test_end_to_end_all_nodes_commit(tmp_path):
    base = fresh_base_port()
    nodes = await _spawn_committee(tmp_path, base, range(4))
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    try:
        chains = []
        for _, commit_q, _ in nodes:
            committed = [
                await asyncio.wait_for(commit_q.get(), timeout=20.0)
                for _ in range(3)
            ]
            chains.append(committed)
        # Every node commits a non-empty chain; rounds strictly increase.
        for committed in chains:
            rounds = [b.round for b in committed]
            assert rounds == sorted(rounds)
            assert len(set(rounds)) == len(rounds)
        # Mutually consistent: same block digest at the same height.
        digests = [[b.digest() for b in committed] for committed in chains]
        common_len = min(len(d) for d in digests)
        for d in digests[1:]:
            assert d[:common_len] == digests[0][:common_len]
    finally:
        await _shutdown(nodes, feeder)


@async_test
async def test_end_to_end_one_crash_fault(tmp_path):
    """3 of 4 nodes still reach quorum (2f+1 = 3) and commit, riding the
    timeout/TC view-change path whenever the dead node leads a round."""
    base = fresh_base_port()
    nodes = await _spawn_committee(tmp_path, base, [0, 1, 2], timeout_delay=500)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    try:
        for _, commit_q, _ in nodes:
            # the chain may start with the genesis block (commit walks the
            # whole chain from round 0, like the reference's ancestor walk)
            committed = await asyncio.wait_for(commit_q.get(), timeout=30.0)
            while committed.round == 0:
                committed = await asyncio.wait_for(commit_q.get(), timeout=30.0)
            assert committed.round >= 1
    finally:
        await _shutdown(nodes, feeder)


@async_test
async def test_end_to_end_native_transport(tmp_path):
    """The full committee over the native C++ transport (one shared
    epoll reactor carrying every node's framed TCP in this process):
    all nodes commit a mutually consistent chain."""
    import pytest

    pytest.importorskip("hotstuff_tpu.network.native")
    base = fresh_base_port()
    nodes = await _spawn_committee(tmp_path, base, range(4), transport="native")
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    try:
        chains = []
        for _, commit_q, _ in nodes:
            committed = [
                await asyncio.wait_for(commit_q.get(), timeout=20.0)
                for _ in range(3)
            ]
            chains.append(committed)
        digests = [[b.digest() for b in committed] for committed in chains]
        common_len = min(len(d) for d in digests)
        for d in digests[1:]:
            assert d[:common_len] == digests[0][:common_len]
    finally:
        await _shutdown(nodes, feeder)
