"""Chaos-plane unit tests: spec parsing/sugar, window math, the
seeded-determinism contract, and the committee-wide invariant checkers
(including a deliberately UNSAFE toy history that must FAIL safety —
the checker proving it can catch what it exists to catch).
"""

from __future__ import annotations

import json
import math

from benchmark.invariants import (
    chaos_block,
    check_liveness,
    check_safety,
    commits_from_logs,
)
from hotstuff_tpu.faults.plane import (
    PASS,
    FaultPlane,
    FaultRule,
    corrupt_frame,
    expand_rules,
)
from hotstuff_tpu.faults.scenarios import SCENARIOS, build, last_heal

EPOCH = 1_000_000.0  # injected scenario t=0 (no wall-clock in these tests)


def _plane(spec: dict, self_addr="127.0.0.1:9000", nodes=4) -> FaultPlane:
    spec = dict(spec)
    spec.setdefault("epoch_unix", EPOCH)
    spec.setdefault(
        "nodes", {f"127.0.0.1:{9000 + i}": i for i in range(nodes)}
    )
    return FaultPlane(spec, self_addr, now=EPOCH)


# ---- primitives ------------------------------------------------------------


def test_corrupt_frame_flips_one_byte():
    data = bytes(range(32))
    out = corrupt_frame(data)
    assert len(out) == len(data) and out != data
    diff = [i for i in range(32) if out[i] != data[i]]
    assert diff == [16]
    assert corrupt_frame(b"") == b""


def test_rule_window_and_flapping():
    rule = FaultRule("r", at=5.0, until=11.0, src="*", dst="*", drop=1.0,
                     every=3.0, for_=1.5)
    # duty cycle: on for 1.5s of every 3s, inside [5, 11)
    assert not rule.active(4.9)
    assert rule.active(5.0) and rule.active(6.4)
    assert not rule.active(6.5) and not rule.active(7.9)
    assert rule.active(8.0)
    assert not rule.active(11.0)
    assert rule.reps() == [(5.0, 6.5), (8.0, 9.5)]


def test_expand_partition_sugar():
    link, inbound = expand_rules(
        {"rules": [{"partition": [[0, 1], [2, 3]], "at": 5, "until": 13}]}
    )
    assert not inbound
    assert len(link) == 2
    crossings = set()
    for rule in link:
        assert rule.drop == 1.0
        for s in rule.src:
            for d in rule.dst:
                crossings.add((s, d))
    # every cross-group directed pair, both directions; no intra-group
    assert crossings == {
        (0, 2), (0, 3), (1, 2), (1, 3), (2, 0), (2, 1), (3, 0), (3, 1)
    }


def test_expand_isolate_sugar():
    link, inbound = expand_rules(
        {"rules": [{"isolate": 2, "at": 1, "until": 2}]}
    )
    assert len(link) == 2 and len(inbound) == 1
    out_rule = next(r for r in link if r.src != "*")
    in_rule = next(r for r in link if r.src == "*")
    assert out_rule.src == frozenset({2}) and out_rule.dst == "*"
    assert in_rule.dst == frozenset({2})
    assert inbound[0].matches(0, 2) and not inbound[0].matches(0, 1)


# ---- plane resolution ------------------------------------------------------


def test_link_resolution_and_fast_path():
    plane = _plane(
        {"seed": 3, "rules": [{"from": [0], "to": [1], "drop": 0.5,
                               "at": 0, "until": 10}]}
    )
    assert plane.self_id == 0
    assert plane.link("127.0.0.1:9001") is not None
    # no rule ever touches 0->2: the sender gets the None fast path
    assert plane.link("127.0.0.1:9002") is None
    # unknown address (a client): never intercepted
    assert plane.link("127.0.0.1:5555") is None


def test_inbound_cut_only_for_isolated_node():
    spec = {"seed": 0, "rules": [{"isolate": 2, "at": 5, "until": 9}]}
    isolated = _plane(spec, self_addr="127.0.0.1:9002")
    other = _plane(spec, self_addr="127.0.0.1:9000")
    assert not isolated.inbound_cut(now=EPOCH + 4)
    assert isolated.inbound_cut(now=EPOCH + 6)
    assert not isolated.inbound_cut(now=EPOCH + 9)
    assert not other.inbound_cut(now=EPOCH + 6)
    assert isolated.counts["inbound_dropped"] == 1


def test_barrier_during_hard_cut():
    plane = _plane(
        {"seed": 0, "rules": [{"partition": [[0, 1], [2, 3]],
                               "at": 6, "until": 14}]}
    )
    link = plane.link("127.0.0.1:9002")
    assert not link.barrier(now=EPOCH + 5)
    assert link.barrier(now=EPOCH + 7)
    assert not link.barrier(now=EPOCH + 14)
    # decisions inside the window are hard drops
    assert link.decide(now=EPOCH + 7).drop
    assert link.decide(now=EPOCH + 20) is PASS


# ---- the determinism contract ----------------------------------------------


def _spec_probabilistic(seed):
    return {
        "seed": seed,
        "rules": [
            {"from": [0], "to": [1], "drop": 0.3, "delay_ms": 5,
             "jitter_pct": 50, "duplicate": 0.2, "corrupt": 0.1,
             "at": 0, "until": 1e9},
        ],
    }


def test_same_seed_same_decision_stream():
    stream = []
    for _ in range(2):
        plane = _plane(_spec_probabilistic(seed=42))
        link = plane.link("127.0.0.1:9001")
        stream.append([link.decide(now=EPOCH + 1) for _ in range(200)])
    assert stream[0] == stream[1]
    # and a different seed diverges (within 200 draws, overwhelmingly)
    other = _plane(_spec_probabilistic(seed=43)).link("127.0.0.1:9001")
    assert [other.decide(now=EPOCH + 1) for _ in range(200)] != stream[0]


def test_decision_n_is_independent_of_window_state():
    """decide() always consumes exactly 4 draws, so the n-th decision is
    the same whether earlier frames fell inside or outside a window —
    and barrier() consumes none at all."""
    spec = {
        "seed": 7,
        "rules": [{"from": [0], "to": [1], "drop": 0.5, "at": 10,
                   "until": 1e9}],
    }
    a = _plane(spec).link("127.0.0.1:9001")
    b = _plane(spec).link("127.0.0.1:9001")
    # a: 50 decisions before the window opens (all PASS), b: 50 inside;
    # interleave barrier() probes on a to prove they are draw-free
    for _ in range(50):
        assert a.decide(now=EPOCH + 1) is PASS
        a.barrier(now=EPOCH + 1)
        b.decide(now=EPOCH + 11)
    tail_a = [a.decide(now=EPOCH + 11) for _ in range(50)]
    tail_b = [b.decide(now=EPOCH + 11) for _ in range(50)]
    assert tail_a == tail_b
    assert a.seq == b.seq == 100


def test_per_link_streams_are_independent():
    spec = {
        "seed": 9,
        "rules": [{"from": "*", "to": "*", "drop": 0.5, "at": 0,
                   "until": 1e9}],
    }
    p = _plane(spec)
    d1 = [p.link("127.0.0.1:9001").decide(now=EPOCH + 1) for _ in range(64)]
    d2 = [p.link("127.0.0.1:9002").decide(now=EPOCH + 1) for _ in range(64)]
    assert d1 != d2  # per-directed-link RNG, not a shared stream


def test_load_inline_json_and_file(tmp_path):
    spec = {"name": "x", "seed": 1, "nodes": {"127.0.0.1:9000": 0},
            "rules": [], "epoch_unix": EPOCH}
    inline = FaultPlane.load(json.dumps(spec), "127.0.0.1:9000", now=EPOCH)
    path = tmp_path / "spec.json"
    path.write_text(json.dumps(spec))
    from_file = FaultPlane.load(str(path), ("127.0.0.1", 9000), now=EPOCH)
    assert inline.self_id == from_file.self_id == 0
    assert inline.name == from_file.name == "x"


def test_stale_epoch_falls_back_to_boot():
    spec = {"seed": 0, "nodes": {"127.0.0.1:9000": 0}, "rules": [],
            "epoch_unix": EPOCH - 10_000}
    plane = FaultPlane(spec, "127.0.0.1:9000", now=EPOCH)
    assert plane.epoch == EPOCH


def test_window_edges_dedup_and_flapping():
    plane = _plane(
        {"seed": 0, "rules": [{"partition": [[0, 1], [2, 3]],
                               "at": 6, "until": 14, "label": "p"}]}
    )
    # partition sugar expands to 2 rules; edges dedup to one open/close
    assert plane.window_edges() == [(6.0, "open", "p"), (14.0, "close", "p")]
    flappy = _plane(build("flapping-link", seed=0))
    edges = flappy.window_edges()
    # 12s window, one rep every 3s, per direction label
    opens = [e for e in edges if e[1] == "open" and e[2] == "flap-0-1"]
    assert len(opens) == 4


# ---- canned scenarios ------------------------------------------------------


def test_all_scenarios_build_and_heal():
    for name in SCENARIOS:
        spec = build(name, nodes=4, seed=7)
        assert spec["seed"] == 7 and spec["name"] == name
        heal = last_heal(spec)
        assert 0 <= heal < math.inf
        if (
            not name.startswith("byz-") or name == "byz-withhold"
        ) and name != "reconfig-rotate":
            # network faults and vote withholding impair liveness and
            # must heal strictly after t=0; the other byz scenarios are
            # pure attacks (never impairing) and heal at 0.0, as does
            # reconfig-rotate — a fault-free rotation (its siblings
            # add a partition or a crash and do heal later)
            assert heal > 0
        assert spec["liveness"]["resume_within_s"] > 0
        # every scenario resolves to a working plane for node 0
        plane = _plane(spec, self_addr="127.0.0.1:9000")
        assert plane.self_id == 0


def test_build_unknown_scenario():
    import pytest

    with pytest.raises(ValueError, match="unknown scenario"):
        build("no-such-thing")


def test_last_heal_unbounded():
    assert math.isinf(
        last_heal({"rules": [{"from": [0], "to": [1], "drop": 1.0,
                              "at": 5}]})
    )
    assert math.isinf(
        last_heal({"rules": [], "crashes": [{"node": 1, "at": 3}]})
    )
    # a delay-only rule with no `until` still never heals
    assert last_heal({"rules": [{"from": [0], "to": [1], "delay_ms": 10,
                                 "at": 0, "until": 4}]}) == 4.0


# ---- invariants ------------------------------------------------------------


def test_safety_passes_on_consistent_history():
    ok, violations = check_safety({
        "node-0": [(10.0, 1, "A"), (11.0, 2, "B")],
        "node-1": [(10.1, 1, "A"), (11.2, 2, "B")],
        # a restart legitimately RE-commits the same block
        "node-2": [(10.0, 1, "A"), (15.0, 1, "A"), (15.1, 2, "B")],
    })
    assert ok and not violations


def test_safety_fails_on_unsafe_toy_history():
    """The demonstrated-FAIL case: two halves of a (hypothetically
    broken) committee commit DIFFERENT blocks at the same round — the
    checker must flag it, or every PASS it prints is meaningless."""
    ok, violations = check_safety({
        "node-0": [(10.0, 5, "AAAA")],
        "node-1": [(10.0, 5, "AAAA")],
        "node-2": [(10.2, 5, "ZZZZ")],
        "node-3": [(10.2, 5, "ZZZZ")],
    })
    assert not ok
    assert any("conflicting commits at round 5" in v for v in violations)
    # single-node equivocation is also flagged
    ok, violations = check_safety({"node-0": [(1.0, 3, "A"), (2.0, 3, "B")]})
    assert not ok and "two blocks" in violations[0]


def test_liveness_bounds():
    history = {
        "node-0": [(100.0, 1, "A"), (120.0, 9, "B")],
        "node-1": [(100.1, 1, "A"), (120.5, 9, "B")],
    }
    ok, _, details = check_liveness(history, heal_unix=110.0,
                                    resume_within_s=15.0, max_round_gap=50)
    assert ok and abs(details["resumed_after_s"] - 10.0) < 1e-6
    assert details["round_gap"] == 8
    ok, violations, _ = check_liveness(history, heal_unix=110.0,
                                       resume_within_s=5.0)
    assert not ok and "resumed" in violations[0]
    ok, violations, _ = check_liveness(history, heal_unix=110.0,
                                       resume_within_s=15.0, max_round_gap=4)
    assert not ok and "round gap" in violations[0]
    ok, violations, _ = check_liveness(history, heal_unix=130.0)
    assert not ok and "no new rounds" in violations[0]
    ok, violations, _ = check_liveness({}, heal_unix=0.0)
    assert not ok and "no commits" in violations[0]


def test_chaos_block_rendering():
    block = chaos_block("split-brain", 7, True, [], True, [],
                        {"resumed_after_s": 2.5, "round_gap": 12},
                        heal_rel=14.0)
    assert " + CHAOS:" in block
    assert "Scenario: split-brain (seed 7)" in block
    assert "Safety (no conflicting commits): PASS" in block
    assert "resumed 2.5s after heal, round gap 12" in block
    block = chaos_block("x", 0, False, ["boom"], None, [], {})
    assert "FAIL" in block and "! boom" in block
    assert "n/a (scenario never heals)" in block


def test_commits_from_logs(tmp_path):
    (tmp_path / "node-0.log").write_text(
        "2026-01-01T00:00:01.000Z [INFO] core Committed block 2 -> BLK1\n"
        "2026-01-01T00:00:02.000Z [INFO] core Committed block 3 -> BLK2\n"
    )
    (tmp_path / "node-1.log").write_text(
        "2026-01-01T00:00:01.500Z [INFO] core Committed block 2 -> BLK1\n"
    )
    commits = commits_from_logs(str(tmp_path))
    assert set(commits) == {"node-0", "node-1"}
    assert [(r, d) for _, r, d in commits["node-0"]] == [
        (2, "BLK1"), (3, "BLK2")
    ]
    ok, _ = check_safety(commits)
    assert ok


def test_state_root_agreement():
    from benchmark.invariants import check_state_root_agreement

    # agreement: same root per version, even when a snapshot-rejoined
    # node skips versions and a restarted node re-reports one
    ok, viol, details = check_state_root_agreement({
        "node-0": [(1, "R1", 1), (2, "R2", 2), (3, "R3", 3)],
        "node-1": [(1, "R1", 1), (2, "R2", 2), (2, "R2", 2), (3, "R3", 3)],
        "node-2": [(3, "R3", 3)],  # snapshot rejoin: versions 1-2 skipped
    })
    assert ok and not viol
    assert details["versions_compared"] == 3
    assert details["max_version"] == 3

    # divergence at one version is a violation naming both parties
    ok, viol, _ = check_state_root_agreement({
        "node-0": [(1, "R1", 1), (2, "R2", 2)],
        "node-1": [(1, "R1", 1), (2, "SHADOW", 2)],
    })
    assert not ok
    assert "version 2" in viol[0]
    assert "SHADOW" in viol[0]

    # a node contradicting ITSELF at a version is also a violation
    ok, viol, _ = check_state_root_agreement({
        "node-0": [(1, "R1", 1), (1, "R1b", 1)],
    })
    assert not ok and "two state roots" in viol[0]

    # no roots at all -> n/a, not a failure
    ok, viol, details = check_state_root_agreement({"node-0": []})
    assert ok is None and not viol
    assert details["nodes_reporting"] == 0


def test_state_roots_from_logs_and_block_rendering(tmp_path):
    from benchmark.invariants import (
        check_state_root_agreement,
        state_roots_from_logs,
    )

    (tmp_path / "node-0.log").write_text(
        "2026-01-01T00:00:01.000Z [INFO] core State root 1 -> AA (round 2)\n"
        "2026-01-01T00:00:02.000Z [INFO] core State root 2 -> BB (round 3)\n"
    )
    (tmp_path / "node-1.log").write_text(
        "2026-01-01T00:00:01.200Z [INFO] core State root 1 -> AA (round 2)\n"
        "2026-01-01T00:00:02.300Z [INFO] core State root 2 -> XX (round 3)\n"
    )
    roots = state_roots_from_logs(str(tmp_path))
    assert roots["node-0"] == [(1, "AA", 2), (2, "BB", 3)]
    ok, viol, details = check_state_root_agreement(roots)
    assert not ok and len(viol) == 1

    block = chaos_block("x", 0, True, [], None, [], {},
                        state_ok=ok, state_violations=viol,
                        state_details=details)
    assert "State-root agreement: FAIL" in block
    assert "state-root divergence at version 2" in block
    block = chaos_block("x", 0, True, [], None, [], {},
                        state_ok=None, state_violations=[],
                        state_details={"versions_compared": 0})
    assert "State-root agreement: n/a" in block
    # no state_details at all -> line omitted entirely
    block = chaos_block("x", 0, True, [], None, [], {})
    assert "State-root" not in block


# ---- the chaos runner (config only; full runs live in the slow tier) -------


def test_chaos_bench_extends_duration_to_cover_heal(monkeypatch, tmp_path):
    from benchmark.chaos import BOOT_MARGIN_S, ChaosBench

    monkeypatch.chdir(tmp_path)
    bench = ChaosBench(scenario="split-brain", seed=7, duration=5.0)
    spec = bench.spec
    need = last_heal(spec) + spec["liveness"]["resume_within_s"] + 4.0
    assert bench.duration == need
    # config writes the spec with the committee map and a future epoch
    bench._config()
    assert "HOTSTUFF_FAULTS" in bench.extra_env
    with open(bench.extra_env["HOTSTUFF_FAULTS"]) as f:
        written = json.load(f)
    assert written["nodes"] == {
        f"127.0.0.1:{bench.base_port + i}": i for i in range(4)
    }
    assert written["epoch_unix"] == bench._epoch
    assert bench._epoch > written["epoch_unix"] - BOOT_MARGIN_S - 1
