"""Byzantine-member end-to-end: liveness under active attack.

The reference's test suite only covers crash faults ("don't boot f
nodes" — SURVEY.md §4 lists the absence of Byzantine-behavior tests as
a gap).  Here one committee slot is held by an ACTIVE adversary that
floods the three honest nodes with:

- votes carrying garbage signatures under its OWN identity for random
  block digests (the per-round digest-cell exhaustion attack from
  round 1's ADVICE — unauthenticated aggregation state);
- spoofed votes naming HONEST authorities with garbage signatures (the
  vote-suppression race the aggregator's eviction/replacement logic
  defends against);
- timeouts with garbage signatures (eager-verify path);
- structurally malformed frames (decode error handling).

Quorum is 3 of 4, so liveness requires ALL THREE honest nodes' votes to
keep landing while the flood runs: if any spoofed garbage suppresses an
honest vote for a full round, rounds stall into view changes and the
20 s commit deadline fails.
"""

from __future__ import annotations

import asyncio
import os

from hotstuff_tpu.consensus import Consensus, Parameters, Vote
from hotstuff_tpu.consensus.wire import encode_timeout, encode_vote
from hotstuff_tpu.consensus.messages import QC, Timeout
from hotstuff_tpu.crypto import Digest, Signature, SignatureService
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .common import async_test, committee, fresh_base_port, keys


async def _byzantine_flood(com, my_pk, honest_pks, stop: asyncio.Event):
    """The adversary loop: one burst of garbage per 25 ms."""
    sender = SimpleSender()
    addresses = [addr for _, addr in com.broadcast_addresses(my_pk)]
    rnd = 1
    try:
        while not stop.is_set():
            # (a) own-identity garbage votes for random digests
            for _ in range(3):
                v = Vote(
                    hash=Digest.random(),
                    round=rnd,
                    author=my_pk,
                    signature=Signature(os.urandom(64)),
                )
                await sender.broadcast(addresses, encode_vote(v))
            # (b) spoofed votes naming honest authorities
            for pk in honest_pks:
                v = Vote(
                    hash=Digest.random(),
                    round=rnd,
                    author=pk,
                    signature=Signature(os.urandom(64)),
                )
                await sender.broadcast(addresses, encode_vote(v))
            # (c) garbage timeouts
            t = Timeout(
                high_qc=QC.genesis(),
                round=rnd,
                author=my_pk,
                signature=Signature(os.urandom(64)),
            )
            await sender.broadcast(addresses, encode_timeout(t))
            # (d) malformed frames
            await sender.broadcast(addresses, os.urandom(48))
            rnd += 1
            await asyncio.sleep(0.025)
    finally:
        sender.close()


@async_test
async def test_honest_quorum_commits_under_byzantine_flood(tmp_path):
    base = fresh_base_port()
    com = committee(base)
    fixture = keys()
    byz_index = 3  # the slot that never runs a real node
    honest = [i for i in range(4) if i != byz_index]

    nodes = []
    for i in honest:
        name, secret = fixture[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=2_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
        )
        nodes.append((stack, commit_q, store))

    stop = asyncio.Event()
    flood = asyncio.ensure_future(
        _byzantine_flood(
            com,
            fixture[byz_index][0],
            [fixture[i][0] for i in honest],
            stop,
        )
    )

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.03)

    feeder = asyncio.ensure_future(feed())
    try:
        chains = []
        for _, commit_q, _ in nodes:
            committed = []
            while len(committed) < 2:
                b = await asyncio.wait_for(commit_q.get(), timeout=30.0)
                if b.round > 0:
                    committed.append(b)
            chains.append(committed)
        # consistent prefixes across the honest quorum
        digests = [[b.digest() for b in chain] for chain in chains]
        common_len = min(len(d) for d in digests)
        for d in digests[1:]:
            assert d[:common_len] == digests[0][:common_len]
        # and no honest node ever committed a block authored by the
        # adversary (it never made a valid proposal)
        byz_pk = fixture[byz_index][0]
        for chain in chains:
            assert all(b.author != byz_pk for b in chain)
    finally:
        stop.set()
        feeder.cancel()
        flood.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()
