"""Byzantine-member end-to-end: liveness under active attack.

The reference's test suite only covers crash faults ("don't boot f
nodes" — SURVEY.md §4 lists the absence of Byzantine-behavior tests as
a gap).  Here one committee slot is held by an ACTIVE adversary that
floods the three honest nodes with:

- votes carrying garbage signatures under its OWN identity for random
  block digests (the per-round digest-cell exhaustion attack from
  round 1's ADVICE — unauthenticated aggregation state);
- spoofed votes naming HONEST authorities with garbage signatures (the
  vote-suppression race the aggregator's eviction/replacement logic
  defends against);
- timeouts with garbage signatures (eager-verify path);
- structurally malformed frames (decode error handling).

Quorum is 3 of 4, so liveness requires ALL THREE honest nodes' votes to
keep landing while the flood runs: if any spoofed garbage suppresses an
honest vote for a full round, rounds stall into view changes and the
20 s commit deadline fails.
"""

from __future__ import annotations

import asyncio
import os

from hotstuff_tpu.consensus import Consensus, Parameters, Vote
from hotstuff_tpu.consensus.wire import encode_timeout, encode_vote
from hotstuff_tpu.consensus.messages import QC, Timeout
from hotstuff_tpu.crypto import Digest, Signature, SignatureService
from hotstuff_tpu.network import SimpleSender
from hotstuff_tpu.store import Store

from .common import async_test, committee, fresh_base_port, keys


async def _byzantine_flood(com, my_pk, honest_pks, stop: asyncio.Event):
    """The adversary loop: one burst of garbage per 25 ms."""
    sender = SimpleSender()
    addresses = [addr for _, addr in com.broadcast_addresses(my_pk)]
    rnd = 1
    try:
        while not stop.is_set():
            # (a) own-identity garbage votes for random digests
            for _ in range(3):
                v = Vote(
                    hash=Digest.random(),
                    round=rnd,
                    author=my_pk,
                    signature=Signature(os.urandom(64)),
                )
                await sender.broadcast(addresses, encode_vote(v))
            # (b) spoofed votes naming honest authorities
            for pk in honest_pks:
                v = Vote(
                    hash=Digest.random(),
                    round=rnd,
                    author=pk,
                    signature=Signature(os.urandom(64)),
                )
                await sender.broadcast(addresses, encode_vote(v))
            # (c) garbage timeouts
            t = Timeout(
                high_qc=QC.genesis(),
                round=rnd,
                author=my_pk,
                signature=Signature(os.urandom(64)),
            )
            await sender.broadcast(addresses, encode_timeout(t))
            # (d) malformed frames
            await sender.broadcast(addresses, os.urandom(48))
            rnd += 1
            await asyncio.sleep(0.025)
    finally:
        sender.close()


@async_test
async def test_honest_quorum_commits_under_byzantine_flood(tmp_path):
    base = fresh_base_port()
    com = committee(base)
    fixture = keys()
    byz_index = 3  # the slot that never runs a real node
    honest = [i for i in range(4) if i != byz_index]

    nodes = []
    for i in honest:
        name, secret = fixture[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=2_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
        )
        nodes.append((stack, commit_q, store))

    stop = asyncio.Event()
    flood = asyncio.ensure_future(
        _byzantine_flood(
            com,
            fixture[byz_index][0],
            [fixture[i][0] for i in honest],
            stop,
        )
    )

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.03)

    feeder = asyncio.ensure_future(feed())
    try:
        chains = []
        for _, commit_q, _ in nodes:
            committed = []
            while len(committed) < 2:
                b = await asyncio.wait_for(commit_q.get(), timeout=30.0)
                if b.round > 0:
                    committed.append(b)
            chains.append(committed)
        # consistent prefixes across the honest quorum
        digests = [[b.digest() for b in chain] for chain in chains]
        common_len = min(len(d) for d in digests)
        for d in digests[1:]:
            assert d[:common_len] == digests[0][:common_len]
        # and no honest node ever committed a block authored by the
        # adversary (it never made a valid proposal)
        byz_pk = fixture[byz_index][0]
        for chain in chains:
            assert all(b.author != byz_pk for b in chain)
    finally:
        stop.set()
        feeder.cancel()
        flood.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()


@async_test
async def test_safety_under_equivocating_leader(tmp_path):
    """The canonical BFT attack: when the Byzantine member's turn to
    lead comes, it assembles a real QC from the round's votes (which
    honest voters address to it, the next leader), then proposes TWO
    conflicting valid blocks — block A to two honest nodes (plus its
    own vote for A, so A can reach quorum) and block B to the third.
    Safety demand: the honest nodes never commit divergent chains —
    whatever happens to the minority branch, committed prefixes agree.
    """
    from hotstuff_tpu.consensus.messages import Block
    from hotstuff_tpu.consensus.wire import (
        TAG_PROPOSE,
        TAG_VOTE,
        decode_message,
        encode_propose,
    )
    from hotstuff_tpu.network import Receiver

    base = fresh_base_port()
    com = committee(base)
    fixture = keys()
    byz_index = 3
    byz_pk, byz_sk = fixture[byz_index]
    honest = [i for i in range(4) if i != byz_index]

    nodes = []
    for i in honest:
        name, secret = fixture[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=1_500, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
        )
        nodes.append((stack, commit_q, store))

    # --- the adversary: listens on its committee slot, collects votes
    # addressed to it (it IS the next leader for rounds r-1 where it
    # leads r), and equivocates ONCE when it can form a QC.
    sender = SimpleSender()
    equivocated = asyncio.Event()
    votes_by_digest: dict = {}
    sorted_keys = com.sorted_keys()

    class ByzHandler:
        async def dispatch(self, writer, frame: bytes) -> None:
            try:
                tag, payload = decode_message(frame)
            except Exception:
                return
            if tag == TAG_PROPOSE:
                try:
                    await writer.send(b"Ack")
                except Exception:
                    pass
                return
            if tag != TAG_VOTE or equivocated.is_set():
                return
            vote = payload
            votes_by_digest.setdefault(
                (vote.hash, vote.round), []
            ).append(vote)
            bucket = votes_by_digest[(vote.hash, vote.round)]
            # the round the adversary leads next
            lead_round = vote.round + 1
            if sorted_keys[lead_round % 4] != byz_pk:
                return
            authors = {v.author for v in bucket}
            if len(authors) < 3:
                return
            equivocated.set()
            qc = QC(
                hash=vote.hash,
                round=vote.round,
                votes=[(v.author, v.signature) for v in bucket[:3]],
            )
            block_a = Block(
                qc=qc, author=byz_pk, round=lead_round,
                payloads=(Digest.of(b"equivocation A"),),
            )
            block_a.signature = Signature.new(block_a.digest(), byz_sk)
            block_b = Block(
                qc=qc, author=byz_pk, round=lead_round,
                payloads=(Digest.of(b"equivocation B"),),
            )
            block_b.signature = Signature.new(block_b.digest(), byz_sk)
            addr = {pk: a for pk, a in com.broadcast_addresses(byz_pk)}
            # A -> honest[0], honest[1]; B -> honest[2]
            for i in (0, 1):
                await sender.send(
                    addr[fixture[honest[i]][0]], encode_propose(block_a)
                )
            await sender.send(
                addr[fixture[honest[2]][0]], encode_propose(block_b)
            )
            # vote for A, addressed to the NEXT round's leader
            my_vote = Vote.for_block(block_a, byz_pk)
            my_vote.signature = Signature.new(my_vote.digest(), byz_sk)
            nxt = sorted_keys[(lead_round + 1) % 4]
            await sender.send(addr[nxt], encode_vote(my_vote))

    receiver = Receiver("127.0.0.1", base + byz_index, ByzHandler())
    await receiver.spawn()

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.03)

    feeder = asyncio.ensure_future(feed())
    try:
        chains = []
        for _, commit_q, _ in nodes:
            committed = []
            while len(committed) < 4:
                b = await asyncio.wait_for(commit_q.get(), timeout=40.0)
                if b.round > 0:
                    committed.append(b)
            chains.append(committed)
        assert equivocated.is_set(), "the adversary never got to equivocate"
        # SAFETY: committed prefixes agree across the honest committee
        digests = [[b.digest() for b in chain] for chain in chains]
        common_len = min(len(d) for d in digests)
        for d in digests[1:]:
            assert d[:common_len] == digests[0][:common_len]
        # at most ONE of the two equivocating payloads may ever commit
        committed_payloads = {
            p for chain in chains for b in chain for p in b.payloads
        }
        assert not (
            Digest.of(b"equivocation A") in committed_payloads
            and Digest.of(b"equivocation B") in committed_payloads
        )
    finally:
        feeder.cancel()
        await receiver.shutdown()
        sender.close()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()
