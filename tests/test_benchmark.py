"""Benchmark-harness tests: the log-schema contract and aggregation.

The reference's harness was stale against its own log format (SURVEY.md
§2.6); these tests pin OUR contract: the parser's regexes match exactly
what the framework logs.
"""

import os

from benchmark.aggregate import parse_result_file
from benchmark.logs import LogParser

NODE_LOG = """\
2026-01-01T00:00:00.000Z [INFO] node Timeout delay set to 5000 ms
2026-01-01T00:00:01.000Z [INFO] hotstuff_tpu.consensus.proposer.aaaa Created block 2 (payloads PAY1) -> BLK1
2026-01-01T00:00:01.100Z [INFO] hotstuff_tpu.consensus.core.aaaa Committed block 2 -> BLK1
2026-01-01T00:00:02.000Z [INFO] hotstuff_tpu.consensus.proposer.aaaa Created block 3 (payloads PAY2,PAY3) -> BLK2
2026-01-01T00:00:02.300Z [INFO] hotstuff_tpu.consensus.core.aaaa Committed block 3 -> BLK2
2026-01-01T00:00:03.000Z [WARNING] hotstuff_tpu.consensus.core.aaaa Timeout reached for round 4
"""

NODE_LOG_B = """\
2026-01-01T00:00:01.050Z [INFO] hotstuff_tpu.consensus.core.bbbb Committed block 2 -> BLK1
2026-01-01T00:00:02.200Z [INFO] hotstuff_tpu.consensus.core.bbbb Committed block 3 -> BLK2
"""

CLIENT_LOG = """\
2026-01-01T00:00:00.500Z [INFO] Transactions rate: 1000 tx/s
2026-01-01T00:00:00.900Z [INFO] Sending sample payload PAY1
2026-01-01T00:00:01.900Z [INFO] Sending sample payload PAY2
"""


def test_log_parser_metrics():
    parser = LogParser([NODE_LOG, NODE_LOG_B], [CLIENT_LOG])
    tps, duration = parser.consensus_throughput()
    # window: first Created (1.0) -> last commit (2.2 on node B, earliest
    # per block: BLK2 at 2.2), 3 unique payloads over 2 blocks
    assert abs(duration - 1.2) < 1e-6
    assert abs(tps - 3 / 1.2) < 1e-6
    # latency: BLK1 1.0->1.05 (earliest commit), BLK2 2.0->2.2
    assert abs(parser.consensus_latency() - 0.125) < 1e-6
    # e2e latency: PAY1 0.9->1.05, PAY2 1.9->2.2
    assert abs(parser.end_to_end_latency() - 0.225) < 1e-6
    assert parser.timeouts == 1
    assert parser.input_rate == 1000
    assert parser.timeout_delay == 5000


def test_log_parser_matches_real_client_format():
    """The contract lines as actually produced by the client module."""
    import logging
    from io import StringIO

    stream = StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s.%(msecs)03dZ [%(levelname)s] %(message)s",
            datefmt="%Y-%m-%dT%H:%M:%S",
        )
    )
    log = logging.getLogger("contract-test")
    log.addHandler(handler)
    log.setLevel(logging.INFO)
    log.info("Transactions rate: %d tx/s", 777)
    log.info("Sending sample payload %s", "AbCd+/==")
    handler.flush()

    parser = LogParser([NODE_LOG], [stream.getvalue()])
    assert parser.input_rate == 777
    assert "AbCd+/==" in parser.samples


def test_consensus_latency_excludes_empty_blocks():
    """Latency population parity with the reference (its latency is per
    batch digest): deliberately-EMPTY 2-chain-driver blocks wait for the
    producer's next burst before their successor commits them — pacing,
    not consensus work — and must not inflate the mean."""
    node_log = (
        "2026-01-01T00:00:01.000Z [INFO] x Created block 2 (payloads PAY1) -> BLK1\n"
        "2026-01-01T00:00:01.010Z [INFO] x Committed block 2 -> BLK1\n"
        "2026-01-01T00:00:01.020Z [INFO] x Created block 3 (payloads ) -> EMPTY1\n"
        "2026-01-01T00:00:01.500Z [INFO] x Committed block 3 -> EMPTY1\n"
    )
    parser = LogParser([node_log], [])
    # only BLK1 (10 ms) counts; EMPTY1's 480 ms pacing lag is excluded
    assert abs(parser.consensus_latency() - 0.010) < 1e-6


def test_bps_reported_from_tx_size():
    """Byte-throughput parity (VERDICT r3 item 4): the client logs the
    transaction size; the SUMMARY reports consensus/e2e BPS like the
    reference (logs.py:147-169)."""
    client_log = (
        "2026-01-01T00:00:00.500Z [INFO] Transactions rate: 1000 tx/s\n"
        "2026-01-01T00:00:00.600Z [INFO] Transactions size: 512 B\n"
        "2026-01-01T00:00:00.900Z [INFO] Sending sample payload PAY1\n"
    )
    parser = LogParser([NODE_LOG, NODE_LOG_B], [client_log])
    assert parser.tx_size == 512
    summary = parser.result(faults=0, nodes=2, verifier="cpu")
    tps, _ = parser.consensus_throughput()
    assert f"Consensus BPS: {round(tps * 512):,} B/s" in summary
    assert "Transaction size: 512 B" in summary
    # digest-only runs must say so, not claim 0 B/s
    parser2 = LogParser([NODE_LOG], [CLIENT_LOG])
    assert "Consensus BPS: n/a (digest-only payloads)" in parser2.result()


def test_no_sample_committed_reports_na_not_zero():
    """Result honesty (VERDICT r3 item 5): when no sample payload lands
    in the window, the e2e latency must read n/a — a 0 ms would read as
    a (great) measurement."""
    client_log = (
        "2026-01-01T00:00:00.500Z [INFO] Transactions rate: 1000 tx/s\n"
        "2026-01-01T00:00:00.900Z [INFO] Sending sample payload NEVERCOMMITTED\n"
    )
    parser = LogParser([NODE_LOG], [client_log])
    assert parser.end_to_end_latency() is None
    summary = parser.result(faults=0, nodes=1, verifier="cpu")
    assert "End-to-end latency: n/a" in summary
    assert "End-to-end latency: 0 ms" not in summary


def test_result_summary_and_aggregate(tmp_path):
    parser = LogParser([NODE_LOG, NODE_LOG_B], [CLIENT_LOG])
    summary = parser.result(faults=0, nodes=2, verifier="cpu")
    assert "Consensus TPS:" in summary
    path = str(tmp_path / "bench-0-2-1000-cpu.txt")
    with open(path, "w") as f:
        f.write(summary)
        f.write(summary)  # two runs aggregate
    metrics = parse_result_file(path)
    assert metrics["consensus_tps"] > 0
    assert metrics["consensus_tps_stdev"] == 0.0


def test_created_line_contract_matches_proposer_emitter():
    """Anti-drift: format the proposer's actual Created log template and
    feed it through the parser (benchmark/logs.py contract)."""
    line = (
        "2026-01-01T00:00:01.000Z [INFO] hotstuff_tpu.consensus.proposer.x "
        + "Created block %d (payloads %s) -> %s"
        % (7, ",".join(["dA+/b==", "c99x=="]), "BLOCKD==")
    )
    commit = (
        "2026-01-01T00:00:01.500Z [INFO] hotstuff_tpu.consensus.core.x "
        "Committed block 7 -> BLOCKD=="
    )
    parser = LogParser([line + "\n" + commit + "\n"], [])
    assert parser.block_payloads["BLOCKD=="] == ("dA+/b==", "c99x==")
    assert parser.committed_payloads() == 2
    # empty-payload blocks parse too
    line0 = (
        "2026-01-01T00:00:02.000Z [INFO] hotstuff_tpu.consensus.proposer.x "
        "Created block 8 (payloads ) -> EMPTY=="
    )
    parser = LogParser([line0 + "\n"], [])
    assert parser.block_payloads["EMPTY=="] == ()


def test_plots_render_from_synthetic_groups(tmp_path):
    """All three plots (latency-vs-throughput, tps-vs-committee,
    robustness — reference Ploter parity) render from aggregated
    groups without a display."""
    import pytest

    pytest.importorskip("matplotlib")
    from benchmark.plot import (
        plot_latency_vs_throughput,
        plot_robustness,
        plot_tps_vs_committee,
    )

    groups = {
        (0, 4, 1000, "cpu"): {"consensus_tps": 950.0, "consensus_latency_ms": 20.0},
        (0, 4, 5000, "cpu"): {"consensus_tps": 4600.0, "consensus_latency_ms": 40.0},
        (0, 8, 1000, "tpu"): {"consensus_tps": 900.0, "consensus_latency_ms": 55.0},
        (1, 4, 1000, "cpu"): {"consensus_tps": 70.0, "consensus_latency_ms": 30.0},
        (1, 4, 5000, "cpu"): {"consensus_tps": 300.0, "consensus_latency_ms": 90.0},
    }
    for fn, name in (
        (plot_latency_vs_throughput, "lat.png"),
        (plot_tps_vs_committee, "tps.png"),
        (plot_robustness, "rob.png"),
    ):
        out = fn(groups, str(tmp_path / name))
        assert (tmp_path / name).exists() and (tmp_path / name).stat().st_size > 0


def test_log_parser_verify_stats_routing_split():
    """Cumulative per-service routing counters: the LAST line per
    service tag wins, tags sum across logs — the device-routing proof
    lines in the SUMMARY (VERDICT r5 item 1)."""
    node_log = (
        "2026-01-01T00:00:01.000Z [INFO] Verify service stats [tpu#1]: "
        "dispatches=5 device=3 device_sigs=100 cpu_sigs=50 "
        "deadline_misses=0 ewma_ms=1.5\n"
        "2026-01-01T00:00:06.000Z [INFO] Verify service stats [tpu#1]: "
        "dispatches=20 device=15 device_sigs=900 cpu_sigs=100 "
        "deadline_misses=1 ewma_ms=2.0\n"
        "2026-01-01T00:00:06.200Z [INFO] Verify service stats [tpu#2]: "
        "dispatches=4 device=0 device_sigs=0 cpu_sigs=300 "
        "deadline_misses=0 ewma_ms=120.0\n"
        + NODE_LOG
    )
    parser = LogParser([node_log], [CLIENT_LOG])
    assert parser.device_sigs == 900  # last tpu#1 line only
    assert parser.cpu_route_sigs == 400  # 100 (tpu#1) + 300 (tpu#2)
    assert parser.deadline_misses == 1
    assert parser.verify_ewma_ms == 120.0
    out = parser.result(nodes=2, verifier="tpu")
    assert "Verify sigs device-routed: 900 of 1,300 (69%)" in out
    assert "Verify dispatch EWMA (worst service): 120.0 ms" in out
    # runs without async services print no routing lines
    assert "device-routed" not in LogParser([NODE_LOG], [CLIENT_LOG]).result()
