"""Telemetry subsystem tests (ISSUE 1).

Covers the tentpole pieces — bounded trace recorder, log-bucket
histograms, Prometheus /metrics golden output, the snapshot document's
'Work stats:' superset contract, and a 4-node in-process run producing
a commit-latency breakdown — plus regressions for the satellite fixes
(fd-limit RLIM_INFINITY, gc gen2 knob, reliable-sender idle eviction,
broadcast pacing).
"""

import asyncio
import gc
import json
import os

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry.metrics import (
    LATENCY_BOUNDS_S,
    Histogram,
    Registry,
)
from hotstuff_tpu.telemetry.trace import TraceRecorder
from hotstuff_tpu.utils.workstats import WORKSTATS_KEYS, WorkStats

from .common import async_test, committee, fresh_base_port, keys


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    """Telemetry state is process-global: every test starts disabled
    with an empty registry and leaves it that way."""
    monkeypatch.delenv("HOTSTUFF_TELEMETRY", raising=False)
    monkeypatch.delenv("HOTSTUFF_METRICS_PORT", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


# ---- instruments --------------------------------------------------------


def test_histogram_bucketing():
    h = Histogram("lat", bounds=LATENCY_BOUNDS_S)
    h.observe(0.00005)  # below the first bound (100 us)
    h.observe(0.0003)  # bucket with bound 0.0004
    h.observe(1.0)
    h.observe(500.0)  # beyond the last bound -> overflow bucket
    assert h.count == 4
    assert h.counts[0] == 1
    assert h.counts[-1] == 1  # overflow
    assert h.max == 500.0
    j = h.to_json()
    assert j["count"] == 4
    assert j["max_ms"] == 500000.0
    # percentile is an upper-bound estimate: p50 of this set must be a
    # real bucket bound >= the true median
    assert h.percentile(0.5) in LATENCY_BOUNDS_S


def test_histogram_empty_snapshot():
    h = Histogram("lat")
    assert h.to_json() == {"count": 0}
    assert h.percentile(0.99) == 0.0


def test_registry_idempotent_and_labels():
    reg = Registry()
    a = reg.counter("foo", "help", {"node": "a"})
    again = reg.counter("foo", "other help ignored", {"node": "a"})
    other = reg.counter("foo", "", {"node": "b"})
    assert a is again
    assert a is not other
    a.inc(3)
    assert again.value == 3


def test_prometheus_golden_output():
    reg = Registry()
    c = reg.counter("commits", "Blocks committed", {"node": "n0"})
    c.inc(7)
    reg.gauge("depth", "Queue depth", {"node": "n0"}, fn=lambda: 4)
    h = reg.histogram("lat", "Latency", {"node": "n0"}, bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    text = reg.render_prometheus()
    expected = (
        "# HELP hotstuff_commits Blocks committed\n"
        "# TYPE hotstuff_commits counter\n"
        'hotstuff_commits{node="n0"} 7\n'
        "# HELP hotstuff_depth Queue depth\n"
        "# TYPE hotstuff_depth gauge\n"
        'hotstuff_depth{node="n0"} 4\n'
        "# HELP hotstuff_lat Latency\n"
        "# TYPE hotstuff_lat histogram\n"
        'hotstuff_lat_bucket{node="n0",le="0.1"} 1\n'
        'hotstuff_lat_bucket{node="n0",le="1"} 1\n'
        'hotstuff_lat_bucket{node="n0",le="+Inf"} 2\n'
        'hotstuff_lat_sum{node="n0"} 5.05\n'
        'hotstuff_lat_count{node="n0"} 2\n'
    )
    assert text == expected


def test_openmetrics_golden_output():
    """OpenMetrics 1.0 exposition: counter FAMILY names drop _total in
    metadata while counter SAMPLES carry it (no double suffix for
    instruments already named *_total), and the body ends in # EOF."""
    reg = Registry()
    reg.counter("commits", "Blocks committed", {"node": "n0"}).inc(7)
    reg.counter("requests_total", "Requests", {"node": "n0"}).inc(2)
    reg.gauge("depth", "Queue depth", {"node": "n0"}, fn=lambda: 4)
    text = reg.render_openmetrics()
    expected = (
        "# HELP hotstuff_commits Blocks committed\n"
        "# TYPE hotstuff_commits counter\n"
        'hotstuff_commits_total{node="n0"} 7\n'
        "# HELP hotstuff_requests Requests\n"
        "# TYPE hotstuff_requests counter\n"
        'hotstuff_requests_total{node="n0"} 2\n'
        "# HELP hotstuff_depth Queue depth\n"
        "# TYPE hotstuff_depth gauge\n"
        'hotstuff_depth{node="n0"} 4\n'
        "# EOF\n"
    )
    assert text == expected


def test_gauge_callback_failure_is_sentinel():
    reg = Registry()
    g = reg.gauge("bad", fn=lambda: 1 / 0)
    assert g.value == -1.0  # a scrape must never throw


# ---- trace recorder -----------------------------------------------------


def test_trace_open_records_bounded():
    reg = Registry()
    tr = TraceRecorder(reg, capacity=8, ring=4)
    for i in range(100):
        tr.mark_proposed(i.to_bytes(32, "big"), i)
    assert tr.open_count() == 8  # FIFO eviction at capacity


def test_trace_ring_bounded_and_edges():
    t = [0.0]

    def clock():
        t[0] += 0.010
        return t[0]

    reg = Registry()
    tr = TraceRecorder(reg, ring=4, clock=clock)
    for i in range(10):
        d = i.to_bytes(32, "big")
        tr.mark_proposed(d, i + 1)
        tr.mark_first_vote(d)
        tr.mark_qc_formed(d)
        tr.mark_committed(d, i + 1)
    assert len(tr.ring) == 4  # bounded ring, newest kept
    assert tr.ring[-1]["round"] == 10
    j = tr.to_json()
    assert j["commits"] == 10
    assert j["open_traces"] == 0
    for edge in ("propose_to_vote", "vote_to_qc", "qc_to_commit",
                 "propose_to_commit"):
        assert j["edges"][edge]["count"] == 10
    # each edge is one 10 ms clock tick; the total is three
    assert j["edges"]["propose_to_commit"]["mean_ms"] == pytest.approx(
        30.0, abs=0.1
    )
    # consecutive commits one round apart: gap histogram all 1s
    assert j["round_gap"]["count"] == 9


def test_trace_commit_without_proposal_counts_only():
    reg = Registry()
    tr = TraceRecorder(reg)
    tr.mark_committed(b"y" * 32, 3)  # sync'd ancestor, never proposed
    j = tr.to_json()
    assert j["commits"] == 1
    assert j["edges"]["propose_to_commit"]["count"] == 0


def test_trace_duplicate_marks_first_only():
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    reg = Registry()
    tr = TraceRecorder(reg, clock=clock)
    d = b"z" * 32
    tr.mark_proposed(d, 1)
    tr.mark_first_vote(d)
    first_vote_t = tr._open[d][2]
    tr.mark_first_vote(d)  # re-delivery must not move the timestamp
    tr.mark_proposed(d, 1)
    assert tr._open[d][2] == first_vote_t


# ---- enablement / snapshot contract ------------------------------------


def test_disabled_by_default():
    assert not telemetry.enabled()
    assert telemetry.for_node("x") is None


def test_env_enablement(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_TELEMETRY", "1")
    assert telemetry.enabled()
    monkeypatch.setenv("HOTSTUFF_TELEMETRY", "off")
    assert not telemetry.enabled()
    # a configured metrics port implies collection
    monkeypatch.delenv("HOTSTUFF_TELEMETRY")
    monkeypatch.setenv("HOTSTUFF_METRICS_PORT", "9464")
    assert telemetry.enabled()


def test_snapshot_is_workstats_superset():
    """The 'Telemetry snapshot:' document must carry every 'Work stats:'
    key at top level — the scaling harness's scrape contract is
    subsumed, not broken."""
    telemetry.enable()
    tel = telemetry.for_node("n0")
    stats = WorkStats()
    stats.verify_calls = 5
    tel.attach_workstats(stats)
    doc = tel.snapshot()
    for key in WORKSTATS_KEYS:
        assert key in doc, f"snapshot missing Work stats key {key!r}"
    assert doc["verify_calls"] == 5
    assert doc["node"] == "n0"
    assert "trace" in doc
    json.dumps(doc)  # and it is one JSON-serializable log line


def test_for_node_cached_per_name():
    telemetry.enable()
    assert telemetry.for_node("a") is telemetry.for_node("a")
    assert telemetry.for_node("a") is not telemetry.for_node("b")


# ---- /metrics endpoint --------------------------------------------------


async def _http_get(port: int, path: str, method: str = "GET") -> tuple[int, str, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"{method} {path} HTTP/1.0\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(), timeout=5.0)
    writer.close()
    head, _, body = raw.decode().partition("\r\n\r\n")
    status = int(head.split()[1])
    ctype = ""
    for line in head.split("\r\n")[1:]:
        if line.lower().startswith("content-type:"):
            ctype = line.split(":", 1)[1].strip()
    return status, ctype, body


@async_test
async def test_metrics_endpoint():
    from hotstuff_tpu.telemetry.exporter import MetricsServer

    telemetry.enable()
    tel = telemetry.for_node("srv")
    tel.counter("requests_total", "Requests").inc(3)
    server = await MetricsServer(
        telemetry.registry(), host="127.0.0.1", port=0
    ).start()
    try:
        assert server.port > 0  # ephemeral port was bound and recorded
        status, ctype, body = await _http_get(server.port, "/metrics")
        assert status == 200
        assert ctype.startswith("application/openmetrics-text; version=1.0.0")
        assert 'hotstuff_requests_total{node="srv"} 3' in body
        assert body.rstrip().endswith("# EOF")

        status, ctype, body = await _http_get(server.port, "/snapshot")
        assert status == 200
        assert ctype == "application/json"
        assert json.loads(body)["srv"]["node"] == "srv"

        # delta stream: a full frame first, then O(changed) increments
        status, ctype, body = await _http_get(server.port, "/delta")
        assert status == 200
        assert ctype == "application/json"
        frame = json.loads(body)
        assert "full" in frame
        assert frame["full"]["srv.metrics.hotstuff_requests_total"] == 3
        seq = frame["seq"]
        _, _, body = await _http_get(server.port, f"/delta?since={seq}")
        again = json.loads(body)
        assert again["seq"] == seq  # nothing changed -> same frame id
        tel.counter("requests_total", "Requests").inc()
        _, _, body = await _http_get(server.port, f"/delta?since={seq}")
        delta = json.loads(body)
        assert delta.get("base") == seq
        assert delta["set"]["srv.metrics.hotstuff_requests_total"] == 4
        assert "srv.node" not in delta["set"]  # unchanged keys not resent

        status, _, _ = await _http_get(server.port, "/nope")
        assert status == 404
        status, _, _ = await _http_get(server.port, "/metrics", method="POST")
        assert status == 405
    finally:
        await server.stop()


@async_test
async def test_maybe_start_server_none_is_off():
    assert await telemetry.maybe_start_server(None) is None
    assert not telemetry.enabled()


# ---- 4-node in-process run ---------------------------------------------


@async_test
async def test_end_to_end_commit_breakdown(tmp_path):
    """A telemetry-enabled 4-node committee commits blocks and the
    commit-latency breakdown shows up in BOTH the snapshot document and
    the /metrics exposition (ISSUE 1 acceptance)."""
    from hotstuff_tpu.consensus import Consensus, Parameters
    from hotstuff_tpu.crypto import Digest, SignatureService
    from hotstuff_tpu.store import Store
    from hotstuff_tpu.telemetry.exporter import MetricsServer

    telemetry.enable()
    base = fresh_base_port()
    com = committee(base)
    nodes = []
    for i in range(4):
        name, secret = keys()[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        tel = telemetry.for_node(f"node{i}")
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=1_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
            telemetry=tel,
        )
        nodes.append((stack, commit_q, store, tel))

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.02)

    feeder = asyncio.ensure_future(feed())
    server = await MetricsServer(
        telemetry.registry(), host="127.0.0.1", port=0
    ).start()
    try:
        for _, commit_q, _, _ in nodes:
            for _ in range(3):
                await asyncio.wait_for(commit_q.get(), timeout=20.0)

        # snapshot side: every node committed and recorded edge latencies
        for _, _, _, tel in nodes:
            doc = tel.snapshot()
            assert doc["trace"]["commits"] >= 3
            edges = doc["trace"]["edges"]
            assert edges["propose_to_commit"]["count"] >= 1
            assert edges["propose_to_commit"]["mean_ms"] > 0
            assert "net" in doc  # sender pools registered
            assert "aggregator" in doc  # core section registered
            json.dumps(doc)

        # /metrics side: the same histograms render per node
        status, _, body = await _http_get(server.port, "/metrics")
        assert status == 200
        for i in range(4):
            assert (
                f'hotstuff_commit_edge_seconds_count'
                f'{{node="node{i}",edge="propose_to_commit"}}'
            ) in body
        assert "hotstuff_committed_blocks_total" in body
        assert "hotstuff_net_pool_connections" in body
    finally:
        feeder.cancel()
        await server.stop()
        for stack, _, store, _ in nodes:
            await stack.shutdown()
            store.close()


# ---- satellite regressions ---------------------------------------------


def test_raise_fd_limit_keeps_infinite_hard_cap(monkeypatch):
    """RLIM_INFINITY is -1 on Linux: max(hard, target) would replace an
    unlimited hard cap with `target` — an irreversible lowering for a
    non-root process."""
    import resource

    from hotstuff_tpu.node.main import _raise_fd_limit

    calls = []
    monkeypatch.setattr(
        resource, "getrlimit", lambda res: (1024, resource.RLIM_INFINITY)
    )
    monkeypatch.setattr(
        resource, "setrlimit", lambda res, lim: calls.append(lim)
    )
    _raise_fd_limit(50_000)
    assert calls == [(50_000, resource.RLIM_INFINITY)]


def test_raise_fd_limit_raises_finite_hard_cap(monkeypatch):
    import resource

    from hotstuff_tpu.node.main import _raise_fd_limit

    calls = []
    monkeypatch.setattr(resource, "getrlimit", lambda res: (1024, 4096))
    monkeypatch.setattr(
        resource, "setrlimit", lambda res, lim: calls.append(lim)
    )
    _raise_fd_limit(50_000)
    assert calls == [(50_000, 50_000)]


def test_raise_fd_limit_noop_when_enough(monkeypatch):
    import resource

    from hotstuff_tpu.node.main import _raise_fd_limit

    calls = []
    monkeypatch.setattr(resource, "getrlimit", lambda res: (60_000, 60_000))
    monkeypatch.setattr(
        resource, "setrlimit", lambda res, lim: calls.append(lim)
    )
    _raise_fd_limit(50_000)
    assert calls == []


def test_gc_gen2_stretch_knob(monkeypatch):
    from hotstuff_tpu.node.main import _freeze_boot_objects

    before = gc.get_threshold()
    monkeypatch.setenv("HOTSTUFF_GC_GEN2_PERIOD", "0")  # no sweeper task
    try:
        monkeypatch.setenv("HOTSTUFF_GC_GEN2_STRETCH", "0")
        _freeze_boot_objects()
        assert gc.get_threshold() == before  # opt-out keeps defaults

        monkeypatch.setenv("HOTSTUFF_GC_GEN2_STRETCH", "1")
        _freeze_boot_objects()
        assert gc.get_threshold() == (before[0], before[1], 500)
    finally:
        gc.set_threshold(*before)
        gc.unfreeze()


@async_test
async def test_reliable_connection_in_retry_is_idle():
    """A ReliableSender connection whose peer never accepts (connect
    refused, retry/backoff loop) must report idle with nothing queued —
    otherwise a dead peer pins its pool slot forever."""
    from hotstuff_tpu.network.reliable_sender import _Connection

    conn = _Connection(("127.0.0.1", fresh_base_port()))  # nothing listens
    try:
        await asyncio.sleep(0.3)  # let at least one connect attempt fail
        assert conn.connect_failures >= 1
        assert conn.idle  # evictable: no queue, no pending, no socket
    finally:
        conn.close()
        await asyncio.sleep(0)


@async_test
async def test_broadcast_pacing_ignores_unrelated_connections():
    """SimpleSender's bounded-pool pacing must count only THIS
    broadcast's connections: busy connections from other traffic on a
    shared sender previously consumed the (single, shared) 2 s deadline
    and stalled every chunk."""
    from hotstuff_tpu.network.simple_sender import SimpleSender

    loop = asyncio.get_running_loop()

    async def sink(reader, writer):
        try:
            while await reader.read(4096):
                pass
        except (ConnectionError, OSError):
            pass

    base = fresh_base_port()
    servers = [
        await asyncio.start_server(sink, "127.0.0.1", base + i)
        for i in range(3)
    ]
    sender = SimpleSender(max_conns=1)

    class _Busy:  # unrelated, permanently-busy pool entries
        idle = False

        def __init__(self):
            self.queue = asyncio.Queue()
            self.task = loop.create_task(asyncio.sleep(3600))

        def close(self):
            self.task.cancel()

    for i in range(3):
        sender._connections[("10.0.0.1", 1000 + i)] = _Busy()

    try:
        t0 = loop.time()
        await sender.broadcast(
            [("127.0.0.1", base + i) for i in range(3)], b"hello"
        )
        elapsed = loop.time() - t0
        # old code: 3 unrelated busy conns > max_conns=1 kept every chunk
        # waiting out the deadline (2 s shared). New code ignores them.
        assert elapsed < 1.5, f"broadcast stalled {elapsed:.2f}s on unrelated conns"
    finally:
        sender.close()
        for s in servers:
            s.close()
        await asyncio.sleep(0)


def test_pool_eviction_counter():
    from hotstuff_tpu.network.pool import BoundedPoolMixin

    class _Idle:
        idle = True

        class task:
            @staticmethod
            def done():
                return False

        def close(self):
            pass

    class Pool(BoundedPoolMixin):
        def __init__(self):
            self._connections = {}
            self._max_conns = 2
            self._sweeper = None

    p = Pool()
    p._connections = {i: _Idle() for i in range(5)}
    p._evict_idle(keep=2)
    assert len(p._connections) == 2
    assert p.pool_evictions == 3
