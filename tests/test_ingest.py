"""Admission-plane tests (ISSUE 10): credit computation, shed
determinism and retry-after bounds on the controller; the loadgen
scrape helpers and SUMMARY percentiles; and a slow end-to-end run
driving a 4-node committee past saturation — sheds must be typed and
counted while the proposer buffer never silently drops.
"""

from __future__ import annotations

import asyncio
import json
import os

import pytest

from hotstuff_tpu.ingest import AdmissionController, Decision
from hotstuff_tpu.ingest.admission import (
    CREDIT_SAMPLE_EVERY,
    MIN_CREDIT,
    RETRY_MAX_MS,
    RETRY_MIN_MS,
)

from .common import async_test, committee, fresh_base_port, keys


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class FakeJournal:
    def __init__(self):
        self.records: list[tuple[str, int | None]] = []

    def record(self, event, round_=0, digest=None, peer="", dur_ns=None):
        self.records.append((event, dur_ns))


def _controller(occupancy=0, **kw):
    kw.setdefault("capacity", 1_000)
    kw.setdefault("watermark", 0.5)
    kw.setdefault("horizon_ms", 500.0)
    kw.setdefault("time_fn", FakeClock())
    ctl = AdmissionController(**kw)
    state = {"occ": occupancy}
    ctl.bind(lambda: state["occ"])
    return ctl, state


# ---- credit computation ----------------------------------------------------


def test_admit_under_watermark_accepts_all_with_floor_credit():
    ctl, _ = _controller()
    d = ctl.admit(10)
    assert d == Decision(10, 0, MIN_CREDIT, 0)
    assert not d.busy
    assert ctl.accepted_total == 10 and ctl.shed_total == 0
    assert ctl.busy_frames == 0


def test_credit_is_drain_rate_times_horizon():
    ctl, _ = _controller()
    ctl.commit_rate = 2_000.0  # payloads/s
    d = ctl.admit(1)
    # one 500 ms horizon of drain: 1000 payloads, capped by the
    # watermark headroom left after this batch (500 - 1 = 499)
    assert d.credit == 499
    ctl.commit_rate = 400.0
    assert ctl.admit(1).credit == 200  # window below headroom wins


def test_credit_never_exceeds_watermark_headroom():
    ctl, state = _controller()
    ctl.commit_rate = 1e9
    for occ in (0, 100, 499, 500, 900):
        state["occ"] = occ
        d = ctl.admit(0)
        assert d.credit == max(0, 500 - occ)


def test_commit_rate_ewma():
    clock = FakeClock()
    ctl, _ = _controller(time_fn=clock)
    ctl.on_committed(100)  # first feed only anchors the clock
    assert ctl.commit_rate == 0.0
    clock.t = 1.0
    ctl.on_committed(100)  # inst 100/s, alpha = 1/RATE_TAU_S = 0.5
    assert ctl.commit_rate == pytest.approx(50.0)
    clock.t = 2.0
    ctl.on_committed(100)
    assert ctl.commit_rate == pytest.approx(75.0)
    # dt >= tau snaps straight to the instantaneous rate
    clock.t = 10.0
    ctl.on_committed(80)
    assert ctl.commit_rate == pytest.approx(10.0)
    ctl.on_committed(0)  # no-op feeds don't disturb the estimate
    assert ctl.commit_rate == pytest.approx(10.0)


# ---- shed determinism ------------------------------------------------------


def test_shed_split_is_deterministic_in_state():
    ctl, state = _controller()
    state["occ"] = 490  # limit 500 -> headroom 10
    first = ctl.admit(25)
    assert (first.accepted, first.shed) == (10, 15)
    assert first.busy
    # same (occupancy, rate, requested) -> exactly the same decision
    for _ in range(5):
        assert ctl.admit(25) == first
    state["occ"] = 500  # at the watermark: everything sheds
    d = ctl.admit(3)
    assert (d.accepted, d.shed) == (0, 3)


def test_shed_counters_accumulate():
    ctl, state = _controller()
    state["occ"] = 500
    for _ in range(4):
        ctl.admit(2)
    assert ctl.shed_total == 8
    assert ctl.busy_frames == 4
    assert ctl.accepted_total == 0


# ---- retry-after bounds ----------------------------------------------------


def test_retry_after_zero_rate_is_max_clamp():
    ctl, state = _controller()
    state["occ"] = 500
    assert ctl.admit(1).retry_after_ms == RETRY_MAX_MS


def test_retry_after_fast_drain_is_min_clamp():
    ctl, state = _controller()
    state["occ"] = 500
    ctl.commit_rate = 1e6  # drains any excess near-instantly
    assert ctl.admit(1).retry_after_ms == RETRY_MIN_MS


def test_retry_after_always_within_bounds():
    ctl, state = _controller()
    for occ in (500, 600, 1_000):
        for rate in (0.0, 0.5, 10.0, 1e3, 1e9):
            for req in (1, 64, 10_000):
                state["occ"] = occ
                ctl.commit_rate = rate
                d = ctl.admit(req)
                if d.shed:
                    assert RETRY_MIN_MS <= d.retry_after_ms <= RETRY_MAX_MS
                else:
                    assert d.retry_after_ms == 0


def test_retry_after_scales_with_excess():
    ctl, state = _controller()
    ctl.commit_rate = 100.0  # payloads/s
    state["occ"] = 510  # excess 10+req over the 500 limit
    short = ctl.admit(10).retry_after_ms
    state["occ"] = 900
    long = ctl.admit(10).retry_after_ms
    assert RETRY_MIN_MS <= short < long <= RETRY_MAX_MS
    # 20 excess over 100/s = 200 ms, 410 excess = 4100 ms
    assert short == 200 and long == 4_100


# ---- env knobs and journal -------------------------------------------------


def test_watermark_env_clamped(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_INGEST_WATERMARK", "7.5")
    assert AdmissionController(capacity=100).watermark == 1.0
    monkeypatch.setenv("HOTSTUFF_INGEST_WATERMARK", "-1")
    assert AdmissionController(capacity=100).watermark == 0.01
    monkeypatch.setenv("HOTSTUFF_INGEST_WATERMARK", "not-a-float")
    assert AdmissionController(capacity=100).watermark == 0.75


def test_bind_retargets_capacity():
    ctl, _ = _controller()
    assert ctl.capacity == 1_000
    ctl.bind(lambda: 0, capacity=40)
    assert ctl.capacity == 40
    # limit is now 20; a 25-payload batch sheds 5
    d = ctl.admit(25)
    assert (d.accepted, d.shed) == (20, 5)


def test_journal_sheds_every_busy_and_samples_credit():
    journal = FakeJournal()
    ctl, state = _controller(journal=journal)
    state["occ"] = 500
    for _ in range(CREDIT_SAMPLE_EVERY + 1):
        ctl.admit(2)
    sheds = [r for r in journal.records if r[0] == "ingest.shed"]
    credits = [r for r in journal.records if r[0] == "ingest.credit"]
    # every busy decision journals its shed count...
    assert len(sheds) == CREDIT_SAMPLE_EVERY + 1
    assert all(v == 2 for _, v in sheds)
    # ...while the credit series is sampled (decision 1, then 65, ...)
    assert len(credits) == 2


def test_stats_snapshot_keys():
    ctl, state = _controller()
    state["occ"] = 7
    ctl.admit(3)
    s = ctl.stats()
    assert s["occupancy"] == 7 and s["accepted_total"] == 3
    for key in (
        "capacity",
        "watermark",
        "commit_rate",
        "shed_total",
        "busy_frames",
        "last_credit",
    ):
        assert key in s


# ---- loadgen scrape helpers ------------------------------------------------


def test_scrape_load_stats_takes_last_document():
    from benchmark.loadgen import scrape_load_stats

    log = (
        "2026-01-01T00:00:00.000Z [INFO] Load stats: "
        + json.dumps({"offered": 10})
        + "\n2026-01-01T00:00:09.000Z [INFO] Load stats: "
        + json.dumps({"offered": 20, "shed_client": 3})
        + "\n"
    )
    assert scrape_load_stats(log) == {"offered": 20, "shed_client": 3}
    assert scrape_load_stats("no stats here") == {}


def test_scrape_ingest_sums_sections():
    from benchmark.loadgen import scrape_ingest

    docs = [
        {"ingest": {"accepted_total": 10, "shed_total": 2, "busy_frames": 1,
                    "drop_newest": 0}},
        {"ingest": {"accepted_total": 5, "shed_total": 0, "busy_frames": 0,
                    "drop_newest": 1}},
        {"other": {}},  # a node without the section doesn't poison the sum
    ]
    out = scrape_ingest(docs)
    assert out["accepted_total"] == 15 and out["shed_total"] == 2
    assert out["busy_frames"] == 1 and out["drop_newest"] == 1
    assert out["present"] is True
    assert scrape_ingest([{}])["present"] is False


def test_log_parser_latency_percentiles():
    from benchmark.logs import LogParser

    # three sample payloads committed 100/200/300 ms after their sends
    node = (
        "Timeout delay set to 5000 ms\n"
        "2026-01-01T00:00:01.000Z [INFO] Created block 1 (payloads p1,p2,p3)"
        " -> b1\n"
        "2026-01-01T00:00:01.300Z [INFO] Committed block 1 -> b1\n"
    )
    client = (
        "2026-01-01T00:00:00.900Z [INFO] Transactions rate: 100 tx/s\n"
        "2026-01-01T00:00:01.200Z [INFO] Sending sample payload p1\n"
        "2026-01-01T00:00:01.100Z [INFO] Sending sample payload p2\n"
        "2026-01-01T00:00:01.000Z [INFO] Sending sample payload p3\n"
    )
    parser = LogParser([node], [client])
    pcts = parser.end_to_end_latency_percentiles()
    assert pcts is not None
    p50, p99 = pcts
    assert p50 == pytest.approx(0.2, abs=1e-6)
    assert p99 == pytest.approx(0.3, abs=1e-6)
    assert "End-to-end latency p50/p99:" in parser.result()
    # no committed samples -> None, and the SUMMARY omits the line
    empty = LogParser([node], ["nothing"])
    assert empty.end_to_end_latency_percentiles() is None
    assert "p50/p99" not in empty.result()


# ---- end to end: committee past saturation ---------------------------------


@pytest.mark.slow
@async_test
async def test_e2e_overload_sheds_without_silent_drops(tmp_path, monkeypatch):
    """Drive a live 4-node committee well past what it can commit with a
    deliberately tiny proposer buffer: the admission plane must shed
    (typed BUSY and/or client-side credit starvation) while the buffer
    never silently drops (drop_newest == 0 on every node)."""
    from benchmark.loadgen import run_load
    from hotstuff_tpu.consensus import Consensus, Parameters
    from hotstuff_tpu.crypto import SignatureService
    from hotstuff_tpu.store import Store

    # a buffer this small WOULD overflow in seconds at 3000 tx/s if
    # credits failed; the low watermark makes sheds reachable fast
    monkeypatch.setenv("HOTSTUFF_MAX_PENDING", "200")
    monkeypatch.setenv("HOTSTUFF_INGEST_WATERMARK", "0.5")

    base = fresh_base_port()
    com = committee(base)
    nodes = []
    for i in range(4):
        name, secret = keys()[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=2_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
        )
        nodes.append((stack, commit_q, store))

    async def drain(q: asyncio.Queue):
        while True:
            await q.get()

    drains = [asyncio.ensure_future(drain(q)) for _, q, _ in nodes]
    try:
        stats = await run_load(
            [("127.0.0.1", base + i) for i in range(4)],
            rate=3_000,
            duration=6.0,
            clients=16,
            conns_per_node=1,
            size=64,
            seed=7,
        )
        assert stats, "fleet produced no stats"
        assert stats["accepted"] > 0 or stats["submitted"] > 0
        server_shed = sum(s.admission.shed_total for s, _, _ in nodes)
        total_shed = server_shed + stats["shed_client"]
        assert total_shed > 0, (
            f"no sheds at 3000 tx/s vs a 200-payload buffer: {stats}"
        )
        for stack, _, _ in nodes:
            assert stack.proposer.drop_newest == 0, (
                "proposer silently dropped payloads despite admission "
                f"control (occupancy cap {stack.proposer.max_pending})"
            )
        # credits actually constrained the fleet: the committee's
        # buffers stayed at or below the configured cap throughout
        for stack, _, _ in nodes:
            assert len(stack.proposer.pending) <= stack.proposer.max_pending
    finally:
        for t in drains:
            t.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()
