"""Store tests — ports of the reference's store_tests.rs (create, read/write,
missing key, notify_read before/after write) plus WAL crash-recovery cases
the reference lacks (SURVEY.md §4 gaps)."""

import asyncio
import os

from hotstuff_tpu.store import Store, WalEngine


def run(coro):
    return asyncio.run(coro)


def test_create_store(tmp_path):
    store = Store(str(tmp_path / "db"))
    store.close()


def test_read_write_value(tmp_path):
    async def body():
        store = Store(str(tmp_path / "db"))
        await store.write(b"hello", b"world")
        assert await store.read(b"hello") == b"world"
        store.close()

    run(body())


def test_read_unknown_key(tmp_path):
    async def body():
        store = Store(str(tmp_path / "db"))
        assert await store.read(b"nope") is None
        store.close()

    run(body())


def test_read_notify_existing(tmp_path):
    async def body():
        store = Store(str(tmp_path / "db"))
        await store.write(b"k", b"v")
        assert await store.notify_read(b"k") == b"v"
        store.close()

    run(body())


def test_read_notify_parks_until_write(tmp_path):
    async def body():
        store = Store(str(tmp_path / "db"))
        waiter = asyncio.create_task(store.notify_read(b"later"))
        await asyncio.sleep(0.05)
        assert not waiter.done()
        await store.write(b"later", b"arrived")
        assert await asyncio.wait_for(waiter, 1) == b"arrived"
        # multiple waiters on one key all resolve
        w1 = asyncio.create_task(store.notify_read(b"multi"))
        w2 = asyncio.create_task(store.notify_read(b"multi"))
        await asyncio.sleep(0.05)
        await store.write(b"multi", b"x")
        assert await asyncio.wait_for(asyncio.gather(w1, w2), 1) == [b"x", b"x"]
        store.close()

    run(body())


def test_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "db")

    async def write_phase():
        store = Store(path)
        for i in range(100):
            await store.write(b"key-%d" % i, b"value-%d" % i)
        await store.read(b"key-0")  # drain the queue
        store.close()

    async def read_phase():
        store = Store(path)
        for i in range(100):
            assert await store.read(b"key-%d" % i) == b"value-%d" % i
        store.close()

    run(write_phase())
    run(read_phase())


def test_torn_tail_record_discarded(tmp_path):
    path = str(tmp_path / "db")
    eng = WalEngine(path)
    eng.put(b"good", b"value")
    eng.close()
    # simulate a crash mid-append
    with open(os.path.join(path, "wal.log"), "ab") as f:
        f.write(b"\x10\x00\x00\x00\x10\x00\x00\x00partial")
    eng2 = WalEngine(path)
    assert eng2.get(b"good") == b"value"
    assert len(eng2) == 1
    # engine still writable after recovery
    eng2.put(b"after", b"crash")
    assert eng2.get(b"after") == b"crash"
    eng2.close()
    # records written after recovery must survive a SECOND reopen
    eng3 = WalEngine(path)
    assert eng3.get(b"good") == b"value"
    assert eng3.get(b"after") == b"crash"
    eng3.close()


def test_torn_tail_every_byte_offset(tmp_path):
    """Crash-chop the log at EVERY byte offset inside the final record.

    Whatever prefix of the last append survives the crash, replay must keep
    all fully-written records, drop the torn one, truncate the tail, and
    leave the engine writable — and a further reopen must see the post-crash
    writes."""
    key, value = b"final-key", b"final-value!"
    record_len = 8 + len(key) + len(value)
    for cut in range(record_len):
        path = str(tmp_path / ("db-%d" % cut))
        eng = WalEngine(path)
        eng.put(b"keep-a", b"1")
        eng.put(b"keep-b", b"2")
        eng.put(key, value)
        eng.close()
        wal = os.path.join(path, "wal.log")
        full = os.path.getsize(wal)
        with open(wal, "ab") as f:
            f.truncate(full - record_len + cut)
        eng2 = WalEngine(path)
        assert eng2.get(b"keep-a") == b"1"
        assert eng2.get(b"keep-b") == b"2"
        assert eng2.get(key) is None
        assert len(eng2) == 2
        eng2.put(b"post", b"crash")
        eng2.close()
        eng3 = WalEngine(path)
        assert eng3.get(b"keep-a") == b"1"
        assert eng3.get(key) is None
        assert eng3.get(b"post") == b"crash"
        eng3.close()


def test_torn_tail_delete_record(tmp_path):
    """A torn trailing tombstone must not delete the key it targeted."""
    path = str(tmp_path / "db")
    eng = WalEngine(path)
    eng.put(b"victim", b"alive")
    eng.delete(b"victim")
    eng.close()
    wal = os.path.join(path, "wal.log")
    with open(wal, "ab") as f:
        f.truncate(os.path.getsize(wal) - 1)
    eng2 = WalEngine(path)
    assert eng2.get(b"victim") == b"alive"
    eng2.close()


def test_delete_tombstone_survives_reopen(tmp_path):
    path = str(tmp_path / "db")
    eng = WalEngine(path)
    eng.put(b"a", b"1")
    eng.put(b"b", b"2")
    eng.delete(b"a")
    eng.close()
    eng2 = WalEngine(path)
    assert eng2.get(b"a") is None
    assert eng2.get(b"b") == b"2"
    eng2.close()


def test_overwrite_uses_latest(tmp_path):
    path = str(tmp_path / "db")
    eng = WalEngine(path)
    eng.put(b"k", b"old")
    eng.put(b"k", b"new")
    eng.close()
    eng2 = WalEngine(path)
    assert eng2.get(b"k") == b"new"
    eng2.close()
