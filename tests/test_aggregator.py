"""Aggregator tests: QC/TC formation, cleanup, and the
accumulate-then-dispatch eviction of invalid signatures (reference
aggregator_tests.rs:12-56 + new coverage for the batch-at-quorum rewrite).
"""

import pytest

from hotstuff_tpu.consensus import QC, Aggregator, AuthorityReuse, ConsensusError
from hotstuff_tpu.crypto import Signature
from hotstuff_tpu.crypto.service import CpuVerifier

from .common import chain, committee, keys, signed_timeout, signed_vote


@pytest.fixture
def aggregator():
    return Aggregator(committee(9_100), CpuVerifier())


def test_add_vote_forms_qc_at_quorum(aggregator):
    block = chain(1)[0]
    votes = [signed_vote(block, pk, sk) for pk, sk in keys()]
    assert aggregator.add_vote(votes[0]) is None
    assert aggregator.add_vote(votes[1]) is None
    qc = aggregator.add_vote(votes[2])
    assert qc is not None
    assert qc.hash == block.digest()
    assert qc.round == block.round
    assert len(qc.votes) == 3
    # the emitted QC verifies
    qc.verify(aggregator.committee, aggregator.verifier)
    # a QC is made at most once: the 4th vote must not emit another
    assert aggregator.add_vote(votes[3]) is None


def test_authority_reuse_rejected(aggregator):
    block = chain(1)[0]
    pk, sk = keys()[0]
    vote = signed_vote(block, pk, sk)
    aggregator.add_vote(vote)
    with pytest.raises(AuthorityReuse):
        aggregator.add_vote(vote)


def test_invalid_signature_evicted_at_quorum(aggregator):
    """A garbage vote cannot poison the quorum: it is evicted when the
    batch check fails, and the QC forms once an honest replacement
    arrives."""
    block = chain(1)[0]
    pairs = keys()
    bad = signed_vote(block, pairs[0][0], pairs[0][1])
    bad.signature = Signature(b"\x05" * 64)

    assert aggregator.add_vote(bad) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[1])) is None
    # quorum stake reached, but batch verify fails -> eviction, no QC
    assert aggregator.add_vote(signed_vote(block, *pairs[2])) is None
    # honest 4th vote completes the quorum
    qc = aggregator.add_vote(signed_vote(block, *pairs[3]))
    assert qc is not None
    assert len(qc.votes) == 3
    qc.verify(aggregator.committee, aggregator.verifier)


def test_spoofed_vote_cannot_suppress_honest_author(aggregator):
    """Vote-suppression resistance: a spoofed garbage vote naming an
    honest authority is evicted AND releases the author, so the real vote
    still completes the quorum (a keyless network attacker must not be
    able to block QC formation)."""
    from hotstuff_tpu.consensus import InvalidSignature as InvSig

    block = chain(1)[0]
    pairs = keys()
    spoof = signed_vote(block, pairs[0][0], pairs[0][1])
    spoof.signature = Signature(b"\x06" * 64)  # attacker-forged, names pairs[0]

    assert aggregator.add_vote(spoof) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[1])) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[2])) is None  # evicts
    # the honest author's REAL vote is now accepted (eagerly verified)
    qc = aggregator.add_vote(signed_vote(block, *pairs[0]))
    assert qc is not None
    qc.verify(aggregator.committee, aggregator.verifier)
    # ...and further forged votes naming a suspect author are rejected on entry
    spoof2 = signed_vote(block, pairs[0][0], pairs[0][1])
    spoof2.signature = Signature(b"\x07" * 64)
    aggregator.cleanup(0)
    with pytest.raises(ConsensusError):
        # author now in `used` again (accepted) OR rejected as invalid;
        # either way the garbage cannot enter silently
        aggregator.add_vote(spoof2)
    assert InvSig  # imported for documentation of the expected error family


def test_aggregation_bounds(aggregator):
    """Far-future rounds and digest-cell floods are rejected (DoS bound the
    reference lacks, aggregator.rs:29-30 TODO)."""
    from hotstuff_tpu.consensus.aggregator import (
        MAX_DIGEST_CELLS,
        ROUND_LOOKAHEAD,
        AggregationBounds,
    )
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    block = chain(1)[0]
    pk, sk = keys()[0]
    far = signed_vote(block, pk, sk)
    far.round = ROUND_LOOKAHEAD + 100
    with pytest.raises(AggregationBounds):
        aggregator.add_vote(far, current_round=1)

    # distinct-digest flood within one round
    with pytest.raises(AggregationBounds):
        for i in range(MAX_DIGEST_CELLS + 1):
            v = Vote(hash=Digest.random(), round=5, author=pk)
            aggregator.add_vote(v, current_round=5)


def test_add_timeout_forms_tc(aggregator):
    pairs = keys()
    timeouts = [signed_timeout(QC.genesis(), 4, pk, sk) for pk, sk in pairs]
    assert aggregator.add_timeout(timeouts[0]) is None
    assert aggregator.add_timeout(timeouts[1]) is None
    tc = aggregator.add_timeout(timeouts[2])
    assert tc is not None
    assert tc.round == 4
    assert tc.high_qc_rounds() == [0, 0, 0]
    tc.verify(aggregator.committee, aggregator.verifier)


def test_cleanup_drops_old_rounds(aggregator):
    block = chain(1)[0]
    pairs = keys()
    aggregator.add_vote(signed_vote(block, *pairs[0]))
    aggregator.add_timeout(signed_timeout(QC.genesis(), 1, *pairs[0]))
    assert aggregator.votes_aggregators and aggregator.timeouts_aggregators
    aggregator.cleanup(2)
    assert not aggregator.votes_aggregators
    assert not aggregator.timeouts_aggregators
