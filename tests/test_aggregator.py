"""Aggregator tests: QC/TC formation, cleanup, and the
accumulate-then-dispatch eviction of invalid signatures (reference
aggregator_tests.rs:12-56 + new coverage for the batch-at-quorum rewrite).
"""

import pytest

from hotstuff_tpu.consensus import QC, Aggregator, AuthorityReuse, ConsensusError
from hotstuff_tpu.crypto import Signature
from hotstuff_tpu.crypto.service import CpuVerifier

from .common import chain, committee, keys, signed_timeout, signed_vote


@pytest.fixture
def aggregator():
    return Aggregator(committee(9_100), CpuVerifier())


def test_add_vote_forms_qc_at_quorum(aggregator):
    block = chain(1)[0]
    votes = [signed_vote(block, pk, sk) for pk, sk in keys()]
    assert aggregator.add_vote(votes[0]) is None
    assert aggregator.add_vote(votes[1]) is None
    qc = aggregator.add_vote(votes[2])
    assert qc is not None
    assert qc.hash == block.digest()
    assert qc.round == block.round
    assert len(qc.votes) == 3
    # the emitted QC verifies
    qc.verify(aggregator.committee, aggregator.verifier)
    # a QC is made at most once: the 4th vote must not emit another
    assert aggregator.add_vote(votes[3]) is None


def test_authority_reuse_rejected(aggregator):
    block = chain(1)[0]
    pk, sk = keys()[0]
    vote = signed_vote(block, pk, sk)
    aggregator.add_vote(vote)
    with pytest.raises(AuthorityReuse):
        aggregator.add_vote(vote)


def test_invalid_signature_evicted_at_quorum(aggregator):
    """A garbage vote cannot poison the quorum: it is evicted when the
    batch check fails, and the QC forms once an honest replacement
    arrives."""
    block = chain(1)[0]
    pairs = keys()
    bad = signed_vote(block, pairs[0][0], pairs[0][1])
    bad.signature = Signature(b"\x05" * 64)

    assert aggregator.add_vote(bad) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[1])) is None
    # quorum stake reached, but batch verify fails -> eviction, no QC
    assert aggregator.add_vote(signed_vote(block, *pairs[2])) is None
    # honest 4th vote completes the quorum
    qc = aggregator.add_vote(signed_vote(block, *pairs[3]))
    assert qc is not None
    assert len(qc.votes) == 3
    qc.verify(aggregator.committee, aggregator.verifier)


def test_spoofed_vote_cannot_suppress_honest_author(aggregator):
    """Vote-suppression resistance: a spoofed garbage vote naming an
    honest authority is evicted AND releases the author, so the real vote
    still completes the quorum (a keyless network attacker must not be
    able to block QC formation)."""
    from hotstuff_tpu.consensus import InvalidSignature as InvSig

    block = chain(1)[0]
    pairs = keys()
    spoof = signed_vote(block, pairs[0][0], pairs[0][1])
    spoof.signature = Signature(b"\x06" * 64)  # attacker-forged, names pairs[0]

    assert aggregator.add_vote(spoof) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[1])) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[2])) is None  # evicts
    # the honest author's REAL vote is now accepted (eagerly verified)
    qc = aggregator.add_vote(signed_vote(block, *pairs[0]))
    assert qc is not None
    qc.verify(aggregator.committee, aggregator.verifier)
    # ...and further forged votes naming a suspect author are rejected on entry
    spoof2 = signed_vote(block, pairs[0][0], pairs[0][1])
    spoof2.signature = Signature(b"\x07" * 64)
    aggregator.cleanup(0)
    with pytest.raises(ConsensusError):
        # author now in `used` again (accepted) OR rejected as invalid;
        # either way the garbage cannot enter silently
        aggregator.add_vote(spoof2)
    assert InvSig  # imported for documentation of the expected error family


def test_aggregation_bounds(aggregator):
    """Far-future rounds and digest-cell floods are rejected (DoS bound the
    reference lacks, aggregator.rs:29-30 TODO)."""
    from hotstuff_tpu.consensus.aggregator import (
        MAX_DIGEST_CELLS,
        ROUND_LOOKAHEAD,
        AggregationBounds,
    )
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    block = chain(1)[0]
    pk, sk = keys()[0]
    far = signed_vote(block, pk, sk)
    far.round = ROUND_LOOKAHEAD + 100
    with pytest.raises(AggregationBounds):
        aggregator.add_vote(far, current_round=1)

    # ONE author cannot flood cells: the second cell paid by the same
    # author is refused as proof of equivocation (cell #0 is free, the
    # first paid cell lands, the next one trips the bound)
    with pytest.raises(AggregationBounds):
        for i in range(3):
            v = Vote(hash=Digest.random(), round=5, author=pk)
            v.signature = Signature.new(v.digest(), sk)
            aggregator.add_vote(v, current_round=5)
    assert len(aggregator.votes_aggregators[5]) == 2


def test_distinct_author_cell_flood_capped():
    """Even distinct authors (large Byzantine coalition) are capped at
    MAX_DIGEST_CELLS cells per round."""
    from hotstuff_tpu.consensus.aggregator import (
        MAX_DIGEST_CELLS,
        AggregationBounds,
    )
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    n = MAX_DIGEST_CELLS + 4
    agg = Aggregator(committee(9_200, n=n), CpuVerifier())
    pairs = keys(n)
    with pytest.raises(AggregationBounds):
        for pk, sk in pairs:
            v = Vote(hash=Digest.random(), round=5, author=pk)
            v.signature = Signature.new(v.digest(), sk)
            agg.add_vote(v, current_round=5)
    assert len(agg.votes_aggregators[5]) == MAX_DIGEST_CELLS


def test_self_vote_cell_admitted_through_full_verified_budget():
    """Liveness guarantee: even when a Byzantine coalition fills every
    cell with validly-signed equivocations BEFORE the honest votes
    arrive, the cell for the digest this node itself votes for is
    admitted (evicting a coalition cell), is never evicted, and the QC
    for the real block still forms."""
    from hotstuff_tpu.consensus.aggregator import MAX_DIGEST_CELLS
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    n = 16
    pairs = keys(n)
    self_pk, self_sk = pairs[0]
    agg = Aggregator(committee(9_300, n=n), CpuVerifier(), self_key=self_pk)

    block = chain(1, n=n)[0]
    # coalition pre-fills the whole budget with verified equivocations
    for pk, sk in pairs[1 : MAX_DIGEST_CELLS + 1]:
        v = Vote(hash=Digest.random(), round=block.round, author=pk)
        v.signature = Signature.new(v.digest(), sk)
        agg.add_vote(v)
    assert len(agg.votes_aggregators[block.round]) == MAX_DIGEST_CELLS

    # the node's own vote for the real block is admitted regardless
    assert agg.add_vote(signed_vote(block, self_pk, self_sk)) is None
    makers = agg.votes_aggregators[block.round]
    assert len(makers) == MAX_DIGEST_CELLS
    own_cell = makers[signed_vote(block, self_pk, self_sk).digest()]
    assert own_cell.protected and own_cell.verified

    # enough honest votes arrive for the real block: QC forms
    quorum = agg.committee.quorum_threshold()
    qc = None
    for pk, sk in pairs[1:quorum]:
        qc = agg.add_vote(signed_vote(block, pk, sk))
    assert qc is not None
    assert qc.hash == block.digest()
    qc.verify(agg.committee, agg.verifier)


def test_spoof_digest_flood_cannot_suppress_honest_votes(aggregator):
    """ADVICE r1 (medium): unsigned votes with random digests must not
    exhaust the digest-cell budget — honest votes for the real block must
    still form a QC after a garbage flood."""
    from hotstuff_tpu.consensus import InvalidSignature
    from hotstuff_tpu.consensus.aggregator import MAX_DIGEST_CELLS
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    block = chain(1)[0]
    pairs = keys()
    pk = pairs[0][0]

    # attacker floods round 1 with garbage-signed votes for random digests;
    # the first one lands as cell #0 for free, the rest are rejected at
    # the door with a failed eager verify
    garbage = Vote(hash=Digest.random(), round=1, author=pk)
    assert aggregator.add_vote(garbage, current_round=1) is None
    for _ in range(2 * MAX_DIGEST_CELLS):
        with pytest.raises(InvalidSignature):
            aggregator.add_vote(
                Vote(hash=Digest.random(), round=1, author=pk), current_round=1
            )
    assert len(aggregator.votes_aggregators[1]) == 1  # only the free cell

    # honest votes for the real block still form a QC
    assert aggregator.add_vote(signed_vote(block, *pairs[1])) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[2])) is None
    qc = aggregator.add_vote(signed_vote(block, *pairs[3]))
    assert qc is not None
    qc.verify(aggregator.committee, aggregator.verifier)


def test_verified_cell_evicts_unverified_spam_at_cap():
    """When the cell budget is full and contains an unverified spam cell,
    a verified vote for a new digest evicts the spam cell instead of
    bouncing."""
    from hotstuff_tpu.consensus.aggregator import MAX_DIGEST_CELLS
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    n = MAX_DIGEST_CELLS + 4
    agg = Aggregator(committee(9_400, n=n), CpuVerifier())
    pairs = keys(n)
    # one free unverified spam cell (garbage signature, spoofed author)
    agg.add_vote(Vote(hash=Digest.random(), round=1, author=pairs[0][0]))
    # fill the rest of the budget with verified cells from distinct authors
    for pk, sk in pairs[1:MAX_DIGEST_CELLS]:
        v = Vote(hash=Digest.random(), round=1, author=pk)
        v.signature = Signature.new(v.digest(), sk)
        agg.add_vote(v)
    assert len(agg.votes_aggregators[1]) == MAX_DIGEST_CELLS
    # a fresh VERIFIED digest evicts the spam cell, not the vote
    block = chain(1, n=n)[0]
    assert agg.add_vote(signed_vote(block, *pairs[MAX_DIGEST_CELLS])) is None
    makers = agg.votes_aggregators[1]
    assert len(makers) == MAX_DIGEST_CELLS
    assert all(m.verified for m in makers.values())


def test_byzantine_equivocation_cannot_evict_honest_subquorum_cell(aggregator):
    """A Byzantine insider signing votes for many random digests must not
    evict the honest block's cell while its (deferred-verify) sub-quorum
    votes are accumulating — eviction requires proving the victim cell
    holds no genuine signature."""
    from hotstuff_tpu.consensus.aggregator import MAX_DIGEST_CELLS, AggregationBounds
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    block = chain(1)[0]
    pairs = keys()
    byz_pk, byz_sk = pairs[0]

    # honest cell #0 accumulates 2 of 3 needed votes (unverified: batch
    # check is deferred until quorum)
    assert aggregator.add_vote(signed_vote(block, *pairs[1])) is None
    assert aggregator.add_vote(signed_vote(block, *pairs[2])) is None

    # Byzantine member floods validly-signed votes for random digests
    with pytest.raises(AggregationBounds):
        for _ in range(MAX_DIGEST_CELLS + 2):
            v = Vote(hash=Digest.random(), round=block.round, author=byz_pk)
            v.signature = Signature.new(v.digest(), byz_sk)
            aggregator.add_vote(v)

    # the honest cell survived with both its votes
    vote_digest = signed_vote(block, *pairs[1]).digest()
    honest_cell = aggregator.votes_aggregators[block.round][vote_digest]
    assert len(honest_cell.votes) == 2
    # ...and the third vote forms the QC
    qc = aggregator.add_vote(signed_vote(block, *pairs[3]))
    assert qc is not None
    assert qc.hash == block.digest()
    qc.verify(aggregator.committee, aggregator.verifier)


def test_parked_votes_replay_when_protected_cell_lands():
    """Coalition races its equivocations ahead of the real proposal and
    fills every cell verified BEFORE any honest vote arrives: honest
    votes are parked (not dropped) and replayed once the node's own
    protected cell is admitted — the QC still forms."""
    from hotstuff_tpu.consensus.aggregator import (
        MAX_DIGEST_CELLS,
        AggregationBounds,
    )
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus import Vote

    n = 16
    pairs = keys(n)
    self_pk, self_sk = pairs[0]
    agg = Aggregator(committee(9_500, n=n), CpuVerifier(), self_key=self_pk)
    block = chain(1, n=n)[0]

    # coalition pre-fills the whole budget before any honest vote
    for pk, sk in pairs[1 : MAX_DIGEST_CELLS + 1]:
        v = Vote(hash=Digest.random(), round=block.round, author=pk)
        v.signature = Signature.new(v.digest(), sk)
        agg.add_vote(v)
    assert len(agg.votes_aggregators[block.round]) == MAX_DIGEST_CELLS

    # honest votes arrive next: each bounces but is PARKED
    quorum = agg.committee.quorum_threshold()
    honest = pairs[1:quorum]  # coalition members also vote for the real block
    for pk, sk in honest:
        with pytest.raises(AggregationBounds):
            agg.add_vote(signed_vote(block, pk, sk))
    assert len(agg.parked[block.round]) == len(honest)

    # the node's own vote admits the protected cell and replays the lot:
    # self + (quorum-1) parked = quorum -> the QC forms right here
    qc = agg.add_vote(signed_vote(block, self_pk, self_sk))
    assert qc is not None
    assert qc.hash == block.digest()
    qc.verify(agg.committee, agg.verifier)
    assert not agg.parked[block.round]


def test_unknown_authority_leaves_no_cell(aggregator):
    """ADVICE r1: UnknownAuthority rejections must not leave empty cells."""
    from hotstuff_tpu.consensus import UnknownAuthority
    from hotstuff_tpu.crypto import generate_keypair

    block = chain(1)[0]
    outsider_pk, outsider_sk = generate_keypair(b"\x55" * 32, 99)
    vote = signed_vote(block, outsider_pk, outsider_sk)
    with pytest.raises(UnknownAuthority):
        aggregator.add_vote(vote)
    makers = aggregator.votes_aggregators.get(vote.round, {})
    assert vote.digest() not in makers


def test_add_timeout_forms_tc(aggregator):
    pairs = keys()
    timeouts = [signed_timeout(QC.genesis(), 4, pk, sk) for pk, sk in pairs]
    assert aggregator.add_timeout(timeouts[0]) is None
    assert aggregator.add_timeout(timeouts[1]) is None
    tc = aggregator.add_timeout(timeouts[2])
    assert tc is not None
    assert tc.round == 4
    assert tc.high_qc_rounds() == [0, 0, 0]
    tc.verify(aggregator.committee, aggregator.verifier)


def test_cleanup_drops_old_rounds(aggregator):
    block = chain(1)[0]
    pairs = keys()
    aggregator.add_vote(signed_vote(block, *pairs[0]))
    aggregator.add_timeout(signed_timeout(QC.genesis(), 1, *pairs[0]))
    assert aggregator.votes_aggregators and aggregator.timeouts_aggregators
    aggregator.cleanup(2)
    assert not aggregator.votes_aggregators
    assert not aggregator.timeouts_aggregators
