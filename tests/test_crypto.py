"""Crypto layer tests.

Ports the reference's crypto test coverage (crypto/src/tests/crypto_tests.rs:
key import/export round trips, single verify incl. negative cases, batch
verify incl. negative cases, signature service) and adds RFC 8032 known-
answer vectors for the pure-Python oracle.
"""

import asyncio

import pytest

from hotstuff_tpu.crypto import (
    CryptoError,
    Digest,
    PublicKey,
    SecretKey,
    Signature,
    SignatureService,
    batch_verify_arrays,
    generate_keypair,
    generate_production_keypair,
)
from hotstuff_tpu.crypto import ed25519_ref as ref

# RFC 8032 §7.1 test vectors (TEST 1-3).
RFC_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("seed,pub,msg,sig", RFC_VECTORS)
def test_rfc8032_vectors(seed, pub, msg, sig):
    seed, pub, msg, sig = (
        bytes.fromhex(seed),
        bytes.fromhex(pub),
        bytes.fromhex(msg),
        bytes.fromhex(sig),
    )
    assert ref.public_from_seed(seed) == pub
    assert ref.sign(seed, msg) == sig
    assert ref.verify(sig, pub, msg)
    # flip one bit -> invalid
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not ref.verify(bytes(bad), pub, msg)


def test_ref_matches_openssl_signing():
    pk, sk = generate_keypair(b"\x07" * 32, index=3)
    d = Digest.of(b"hello world")
    sig = Signature.new(d, sk)
    assert ref.sign(sk.seed, d.to_bytes()) == sig.to_bytes()
    assert ref.verify(sig.to_bytes(), pk.to_bytes(), d.to_bytes())


def test_digest_basics():
    d = Digest.of(b"payload")
    assert d.size == 32
    assert Digest.decode_base64(d.encode_base64()) == d
    assert Digest.of(b"payload") == d
    assert Digest.of(b"other") != d
    assert len({d, Digest.of(b"payload"), Digest.of(b"other")}) == 2
    assert Digest.random() != Digest.random()
    assert str(d) == d.encode_base64()[:16]


def test_key_import_export():
    pk, sk = generate_production_keypair()
    assert PublicKey.decode_base64(pk.encode_base64()) == pk
    sk2 = SecretKey.decode_base64(sk.encode_base64())
    assert sk2.to_bytes() == sk.to_bytes()
    assert sk.public_bytes == pk.to_bytes()


def test_seeded_keygen_deterministic():
    a = generate_keypair(b"\x00" * 32, 0)
    b = generate_keypair(b"\x00" * 32, 0)
    c = generate_keypair(b"\x00" * 32, 1)
    assert a[0] == b[0] and a[1].to_bytes() == b[1].to_bytes()
    assert a[0] != c[0]


def test_verify_valid_signature():
    pk, sk = generate_production_keypair()
    d = Digest.of(b"Hello, world!")
    Signature.new(d, sk).verify(d, pk)  # must not raise


def test_verify_invalid_signature():
    pk, sk = generate_production_keypair()
    d = Digest.of(b"Hello, world!")
    sig = Signature.new(d, sk)
    with pytest.raises(CryptoError):
        sig.verify(Digest.of(b"other message"), pk)
    other_pk, _ = generate_production_keypair()
    with pytest.raises(CryptoError):
        sig.verify(d, other_pk)


def test_verify_batch():
    d = Digest.of(b"Hello, batch!")
    votes = []
    for i in range(4):
        pk, sk = generate_keypair(b"\x01" * 32, i)
        votes.append((pk, Signature.new(d, sk)))
    Signature.verify_batch(d, votes)  # must not raise


def test_verify_batch_one_bad():
    d = Digest.of(b"Hello, batch!")
    votes = []
    for i in range(4):
        pk, sk = generate_keypair(b"\x02" * 32, i)
        votes.append((pk, Signature.new(d, sk)))
    # corrupt one signature
    bad = bytearray(votes[2][1].to_bytes())
    bad[10] ^= 0xFF
    votes[2] = (votes[2][0], Signature(bytes(bad)))
    with pytest.raises(CryptoError):
        Signature.verify_batch(d, votes)


def test_batch_verify_arrays_distinct_messages():
    msgs, pks, sigs = [], [], []
    for i in range(5):
        pk, sk = generate_keypair(b"\x03" * 32, i)
        d = Digest.of(bytes([i]))
        msgs.append(d.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(d, sk).to_bytes())
    # corrupt item 1
    sigs[1] = bytes(64)
    assert batch_verify_arrays(msgs, pks, sigs) == [True, False, True, True, True]


def test_signature_service():
    async def run():
        pk, sk = generate_production_keypair()
        service = SignatureService(sk)
        d = Digest.of(b"Hello, service!")
        sig = await service.request_signature(d)
        sig.verify(d, pk)
        service.shutdown()

    asyncio.run(run())


# ---- native dalek-parity batch verification (native/ed25519_batch.cpp) ----


def _native_batch_available():
    from hotstuff_tpu.crypto import native_ed25519

    return native_ed25519.available()


nativebatch = pytest.mark.skipif(
    not _native_batch_available(), reason="native batch verifier not built"
)


@nativebatch
@pytest.mark.parametrize("seed,pub,msg,sig", RFC_VECTORS)
def test_native_batch_rfc8032_vectors(seed, pub, msg, sig):
    """The batch equation accepts every RFC 8032 test vector as a
    single-element batch (arbitrary message lengths) and rejects a
    flipped bit."""
    from hotstuff_tpu.crypto import native_ed25519

    pub, msg, sig = bytes.fromhex(pub), bytes.fromhex(msg), bytes.fromhex(sig)
    assert native_ed25519.batch_verify(msg, len(msg), pub, sig, 1, shared=True)
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not native_ed25519.batch_verify(
        msg, len(msg), pub, bytes(bad), 1, shared=True
    )


@nativebatch
def test_native_batch_shared_digest_parity():
    """QC shape: N signatures over one digest — agreement with the
    OpenSSL loop on valid batches, single corruption, and wrong-key."""
    from hotstuff_tpu.crypto import native_ed25519

    d = Digest.of(b"native batch parity")
    votes = []
    for i in range(32):
        pk, sk = generate_keypair(b"\x11" * 32, i)
        votes.append((pk.to_bytes(), Signature.new(d, sk).to_bytes()))
    assert native_ed25519.batch_verify_shared(d.to_bytes(), votes)
    # corrupt one signature
    bad = list(votes)
    sig = bytearray(bad[7][1])
    sig[10] ^= 1
    bad[7] = (bad[7][0], bytes(sig))
    assert not native_ed25519.batch_verify_shared(d.to_bytes(), bad)
    # swap two signatures between authorities
    swapped = list(votes)
    swapped[0], swapped[1] = (
        (votes[0][0], votes[1][1]),
        (votes[1][0], votes[0][1]),
    )
    assert not native_ed25519.batch_verify_shared(d.to_bytes(), swapped)


@nativebatch
def test_native_batch_distinct_messages():
    from hotstuff_tpu.crypto import native_ed25519

    msgs, pks, sigs = [], [], []
    for i in range(16):
        pk, sk = generate_keypair(b"\x12" * 32, i)
        d = Digest.of(bytes([i]) * 3)
        msgs.append(d.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(d, sk).to_bytes())
    assert native_ed25519.batch_verify(
        b"".join(msgs), 32, b"".join(pks), b"".join(sigs), 16, shared=False
    )
    # one message swapped out
    msgs[3] = Digest.of(b"other").to_bytes()
    assert not native_ed25519.batch_verify(
        b"".join(msgs), 32, b"".join(pks), b"".join(sigs), 16, shared=False
    )


@nativebatch
def test_native_batch_rejects_noncanonical_scalar():
    """Malleability: adding the group order L to s yields the same
    verification equation but a non-canonical encoding — the batch
    path must reject it (dalek rejects it too)."""
    from hotstuff_tpu.crypto import native_ed25519

    L = 2**252 + 27742317777372353535851937790883648493
    d = Digest.of(b"malleability")
    pk, sk = generate_keypair(b"\x13" * 32, 0)
    sig = Signature.new(d, sk).to_bytes()
    s = int.from_bytes(sig[32:], "little")
    malleated = sig[:32] + (s + L).to_bytes(32, "little")
    assert native_ed25519.batch_verify(
        d.to_bytes(), 32, pk.to_bytes(), sig, 1, shared=True
    )
    assert not native_ed25519.batch_verify(
        d.to_bytes(), 32, pk.to_bytes(), malleated, 1, shared=True
    )


@nativebatch
def test_cpu_verifier_uses_native_batch_for_large_qcs():
    """CpuVerifier.verify_shared_msg routes large QC batches through the
    native equation and still agrees with the loop on validity."""
    from hotstuff_tpu.crypto.service import NATIVE_BATCH_MIN, CpuVerifier

    v = CpuVerifier()
    d = Digest.of(b"qc route")
    n = NATIVE_BATCH_MIN + 5
    votes = []
    for i in range(n):
        pk, sk = generate_keypair(b"\x14" * 32, i)
        votes.append((pk, Signature.new(d, sk)))
    assert v.verify_shared_msg(d, votes)
    bad = list(votes)
    bad[2] = (bad[2][0], Signature(b"\x05" * 64))
    assert not v.verify_shared_msg(d, bad)
    # verify_many certificate shape: all-pass via one equation,
    # per-item attribution preserved on failure
    msgs = [Digest.of(bytes([i])).to_bytes() for i in range(n)]
    pks, sigs = [], []
    for i in range(n):
        pk, sk = generate_keypair(b"\x15" * 32, i)
        pks.append(pk.to_bytes())
        sigs.append(Signature.new(Digest(msgs[i]), sk).to_bytes())
    assert v.verify_many(msgs, pks, sigs, aggregate_ok=True) == [True] * n
    sigs[4] = bytes(64)
    out = v.verify_many(msgs, pks, sigs, aggregate_ok=True)
    assert out == [True] * 4 + [False] + [True] * (n - 5)


@nativebatch
def test_native_batch_rejects_short_buffers():
    """Length mismatches (e.g. a 48-byte BLS-sized signature smuggled
    into an ed25519 batch) must verdict False, never reach C with an
    out-of-bounds read."""
    from hotstuff_tpu.crypto import native_ed25519

    d = Digest.of(b"short")
    pk, sk = generate_keypair(b"\x16" * 32, 0)
    good = Signature.new(d, sk).to_bytes()
    assert not native_ed25519.batch_verify(
        d.to_bytes(), 32, pk.to_bytes(), good[:48], 1, shared=True
    )
    assert not native_ed25519.batch_verify(
        d.to_bytes(), 32, pk.to_bytes()[:16], good, 1, shared=True
    )
    assert not native_ed25519.batch_verify(
        d.to_bytes()[:8], 32, pk.to_bytes(), good, 1, shared=True
    )
