"""The static analysis plane (ISSUE 12): every rule catches its
synthetic violation, respects ``# lint: allow``, the allowlist
round-trips with stale detection, and the real tree passes clean.

Fixture trees are written under ``tmp_path`` at the repo-relative paths
each rule targets, so the tests exercise the same glob/targeting logic
the LINT=1 gate uses.  Everything here is stdlib-only — no jax, no
node runtime — by the analysis plane's own design constraint.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap

from hotstuff_tpu.analysis import (
    Finding,
    load_allowlist,
    run_rules,
)
from hotstuff_tpu.analysis import knobgen
from hotstuff_tpu.analysis.framework import apply_allowlist, repo_root
from hotstuff_tpu.analysis.rules import ALL_RULES
from hotstuff_tpu.analysis.rules.blocking import NoBlockingInAsync
from hotstuff_tpu.analysis.rules.env_knobs import EnvKnobRegistry
from hotstuff_tpu.analysis.rules.guarded_by import GuardedBy
from hotstuff_tpu.analysis.rules.taxonomy_rule import TaxonomyRegistry
from hotstuff_tpu.analysis.rules.wire_bounds import WireDecoderBounds


def _tree(tmp_path, files: dict) -> str:
    """Write ``{repo-relative path: source}`` under tmp_path."""
    for rel, text in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(text))
    return str(tmp_path)


def _codes(findings) -> set:
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# no-blocking-in-async


def test_blocking_rule_catches_sync_calls_in_async_def(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/actor.py": """\
                import time


                async def propose(self, fut, sock):
                    time.sleep(0.1)
                    stake = fut.result()
                    value = self.store.engine.get(b"k")
                    data = sock.recv(1024)
                    return stake, value, data
                """,
        },
    )
    findings = run_rules([NoBlockingInAsync()], root)
    assert _codes(findings) == {
        "time.sleep",
        "fut.result",
        "self.store.engine.get",
        "sock.recv",
    }
    assert all(f.rule == "no-blocking-in-async" for f in findings)


def test_blocking_rule_ignores_sync_defs_and_nested_functions(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/actor.py": """\
                import time


                def sync_helper():
                    time.sleep(1)  # sync context: out of scope


                async def run(self, loop):
                    def callback():
                        time.sleep(1)  # nested def: different schedule

                    await loop.run_in_executor(None, callback)
                    result = await self.task  # awaited, not blocking
                    return result
                """,
        },
    )
    assert run_rules([NoBlockingInAsync()], root) == []


def test_blocking_rule_respects_inline_allow(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/actor.py": """\
                async def tally(done):
                    total = 0
                    for t in done:
                        # t is in asyncio.wait's done set: result() is an
                        # immediate read, never a block
                        # lint: allow(no-blocking-in-async)
                        total += t.result()
                    return total
                """,
        },
    )
    assert run_rules([NoBlockingInAsync()], root) == []


def test_allow_marker_works_anywhere_in_comment_block(tmp_path):
    # the marker ABOVE the justification lines, not adjacent to the code
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/actor.py": """\
                async def tally(t):
                    # lint: allow(no-blocking-in-async)
                    # a multi-line justification sits between the marker
                    # and the flagged call; the contiguous block carries it
                    return t.result()
                """,
        },
    )
    assert run_rules([NoBlockingInAsync()], root) == []


def test_allow_for_a_different_rule_does_not_suppress(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/actor.py": """\
                async def tally(t):
                    # lint: allow(wire-decoder-bounds)
                    return t.result()
                """,
        },
    )
    assert _codes(run_rules([NoBlockingInAsync()], root)) == {"t.result"}


# ---------------------------------------------------------------------------
# wire-decoder-bounds


def test_wire_bounds_catches_unbounded_count(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/wire.py": """\
                def decode_votes(dec):
                    n = dec.u32()
                    return [dec.raw(64) for _ in range(n)]
                """,
        },
    )
    findings = run_rules([WireDecoderBounds()], root)
    assert _codes(findings) == {"decode_votes:n"}


def test_wire_bounds_accepts_bounded_count(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/wire.py": """\
                MAX = 4096


                def decode_votes(dec):
                    n = dec.u32()
                    if n > MAX:
                        raise ValueError("vote count over cap")
                    return [dec.raw(64) for _ in range(n)]
                """,
        },
    )
    assert run_rules([WireDecoderBounds()], root) == []


def test_wire_bounds_equality_check_is_not_a_bound(tmp_path):
    # ``n == SENTINEL`` routes a format variant; it bounds nothing
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/wire.py": """\
                SENTINEL = 0xFFFFFFFF


                def decode_votes(dec):
                    n = dec.u32()
                    if n == SENTINEL:
                        return None
                    return [dec.raw(64) for _ in range(n)]
                """,
        },
    )
    assert _codes(run_rules([WireDecoderBounds()], root)) == {
        "decode_votes:n"
    }


def test_wire_bounds_flags_uncapped_var_bytes(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/wire.py": """\
                def decode_blob(dec):
                    return dec.var_bytes()
                """,
        },
    )
    assert _codes(run_rules([WireDecoderBounds()], root)) == {
        "decode_blob:var_bytes"
    }


def test_wire_bounds_accepts_capped_var_bytes(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/wire.py": """\
                def decode_blob(dec):
                    return dec.var_bytes(1024)
                """,
        },
    )
    assert run_rules([WireDecoderBounds()], root) == []


# ---------------------------------------------------------------------------
# taxonomy-registry (fixture trees carry no taxonomy.py, so the rule
# falls back to the real repo's registry)


def test_taxonomy_rule_catches_unregistered_edge_and_stage(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/core.py": """\
                def on_commit(self, j, rec, block, t0, dur):
                    j.record("commmit", block.digest())  # typo
                    j.record("commit", block.digest())   # registered
                    rec.add("dispatch.typo", t0, dur)    # unregistered
                    rec.add("dispatch", t0, dur)         # registered
                """,
        },
    )
    findings = run_rules([TaxonomyRegistry()], root)
    assert _codes(findings) == {"edge:commmit", "stage:dispatch.typo"}


def test_taxonomy_rule_dynamic_edges_need_registered_prefix(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/faults_like.py": "",
            "hotstuff_tpu/consensus/core.py": """\
                def on_fault(self, j, kind):
                    j.record(f"fault.{kind}", None)  # registered prefix
                    j.record(f"byz.{kind}", None)    # registered prefix
                    j.record(f"oops.{kind}", None)   # unregistered
                """,
        },
    )
    findings = run_rules([TaxonomyRegistry()], root)
    assert _codes(findings) == {"edge:<dynamic>"}
    assert len(findings) == 1


def test_taxonomy_rule_ignores_non_journal_receivers(tmp_path):
    # .record() on something that is not a journal handle is out of
    # scope — only the conventional receiver names are checked
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/consensus/core.py": """\
                def run(self, metrics):
                    metrics.record("whatever.metric", 1)
                """,
        },
    )
    assert run_rules([TaxonomyRegistry()], root) == []


# ---------------------------------------------------------------------------
# env-knob-registry + knobgen


_KNOB_TREE = {
    "hotstuff_tpu/__init__.py": """\
        import os

        WINDOW = int(os.environ.get("HOTSTUFF_FIXTURE_WINDOW", "64"))
        """,
}


def test_env_knob_rule_flags_missing_and_stale_docs(tmp_path):
    root = _tree(tmp_path, _KNOB_TREE)
    findings = run_rules([EnvKnobRegistry()], root)
    assert _codes(findings) == {"missing"}

    # regenerating clears the finding
    knobgen.write(root)
    assert run_rules([EnvKnobRegistry()], root) == []

    # a new knob read makes the committed table stale
    extra = tmp_path / "hotstuff_tpu" / "extra.py"
    extra.write_text(
        'import os\nN = int(os.getenv("HOTSTUFF_FIXTURE_NEW", "8"))\n'
    )
    findings = run_rules([EnvKnobRegistry()], root)
    assert _codes(findings) == {"stale"}


def test_knobgen_discovers_helper_routed_and_subscript_reads(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/knobs.py": """\
                import os


                def _env_int(name, default):
                    return int(os.environ.get(name, str(default)))


                A = _env_int("HOTSTUFF_FIXTURE_HELPER", 512)
                B = os.environ["HOTSTUFF_FIXTURE_SUBSCRIPT"]
                C = "HOTSTUFF_FIXTURE_MEMBER" in os.environ
                """,
        },
    )
    knobs = knobgen.scan(root)
    assert set(knobs) == {
        "HOTSTUFF_FIXTURE_HELPER",
        "HOTSTUFF_FIXTURE_SUBSCRIPT",
        "HOTSTUFF_FIXTURE_MEMBER",
    }
    assert knobs["HOTSTUFF_FIXTURE_HELPER"]["defaults"] == ["512"]
    rendered = knobgen.render(root)
    assert "HOTSTUFF_FIXTURE_SUBSCRIPT" in rendered
    assert "3 knobs registered." in rendered


def test_committed_knobs_doc_is_fresh():
    """docs/KNOBS.md matches the real tree — the same invariant the
    gate enforces, asserted here so a stale table fails tier-1 too."""
    assert knobgen.is_fresh(repo_root())


# ---------------------------------------------------------------------------
# guarded-by


_RACY_CLASS = """\
    import threading


    class Service:
        def __init__(self):
            self.count = 0
            self._thread = threading.Thread(target=self._worker)

        def _worker(self):
            self.count += 1

        def snapshot(self):
            return self.count
    """


def test_guarded_by_flags_unannotated_cross_thread_field(tmp_path):
    root = _tree(tmp_path, {"hotstuff_tpu/telemetry/svc.py": _RACY_CLASS})
    findings = run_rules([GuardedBy()], root)
    assert _codes(findings) == {"Service.count"}


def test_guarded_by_accepts_documented_discipline(tmp_path):
    annotated = _RACY_CLASS.replace(
        "self.count += 1",
        "# guarded-by: gil\n            self.count += 1",
    )
    root = _tree(tmp_path, {"hotstuff_tpu/telemetry/svc.py": annotated})
    assert run_rules([GuardedBy()], root) == []


def test_guarded_by_lockset_checks_annotated_lock(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/telemetry/svc.py": """\
                import threading


                class Service:
                    def __init__(self):
                        self._mu = threading.Lock()
                        self.count = 0
                        self._thread = threading.Thread(target=self._worker)

                    def _worker(self):
                        with self._mu:
                            # guarded-by: _mu
                            self.count += 1

                    def reset(self):
                        self.count = 0  # write without holding _mu
                """,
        },
    )
    findings = run_rules([GuardedBy()], root)
    assert _codes(findings) == {"Service.count:unlocked"}


def test_guarded_by_lockset_passes_when_all_writes_hold_lock(tmp_path):
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/telemetry/svc.py": """\
                import threading


                class Service:
                    def __init__(self):
                        self._mu = threading.Lock()
                        self.count = 0
                        self._thread = threading.Thread(target=self._worker)

                    def _worker(self):
                        with self._mu:
                            # guarded-by: _mu
                            self.count += 1

                    def reset(self):
                        with self._mu:
                            self.count = 0
                """,
        },
    )
    assert run_rules([GuardedBy()], root) == []


def test_guarded_by_drift_check_without_thread_creation(tmp_path):
    # no visible Thread(): callers thread from outside.  A field written
    # both under and outside the class lock with no annotation is drift.
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/tpu/dev.py": """\
                import threading


                class Cache:
                    def __init__(self):
                        self._mu = threading.Lock()
                        self.slots = {}

                    def insert(self, k, v):
                        with self._mu:
                            self.slots[k] = v

                    def wipe(self):
                        self.slots = {}
                """,
        },
    )
    findings = run_rules([GuardedBy()], root)
    assert _codes(findings) == {"Cache.slots:drift"}


# ---------------------------------------------------------------------------
# framework: syntax errors, allowlist round-trip


def test_unparseable_target_is_its_own_finding(tmp_path):
    root = _tree(
        tmp_path,
        {"hotstuff_tpu/consensus/wire.py": "def broken(:\n"},
    )
    findings = run_rules([WireDecoderBounds()], root)
    assert _codes(findings) == {"syntax-error"}


def test_allowlist_round_trip_and_stale_detection(tmp_path):
    findings = [
        Finding("r", "a.py", 3, "x", "m1"),
        Finding("r", "b.py", 9, "y", "m2"),
    ]
    path = tmp_path / "allowlist.txt"
    path.write_text(
        "# grandfathered\n"
        "\n"
        f"{findings[0].key}\n"
        "r:gone.py:z\n"  # file since fixed: stale
    )
    keys = load_allowlist(str(path))
    assert keys == {"r:a.py:x", "r:gone.py:z"}
    kept, used, stale = apply_allowlist(findings, keys)
    assert [f.key for f in kept] == ["r:b.py:y"]
    assert used == {"r:a.py:x"}
    assert stale == {"r:gone.py:z"}


def test_finding_keys_are_line_number_free():
    a = Finding("r", "p.py", 10, "tok", "m")
    b = Finding("r", "p.py", 99, "tok", "m")
    assert a.key == b.key == "r:p.py:tok"
    assert "10" in a.render() and "[r]" in a.render()


# ---------------------------------------------------------------------------
# the gate itself


def test_real_tree_passes_clean():
    """The merged repo has zero findings after the committed allowlist —
    exactly what ``LINT=1 scripts/trace.sh`` asserts in CI."""
    import os

    root = repo_root()
    findings = run_rules(ALL_RULES, root)
    allow = load_allowlist(
        os.path.join(root, "hotstuff_tpu", "analysis", "allowlist.txt")
    )
    kept, _, stale = apply_allowlist(findings, allow)
    assert kept == [], "\n".join(f.render() for f in kept)
    assert stale == set(), f"stale allowlist entries: {sorted(stale)}"


def test_cli_check_exits_nonzero_on_violation_fixture(tmp_path):
    """Introducing any rule's violation flips the gate to a non-zero
    exit — the ISSUE 12 acceptance demonstration, via the same
    ``python -m hotstuff_tpu.analysis check`` entry the gate runs."""
    root = _tree(
        tmp_path,
        {
            "hotstuff_tpu/__init__.py": "",
            "hotstuff_tpu/consensus/wire.py": """\
                def decode_votes(dec):
                    n = dec.u32()
                    return [dec.raw(64) for _ in range(n)]
                """,
        },
    )
    knobgen.write(root)  # keep the knob rule out of this fixture's way
    dirty = subprocess.run(
        [
            sys.executable, "-m", "hotstuff_tpu.analysis", "check",
            "--root", root,
        ],
        capture_output=True,
        text=True,
        cwd=repo_root(),
    )
    assert dirty.returncode == 1
    assert "wire-decoder-bounds" in dirty.stdout
    assert "FAIL" in dirty.stdout

    # fixing the fixture flips it back to 0
    (tmp_path / "hotstuff_tpu" / "consensus" / "wire.py").write_text(
        textwrap.dedent(
            """\
            def decode_votes(dec):
                n = dec.u32()
                if n > 4096:
                    raise ValueError("over cap")
                return [dec.raw(64) for _ in range(n)]
            """
        )
    )
    clean = subprocess.run(
        [
            sys.executable, "-m", "hotstuff_tpu.analysis", "check",
            "--root", root,
        ],
        capture_output=True,
        text=True,
        cwd=repo_root(),
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "OK: no findings" in clean.stdout
