"""Crypto-scheme registry coverage (crypto/scheme.py): deterministic
keygen, secret file round-trips, dispatch, and the PoP helper."""

from __future__ import annotations

import pytest

from hotstuff_tpu.crypto.scheme import (
    OpaqueSecret,
    UnknownScheme,
    bls_keygen,
    bls_pop,
    check_scheme,
    keygen_deterministic,
    keygen_production,
    make_cpu_verifier,
    make_signing_service,
    read_secret,
)


def test_unknown_scheme_rejected_everywhere():
    for fn in (check_scheme, make_cpu_verifier):
        with pytest.raises(UnknownScheme):
            fn("rsa")
    with pytest.raises(UnknownScheme):
        keygen_production("ed448")


def test_deterministic_bls_keygen_stable_and_indexed():
    pk_a, sk_a = bls_keygen(b"\x01" * 32, 0)
    pk_b, sk_b = bls_keygen(b"\x01" * 32, 0)
    assert pk_a == pk_b and sk_a == sk_b  # same seed+index -> same key
    pk_c, _ = bls_keygen(b"\x01" * 32, 1)
    assert pk_c != pk_a  # index separates
    pk_d, _ = bls_keygen(b"\x02" * 32, 0)
    assert pk_d != pk_a  # seed separates
    assert len(pk_a.to_bytes()) == 96 and len(sk_a) == 32


def test_pop_binds_the_key():
    from hotstuff_tpu.crypto.bls import BlsPublicKey, BlsSignature, verify_possession

    pk, secret = bls_keygen(b"\x03" * 32, 7)
    pop = bls_pop(secret)
    assert len(pop) == 48
    assert verify_possession(
        BlsPublicKey.from_bytes(pk.to_bytes()), BlsSignature.from_bytes(pop)
    )
    other_pk, _ = bls_keygen(b"\x03" * 32, 8)
    assert not verify_possession(
        BlsPublicKey.from_bytes(other_pk.to_bytes()),
        BlsSignature.from_bytes(pop),
    )


def test_secret_round_trip_and_wipe_per_scheme():
    for scheme in ("ed25519", "bls"):
        _, secret = keygen_deterministic(scheme, b"\x05" * 32, 3)
        b64 = secret.encode_base64()
        back = read_secret(scheme, b64)
        assert back.to_bytes() == secret.to_bytes()
        svc = make_signing_service(scheme, back)
        from hotstuff_tpu.crypto import Digest

        sig = svc.sign_sync(Digest.of(b"scheme round trip"))
        assert len(sig.to_bytes()) == (64 if scheme == "ed25519" else 48)
        svc.shutdown()
        # the service wiped/dropped the key; signing must now fail
        with pytest.raises(RuntimeError):
            svc.sign_sync(Digest.of(b"after shutdown"))


def test_opaque_secret_wipe_contract():
    s = OpaqueSecret(b"\xaa" * 32)
    assert s.to_bytes() == b"\xaa" * 32
    s.wipe()
    assert s.wiped
    with pytest.raises(RuntimeError):
        s.to_bytes()
    with pytest.raises(RuntimeError):
        s.encode_base64()
