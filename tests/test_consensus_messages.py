"""Protocol-object tests: codec round-trips and verification rules.

Ports the reference's messages_tests.rs:7-55 (QC verify success /
authority reuse / unknown authority / insufficient stake) plus wire-codec
coverage for every message type.
"""

import pytest

from hotstuff_tpu.consensus import (
    QC,
    TC,
    AuthorityReuse,
    Block,
    InvalidSignature,
    QCRequiresQuorum,
    TCRequiresQuorum,
    Timeout,
    UnknownAuthority,
    Vote,
    timeout_digest,
)
from hotstuff_tpu.consensus.wire import (
    TAG_PRODUCER,
    TAG_PROPOSE,
    TAG_SYNC_REQUEST,
    TAG_TC,
    TAG_TIMEOUT,
    TAG_VOTE,
    decode_message,
    encode_producer,
    encode_propose,
    encode_sync_request,
    encode_tc,
    encode_timeout,
    encode_vote,
)
from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
from hotstuff_tpu.crypto.service import CpuVerifier

from .common import (
    async_test,
    chain,
    committee,
    keys,
    qc_for_block,
    signed_block,
    signed_timeout,
    signed_vote,
)

VERIFIER = CpuVerifier()
COMMITTEE = committee(9_000)


def test_block_roundtrip():
    blocks = chain(3)
    b = blocks[-1]
    again = Block.deserialize(b.serialize())
    assert again.digest() == b.digest()
    assert again.qc == b.qc
    assert again.round == b.round
    assert again.signature == b.signature


def test_wire_roundtrip_all_tags():
    blocks = chain(2)
    pk, sk = keys()[0]
    vote = signed_vote(blocks[0], pk, sk)
    timeout = signed_timeout(QC.genesis(), 3, pk, sk)
    tc = TC(round=3, votes=[(pk, timeout.signature, 0)])
    digest = Digest.random()

    for encoded, tag in [
        (encode_propose(blocks[1]), TAG_PROPOSE),
        (encode_vote(vote), TAG_VOTE),
        (encode_timeout(timeout), TAG_TIMEOUT),
        (encode_tc(tc), TAG_TC),
        (encode_sync_request(digest, pk), TAG_SYNC_REQUEST),
        (encode_producer(digest), TAG_PRODUCER),
    ]:
        got_tag, payload = decode_message(encoded)
        assert got_tag == tag
        assert payload is not None


def test_producer_body_roundtrip():
    """Producer messages carry an optional content-addressed body
    (VERDICT r3 item 4: real transaction bytes through the producer
    path)."""
    body = b"\xab" * 512
    digest = Digest.of(body)
    tag, (got_digest, got_body) = decode_message(encode_producer(digest, body))
    assert tag == TAG_PRODUCER
    assert got_digest == digest and got_body == body
    # digest-only form still round-trips (empty body)
    tag, (d2, b2) = decode_message(encode_producer(digest))
    assert d2 == digest and b2 == b""


@async_test
async def test_receiver_handler_stores_body_and_rejects_mismatch(tmp_path):
    """The ingest handler verifies content addressing, stores the body
    keyed by digest, and forwards the bare digest to the proposer; a
    body that does not hash to its digest is dropped without an ACK."""
    import asyncio

    from hotstuff_tpu.consensus.consensus import (
        ConsensusReceiverHandler,
        PayloadBodies,
        payload_key,
    )
    from hotstuff_tpu.store import Store

    class FakeWriter:
        def __init__(self):
            self.sent = []

        async def send(self, data):
            self.sent.append(data)

    store = Store(str(tmp_path / "db"))
    tx_producer: asyncio.Queue = asyncio.Queue()
    handler = ConsensusReceiverHandler(
        asyncio.Queue(),
        asyncio.Queue(),
        tx_producer,
        bodies=PayloadBodies(store, 1 << 20),
    )
    body = b"\xcd" * 512
    digest = Digest.of(body)
    w = FakeWriter()
    await handler.dispatch(w, encode_producer(digest, body))
    assert w.sent  # ACK
    assert tx_producer.get_nowait() == digest
    assert await store.read(payload_key(digest)) == body

    # poisoned: body does not hash to the claimed digest
    w2 = FakeWriter()
    await handler.dispatch(w2, encode_producer(Digest.random(), body))
    assert not w2.sent  # no ACK
    assert tx_producer.empty()
    store.close()


@async_test
async def test_payload_body_budget_evicts_uncommitted(tmp_path):
    """Advisor r4 (medium): unauthenticated producer bodies are admitted
    against a byte budget — overflow evicts the OLDEST uncommitted body
    from the store; committed bodies become history and are never
    evicted."""
    from hotstuff_tpu.consensus.consensus import PayloadBodies, payload_key
    from hotstuff_tpu.store import Store

    store = Store(str(tmp_path / "db"))
    bodies = PayloadBodies(store, budget=1024)

    def make(i):
        body = bytes([i]) * 400
        return Digest.of(body), body

    d0, b0 = make(0)
    d1, b1 = make(1)
    d2, b2 = make(2)
    await bodies.admit(d0, b0)
    # committed bodies leave the budget: d0 no longer counts or evicts
    bodies.mark_committed([d0])
    await bodies.admit(d1, b1)
    await bodies.admit(d2, b2)  # 800 uncommitted bytes — fits
    assert bodies.evicted == 0
    d3, b3 = make(3)
    await bodies.admit(d3, b3)  # would be 1200 > 1024: evicts d1 (oldest)
    assert bodies.evicted == 1
    assert await store.read(payload_key(d1)) is None
    # committed d0 and newer uncommitted bodies survive
    assert await store.read(payload_key(d0)) == b0
    assert await store.read(payload_key(d2)) == b2
    assert await store.read(payload_key(d3)) == b3
    # duplicate admit of an already-pending digest is a no-op
    await bodies.admit(d3, b3)
    assert bodies.evicted == 1
    store.close()


def test_verify_valid_block():
    blocks = chain(2)
    blocks[1].verify(COMMITTEE, VERIFIER)  # should not raise


def test_verify_wrong_signature():
    blocks = chain(2)
    b = blocks[1]
    b.signature = Signature(b"\x01" * 64)
    with pytest.raises(InvalidSignature):
        b.verify(COMMITTEE, VERIFIER)


def test_verify_valid_qc():
    block = chain(1)[0]
    qc_for_block(block).verify(COMMITTEE, VERIFIER)  # should not raise


def test_qc_authority_reuse():
    block = chain(1)[0]
    qc = qc_for_block(block)
    qc.votes.append(qc.votes[0])  # duplicate first voter
    with pytest.raises(AuthorityReuse):
        qc.verify(COMMITTEE, VERIFIER)


def test_qc_unknown_authority():
    block = chain(1)[0]
    qc = qc_for_block(block)
    outsider_pk, outsider_sk = generate_keypair(b"\x01" * 32, 99)
    vote_digest = Vote.for_block(block, outsider_pk).digest()
    qc.votes[0] = (outsider_pk, Signature.new(vote_digest, outsider_sk))
    with pytest.raises(UnknownAuthority):
        qc.verify(COMMITTEE, VERIFIER)


def test_qc_insufficient_stake():
    block = chain(1)[0]
    qc = qc_for_block(block, voters=2)  # 2 of 4 < quorum (3)
    with pytest.raises(QCRequiresQuorum):
        qc.verify(COMMITTEE, VERIFIER)


def test_qc_bad_signature_in_batch():
    block = chain(1)[0]
    qc = qc_for_block(block)
    pk0, _ = keys()[0]
    qc.votes[0] = (pk0, Signature(b"\x02" * 64))
    with pytest.raises(InvalidSignature):
        qc.verify(COMMITTEE, VERIFIER)


def test_timeout_verify_and_digest():
    pk, sk = keys()[0]
    t = signed_timeout(QC.genesis(), 7, pk, sk)
    t.verify(COMMITTEE, VERIFIER)
    assert t.digest() == timeout_digest(7, 0)


def test_tc_verify():
    # 3 authorities time out at round 5 with genesis high QCs
    votes = []
    for pk, sk in keys()[:3]:
        t = signed_timeout(QC.genesis(), 5, pk, sk)
        votes.append((pk, t.signature, 0))
    tc = TC(round=5, votes=votes)
    tc.verify(COMMITTEE, VERIFIER)  # should not raise


def test_tc_insufficient_stake():
    votes = []
    for pk, sk in keys()[:2]:
        t = signed_timeout(QC.genesis(), 5, pk, sk)
        votes.append((pk, t.signature, 0))
    with pytest.raises(TCRequiresQuorum):
        TC(round=5, votes=votes).verify(COMMITTEE, VERIFIER)


def test_tc_bad_signature():
    votes = []
    for pk, sk in keys()[:3]:
        t = signed_timeout(QC.genesis(), 5, pk, sk)
        votes.append((pk, t.signature, 0))
    # entry 0 claims a different high_qc_round than it signed
    votes[0] = (votes[0][0], votes[0][1], 3)
    with pytest.raises(InvalidSignature):
        TC(round=5, votes=votes).verify(COMMITTEE, VERIFIER)


def test_genesis_identities():
    assert Block.genesis().digest() == Block.genesis().digest()
    assert QC.genesis().is_genesis()
    assert not qc_for_block(chain(1)[0]).is_genesis()


def test_vote_verify():
    block = chain(1)[0]
    pk, sk = keys()[0]
    vote = signed_vote(block, pk, sk)
    vote.verify(COMMITTEE, VERIFIER)
    vote.signature = Signature(b"\x03" * 64)
    with pytest.raises(InvalidSignature):
        vote.verify(COMMITTEE, VERIFIER)


def test_qc_verify_cache_skips_repeat_batches():
    """The per-core verified-QC memo: a view-change storm delivers the
    same high_qc inside every one of n timeouts; with a cache the
    expensive batch verification runs once, and tampered copies (new
    cache key) still verify from scratch."""
    block = chain(2)[-1]
    qc = qc_for_block(block)

    class CountingVerifier(CpuVerifier):
        calls = 0

        def verify_shared_msg(self, d, votes):
            CountingVerifier.calls += 1
            return super().verify_shared_msg(d, votes)

    v = CountingVerifier()
    cache: set = set()
    for _ in range(5):
        qc.verify(COMMITTEE, v, cache=cache)
    assert CountingVerifier.calls == 1
    # a tampered QC (different votes → different key) re-verifies
    bad = QC(
        hash=qc.hash, round=qc.round, votes=qc.votes[:2] + [qc.votes[0]]
    )
    with pytest.raises(AuthorityReuse):
        bad.verify(COMMITTEE, v, cache=cache)


def test_qc_cache_key_is_injective_in_vote_framing():
    """ADVICE r2: an unframed concatenation of variable-size pk/sig bytes
    lets a different partitioning of the same byte stream collide with a
    verified QC's cache key.  The key must separate vote boundaries: two
    96+48-byte (BLS-shaped) votes and three 32+64-byte (ed25519-shaped)
    chunks of the SAME 288-byte stream must hash differently."""
    from hotstuff_tpu.crypto import PublicKey

    stream = bytes(range(256)) + bytes(32)  # 288 deterministic bytes
    as_bls = QC(
        hash=Digest(b"\x01" * 32),
        round=7,
        votes=[
            (PublicKey(stream[0:96]), Signature(stream[96:144])),
            (PublicKey(stream[144:240]), Signature(stream[240:288])),
        ],
    )
    as_ed = QC(
        hash=Digest(b"\x01" * 32),
        round=7,
        votes=[
            (PublicKey(stream[0:32]), Signature(stream[32:96])),
            (PublicKey(stream[96:128]), Signature(stream[128:192])),
            (PublicKey(stream[192:224]), Signature(stream[224:288])),
        ],
    )
    assert b"".join(pk.data + sig.data for pk, sig in as_bls.votes) == \
           b"".join(pk.data + sig.data for pk, sig in as_ed.votes)
    assert as_bls._cache_key() != as_ed._cache_key()


def test_decode_narrows_keysig_sizes_to_committee_scheme():
    """ADVICE r2: an ed25519 committee must reject BLS-sized (96/48)
    key/signature material at decode time, and vice versa, instead of
    relying on later stake/crypto checks."""
    from hotstuff_tpu.consensus.errors import SerializationError
    from hotstuff_tpu.crypto import PublicKey

    block = chain(1)[0]
    pk, sk = keys()[0]
    vote = signed_vote(block, pk, sk)  # ed25519-sized: 32/64
    data = encode_vote(vote)
    # accepted under its own scheme and under no scheme (union)
    decode_message(data)
    decode_message(data, scheme="ed25519")
    # rejected under the other scheme's sizes
    with pytest.raises(SerializationError):
        decode_message(data, scheme="bls")
    # BLS-shaped material rejected by an ed25519 committee
    vote_bls = Vote(
        hash=vote.hash,
        round=vote.round,
        author=PublicKey(b"\x05" * 96),
        signature=Signature(b"\x06" * 48),
    )
    data_bls = encode_vote(vote_bls)
    decode_message(data_bls, scheme="bls")
    with pytest.raises(SerializationError):
        decode_message(data_bls, scheme="ed25519")


@async_test
async def test_payload_body_replay_after_commit_not_evictable(tmp_path):
    """A replayed producer frame for an already-committed (stored) body
    must not re-enter it into the evictable set — flooding the budget
    after the replay may never delete committed history."""
    from hotstuff_tpu.consensus.consensus import PayloadBodies, payload_key
    from hotstuff_tpu.store import Store

    store = Store(str(tmp_path / "db"))
    bodies = PayloadBodies(store, budget=1024)
    body0 = b"\x01" * 400
    d0 = Digest.of(body0)
    await bodies.admit(d0, body0)
    bodies.mark_committed([d0])
    # replay: must be a no-op (history is not evictable)
    await bodies.admit(d0, body0)
    # flood with unique bodies well past the budget
    for i in range(2, 8):
        b = bytes([i]) * 400
        await bodies.admit(Digest.of(b), b)
    assert await store.read(payload_key(d0)) == body0
    store.close()
