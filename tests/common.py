"""Shared consensus test fixtures.

Mirrors the reference's fixture strategy (consensus/src/tests/common.rs:
17-198): a deterministic 4-node committee from a fixed seed, synchronous
signing constructors, a valid-chain builder, and raw-TCP listener tasks
standing in for remote peers.
"""

from __future__ import annotations

import asyncio
import functools
import itertools

from hotstuff_tpu.consensus import QC, TC, Block, Committee, Timeout, Vote
from hotstuff_tpu.crypto import Digest, PublicKey, SecretKey, Signature, generate_keypair
from hotstuff_tpu.network.framing import read_frame, send_frame

SEED = bytes(32)

# unique port ranges per test to avoid clashes (common.rs:39-46)
_port_counter = itertools.count(26_000, 20)


def fresh_base_port() -> int:
    return next(_port_counter)


def async_test(fn):
    """Run an async test function to completion on a fresh event loop
    (the image has no pytest-asyncio)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))

    return wrapper


def keys(n: int = 4) -> list[tuple[PublicKey, SecretKey]]:
    """Deterministic committee keypairs, ordered by public key (so index i
    is also the round-robin leader of round r when r % n == i)."""
    pairs = [generate_keypair(SEED, i) for i in range(n)]
    pairs.sort(key=lambda kp: kp[0])
    return pairs


def committee(base_port: int, n: int = 4) -> Committee:
    return Committee.new(
        [
            (pk, 1, ("127.0.0.1", base_port + i))
            for i, (pk, _) in enumerate(keys(n))
        ]
    )


def secret_for(pk: PublicKey, n: int = 4) -> SecretKey:
    for cand, sk in keys(n):
        if cand == pk:
            return sk
    raise KeyError(pk)


def signed_block(
    author: PublicKey,
    secret: SecretKey,
    round_: int,
    qc: QC | None = None,
    tc: TC | None = None,
    payload: Digest | None = None,
) -> Block:
    block = Block(
        qc=qc if qc is not None else QC.genesis(),
        tc=tc,
        author=author,
        round=round_,
        payloads=(payload,) if payload is not None else (),
    )
    block.signature = Signature.new(block.digest(), secret)
    return block


def signed_vote(block: Block, author: PublicKey, secret: SecretKey) -> Vote:
    vote = Vote.for_block(block, author)
    vote.signature = Signature.new(vote.digest(), secret)
    return vote


def signed_timeout(
    high_qc: QC, round_: int, author: PublicKey, secret: SecretKey
) -> Timeout:
    timeout = Timeout(high_qc=high_qc, round=round_, author=author)
    timeout.signature = Signature.new(timeout.digest(), secret)
    return timeout


def qc_for_block(block: Block, n: int = 4, voters: int = 3) -> QC:
    """A valid QC over ``block`` signed by the first ``voters`` authorities
    (3 of 4 = quorum)."""
    vote_digest = Vote.for_block(block, keys(n)[0][0]).digest()
    return QC(
        hash=block.digest(),
        round=block.round,
        votes=[
            (pk, Signature.new(vote_digest, sk)) for pk, sk in keys(n)[:voters]
        ],
    )


def chain(length: int, n: int = 4) -> list[Block]:
    """A valid block chain b1..b_length with full QCs, each block authored
    by its round's round-robin leader (common.rs:147-179)."""
    pairs = keys(n)
    blocks: list[Block] = []
    qc = QC.genesis()
    for round_ in range(1, length + 1):
        author, secret = pairs[round_ % n]
        block = signed_block(
            author, secret, round_, qc=qc, payload=Digest.random()
        )
        blocks.append(block)
        qc = qc_for_block(block, n)
    return blocks


async def listener(
    port: int, expected: bytes | None = None, reply: bytes = b"Ack"
) -> bytes:
    """Bind a socket, accept one connection, return the first frame
    (optionally asserting its contents), reply with an ACK
    (common.rs:182-198)."""
    received: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()

    async def handle(reader, writer):
        try:
            frame = await read_frame(reader)
            await send_frame(writer, reply)
            if not received.done():
                received.set_result(frame)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", port)
    try:
        frame = await received
    finally:
        # NOTE: no wait_closed() — in 3.12 it blocks until every accepted
        # connection closes, and persistent senders hold theirs open.
        server.close()
    if expected is not None:
        assert frame == expected, "listener received unexpected frame"
    return frame
