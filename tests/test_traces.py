"""Flight-recorder + trace-reconstruction tests (ISSUE 2).

Covers the tentpole pieces — the bounded JSONL ring journal
(hotstuff_tpu/telemetry/journal.py) and the cross-node timeline
reconstruction (benchmark/traces.py): ring-segment bounds/rotation,
flush-on-close durability, clock-offset estimation on synthetic skewed
journals, a golden Perfetto (Chrome trace-event) export, and a 4-node
in-process end-to-end reconstruction — plus the off-by-default contract
(no journal dir resolved, no files written, when the knobs are unset).
"""

import asyncio
import json
import os

import pytest

from benchmark.traces import TraceSet, estimate_offsets, load_journals
from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry.journal import Journal

from .common import async_test, committee, fresh_base_port, keys

MS = 1_000_000  # ns per ms


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    """Telemetry/journal state is process-global: every test starts with
    journaling off and an empty registry, and leaves it that way."""
    monkeypatch.delenv("HOTSTUFF_TELEMETRY", raising=False)
    monkeypatch.delenv("HOTSTUFF_METRICS_PORT", raising=False)
    monkeypatch.delenv("HOTSTUFF_JOURNAL", raising=False)
    monkeypatch.delenv("HOTSTUFF_JOURNAL_DIR", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


class FakeDigest:
    """Stands in for crypto.Digest at journal-record time: the journal
    only calls encode_base64() at flush."""

    def __init__(self, s: str):
        self._s = s

    def encode_base64(self) -> str:
        return (self._s * 16)[:22]


# ---- journal ring segments ----------------------------------------------


def test_ring_rotation_bounds(tmp_path):
    """Segments rotate at segment_bytes and the ring keeps at most
    `segments` files on disk — a long run loses oldest events only."""
    j = Journal(
        "nodeA",
        str(tmp_path),
        segment_bytes=512,
        segments=3,
        buffer_records=4,
    )
    for i in range(400):
        j.record("commit", i, FakeDigest(f"d{i}"))
    j.close()

    files = sorted(tmp_path.glob("*.jsonl"))
    assert 1 <= len(files) <= 3
    assert j.segments_rotated > 0
    total_bytes = sum(f.stat().st_size for f in files)
    # the ring bound: segments * segment_bytes plus one record of slack
    # per file (rotation happens after the write that crosses the line)
    assert total_bytes < 3 * (512 + 256)

    highest_round = -1
    for f in files:
        lines = f.read_text().splitlines()
        # every segment opens with a meta line naming the node
        meta = json.loads(lines[0])
        assert meta["e"] == "meta"
        assert meta["n"] == "nodeA"
        for line in lines[1:]:
            rec = json.loads(line)  # all lines are valid JSON
            assert rec["e"] == "commit"
            highest_round = max(highest_round, rec["r"])
    # the NEWEST events survive rotation (flight recorder, not archive)
    assert highest_round == 399


def test_flush_on_close_and_stats(tmp_path):
    """Buffered records survive close() even below the flush threshold,
    and stats() reflects the buffer/disk split."""
    j = Journal("nodeB", str(tmp_path), buffer_records=100)
    j.record("propose", 7, FakeDigest("x"), "peer1")
    j.record("timeout", 8)
    st = j.stats()
    assert st["records"] == 0 and st["buffered"] == 2
    j.close()
    assert j.stats()["records"] == 2

    journals = load_journals(str(tmp_path))
    assert list(journals) == ["nodeB"]
    events = [r["e"] for r in journals["nodeB"]]
    assert events == ["propose", "timeout"]
    rec = journals["nodeB"][0]
    assert rec["r"] == 7 and rec["p"] == "peer1"
    assert len(rec["d"]) == 16
    assert rec["m"] > 0 and rec["w"] > 0


def test_sanitized_filenames_meta_authority(tmp_path):
    """Node ids are base64 prefixes ('/', '+' are legal): filenames are
    sanitized but load_journals recovers the true id from the meta
    line."""
    node = "ab/+C3=="
    j = Journal(node, str(tmp_path), buffer_records=1)
    j.record("commit", 1, FakeDigest("z"))
    j.close()
    (path,) = tmp_path.glob("*.jsonl")
    assert "/" not in path.name[:-6] and "+" not in path.name
    journals = load_journals(str(tmp_path))
    assert list(journals) == [node]


def test_stale_segments_dropped_on_reopen(tmp_path):
    """A new Journal under the same node prefix removes the previous
    run's segments, so trace merges never mix two runs."""
    j1 = Journal("nodeC", str(tmp_path), buffer_records=1)
    j1.record("commit", 1, FakeDigest("old"))
    j1.close()
    j2 = Journal("nodeC", str(tmp_path), buffer_records=1)
    j2.record("commit", 2, FakeDigest("new"))
    j2.close()
    journals = load_journals(str(tmp_path))
    assert [r["r"] for r in journals["nodeC"]] == [2]


def test_torn_line_skipped(tmp_path):
    """A crash mid-write leaves a torn final line; the loader skips it
    and keeps everything before it."""
    j = Journal("nodeD", str(tmp_path), buffer_records=1)
    j.record("commit", 1, FakeDigest("a"))
    j.record("commit", 2, FakeDigest("b"))
    j.close()
    (path,) = tmp_path.glob("*.jsonl")
    with open(path, "a") as f:
        f.write('{"e":"commit","r":3,"d":"tr')  # torn
    journals = load_journals(str(tmp_path))
    assert [r["r"] for r in journals["nodeD"]] == [1, 2]


# ---- off-by-default contract --------------------------------------------


def test_journal_off_by_default(tmp_path):
    """With no knob set nothing resolves a journal dir — so no Journal
    is built and no files appear (the overhead contract)."""
    assert not telemetry.journal_enabled()
    assert telemetry.journal_dir(str(tmp_path / "store")) is None


def test_journal_dir_resolution(tmp_path, monkeypatch):
    """HOTSTUFF_JOURNAL=1 defaults to <store>.journal; the explicit dir
    knobs (env, then set_journal_dir / --journal-dir) take precedence."""
    store = str(tmp_path / "store")
    monkeypatch.setenv("HOTSTUFF_JOURNAL", "1")
    assert telemetry.journal_enabled()
    assert telemetry.journal_dir(store) == store + ".journal"
    monkeypatch.setenv("HOTSTUFF_JOURNAL_DIR", str(tmp_path / "env_dir"))
    assert telemetry.journal_dir(store) == str(tmp_path / "env_dir")
    telemetry.set_journal_dir(str(tmp_path / "flag_dir"))
    assert telemetry.journal_dir(store) == str(tmp_path / "flag_dir")
    # an explicit dir alone (the --journal-dir flag path) also enables
    telemetry.reset()
    monkeypatch.delenv("HOTSTUFF_JOURNAL", raising=False)
    monkeypatch.delenv("HOTSTUFF_JOURNAL_DIR", raising=False)
    telemetry.set_journal_dir(str(tmp_path / "flag_dir"))
    assert telemetry.journal_enabled()
    assert telemetry.journal_dir(store) == str(tmp_path / "flag_dir")


# ---- clock-offset estimation --------------------------------------------


def _rec(e, r=0, d="", p="", m=0, w=0):
    return {"e": e, "r": r, "d": d, "p": p, "m": m, "w": w}


def _skewed_journals(skew_b=50 * MS, skew_c=-20 * MS):
    """Three nodes, A's clock true, B ahead by skew_b, C by skew_c.
    A proposes rounds 1..8; B and C receive after a 2 ms network delay,
    vote 0.5 ms later; the votes arrive back at A 2 ms after sending
    (the symmetric reverse path the offset estimate needs); A forms the
    QC at +5 ms and everyone commits at +8/+9/+10 ms.  Each journal
    stamps `w` with ITS OWN skewed clock."""
    t0 = 1_000_000 * MS
    a, b, c = [], [], []
    for i in range(1, 9):
        d = f"digest{i:02d}00000000"[:16]
        tp = t0 + i * 100 * MS  # true propose instant
        a.append(_rec("propose", i, d, m=tp, w=tp))
        for recs, skew, node in ((b, skew_b, "B"), (c, skew_c, "C")):
            tr = tp + 2 * MS  # true arrival
            recs.append(_rec("recv.propose", i, d, "A", m=tr, w=tr + skew))
            tv = tr + MS // 2
            recs.append(_rec("vote.send", i, d, "A", m=tv, w=tv + skew))
            ta = tv + 2 * MS  # vote crosses back to A, symmetric delay
            a.append(_rec("recv.vote", i, d, node, m=ta, w=ta))
        tq = tp + 5 * MS
        a.append(_rec("qc", i, d, m=tq, w=tq))
        for recs, skew, dt in ((a, 0, 8), (b, skew_b, 9), (c, skew_c, 10)):
            tc_ = tp + dt * MS
            recs.append(_rec("commit", i, d, m=tc_, w=tc_ + skew))
    return {"A": a, "B": b, "C": c}


def test_offset_estimation_recovers_skew():
    journals = _skewed_journals()
    offsets, reference = estimate_offsets(journals)
    assert reference is not None
    # relative offsets are what matters: rebase onto A
    rel = {n: (offsets[n] - offsets["A"]) / MS for n in offsets}
    assert rel["A"] == pytest.approx(0.0, abs=0.6)
    assert rel["B"] == pytest.approx(50.0, abs=0.6)
    assert rel["C"] == pytest.approx(-20.0, abs=0.6)


def test_reconstruction_and_edge_gaps():
    ts = TraceSet(_skewed_journals())
    assert len(ts.committed()) == 8
    assert ts.coverage() == 1.0
    gaps = ts.edge_gaps()
    # corrected clocks put every edge back at its true duration
    from statistics import mean

    assert mean(gaps["propose_to_recv"]) == pytest.approx(2.0, abs=0.1)
    assert mean(gaps["recv_to_vote"]) == pytest.approx(0.5, abs=0.1)
    assert mean(gaps["propose_to_qc"]) == pytest.approx(5.0, abs=0.1)
    assert max(gaps["propose_to_commit"]) == pytest.approx(10.0, abs=0.1)
    # C commits last every round — straggler attribution names it
    node, hits = gaps["commit_straggler"].most_common(1)[0]
    assert node == "C" and hits == 8
    text = ts.summary()
    assert "CROSS-NODE TRACE" in text
    assert "8/8 (100%)" in text
    assert "Straggler (last to commit): C" in text


def test_uncorrected_skew_would_dominate():
    """Sanity check that the correction is load-bearing: with 50 ms of
    skew and 2 ms of delay, RAW wall deltas would put propose->recv at
    ~52 ms; the corrected estimate must not."""
    ts = TraceSet(_skewed_journals())
    from statistics import mean

    assert mean(ts.edge_gaps()["propose_to_recv"]) < 5.0


# ---- producer edges and chaos-plane spans --------------------------------


def test_producer_waits_and_fault_spans():
    """Synthetic journal exercising the PR 3 record kinds: the
    recv.producer -> payload.first wait lands in payload_waits, and
    fault.open/close edges pair into labelled spans (a never-closed
    window survives with end=None and stretches to the horizon in the
    Perfetto export)."""
    s = 1_000_000_000  # 1 s in ns
    recs = [
        _rec("recv.producer", d="PAY1000000000000", p="client", m=s, w=s),
        _rec("payload.first", 3, "PAY1000000000000", m=s + s // 4, w=s + s // 4),
        # payload.first with no matching producer record: ignored
        _rec("payload.first", 4, "PAY2000000000000", m=2 * s, w=2 * s),
        _rec("fault.open", p="split", m=3 * s, w=3 * s),
        _rec("fault.close", p="split", m=8 * s, w=8 * s),
        # close without a prior open for that label: ignored
        _rec("fault.close", p="ghost", m=8 * s, w=8 * s),
        _rec("fault.open", p="flap", m=9 * s, w=9 * s),
        # an anchor block so the summary/export paths see real traffic
        _rec("propose", 5, "blk5000000000000", m=10 * s, w=10 * s),
        _rec("commit", 5, "blk5000000000000", m=11 * s, w=11 * s),
    ]
    ts = TraceSet({"A": recs})
    assert ts.payload_waits == pytest.approx([250.0])
    assert ts.fault_spans == [
        ("split", 3 * s, 8 * s),
        ("flap", 9 * s, None),
    ]
    text = ts.summary()
    assert "producer recv -> proposed" in text
    assert "mean  250.00 ms" in text
    assert "Fault windows journaled: 2 (flap, split)" in text

    doc = ts.chrome_trace()
    chaos = [e for e in doc["traceEvents"] if e.get("cat") == "fault"]
    assert {e["name"] for e in chaos} == {"split", "flap"}
    by_name = {e["name"]: e for e in chaos}
    assert by_name["split"]["args"]["closed"] is True
    assert by_name["split"]["dur"] == pytest.approx(5e6)  # 5 s in us
    # the open window runs to the horizon (the 11 s commit anchor is
    # not a span anchor; the last anchor is the 10 s propose)
    assert by_name["flap"]["args"]["closed"] is False
    assert by_name["flap"]["dur"] == pytest.approx(1e6)
    tracks = [
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("name") == "process_name"
    ]
    assert "chaos plane" in tracks


# ---- golden Perfetto export ---------------------------------------------


def test_chrome_trace_golden(tmp_path):
    journals = _skewed_journals()
    journals["A"].append(_rec("timeout", 9, m=10**9, w=2_000_000 * MS))
    ts = TraceSet(journals)
    doc = ts.chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]

    meta = [e for e in events if e["ph"] == "M"]
    assert {e["args"]["name"] for e in meta} == {
        "node A",
        "node B",
        "node C",
    }
    pids = {e["args"]["name"]: e["pid"] for e in meta}

    slices = [e for e in events if e["ph"] == "X"]
    # per block: one leader slice + one replica slice per receiver
    assert len(slices) == 8 * 3
    leader = [e for e in slices if e["args"]["role"] == "leader"]
    assert all(e["pid"] == pids["node A"] for e in leader)
    first = min(leader, key=lambda e: e["ts"])
    assert first["ts"] == pytest.approx(0.0, abs=1e3)  # anchored at run start
    # leader slice spans propose -> its own commit: 8 ms = 8000 us
    assert first["dur"] == pytest.approx(8_000.0, rel=0.05)
    assert all(e["dur"] >= 1.0 for e in slices)

    flows_s = {e["id"] for e in events if e["ph"] == "s"}
    flows_f = {e["id"] for e in events if e["ph"] == "f"}
    assert flows_s == flows_f  # every arrow has both ends
    assert len(flows_s) == 8 * 2  # one per propose->recv edge

    instants = [e for e in events if e["ph"] == "i"]
    assert len(instants) == 1
    assert instants[0]["name"] == "timeout r9"

    path = ts.export_chrome_trace(str(tmp_path / "sub" / "trace.json"))
    with open(path) as f:
        assert json.load(f) == doc  # valid JSON roundtrip


def test_empty_dir_yields_empty_trace(tmp_path):
    ts = TraceSet.load(str(tmp_path))
    assert ts.coverage() == 0.0
    assert ts.summary() == ""
    assert ts.chrome_trace()["traceEvents"] == []


# ---- 4-node end-to-end reconstruction -----------------------------------


@async_test
async def test_end_to_end_trace_reconstruction(tmp_path):
    """A journal-enabled 4-node committee commits blocks; the merged
    journals reconstruct >=95% of committed rounds, attribute
    stragglers, and export a valid Chrome trace (ISSUE 2 acceptance)."""
    from hotstuff_tpu.consensus import Consensus, Parameters
    from hotstuff_tpu.crypto import Digest, SignatureService
    from hotstuff_tpu.store import Store

    telemetry.enable()
    jdir = str(tmp_path / "journals")
    base = fresh_base_port()
    com = committee(base)
    nodes = []
    for i in range(4):
        name, secret = keys()[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        # the journal id must be str(name)[:8] — the id recv.* records
        # use for peers — and attach BEFORE spawn (actors capture
        # telemetry.journal at construction)
        tel = telemetry.for_node(str(name)[:8])
        journal = Journal(str(name)[:8], jdir, buffer_records=8)
        tel.attach_journal(journal)
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=1_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
            telemetry=tel,
        )
        nodes.append((stack, commit_q, store, journal))

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.02)

    feeder = asyncio.ensure_future(feed())
    try:
        for _, commit_q, _, _ in nodes:
            for _ in range(3):
                await asyncio.wait_for(commit_q.get(), timeout=20.0)
    finally:
        feeder.cancel()
        for stack, _, store, journal in nodes:
            await stack.shutdown()
            journal.close()
            store.close()

    ts = TraceSet.load(jdir)
    assert len(ts.nodes) == 4
    committed = ts.committed()
    assert len(committed) >= 3
    assert ts.coverage() >= 0.95

    # every reconstructed block has a full committee story: a leader,
    # receives at the other 3 nodes, and commits
    for d in ts.reconstructed():
        info = ts.blocks[d]
        assert info["leader"] in ts.nodes
        assert len(info["recv"]) == 3
        assert info["commit"]

    gaps = ts.edge_gaps()
    assert gaps["propose_to_recv"]
    assert all(-100.0 < v < 10_000.0 for v in gaps["propose_to_commit"])

    text = ts.summary()
    assert "CROSS-NODE TRACE" in text
    assert "propose -> replica recv" in text
    assert "100%" in text or "9" in text  # coverage line rendered

    doc = ts.chrome_trace()
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) >= 4
    path = ts.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        json.load(f)

    # journal stats flowed into the telemetry snapshot document
    snap_section = json.loads(json.dumps(journal.stats()))
    assert snap_section["records"] > 0
    assert os.listdir(jdir)
