"""Consensus-driven live reconfiguration (docs/RECONFIG.md): the typed
epoch-change op's codec and validation gate, schedule splicing, the
certified schedule-link walk joiners and restarts replay, the
epoch-boundary view-change backoff reset, and the reconfiguration
invariants the chaos harness applies to run logs.
"""

from __future__ import annotations

import asyncio
from types import SimpleNamespace

import pytest

from hotstuff_tpu.consensus import (
    QC,
    Committee,
    CommitteeSchedule,
    Core,
    Synchronizer,
    Vote,
)
from hotstuff_tpu.consensus.config import Authority, InvalidCommittee
from hotstuff_tpu.consensus.core import make_event_channels
from hotstuff_tpu.consensus.errors import InvalidReconfig
from hotstuff_tpu.consensus.leader import LeaderElector
from hotstuff_tpu.consensus.messages import Block
from hotstuff_tpu.consensus.reconfig import (
    MAX_RECONFIG_MEMBERS,
    RECONFIG_MAX_MARGIN,
    RECONFIG_MIN_MARGIN,
    ReconfigOp,
    newest_epoch,
    splice_schedule_links,
    validate_reconfig,
)
from hotstuff_tpu.consensus.wire import (
    MAX_SCHEDULE_LINKS,
    decode_schedule_links,
    encode_schedule_links,
)
from hotstuff_tpu.crypto import (
    Digest,
    Signature,
    SignatureService,
    generate_keypair,
)
from hotstuff_tpu.crypto.service import CpuVerifier
from hotstuff_tpu.store import Store
from hotstuff_tpu.utils.codec import CodecError, Encoder

from .common import SEED, async_test, fresh_base_port

MARGIN = 4


def five_keys():
    pairs = [generate_keypair(SEED, i) for i in range(5)]
    pairs.sort(key=lambda kp: kp[0])
    return pairs


def epoch1_committee(base: int, ks):
    return Committee.new(
        [(ks[i][0], 1, ("127.0.0.1", base + i)) for i in range(4)], epoch=1
    )


def epoch2_committee(base: int, ks):
    """Member 3 rotates out, member 4 in."""
    return Committee.new(
        [(ks[i][0], 1, ("127.0.0.1", base + i)) for i in (0, 1, 2, 4)],
        epoch=2,
    )


def sponsored_op(new_committee, sponsor_pair, margin: int = MARGIN):
    pk, sk = sponsor_pair
    op = ReconfigOp(new_committee=new_committee, margin=margin, sponsor=pk)
    op.signature = Signature.new(Digest(op.digest()), sk)
    return op


def reconfig_block(op, author_pair, round_: int) -> Block:
    pk, sk = author_pair
    block = Block(qc=QC.genesis(), author=pk, round=round_, reconfig=op)
    block.signature = Signature.new(block.digest(), sk)
    return block


def qc_over(block: Block, ks) -> QC:
    """3-of-4 epoch-1 quorum over ``block``."""
    vote_digest = Vote.for_block(block, ks[0][0]).digest()
    return QC(
        hash=block.digest(),
        round=block.round,
        votes=[(pk, Signature.new(vote_digest, sk)) for pk, sk in ks[:3]],
    )


# ---- op codec ---------------------------------------------------------------


def test_op_serialize_roundtrip():
    ks = five_keys()
    op = sponsored_op(epoch2_committee(9_300, ks), ks[0])
    again = ReconfigOp.deserialize(op.serialize())
    assert again.margin == op.margin
    assert again.sponsor == op.sponsor
    assert again.signature == op.signature
    assert again.new_committee.epoch == 2
    assert again.new_committee.scheme == "ed25519"
    assert again.new_committee.sorted_keys() == op.new_committee.sorted_keys()
    for name in op.new_committee.authorities:
        assert again.new_committee.address(name) == op.new_committee.address(
            name
        )
        assert again.new_committee.stake(name) == 1
    # the digest covers the body only, so the round-trip preserves it
    # and the sponsor signature still verifies
    assert again.digest() == op.digest()
    assert CpuVerifier().verify_one(
        Digest(again.digest()), again.sponsor, again.signature
    )


def test_op_decode_rejects_unknown_version():
    ks = five_keys()
    data = bytearray(sponsored_op(epoch2_committee(9_310, ks), ks[0]).serialize())
    data[0] = 0xFE
    with pytest.raises(CodecError, match="unknown reconfig op version"):
        ReconfigOp.deserialize(bytes(data))


def test_op_decode_caps_member_count():
    """A forged count field dies at the cap BEFORE any member reads."""
    ks = five_keys()
    op = sponsored_op(epoch2_committee(9_320, ks), ks[0])
    enc = Encoder()
    enc.u8(1)
    enc.u64(2)
    enc.var_bytes(b"ed25519")
    enc.u16(MAX_RECONFIG_MEMBERS + 1)
    with pytest.raises(CodecError, match="exceeds cap"):
        ReconfigOp.deserialize(enc.finish() + op.serialize())


def test_schedule_links_codec_roundtrip_and_cap():
    links = [(b"block-%d" % i, b"qc-%d" % i) for i in range(3)]
    assert decode_schedule_links(encode_schedule_links(links)) == links
    assert decode_schedule_links(encode_schedule_links([])) == []
    bomb = [(b"b", b"q")] * (MAX_SCHEDULE_LINKS + 1)
    with pytest.raises(CodecError, match="exceeds cap"):
        decode_schedule_links(encode_schedule_links(bomb))


# ---- the validation gate ----------------------------------------------------


def test_validate_accepts_a_well_formed_op():
    ks = five_keys()
    schedule = CommitteeSchedule([(1, epoch1_committee(9_330, ks))])
    op = sponsored_op(epoch2_committee(9_330, ks), ks[0])
    validate_reconfig(op, schedule, 5, verifier=CpuVerifier())
    assert newest_epoch(schedule) == 1


def test_validate_rejects_margin_out_of_bounds():
    ks = five_keys()
    schedule = CommitteeSchedule([(1, epoch1_committee(9_340, ks))])
    for margin in (0, RECONFIG_MIN_MARGIN - 1, RECONFIG_MAX_MARGIN + 1):
        op = sponsored_op(epoch2_committee(9_340, ks), ks[0], margin=margin)
        with pytest.raises(InvalidReconfig, match="activation margin"):
            validate_reconfig(op, schedule, 5)


def test_validate_rejects_malformed_committees():
    ks = five_keys()
    current = epoch1_committee(9_350, ks)
    schedule = CommitteeSchedule([(1, current)])

    empty = Committee(authorities={}, epoch=2, scheme="ed25519")
    with pytest.raises(InvalidReconfig, match="empty"):
        validate_reconfig(sponsored_op(empty, ks[0]), schedule, 5)

    zero_stake = Committee(
        authorities={
            pk: Authority(1 if i else 0, ("127.0.0.1", 9_350 + i))
            for i, (pk, _) in enumerate(ks[:4])
        },
        epoch=2,
        scheme="ed25519",
    )
    with pytest.raises(InvalidReconfig, match="zero-stake"):
        validate_reconfig(sponsored_op(zero_stake, ks[0]), schedule, 5)

    skipped = Committee(
        authorities=dict(current.authorities), epoch=3, scheme="ed25519"
    )
    with pytest.raises(InvalidReconfig, match="does not succeed"):
        validate_reconfig(sponsored_op(skipped, ks[0]), schedule, 5)


def test_validate_rejects_attacker_only_committee():
    """A structurally valid committee of all-fresh keys fails the
    carried-over-stake continuity rule."""
    ks = five_keys()
    schedule = CommitteeSchedule([(1, epoch1_committee(9_360, ks))])
    strangers = [generate_keypair(b"\x42" * 32, i) for i in range(4)]
    foreign = Committee.new(
        [(pk, 1, ("10.0.0.1", 9_000 + i)) for i, (pk, _) in enumerate(strangers)],
        epoch=2,
    )
    with pytest.raises(InvalidReconfig, match="carried-over stake"):
        validate_reconfig(sponsored_op(foreign, ks[0]), schedule, 5)


def test_validate_rejects_bad_sponsor():
    ks = five_keys()
    schedule = CommitteeSchedule([(1, epoch1_committee(9_370, ks))])
    new = epoch2_committee(9_370, ks)

    # a non-member sponsor is refused before any signature check
    stranger = generate_keypair(b"\x43" * 32, 0)
    with pytest.raises(InvalidReconfig, match="sponsor"):
        validate_reconfig(sponsored_op(new, stranger), schedule, 5)

    # a member sponsor with a forged signature dies at the verifier
    op = sponsored_op(new, ks[0])
    op.signature = Signature.new(Digest(op.digest()), ks[1][1])  # wrong key
    with pytest.raises(InvalidReconfig, match="bad sponsor signature"):
        validate_reconfig(op, schedule, 5, verifier=CpuVerifier())
    # ... but passes the structural gate when no verifier is supplied
    validate_reconfig(op, schedule, 5)


def test_block_verify_gates_the_embedded_op():
    """A block carrying an epoch change is verified as a unit: the op is
    covered by the block digest and re-validated inside Block.verify, so
    a forged reconfiguration never earns an honest vote."""
    ks = five_keys()
    schedule = CommitteeSchedule([(1, epoch1_committee(9_380, ks))])
    verifier = CpuVerifier()

    op = sponsored_op(epoch2_committee(9_380, ks), ks[0])
    block = reconfig_block(op, ks[1], round_=3)
    block.verify(schedule, verifier)
    # the op digest is part of the block digest
    plain = Block(qc=QC.genesis(), author=ks[1][0], round=3)
    assert block.digest() != plain.digest()
    # wire round-trip preserves the op and still verifies
    again = Block.deserialize(block.serialize())
    assert again.reconfig is not None
    assert again.reconfig.digest() == op.digest()
    again.verify(schedule, verifier)

    forged = sponsored_op(epoch2_committee(9_380, ks), ks[0])
    forged.signature = Signature.new(Digest(forged.digest()), ks[1][1])
    bad = reconfig_block(forged, ks[1], round_=3)
    with pytest.raises(InvalidReconfig):
        bad.verify(schedule, verifier)


# ---- splicing and the certified-link walk ----------------------------------


def test_splice_is_idempotent_and_monotonic():
    ks = five_keys()
    epoch1 = epoch1_committee(9_390, ks)
    epoch2 = epoch2_committee(9_390, ks)
    schedule = CommitteeSchedule([(1, epoch1)])
    gen = schedule.generation

    assert schedule.splice(10, epoch2) is True
    assert schedule.generation == gen + 1
    assert schedule.for_round(9) is epoch1
    assert schedule.for_round(10) is epoch2
    # exact replay (crash-recovery re-commit): no-op, no generation bump
    assert schedule.splice(10, epoch2) is False
    assert schedule.generation == gen + 1
    # genuinely conflicting splices are refused
    with pytest.raises(InvalidCommittee):
        schedule.splice(8, Committee(
            authorities=dict(epoch2.authorities), epoch=3, scheme="ed25519"
        ))
    with pytest.raises(InvalidCommittee):
        schedule.splice(20, epoch1)  # non-monotonic epoch


def test_splice_schedule_links_walk():
    """The verified-successor walk: a joiner holding only the genesis
    committee replays a certified (block, QC) chain into the same
    schedule a live witness holds — and rejects tampered links."""
    ks = five_keys()
    base = 9_400
    verifier = CpuVerifier()
    epoch2 = epoch2_committee(base, ks)
    op = sponsored_op(epoch2, ks[0])
    block = reconfig_block(op, ks[1], round_=6)
    qc = qc_over(block, ks)
    enc = Encoder()
    qc.encode(enc)
    links = [(block.serialize(), enc.finish())]

    joiner = CommitteeSchedule([(1, epoch1_committee(base, ks))])
    assert splice_schedule_links(links, joiner, verifier) == 1
    assert joiner.for_round(6 + MARGIN).epoch == 2
    assert joiner.for_round(6 + MARGIN - 1).epoch == 1
    # replay: already-spliced epochs are skipped, not re-validated
    assert splice_schedule_links(links, joiner, verifier) == 0

    # a QC that does not certify the link's block is rejected
    other = reconfig_block(op, ks[2], round_=6)
    enc = Encoder()
    qc_over(other, ks).encode(enc)
    fresh = CommitteeSchedule([(1, epoch1_committee(base, ks))])
    with pytest.raises(InvalidReconfig, match="does not certify"):
        splice_schedule_links([(block.serialize(), enc.finish())], fresh, verifier)

    # a sub-quorum certificate is rejected too
    weak = QC(hash=qc.hash, round=qc.round, votes=qc.votes[:2])
    enc = Encoder()
    weak.encode(enc)
    fresh = CommitteeSchedule([(1, epoch1_committee(base, ks))])
    with pytest.raises(InvalidReconfig, match="failed to verify"):
        splice_schedule_links([(block.serialize(), enc.finish())], fresh, verifier)

    # corrupt bytes are a clean typed error, never a crash
    fresh = CommitteeSchedule([(1, epoch1_committee(base, ks))])
    with pytest.raises(InvalidReconfig, match="corrupt"):
        splice_schedule_links([(b"\x00\x01", b"\x02")], fresh, verifier)

    # a static committee cannot accept links at all
    with pytest.raises(InvalidReconfig, match="static committee"):
        splice_schedule_links(links, epoch1_committee(base, ks), verifier)


# ---- the epoch-boundary backoff reset (bugfix) ------------------------------


def make_core(tmp_path, schedule, name, secret, timeout_ms=10_000):
    store = Store(str(tmp_path / "db"))
    rx_events, rx_message, loopback = make_event_channels(2_000)
    sync = Synchronizer(name, schedule, store, loopback, 10_000)
    core = Core(
        name,
        schedule,
        SignatureService(secret),
        CpuVerifier(),
        store,
        LeaderElector(schedule),
        sync,
        timeout_ms,
        rx_events=rx_events,
        rx_loopback=loopback,
        tx_proposer=asyncio.Queue(),
        tx_commit=asyncio.Queue(),
    )
    return SimpleNamespace(core=core, store=store, sync=sync)


@async_test
async def test_backoff_exponent_resets_on_epoch_activation(tmp_path):
    """Bugfix coverage: a backed-off view-change timer carried across an
    epoch boundary measured the OLD committee's liveness trouble — the
    boundary must snap it back to base, exactly like a QC advance."""
    ks = five_keys()
    base = fresh_base_port()
    schedule = CommitteeSchedule(
        [(1, epoch1_committee(base, ks)), (10, epoch2_committee(base, ks))]
    )
    h = make_core(tmp_path, schedule, ks[0][0], ks[0][1])
    try:
        core = h.core
        core.round = 9
        core._active_epoch = 1
        core._timeout_exponent = 3
        core._consecutive_tcs = 3
        core.timer.set_duration_ms(80_000)

        core._maybe_activate_epoch()  # same epoch: backoff untouched
        assert core._timeout_exponent == 3
        assert core._active_epoch == 1

        core.round = 10
        core._maybe_activate_epoch()
        assert core._active_epoch == 2
        assert core._timeout_exponent == 0
        assert core._consecutive_tcs == 0
        assert core.timer.duration == pytest.approx(10_000 / 1000.0)
        # still a member of epoch 2: no retirement scheduled
        assert core._retire_after is None
    finally:
        h.core.shutdown()
        h.sync.shutdown()
        h.store.close()


@async_test
async def test_excluded_member_schedules_retirement(tmp_path):
    """Crossing into an epoch that drops this node arms the grace-window
    retirement instead of an abrupt exit."""
    ks = five_keys()
    base = fresh_base_port()
    schedule = CommitteeSchedule(
        [(1, epoch1_committee(base, ks)), (10, epoch2_committee(base, ks))]
    )
    # member 3 is rotated out at round 10
    h = make_core(tmp_path, schedule, ks[3][0], ks[3][1])
    try:
        core = h.core
        core._active_epoch = 1
        core.round = 10
        core._maybe_activate_epoch()
        assert core._retire_after == 10 + core._grace_rounds
        assert core.retired is False
    finally:
        h.core.shutdown()
        h.sync.shutdown()
        h.store.close()


# ---- run-log invariants (benchmark/invariants.py, telemetry/health.py) ------


def test_epoch_agreement_invariant():
    from benchmark.invariants import check_epoch_agreement

    ok, viol, details = check_epoch_agreement({})
    assert ok is None and not viol

    ok, viol, details = check_epoch_agreement(
        {"node-0": [(2, 20)], "node-1": [(2, 20)], "node-2": [(2, 20)]}
    )
    assert ok is True and not viol
    assert details["max_epoch"] == 2

    ok, viol, _ = check_epoch_agreement(
        {"node-0": [(2, 20)], "node-1": [(2, 23)]}
    )
    assert ok is False
    assert any("epoch 2" in v for v in viol)

    # a node re-activating the same epoch at a different round (restart
    # replaying a divergent history) is a violation too
    ok, viol, _ = check_epoch_agreement({"node-0": [(2, 20), (2, 21)]})
    assert ok is False


def test_handoff_gap_invariant():
    from benchmark.invariants import check_handoff_gap

    commits = {
        "node-0": [(0.0, r, "d") for r in (17, 18, 19, 23, 24)],
        "node-1": [(0.0, r, "d") for r in (18, 19, 23)],
    }
    epochs = {"node-0": [(2, 20)], "node-1": [(2, 20)]}

    ok, viol, details = check_handoff_gap(commits, epochs, bound=8)
    assert ok is True and not viol
    assert details["max_gap"] == 4  # 23 - 19 across the boundary at 20

    ok, viol, _ = check_handoff_gap(commits, epochs, bound=3)
    assert ok is False

    # a shadow reporter cannot move the modal boundary, and untrusted
    # observations are dropped entirely
    skewed = dict(epochs)
    skewed["node-2"] = [(2, 27)]
    ok, _, details = check_handoff_gap(
        commits, skewed, bound=8, untrusted={"node-2"}
    )
    assert ok is True and details["max_gap"] == 4

    # no commit at/after the boundary = a stalled handoff
    stalled = {"node-0": [(0.0, r, "d") for r in (17, 18, 19)]}
    ok, viol, _ = check_handoff_gap(stalled, epochs, bound=8)
    assert ok is False
    assert any("stall" in v for v in viol)

    ok, _, _ = check_handoff_gap(commits, {}, bound=8)
    assert ok is None


def test_epoch_skew_health_detector():
    from hotstuff_tpu.telemetry.health import epoch_skew

    assert epoch_skew({}) == []
    assert epoch_skew({"node-0": 2}) == []
    assert epoch_skew({"node-0": 2, "node-1": 2}) == []

    fired = epoch_skew({"node-0": 2, "node-1": 1, "node-2": None})
    assert len(fired) == 1
    incident = fired[0]
    assert incident.kind == "epoch_skew"
    assert incident.severity == "crit"
    assert "node-1@1" in incident.detail


def test_summary_epoch_lines():
    """The SUMMARY surfaces epoch transitions and the boundary commit
    gap (benchmark/logs.py plumbing, driven without real log files)."""
    from benchmark.logs import LogParser, RE_EPOCH

    line = (
        "(2026-08-05T12:00:01.123Z) [2026-08-05 12:00:01,123] INFO "
        "Epoch 2 activated at round 20"
    )
    assert RE_EPOCH.findall(line) == [("2026-08-05T12:00:01.123", "2", "20")]

    parser = LogParser.__new__(LogParser)
    parser.epoch_activations = {2: {20}}
    parser.commits = {f"b{r}": float(r) for r in (17, 18, 19, 23)}
    parser.block_round = {f"b{r}": r for r in (17, 18, 19, 23)}
    gap = parser.epoch_boundary_gap()
    assert gap == 4
    txt = parser._epoch_txt()
    assert "Epoch transitions: 1" in txt
    assert "epoch 2 at round 20" in txt
    assert "Max commit gap across a boundary: 4" in txt

    parser.epoch_activations = {}
    assert parser.epoch_boundary_gap() is None
    assert parser._epoch_txt() == ""
