"""Native C++ WAL engine: parity with the Python engine, crash-kill
recovery, compaction, fsync modes (reference store durability semantics,
store/src/lib.rs + SURVEY.md §5 "the store IS the checkpoint")."""

from __future__ import annotations

import os
import struct
import subprocess
import sys

import pytest

from hotstuff_tpu.store.engine import WalEngine

try:
    from hotstuff_tpu.store.native import NativeEngine

    _HAVE_NATIVE = True
except (ImportError, OSError):  # no compiler in this environment
    _HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not _HAVE_NATIVE, reason="native lib not built")


@needs_native
def test_native_put_get_delete_roundtrip(tmp_path):
    e = NativeEngine(str(tmp_path / "db"))
    e.put(b"a", b"1")
    e.put(b"b", b"2" * 1000)
    e.put(b"a", b"3")  # overwrite
    e.delete(b"b")
    assert e.get(b"a") == b"3"
    assert e.get(b"b") is None
    assert e.get(b"missing") is None
    assert len(e) == 1
    assert set(e.keys()) == {b"a"}
    e.put(b"", b"empty-key")  # empty key and value edge cases
    e.put(b"ev", b"")
    assert e.get(b"") == b"empty-key"
    assert e.get(b"ev") == b""
    e.close()


@needs_native
def test_native_reopen_recovers(tmp_path):
    path = str(tmp_path / "db")
    e = NativeEngine(path)
    for i in range(100):
        e.put(f"k{i}".encode(), f"v{i}".encode() * 10)
    e.delete(b"k50")
    e.close()
    e2 = NativeEngine(path)
    assert len(e2) == 99
    assert e2.get(b"k7") == b"v7" * 10
    assert e2.get(b"k50") is None
    e2.close()


@needs_native
def test_cross_engine_wal_interop(tmp_path):
    """Python and C++ engines share the WAL format bit-for-bit."""
    path = str(tmp_path / "db")
    w = WalEngine(path)
    w.put(b"py", b"from-python")
    w.delete(b"gone")
    w.close()
    e = NativeEngine(path)
    assert e.get(b"py") == b"from-python"
    e.put(b"cc", b"from-cpp")
    e.close()
    w2 = WalEngine(path)
    assert w2.get(b"py") == b"from-python"
    assert w2.get(b"cc") == b"from-cpp"
    w2.close()


@needs_native
def test_native_torn_tail_truncated(tmp_path):
    """A torn (half-written) trailing record is discarded and truncated."""
    path = str(tmp_path / "db")
    e = NativeEngine(path)
    e.put(b"good", b"value")
    e.close()
    wal = os.path.join(path, "wal.log")
    with open(wal, "ab") as f:
        f.write(struct.pack("<II", 4, 100))  # header promises 100-byte value
        f.write(b"torn")  # ...but the process died here
    e2 = NativeEngine(path)
    assert e2.get(b"good") == b"value"
    assert len(e2) == 1
    e2.close()
    # tail was truncated: a fresh append replays cleanly
    e3 = NativeEngine(path)
    e3.put(b"after", b"recovery")
    e3.close()
    e4 = NativeEngine(path)
    assert e4.get(b"after") == b"recovery"
    assert len(e4) == 2
    e4.close()


_KILL_SCRIPT = r"""
import os, sys
sys.path.insert(0, {root!r})
from hotstuff_tpu.store.native import NativeEngine
e = NativeEngine({path!r}, fsync_mode=1)
for i in range(50):
    e.put(f"key{{i}}".encode(), b"x" * 100)
os.kill(os.getpid(), 9)  # die without close()
"""


@needs_native
def test_native_survives_sigkill(tmp_path):
    """Process killed mid-sequence (no close): every acknowledged put is
    recovered on reopen (VERDICT r1 item 9)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = str(tmp_path / "db")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT.format(root=root, path=path)],
        capture_output=True,
        timeout=60,
    )
    assert proc.returncode == -9  # SIGKILL
    e = NativeEngine(path)
    assert len(e) == 50
    for i in range(50):
        assert e.get(f"key{i}".encode()) == b"x" * 100
    e.close()


@needs_native
def test_native_compaction_bounds_wal(tmp_path):
    """Overwriting the same keys grows the log; reopen compacts it."""
    path = str(tmp_path / "db")
    e = NativeEngine(path)
    for round_ in range(300):
        for k in range(10):
            e.put(f"key{k}".encode(), bytes([round_ % 256]) * 1024)
    grown = e.wal_bytes()
    e.close()
    assert grown > 2 * 10 * 1100  # lots of dead records
    e2 = NativeEngine(path)
    assert e2.wal_bytes() < grown / 10  # compacted on open
    assert len(e2) == 10
    for k in range(10):
        assert e2.get(f"key{k}".encode()) == bytes([299 % 256]) * 1024
    e2.close()


def test_python_wal_compaction_and_fsync(tmp_path):
    """The pure-Python engine has the same compaction + fsync options."""
    path = str(tmp_path / "db")
    e = WalEngine(path, fsync_mode=1)
    for round_ in range(300):
        for k in range(10):
            e.put(f"key{k}".encode(), bytes([round_ % 256]) * 1024)
    e.close()
    grown = os.path.getsize(os.path.join(path, "wal.log"))
    e2 = WalEngine(path)
    compacted = os.path.getsize(os.path.join(path, "wal.log"))
    assert compacted < grown / 10
    assert len(e2) == 10
    e2.close()


@needs_native
def test_store_actor_uses_native_engine(tmp_path):
    """open_engine prefers the native engine when the library is built."""
    from hotstuff_tpu.store import open_engine

    e = open_engine(str(tmp_path / "db"))
    assert type(e).__name__ == "NativeEngine"
    e.close()
