"""Replicated execution layer unit tests: typed-op codec, incremental
state root, determinism across replicas, meta persistence, snapshot
manifest/chunk/adopt roundtrips, delta filtering, and the state wire
frames (request/manifest/chunk/read/value).

The e2e half (SIGKILL + snapshot rejoin with converging roots) lives in
tests/test_crash_rejoin_e2e.py; these tests pin the building blocks it
relies on.
"""

from __future__ import annotations

import pytest

from hotstuff_tpu.consensus.errors import SerializationError
from hotstuff_tpu.consensus.wire import (
    MAX_STATE_CHUNK_ENTRIES,
    STATE_REQ_CHUNK,
    STATE_REQ_DELTA,
    STATE_REQ_MANIFEST,
    STATE_READ_LEDGER,
    STATE_READ_USER,
    STATE_VALUE_TAG,
    TAG_STATE_CHUNK,
    TAG_STATE_MANIFEST,
    TAG_STATE_READ,
    TAG_STATE_REQUEST,
    decode_message,
    decode_state_value,
    encode_state_chunk,
    encode_state_manifest,
    encode_state_read,
    encode_state_request,
    encode_state_value,
)
from hotstuff_tpu.crypto import Digest
from hotstuff_tpu.store import Store
from hotstuff_tpu.store.state import (
    GENESIS_ROOT,
    MAX_OPS_PER_BODY,
    OP_BODY_OFFSET,
    OP_MAGIC,
    SNAPSHOT_CHUNK_ENTRIES,
    SnapshotManifest,
    StateError,
    StateMachine,
    decode_ops,
    encode_ops,
    fold_root,
)

from .common import chain, keys, qc_for_block


def _store(tmp_path, name: str) -> Store:
    return Store(str(tmp_path / name))


def _typed_body(ops) -> bytes:
    """A payload body as the ingest plane stores it: the 8-byte producer
    counter prefix, then the typed-op blob."""
    return b"\x00" * OP_BODY_OFFSET + encode_ops(ops)


# ---- typed-op codec --------------------------------------------------------


def test_ops_codec_roundtrip():
    ops = [
        ("put", b"alpha", b"1"),
        ("del", b"beta"),
        ("put", b"gamma", b""),
        ("put", b"k" * 256, b"v" * 4096),
    ]
    body = _typed_body(ops)
    assert decode_ops(body) == ops
    assert decode_ops(_typed_body([])) == []


def test_decode_ops_rejects_malformed():
    # opaque (non-typed) bodies are legal and decode to None
    assert decode_ops(b"\x00" * OP_BODY_OFFSET + b"not-typed") is None
    assert decode_ops(b"") is None

    good = _typed_body([("put", b"key", b"value")])
    # truncation anywhere inside the op must yield None, never raise
    for cut in range(OP_BODY_OFFSET + len(OP_MAGIC) + 1, len(good)):
        assert decode_ops(good[:cut]) is None

    prefix = b"\x00" * OP_BODY_OFFSET + OP_MAGIC
    # zero-length key
    assert decode_ops(prefix + bytes([0, 0, 0, 0, 0, 0, 0])) is None
    # unknown op kind
    assert decode_ops(prefix + bytes([7, 1, 0, 0, 0, 0, 0]) + b"k") is None
    # delete carrying a value length
    assert decode_ops(prefix + bytes([1, 1, 0, 1, 0, 0, 0]) + b"k") is None
    # op-count bomb
    too_many = _typed_body(
        [("put", b"k", b"v")] * (MAX_OPS_PER_BODY + 1)
    )
    assert decode_ops(too_many) is None
    # exactly at the cap is fine
    at_cap = _typed_body([("put", b"k", b"v")] * MAX_OPS_PER_BODY)
    assert len(decode_ops(at_cap)) == MAX_OPS_PER_BODY


def test_fold_root_accepts_bytes_and_digest():
    d = Digest.random()
    block = Digest.random().to_bytes()
    via_digest = fold_root(GENESIS_ROOT, 7, block, [d])
    via_bytes = fold_root(GENESIS_ROOT, 7, block, [d.to_bytes()])
    assert via_digest == via_bytes
    assert via_digest != GENESIS_ROOT
    # the fold is order- and round-sensitive
    assert fold_root(GENESIS_ROOT, 8, block, [d]) != via_digest


# ---- deterministic apply ---------------------------------------------------


def test_apply_is_deterministic_across_replicas(tmp_path):
    blocks = chain(5)
    sm_a = StateMachine(_store(tmp_path, "a"))
    sm_b = StateMachine(_store(tmp_path, "b"))
    for block in blocks:
        root_a = sm_a.apply_block(block)
        root_b = sm_b.apply_block(block)
        assert root_a == root_b
    assert sm_a.version == sm_b.version == len(blocks)
    assert sm_a.root == sm_b.root
    assert sm_a.reported_root == sm_a.root
    assert sm_a.last_round == blocks[-1].round


def test_reported_root_diverges_under_shadow_digest(tmp_path):
    blocks = chain(3)
    honest = StateMachine(_store(tmp_path, "honest"))
    collude = StateMachine(_store(tmp_path, "collude"))
    for block in blocks[:-1]:
        honest.apply_block(block)
        collude.apply_block(block)
    honest.apply_block(blocks[-1])
    collude.apply_block(blocks[-1], reported_digest=Digest.random())
    # the lie shows up in the claimed root, never in the real state
    assert collude.root == honest.root
    assert collude.reported_root != honest.reported_root


def test_apply_skips_already_applied_rounds(tmp_path):
    blocks = chain(2)
    sm = StateMachine(_store(tmp_path, "db"))
    assert sm.apply_block(blocks[0]) is not None
    before = (sm.version, sm.root, sm.applied_payloads)
    # crash-recovery overlap: the consensus cursor can trail state
    assert sm.apply_block(blocks[0]) is None
    assert (sm.version, sm.root, sm.applied_payloads) == before
    assert sm.apply_block(blocks[1]) is not None
    assert sm.version == 2


def test_meta_persists_across_reopen(tmp_path):
    store = _store(tmp_path, "db")
    sm = StateMachine(store)
    for block in chain(4):
        sm.apply_block(block)
    anchor = sm.anchor()
    reported = sm.reported_root
    store.engine.close()

    sm2 = StateMachine(_store(tmp_path, "db"))
    assert sm2.anchor() == anchor
    assert sm2.reported_root == reported
    assert sm2.applied_payloads == sm.applied_payloads


# ---- typed ops and the read path -------------------------------------------


def test_typed_ops_materialize_user_state(tmp_path):
    store = _store(tmp_path, "db")
    blocks = chain(3)
    # stash typed bodies for the first two blocks' payloads, as the
    # ingest plane would have before commit
    body0 = _typed_body([("put", b"user", b"v1")])
    body1 = _typed_body([("put", b"user", b"v2"), ("del", b"gone")])
    store.engine.put(b"p" + blocks[0].payloads[0].to_bytes(), body0)
    store.engine.put(b"p" + blocks[1].payloads[0].to_bytes(), body1)

    sm = StateMachine(store)
    for block in blocks:
        sm.apply_block(block)

    round_, value = sm.read_user(b"user")
    assert value == b"v2"
    assert round_ == blocks[1].round
    # tombstone and never-written keys both read as absent
    assert sm.read_user(b"gone") is None
    assert sm.read_user(b"never") is None
    assert sm.typed_ops == 3

    # every committed payload is in the ledger index
    for block in blocks:
        entry = sm.read_ledger(block.payloads[0].to_bytes())
        assert entry == (block.round, 0)
    assert sm.read_ledger(Digest.random().to_bytes()) is None


# ---- snapshots -------------------------------------------------------------


def test_snapshot_roundtrip_into_fresh_store(tmp_path):
    src_store = _store(tmp_path, "src")
    blocks = chain(6)
    src_store.engine.put(
        b"p" + blocks[2].payloads[0].to_bytes(),
        _typed_body([("put", b"carried", b"over")]),
    )
    src = StateMachine(src_store)
    for block in blocks:
        src.apply_block(block)

    manifest = src.manifest()
    assert manifest.version == src.version
    assert manifest.root == src.root
    entries = []
    for index in range(manifest.chunk_count):
        chunk = src.chunk(index)
        assert 0 < len(chunk) <= SNAPSHOT_CHUNK_ENTRIES
        entries.extend(chunk)

    dst = StateMachine(_store(tmp_path, "dst"))
    dst.adopt(manifest, entries)
    assert dst.anchor() == src.anchor()
    assert dst.reported_root == src.root
    assert dst.synced_from_snapshot
    # the adopted state answers the same reads as the source
    assert dst.read_user(b"carried") == src.read_user(b"carried")
    for block in blocks:
        digest = block.payloads[0].to_bytes()
        assert dst.read_ledger(digest) == src.read_ledger(digest)


def test_delta_entries_filter_by_round(tmp_path):
    sm = StateMachine(_store(tmp_path, "db"))
    blocks = chain(6)
    for block in blocks:
        sm.apply_block(block)
    cut = blocks[3].round
    full = sm._entries()
    delta = sm._entries(from_round=cut)
    assert len(full) == len(blocks)
    assert len(delta) == len([b for b in blocks if b.round > cut])
    assert set(delta) <= set(full)
    for _, value in delta:
        assert int.from_bytes(value[:8], "little") > cut
    # the delta manifest still anchors at the server's full cursor
    assert sm.manifest(from_round=cut).version == sm.version


def test_adopt_rejects_entries_outside_state_namespace(tmp_path):
    sm = StateMachine(_store(tmp_path, "db"))
    manifest = SnapshotManifest(1, Digest.random().to_bytes(), 1, 0, 1)
    with pytest.raises(StateError):
        sm.adopt(manifest, [(b"p" + b"\x00" * 32, b"smuggled body")])
    with pytest.raises(StateError):
        sm.adopt(manifest, [(b"s/meta", b"cursor overwrite")])
    # a poisoned snapshot must not move the cursor
    assert sm.version == 0
    assert sm.root == GENESIS_ROOT


# ---- state wire frames -----------------------------------------------------


def test_state_request_wire_roundtrip():
    origin = keys()[0][0]
    for kind in (STATE_REQ_MANIFEST, STATE_REQ_CHUNK, STATE_REQ_DELTA):
        frame = encode_state_request(kind, origin, index=3, from_round=17)
        tag, msg = decode_message(frame)
        assert tag == TAG_STATE_REQUEST
        assert (msg.kind, msg.index, msg.from_round) == (kind, 3, 17)
        assert msg.origin == origin


def test_state_manifest_wire_roundtrip():
    block = chain(2)[-1]
    qc = qc_for_block(block)
    origin = keys()[1][0]
    root = Digest.random().to_bytes()
    frame = encode_state_manifest(9, root, block.round, 42, 2, 5, qc, origin)
    tag, msg = decode_message(frame)
    assert tag == TAG_STATE_MANIFEST
    assert (msg.version, msg.root, msg.last_round) == (9, root, block.round)
    assert (msg.applied_payloads, msg.chunk_count, msg.from_round) == (42, 2, 5)
    assert msg.qc.hash == qc.hash and msg.qc.round == qc.round
    assert msg.origin == origin


def test_state_chunk_wire_roundtrip_and_cap():
    entries = [(b"s/l" + bytes([i]) * 32, bytes(8) + bytes([i])) for i in range(5)]
    frame = encode_state_chunk(4, 1, 10, entries)
    tag, msg = decode_message(frame)
    assert tag == TAG_STATE_CHUNK
    assert (msg.version, msg.index, msg.from_round) == (4, 1, 10)
    assert list(msg.entries) == entries
    assert decode_message(encode_state_chunk(1, 0, 0, []))[1].entries == ()
    with pytest.raises(ValueError):
        encode_state_chunk(
            1, 0, 0, [(b"k", b"v")] * (MAX_STATE_CHUNK_ENTRIES + 1)
        )


def test_state_read_wire_roundtrip():
    for space in (STATE_READ_LEDGER, STATE_READ_USER):
        tag, msg = decode_message(encode_state_read(space, b"some-key"))
        assert tag == TAG_STATE_READ
        assert msg == (space, b"some-key")
    # unknown read space must be a clean decode error
    bad = bytearray(encode_state_read(STATE_READ_USER, b"k"))
    bad[2] = 99
    with pytest.raises(SerializationError):
        decode_message(bytes(bad))


def test_state_value_reply_roundtrip():
    root = Digest.random().to_bytes()
    frame = encode_state_value(True, 11, root, 13, 9, b"payload-value")
    reply = decode_state_value(frame)
    assert reply.found is True
    assert (reply.state_version, reply.root) == (11, root)
    assert (reply.last_round, reply.entry_round) == (13, 9)
    assert reply.value == b"payload-value"
    assert frame[0] == STATE_VALUE_TAG
    # non-reply frames (e.g. ingest ACKs) pass through as None
    assert decode_state_value(b"Ack") is None
    assert decode_state_value(b"") is None
    miss = decode_state_value(
        encode_state_value(False, 11, root, 13, 0, b"")
    )
    assert miss.found is False and miss.value == b""
