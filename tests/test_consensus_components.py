"""Component tests: timer, synchronizer, helper (reference
timer_tests.rs, synchronizer_tests.rs:5-110, helper_tests.rs:7-37).
"""

import asyncio

from hotstuff_tpu.consensus import Block, Synchronizer, Timer
from hotstuff_tpu.consensus.helper import Helper
from hotstuff_tpu.consensus.wire import (
    TAG_PROPOSE,
    decode_message,
    encode_sync_request,
)
from hotstuff_tpu.store import Store

from .common import async_test, chain, committee, fresh_base_port, keys, listener


@async_test
async def test_timer_fires_after_delay():
    timer = Timer(50)
    timer.reset()
    await asyncio.wait_for(timer.wait(), timeout=1.0)


@async_test
async def test_timer_reset_postpones():
    timer = Timer(100)
    timer.reset()
    waiter = asyncio.ensure_future(timer.wait())
    await asyncio.sleep(0.06)
    timer.reset()  # push the deadline out
    await asyncio.sleep(0.06)
    assert not waiter.done()  # old deadline passed but reset extended it
    await asyncio.wait_for(waiter, timeout=1.0)


@async_test
async def test_synchronizer_parent_hit(tmp_path):
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    blocks = chain(2)
    await store.write(blocks[0].digest().to_bytes(), blocks[0].serialize())
    sync = Synchronizer(
        keys()[0][0], committee(base), store, asyncio.Queue(), 10_000
    )
    parent = await sync.get_parent_block(blocks[1])
    assert parent is not None
    assert parent.digest() == blocks[0].digest()
    sync.shutdown()
    store.close()


@async_test
async def test_synchronizer_genesis(tmp_path):
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    sync = Synchronizer(
        keys()[0][0], committee(base), store, asyncio.Queue(), 10_000
    )
    parent = await sync.get_parent_block(chain(1)[0])
    assert parent == Block.genesis()
    sync.shutdown()
    store.close()


@async_test
async def test_synchronizer_miss_requests_then_loopback(tmp_path):
    """Store miss: a SyncRequest goes to the block author; once the parent
    is written, the suspended child comes back on the loopback channel
    (synchronizer_tests.rs miss case)."""
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    blocks = chain(2)
    name = keys()[0][0]
    loopback: asyncio.Queue = asyncio.Queue()
    sync = Synchronizer(name, committee(base), store, loopback, 10_000)

    # the author of blocks[1] will receive the sync request
    author_port = base + [pk for pk, _ in keys()].index(blocks[1].author)
    expected = encode_sync_request(blocks[0].digest(), name)
    listen = asyncio.ensure_future(listener(author_port, expected))
    await asyncio.sleep(0.05)

    assert await sync.get_parent_block(blocks[1]) is None
    await asyncio.wait_for(listen, timeout=2.0)

    # writing the parent wakes the waiter and re-injects the child
    await store.write(blocks[0].digest().to_bytes(), blocks[0].serialize())
    child = await asyncio.wait_for(loopback.get(), timeout=2.0)
    assert child.digest() == blocks[1].digest()
    sync.shutdown()
    store.close()


@async_test
async def test_synchronizer_snapshot_barrier(tmp_path):
    """A missing parent certified at or below the floor (the adopted
    snapshot's commit cursor) resolves to the genesis stand-in instead of
    a network fetch: a snapshot rejoin must not backfill pre-snapshot
    ancestry, which may be unreachable under an active partition."""
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    blocks = chain(2)
    name = keys()[0][0]
    sync = Synchronizer(
        name, committee(base), store, asyncio.Queue(), 10_000
    )
    child = blocks[1]  # parent blocks[0] deliberately NOT in the store
    # at/below the floor: stand-in, and no request or waiter is parked
    parent = await sync.get_parent_block(child, floor=child.qc.round)
    assert parent == Block.genesis()
    assert not sync._requests and not sync._pending
    # above the floor: the ordinary fetch path engages and suspends
    assert (
        await sync.get_parent_block(child, floor=child.qc.round - 1) is None
    )
    assert sync._requests and sync._pending
    # get_ancestors applies the barrier to both hops: the outer hop
    # finds nothing below the floor to fetch either
    sync2 = Synchronizer(
        name, committee(base), store, asyncio.Queue(), 10_000
    )
    ancestors = await sync2.get_ancestors(child, floor=child.qc.round)
    assert ancestors == (Block.genesis(), Block.genesis())
    assert not sync2._requests
    sync.shutdown()
    sync2.shutdown()
    store.close()


def test_parameters_reject_incoherent_backoff():
    """ADVICE r3: a backoff < 1.0 would geometrically SHRINK the round
    timer under consecutive timeouts (view-change storm from a typo); a
    cap below the base delay is equally incoherent."""
    import pytest

    from hotstuff_tpu.consensus.config import InvalidParameters, Parameters

    with pytest.raises(InvalidParameters):
        Parameters(timeout_backoff=0.5)
    with pytest.raises(InvalidParameters):
        Parameters(timeout_delay=5_000, timeout_cap_ms=1_000)
    with pytest.raises(InvalidParameters):
        Parameters.from_json({"timeout_backoff": 0.9})
    # the reference-parity fixed timer (backoff exactly 1.0) stays legal
    Parameters(timeout_backoff=1.0)


def test_leader_cache_distinguishes_same_epoch_committees():
    """ADVICE r3: the elector's key cache must never alias two distinct
    committee objects — including schedule entries that share the
    default epoch number (legal in existing committee files)."""
    from hotstuff_tpu.consensus.config import CommitteeSchedule
    from hotstuff_tpu.consensus.leader import RoundRobinLeaderElector

    base = fresh_base_port()
    c1 = committee(base)
    # a second epoch with the SAME default epoch number but its members
    # rotated: the leader sequence must follow the active committee
    c2 = committee(base + 100)
    drop = c2.sorted_keys()[0]
    del c2.authorities[drop]
    schedule = CommitteeSchedule([(1, c1), (100, c2)])
    elector = RoundRobinLeaderElector(schedule)
    assert elector.get_leader(4) in c1.authorities
    assert elector.get_leader(4) == c1.sorted_keys()[4 % 4]
    assert elector.get_leader(103) == c2.sorted_keys()[103 % 3]
    assert elector.get_leader(103) != drop


def test_proposer_inflight_bound_requeues_oldest():
    """ADVICE r3: inflight must not grow without bound when commit
    signals stall — the oldest undecided proposal's payloads return to
    the buffer instead."""
    import logging
    from collections import OrderedDict

    import hotstuff_tpu.consensus.proposer as P
    from hotstuff_tpu.consensus.proposer import Proposer
    from hotstuff_tpu.crypto import Digest

    proposer = Proposer.__new__(Proposer)  # state-only exercise
    proposer.pending = OrderedDict()
    proposer.committed_seen = OrderedDict()
    proposer.inflight = {}
    proposer.log = logging.getLogger("test-proposer")

    digests = [Digest(bytes([i]) * 32) for i in range(8)]
    for r in range(1, 6):
        proposer.inflight[r] = (digests[r],)
    proposer.committed_seen[digests[1]] = None  # round 1's payload committed
    old_cap = P.MAX_INFLIGHT
    P.MAX_INFLIGHT = 3
    try:
        while len(proposer.inflight) > P.MAX_INFLIGHT:
            proposer._requeue_oldest_inflight()
    finally:
        P.MAX_INFLIGHT = old_cap
    assert set(proposer.inflight) == {3, 4, 5}
    # round 1's payload was already committed -> NOT re-buffered;
    # round 2's was orphan-requeued
    assert list(proposer.pending) == [digests[2]]


@async_test
async def test_helper_replies_to_sync_request(tmp_path):
    """Helper reads the requested block and sends it back as a Propose
    (helper_tests.rs:7-37)."""
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    com = committee(base)
    block = chain(1)[0]
    await store.write(block.digest().to_bytes(), block.serialize())

    requests: asyncio.Queue = asyncio.Queue()
    helper = Helper(com, store, requests)
    helper.spawn()

    requester = keys()[1][0]
    requester_port = base + 1
    listen = asyncio.ensure_future(listener(requester_port))
    await asyncio.sleep(0.05)

    await requests.put((block.digest(), requester))
    frame = await asyncio.wait_for(listen, timeout=2.0)
    tag, payload = decode_message(frame)
    assert tag == TAG_PROPOSE
    assert payload.digest() == block.digest()
    helper.shutdown()
    store.close()
