"""Component tests: timer, synchronizer, helper (reference
timer_tests.rs, synchronizer_tests.rs:5-110, helper_tests.rs:7-37).
"""

import asyncio

from hotstuff_tpu.consensus import Block, Synchronizer, Timer
from hotstuff_tpu.consensus.helper import Helper
from hotstuff_tpu.consensus.wire import (
    TAG_PROPOSE,
    decode_message,
    encode_sync_request,
)
from hotstuff_tpu.store import Store

from .common import async_test, chain, committee, fresh_base_port, keys, listener


@async_test
async def test_timer_fires_after_delay():
    timer = Timer(50)
    timer.reset()
    await asyncio.wait_for(timer.wait(), timeout=1.0)


@async_test
async def test_timer_reset_postpones():
    timer = Timer(100)
    timer.reset()
    waiter = asyncio.ensure_future(timer.wait())
    await asyncio.sleep(0.06)
    timer.reset()  # push the deadline out
    await asyncio.sleep(0.06)
    assert not waiter.done()  # old deadline passed but reset extended it
    await asyncio.wait_for(waiter, timeout=1.0)


@async_test
async def test_synchronizer_parent_hit(tmp_path):
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    blocks = chain(2)
    await store.write(blocks[0].digest().to_bytes(), blocks[0].serialize())
    sync = Synchronizer(
        keys()[0][0], committee(base), store, asyncio.Queue(), 10_000
    )
    parent = await sync.get_parent_block(blocks[1])
    assert parent is not None
    assert parent.digest() == blocks[0].digest()
    sync.shutdown()
    store.close()


@async_test
async def test_synchronizer_genesis(tmp_path):
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    sync = Synchronizer(
        keys()[0][0], committee(base), store, asyncio.Queue(), 10_000
    )
    parent = await sync.get_parent_block(chain(1)[0])
    assert parent == Block.genesis()
    sync.shutdown()
    store.close()


@async_test
async def test_synchronizer_miss_requests_then_loopback(tmp_path):
    """Store miss: a SyncRequest goes to the block author; once the parent
    is written, the suspended child comes back on the loopback channel
    (synchronizer_tests.rs miss case)."""
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    blocks = chain(2)
    name = keys()[0][0]
    loopback: asyncio.Queue = asyncio.Queue()
    sync = Synchronizer(name, committee(base), store, loopback, 10_000)

    # the author of blocks[1] will receive the sync request
    author_port = base + [pk for pk, _ in keys()].index(blocks[1].author)
    expected = encode_sync_request(blocks[0].digest(), name)
    listen = asyncio.ensure_future(listener(author_port, expected))
    await asyncio.sleep(0.05)

    assert await sync.get_parent_block(blocks[1]) is None
    await asyncio.wait_for(listen, timeout=2.0)

    # writing the parent wakes the waiter and re-injects the child
    await store.write(blocks[0].digest().to_bytes(), blocks[0].serialize())
    child = await asyncio.wait_for(loopback.get(), timeout=2.0)
    assert child.digest() == blocks[1].digest()
    sync.shutdown()
    store.close()


@async_test
async def test_helper_replies_to_sync_request(tmp_path):
    """Helper reads the requested block and sends it back as a Propose
    (helper_tests.rs:7-37)."""
    store = Store(str(tmp_path / "db"))
    base = fresh_base_port()
    com = committee(base)
    block = chain(1)[0]
    await store.write(block.digest().to_bytes(), block.serialize())

    requests: asyncio.Queue = asyncio.Queue()
    helper = Helper(com, store, requests)
    helper.spawn()

    requester = keys()[1][0]
    requester_port = base + 1
    listen = asyncio.ensure_future(listener(requester_port))
    await asyncio.sleep(0.05)

    await requests.put((block.digest(), requester))
    frame = await asyncio.wait_for(listen, timeout=2.0)
    tag, payload = decode_message(frame)
    assert tag == TAG_PROPOSE
    assert payload.digest() == block.digest()
    helper.shutdown()
    store.close()
