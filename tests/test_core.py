"""Core state-machine tests, driven purely through its channels against a
real store, signature service, and synchronizer (reference
core_tests.rs:61-183), plus crash-recovery coverage the reference lacks
(SURVEY.md §4 gaps).
"""

import asyncio
from types import SimpleNamespace

from hotstuff_tpu.consensus import Core, ConsensusState, ProposerMessage, Synchronizer
from hotstuff_tpu.consensus.core import CONSENSUS_STATE_KEY, make_event_channels
from hotstuff_tpu.consensus.leader import LeaderElector
from hotstuff_tpu.consensus.wire import TAG_PROPOSE, TAG_VOTE, encode_timeout, encode_vote
from hotstuff_tpu.crypto import SignatureService
from hotstuff_tpu.crypto.service import CpuVerifier
from hotstuff_tpu.store import Store

from .common import (
    async_test,
    chain,
    committee,
    fresh_base_port,
    keys,
    listener,
    signed_timeout,
    signed_vote,
)


def make_core(tmp_path, base, name_idx, timeout_ms=10_000):
    store = Store(str(tmp_path / "db"))
    com = committee(base)
    name, secret = keys()[name_idx]
    sig_service = SignatureService(secret)
    rx_events, rx_message, loopback = make_event_channels(2_000)
    tx_proposer: asyncio.Queue = asyncio.Queue()
    tx_commit: asyncio.Queue = asyncio.Queue()
    sync = Synchronizer(name, com, store, loopback, 10_000)
    core = Core(
        name,
        com,
        sig_service,
        CpuVerifier(),
        store,
        LeaderElector(com),
        sync,
        timeout_ms,
        rx_events=rx_events,
        rx_loopback=loopback,
        tx_proposer=tx_proposer,
        tx_commit=tx_commit,
    )
    return SimpleNamespace(
        core=core,
        store=store,
        committee=com,
        name=name,
        secret=secret,
        rx_message=rx_message,
        tx_proposer=tx_proposer,
        tx_commit=tx_commit,
        sync=sync,
    )


def teardown(h):
    h.core.shutdown()
    h.sync.shutdown()
    h.store.close()


@async_test
async def test_handle_proposal_votes_to_next_leader(tmp_path):
    """A valid round-1 proposal produces our vote at the round-2 leader
    (core_tests.rs:61-85)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0)  # not leader of rounds 1/2
    b1 = chain(1)[0]

    expected_vote = signed_vote(b1, h.name, h.secret)
    listen = asyncio.ensure_future(listener(base + 2, encode_vote(expected_vote)))
    await asyncio.sleep(0.05)

    h.core.spawn()
    await h.rx_message.put((TAG_PROPOSE, b1))
    await asyncio.wait_for(listen, timeout=2.0)
    teardown(h)


@async_test
async def test_generate_proposal_after_quorum(tmp_path):
    """2f+1 votes assemble a QC and, as the new leader, we ask the
    proposer for a block with that QC (core_tests.rs:87-132)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=2)  # leader of round 2
    b1 = chain(1)[0]
    h.core.spawn()

    for pk, sk in keys()[:3]:
        await h.rx_message.put((TAG_VOTE, signed_vote(b1, pk, sk)))

    # round advances also emit best-effort Cleanup pings; the MAKE is the
    # first non-cleanup message
    while True:
        message: ProposerMessage = await asyncio.wait_for(
            h.tx_proposer.get(), timeout=2.0
        )
        if message.kind == ProposerMessage.MAKE:
            break
    assert message.round == 2
    assert message.qc.hash == b1.digest()
    assert message.qc.round == 1
    assert message.tc is None
    teardown(h)


@async_test
async def test_commit_chain_head(tmp_path):
    """Processing a 3-block chain commits its head (core_tests.rs:134-160)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0)
    blocks = chain(3)
    h.core.spawn()
    for b in blocks:
        await h.rx_message.put((TAG_PROPOSE, b))

    committed = await asyncio.wait_for(h.tx_commit.get(), timeout=2.0)
    assert committed.digest() == blocks[0].digest()
    teardown(h)


@async_test
async def test_local_timeout_broadcasts(tmp_path):
    """The round timer firing broadcasts a signed Timeout to every peer
    (core_tests.rs:162-183)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0, timeout_ms=100)
    from hotstuff_tpu.consensus import QC

    expected = encode_timeout(signed_timeout(QC.genesis(), 1, h.name, h.secret))
    listens = [
        asyncio.ensure_future(listener(base + i, expected)) for i in (1, 2, 3)
    ]
    await asyncio.sleep(0.05)
    h.core.spawn()
    await asyncio.wait_for(asyncio.gather(*listens), timeout=2.0)
    teardown(h)


@async_test
async def test_timeout_join_round_sync(tmp_path):
    """f+1 distinct timeouts for a round AHEAD of ours make the core
    join that round and emit its own timeout (round synchronization): a
    node that missed a one-shot TC broadcast — routine during a
    snapshot-sync bootstrap — must not wedge one round behind a
    committee whose next TC needs this node's timeout."""
    from hotstuff_tpu.consensus import QC

    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0, timeout_ms=60_000)
    try:
        ks = keys()
        assert h.core.round == 1
        # one authority ahead of us: below the f+1 validity threshold,
        # we stay put
        await h.core._handle_timeout(
            signed_timeout(QC.genesis(), 3, ks[1][0], ks[1][1])
        )
        assert h.core.round == 1
        # a second distinct authority reaches f+1 = 2 of 4: join round
        # 3 and time it out ourselves — and with 3 of 4 timeouts the TC
        # assembles immediately, advancing the core into round 4
        await h.core._handle_timeout(
            signed_timeout(QC.genesis(), 3, ks[2][0], ks[2][1])
        )
        assert h.core.round == 4
    finally:
        teardown(h)


@async_test
async def test_local_timeout_fires_under_message_flood(tmp_path):
    """View-change liveness bound: a flood of cheap protocol messages
    queued ahead of the timer must delay the local timeout by at most
    one processing batch — the expiry check runs every loop iteration,
    not only when the timer pump's event drains through the merged
    queue (review finding on the r5 select-loop merge)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0, timeout_ms=150)
    from hotstuff_tpu.consensus import QC

    expected = encode_timeout(signed_timeout(QC.genesis(), 1, h.name, h.secret))
    listens = [
        asyncio.ensure_future(listener(base + i, expected)) for i in (1, 2, 3)
    ]
    await asyncio.sleep(0.05)
    # pre-load a deep backlog of far-future votes (free rejections, but
    # each occupies a queue slot ahead of any timer event)
    pk, sk = keys()[1]
    junk = signed_vote(chain(1)[0], pk, sk)
    junk.round = 10_000
    for _ in range(1_500):
        h.rx_message.put_nowait((TAG_VOTE, junk))
    h.core.spawn()
    # keep feeding while the timer runs so the queue never drains
    async def feeder():
        while True:
            try:
                h.rx_message.put_nowait((TAG_VOTE, junk))
            except asyncio.QueueFull:
                pass
            await asyncio.sleep(0.01)

    feed = asyncio.ensure_future(feeder())
    try:
        await asyncio.wait_for(asyncio.gather(*listens), timeout=2.0)
    finally:
        feed.cancel()
    teardown(h)


@async_test
async def test_loopback_backlog_drains_without_external_wakeups(tmp_path):
    """>64 loopback blocks queued in one burst exceed the per-iteration
    drain cap; the re-armed wake token must keep the loop processing
    them with NO network traffic or timer expiry (review finding: the
    capped drain could strand the tail until the round timeout)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0, timeout_ms=60_000)
    b1 = chain(1)[0]
    h.core.spawn()
    for _ in range(150):
        h.core.rx_loopback.put_nowait(b1)
    deadline = asyncio.get_running_loop().time() + 2.0
    while h.core.rx_loopback.qsize() > 0:
        assert asyncio.get_running_loop().time() < deadline, (
            f"loopback backlog stranded: {h.core.rx_loopback.qsize()} left"
        )
        await asyncio.sleep(0.02)
    teardown(h)


@async_test
async def test_loopback_processed_under_message_flood(tmp_path):
    """Loopback liveness bound: the node's own/sync-resumed blocks ride
    a priority channel drained every iteration, never queued behind the
    network backlog (review finding on the r5 select-loop merge) — a
    loopback proposal still produces our vote while junk floods the
    message queue."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0, timeout_ms=60_000)
    b1 = chain(1)[0]
    expected_vote = signed_vote(b1, h.name, h.secret)
    listen = asyncio.ensure_future(listener(base + 2, encode_vote(expected_vote)))
    await asyncio.sleep(0.05)

    pk, sk = keys()[1]
    junk = signed_vote(b1, pk, sk)
    junk.round = 10_000
    for _ in range(1_500):
        h.rx_message.put_nowait((TAG_VOTE, junk))
    h.core.spawn()

    async def feeder():
        while True:
            try:
                h.rx_message.put_nowait((TAG_VOTE, junk))
            except asyncio.QueueFull:
                pass
            await asyncio.sleep(0.01)

    feed = asyncio.ensure_future(feeder())
    try:
        await h.core.rx_loopback.put(b1)
        await asyncio.wait_for(listen, timeout=2.0)
    finally:
        feed.cancel()
    teardown(h)


@async_test
async def test_state_persisted_after_vote(tmp_path):
    """Any state-changing iteration rewrites ConsensusState (the fork's
    crash-recovery addition, core.rs:484-492)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0)
    b1 = chain(1)[0]
    h.core.spawn()
    await h.rx_message.put((TAG_PROPOSE, b1))
    await asyncio.sleep(0.3)

    raw = await h.store.read(CONSENSUS_STATE_KEY)
    assert raw is not None
    state = ConsensusState.deserialize(raw)
    assert state.last_voted_round == 1
    teardown(h)


@async_test
async def test_recovery_resumes_round(tmp_path):
    """A restarted core reloads its persisted round and (as leader of that
    round) immediately proposes — no test exists for this in the
    reference (SURVEY.md §4)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=3)  # leader of round 7 (7 % 4 == 3)
    state = ConsensusState(round_=7, last_voted_round=6, last_committed_round=5)
    await h.store.write(CONSENSUS_STATE_KEY, state.serialize())

    h.core.spawn()
    message: ProposerMessage = await asyncio.wait_for(
        h.tx_proposer.get(), timeout=2.0
    )
    assert message.kind == ProposerMessage.MAKE
    assert message.round == 7
    assert h.core.last_voted_round == 6
    assert h.core.last_committed_round == 5
    teardown(h)


@async_test
async def test_allow_empty_proposal_when_payloads_in_flight(tmp_path):
    """A Make issued while payload-carrying blocks are uncommitted sets
    allow_empty, so the next leader can advance the 2-chain with an empty
    block instead of parking the commit until the producer's next burst
    (this build's latency fix; the reference always defers,
    proposer.rs:74-78)."""
    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=3)  # leader of round 3
    blocks = chain(2)  # payload-carrying blocks for rounds 1..2
    h.core.spawn()
    for b in blocks:
        await h.rx_message.put((TAG_PROPOSE, b))

    # voting on b2 as round-3 leader needs 2f+1 votes to form the QC
    for pk, sk in keys()[:3]:
        await h.rx_message.put((TAG_VOTE, signed_vote(blocks[1], pk, sk)))

    while True:
        message: ProposerMessage = await asyncio.wait_for(
            h.tx_proposer.get(), timeout=2.0
        )
        if message.kind == ProposerMessage.MAKE:
            break
    assert message.round == 3
    # blocks 1..2 carry payloads and nothing is committed yet
    assert message.allow_empty
    teardown(h)


@async_test
async def test_proposer_makes_empty_block_when_allowed():
    """Proposer with an empty buffer: allow_empty Make emits an empty
    block on the loopback; without allow_empty it defers."""
    from hotstuff_tpu.consensus.proposer import Proposer

    name, secret = keys()[0]
    com = committee(fresh_base_port())
    loopback: asyncio.Queue = asyncio.Queue()
    proposer = Proposer(
        name,
        com,
        SignatureService(secret),
        rx_producer=asyncio.Queue(),
        rx_message=asyncio.Queue(),
        tx_loopback=loopback,
    )
    from hotstuff_tpu.consensus.messages import QC

    # deferred: no payloads, allow_empty=False
    await proposer._make_block(5, QC.genesis(), None, allow_empty=False)
    assert proposer.deferred is not None and loopback.empty()

    # allow_empty=True -> an empty block is created and looped back
    # (broadcast ACK-wait is cancelled on shutdown; peers are not up)
    task = asyncio.ensure_future(
        proposer._make_block(5, QC.genesis(), None, allow_empty=True)
    )
    block = await asyncio.wait_for(loopback.get(), timeout=2.0)
    assert block.round == 5 and block.payloads == ()
    task.cancel()
    proposer.shutdown()


@async_test
async def test_wrong_leader_proposal_rejected(tmp_path):
    """A Byzantine node proposing out of turn is rejected: no vote is
    emitted for a round-1 block authored by anyone but round 1's leader
    (core.rs:420-427 WrongLeader)."""
    from .common import qc_for_block, signed_block
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.consensus.messages import QC

    base = fresh_base_port()
    h = make_core(tmp_path, base, name_idx=0)
    # round 1's leader is keys()[1 % 4]; author with keys()[3] instead
    author, secret = keys()[3]
    bad = signed_block(author, secret, 1, qc=QC.genesis(), payload=Digest.random())

    listen = asyncio.ensure_future(listener(base + 2))  # round-2 leader's port
    await asyncio.sleep(0.05)
    h.core.spawn()
    await h.rx_message.put((TAG_PROPOSE, bad))
    # the proposal must NOT produce a vote
    with __import__("pytest").raises(asyncio.TimeoutError):
        await asyncio.wait_for(asyncio.shield(listen), timeout=0.6)
    listen.cancel()
    teardown(h)


@async_test
async def test_timeout_backoff_grows_and_resets_on_progress(tmp_path):
    """Exponential view-change backoff (beyond reference parity): each
    consecutive local timeout stretches the round timer geometrically
    (capped); observing a newer QC snaps it back to the base delay."""
    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=100)
    try:
        core = h.core
        base = 0.1
        assert core.timer.duration == base
        # mark the committee ACTIVE (uncommitted payload block in
        # flight): idle timeouts deliberately never grow the backoff
        # (see test_idle_timeouts_keep_base_timer)
        core.last_payload_round = 1
        from hotstuff_tpu.consensus.errors import ConsensusError

        async def fire_timer():
            # as in Core.run: re-firing for the same round raises benign
            # AuthorityReuse from the aggregator, which the loop logs
            try:
                await core._local_timeout_round()
            except ConsensusError:
                pass

        for expected_exp in (1, 2, 3):
            await fire_timer()
            assert core._timeout_exponent == expected_exp
            assert core.timer.duration == base * 2**expected_exp
        # cap: exponent keeps counting but the duration is clamped
        core._timeout_cap_ms = 500
        await fire_timer()
        assert core.timer.duration == 0.5
        # FIRST TC after progress: retry at base once (a single dead
        # leader structurally costs two view changes per lap — paying
        # base + backed-off for it would halve fault throughput)
        core._advance_round(core.round, via_tc=True)
        assert core._timeout_exponent == 0
        assert core.timer.duration == base
        # CONSECUTIVE TCs (no QC between): keep the backed-off timer —
        # under a uniformly slow but live network TCs keep forming, and
        # resetting on every one would pin the timer at base forever
        await fire_timer()
        assert core._timeout_exponent == 1
        core._advance_round(core.round, via_tc=True)
        assert core._timeout_exponent == 1
        assert core.timer.duration == base * 2
        # a QC-driven advance IS progress: backoff and TC streak reset
        blocks = chain(4)
        qc = blocks[-1].qc
        core.round = qc.round  # pretend we stalled at the QC's round
        core._process_qc(qc)
        assert core._timeout_exponent == 0
        assert core._consecutive_tcs == 0
        assert core.timer.duration == base
    finally:
        teardown(h)


@async_test
async def test_idle_timeouts_keep_base_timer(tmp_path):
    """An IDLE committee (no proposals seen, nothing uncommitted in
    flight — e.g. waiting for the first client payload) must not grow
    the view-change backoff: a WAN f=3 committee was measured wedging
    to ZERO commits because boot-time idle rounds compounded the timer
    to 16 s+ before the first transaction arrived."""
    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=100)
    try:
        core = h.core
        base = 0.1
        from hotstuff_tpu.consensus.errors import ConsensusError

        async def fire_timer():
            try:
                await core._local_timeout_round()
            except ConsensusError:
                pass

        for _ in range(4):  # idle spin: timer must stay at base
            await fire_timer()
            core._advance_round(core.round, via_tc=True)
        assert core._timeout_exponent == 0
        assert core.timer.duration == base

        # a verified proposal for the current round marks it active:
        # the NEXT timeout is a real liveness signal and backs off
        core._saw_proposal = True
        await fire_timer()
        assert core._timeout_exponent == 1
        assert core.timer.duration == base * 2
    finally:
        teardown(h)


@async_test
async def test_timeout_burst_aggregate_verification(tmp_path):
    """A view-change storm's timeout flood arriving in one burst is
    signature-verified as ONE coalesced claim batch (flood entries over
    the same digest form one shared claim); a garbage timeout in the
    burst makes its group fall back to per-item verification, where it
    is rejected while the honest timeouts still land in the TC maker."""
    from hotstuff_tpu.consensus.wire import TAG_TIMEOUT
    from hotstuff_tpu.crypto import Signature
    from hotstuff_tpu.crypto.async_service import AsyncVerifyService
    from hotstuff_tpu.crypto.service import CpuVerifier

    class CountingVerifier(CpuVerifier):
        ones = 0
        many = 0

        def verify_one(self, d, pk, sig):
            CountingVerifier.ones += 1
            return super().verify_one(d, pk, sig)

        def verify_many(self, digests, pks, sigs, aggregate_ok=False):
            CountingVerifier.many += 1
            return super().verify_many(digests, pks, sigs)

    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=60_000)
    try:
        from hotstuff_tpu.consensus import QC

        h.core.verifier = CountingVerifier()
        h.core.averifier = AsyncVerifyService.for_backend(h.core.verifier)
        ks = keys()
        # clean burst: 3 timeouts over the same digest (round 1, genesis
        # high_qc) -> one flattened claim batch, zero per-item checks
        burst = [
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, pk, sk))
            for pk, sk in ks[:3]
        ]
        pre = await h.core._preverify_burst(burst)
        assert pre == {0, 1, 2}
        # one aggregated crypto call, zero per-item checks: with the
        # native lib the whole wave is ONE flat batch equation
        # (verify_many never runs); without it, one verify_many call
        from hotstuff_tpu.crypto import native_ed25519

        assert CountingVerifier.many == (
            0 if native_ed25519.available() else 1
        )
        assert CountingVerifier.ones == 0

        # poisoned burst: one garbage signature -> the group's shared
        # claim fails, nothing is preverified (per-item fallback happens
        # in _handle_timeout, where the garbage one raises)
        bad = signed_timeout(QC.genesis(), 1, ks[2][0], ks[2][1])
        bad.signature = Signature(b"\x01" * 64)
        burst_bad = [
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, ks[0][0], ks[0][1])),
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, ks[1][0], ks[1][1])),
            (TAG_TIMEOUT, bad),
        ]
        pre = await h.core._preverify_burst(burst_bad)
        assert pre == set()

        # NON-MEMBER authors must never enter an aggregate (the BLS
        # rogue-key precondition: only PoP-checked committee keys may
        # be summed) — a stranger's timeout is excluded from grouping
        # even when the rest of the burst is honest
        from hotstuff_tpu.crypto import generate_keypair

        spk, ssk = generate_keypair(b"\x77" * 32, 0)  # not in committee
        stranger = signed_timeout(QC.genesis(), 1, spk, ssk)
        burst_mixed = [
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, ks[0][0], ks[0][1])),
            (TAG_TIMEOUT, stranger),
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, ks[1][0], ks[1][1])),
        ]
        pre = await h.core._preverify_burst(burst_mixed)
        assert pre == {0, 2}  # members aggregate; the stranger never joins
        # the per-item path still accepts the honest ones and rejects
        # the garbage one
        await h.core._handle_timeout(burst_bad[0][1])
        from hotstuff_tpu.consensus.errors import InvalidSignature

        try:
            await h.core._handle_timeout(bad)
            raise AssertionError("garbage timeout accepted")
        except InvalidSignature:
            pass
    finally:
        teardown(h)


@async_test
async def test_timeout_burst_mixed_rounds_group_separately(tmp_path):
    """Timeouts for different rounds (distinct digests) in one burst
    form one claim group per round — each verifies independently, and on
    an aggregate-preferring backend (BLS) each group costs exactly one
    shared-message check."""
    from hotstuff_tpu.consensus import QC
    from hotstuff_tpu.consensus.wire import TAG_TIMEOUT
    from hotstuff_tpu.crypto.async_service import AsyncVerifyService
    from hotstuff_tpu.crypto.service import CpuVerifier

    class AggregateCountingVerifier(CpuVerifier):
        """Counts shared-claim checks the way a BLS backend would see
        them (prefers_aggregate routes shared claims to
        verify_shared_msg instead of flattening)."""

        prefers_aggregate = True
        shared = 0

        def verify_shared_msg(self, d, votes):
            AggregateCountingVerifier.shared += 1
            return super().verify_shared_msg(d, votes)

    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=60_000)
    try:
        h.core.verifier = AggregateCountingVerifier()
        h.core.averifier = AsyncVerifyService.for_backend(h.core.verifier)
        ks = keys()
        burst = [
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, ks[0][0], ks[0][1])),
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 2, ks[1][0], ks[1][1])),
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 1, ks[2][0], ks[2][1])),
            (TAG_TIMEOUT, signed_timeout(QC.genesis(), 2, ks[3][0], ks[3][1])),
        ]
        pre = await h.core._preverify_burst(burst)
        assert pre == {0, 1, 2, 3}
        assert AggregateCountingVerifier.shared == 2  # one aggregate per round
    finally:
        teardown(h)


@async_test
async def test_preverify_skips_far_future_votes(tmp_path):
    """Advisor r4: votes beyond the aggregator's ROUND_LOOKAHEAD bound
    are rejected by add_vote with ZERO crypto — the preverify batch must
    not convert that free rejection into signature work."""
    from hotstuff_tpu.consensus.aggregator import ROUND_LOOKAHEAD
    from hotstuff_tpu.consensus.messages import Vote
    from hotstuff_tpu.crypto import Signature

    class Counting(CpuVerifier):
        calls = 0

        def verify_many(self, d, p, s, aggregate_ok=False):
            Counting.calls += len(d)
            return super().verify_many(d, p, s)

        def verify_one(self, d, pk, sig):
            Counting.calls += 1
            return super().verify_one(d, pk, sig)

        def verify_shared_msg(self, d, votes):
            Counting.calls += len(votes)
            return super().verify_shared_msg(d, votes)

    h = make_core(tmp_path, fresh_base_port(), 0, timeout_ms=60_000)
    try:
        h.core.verifier = Counting()
        pk, sk = keys()[1]
        far = Vote(
            hash=__import__("hotstuff_tpu.crypto", fromlist=["Digest"])
            .Digest.random(),
            round=h.core.round + ROUND_LOOKAHEAD + 1,
            author=pk,
        )
        far.signature = Signature.new(far.digest(), sk)
        pre = await h.core._preverify_burst([(TAG_VOTE, far)])
        assert pre == set()
        assert Counting.calls == 0

        # same bound for timeouts
        from .common import qc_for_block, signed_timeout

        t = signed_timeout(
            h.core.high_qc, h.core.round + ROUND_LOOKAHEAD + 1, pk, sk
        )
        from hotstuff_tpu.consensus.wire import TAG_TIMEOUT

        pre = await h.core._preverify_burst([(TAG_TIMEOUT, t)])
        assert pre == set()
        assert Counting.calls == 0
    finally:
        teardown(h)
