"""Crash-and-rejoin under a LIVE committee: real node subprocesses, one
killed with SIGKILL mid-run and restarted against the same store.

This is the fork's marquee feature (ConsensusState persistence,
reference core.rs:52-58/484-492) exercised the way the reference never
tests it: the restarted node must (a) recover its persisted round state
(no double-voting window), (b) rejoin the live committee, and (c) the
committee must keep committing before, during, AND after the outage.
Uses the producer-path client harness pieces (subprocess nodes, log
scrape) — runtime ~25 s, so this lives in its own file for -x runs.
"""

from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
import time

from hotstuff_tpu.consensus import Committee, Parameters
from hotstuff_tpu.node.config import Secret, write_committee, write_parameters

from .common import fresh_base_port

RE_COMMIT = re.compile(r"Committed block (\d+) -> (\S+)")
RE_RECOVER = re.compile(r"Recovered consensus state at round (\d+)")
RE_STATE_ROOT = re.compile(r"State root (\d+) -> (\S+) \(round (\d+)\)")
RE_ADOPTED = re.compile(r"Adopted state snapshot version (\d+) at round (\d+)")
RE_CURSOR = re.compile(
    r"State sync advanced commit cursor (\d+) -> (\d+) "
    r"\(history replay skipped\)"
)


def _state_roots(tmp_path, n=4):
    """Per-node (version, root, round) observations for the state-root
    agreement checker (benchmark.invariants schema)."""
    out = {}
    for i in range(n):
        path = tmp_path / f"node_{i}.log"
        content = path.read_text(errors="replace") if path.exists() else ""
        out[f"node-{i}"] = [
            (int(v), root, int(r))
            for v, root, r in RE_STATE_ROOT.findall(content)
        ]
    return out


def _spawn_node(tmp_path, i, repo_root, extra_env=None):
    log = open(tmp_path / f"node_{i}.log", "a")
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "hotstuff_tpu.node",
            "-vv",
            "run",
            "--keys",
            str(tmp_path / f"key_{i}.json"),
            "--committee",
            str(tmp_path / "committee.json"),
            "--store",
            str(tmp_path / f"db_{i}"),
            "--parameters",
            str(tmp_path / "parameters.json"),
        ],
        stdout=log,
        stderr=subprocess.STDOUT,
        env={**os.environ, "PYTHONPATH": repo_root, **(extra_env or {})},
    )


def _commits(tmp_path, i):
    path = tmp_path / f"node_{i}.log"
    if not path.exists():
        return []
    return RE_COMMIT.findall(path.read_text(errors="replace"))


def _wait_commits(tmp_path, i, minimum, deadline_s, baseline=0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if len(_commits(tmp_path, i)) >= baseline + minimum:
            return True
        time.sleep(0.5)
    return False


def _write_config(tmp_path, base):
    keys = [Secret.new() for _ in range(4)]
    committee = Committee.new(
        [
            (s.name, 1, ("127.0.0.1", base + i))
            for i, s in enumerate(keys)
        ]
    )
    write_committee(committee, str(tmp_path / "committee.json"))
    # cap the view-change backoff: the partition test deliberately holds
    # the committee below quorum for many seconds, and an uncapped
    # exponential would stretch every post-heal round to tens of seconds.
    # The cap must still exceed the worst-case round turnaround — after a
    # stall the leader's proposal carries a large payload backlog and can
    # take several seconds to form and circulate under suite CPU load; a
    # cap below that keeps firing timeouts before any proposal lands and
    # the committee never re-converges.
    write_parameters(
        Parameters(timeout_delay=1_000, sync_retry_delay=2_000,
                   timeout_cap_ms=8_000),
        str(tmp_path / "parameters.json"),
    )
    for i, s in enumerate(keys):
        s.write(str(tmp_path / f"key_{i}.json"))
    import hotstuff_tpu

    return os.path.dirname(
        os.path.dirname(os.path.abspath(hotstuff_tpu.__file__))
    )


def test_sigkill_node_rejoins_and_commits(tmp_path):
    base = fresh_base_port()
    repo_root = _write_config(tmp_path, base)
    procs = {}
    feeder = None
    try:
        for i in range(4):
            procs[i] = _spawn_node(tmp_path, i, repo_root)
        # feed producer digests to every node
        feeder = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "hotstuff_tpu.node.client",
                "--committee",
                str(tmp_path / "committee.json"),
                "--rate",
                "200",
                "--duration",
                "150",
                "--warmup",
                "1",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={**os.environ, "PYTHONPATH": repo_root},
        )
        # phase 1: everyone commits
        assert _wait_commits(tmp_path, 3, minimum=5, deadline_s=30), (
            "no commits before the crash"
        )
        # phase 2: SIGKILL node 3 (no graceful shutdown, no state flush)
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        survivors_baseline = len(_commits(tmp_path, 0))
        # the 3 survivors (= quorum) must keep committing through the hole
        assert _wait_commits(
            tmp_path, 0, minimum=5, deadline_s=30, baseline=survivors_baseline
        ), "survivors stalled during the outage"
        # phase 3: restart node 3 against the SAME store.  With the
        # outage measured in dozens of rounds and the sync lag floor
        # lowered, the node must rejoin via snapshot state-sync — NOT by
        # replaying the commit history it slept through.
        dead_baseline = len(_commits(tmp_path, 3))
        procs[3] = _spawn_node(
            tmp_path, 3, repo_root,
            extra_env={"HOTSTUFF_STATE_SYNC_LAG": "2"},
        )
        assert _wait_commits(
            tmp_path, 3, minimum=5, deadline_s=40, baseline=dead_baseline
        ), "restarted node never resumed committing"
        log3 = (tmp_path / "node_3.log").read_text(errors="replace")
        m = RE_RECOVER.findall(log3)
        assert m and int(m[-1]) >= 1, "no persisted-state recovery logged"
        # snapshot path, not history replay: the adopt + cursor-advance
        # contract lines must both be present
        adopted = RE_ADOPTED.findall(log3)
        assert adopted, "rejoin did not go through snapshot state-sync"
        cursor = RE_CURSOR.findall(log3)
        assert cursor, "state sync never advanced the commit cursor"
        lo, hi = (int(x) for x in cursor[-1])
        assert hi > lo, "cursor advance did not skip any history"
        # consistency: the rejoined node's commit sequence agrees with a
        # survivor's on common digests
        c0 = dict(_commits(tmp_path, 0))
        c3 = dict(_commits(tmp_path, 3))
        common = set(c0) & set(c3)
        assert common, "no common committed rounds to compare"
        for rnd in common:
            assert c0[rnd] == c3[rnd], f"divergent commit at round {rnd}"
        # replicated execution converged: every node that reports a
        # state root at a version reports the SAME root, across both of
        # node 3's lifetimes and the snapshot jump
        from benchmark.invariants import check_state_root_agreement

        ok, violations, details = check_state_root_agreement(
            _state_roots(tmp_path)
        )
        assert ok is True, violations
        assert details["nodes_reporting"] == 4, details
        # node 3 reported roots AFTER the snapshot version it adopted
        # (i.e. it is executing again, not just serving the snapshot)
        adopted_version = int(adopted[-1][0])
        post = [v for v, _r, _rnd in _state_roots(tmp_path)["node-3"]
                if v > adopted_version]
        assert post, "no state roots applied after snapshot adoption"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        if feeder is not None and feeder.poll() is None:
            feeder.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def test_crash_restart_under_partition(tmp_path):
    """A crash INSIDE a network partition window, and a REJOIN inside a
    second one: split-brain 0,1|2,3 opens at t=6, node 3 is SIGKILLed at
    t=6 (leaving 2|1 — no quorum anywhere), the partition heals at t=11
    (3/4 = quorum resumes), a second partition isolates node 1 from
    t=20, and node 3 restarts at t=21 WHILE that partition is active —
    it must state-sync from the reachable peers {0, 2} and restore the
    quorum {0, 2, 3}.  Safety and state-root agreement must hold across
    every log and both of node 3's lifetimes."""
    import json

    from benchmark.invariants import check_safety, check_state_root_agreement

    base = fresh_base_port()
    repo_root = _write_config(tmp_path, base)
    epoch = time.time()
    spec = {
        "name": "crash-under-partition",
        "seed": 11,
        "epoch_unix": epoch,
        "nodes": {f"127.0.0.1:{base + i}": i for i in range(4)},
        "rules": [
            {
                "label": "split",
                "partition": [[0, 1], [2, 3]],
                "at": 6.0,
                "until": 11.0,
            },
            {
                "label": "isolate-1",
                "partition": [[0, 2, 3], [1]],
                "at": 40.0,
                "until": 100.0,
            },
        ],
    }
    extra_env = {"HOTSTUFF_FAULTS": json.dumps(spec)}
    procs = {}
    feeder = None
    try:
        for i in range(4):
            procs[i] = _spawn_node(tmp_path, i, repo_root, extra_env)
        feeder = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "hotstuff_tpu.node.client",
                "--committee",
                str(tmp_path / "committee.json"),
                "--rate",
                "200",
                "--duration",
                "150",
                "--warmup",
                "1",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={**os.environ, "PYTHONPATH": repo_root},
        )
        # clean commits before the window opens at t=6
        assert _wait_commits(
            tmp_path, 3, minimum=3, deadline_s=max(0.1, epoch + 6 - time.time())
        ), "no commits before the partition opened"
        # crash node 3 just as the partition bites: groups are now 2|1
        delay = epoch + 6.0 - time.time()
        if delay > 0:
            time.sleep(delay)
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        dead_baseline = len(_commits(tmp_path, 3))
        survivor_baseline = len(_commits(tmp_path, 0))
        # heal at t=11: {0,1,2} are 3/4 = quorum again and must resume
        delay = epoch + 11.0 - time.time()
        if delay > 0:
            time.sleep(delay)
        assert _wait_commits(
            tmp_path, 0, minimum=3,
            deadline_s=max(0.1, epoch + 39.0 - time.time()),
            baseline=survivor_baseline,
        ), "survivors never resumed after the heal"
        # t=40: node 1 drops off; {0,2} alone are below quorum — the
        # committee is STALLED until node 3 comes back.  Restart it at
        # t=41, inside the active partition: it must state-sync from the
        # reachable peers {0,2} and its return restores the quorum.
        delay = epoch + 41.0 - time.time()
        if delay > 0:
            time.sleep(delay)
        procs[3] = _spawn_node(
            tmp_path, 3, repo_root,
            {**extra_env, "HOTSTUFF_STATE_SYNC_LAG": "2"},
        )
        assert _wait_commits(
            tmp_path, 3, minimum=3, deadline_s=50, baseline=dead_baseline
        ), "restarted node never resumed committing under the partition"
        log3 = (tmp_path / "node_3.log").read_text(errors="replace")
        assert RE_ADOPTED.findall(log3), (
            "partition rejoin did not go through snapshot state-sync"
        )
        # committee-wide safety across both of node 3's lifetimes
        history = {
            f"node-{i}": [(0.0, int(r), d) for r, d in _commits(tmp_path, i)]
            for i in range(4)
        }
        ok, violations = check_safety(history)
        assert ok, violations
        # replicated execution agrees per version (the isolated node 1
        # simply stops reporting — its prefix still has to match)
        s_ok, s_viol, _details = check_state_root_agreement(
            _state_roots(tmp_path)
        )
        assert s_ok is True, s_viol
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        if feeder is not None and feeder.poll() is None:
            feeder.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
