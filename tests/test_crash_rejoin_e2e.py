"""Crash-and-rejoin under a LIVE committee: real node subprocesses, one
killed with SIGKILL mid-run and restarted against the same store.

This is the fork's marquee feature (ConsensusState persistence,
reference core.rs:52-58/484-492) exercised the way the reference never
tests it: the restarted node must (a) recover its persisted round state
(no double-voting window), (b) rejoin the live committee, and (c) the
committee must keep committing before, during, AND after the outage.
Uses the producer-path client harness pieces (subprocess nodes, log
scrape) — runtime ~25 s, so this lives in its own file for -x runs.
"""

from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
import time

from hotstuff_tpu.consensus import Committee, Parameters
from hotstuff_tpu.node.config import Secret, write_committee, write_parameters

from .common import fresh_base_port

RE_COMMIT = re.compile(r"Committed block (\d+) -> (\S+)")
RE_RECOVER = re.compile(r"Recovered consensus state at round (\d+)")


def _spawn_node(tmp_path, i, repo_root, extra_env=None):
    log = open(tmp_path / f"node_{i}.log", "a")
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "hotstuff_tpu.node",
            "-vv",
            "run",
            "--keys",
            str(tmp_path / f"key_{i}.json"),
            "--committee",
            str(tmp_path / "committee.json"),
            "--store",
            str(tmp_path / f"db_{i}"),
            "--parameters",
            str(tmp_path / "parameters.json"),
        ],
        stdout=log,
        stderr=subprocess.STDOUT,
        env={**os.environ, "PYTHONPATH": repo_root, **(extra_env or {})},
    )


def _commits(tmp_path, i):
    path = tmp_path / f"node_{i}.log"
    if not path.exists():
        return []
    return RE_COMMIT.findall(path.read_text(errors="replace"))


def _wait_commits(tmp_path, i, minimum, deadline_s, baseline=0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if len(_commits(tmp_path, i)) >= baseline + minimum:
            return True
        time.sleep(0.5)
    return False


def _write_config(tmp_path, base):
    keys = [Secret.new() for _ in range(4)]
    committee = Committee.new(
        [
            (s.name, 1, ("127.0.0.1", base + i))
            for i, s in enumerate(keys)
        ]
    )
    write_committee(committee, str(tmp_path / "committee.json"))
    write_parameters(
        Parameters(timeout_delay=1_000, sync_retry_delay=2_000),
        str(tmp_path / "parameters.json"),
    )
    for i, s in enumerate(keys):
        s.write(str(tmp_path / f"key_{i}.json"))
    import hotstuff_tpu

    return os.path.dirname(
        os.path.dirname(os.path.abspath(hotstuff_tpu.__file__))
    )


def test_sigkill_node_rejoins_and_commits(tmp_path):
    base = fresh_base_port()
    repo_root = _write_config(tmp_path, base)
    procs = {}
    feeder = None
    try:
        for i in range(4):
            procs[i] = _spawn_node(tmp_path, i, repo_root)
        # feed producer digests to every node
        feeder = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "hotstuff_tpu.node.client",
                "--committee",
                str(tmp_path / "committee.json"),
                "--rate",
                "200",
                "--duration",
                "150",
                "--warmup",
                "1",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={**os.environ, "PYTHONPATH": repo_root},
        )
        # phase 1: everyone commits
        assert _wait_commits(tmp_path, 3, minimum=5, deadline_s=30), (
            "no commits before the crash"
        )
        # phase 2: SIGKILL node 3 (no graceful shutdown, no state flush)
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        survivors_baseline = len(_commits(tmp_path, 0))
        # the 3 survivors (= quorum) must keep committing through the hole
        assert _wait_commits(
            tmp_path, 0, minimum=5, deadline_s=30, baseline=survivors_baseline
        ), "survivors stalled during the outage"
        # phase 3: restart node 3 against the SAME store
        dead_baseline = len(_commits(tmp_path, 3))
        procs[3] = _spawn_node(tmp_path, 3, repo_root)
        assert _wait_commits(
            tmp_path, 3, minimum=5, deadline_s=40, baseline=dead_baseline
        ), "restarted node never resumed committing"
        log3 = (tmp_path / "node_3.log").read_text(errors="replace")
        m = RE_RECOVER.findall(log3)
        assert m and int(m[-1]) >= 1, "no persisted-state recovery logged"
        # consistency: the rejoined node's commit sequence agrees with a
        # survivor's on common digests
        c0 = dict(_commits(tmp_path, 0))
        c3 = dict(_commits(tmp_path, 3))
        common = set(c0) & set(c3)
        assert common, "no common committed rounds to compare"
        for rnd in common:
            assert c0[rnd] == c3[rnd], f"divergent commit at round {rnd}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        if feeder is not None and feeder.poll() is None:
            feeder.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def test_crash_restart_under_partition(tmp_path):
    """A crash INSIDE a network partition window: split-brain 0,1|2,3
    opens at t=6, node 3 is SIGKILLed at t=6 (leaving 2|1 — no quorum
    anywhere), the partition heals at t=11 (3/4 = quorum resumes), and
    node 3 restarts at t=12 against its old store.  Safety must hold
    across every log; everyone commits new rounds after the heal."""
    import json

    from benchmark.invariants import check_safety

    base = fresh_base_port()
    repo_root = _write_config(tmp_path, base)
    epoch = time.time()
    spec = {
        "name": "crash-under-partition",
        "seed": 11,
        "epoch_unix": epoch,
        "nodes": {f"127.0.0.1:{base + i}": i for i in range(4)},
        "rules": [
            {
                "label": "split",
                "partition": [[0, 1], [2, 3]],
                "at": 6.0,
                "until": 11.0,
            }
        ],
    }
    extra_env = {"HOTSTUFF_FAULTS": json.dumps(spec)}
    procs = {}
    feeder = None
    try:
        for i in range(4):
            procs[i] = _spawn_node(tmp_path, i, repo_root, extra_env)
        feeder = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "hotstuff_tpu.node.client",
                "--committee",
                str(tmp_path / "committee.json"),
                "--rate",
                "200",
                "--duration",
                "150",
                "--warmup",
                "1",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={**os.environ, "PYTHONPATH": repo_root},
        )
        # clean commits before the window opens at t=6
        assert _wait_commits(
            tmp_path, 3, minimum=3, deadline_s=max(0.1, epoch + 6 - time.time())
        ), "no commits before the partition opened"
        # crash node 3 just as the partition bites: groups are now 2|1
        delay = epoch + 6.0 - time.time()
        if delay > 0:
            time.sleep(delay)
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)
        dead_baseline = len(_commits(tmp_path, 3))
        survivor_baseline = len(_commits(tmp_path, 0))
        # heal at t=11: {0,1,2} are 3/4 = quorum again and must resume
        delay = epoch + 11.0 - time.time()
        if delay > 0:
            time.sleep(delay)
        assert _wait_commits(
            tmp_path, 0, minimum=3, deadline_s=30,
            baseline=survivor_baseline,
        ), "survivors never resumed after the heal"
        # restart node 3 (t>=12, outside every window) on its old store
        delay = epoch + 12.0 - time.time()
        if delay > 0:
            time.sleep(delay)
        procs[3] = _spawn_node(tmp_path, 3, repo_root, extra_env)
        assert _wait_commits(
            tmp_path, 3, minimum=3, deadline_s=40, baseline=dead_baseline
        ), "restarted node never resumed committing"
        # committee-wide safety across both of node 3's lifetimes
        history = {
            f"node-{i}": [(0.0, int(r), d) for r, d in _commits(tmp_path, i)]
            for i in range(4)
        }
        ok, violations = check_safety(history)
        assert ok, violations
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        if feeder is not None and feeder.poll() is None:
            feeder.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
