"""BLS12-381 backend tests: pairing bilinearity, sign/verify, aggregation,
threshold reconstruction, proof-of-possession, VerifierBackend adapter.

The pairing has no external library oracle in this image; correctness is
pinned by bilinearity identities (which a wrong Miller loop / final
exponentiation cannot satisfy) plus subgroup/on-curve checks against the
standard BLS12-381 constants."""

from __future__ import annotations

import asyncio

import pytest

from hotstuff_tpu.crypto.bls import (
    BlsPublicKey,
    BlsSignature,
    aggregate_public_keys,
    aggregate_signatures,
    combine_partials,
    keygen,
    prove_possession,
    split_secret,
    verify_aggregate,
    verify_possession,
)
from hotstuff_tpu.crypto.bls.curve import G1Point, G2Point, hash_to_g1
from hotstuff_tpu.crypto.bls.fields import P, R
from hotstuff_tpu.crypto.bls.pairing import pairing, pairings_equal
from hotstuff_tpu.crypto.bls.service import BlsSigningService, BlsVerifier


def test_curve_constants():
    g1, g2 = G1Point.generator(), G2Point.generator()
    assert g1.is_on_curve() and g2.is_on_curve()
    # prime-order subgroup — via the unreduced ladder (mul() reduces mod
    # R, so mul(R) would be the trivial mul(0))
    assert g1.in_subgroup() and g2.in_subgroup()
    assert not g1.mul(R - 1).inf
    # group laws
    assert g1 + G1Point.identity() == g1
    assert (g1 + g1) + g1 == g1.mul(3)
    assert (g2 + g2) + g2 == g2.mul(3)
    assert (g1 + (-g1)).inf


def test_pairing_bilinearity():
    g1, g2 = G1Point.generator(), G2Point.generator()
    e = pairing(g1, g2)
    assert pairing(g1.mul(5), g2.mul(3)) == e.pow(15)
    assert pairing(g1.mul(2), g2) == pairing(g1, g2.mul(2))
    assert pairings_equal(g1.mul(6), g2, g1.mul(2), g2.mul(3))
    assert not pairings_equal(g1.mul(6), g2, g1.mul(2), g2.mul(4))


def test_point_serialization_roundtrip():
    _, sk = keygen(b"serde-seed")
    pk = sk.public_key()
    sig = sk.sign(b"message")
    assert BlsPublicKey.from_bytes(pk.to_bytes()) == pk
    s2 = BlsSignature.from_bytes(sig.to_bytes())
    assert s2 is not None and s2.point == sig.point
    # identity + malformed encodings
    assert G1Point.from_bytes(bytes([0xC0] + [0] * 47)).inf
    assert G1Point.from_bytes(b"\x00" * 48) is None  # no compression bit
    assert G1Point.from_bytes((P).to_bytes(48, "big")) is None  # x >= p
    assert BlsPublicKey.from_bytes(b"junk") is None


def test_sign_verify_and_negatives():
    pk, sk = keygen(b"seed-1")
    sig = sk.sign(b"block digest")
    assert pk.verify(b"block digest", sig)
    assert not pk.verify(b"other digest", sig)
    pk2, _ = keygen(b"seed-2")
    assert not pk2.verify(b"block digest", sig)
    # identity signature must not verify (rogue trivial forgery)
    assert not pk.verify(b"block digest", BlsSignature(G1Point.identity()))


def test_shared_message_aggregation():
    """The QC shape: n signers, one digest, ONE pairing equality."""
    msg = b"qc digest"
    pairs = [keygen(bytes([i])) for i in range(5)]
    sigs = [sk.sign(msg) for _, sk in pairs]
    pks = [pk for pk, _ in pairs]
    agg = aggregate_signatures(sigs)
    assert verify_aggregate(msg, pks, agg)
    # any tampering breaks it
    assert not verify_aggregate(b"other", pks, agg)
    assert not verify_aggregate(msg, pks[:-1], agg)
    bad = aggregate_signatures(sigs[:-1])
    assert not verify_aggregate(msg, pks, bad)


def test_proof_of_possession():
    pk, sk = keygen(b"pop-seed")
    proof = prove_possession(sk)
    assert verify_possession(pk, proof)
    other_pk, other_sk = keygen(b"pop-other")
    assert not verify_possession(other_pk, proof)
    assert verify_possession(other_pk, prove_possession(other_sk))


def test_threshold_signatures():
    """3-of-5: any 3 partials reconstruct the group signature; 2 don't."""
    group_pk, group_sk = keygen(b"threshold-seed")
    shares = split_secret(group_sk, t=3, n=5, seed=b"shamir")
    msg = b"threshold digest"
    partials = [(idx, share.sign(msg)) for idx, share in shares]

    expected = group_sk.sign(msg)
    # any 3-subset reconstructs
    for subset in ([0, 1, 2], [0, 2, 4], [1, 3, 4]):
        combined = combine_partials([partials[i] for i in subset])
        assert combined.point == expected.point
        assert group_pk.verify(msg, combined)
    # 2 shares do NOT
    combined2 = combine_partials(partials[:2])
    assert combined2.point != expected.point
    assert not group_pk.verify(msg, combined2)


def test_verifier_backend_adapter():
    v = BlsVerifier()
    msg = b"adapter digest"
    pairs = [keygen(bytes([10 + i])) for i in range(4)]
    votes = [
        (pk.to_bytes(), sk.sign(msg).to_bytes()) for pk, sk in pairs
    ]
    assert v.verify_one(msg, votes[0][0], votes[0][1])
    assert not v.verify_one(b"other", votes[0][0], votes[0][1])
    assert v.verify_shared_msg(msg, votes)
    # one forged signature poisons the aggregate
    forged = votes[:3] + [(votes[3][0], votes[0][1])]
    assert not v.verify_shared_msg(msg, forged)
    oks = v.verify_many(
        [msg] * 4, [pk for pk, _ in votes], [s for _, s in votes]
    )
    assert oks == [True] * 4
    # distinct messages (the TC shape): batched multi-pairing fast path…
    msgs = [bytes([i]) * 32 for i in range(4)]
    dsigs = [sk.sign(m).to_bytes() for (_, sk), m in zip(pairs, msgs)]
    assert v.verify_many(msgs, [pk for pk, _ in votes], dsigs) == [True] * 4
    # …and the per-item fallback pinpoints the invalid entry
    bad = list(dsigs)
    bad[2] = dsigs[1]
    assert v.verify_many(msgs, [pk for pk, _ in votes], bad) == [
        True,
        True,
        False,
        True,
    ]


def test_bls_signing_service():
    async def run():
        pk, sk = keygen(b"svc-seed")
        svc = BlsSigningService(sk)
        sig = await svc.request_signature(b"actor digest")
        # returns the scheme-agnostic 48-byte consensus Signature wrapper
        decoded = BlsSignature.from_bytes(sig.to_bytes())
        assert decoded is not None and pk.verify(b"actor digest", decoded)
        svc.shutdown()
        with pytest.raises(RuntimeError):
            svc.sign_sync(b"after shutdown")

    asyncio.run(run())


def test_hash_to_g1_deterministic_and_in_subgroup():
    h1 = hash_to_g1(b"same input")
    h2 = hash_to_g1(b"same input")
    assert h1 == h2
    assert h1.is_on_curve() and h1.in_subgroup()
    assert hash_to_g1(b"different") != h1


# -- round-2 rewrite pins: Jacobian ladder, sparse Miller loop, GS chain ----


def _affine_mul_g1(pt: G1Point, k: int) -> G1Point:
    acc, add = G1Point.identity(), pt
    while k:
        if k & 1:
            acc = acc + add
        add = add + add
        k >>= 1
    return acc


def test_jacobian_mul_matches_affine_ladder():
    g = G1Point.generator()
    for k in [0, 1, 2, 3, 7, 0xDEADBEEF, R - 1, R, R + 5]:
        assert g.mul(k) == _affine_mul_g1(g, k % R)
    g2 = G2Point.generator()
    acc = G2Point.identity()
    for _ in range(17):
        acc = acc + g2
    assert g2.mul(17) == acc


def test_point_sum_matches_serial_addition():
    g = G1Point.generator()
    pts = [g.mul(i + 1) for i in range(9)]
    serial = G1Point.identity()
    for p in pts:
        serial = serial + p
    assert G1Point.sum(pts) == serial
    assert G1Point.sum([]).inf
    assert G1Point.sum([G1Point.identity()]).inf
    g2 = G2Point.generator()
    assert G2Point.sum([g2.mul(2), g2.mul(3)]) == g2.mul(5)


def test_fast_pairing_matches_textbook_oracle():
    """The production pairing is the textbook ate pairing cubed (the
    BLS12 hard-part chain computes 3·(p⁴−p²+1)/r exactly)."""
    from hotstuff_tpu.crypto.bls.pairing import pairing_textbook

    g1, g2 = G1Point.generator(), G2Point.generator()
    p, q = g1.mul(0xA5A5), g2.mul(0x5A5A)
    assert pairing(p, q) == pairing_textbook(p, q).pow(3)


def test_subgroup_check_rejects_non_subgroup_point():
    """G1 curve order is R·H1: an on-curve point from hash-and-check
    WITHOUT cofactor clearing is (overwhelmingly) outside the r-torsion.
    Round-1 bug pinned here: mul() reduces k mod R, so the old
    ``pt.mul(R).inf`` subgroup check was a no-op that accepted these."""
    import hashlib

    counter = 0
    while True:
        h = hashlib.sha256(b"raw-point" + counter.to_bytes(4, "big")).digest()
        x = int.from_bytes(h + h[:16], "big") % P
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P == y2:
            raw = G1Point(x, y)
            break
        counter += 1
    assert raw.is_on_curve()
    assert not raw.in_subgroup()
    assert G1Point.from_bytes(raw.to_bytes()) is None


def test_cyclotomic_square_matches_generic_square():
    """Granger-Scott squaring agrees with the generic square on
    cyclotomic-subgroup elements (where alone it is defined)."""
    from hotstuff_tpu.crypto.bls.fields import Fq12
    from hotstuff_tpu.crypto.bls.pairing import miller_loop

    g1, g2 = G1Point.generator(), G2Point.generator()
    f = miller_loop(g1.mul(3), g2.mul(5))
    t = f.conjugate() * f.inverse()
    g = t.frobenius(2) * t  # easy part → cyclotomic subgroup
    assert g.cyclotomic_square() == g * g
    gg = g * g * g
    assert gg.cyclotomic_square() == gg * gg


# -- native C++ pairing (native/bls_pairing.cpp) ----------------------------


def _native_or_skip():
    try:
        from hotstuff_tpu.crypto.bls import native
    except ImportError:
        pytest.skip("native BLS library unavailable")
    return native


def test_native_verify_parity_with_python_oracle():
    """The C++ port must agree with the Python implementation it was
    ported from: valid signatures verify, tampered signatures / wrong
    messages / wrong keys / malformed encodings are rejected."""
    native = _native_or_skip()
    from hotstuff_tpu.crypto.bls.curve import G1Point

    for i in range(4):
        pk, sk = keygen(bytes([120 + i]))
        msg = b"native parity %d" % i
        sig = sk.sign(msg)
        assert native.verify_one(msg, pk.to_bytes(), sig.to_bytes())
        bad = bytearray(sig.to_bytes())
        bad[17] ^= 0x04
        assert not native.verify_one(msg, pk.to_bytes(), bytes(bad))
        assert not native.verify_one(b"other", pk.to_bytes(), sig.to_bytes())
        pk2, _ = keygen(bytes([200 + i]))
        assert not native.verify_one(msg, pk2.to_bytes(), sig.to_bytes())
    # malformed operands
    pk, sk = keygen(b"native-malformed")
    sig = sk.sign(b"m").to_bytes()
    assert not native.verify_one(b"m", b"\x00" * 96, sig)
    assert not native.verify_one(b"m", pk.to_bytes(), b"\x00" * 48)
    assert not native.verify_one(b"m", pk.to_bytes()[:95], sig)
    # identity signature rejected (infinity encoding)
    inf_sig = G1Point.identity().to_bytes()
    assert not native.verify_one(b"m", pk.to_bytes(), inf_sig)


def test_native_subgroup_rejection():
    """The native decompressor must reject on-curve points outside the
    r-torsion, exactly like the round-2 Python fix."""
    native = _native_or_skip()
    import hashlib

    counter = 0
    while True:
        h = hashlib.sha256(b"raw-native" + counter.to_bytes(4, "big")).digest()
        x = int.from_bytes(h + h[:16], "big") % P
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P == y2:
            from hotstuff_tpu.crypto.bls.curve import G1Point

            raw = G1Point(x, y)
            if not raw.in_subgroup():
                break
        counter += 1
    pk, _ = keygen(b"native-subgroup")
    assert not native.verify_one(b"m", pk.to_bytes(), raw.to_bytes())


def test_bls_verifier_uses_native_and_agrees_with_python():
    """BlsVerifier picks the native path automatically; the pure-Python
    fallback (HOTSTUFF_BLS_NATIVE=0 construction path) returns identical
    verdicts on the same inputs, including the aggregate QC check."""
    _native_or_skip()
    v_native = BlsVerifier()
    assert v_native._native_verify is not None
    # force the Python path by stripping the native hook
    v_py = BlsVerifier()
    v_py._native_verify = None

    msg = b"native vs python verifier"
    pairs = [keygen(bytes([140 + i])) for i in range(4)]
    votes = [(pk.to_bytes(), sk.sign(msg).to_bytes()) for pk, sk in pairs]
    assert v_native.verify_shared_msg(msg, votes)
    assert v_py.verify_shared_msg(msg, votes)
    forged = votes[:3] + [(votes[3][0], votes[0][1])]
    assert not v_native.verify_shared_msg(msg, forged)
    assert not v_py.verify_shared_msg(msg, forged)
    msgs = [bytes([i]) * 32 for i in range(4)]
    dsigs = [sk.sign(m).to_bytes() for (_, sk), m in zip(pairs, msgs)]
    want = [True] * 4
    assert v_native.verify_many(msgs, [p for p, _ in votes], dsigs) == want
    assert v_py.verify_many(msgs, [p for p, _ in votes], dsigs) == want


def test_native_aggregation_matches_python():
    """Native G1/G2 aggregate functions agree with the Python sums,
    including identity entries and malformed rejection."""
    native = _native_or_skip()
    from hotstuff_tpu.crypto.bls.curve import G1Point

    msg = b"native aggregation"
    pairs = [keygen(bytes([160 + i])) for i in range(5)]
    sigs = [sk.sign(msg) for _, sk in pairs]
    want_sig = aggregate_signatures(sigs).point.to_bytes()
    got_sig = native.aggregate_sigs([s.to_bytes() for s in sigs])
    assert got_sig == want_sig
    # identity entries are skipped like the Python sum
    with_inf = [s.to_bytes() for s in sigs] + [G1Point.identity().to_bytes()]
    assert native.aggregate_sigs(with_inf) == want_sig
    # malformed rejection
    assert native.aggregate_sigs([b"\x00" * 48]) is None
    assert native.aggregate_sigs([b"short"]) is None


def test_native_batch_rejects_small_order_component():
    """Soundness regression for the batched verifier: the G1 cofactor
    has SMALL factors (3, 11, ...), so sig* = sig + T with ord(T) = 3
    would survive a weighted-AGGREGATE-only subgroup check whenever the
    random weight is divisible by 3 — the batch must subgroup-check
    each signature individually (review finding, fixed in
    native/bls_pairing.cpp)."""
    native = _native_or_skip()
    import hashlib

    from hotstuff_tpu.crypto.bls.curve import H1, G1Point

    assert H1 % 3 == 0  # the attack's premise
    # an order-dividing-H1 point: clear the r-part of any curve point
    counter = 0
    small = None
    while small is None:
        h = hashlib.sha256(b"small-order" + bytes([counter])).digest()
        x = int.from_bytes(h + h[:16], "big") % P
        y2 = (x**3 + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P == y2:
            t = G1Point(x, y)._mul_raw(R)  # order divides H1
            if not t.inf:
                order3 = t._mul_raw(H1 // 3)
                small = order3 if not order3.inf else t
        counter += 1

    n = 4
    pairs = [keygen(bytes([170 + i])) for i in range(n)]
    msgs = [bytes([i]) * 32 for i in range(n)]
    sigs = [sk.sign(m).to_bytes() for (_, sk), m in zip(pairs, msgs)]
    pks = [pk.to_bytes() for pk, _ in pairs]
    evil = (G1Point.from_bytes(sigs[0]) + small).to_bytes()
    tampered = [evil] + sigs[1:]
    # with prob 1/3 per trial a weighted-aggregate-only check would pass;
    # 8 trials make a regression fail with prob (2/3)^8 < 5%... inverted:
    # ANY accepting trial is the bug
    for _ in range(8):
        assert not native.verify_batch(msgs, pks, tampered)
    # equal-length contract (out-of-bounds regression)
    assert not native.verify_batch(msgs, pks[:-1], sigs)
    assert not native.verify_batch(msgs, pks, sigs[:-1])
    # and the untampered set still verifies
    assert native.verify_batch(msgs, pks, sigs)


def test_grouped_tc_batch_verification():
    """The same-digest grouped TC path (storm shape: every entry shares
    one timeout digest): a valid grouped batch passes, one tampered
    entry is pinpointed by the per-item fallback, and a mixed batch
    (two digest groups) verifies group-aggregated."""
    from hotstuff_tpu.crypto.scheme import bls_keygen, make_cpu_verifier

    v = make_cpu_verifier("bls")
    members = [bls_keygen(b"\x61" * 32, i) for i in range(8)]
    d1, d2 = b"\x01" * 32, b"\x02" * 32

    def sign(secret, msg):
        from hotstuff_tpu.crypto.bls import BlsSecretKey

        scalar = int.from_bytes(secret, "big")
        return BlsSecretKey(scalar).sign(msg).to_bytes()

    # one shared digest (the realistic storm TC)
    digests = [d1] * 8
    pks = [pk.to_bytes() for pk, _ in members]
    sigs = [sign(sk, d1) for _, sk in members]
    assert v.verify_many(digests, pks, sigs) == [True] * 8

    # two groups
    digests2 = [d1] * 5 + [d2] * 3
    sigs2 = [sign(sk, d) for (_, sk), d in zip(members, digests2)]
    assert v.verify_many(digests2, pks, sigs2) == [True] * 8

    # tampered entry: the grouped aggregate fails, the per-item
    # fallback pinpoints exactly the bad index
    bad = bytearray(sigs[3])
    bad[1] ^= 0xFF
    sigs_bad = sigs[:3] + [bytes(bad)] + sigs[4:]
    out = v.verify_many(digests, pks, sigs_bad)
    assert out == [True] * 3 + [False] + [True] * 4


def test_native_g1_membership_endomorphism_parity():
    """The production subgroup check is the GLV-endomorphism test
    (phi(P) == -[x^2]P); the full r-order ladder stays in the library
    as the oracle.  Parity over every torsion shape an adversary can
    reach: raw curve points, cofactor-cleared (in G1), pure-cofactor,
    mixed, and order-3 components (3 divides the G1 cofactor).  A wrong
    beta (the other cube root's eigenvalue) or ladder edge case flips
    one of these."""
    native = _native_or_skip()
    import ctypes
    import hashlib

    lib = native._lib
    lib.hs_bls_g1_membership.restype = ctypes.c_int
    lib.hs_bls_g1_membership.argtypes = [ctypes.c_char_p, ctypes.c_int]

    bls_x = -0xD201000000010000
    h1 = (bls_x - 1) ** 2 // 3

    def ser(pt: G1Point) -> bytes:
        if pt.inf:
            return bytes(96)
        return pt.x.to_bytes(48, "big") + pt.y.to_bytes(48, "big")

    def curve_point(seed: bytes) -> G1Point:
        counter = 0
        while True:
            h = hashlib.sha256(seed + counter.to_bytes(4, "big")).digest()
            x = int.from_bytes(h + h[:16], "big") % P
            y2 = (x**3 + 4) % P
            y = pow(y2, (P + 1) // 4, P)
            if y * y % P == y2:
                return G1Point(x, y)
            counter += 1

    g = G1Point.generator()
    points = [G1Point.identity(), g, g._mul_raw(12345)]
    for i in range(3):
        q = curve_point(bytes([i, 0x7C]) * 16)
        points += [
            q,
            q._mul_raw(h1),  # in G1
            q._mul_raw(R),  # pure cofactor torsion
            q._mul_raw(h1) + q._mul_raw(R),  # mixed
            q._mul_raw(R)._mul_raw(h1 // 3),  # order 1 or 3
        ]
    checked = 0
    for pt in points:
        fast = lib.hs_bls_g1_membership(ser(pt), 0)
        slow = lib.hs_bls_g1_membership(ser(pt), 1)
        assert fast == slow != -1, (pt.inf, fast, slow)
        checked += 1
    assert checked == len(points)
