"""BLS12-381 backend tests: pairing bilinearity, sign/verify, aggregation,
threshold reconstruction, proof-of-possession, VerifierBackend adapter.

The pairing has no external library oracle in this image; correctness is
pinned by bilinearity identities (which a wrong Miller loop / final
exponentiation cannot satisfy) plus subgroup/on-curve checks against the
standard BLS12-381 constants."""

from __future__ import annotations

import asyncio

import pytest

from hotstuff_tpu.crypto.bls import (
    BlsPublicKey,
    BlsSignature,
    aggregate_public_keys,
    aggregate_signatures,
    combine_partials,
    keygen,
    prove_possession,
    split_secret,
    verify_aggregate,
    verify_possession,
)
from hotstuff_tpu.crypto.bls.curve import G1Point, G2Point, hash_to_g1
from hotstuff_tpu.crypto.bls.fields import P, R
from hotstuff_tpu.crypto.bls.pairing import pairing, pairings_equal
from hotstuff_tpu.crypto.bls.service import BlsSignatureService, BlsVerifier


def test_curve_constants():
    g1, g2 = G1Point.generator(), G2Point.generator()
    assert g1.is_on_curve() and g2.is_on_curve()
    assert g1.mul(R).inf and g2.mul(R).inf  # prime-order subgroup
    assert not g1.mul(R - 1).inf
    # group laws
    assert g1 + G1Point.identity() == g1
    assert (g1 + g1) + g1 == g1.mul(3)
    assert (g2 + g2) + g2 == g2.mul(3)
    assert (g1 + (-g1)).inf


def test_pairing_bilinearity():
    g1, g2 = G1Point.generator(), G2Point.generator()
    e = pairing(g1, g2)
    assert pairing(g1.mul(5), g2.mul(3)) == e.pow(15)
    assert pairing(g1.mul(2), g2) == pairing(g1, g2.mul(2))
    assert pairings_equal(g1.mul(6), g2, g1.mul(2), g2.mul(3))
    assert not pairings_equal(g1.mul(6), g2, g1.mul(2), g2.mul(4))


def test_point_serialization_roundtrip():
    _, sk = keygen(b"serde-seed")
    pk = sk.public_key()
    sig = sk.sign(b"message")
    assert BlsPublicKey.from_bytes(pk.to_bytes()) == pk
    s2 = BlsSignature.from_bytes(sig.to_bytes())
    assert s2 is not None and s2.point == sig.point
    # identity + malformed encodings
    assert G1Point.from_bytes(bytes([0xC0] + [0] * 47)).inf
    assert G1Point.from_bytes(b"\x00" * 48) is None  # no compression bit
    assert G1Point.from_bytes((P).to_bytes(48, "big")) is None  # x >= p
    assert BlsPublicKey.from_bytes(b"junk") is None


def test_sign_verify_and_negatives():
    pk, sk = keygen(b"seed-1")
    sig = sk.sign(b"block digest")
    assert pk.verify(b"block digest", sig)
    assert not pk.verify(b"other digest", sig)
    pk2, _ = keygen(b"seed-2")
    assert not pk2.verify(b"block digest", sig)
    # identity signature must not verify (rogue trivial forgery)
    assert not pk.verify(b"block digest", BlsSignature(G1Point.identity()))


def test_shared_message_aggregation():
    """The QC shape: n signers, one digest, ONE pairing equality."""
    msg = b"qc digest"
    pairs = [keygen(bytes([i])) for i in range(5)]
    sigs = [sk.sign(msg) for _, sk in pairs]
    pks = [pk for pk, _ in pairs]
    agg = aggregate_signatures(sigs)
    assert verify_aggregate(msg, pks, agg)
    # any tampering breaks it
    assert not verify_aggregate(b"other", pks, agg)
    assert not verify_aggregate(msg, pks[:-1], agg)
    bad = aggregate_signatures(sigs[:-1])
    assert not verify_aggregate(msg, pks, bad)


def test_proof_of_possession():
    pk, sk = keygen(b"pop-seed")
    proof = prove_possession(sk)
    assert verify_possession(pk, proof)
    other_pk, other_sk = keygen(b"pop-other")
    assert not verify_possession(other_pk, proof)
    assert verify_possession(other_pk, prove_possession(other_sk))


def test_threshold_signatures():
    """3-of-5: any 3 partials reconstruct the group signature; 2 don't."""
    group_pk, group_sk = keygen(b"threshold-seed")
    shares = split_secret(group_sk, t=3, n=5, seed=b"shamir")
    msg = b"threshold digest"
    partials = [(idx, share.sign(msg)) for idx, share in shares]

    expected = group_sk.sign(msg)
    # any 3-subset reconstructs
    for subset in ([0, 1, 2], [0, 2, 4], [1, 3, 4]):
        combined = combine_partials([partials[i] for i in subset])
        assert combined.point == expected.point
        assert group_pk.verify(msg, combined)
    # 2 shares do NOT
    combined2 = combine_partials(partials[:2])
    assert combined2.point != expected.point
    assert not group_pk.verify(msg, combined2)


def test_verifier_backend_adapter():
    v = BlsVerifier()
    msg = b"adapter digest"
    pairs = [keygen(bytes([10 + i])) for i in range(4)]
    votes = [
        (pk.to_bytes(), sk.sign(msg).to_bytes()) for pk, sk in pairs
    ]
    assert v.verify_one(msg, votes[0][0], votes[0][1])
    assert not v.verify_one(b"other", votes[0][0], votes[0][1])
    assert v.verify_shared_msg(msg, votes)
    # one forged signature poisons the aggregate
    forged = votes[:3] + [(votes[3][0], votes[0][1])]
    assert not v.verify_shared_msg(msg, forged)
    oks = v.verify_many(
        [msg] * 4, [pk for pk, _ in votes], [s for _, s in votes]
    )
    assert oks == [True] * 4


def test_bls_signature_service_actor():
    async def run():
        pk, sk = keygen(b"svc-seed")
        svc = BlsSignatureService(sk)
        sig = await svc.request_signature(b"actor digest")
        assert pk.verify(b"actor digest", sig)
        svc.shutdown()

    asyncio.run(run())


def test_hash_to_g1_deterministic_and_in_subgroup():
    h1 = hash_to_g1(b"same input")
    h2 = hash_to_g1(b"same input")
    assert h1 == h2
    assert h1.is_on_curve() and h1.mul(R).inf
    assert hash_to_g1(b"different") != h1
