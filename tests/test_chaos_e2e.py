"""Chaos-plane end-to-end tests.

The fast smoke (tier-1) runs a REAL 4-node committee in-process under a
seeded split-brain window and checks the committee-wide invariants on
the live commit streams: safety throughout, total stall while neither
half has quorum, and commit resumption after the heal.

The slow tier runs every canned scenario through the full
``python -m benchmark chaos`` path (subprocess committee + client +
crash schedule + log-scrape invariant check) on both transports.
"""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from benchmark.invariants import check_liveness, check_safety

from .common import async_test, fresh_base_port
from .test_consensus_e2e import _feed_producers, _shutdown, _spawn_committee

PARTITION_AT = 3.0
HEAL_AT = 7.0


@async_test
async def test_split_brain_partition_heals_in_process(tmp_path, monkeypatch):
    """Seeded split-brain on a live in-process committee: commits before
    the window, a hard stall inside it (2/2 — neither half has quorum),
    and recovery after the heal, with safety holding end to end."""
    base = fresh_base_port()
    epoch = time.time()
    spec = {
        "name": "smoke-split-brain",
        "seed": 7,
        "epoch_unix": epoch,
        "nodes": {f"127.0.0.1:{base + i}": i for i in range(4)},
        "rules": [
            {
                "label": "split",
                "partition": [[0, 1], [2, 3]],
                "at": PARTITION_AT,
                "until": HEAL_AT,
            }
        ],
    }
    monkeypatch.setenv("HOTSTUFF_FAULTS", json.dumps(spec))
    nodes = await _spawn_committee(tmp_path, base, range(4), timeout_delay=500)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    records: dict[str, list[tuple[float, int, str]]] = {
        f"node-{i}": [] for i in range(4)
    }

    async def collect(i, commit_q):
        while True:
            block = await commit_q.get()
            records[f"node-{i}"].append(
                (time.time(), block.round, str(block.digest()))
            )

    collectors = [
        asyncio.ensure_future(collect(i, commit_q))
        for i, (_, commit_q, _) in enumerate(nodes)
    ]
    try:
        heal_unix = epoch + HEAL_AT
        deadline = heal_unix + 25.0
        recovered = False
        while time.time() < deadline:
            ok, _, _ = check_liveness(records, heal_unix=heal_unix)
            if ok:
                recovered = True
                break
            await asyncio.sleep(0.5)

        every = [obs for commits in records.values() for obs in commits]
        pre_window = [r for t, r, _ in every if t <= epoch + PARTITION_AT]
        assert pre_window, "no commits before the partition opened"
        assert recovered, (
            "no new rounds committed within 25s of the heal; observed "
            f"{sorted({r for _, r, _ in every})}"
        )
        # the partition actually bit: once in-flight blocks drained,
        # no NEW round committed until the heal (neither half = quorum)
        stall_from = epoch + PARTITION_AT + 1.5
        pre_stall = [r for t, r, _ in every if t <= stall_from]
        during = [r for t, r, _ in every if stall_from < t <= heal_unix]
        assert not during or max(during) <= max(pre_stall), (
            "rounds advanced inside the partition window"
        )
        ok, violations = check_safety(records)
        assert ok, violations
    finally:
        for c in collectors:
            c.cancel()
        await _shutdown(nodes, feeder)


@async_test
async def test_leader_isolation_heals_in_process(tmp_path, monkeypatch):
    """Isolate node 0 (leader of round 0 mod 4): the other three keep
    quorum through the window via view changes; node 0 rejoins after."""
    base = fresh_base_port()
    epoch = time.time()
    spec = {
        "name": "smoke-isolation",
        "seed": 3,
        "epoch_unix": epoch,
        "nodes": {f"127.0.0.1:{base + i}": i for i in range(4)},
        "rules": [{"label": "iso", "isolate": 0, "at": 3.0, "until": 6.0}],
    }
    monkeypatch.setenv("HOTSTUFF_FAULTS", json.dumps(spec))
    nodes = await _spawn_committee(tmp_path, base, range(4), timeout_delay=500)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    records: dict[str, list[tuple[float, int, str]]] = {
        f"node-{i}": [] for i in range(4)
    }

    async def collect(i, commit_q):
        while True:
            block = await commit_q.get()
            records[f"node-{i}"].append(
                (time.time(), block.round, str(block.digest()))
            )

    collectors = [
        asyncio.ensure_future(collect(i, commit_q))
        for i, (_, commit_q, _) in enumerate(nodes)
    ]
    try:
        heal_unix = epoch + 6.0
        deadline = heal_unix + 25.0
        while time.time() < deadline:
            survivors = {k: v for k, v in records.items() if k != "node-0"}
            ok, _, _ = check_liveness(survivors, heal_unix=heal_unix)
            # the isolated node must also catch up post-heal
            if ok and any(t > heal_unix for t, _, _ in records["node-0"]):
                break
            await asyncio.sleep(0.5)
        else:
            pytest.fail(
                "committee (or the isolated node) never recovered: "
                + str({k: len(v) for k, v in records.items()})
            )
        ok, violations = check_safety(records)
        assert ok, violations
    finally:
        for c in collectors:
            c.cancel()
        await _shutdown(nodes, feeder)


# ---- full-harness scenario runs (slow tier) --------------------------------


def _run_scenario(tmp_path, monkeypatch, scenario, transport, seed=7):
    from benchmark.chaos import ChaosBench

    monkeypatch.chdir(tmp_path)
    bench = ChaosBench(
        scenario=scenario,
        seed=seed,
        nodes=4,
        rate=400,
        duration=10.0,  # extended automatically past last heal
        timeout_delay=1_000,
        transport=transport,
    )
    parser = bench.run()
    ok, block = bench.check_invariants()
    assert parser.has_window(), "no commits at all"
    assert ok, f"invariants failed:\n{block}"
    assert "Safety (no conflicting commits): PASS" in block
    assert "Liveness" in block and "PASS" in block
    return block


@pytest.mark.slow
@pytest.mark.parametrize("transport", ["asyncio", "native"])
@pytest.mark.parametrize(
    "scenario",
    ["split-brain", "leader-isolation", "flapping-link",
     "rolling-crash-restart"],
)
def test_canned_scenarios_full_harness(
    tmp_path, monkeypatch, scenario, transport
):
    if transport == "native":
        pytest.importorskip("hotstuff_tpu.network.native")
    _run_scenario(tmp_path, monkeypatch, scenario, transport)
