"""Aggregated compact certificates (ISSUE 9): parity with the vote-list
form, the QC-verify memo, the device running sum, the Handel aggregation
plane, and the async claims routing.

The load-bearing property is VERDICT PARITY: for every input — honest
quorum, forged certificate, equivocating twin — the compact form (one
aggregate + signer bitmap, one pairing) and the vote-list form (n
signatures, batch pairing) must accept and reject IDENTICALLY at every
committee size.  A divergence in either direction is a safety bug (the
aggregate path accepting what the batch path rejects) or a liveness bug
(the reverse).
"""

from __future__ import annotations

import time

import pytest

from hotstuff_tpu.consensus.config import Committee
from hotstuff_tpu.consensus.errors import ConsensusError
from hotstuff_tpu.consensus.messages import (
    QC,
    QC_CACHE_STATS,
    TC,
    Vote,
    bitmap_indices,
    bitmap_keys,
    make_signer_bitmap,
    timeout_digest,
)
from hotstuff_tpu.crypto import Digest, PublicKey, Signature
from hotstuff_tpu.crypto.bls import BlsSecretKey, prove_possession
from hotstuff_tpu.crypto.bls.curve import G1Point
from hotstuff_tpu.crypto.scheme import make_cpu_verifier


def bls_committee(n: int, base_port: int = 24_000):
    """(committee, {pk: sk}) with small-scalar secrets — fixture cost is
    O(n) cheap multiplies, verification cost is the real thing."""
    sks = [BlsSecretKey(i + 2) for i in range(n)]
    by_pk = {PublicKey(sk.public_key().to_bytes()): sk for sk in sks}
    com = Committee.new(
        [
            (pk, 1, ("127.0.0.1", base_port + i))
            for i, pk in enumerate(sorted(by_pk))
        ],
        scheme="bls",
        pops={pk: prove_possession(sk).to_bytes() for pk, sk in by_pk.items()},
    )
    return com, by_pk


def quorum_votes(com, by_pk, digest, round_=3):
    """Quorum-many (pk, sig) pairs over the QC digest for (digest, round)."""
    msg = QC(hash=digest, round=round_).digest().to_bytes()
    return [
        (pk, Signature(by_pk[pk].sign(msg).to_bytes()))
        for pk in com.sorted_keys()[: com.quorum_threshold()]
    ]


def compact_from(votes, com, digest, round_=3) -> QC:
    agg = G1Point.sum(
        [
            G1Point.from_bytes(sig.to_bytes(), subgroup_check=False)
            for _, sig in votes
        ]
    ).to_bytes()
    return QC(
        hash=digest,
        round=round_,
        votes=[],
        agg_sig=Signature(agg),
        signers=make_signer_bitmap(
            [pk for pk, _ in votes], com.sorted_keys()
        ),
    )


def verdict(qc: QC, com, verifier) -> bool:
    try:
        qc.check_weight(com)
        qc.verify(com, verifier)
        return True
    except ConsensusError:
        return False


@pytest.mark.parametrize("n", [4, 16, 64])
def test_compact_votelist_verdict_parity(n):
    """Identical accept/reject at committee sizes 4/16/64 for honest,
    forged and wrong-digest certificates — both forms, same verdicts."""
    com, by_pk = bls_committee(n)
    verifier = make_cpu_verifier("bls")
    digest = Digest.of(f"parity-{n}".encode())
    votes = quorum_votes(com, by_pk, digest)

    honest_list = QC(hash=digest, round=3, votes=list(votes))
    honest_compact = compact_from(votes, com, digest)
    assert honest_compact.wire_size() < honest_list.wire_size()
    assert verdict(honest_list, com, verifier) is True
    assert verdict(honest_compact, com, verifier) is True

    # a quorum's signatures over a DIFFERENT digest: both forms reject
    other = Digest.of(f"equivocating-twin-{n}".encode())
    wrong_list = QC(hash=other, round=3, votes=list(votes))
    wrong_compact = QC(
        hash=other,
        round=3,
        votes=[],
        agg_sig=honest_compact.agg_sig,
        signers=honest_compact.signers,
    )
    assert verdict(wrong_list, com, verifier) is False
    assert verdict(wrong_compact, com, verifier) is False

    # one flipped signature / one flipped aggregate byte: both reject
    bad_sig = bytearray(votes[0][1].to_bytes())
    bad_sig[5] ^= 0xFF
    tampered_list = QC(
        hash=digest,
        round=3,
        votes=[(votes[0][0], Signature(bytes(bad_sig)))] + votes[1:],
    )
    bad_agg = bytearray(honest_compact.agg_sig.to_bytes())
    bad_agg[5] ^= 0xFF
    tampered_compact = QC(
        hash=digest,
        round=3,
        votes=[],
        agg_sig=Signature(bytes(bad_agg)),
        signers=honest_compact.signers,
    )
    assert verdict(tampered_list, com, verifier) is False
    assert verdict(tampered_compact, com, verifier) is False


def test_adversary_forgeries_fail_both_forms():
    """faults/adversary.py's forged certificates keep failing against
    the aggregate path: forged_qc (vote-list garbage) and its compact
    twin forged_compact_qc both pass check_weight and both die in
    verification."""
    from hotstuff_tpu.faults.adversary import AdversaryPlane

    com, by_pk = bls_committee(4)
    plane = AdversaryPlane(
        {
            "name": "byz-forge-agg",
            "seed": 11,
            "epoch_unix": time.time(),
            "nodes": {f"127.0.0.1:{24_000 + i}": i for i in range(4)},
            "adversary": [{"policy": "forge-qc", "node": 0, "at": 0.0}],
        },
        ("127.0.0.1", 24_000),
    )
    verifier = make_cpu_verifier("bls")
    compact = plane.forged_compact_qc(com, 9)
    assert compact.is_compact
    compact.check_weight(com)  # structurally a quorum, by construction
    assert verdict(compact, com, verifier) is False
    # the compact forgery round-trips the wire like any real certificate
    from hotstuff_tpu.consensus.wire import decode_message, encode_tc

    tc = TC(round=9, votes=[], groups=None)
    assert not tc.is_compact  # sanity on the flag itself

    # the vote-list forgery still fails too (BLS sigs are 48B; the
    # plane draws 64B garbage — rejected before crypto by the wire
    # rules, and by crypto here)
    forged = plane.forged_qc(com, 9)
    assert verdict(forged, com, verifier) is False


def test_qc_verify_memoized_by_digest():
    """The same certificate arriving via Propose, sync and TC high-QCs
    is verified ONCE per cache: the second verify is a cache hit
    (qc_verify_cache_hit telemetry) and skips crypto entirely."""
    com, by_pk = bls_committee(4)
    verifier = make_cpu_verifier("bls")
    digest = Digest.of(b"memo block")
    votes = quorum_votes(com, by_pk, digest)
    qc = compact_from(votes, com, digest)

    cache: set = set()
    before = dict(QC_CACHE_STATS)
    qc.verify(com, verifier, cache=cache)
    assert len(cache) == 1
    assert QC_CACHE_STATS["misses"] == before["misses"] + 1

    # a BYTE-IDENTICAL copy (fresh object) hits the memo
    copy = compact_from(votes, com, digest)

    class Exploding:
        def __getattr__(self, name):  # any crypto call is a test failure
            raise AssertionError("cache hit must not touch the verifier")

    copy.verify(com, Exploding(), cache=cache)
    assert QC_CACHE_STATS["hits"] == before["hits"] + 1

    # claims() honours the same memo: no claims for a cached certificate
    assert copy.claims(cache=cache, committee=com) == []
    assert QC_CACHE_STATS["hits"] == before["hits"] + 2

    # a DIFFERENT certificate (vote-list form of the same quorum) has
    # its own key — compact and vote-list forms never collide
    aslist = QC(hash=digest, round=3, votes=list(votes))
    assert aslist._cache_key() not in cache
    aslist.verify(com, verifier, cache=cache)
    assert len(cache) == 2


def test_running_sum_matches_host_aggregate():
    """TpuG1RunningSum: k incremental device adds equal the host
    G1Point.sum of the same points, including past the naive chained-add
    overflow depth (the _freshen guard)."""
    jnp = pytest.importorskip("jax.numpy")  # noqa: F841 (jax gate)
    from hotstuff_tpu.tpu.bls import TpuG1RunningSum

    com, by_pk = bls_committee(4)
    digest = Digest.of(b"running sum")
    msg = QC(hash=digest, round=3).digest().to_bytes()
    # 60 points (> the ~40-50 chained-add overflow depth) from repeated
    # small-scalar signatures
    pts = [
        G1Point.from_bytes(
            BlsSecretKey(i + 2).sign(msg).to_bytes(), subgroup_check=False
        )
        for i in range(12)
    ] * 5
    acc = TpuG1RunningSum()
    for p in pts:
        acc.add(p)
    assert len(acc) == len(pts)
    assert acc.snapshot().to_bytes() == G1Point.sum(pts).to_bytes()
    acc.reset()
    assert len(acc) == 0


def test_aggregator_emits_compact_and_invalidates_on_replacement():
    """The vote Aggregator emits the compact form for BLS committees,
    counts it, records qc_bytes — and a replaced vote (equivocation
    repair) invalidates the running accumulator so the emitted aggregate
    still matches the surviving vote set."""
    from hotstuff_tpu.consensus.aggregator import Aggregator

    com, by_pk = bls_committee(4)
    verifier = make_cpu_verifier("bls")
    agg = Aggregator(com, verifier)
    bh = Digest.of(b"agg emission block")

    def signed(pk, h, r=5):
        v = Vote(hash=h, round=r, author=pk)
        v.signature = Signature(by_pk[pk].sign(v.digest().to_bytes()).to_bytes())
        return v

    ordered = com.sorted_keys()
    qc = None
    # first voter equivocates: same round, different digest, then the
    # real one — the maker replaces/evicts, the accumulator must follow
    agg.add_vote(signed(ordered[0], Digest.of(b"equivocation")), current_round=5)
    for pk in ordered[: com.quorum_threshold()]:
        qc = agg.add_vote(signed(pk, bh), current_round=5) or qc
    assert qc is not None and qc.is_compact
    qc.check_weight(com)
    qc.verify(com, verifier)  # the aggregate matches the final vote set
    assert agg.compact_qcs == 1
    assert agg.qc_wire_bytes == qc.wire_size()
    assert agg.stats()["compact_qcs_total"] == 1
    assert agg.stats()["qc_wire_bytes"] == qc.wire_size()

    # env kill-switch: HOTSTUFF_COMPACT_QC=0 reverts to vote lists
    import os

    os.environ["HOTSTUFF_COMPACT_QC"] = "0"
    try:
        agg2 = Aggregator(com, verifier)
        qc2 = None
        for pk in ordered[: com.quorum_threshold()]:
            qc2 = agg2.add_vote(signed(pk, bh, r=6), current_round=6) or qc2
        assert qc2 is not None and not qc2.is_compact
        qc2.verify(com, verifier)
    finally:
        del os.environ["HOTSTUFF_COMPACT_QC"]


def test_compact_tc_from_timeout_quorum():
    """TCMaker's compact form: per-high-qc-round groups, quorum weight
    across groups, verdict parity with the vote-list TC."""
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.consensus.messages import Timeout

    com, by_pk = bls_committee(4)
    verifier = make_cpu_verifier("bls")
    agg = Aggregator(com, verifier)
    ordered = com.sorted_keys()
    # authors split over two high_qc rounds (0 and 2)
    highs = {ordered[0]: 0, ordered[1]: 2, ordered[2]: 2}
    tc = None
    for pk in ordered[:3]:
        t = Timeout(high_qc=QC(round=highs[pk]), round=8, author=pk)
        t.signature = Signature(
            by_pk[pk].sign(t.digest().to_bytes()).to_bytes()
        )
        tc = agg.add_timeout(t) or tc
    assert tc is not None and tc.is_compact
    assert sorted(tc.high_qc_rounds()) == [0, 2, 2]
    tc.verify(com, verifier)  # must not raise
    assert agg.compact_tcs == 1

    # tamper one group's aggregate: rejected, like a bad vote-list TC
    g = tc.groups
    bad = TC(
        round=8,
        votes=[],
        groups=[(g[0][0], Signature(b"\x13" * 48), g[0][2])] + g[1:],
    )
    with pytest.raises(ConsensusError):
        bad.verify(com, verifier)


def test_handel_topology_and_merges():
    """Handel plane: deterministic seeded permutation, disjoint level
    blocks, overlap rejection, and O(log n) leader merges at full
    participation."""
    from hotstuff_tpu.consensus.handel import (
        HandelTopology,
        PartialAggregate,
        PartialOverlap,
        simulate,
    )

    n = 64
    t1 = HandelTopology.for_round(n, round_=4)
    t2 = HandelTopology.for_round(n, round_=4)
    assert t1.validator_at == t2.validator_at  # same round, same order
    t3 = HandelTopology.for_round(n, round_=5)
    assert t1.validator_at != t3.validator_at  # new round reshuffles
    # the permutation is a bijection
    assert sorted(t1.validator_at) == list(range(n))
    assert t1.levels == 6  # log2(64)

    # partial aggregates: disjoint merges combine, overlaps raise
    com, by_pk = bls_committee(4)
    digest = Digest.of(b"handel")
    msg = QC(hash=digest, round=4).digest().to_bytes()
    sigs = {
        i: by_pk[pk].sign(msg).to_bytes()
        for i, pk in enumerate(com.sorted_keys())
    }
    nbytes = 1
    a = PartialAggregate.single(sigs[0], 0, nbytes)
    b = PartialAggregate.single(sigs[1], 1, nbytes)
    ab = a.merge(b)
    assert ab.weight == 2
    with pytest.raises(PartialOverlap):
        ab.merge(b)  # validator 1 contributed twice

    # full simulation at 64: every contribution lands, leader does at
    # most `levels` merges — O(log n), not O(n)
    big_sigs = {
        i: BlsSecretKey(i + 2).sign(msg).to_bytes() for i in range(n)
    }
    topo = HandelTopology.for_round(n, round_=4)
    final, top_merges, total = simulate(topo, big_sigs)
    assert final.weight == n
    assert top_merges <= topo.levels
    # the tree-combined aggregate equals the flat host sum
    flat = G1Point.sum(
        [
            G1Point.from_bytes(s, subgroup_check=False)
            for s in big_sigs.values()
        ]
    )
    assert final.point.to_bytes() == flat.to_bytes()


def test_async_claims_route_agg():
    """'agg' claims take the one-pairing path through eval_claims_sync
    on both the aggregate-preferring (BLS) backend and via graceful
    False on a backend without aggregate support; claim_sig_count
    reports signer counts, not blob lengths."""
    from hotstuff_tpu.crypto.async_service import (
        claim_sig_count,
        eval_claims_sync,
    )

    com, by_pk = bls_committee(4)
    verifier = make_cpu_verifier("bls")
    digest = Digest.of(b"claims block")
    votes = quorum_votes(com, by_pk, digest)
    qc = compact_from(votes, com, digest)
    claims = qc.claims(committee=com)
    assert len(claims) == 1 and claims[0][0] == "agg"
    assert claim_sig_count(claims[0]) == len(votes)  # signers, not 48

    assert eval_claims_sync(verifier, claims) == [True]
    bad = (
        "agg",
        claims[0][1],
        b"\x77" * 48,
        claims[0][3],
    )
    # mixed wave: the bad aggregate fails, the good one still passes
    assert eval_claims_sync(verifier, [bad, claims[0]]) == [False, True]

    # an ed25519 backend has no aggregate form: claim resolves False
    # (never a crash, never a silent accept)
    ed = make_cpu_verifier("ed25519")
    assert eval_claims_sync(ed, claims) == [False]


def test_committee_scheme_selects_wire_form():
    """ed25519 committees keep the vote-list form end to end: the
    Aggregator never emits compact, and Committee.scheme drives it."""
    from hotstuff_tpu.consensus.aggregator import Aggregator
    from hotstuff_tpu.crypto import generate_keypair

    pairs = [generate_keypair(bytes(32), i) for i in range(4)]
    pairs.sort(key=lambda kp: kp[0])
    com = Committee.new(
        [
            (pk, 1, ("127.0.0.1", 25_000 + i))
            for i, (pk, _) in enumerate(pairs)
        ]
    )
    assert com.scheme == "ed25519"
    verifier = make_cpu_verifier("ed25519")
    agg = Aggregator(com, verifier)
    bh = Digest.of(b"ed25519 block")
    qc = None
    for pk, sk in pairs[:3]:
        v = Vote(hash=bh, round=4, author=pk)
        v.signature = Signature.new(v.digest(), sk)
        qc = agg.add_vote(v, current_round=4) or qc
    assert qc is not None and not qc.is_compact
    assert agg.compact_qcs == 0
    qc.verify(com, verifier)


def test_bitmap_helpers_roundtrip():
    """make_signer_bitmap / bitmap_indices / bitmap_keys agree for every
    subset size and preserve the committee order."""
    com, _ = bls_committee(16)
    ordered = com.sorted_keys()
    for k in (1, 5, 11, 16):
        subset = ordered[:k]
        bm = make_signer_bitmap(subset, ordered)
        assert len(bm) == 2  # ceil(16/8)
        assert list(bitmap_indices(bm)) == list(range(k))
        assert bitmap_keys(bm, ordered) == subset
    # scattered subset keeps ascending committee order regardless of
    # input order
    scattered = [ordered[9], ordered[1], ordered[14]]
    bm = make_signer_bitmap(scattered, ordered)
    assert bitmap_keys(bm, ordered) == [ordered[1], ordered[9], ordered[14]]
