"""Native ingest-arena lifecycle (ISSUE 20): the wave packer's ring
mechanics (pack → seal → adopt → recycle, surplus carry, full/discard
resync) and the service-side adoption policy built on top of them.

Wire-level accept/reject parity with the Python Decoder lives in
tests/test_wire_fuzz.py; this file covers the STATE machine — the
properties that make arena adoption safe to run concurrently with the
reactor (claim/row alignment, pad refill on recycle, idempotent
release).  Skips cleanly where the native toolchain is absent.
"""

from __future__ import annotations

import random
import struct

import pytest

from hotstuff_tpu.crypto.async_service import (
    DEFAULT_WAVE_BUCKETS,
    AdoptedWave,
    ZeroCopyIngest,
    eval_claims_arena,
    eval_claims_sync,
    make_pad_claim,
)
from hotstuff_tpu.crypto.digest import Digest


def _native():
    from hotstuff_tpu.crypto import native_ed25519 as ne

    if not ne.wave_pack_available():
        pytest.skip("native wave packer unavailable")
    return ne


def _vote(rng):
    """(wire frame, claim tuple) with random contents — lifecycle tests
    never verify signatures, only byte plumbing."""
    h = rng.randbytes(32)
    rnd = rng.randrange(1 << 63)
    pk = rng.randbytes(32)
    sig = rng.randbytes(64)
    frame = (
        bytes([1]) + h + struct.pack("<Q", rnd)
        + struct.pack("<I", 32) + pk
        + struct.pack("<I", 64) + sig
    )
    msg = h + struct.pack("<Q", rnd)
    return frame, ("one", Digest.of(msg).to_bytes(), pk, sig)


def _packer(ne, capacity=8, ring=3):
    pad = make_pad_claim()
    p = ne.WavePacker(capacity, ring)
    assert p.set_pad(pad[1], pad[2], pad[3])
    return p


def test_pad_must_be_installed_before_packing():
    ne = _native()
    rng = random.Random(1)
    p = ne.WavePacker(8, 2)
    try:
        frame, _ = _vote(rng)
        assert p.pack_vote(frame) == -3  # no pad installed
        pad = make_pad_claim()
        assert p.set_pad(pad[1], pad[2], pad[3])
        res = p.pack_vote(frame)
        assert not isinstance(res, int)
        # once any row is dirty a pad swap is rejected: recycled arenas
        # are re-padded with the INSTALLED pad, so swapping mid-flight
        # would mix pad generations inside one ring
        assert not p.set_pad(pad[1], pad[2], pad[3])
    finally:
        p.close()


def test_pack_seal_adopt_recycle_cycle():
    ne = _native()
    rng = random.Random(2)
    p = _packer(ne, capacity=8, ring=3)
    try:
        frames = [_vote(rng)[0] for _ in range(5)]
        for i, f in enumerate(frames):
            slot, digest = p.pack_vote(f)
            assert slot == i and len(digest) == 32
        assert p.count() == 5
        arena = p.seal(3)  # take 3, carry 2 into the next arena
        assert arena is not None
        info = p.arena_info(arena)
        assert info is not None
        _, _, _, rows, cap = info
        assert rows == 3 and cap == 8
        assert p.count() == 2  # the surplus carried over, still packed
        assert p.counters()["moved"] == 2
        assert p.recycle(arena)
        # the recycled arena rejoins the FREE pool: sealing the carried
        # surplus and three more packs still finds arenas
        for f in (_vote(rng)[0] for _ in range(3)):
            assert not isinstance(p.pack_vote(f), int)
        arena2 = p.seal(5)
        assert arena2 is not None and p.count() == 0
        assert p.recycle(arena2)
    finally:
        p.close()


def test_recycle_restores_pad_rows():
    ne = _native()
    rng = random.Random(3)
    pad = make_pad_claim()
    p = _packer(ne, capacity=4, ring=2)
    try:
        frame, claim = _vote(rng)
        p.pack_vote(frame)
        arena = p.seal(1)
        dig_addr, pk_addr, sig_addr, rows, cap = p.arena_info(arena)
        dig = bytes(ne.column_view(dig_addr, cap * 32))
        assert dig[:32] == claim[1]
        assert dig[32:64] == pad[1]  # untouched rows hold the pad
        assert p.recycle(arena)
        # after recycle the SAME arena must eventually come back clean;
        # drive one full ring cycle and check the dirty row was re-padded
        for _ in range(2):
            f2, _ = _vote(rng)
            p.pack_vote(f2)
            a = p.seal(1)
            info = p.arena_info(a)
            d = bytes(ne.column_view(info[0], 32 * 2))
            assert d[32:64] == pad[1]
            p.recycle(a)
    finally:
        p.close()


def test_open_arena_full_returns_full_code():
    ne = _native()
    rng = random.Random(4)
    p = _packer(ne, capacity=2, ring=2)
    try:
        assert not isinstance(p.pack_vote(_vote(rng)[0]), int)
        assert not isinstance(p.pack_vote(_vote(rng)[0]), int)
        assert p.pack_vote(_vote(rng)[0]) == -2  # open arena full
        assert p.counters()["full"] == 1
        assert p.discard()
        assert p.count() == 0
        assert not isinstance(p.pack_vote(_vote(rng)[0]), int)
    finally:
        p.close()


def test_malformed_frames_rejected_with_code():
    ne = _native()
    rng = random.Random(5)
    p = _packer(ne)
    try:
        good, _ = _vote(rng)
        assert p.pack_vote(good[:-1]) == -1
        assert p.pack_vote(b"") == -1
        assert p.pack_vote(bytes([2]) + good[1:]) == -1
        assert p.counters()["reject"] == 3
        assert p.count() == 0
    finally:
        p.close()


def test_ingest_full_arena_resyncs_instead_of_wedging():
    _native()
    rng = random.Random(6)
    ing = ZeroCopyIngest(capacity=2, ring_depth=2)
    assert ing.note_vote_frame(_vote(rng)[0])
    assert ing.note_vote_frame(_vote(rng)[0])
    # third pack hits the full open arena: the plane resyncs (discard +
    # key clear) so the NEXT vote stream can line up again, rather than
    # wedging with a full arena whose claims never arrive
    assert not ing.note_vote_frame(_vote(rng)[0])
    assert not ing.active
    assert ing.note_vote_frame(_vote(rng)[0])
    assert ing.active


def test_adoption_prefix_and_surplus_carry():
    _native()
    rng = random.Random(7)
    ing = ZeroCopyIngest(capacity=16, ring_depth=3)
    pairs = [_vote(rng) for _ in range(10)]
    for f, _ in pairs:
        assert ing.note_vote_frame(f)
    claims = [c for _, c in pairs]
    # first wave adopts a strict prefix; the surplus rows carry into
    # the next arena and stay adoptable in order
    w1 = ing.try_adopt(claims[:4], DEFAULT_WAVE_BUCKETS)
    assert w1 is not None and w1.n == 4 and w1.rows == 16
    w1.release()
    w2 = ing.try_adopt(claims[4:], DEFAULT_WAVE_BUCKETS)
    assert w2 is not None and w2.n == 6
    w2.release()
    assert not ing.active
    assert ing.zero_copy_waves == 2 and ing.fallback_waves == 0


def test_adoption_policy_disjoint_vs_overlap():
    _native()
    rng = random.Random(8)
    ing = ZeroCopyIngest(capacity=16, ring_depth=2)
    pairs = [_vote(rng) for _ in range(3)]
    for f, _ in pairs:
        ing.note_vote_frame(f)
    claims = [c for _, c in pairs]
    # a wave DISJOINT from the packed votes (pure QC/propose wave
    # between vote bursts) must leave the arena untouched — it is not
    # a fallback, the votes' own wave is still coming
    other = [("one", b"\x11" * 32, b"\x22" * 32, b"\x33" * 64)]
    assert ing.try_adopt(other, DEFAULT_WAVE_BUCKETS) is None
    assert ing.active and ing.fallback_waves == 0
    # a wave that OVERLAPS the packed stream out of position (dedup,
    # a dropped vote, mixed ordering) can never realign: resync + count
    mixed = [claims[1], claims[0]]
    assert ing.try_adopt(mixed, DEFAULT_WAVE_BUCKETS) is None
    assert not ing.active and ing.fallback_waves == 1
    # after the resync the stream lines up again from scratch
    for f, _ in pairs:
        ing.note_vote_frame(f)
    w = ing.try_adopt(claims, DEFAULT_WAVE_BUCKETS)
    assert w is not None
    w.release()


def test_adopted_wave_release_is_idempotent():
    _native()
    rng = random.Random(9)
    ing = ZeroCopyIngest(capacity=4, ring_depth=2)
    f, c = _vote(rng)
    ing.note_vote_frame(f)
    w = ing.try_adopt([c], (4,))
    assert isinstance(w, AdoptedWave)
    w.release()
    w.release()  # second release is a no-op, not a double recycle
    assert ing.packer.counters()["recycle"] == 1


class _PackedBackend:
    """Device-shaped stub: records the packed call, verdicts by row."""

    def __init__(self, rows_ok):
        self.rows_ok = rows_ok
        self.calls = 0

    def verify_packed(self, dig, pk, sig, rows):
        self.calls += 1
        assert len(dig) == rows * 32
        assert len(pk) == rows * 32
        assert len(sig) == rows * 64
        return self.rows_ok[:rows]


def test_eval_claims_arena_device_path_and_release():
    _native()
    rng = random.Random(10)
    ing = ZeroCopyIngest(capacity=4, ring_depth=2)
    pairs = [_vote(rng) for _ in range(2)]
    for f, _ in pairs:
        ing.note_vote_frame(f)
    claims = [c for _, c in pairs]
    w = ing.try_adopt(claims, (4,))
    assert w is not None and w.rows == 4
    backend = _PackedBackend([True, False, True, True])
    out = eval_claims_arena(backend, w, claims)
    assert backend.calls == 1
    assert out == [True, False]  # out[:n], pad rows dropped
    assert w._released  # released even on the happy path


def test_eval_claims_arena_falls_back_to_sync():
    """A backend with neither a packed path nor the flat batch fast
    path serves the CLAIM LIST through eval_claims_sync — the arena is
    an accelerator, never a correctness dependency — and the arena is
    still released."""
    _native()
    rng = random.Random(11)
    ing = ZeroCopyIngest(capacity=4, ring_depth=2)
    f, c = _vote(rng)
    ing.note_vote_frame(f)
    w = ing.try_adopt([c], (4,))
    assert w is not None

    class _Plain:
        supports_flat_batch = False

        def verify_many(self, digests, pks, sigs):
            assert digests == [c[1]] and pks == [c[2]] and sigs == [c[3]]
            return [True]

    out = eval_claims_arena(_Plain(), w, [c])
    assert out == [True]
    assert out == eval_claims_sync(_Plain(), [c])
    assert w._released


def test_counters_surface_expected_names():
    _native()
    ing = ZeroCopyIngest(capacity=4, ring_depth=2)
    counters = ing.counters()
    for name in (
        "packed", "reject", "full", "seal", "discard", "recycle",
        "moved", "zero_copy_waves", "fallback_waves",
    ):
        assert name in counters, name
