"""Field arithmetic tests: JAX limb ops vs arbitrary-precision ints."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hotstuff_tpu.crypto.ed25519_ref import P
from hotstuff_tpu.tpu import field as F

rng = random.Random(1234)

# jit everything once — eager dispatch of the unrolled limb ops is ~100x slower
jadd = jax.jit(F.add)
jsub = jax.jit(F.sub)
jmul = jax.jit(F.mul)
jsqr = jax.jit(F.sqr)
jinv = jax.jit(F.pow_inv)
jcanon = jax.jit(F.canonical)
jeq = jax.jit(F.eq)
jodd = jax.jit(F.is_odd)
jmul_small = jax.jit(F.mul_small, static_argnums=1)


def rand_int():
    return rng.randrange(P)


def to_dev(x: int):
    return jnp.asarray(F.limbs_from_int(x))


def test_limbs_roundtrip():
    for _ in range(20):
        x = rand_int()
        assert F.int_from_limbs(F.limbs_from_int(x)) == x


@pytest.mark.parametrize("op,pyop", [
    (jadd, lambda a, b: (a + b) % P),
    (jsub, lambda a, b: (a - b) % P),
    (jmul, lambda a, b: (a * b) % P),
])
def test_binary_ops(op, pyop):
    cases = [(rand_int(), rand_int()) for _ in range(20)]
    cases += [(0, 0), (P - 1, P - 1), (P - 1, 1), (1, 0), (19, P - 19)]
    a = jnp.stack([to_dev(x) for x, _ in cases])
    b = jnp.stack([to_dev(y) for _, y in cases])
    out = op(a, b)
    for i, (x, y) in enumerate(cases):
        got = F.int_from_limbs(out[i]) % P
        assert got == pyop(x, y), f"case {i}: {x} ? {y}"


def test_mul_chain_stays_bounded():
    # repeated multiplication must keep limbs inside the loose invariant
    x = to_dev(rand_int())[None, :]
    y = to_dev(rand_int())[None, :]
    for _ in range(50):
        x = jmul(x, y)
        arr = np.asarray(x)
        assert arr.min() >= 0
        assert arr[..., 1:19].max() < 2**13
        assert arr[..., 19].max() < 256
        assert arr[..., 0].max() < 2**13 + 1216


def test_sqr_and_mul_small():
    for _ in range(10):
        x = rand_int()
        assert F.int_from_limbs(jsqr(to_dev(x))) % P == x * x % P
        assert F.int_from_limbs(jmul_small(to_dev(x), 608)) % P == x * 608 % P


def test_inverse():
    vals = [rand_int() for _ in range(8)] + [1, 2, P - 1]
    a = jnp.stack([to_dev(x) for x in vals])
    inv = jinv(a)
    for i, x in enumerate(vals):
        assert F.int_from_limbs(inv[i]) % P == pow(x, P - 2, P)


def test_canonical_and_eq():
    for _ in range(10):
        x = rand_int()
        # same value from two different computation paths -> same canonical form
        a = jmul(to_dev(x), to_dev(1))
        b = jadd(to_dev(x), to_dev(0))
        assert np.array_equal(np.asarray(jcanon(a)), F.limbs_from_int(x))
        assert bool(jeq(a, b))
        assert not bool(jeq(a, to_dev((x + 1) % P)))
    # values just below/above p
    assert bool(jeq(jadd(to_dev(P - 1), to_dev(1)), to_dev(0)))
    assert bool(jeq(jadd(to_dev(P - 1), to_dev(2)), to_dev(1)))


def test_is_odd():
    for x in [0, 1, 2, P - 1, P - 2, rand_int(), rand_int()]:
        assert int(jodd(to_dev(x))) == (x % P) & 1


def _loose_max():
    """The inclusive loose-normalized maxima (field.py invariant)."""
    m = np.zeros(F.NLIMBS, np.int32)
    m[0] = (1 << F.LIMB_BITS) + F.FOLD
    m[1:19] = 1 << F.LIMB_BITS
    m[19] = 256
    return m


def test_two_pass_carry_extremes():
    """add/sub/mul_small(k<=4) use 2 carry passes — validate the invariant
    holds (and values are right) at the exact loose-normalized maxima,
    the worst case of the bound analysis in field.carry's docstring."""
    extremes = [
        _loose_max(),
        np.zeros(F.NLIMBS, np.int32),
        F.limbs_from_int(P - 1),
        F.limbs_from_int(1),
    ]
    for a_limbs in extremes:
        for b_limbs in extremes:
            a_int = F.int_from_limbs(a_limbs)
            b_int = F.int_from_limbs(b_limbs)
            a = jnp.asarray(a_limbs)
            b = jnp.asarray(b_limbs)
            for out, want in [
                (jadd(a, b), (a_int + b_int) % P),
                (jsub(a, b), (a_int - b_int) % P),
                (jmul_small(a, 2), a_int * 2 % P),
                (jmul_small(a, 4), a_int * 4 % P),
            ]:
                arr = np.asarray(out)
                assert arr.min() >= 0
                assert arr[0] <= (1 << F.LIMB_BITS) + F.FOLD
                assert arr[1:19].max() <= 1 << F.LIMB_BITS
                assert arr[19] <= 256
                assert F.int_from_limbs(arr) % P == want
