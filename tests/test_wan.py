"""WAN link-delay emulation tests (VERDICT r3 item 3)."""

import asyncio
import json
import time

from hotstuff_tpu.network.wan import LinkScheduler, WanModel, build_spec

from .common import async_test, fresh_base_port, listener


def test_build_spec_round_robins_regions():
    addrs = [("127.0.0.1", 9000 + i) for i in range(7)]
    spec = build_spec(addrs)
    assert spec["regions"]["127.0.0.1:9000"] == "us-east-1"
    assert spec["regions"]["127.0.0.1:9005"] == "us-east-1"  # wraps at 5
    assert spec["regions"]["127.0.0.1:9001"] == "eu-north-1"
    # symmetric matrix resolves both directions
    m = WanModel(spec, ("127.0.0.1", 9000))
    assert m.self_region == "us-east-1"


def test_delay_sampling_matches_matrix():
    addrs = [("127.0.0.1", 9000 + i) for i in range(5)]
    spec = build_spec(addrs)
    spec["jitter_pct"] = 0.0
    m = WanModel(spec, addrs[0])  # us-east-1
    # eu-north-1 peer: 55 ms one-way
    assert abs(m.delay(addrs[1]) - 0.055) < 1e-9
    # same-region peer (none here at 5 nodes) / unknown peer -> 0
    assert m.delay(("10.0.0.9", 1)) == 0.0
    # intra-region: two nodes in the same region at 10 nodes
    spec10 = build_spec([("127.0.0.1", 9100 + i) for i in range(10)])
    spec10["jitter_pct"] = 0.0
    m2 = WanModel(spec10, ("127.0.0.1", 9100))
    assert abs(m2.delay(("127.0.0.1", 9105)) - 0.0005) < 1e-9


@async_test
async def test_link_scheduler_pipelines_without_rate_limit():
    """N messages entering back-to-back all deliver ~one delay later —
    never N x delay (propagation, not a token bucket)."""
    sched = LinkScheduler(lambda: 0.05)
    t0 = asyncio.get_running_loop().time()
    ats = [sched.deliver_at() for _ in range(10)]
    # all deliver-at times are ~t0+50ms, monotone non-decreasing
    assert all(a >= t0 + 0.049 for a in ats)
    assert ats == sorted(ats)
    assert ats[-1] - ats[0] < 0.01
    await LinkScheduler.wait_until(ats[-1])
    assert asyncio.get_running_loop().time() >= ats[-1] - 1e-4


@async_test
async def test_simple_sender_delays_delivery():
    from hotstuff_tpu.network import SimpleSender

    port = fresh_base_port()
    expected = b"delayed hello"
    listen = asyncio.ensure_future(listener(port, expected))
    await asyncio.sleep(0.05)
    sender = SimpleSender(link_delay=lambda addr: (lambda: 0.2))
    t0 = time.perf_counter()
    await sender.send(("127.0.0.1", port), expected)
    await asyncio.wait_for(listen, timeout=2.0)
    elapsed = time.perf_counter() - t0
    assert elapsed >= 0.19, f"frame arrived after only {elapsed*1e3:.0f} ms"
    sender.close()


@async_test
async def test_reliable_sender_ack_sees_full_rtt():
    from hotstuff_tpu.network import ReliableSender

    port = fresh_base_port()
    listen = asyncio.ensure_future(listener(port))
    await asyncio.sleep(0.05)
    sender = ReliableSender(link_delay=lambda addr: (lambda: 0.1))
    t0 = time.perf_counter()
    handle = await sender.send(("127.0.0.1", port), b"ping")
    ack = await asyncio.wait_for(handle, timeout=3.0)
    rtt = time.perf_counter() - t0
    assert ack  # listener replies Ack
    # outbound leg (100 ms) + return leg (100 ms)
    assert rtt >= 0.19, f"ACK resolved after only {rtt*1e3:.0f} ms"
    sender.close()
    listen.cancel()


def test_local_bench_writes_spec(tmp_path, monkeypatch):
    import benchmark.utils as bu
    from benchmark.local import LocalBench

    monkeypatch.setattr(bu.PathMaker, "base_path", staticmethod(lambda: str(tmp_path)))
    bench = LocalBench(nodes=6, wan=True)
    bench._config()
    with open(bench._wan_spec_path()) as f:
        spec = json.load(f)
    assert len(spec["regions"]) == 6
    assert spec["matrix_one_way_ms"]
