"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
(`shard_map` over a Mesh) is exercised without TPU hardware. The axon
sitecustomize registers the real-TPU backend into every interpreter and
programs `jax_platforms="axon,cpu"`, so env vars alone don't stick — we
override through jax.config before any backend is touched. Real-TPU runs
go through bench.py, which leaves the platform alone.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: XLA_FLAGS --xla_force_host_platform_device_count above
    # already provides the 8-device CPU mesh
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: full-scenario committee runs excluded from the tier-1 "
        "sweep (-m 'not slow')",
    )
