"""Verify-pipeline profiler tests (ISSUE 4).

Covers the tentpole pieces — the span recorder (off-by-default
zero-allocation contract, nesting, ring bound, metric/journal fan-out),
the ``python -m benchmark profile`` waterfall math and SUMMARY
rendering, the journal ``"u"`` duration wire field and its Perfetto
"verify pipeline" track — plus the perf regression gate
(scripts/perfgate.py) and the tier-1 overhead bound: profiling disabled
must cost <2% of a real QC claim wave.
"""

from __future__ import annotations

import asyncio
import importlib.util
import json
import os
import time

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry import spans
from hotstuff_tpu.telemetry.journal import Journal

from .common import async_test, committee, fresh_base_port, keys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_profiler(monkeypatch):
    """Profiler/telemetry state is process-global: every test starts
    disabled with the env check re-armed, and leaves it that way."""
    monkeypatch.delenv("HOTSTUFF_TELEMETRY", raising=False)
    monkeypatch.delenv("HOTSTUFF_PROFILE", raising=False)
    monkeypatch.delenv("HOTSTUFF_FORCE_DEVICE_ROUTE", raising=False)
    telemetry.reset()
    spans.disable()
    yield
    telemetry.reset()
    spans.disable()


# ---- span recorder ------------------------------------------------------


def test_disabled_is_shared_noop():
    """Off by default: no recorder, and span() hands every call site the
    SAME no-op context manager — zero allocation on the hot path."""
    assert spans.recorder() is None
    assert not spans.enabled()
    assert spans.span("prepare") is spans.span("dispatch")
    with spans.span("prepare"):
        pass  # and it is a usable (reentrant) context manager


def test_env_knob(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_PROFILE", "1")
    spans.disable()  # re-arm the one-time env check
    assert spans.recorder() is not None
    monkeypatch.setenv("HOTSTUFF_PROFILE", "off")
    spans.disable()
    assert spans.recorder() is None


def test_nesting_depth_and_order():
    rec = spans.enable()
    with spans.span("e2e"):
        with spans.span("prepare"):
            pass
        with spans.span("dispatch"):
            pass
    rows = rec.drain()
    # children append on exit, so they precede their parent in the ring
    names = [r[0] for r in rows]
    assert names == ["prepare", "dispatch", "e2e"]
    depths = {r[0]: r[3] for r in rows}
    assert depths == {"e2e": 0, "prepare": 1, "dispatch": 1}
    assert all(r[2] >= 0 for r in rows)  # durations are non-negative ns


def test_ring_bound_and_stats():
    rec = spans.SpanRecorder(capacity=4)
    for i in range(10):
        rec.add("flatten", 0, i)
    assert len(rec.snapshot()) == 4
    # the ring keeps the NEWEST spans (flight recorder, not archive)
    assert [r[2] for r in rec.snapshot()] == [6, 7, 8, 9]
    st = rec.stats()
    assert st["spans"] == 10 and st["dropped"] == 6 and st["capacity"] == 4
    rec.drain()
    assert rec.stats()["buffered"] == 0


def test_metrics_fanout():
    """With telemetry on, completed spans feed the per-stage
    verify_stage_ms histogram."""
    telemetry.enable()
    spans.enable()
    with spans.span("device.execute"):
        time.sleep(0.001)
    text = telemetry.registry().render_prometheus()
    assert "verify_stage_ms" in text
    assert 'stage="device.execute"' in text


def test_journal_u_roundtrip_and_trace_track(tmp_path):
    """Span records land in the journal with the ``"u"`` duration field
    and render as the per-node tid=1 'verify pipeline' Perfetto track."""
    from benchmark.traces import TraceSet, load_journals

    journal = Journal("nodeA", str(tmp_path), buffer_records=1)
    spans.enable()
    spans.attach_journal(journal)
    with spans.span("dispatch"):
        time.sleep(0.0005)
    journal.close()

    journals = load_journals(str(tmp_path))
    recs = [r for r in journals["nodeA"] if r["e"] == "span"]
    assert len(recs) == 1
    assert recs[0]["p"] == "dispatch"
    assert recs[0]["u"] >= 500_000  # the slept 0.5 ms, in ns

    ts = TraceSet.load(str(tmp_path))
    assert ts.verify_spans["nodeA"]
    assert "Verify-pipeline spans journaled: 1" in ts.summary()
    doc = ts.chrome_trace()
    slices = [e for e in doc["traceEvents"] if e.get("cat") == "verify"]
    assert len(slices) == 1
    assert slices[0]["name"] == "dispatch" and slices[0]["tid"] == 1
    tracks = [
        e
        for e in doc["traceEvents"]
        if e.get("name") == "thread_name"
        and e["args"]["name"] == "verify pipeline"
    ]
    assert len(tracks) == 1


def test_attach_journal_first_wins(tmp_path):
    j1 = Journal("n1", str(tmp_path / "a"), buffer_records=1)
    j2 = Journal("n2", str(tmp_path / "b"), buffer_records=1)
    spans.enable()
    spans.attach_journal(j1)
    spans.attach_journal(j2)  # ignored: spans are process-wide
    with spans.span("flatten"):
        pass
    j1.close()
    j2.close()
    assert j1.records_total == 1
    assert j2.records_total == 0


def test_journal_sink_failure_is_swallowed():
    class Exploding:
        def record(self, *a, **kw):
            raise RuntimeError("disk full")

    rec = spans.enable()
    spans.attach_journal(Exploding())
    with spans.span("prepare"):
        pass  # must not raise
    assert rec.stats()["spans"] == 1


# ---- waterfall math / SUMMARY rendering ---------------------------------


def _rows(name, durs_ms):
    return [(name, 0, int(d * 1e6), 0, "t") for d in durs_ms]


def test_waterfall_coverage_and_multifire():
    from benchmark.profile import waterfall

    e2e = [10.0, 10.0, 10.0, 10.0]
    rows = (
        _rows("prepare", [4.0] * 4)
        + _rows("device.execute", [5.0] * 4)
        # multi-fire: 2 dispatch spans per wave must charge 2 x p50
        + _rows("dispatch", [0.5] * 8)
        # parent frame: reported, never summed into coverage
        + _rows("e2e", [10.0] * 4)
    )
    res = waterfall(rows, e2e)
    assert res["e2e_ms"]["p50"] == 10.0
    assert res["waves"] == 4
    assert res["stages"]["prepare"]["pct_of_e2e"] == 40.0
    assert res["stages"]["dispatch"]["pct_of_e2e"] == 10.0
    assert res["stages"]["dispatch"]["count"] == 8
    assert res["stages"]["e2e"]["p50_ms"] == 10.0
    assert res["coverage_pct"] == pytest.approx(100.0, abs=0.1)


def test_waterfall_empty_is_safe():
    from benchmark.profile import waterfall

    res = waterfall([], [])
    assert res["coverage_pct"] == 0.0
    assert res["e2e_ms"]["p50"] == 0.0


def test_format_waterfall_summary():
    from benchmark.profile import format_waterfall, waterfall

    res = {
        "verifier": "tpu",
        "route": "device",
        "waves": 4,
        "sizes": {
            256: waterfall(
                _rows("prepare", [4.0] * 4) + _rows("e2e", [10.0] * 4),
                [10.0] * 4,
            )
        },
    }
    text = format_waterfall(res)
    assert "PROFILE SUMMARY" in text
    assert "QC size 256" in text
    assert "prepare" in text and "(frame)" in text
    assert "coverage:" in text


# ---- perf regression gate (scripts/perfgate.py) -------------------------


def _perfgate():
    spec = importlib.util.spec_from_file_location(
        "perfgate", os.path.join(REPO, "scripts", "perfgate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perfgate_last_json_line():
    pg = _perfgate()
    text = 'WARNING: jax\n{"broken": \n{"value": 5}\ntrailing noise'
    assert pg.last_json_line(text) == {"value": 5}
    assert pg.last_json_line("no json here") is None


def test_perfgate_compare_directions():
    pg = _perfgate()
    ref = {"value": 100_000, "qc_verify_ms": {"256": {"rig_p50_ms": 90.0}}}
    ok = {"value": 95_000, "qc_verify_ms": {"256": {"rig_p50_ms": 100.0}}}
    assert pg.compare(ok, ref) == []
    slow = {"value": 100_000, "qc_verify_ms": {"256": {"rig_p50_ms": 120.0}}}
    fails = pg.compare(slow, ref)
    assert len(fails) == 1 and "rig_p50_ms" in fails[0]
    weak = {"value": 50_000, "qc_verify_ms": {"256": {"rig_p50_ms": 90.0}}}
    fails = pg.compare(weak, ref)
    assert len(fails) == 1 and "fell" in fails[0]
    # a metric missing on either side is skipped, not failed
    assert pg.compare({"value": 100_000}, ref) == []
    # threshold is tunable
    assert pg.compare(slow, ref, threshold=0.5) == []


def test_perfgate_load_reference_prefers_latest(tmp_path):
    pg = _perfgate()
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": {"value": 1}})
    )
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"tail": 'noise\n{"value": 2}'})
    )
    doc, path = pg.load_reference(str(tmp_path))
    assert doc["value"] == 2 and path.endswith("BENCH_r02.json")
    # no usable artifacts -> None (gate becomes a no-op, not a failure)
    assert pg.load_reference(str(tmp_path / "empty")) is None


def test_perfgate_pipeline_throughput_guard():
    """The ISSUE 5 guard: sustained wave-train throughput may not fall
    >15%; a reference that predates the ``pipeline`` block is skipped."""
    pg = _perfgate()
    ref = {"pipeline": {"train_sigs_per_s": 100_000}}
    assert pg.compare({"pipeline": {"train_sigs_per_s": 99_000}}, ref) == []
    assert pg.compare({"pipeline": {"train_sigs_per_s": 140_000}}, ref) == []
    fails = pg.compare({"pipeline": {"train_sigs_per_s": 60_000}}, ref)
    assert len(fails) == 1 and "train_sigs_per_s" in fails[0]
    assert "fell" in fails[0]
    # old reference without the block -> skipped, not failed
    assert pg.compare({"pipeline": {"train_sigs_per_s": 60_000}}, {}) == []
    assert pg.compare({}, ref) == []


def test_perfgate_tunnel_is_ratcheted_not_guarded():
    """ISSUE 6: the tunnel dispatch cost left the relative-regression
    GUARDS table and became a series-best ratchet — compare() must not
    gate it at all (a fresh value way above the latest reference's is
    compare-clean; the ratchet owns it)."""
    pg = _perfgate()
    assert all(
        "tunnel" not in name for name, *_ in pg.GUARDS
    )
    ref = {"tunnel_dispatch_p50_ms": 0.7}
    assert pg.compare({"tunnel_dispatch_p50_ms": 10.0}, ref) == []


def test_perfgate_ratchet_against_series_best(tmp_path):
    """load_best scans the WHOLE BENCH series for the lowest tunnel
    dispatch cost (one good round permanently raises the bar), and
    ratchet_check fails a fresh value past best x slack."""
    pg = _perfgate()
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": {"tunnel_dispatch_p50_ms": 4.5}})
    )
    # the series BEST is not the latest round — the ratchet must find it
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"tail": 'noise\n{"tunnel_dispatch_p50_ms": 0.8}'})
    )
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"parsed": {"tunnel_dispatch_p50_ms": 113.18}})
    )
    best = pg.load_best(str(tmp_path))
    assert best is not None
    best_val, best_path = best
    assert best_val == 0.8 and best_path.endswith("BENCH_r02.json")
    # within slack (0.8 x 1.25 = 1.0) passes
    assert pg.ratchet_check({"tunnel_dispatch_p50_ms": 0.95}, best) == []
    # past it fails, naming the metric and the source round
    fails = pg.ratchet_check({"tunnel_dispatch_p50_ms": 1.2}, best)
    assert len(fails) == 1
    assert "tunnel_dispatch_p50_ms" in fails[0]
    assert "BENCH_r02.json" in fails[0]
    # slack is tunable; missing on either side skips
    assert pg.ratchet_check(
        {"tunnel_dispatch_p50_ms": 1.2}, best, slack=2.0
    ) == []
    assert pg.ratchet_check({}, best) == []
    assert pg.ratchet_check({"tunnel_dispatch_p50_ms": 1.2}, None) == []
    # a series with no tunnel metric has no ratchet floor
    assert pg.load_best(str(tmp_path / "empty")) is None


def test_perfgate_repo_reference_exists():
    """The committed BENCH_r*.json artifacts must keep satisfying the
    gate's reference contract."""
    pg = _perfgate()
    ref = pg.load_reference()
    assert ref is not None
    doc, _ = ref
    assert doc["qc_verify_ms"]["256"]["rig_p50_ms"] > 0


# ---- wave-train mode (ISSUE 5) ------------------------------------------


def test_make_train_claims_distinct_digests_one_committee():
    """Every wave carries a DISTINCT digest (defeats the service's
    cross-wave claim dedup) signed by the SAME committee (keeps the
    device-resident key cache hot across the train)."""
    from benchmark.profile import make_train_claims

    claims, pks = make_train_claims(4, waves=3)
    assert len(claims) == 3 and len(pks) == 4
    digests = [c[1] for c in claims]
    assert len(set(digests)) == 3
    for kind, _digest, votes in claims:
        assert kind == "shared" and len(votes) == 4
        assert [pk for pk, _sig in votes] == pks
    # and the claims are genuinely valid QC-shaped work
    from hotstuff_tpu.crypto.async_service import eval_claims_sync
    from hotstuff_tpu.crypto.service import CpuVerifier

    assert eval_claims_sync(CpuVerifier(), claims) == [True] * 3


def test_format_train_summary():
    from benchmark.profile import format_train

    result = {
        "verifier": "tpu",
        "qc_size": 256,
        "train_waves": 8,
        "reps": 3,
        "depths": {
            1: {
                "single_wave_p50_ms": 2.0,
                "train_p50_ms": 16.0,
                "amortized_wave_ms": 2.0,
                "peak_inflight": 1,
                "train_sigs_per_s": 128_000.0,
            },
            2: {
                "single_wave_p50_ms": 2.0,
                "train_p50_ms": 12.0,
                "amortized_wave_ms": 1.5,
                "peak_inflight": 2,
                "train_sigs_per_s": 170_000.0,
            },
        },
        "overlap_speedup": 1.33,
        "overlap_efficiency_pct": 25.0,
    }
    text = format_train(result)
    assert "sustained verify wave-train" in text
    assert "QC size 256" in text and "8 waves/train" in text
    assert "1.33x depth-1" in text
    assert "25.0% of the per-wave round trip hidden" in text


# ---- overhead bound (tier-1 acceptance) ---------------------------------


def test_disabled_overhead_under_2pct():
    """Profiling disabled must cost <2% of a 1k-claim wave: the pipeline
    makes at most ~32 span()/recorder() probes per wave, so 32x the
    per-probe disabled cost must sit under 2% of a real wave's time."""
    from benchmark.profile import make_qc_claim
    from hotstuff_tpu.crypto.async_service import eval_claims_sync
    from hotstuff_tpu.crypto.service import CpuVerifier

    assert spans.recorder() is None  # profiling off

    claim, _pks = make_qc_claim(256)
    backend = CpuVerifier()
    assert eval_claims_sync(backend, [claim]) == [True]  # warm
    t0 = time.perf_counter()
    assert eval_claims_sync(backend, [claim]) == [True]
    wave_s = time.perf_counter() - t0

    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        spans.span("prepare")
        spans.recorder()
    per_probe_s = (time.perf_counter() - t0) / n

    budget = 0.02 * wave_s
    assert 32 * per_probe_s < budget, (
        f"32 disabled probes cost {32 * per_probe_s * 1e6:.1f} us, "
        f"budget {budget * 1e6:.1f} us (wave {wave_s * 1e3:.2f} ms)"
    )


# ---- enabled end-to-end: committee still commits (slow tier) ------------


@pytest.mark.slow
@async_test
async def test_profiled_committee_still_commits(tmp_path):
    """With the profiler AND journaling on, a 4-node committee keeps
    committing, and the merged trace carries BOTH consensus round slices
    and the verify-pipeline track on one timeline (ISSUE 4 acceptance)."""
    from benchmark.profile import make_qc_claim
    from benchmark.traces import TraceSet
    from hotstuff_tpu.consensus import Consensus, Parameters
    from hotstuff_tpu.crypto import Digest, SignatureService
    from hotstuff_tpu.crypto.async_service import AsyncVerifyService
    from hotstuff_tpu.crypto.service import CpuVerifier
    from hotstuff_tpu.store import Store

    telemetry.enable()
    spans.enable()
    jdir = str(tmp_path / "journals")
    base = fresh_base_port()
    com = committee(base)
    nodes = []
    for i in range(4):
        name, secret = keys()[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        tel = telemetry.for_node(str(name)[:8])
        journal = Journal(str(name)[:8], jdir, buffer_records=8)
        tel.attach_journal(journal)
        if i == 0:  # the process-wide span track pins to the first node
            spans.attach_journal(journal)
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=1_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
            telemetry=tel,
        )
        nodes.append((stack, commit_q, store, journal))

    async def feed():
        while True:
            digest = Digest.random()
            for stack, _, _, _ in nodes:
                await stack.tx_producer.put(digest)
            await asyncio.sleep(0.02)

    feeder = asyncio.ensure_future(feed())
    try:
        # drive one claim wave through the production dispatch path
        # while the committee runs, so verify spans land in the journal
        svc = AsyncVerifyService(CpuVerifier())
        assert (await svc.verify_claims([make_qc_claim(8)[0]])) == [True]
        for _, commit_q, _, _ in nodes:
            for _ in range(2):
                await asyncio.wait_for(commit_q.get(), timeout=20.0)
    finally:
        feeder.cancel()
        for stack, _, store, journal in nodes:
            await stack.shutdown()
            journal.close()
            store.close()

    ts = TraceSet.load(jdir)
    assert len(ts.committed()) >= 2
    assert ts.verify_spans  # span records survived the merge
    assert "Verify-pipeline spans journaled" in ts.summary()
    doc = ts.chrome_trace()
    cats = {e.get("cat") for e in doc["traceEvents"]}
    assert "block" in cats and "verify" in cats
    verify_stages = {
        e["name"] for e in doc["traceEvents"] if e.get("cat") == "verify"
    }
    # the CPU-inline wave's pipeline stages are on the track, on tid 1
    assert {"flatten", "host.verify"} <= verify_stages
    assert all(
        e["tid"] == 1
        for e in doc["traceEvents"]
        if e.get("cat") == "verify"
    )
