"""Mesh-sharded verifier tests on the virtual 8-device CPU mesh
(conftest sets --xla_force_host_platform_device_count=8).
"""

import numpy as np

import jax

from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
from hotstuff_tpu.parallel import ShardedBatchVerifier, default_mesh


def _batch(n, tamper=()):
    msgs, pks, sigs = [], [], []
    for i in range(n):
        pk, sk = generate_keypair(b"\x09" * 32, i)
        d = Digest.of(f"payload {i}".encode())
        sig = Signature.new(d, sk)
        data = bytearray(sig.to_bytes())
        if i in tamper:
            data[0] ^= 0xFF
        msgs.append(d.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(bytes(data))
    return msgs, pks, sigs


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_verify_matches_expected():
    verifier = ShardedBatchVerifier(default_mesh(), min_device_batch=0)
    msgs, pks, sigs = _batch(19, tamper={3, 11})
    out = verifier.verify(msgs, pks, sigs)
    expected = np.array([i not in {3, 11} for i in range(19)])
    assert (out == expected).all()


def test_sharded_qc_check_scalar():
    from hotstuff_tpu.parallel import make_sharded_qc_check
    from hotstuff_tpu.tpu import curve, field as F
    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    # reuse the base verifier's host prep by verifying through a sharded
    # instance, then cross-check the scalar all-valid kernel
    mesh = default_mesh()
    check = make_sharded_qc_check(mesh)
    verifier = ShardedBatchVerifier(mesh, min_device_batch=0)

    msgs, pks, sigs = _batch(8)
    ok = verifier.verify(msgs, pks, sigs)
    assert ok.all()

    msgs, pks, sigs = _batch(8, tamper={5})
    ok = verifier.verify(msgs, pks, sigs)
    assert not ok[5] and ok.sum() == 7


def test_sharded_verifier_as_consensus_backend():
    """The sharded verifier satisfies the VerifierBackend protocol used by
    the consensus aggregator/QC verify."""
    from tests.common import chain, committee, qc_for_block

    verifier = ShardedBatchVerifier(default_mesh(), min_device_batch=0)
    block = chain(1)[0]
    qc = qc_for_block(block)
    qc.verify(committee(9_300), verifier)  # should not raise


def test_mesh_pallas_branch_selection():
    """Fast structural check: TPU meshes select the per-shard Pallas
    branch, CPU meshes the XLA branch; pad grids are lane-aligned for
    pallas (the production routing contract, no kernel execution)."""
    mesh = default_mesh()
    v = ShardedBatchVerifier(mesh, min_device_batch=0)
    assert v._shard_pallas == (mesh.devices.flat[0].platform == "tpu")
    if not v._shard_pallas:  # CPU test mesh: powers of two from one
        # row per device to 8192 (ISSUE 7 — every wave bucket, incl.
        # the 4096 train bucket, is its own kernel shape)
        assert v.pad_sizes == tuple(8 * 2**j for j in range(11))


def test_mesh_pallas_interpret_256_votes():
    """VERDICT r2 item 7: the EXACT production multi-chip route —
    shard_map + per-shard fused Pallas + psum — at the 256-vote QC
    shape on the 8-device CPU mesh, Pallas in interpret mode (~40 s;
    the round-3 diagonal-collapse rewrite made interpret cheap enough
    to keep this always-on)."""
    import jax.numpy as jnp

    from hotstuff_tpu.parallel.mesh import make_sharded_verify
    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    n = 256
    msgs, pks, sigs = _batch(n, tamper={7, 130, 255})
    # host prep via the plain verifier, padded to 8 x 128 lanes
    prep = BatchVerifier(min_device_batch=0, use_pallas=False)
    prep.pad_sizes = (1024,)  # 128 lanes per device
    valid_host, arrays = prep.prepare(msgs, pks, sigs)
    kernel = make_sharded_verify(default_mesh(), pallas=True, interpret=True)
    out = np.asarray(kernel(*(jnp.asarray(a) for a in arrays)))[:n]
    out = out & valid_host
    expected = np.array([i not in {7, 130, 255} for i in range(n)])
    assert (out == expected).all()
