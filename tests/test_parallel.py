"""Mesh-sharded verifier tests on the virtual 8-device CPU mesh
(conftest sets --xla_force_host_platform_device_count=8).
"""

import numpy as np

import jax

from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
from hotstuff_tpu.parallel import ShardedBatchVerifier, default_mesh


def _batch(n, tamper=()):
    msgs, pks, sigs = [], [], []
    for i in range(n):
        pk, sk = generate_keypair(b"\x09" * 32, i)
        d = Digest.of(f"payload {i}".encode())
        sig = Signature.new(d, sk)
        data = bytearray(sig.to_bytes())
        if i in tamper:
            data[0] ^= 0xFF
        msgs.append(d.to_bytes())
        pks.append(pk.to_bytes())
        sigs.append(bytes(data))
    return msgs, pks, sigs


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_verify_matches_expected():
    verifier = ShardedBatchVerifier(default_mesh(), min_device_batch=0)
    msgs, pks, sigs = _batch(19, tamper={3, 11})
    out = verifier.verify(msgs, pks, sigs)
    expected = np.array([i not in {3, 11} for i in range(19)])
    assert (out == expected).all()


def test_sharded_qc_check_scalar():
    from hotstuff_tpu.parallel import make_sharded_qc_check
    from hotstuff_tpu.tpu import curve, field as F
    from hotstuff_tpu.tpu.ed25519 import BatchVerifier

    # reuse the base verifier's host prep by verifying through a sharded
    # instance, then cross-check the scalar all-valid kernel
    mesh = default_mesh()
    check = make_sharded_qc_check(mesh)
    verifier = ShardedBatchVerifier(mesh, min_device_batch=0)

    msgs, pks, sigs = _batch(8)
    ok = verifier.verify(msgs, pks, sigs)
    assert ok.all()

    msgs, pks, sigs = _batch(8, tamper={5})
    ok = verifier.verify(msgs, pks, sigs)
    assert not ok[5] and ok.sum() == 7


def test_sharded_verifier_as_consensus_backend():
    """The sharded verifier satisfies the VerifierBackend protocol used by
    the consensus aggregator/QC verify."""
    from tests.common import chain, committee, qc_for_block

    verifier = ShardedBatchVerifier(default_mesh(), min_device_batch=0)
    block = chain(1)[0]
    qc = qc_for_block(block)
    qc.verify(committee(9_300), verifier)  # should not raise
