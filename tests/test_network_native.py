"""Native C++ transport tests (native/transport.cpp via network/native.py):
interop with the asyncio implementations in both directions, ACK replies,
best-effort drop semantics, and reconnect-on-next-send."""

import asyncio

import pytest

from hotstuff_tpu.network.framing import read_frame, send_frame
from hotstuff_tpu.network.receiver import Receiver
from hotstuff_tpu.network.simple_sender import SimpleSender

from .common import async_test, fresh_base_port

native = pytest.importorskip("hotstuff_tpu.network.native")


class EchoHandler:
    """Records frames; ACKs each one (the consensus dispatch pattern)."""

    def __init__(self):
        self.frames: list[bytes] = []
        self.got = asyncio.Event()

    async def dispatch(self, writer, message: bytes) -> None:
        self.frames.append(message)
        self.got.set()
        await writer.send(b"Ack")


@pytest.fixture
def reactor():
    yield native.Reactor.shared()
    # each test leaves the process-wide reactor running; the router is
    # reset by receiver shutdown


@async_test
async def test_native_sender_to_asyncio_receiver(reactor):
    """NativeSimpleSender frames arrive intact at an asyncio Receiver."""
    port = fresh_base_port()
    handler = EchoHandler()
    recv = Receiver("127.0.0.1", port, handler)
    await recv.spawn()

    sender = native.NativeSimpleSender()
    await sender.send(("127.0.0.1", port), b"hello-from-native")
    await asyncio.wait_for(handler.got.wait(), timeout=5.0)
    assert handler.frames == [b"hello-from-native"]

    # persistent connection: a second send reuses it
    handler.got.clear()
    await sender.send(("127.0.0.1", port), b"second")
    await asyncio.wait_for(handler.got.wait(), timeout=5.0)
    assert handler.frames[-1] == b"second"
    sender.close()
    await recv.shutdown()


@async_test
async def test_asyncio_sender_to_native_receiver_with_ack(reactor):
    """SimpleSender -> NativeReceiver; the handler's ACK reply reaches
    the sending socket (the proposer back-pressure path shape)."""
    port = fresh_base_port()
    handler = EchoHandler()
    recv = native.NativeReceiver("127.0.0.1", port, handler)
    await recv.spawn()

    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    await send_frame(writer, b"ping-to-native")
    await asyncio.wait_for(handler.got.wait(), timeout=5.0)
    assert handler.frames == [b"ping-to-native"]
    ack = await asyncio.wait_for(read_frame(reader), timeout=5.0)
    assert ack == b"Ack"
    writer.close()
    await recv.shutdown()


@async_test
async def test_native_best_effort_drop_then_reconnect(reactor):
    """Frames to a down peer are dropped; the next send after the peer
    comes up establishes a fresh connection (simple_sender.rs parity)."""
    port = fresh_base_port()
    sender = native.NativeSimpleSender()
    # peer not listening: dropped silently
    await sender.send(("127.0.0.1", port), b"lost")
    await asyncio.sleep(0.3)

    handler = EchoHandler()
    recv = Receiver("127.0.0.1", port, handler)
    await recv.spawn()
    # retry loop: the reactor may need a send to trigger reconnection
    for _ in range(20):
        await sender.send(("127.0.0.1", port), b"after-reconnect")
        try:
            await asyncio.wait_for(handler.got.wait(), timeout=0.5)
            break
        except asyncio.TimeoutError:
            continue
    assert b"after-reconnect" in handler.frames
    assert b"lost" not in handler.frames
    sender.close()
    await recv.shutdown()


@async_test
async def test_native_receiver_native_sender_roundtrip(reactor):
    """Full native path: native sender -> native receiver -> ACK."""
    port = fresh_base_port()
    handler = EchoHandler()
    recv = native.NativeReceiver("127.0.0.1", port, handler)
    await recv.spawn()

    sender = native.NativeSimpleSender()
    payload = bytes(range(256)) * 64  # 16 KB binary frame
    await sender.send(("127.0.0.1", port), payload)
    await asyncio.wait_for(handler.got.wait(), timeout=5.0)
    assert handler.frames == [payload]
    sender.close()
    await recv.shutdown()


@async_test
async def test_native_many_frames_in_order(reactor):
    """Framing survives bursts: 200 frames arrive complete and in order."""
    port = fresh_base_port()
    handler = EchoHandler()
    recv = native.NativeReceiver("127.0.0.1", port, handler)
    await recv.spawn()

    sender = native.NativeSimpleSender()
    for i in range(200):
        await sender.send(("127.0.0.1", port), b"frame-%03d" % i)
    for _ in range(100):
        if len(handler.frames) >= 200:
            break
        await asyncio.sleep(0.05)
    assert handler.frames == [b"frame-%03d" % i for i in range(200)]
    sender.close()
    await recv.shutdown()


@async_test
async def test_native_reliable_sender_ack_future(reactor):
    """NativeReliableSender: the returned future resolves with the
    peer's ACK payload (FIFO pairing — reliable_sender.rs parity)."""
    port = fresh_base_port()
    handler = EchoHandler()
    recv = Receiver("127.0.0.1", port, handler)
    await recv.spawn()

    sender = native.NativeReliableSender()
    f1 = await sender.send(("127.0.0.1", port), b"first")
    f2 = await sender.send(("127.0.0.1", port), b"second")
    ack1 = await asyncio.wait_for(f1, timeout=5.0)
    ack2 = await asyncio.wait_for(f2, timeout=5.0)
    assert ack1 == b"Ack" and ack2 == b"Ack"
    assert handler.frames == [b"first", b"second"]
    sender.close()
    await recv.shutdown()


@async_test
async def test_native_reliable_retry_until_listener_up(reactor):
    """Send before the listener exists: the message is retransmitted
    with backoff and the ACK future eventually resolves (the reference's
    `retry` test, reliable_sender_tests.rs:50-67)."""
    port = fresh_base_port()
    sender = native.NativeReliableSender()
    fut = await sender.send(("127.0.0.1", port), b"early-bird")
    await asyncio.sleep(0.3)
    assert not fut.done()

    handler = EchoHandler()
    recv = Receiver("127.0.0.1", port, handler)
    await recv.spawn()
    ack = await asyncio.wait_for(fut, timeout=10.0)
    assert ack == b"Ack"
    assert handler.frames == [b"early-bird"]
    sender.close()
    await recv.shutdown()


@async_test
async def test_native_receiver_port_reusable_after_shutdown(reactor):
    """Listener close actually releases the port (regression: shutdown
    left the C++ listener accepting forever)."""
    port = fresh_base_port()
    recv1 = native.NativeReceiver("127.0.0.1", port, EchoHandler())
    await recv1.spawn()
    await recv1.shutdown()

    handler = EchoHandler()
    recv2 = native.NativeReceiver("127.0.0.1", port, handler)
    await recv2.spawn()  # would raise OSError if the port were stuck
    sender = native.NativeSimpleSender()
    await sender.send(("127.0.0.1", port), b"to-second-listener")
    await asyncio.wait_for(handler.got.wait(), timeout=5.0)
    assert handler.frames == [b"to-second-listener"]
    sender.close()
    await recv2.shutdown()


@async_test
async def test_flow_control_pauses_and_resumes_under_overload(reactor):
    """Watermarked read-pause flow control (round 4): a sender blasting
    far more frames than HIGH_WATER through a SLOW handler must neither
    lose frames nor stall forever — reads pause past the high-water
    mark (TCP backpressure reaches the sender) and resume below the
    low-water mark until everything is delivered."""

    class SlowHandler:
        def __init__(self):
            self.frames: list[bytes] = []
            self.done = asyncio.Event()

        async def dispatch(self, writer, message: bytes) -> None:
            await asyncio.sleep(0)  # yield: frames outpace dispatch
            self.frames.append(message)
            if len(self.frames) >= TOTAL:
                self.done.set()

    TOTAL = 900  # ~3.5x HIGH_WATER
    port = fresh_base_port()
    handler = SlowHandler()
    receiver = native.NativeReceiver("127.0.0.1", port, handler)
    await receiver.spawn()

    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    paused_seen = False
    for i in range(TOTAL):
        await send_frame(writer, i.to_bytes(4, "big") + b"x" * 200)
        if i % 64 == 0:
            await asyncio.sleep(0)  # let the bridge drain a little
            paused_seen = paused_seen or bool(receiver._paused)

    while not handler.done.is_set():
        paused_seen = paused_seen or bool(receiver._paused)
        await asyncio.wait([asyncio.ensure_future(handler.done.wait())],
                           timeout=0.01)
    assert len(handler.frames) == TOTAL
    # ordered, lossless delivery
    for i, frame in enumerate(handler.frames):
        assert int.from_bytes(frame[:4], "big") == i
    # the pause machinery actually ENGAGED (the queue crossed the
    # high-water mark) and fully released by the time the queue drained
    assert paused_seen
    assert not receiver._paused
    writer.close()
    await receiver.shutdown()
