"""Wire-codec robustness: random and mutated frames must never crash the
decoder — only ``SerializationError`` (or a clean decode) is acceptable.

The reference has no fuzzing at all (SURVEY §4 lists it as a gap); the
receiver dispatch feeds raw unauthenticated TCP frames into
``decode_message``, so "any byte string produces either a message or a
clean error" is a load-bearing property for liveness under garbage.
"""

from __future__ import annotations

import random

import pytest

from hotstuff_tpu.consensus.errors import SerializationError
from hotstuff_tpu.consensus.messages import MAX_BLOCK_PAYLOADS
from hotstuff_tpu.consensus.wire import (
    decode_message,
    encode_propose,
    encode_sync_request,
    encode_tc,
    encode_timeout,
    encode_vote,
)
from hotstuff_tpu.crypto import Digest, Signature

from .common import (
    chain,
    keys,
    qc_for_block,
    secret_for,
    signed_timeout,
    signed_vote,
)


def _decode_must_not_crash(data: bytes) -> None:
    try:
        decode_message(data)
    except SerializationError:
        pass  # the only acceptable failure mode


def test_random_frames_never_crash():
    rng = random.Random(0xF022)
    for _ in range(2_000):
        n = rng.randrange(0, 200)
        _decode_must_not_crash(rng.randbytes(n))


def test_tag_prefixed_random_frames_never_crash():
    """Valid tags followed by garbage exercise each decoder's depths."""
    rng = random.Random(0xF023)
    for tag in range(8):  # includes unknown tags
        for _ in range(500):
            body = rng.randbytes(rng.randrange(0, 400))
            _decode_must_not_crash(bytes([tag]) + body)


def test_mutated_valid_frames_never_crash():
    """Single-byte mutations and truncations of genuine messages — the
    most reachable malformed inputs for a Byzantine peer."""
    rng = random.Random(0xF024)
    blocks = chain(3)
    pk, sk = keys()[0]
    frames = [
        encode_propose(blocks[-1]),
        encode_vote(signed_vote(blocks[1], pk, sk)),
        encode_timeout(signed_timeout(qc_for_block(blocks[1]), 5, pk, sk)),
        encode_sync_request(Digest.of(b"missing"), pk),
    ]
    from hotstuff_tpu.consensus.messages import TC, timeout_digest
    from hotstuff_tpu.crypto import Signature

    tc = TC(
        round=5,
        votes=[
            (p, Signature.new(timeout_digest(5, 0), s), 0)
            for p, s in keys()[:3]
        ],
    )
    frames.append(encode_tc(tc))

    for frame in frames:
        decode_message(frame)  # sanity: the originals decode
        for _ in range(300):
            buf = bytearray(frame)
            pos = rng.randrange(len(buf))
            buf[pos] ^= 1 << rng.randrange(8)
            _decode_must_not_crash(bytes(buf))
        for cut in range(0, len(frame), max(1, len(frame) // 40)):
            _decode_must_not_crash(frame[:cut])
            _decode_must_not_crash(frame + frame[:cut])  # trailing junk


def test_length_field_extremes_never_crash_or_overallocate():
    """Huge declared counts/lengths must be rejected by caps, not
    attempted as allocations."""
    import struct

    # Propose frame claiming 2^32-1 payloads
    from hotstuff_tpu.utils.codec import Encoder

    enc = Encoder().u8(0)
    blocks = chain(2)
    blocks[-1].qc.encode(enc)
    enc.flag(False)
    from hotstuff_tpu.consensus.messages import encode_pk

    encode_pk(enc, blocks[-1].author)
    enc.u64(blocks[-1].round)
    enc.u32(0xFFFFFFFF)  # payload count
    _decode_must_not_crash(enc.finish())
    # vote whose pk length prefix is absurd
    frame = bytes([1]) + b"\x00" * 32 + struct.pack("<Q", 1) + struct.pack(
        "<I", 1 << 30
    )
    _decode_must_not_crash(frame)
    # block payload count just over the protocol cap decodes (the cap is
    # a VERIFY-time rule) or errors cleanly — never crashes
    assert MAX_BLOCK_PAYLOADS == 512


def test_adversarial_well_formed_frames_decode_then_fail_verify():
    """Frames an adversary-plane node actually emits (faults/adversary.py)
    are WELL-FORMED on the wire — they must decode cleanly and be killed
    by verification (``ConsensusError``), never by the codec and never by
    an unhandled crash.  This is a different threat than random mutation:
    every byte here is chosen by a protocol-aware attacker."""
    import time

    from hotstuff_tpu.consensus.errors import ConsensusError
    from hotstuff_tpu.crypto.service import CpuVerifier
    from hotstuff_tpu.faults.adversary import AdversaryPlane

    from .common import committee, signed_block

    base = 9_900
    com = committee(base)
    verifier = CpuVerifier()
    plane = AdversaryPlane(
        {
            "name": "byz-forge-qc",
            "seed": 7,
            "epoch_unix": time.time(),
            "nodes": {f"127.0.0.1:{base + i}": i for i in range(4)},
            "adversary": [{"policy": "forge-qc", "node": 0, "at": 0.0}],
        },
        ("127.0.0.1", base),
    )
    pairs = keys()
    blocks = chain(3)

    # 1. Forged QC smuggled inside an otherwise-genuine timeout: passes
    #    check_weight (real authors, quorum-many), decode round-trips,
    #    verification rejects the garbage signatures.
    forged = plane.forged_qc(com, blocks[1].round)
    forged.check_weight(com)
    pk, sk = pairs[0]
    frame = encode_timeout(signed_timeout(forged, 5, pk, sk))
    _, timeout = decode_message(frame)
    with pytest.raises(ConsensusError):
        timeout.verify(com, verifier)

    # 2. The forged QC as a block's parent certificate.
    author, secret = pairs[2 % 4]
    bad_block = signed_block(author, secret, 2, qc=forged)
    _, decoded = decode_message(encode_propose(bad_block))
    assert decoded.digest() == bad_block.digest()
    with pytest.raises(ConsensusError):
        decoded.verify(com, verifier)

    # 3. A vote whose signature was produced by a DIFFERENT committee
    #    member (signature spoofing a peer): structurally perfect, fails
    #    only on crypto.
    spoofed = signed_vote(blocks[1], pairs[1][0], pairs[2][1])
    _, vote = decode_message(encode_vote(spoofed))
    with pytest.raises(ConsensusError):
        vote.verify(com, verifier)

    # 4. The equivocating twin of a committed block, genuinely signed by
    #    its author: decodes AND verifies — only the safety rule (not the
    #    codec or crypto) can reject it, which is exactly why the
    #    invariant checker needs attribution.
    shadow = plane.shadow_block(blocks[1])
    shadow.signature = Signature.new(
        shadow.digest(), secret_for(shadow.author)
    )
    _, twin = decode_message(encode_propose(shadow))
    twin.verify(com, verifier)
    assert twin.digest() != blocks[1].digest()
    assert twin.round == blocks[1].round and twin.author == blocks[1].author

    # 5. Mutations of the adversarial frames still never crash the codec.
    rng = random.Random(0xF025)
    for f in (frame, encode_propose(bad_block), encode_vote(spoofed)):
        for _ in range(200):
            buf = bytearray(f)
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            _decode_must_not_crash(bytes(buf))


# ---------------------------------------------------------------------------
# decode-time count caps (ISSUE 12): the wire-decoder-bounds lint rule
# flagged the QC/TC vote-count and block payload-count reads as
# unbounded — a forged 4-byte count could size a decode loop before any
# truncation check fired.  The caps added for it must reject the count
# itself, before the first element decode or allocation.


def test_vote_count_bombs_die_in_the_codec():
    from hotstuff_tpu.consensus.messages import MAX_CERT_VOTES
    from hotstuff_tpu.utils.codec import Encoder

    # the cap matches the signer-bitmap member ceiling: no committee the
    # compact form can name could ever produce more votes
    assert MAX_CERT_VOTES == 4096

    # QC claiming cap+1 votes, inside a timeout frame (tag 2): rejected
    # on the count, not after 4097 attempted signature decodes
    bomb = Encoder()
    bomb.raw(Digest.of(b"bomb").to_bytes()).u64(7)
    bomb.u32(MAX_CERT_VOTES + 1)
    with pytest.raises(SerializationError, match="exceeds cap"):
        decode_message(bytes([2]) + bomb.finish())

    # exactly AT the cap the count is legal — the absent vote bytes then
    # die as ordinary truncation, a different failure
    at_cap = Encoder()
    at_cap.raw(Digest.of(b"bomb").to_bytes()).u64(7)
    at_cap.u32(MAX_CERT_VOTES)
    with pytest.raises(SerializationError) as exc:
        decode_message(bytes([2]) + at_cap.finish())
    assert "exceeds cap" not in str(exc.value)

    # TC (tag 3) claiming cap+1 votes: same rejection
    tc_bomb = Encoder().u64(9).u32(MAX_CERT_VOTES + 1)
    with pytest.raises(SerializationError, match="exceeds cap"):
        decode_message(bytes([3]) + tc_bomb.finish())


def test_block_payload_count_bomb_dies_in_the_codec():
    """The payload-count cap was verify-time only (core.py attribution);
    decode-time enforcement stops the forged count from sizing the
    digest-vector read at all."""
    from hotstuff_tpu.consensus.messages import encode_pk
    from hotstuff_tpu.utils.codec import Encoder

    blocks = chain(2)
    b = blocks[-1]
    for count in (MAX_BLOCK_PAYLOADS + 1, 0xFFFFFFFF):
        enc = Encoder().u8(0)  # TAG_PROPOSE
        b.qc.encode(enc)
        enc.flag(False)
        encode_pk(enc, b.author)
        enc.u64(b.round)
        enc.u32(count)
        with pytest.raises(SerializationError, match="exceeds cap"):
            decode_message(enc.finish())


def test_capped_decoder_truncation_sweep():
    """A propose frame carrying a real payload vector: the frame
    decodes whole, every strict prefix dies cleanly, and a count at the
    protocol cap round-trips (the cap rejects forgeries, not the
    protocol's own maximum)."""
    import dataclasses

    blocks = chain(2)
    payloads = tuple(
        Digest.of(bytes([i % 256]) * 8) for i in range(64)
    )
    b = dataclasses.replace(blocks[-1], payloads=payloads)
    frame = encode_propose(b)
    _, decoded = decode_message(frame)
    assert decoded.payloads == payloads

    for cut in range(len(frame)):
        _decode_must_not_crash(frame[:cut])

    full = dataclasses.replace(
        blocks[-1],
        payloads=tuple(
            Digest.of(i.to_bytes(4, "little"))
            for i in range(MAX_BLOCK_PAYLOADS)
        ),
    )
    _, rt = decode_message(encode_propose(full))
    assert len(rt.payloads) == MAX_BLOCK_PAYLOADS


# ---------------------------------------------------------------------------
# compact-certificate corpus (ISSUE 9): the aggregated QC/TC wire form
# is a NEW attack surface — a sentinel vote count, a version byte, one
# aggregate signature and a signer bitmap.  Malformed variants must die
# in the codec (SerializationError) or in verification (ConsensusError),
# never as an unhandled crash, and never be silently accepted.


def _bls_compact_fixture(n: int = 4):
    """(committee, sorted pks, quorum votes, compact QC) over one block
    digest, using small-scalar secrets (bench.py fixture idiom)."""
    from hotstuff_tpu.consensus.config import Committee
    from hotstuff_tpu.consensus.messages import QC, make_signer_bitmap
    from hotstuff_tpu.crypto import PublicKey
    from hotstuff_tpu.crypto.bls import BlsSecretKey, prove_possession
    from hotstuff_tpu.crypto.bls.curve import G1Point

    sks = [BlsSecretKey(i + 2) for i in range(n)]
    by_pk = {PublicKey(sk.public_key().to_bytes()): sk for sk in sks}
    com = Committee.new(
        [
            (pk, 1, ("127.0.0.1", 23_000 + i))
            for i, pk in enumerate(sorted(by_pk))
        ],
        scheme="bls",
        pops={pk: prove_possession(sk).to_bytes() for pk, sk in by_pk.items()},
    )
    pks = com.sorted_keys()
    digest = Digest.of(b"compact fuzz block")
    qc_probe = QC(hash=digest, round=9)
    msg = qc_probe.digest().to_bytes()
    quorum = com.quorum_threshold()
    votes = [
        (pk, Signature(by_pk[pk].sign(msg).to_bytes()))
        for pk in pks[:quorum]
    ]
    agg = G1Point.sum(
        [
            G1Point.from_bytes(sig.to_bytes(), subgroup_check=False)
            for _, sig in votes
        ]
    ).to_bytes()
    qc = QC(
        hash=digest,
        round=9,
        votes=[],
        agg_sig=Signature(agg),
        signers=make_signer_bitmap([pk for pk, _ in votes], pks),
    )
    return com, pks, votes, qc


def test_compact_qc_wire_corpus():
    """Truncations, bitmap/size mismatches, sub-quorum bitmaps and
    garbage aggregates: clean decode errors or verification rejections
    only."""
    from hotstuff_tpu.consensus.errors import (
        ConsensusError,
        QCRequiresQuorum,
    )
    from hotstuff_tpu.consensus.messages import (
        COMPACT_SENTINEL,
        MAX_SIGNER_BITMAP,
        QC,
        make_signer_bitmap,
    )
    from hotstuff_tpu.crypto.scheme import make_cpu_verifier
    from hotstuff_tpu.utils.codec import Encoder

    com, pks, votes, qc = _bls_compact_fixture()
    verifier = make_cpu_verifier("bls")

    # the genuine compact certificate round-trips under the pinned
    # decoder and verifies (inside a timeout frame — QCs never travel
    # bare)
    pk0 = pks[0]
    frame = bytes([2])  # TAG_TIMEOUT
    enc = Encoder()
    qc.encode(enc)
    from hotstuff_tpu.consensus.messages import encode_pk

    enc.u64(9)
    encode_pk(enc, pk0)
    enc.var_bytes(b"\x00" * 48)  # placeholder timeout signature
    frame += enc.finish()
    _, timeout = decode_message(frame, scheme="bls")
    assert timeout.high_qc.is_compact
    assert timeout.high_qc.wire_size() == qc.wire_size()
    timeout.high_qc.verify(com, verifier)  # must not raise

    # 1. truncated bitmap / truncated aggregate: every prefix of the
    #    compact frame dies cleanly in the codec
    for cut in range(len(frame)):
        try:
            decode_message(frame[:cut], scheme="bls")
        except SerializationError:
            pass

    # 2. aggregate-size mismatch: a 64-byte "aggregate" under the BLS
    #    scheme pin (48) is a codec error, not crypto's problem
    wrong = Encoder()
    wrong.raw(qc.hash.to_bytes()).u64(qc.round)
    wrong.u32(COMPACT_SENTINEL).u8(1)
    wrong.var_bytes(b"\x11" * 64)  # ed25519-sized blob
    wrong.var_bytes(qc.signers)
    bad_qc_wire = wrong.finish()
    tc_like = bytes([2]) + bad_qc_wire + frame[1 + qc.wire_size():]
    with pytest.raises(SerializationError):
        decode_message(tc_like, scheme="bls")

    # 3. bitmap above the decode cap dies in the codec
    huge = Encoder()
    huge.raw(qc.hash.to_bytes()).u64(qc.round)
    huge.u32(COMPACT_SENTINEL).u8(1)
    huge.var_bytes(qc.agg_sig.to_bytes())
    huge.var_bytes(b"\xff" * (MAX_SIGNER_BITMAP + 1))
    with pytest.raises(SerializationError):
        decode_message(
            bytes([2]) + huge.finish() + frame[1 + qc.wire_size():],
            scheme="bls",
        )

    # 4. sub-quorum bitmap: decodes fine (structure is legal), fails
    #    check_weight exactly like a sub-quorum vote list
    sub = QC(
        hash=qc.hash,
        round=qc.round,
        votes=[],
        agg_sig=qc.agg_sig,
        signers=make_signer_bitmap([pks[0]], pks),
    )
    with pytest.raises(QCRequiresQuorum):
        sub.check_weight(com)

    # 5. out-of-range signer bit: bit index beyond the committee takes
    #    the UnknownAuthority path in verification, never a crash
    oob = QC(
        hash=qc.hash,
        round=qc.round,
        votes=[],
        agg_sig=qc.agg_sig,
        signers=qc.signers[:-1] + bytes([qc.signers[-1] | 0xF0]),
    )
    with pytest.raises(ConsensusError):
        oob.check_weight(com)

    # 6. garbage aggregate over a valid quorum bitmap: decodes cleanly,
    #    MUST fail verify (the one-pairing check), not decode
    garbage = QC(
        hash=qc.hash,
        round=qc.round,
        votes=[],
        agg_sig=Signature(b"\x99" * 48),
        signers=qc.signers,
    )
    garbage.check_weight(com)  # structurally a quorum
    with pytest.raises(ConsensusError):
        garbage.verify(com, verifier)

    # 7. an ed25519-pinned decoder refuses ANY compact certificate —
    #    the scheme has no aggregate form, so the sentinel itself is
    #    malformed input
    with pytest.raises(SerializationError):
        decode_message(frame, scheme="ed25519")

    # 8. single-byte mutations of the genuine compact frame never crash
    rng = random.Random(0xF026)
    for _ in range(300):
        buf = bytearray(frame)
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        try:
            decode_message(bytes(buf), scheme="bls")
        except SerializationError:
            pass


def test_compact_tc_wire_corpus():
    """The compact TC's per-group form: group-count cap, per-group
    bitmap rules, and garbage aggregates failing verify not decode."""
    from hotstuff_tpu.consensus.errors import ConsensusError
    from hotstuff_tpu.consensus.messages import (
        MAX_COMPACT_GROUPS,
        TC,
        make_signer_bitmap,
        timeout_digest,
    )
    from hotstuff_tpu.crypto.bls import BlsSecretKey
    from hotstuff_tpu.crypto.bls.curve import G1Point
    from hotstuff_tpu.crypto.scheme import make_cpu_verifier

    com, pks, _, _ = _bls_compact_fixture()
    verifier = make_cpu_verifier("bls")
    by_pk = {}
    for i in range(len(pks)):
        sk = BlsSecretKey(i + 2)
        from hotstuff_tpu.crypto import PublicKey

        by_pk[PublicKey(sk.public_key().to_bytes())] = sk

    # genuine compact TC: quorum split across two high-qc-round groups
    def group(authors, hq):
        msg = timeout_digest(11, hq).to_bytes()
        sigs = [
            G1Point.from_bytes(
                by_pk[pk].sign(msg).to_bytes(), subgroup_check=False
            )
            for pk in authors
        ]
        return (
            hq,
            Signature(G1Point.sum(sigs).to_bytes()),
            make_signer_bitmap(authors, pks),
        )

    tc = TC(round=11, votes=[], groups=[group(pks[:2], 8), group(pks[2:3], 9)])
    frame = encode_tc(tc)
    _, decoded = decode_message(frame, scheme="bls")
    assert decoded.is_compact
    assert sorted(decoded.high_qc_rounds()) == [8, 8, 9]
    decoded.verify(com, verifier)  # must not raise

    # a node present in TWO groups is authority reuse
    dup = TC(round=11, votes=[], groups=[group(pks[:2], 8), group(pks[1:3], 9)])
    with pytest.raises(ConsensusError):
        dup.verify(com, verifier)

    # garbage aggregate in one group: decodes, fails verify
    g8, g9 = tc.groups
    forged = TC(
        round=11,
        votes=[],
        groups=[g8, (g9[0], Signature(b"\x42" * 48), g9[2])],
    )
    _, fdec = decode_message(encode_tc(forged), scheme="bls")
    with pytest.raises(ConsensusError):
        fdec.verify(com, verifier)

    # group count over the cap dies in the codec
    from hotstuff_tpu.consensus.messages import COMPACT_SENTINEL
    from hotstuff_tpu.utils.codec import Encoder

    enc = Encoder().u8(3)  # TAG_TC
    enc.u64(11).u32(COMPACT_SENTINEL).u8(1)
    enc.u8(MAX_COMPACT_GROUPS + 1)
    with pytest.raises(SerializationError):
        decode_message(enc.finish(), scheme="bls")

    # mutations of the genuine compact TC frame never crash
    rng = random.Random(0xF027)
    for _ in range(300):
        buf = bytearray(frame)
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        try:
            decode_message(bytes(buf), scheme="bls")
        except SerializationError:
            pass


# ---------------------------------------------------------------------------
# producer-frame-v2 / ingest-ACK corpus (ISSUE 10): the admission plane
# adds a versioned batched submission frame on the consensus port and a
# typed reply frame on the producer socket — both face unauthenticated
# clients, so the same "clean decode or clean error" property is
# load-bearing.


def _v2_frame(n: int = 5, body_size: int = 48) -> bytes:
    from hotstuff_tpu.consensus.wire import encode_producer_batch

    items = []
    for i in range(n):
        body = bytes([i]) * body_size
        items.append((Digest.of(body), body))
    return encode_producer_batch(items)


def test_producer_v2_round_trip():
    from hotstuff_tpu.consensus.wire import TAG_PRODUCER_V2

    frame = _v2_frame(7)
    tag, payload = decode_message(frame)
    assert tag == TAG_PRODUCER_V2
    assert len(payload) == 7
    for digest, body in payload:
        assert digest == Digest.of(body)
    # item order is preserved — the accepted-prefix admission contract
    # depends on it
    assert [b[0] for _, b in payload] == list(range(7))


def test_producer_v2_batch_bounds():
    from hotstuff_tpu.consensus.wire import (
        MAX_PRODUCER_BATCH,
        encode_producer_batch,
    )

    with pytest.raises(ValueError):
        encode_producer_batch([])
    d = Digest.of(b"x")
    with pytest.raises(ValueError):
        encode_producer_batch([(d, b"")] * (MAX_PRODUCER_BATCH + 1))
    # the cap itself encodes and round-trips
    frame = encode_producer_batch([(d, b"")] * MAX_PRODUCER_BATCH)
    _, payload = decode_message(frame)
    assert len(payload) == MAX_PRODUCER_BATCH


def test_producer_v2_wire_corpus():
    """Truncations, bad version byte, oversized declared count, and
    single-byte mutations: SerializationError or clean decode only."""
    from hotstuff_tpu.consensus.wire import (
        MAX_PRODUCER_BATCH,
        PRODUCER_FRAME_VERSION,
        TAG_PRODUCER_V2,
    )

    frame = _v2_frame(5)
    decode_message(frame)  # sanity: the original decodes

    # every truncation dies cleanly
    for cut in range(len(frame)):
        _decode_must_not_crash(frame[:cut])
    _decode_must_not_crash(frame + b"\x00")  # trailing junk

    # any version byte except the pinned one is malformed input
    for version in range(256):
        if version == PRODUCER_FRAME_VERSION:
            continue
        mutated = bytes([frame[0], version]) + frame[2:]
        with pytest.raises(SerializationError):
            decode_message(mutated)

    # declared count of 0 and counts past the batch cap die in the
    # codec, never as an allocation attempt
    import struct

    head = bytes([TAG_PRODUCER_V2, PRODUCER_FRAME_VERSION])
    for count in (0, MAX_PRODUCER_BATCH + 1, 0xFFFFFFFF):
        with pytest.raises(SerializationError):
            decode_message(head + struct.pack("<I", count))

    # a count larger than the items actually present dies cleanly
    inflated = head + struct.pack("<I", 9) + frame[6:]
    with pytest.raises(SerializationError):
        decode_message(inflated)

    rng = random.Random(0xF028)
    for _ in range(400):
        buf = bytearray(frame)
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        _decode_must_not_crash(bytes(buf))


def test_ingest_ack_round_trip_and_corpus():
    from hotstuff_tpu.consensus.wire import (
        INGEST_ACK_TAG,
        INGEST_BUSY,
        INGEST_OK,
        decode_ingest_ack,
        encode_ingest_ack,
    )

    # OK form: nothing shed, no retry hint
    ok = decode_ingest_ack(encode_ingest_ack(12, 0, 640, 0))
    assert ok is not None and not ok.busy and ok.status == INGEST_OK
    assert (ok.accepted, ok.shed, ok.credit, ok.retry_after_ms) == (
        12, 0, 640, 0,
    )
    # BUSY form: a nonzero shed flips the status
    busy = decode_ingest_ack(encode_ingest_ack(3, 9, 0, 250))
    assert busy is not None and busy.busy and busy.status == INGEST_BUSY
    assert (busy.accepted, busy.shed) == (3, 9)
    # encode clamps instead of wrapping
    big = decode_ingest_ack(encode_ingest_ack(1 << 40, -5, 0, 1 << 40))
    assert big.accepted == (1 << 32) - 1 and big.shed == 0

    # non-ACK frames are None, not errors: the legacy reply and
    # anything else that doesn't lead with the ACK tag
    assert decode_ingest_ack(b"Ack") is None
    assert decode_ingest_ack(b"") is None
    assert decode_ingest_ack(b"\x00\x01\x02") is None

    frame = encode_ingest_ack(3, 9, 64, 250)
    # bad version / bad status are malformed, not silently decoded
    with pytest.raises(SerializationError):
        decode_ingest_ack(bytes([INGEST_ACK_TAG, 99]) + frame[2:])
    with pytest.raises(SerializationError):
        decode_ingest_ack(frame[:2] + bytes([7]) + frame[3:])
    # truncations and trailing junk die cleanly
    for cut in range(1, len(frame)):
        with pytest.raises(SerializationError):
            decode_ingest_ack(frame[:cut])
    with pytest.raises(SerializationError):
        decode_ingest_ack(frame + b"\x00")

    # mutations: typed ACK, None, or SerializationError — never a crash
    rng = random.Random(0xF029)
    for _ in range(400):
        buf = bytearray(frame)
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        try:
            decode_ingest_ack(bytes(buf))
        except SerializationError:
            pass


# ---------------------------------------------------------------------------
# reconfiguration corpus (ISSUE 14): the epoch-change submission frame
# (TAG_RECONFIG) arrives on the unauthenticated consensus port, and the
# state-sync manifest v2 carries attacker-relayable certified schedule
# links — both get the same decode-time-cap treatment the wire-decoder-
# bounds lint demands: forged counts and sizes die on the count, never
# as an allocation or a crash.


def _reconfig_frame():
    from hotstuff_tpu.consensus.config import Committee
    from hotstuff_tpu.consensus.reconfig import ReconfigOp
    from hotstuff_tpu.consensus.wire import encode_reconfig

    pairs = keys()
    new = Committee.new(
        [
            (pk, 1, ("127.0.0.1", 24_000 + i))
            for i, (pk, _) in enumerate(pairs)
        ],
        epoch=2,
    )
    sponsor_pk, sponsor_sk = pairs[0]
    op = ReconfigOp(new_committee=new, margin=8, sponsor=sponsor_pk)
    op.signature = Signature.new(Digest(op.digest()), sponsor_sk)
    return encode_reconfig(op), op


def test_reconfig_frame_round_trip():
    from hotstuff_tpu.consensus.wire import TAG_RECONFIG

    frame, op = _reconfig_frame()
    tag, decoded = decode_message(frame)
    assert tag == TAG_RECONFIG
    assert decoded.margin == op.margin
    assert decoded.sponsor == op.sponsor
    assert decoded.signature == op.signature
    assert decoded.new_committee.epoch == 2
    assert decoded.digest() == op.digest()
    # the ed25519-pinned decoder accepts it too (all keys are ed25519)
    decode_message(frame, scheme="ed25519")


def test_reconfig_truncation_sweep():
    frame, _ = _reconfig_frame()
    decode_message(frame)  # sanity: the original decodes
    for cut in range(len(frame)):
        _decode_must_not_crash(frame[:cut])
    _decode_must_not_crash(frame + b"\x00")  # trailing junk
    _decode_must_not_crash(frame + frame)


def test_reconfig_bad_version_bytes():
    from hotstuff_tpu.consensus.reconfig import RECONFIG_OP_VERSION

    frame, _ = _reconfig_frame()
    # the op version byte sits right after the tag
    for version in range(256):
        if version == RECONFIG_OP_VERSION:
            continue
        with pytest.raises(SerializationError, match="version"):
            decode_message(bytes([frame[0], version]) + frame[2:])


def test_reconfig_member_count_bomb_dies_in_the_codec():
    from hotstuff_tpu.consensus.reconfig import (
        MAX_RECONFIG_MEMBERS,
        RECONFIG_OP_VERSION,
    )
    from hotstuff_tpu.consensus.wire import TAG_RECONFIG
    from hotstuff_tpu.utils.codec import Encoder

    # a forged count past the cap is rejected on the count itself,
    # before the first member decode
    bomb = Encoder().u8(TAG_RECONFIG).u8(RECONFIG_OP_VERSION)
    bomb.u64(2).var_bytes(b"ed25519").u16(MAX_RECONFIG_MEMBERS + 1)
    with pytest.raises(SerializationError, match="exceeds cap"):
        decode_message(bomb.finish())

    # exactly AT the cap the count is legal — the absent member bytes
    # then die as ordinary truncation, a different failure
    at_cap = Encoder().u8(TAG_RECONFIG).u8(RECONFIG_OP_VERSION)
    at_cap.u64(2).var_bytes(b"ed25519").u16(MAX_RECONFIG_MEMBERS)
    with pytest.raises(SerializationError) as exc:
        decode_message(at_cap.finish())
    assert "exceeds cap" not in str(exc.value)

    # oversized per-member fields (scheme, host, key) die on their own
    # var_bytes caps
    fat_scheme = Encoder().u8(TAG_RECONFIG).u8(RECONFIG_OP_VERSION)
    fat_scheme.u64(2).var_bytes(b"x" * 64)
    with pytest.raises(SerializationError):
        decode_message(fat_scheme.finish())


def test_reconfig_mutation_storm():
    rng = random.Random(0xF030)
    frame, _ = _reconfig_frame()
    for _ in range(500):
        buf = bytearray(frame)
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        _decode_must_not_crash(bytes(buf))
    # multi-byte storms too: up to 8 flips per frame
    for _ in range(200):
        buf = bytearray(frame)
        for _ in range(rng.randrange(2, 9)):
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        _decode_must_not_crash(bytes(buf))


def test_manifest_schedule_links_corpus():
    """Manifest v2's certified schedule links: round trip, the link-count
    cap, and the per-link byte cap — all enforced at decode time."""
    import struct

    from hotstuff_tpu.consensus.wire import (
        MAX_SCHEDULE_LINKS,
        TAG_STATE_MANIFEST,
        encode_state_manifest,
    )

    pk = keys()[0][0]
    qc = qc_for_block(chain(1)[0])
    links = [(b"block-bytes-%d" % i, b"qc-bytes-%d" % i) for i in range(3)]
    frame = encode_state_manifest(
        7, b"\x11" * 32, 42, 100, 2, 0, qc, pk, links=links
    )
    tag, manifest = decode_message(frame)
    assert tag == TAG_STATE_MANIFEST
    assert manifest.links == tuple(links)

    # the encoder refuses an over-cap link list outright
    with pytest.raises(ValueError, match="schedule links"):
        encode_state_manifest(
            7, b"\x11" * 32, 42, 100, 2, 0, qc, pk,
            links=[(b"b", b"q")] * (MAX_SCHEDULE_LINKS + 1),
        )

    # a forged on-wire count dies on the count (the u16 sits where the
    # empty-list frame ends)
    empty = encode_state_manifest(7, b"\x11" * 32, 42, 100, 2, 0, qc, pk)
    forged = empty[:-2] + struct.pack("<H", MAX_SCHEDULE_LINKS + 1)
    with pytest.raises(SerializationError, match="exceeds cap"):
        decode_message(forged)

    # a link element past the byte cap dies in var_bytes, not as an
    # allocation of attacker-chosen size
    from hotstuff_tpu.consensus.wire import MAX_SCHEDULE_LINK_BYTES

    fat = encode_state_manifest(
        7, b"\x11" * 32, 42, 100, 2, 0, qc, pk,
        links=[(b"\x00" * (MAX_SCHEDULE_LINK_BYTES + 1), b"q")],
    )
    with pytest.raises(SerializationError):
        decode_message(fat)

    # truncation sweep over the linked manifest (stride keeps it fast)
    for cut in range(0, len(frame), max(1, len(frame) // 60)):
        _decode_must_not_crash(frame[:cut])

    # mutations never crash
    rng = random.Random(0xF031)
    for _ in range(300):
        buf = bytearray(frame)
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
        _decode_must_not_crash(bytes(buf))

# ---------------------------------------------------------------------------
# zero-copy ingest differential harness (ISSUE 20): the native frame
# parser (native/wave_pack.cpp) and the Python Decoder must accept /
# reject BYTE-IDENTICAL corpora — a frame only the native side accepts
# would be mis-ingested past the codec's caps, and a frame only Python
# accepts would silently lose the fast path.  Every test here drives
# the SAME byte corpus through both and asserts zero divergence; the
# suite skips cleanly where the native toolchain is absent.


def _wave_native():
    from hotstuff_tpu.crypto import native_ed25519 as ne

    if not ne.wave_pack_available():
        pytest.skip("native wave packer unavailable")
    return ne


def _py_accepts_vote(frame: bytes) -> bool:
    from hotstuff_tpu.consensus.wire import TAG_VOTE

    try:
        tag, _ = decode_message(frame, scheme="ed25519")
    except SerializationError:
        return False
    return tag == TAG_VOTE


def _py_producer_items(frame: bytes):
    from hotstuff_tpu.consensus.wire import TAG_PRODUCER_V2

    try:
        tag, payload = decode_message(frame, scheme="ed25519")
    except SerializationError:
        return None
    if tag != TAG_PRODUCER_V2:
        return None
    return payload


def _raw_vote_frame(rng):
    """A wire-shaped ed25519 vote frame with random contents (decode
    never verifies signatures, so random bytes exercise the codec the
    same way real votes do) and the claim tuple ``Vote.claim()`` would
    produce for it."""
    import struct

    h = rng.randbytes(32)
    rnd = rng.randrange(1 << 63)
    pk = rng.randbytes(32)
    sig = rng.randbytes(64)
    frame = (
        bytes([1]) + h + struct.pack("<Q", rnd)
        + struct.pack("<I", 32) + pk
        + struct.pack("<I", 64) + sig
    )
    claim = (
        "one",
        Digest.of(h + struct.pack("<Q", rnd)).to_bytes(),
        pk,
        sig,
    )
    return frame, claim


def test_ingest_tag_constants_match_wire():
    """The receiver/service ingest taps hardcode wire tags (importing
    consensus.wire there would cycle) — pin them to the live values."""
    from hotstuff_tpu.consensus.wire import TAG_PRODUCER_V2, TAG_VOTE
    from hotstuff_tpu.crypto.async_service import INGEST_TAG_VOTE
    from hotstuff_tpu.network import receiver

    assert INGEST_TAG_VOTE == TAG_VOTE
    assert receiver._TAG_VOTE == TAG_VOTE
    assert receiver._TAG_PRODUCER_V2 == TAG_PRODUCER_V2


def test_native_vote_probe_matches_decoder():
    """Accept/reject parity on the vote corpus: real signed votes,
    every truncation, trailing junk, length-field bombs, and a
    mutation storm — zero divergence allowed."""
    import struct

    ne = _wave_native()
    rng = random.Random(0xF040)

    def check(frame: bytes):
        assert ne.probe_vote(frame) == _py_accepts_vote(frame), frame.hex()

    # a REAL signed vote (and the decoder sanity-checks it first)
    blocks = chain(3)
    pk, sk = keys()[0]
    real = encode_vote(signed_vote(blocks[1], pk, sk))
    assert _py_accepts_vote(real) and ne.probe_vote(real)

    # synthetic well-formed frames
    frames = [real] + [_raw_vote_frame(rng)[0] for _ in range(20)]
    for frame in frames[:4]:
        for cut in range(len(frame) + 1):
            check(frame[:cut])
        check(frame + b"\x00")
        check(frame + frame)
    # forged pk/sig length prefixes around the fixed sizes
    base = bytearray(frames[1])
    for off in (41, 77):
        for val in (0, 1, 31, 33, 48, 63, 65, 96, 1 << 16, 0xFFFFFFFF):
            buf = bytearray(base)
            buf[off : off + 4] = struct.pack("<I", val)
            check(bytes(buf))
    # mutation storm: single- and multi-byte flips
    for frame in frames:
        for _ in range(200):
            buf = bytearray(frame)
            for _ in range(rng.randrange(1, 4)):
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            check(bytes(buf))
    # random tag-1-prefixed garbage of assorted lengths
    for _ in range(500):
        check(b"\x01" + rng.randbytes(rng.randrange(0, 200)))


def test_native_pack_digest_matches_vote_claim():
    """The digest the native packer computes (single-block SHA-512 over
    hash||round) must equal ``Vote.claim()``'s — it becomes the claim
    key the arena adoption matches against."""
    ne = _wave_native()
    from hotstuff_tpu.crypto.async_service import make_pad_claim

    pad = make_pad_claim()
    packer = ne.WavePacker(16, 2)
    try:
        assert packer.set_pad(pad[1], pad[2], pad[3])
        blocks = chain(3)
        for i, (pk, sk) in enumerate(keys()[:3]):
            vote = signed_vote(blocks[1], pk, sk)
            res = packer.pack_vote(encode_vote(vote))
            assert not isinstance(res, int), res
            slot, digest = res
            assert slot == i
            assert digest == vote.claim()[1]
    finally:
        packer.close()


def test_native_producer_parse_matches_decoder():
    """Producer-v2 parity: on every corpus frame the native parser and
    the Python Decoder agree on accept/reject, and on acceptance the
    digest column and body spans reproduce the decoded items exactly."""
    import struct

    ne = _wave_native()
    from hotstuff_tpu.consensus.wire import (
        MAX_PRODUCER_BATCH,
        PRODUCER_FRAME_VERSION,
        TAG_PRODUCER_V2,
    )

    assert ne.MAX_PRODUCER_BATCH == MAX_PRODUCER_BATCH

    def check(frame: bytes):
        native = ne.parse_producer(frame)
        items = _py_producer_items(frame)
        if items is None:
            assert native is None, frame[:32].hex()
            return
        assert native is not None, frame[:32].hex()
        digests, spans = native
        assert len(spans) == len(items)
        for i, (digest, body) in enumerate(items):
            assert digests[i * 32 : (i + 1) * 32] == digest.to_bytes()
            off, ln = spans[i]
            assert frame[off : off + ln] == body

    rng = random.Random(0xF041)
    frames = [
        _v2_frame(1, body_size=0),
        _v2_frame(5),
        _v2_frame(16, body_size=1),
        _v2_frame(3, body_size=300),
    ]
    for frame in frames:
        check(frame)
        for cut in range(len(frame) + 1):
            check(frame[:cut])
        check(frame + b"\x00")
    # version bytes and count bombs
    frame = frames[1]
    for version in (0, 1, 3, 255):
        check(bytes([frame[0], version]) + frame[2:])
    head = bytes([TAG_PRODUCER_V2, PRODUCER_FRAME_VERSION])
    for count in (0, 1, MAX_PRODUCER_BATCH, MAX_PRODUCER_BATCH + 1,
                  0xFFFFFFFF):
        check(head + struct.pack("<I", count))
        check(head + struct.pack("<I", count) + frame[6:])
    # per-item length bombs around the body cap
    for ln in (0, 1, 65536, 65537, 0xFFFFFFFF):
        bomb = head + struct.pack("<I", 1) + b"\xaa" * 32
        bomb += struct.pack("<I", ln) + b"\xbb" * min(ln, 70_000)
        check(bomb)
    # mutation storm
    for frame in frames:
        for _ in range(300):
            buf = bytearray(frame)
            for _ in range(rng.randrange(1, 4)):
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            check(bytes(buf))
    # random tag-6-prefixed garbage
    for _ in range(500):
        check(bytes([TAG_PRODUCER_V2]) + rng.randbytes(rng.randrange(0, 300)))


def test_flatten_claims_vs_arena_columns_every_bucket():
    """Column parity at every wave bucket: the adopted arena's
    digest/pk/sig columns must be byte-identical to what
    ``flatten_claims`` produces for the same claims, with pad rows
    equal to the shared pad claim — the property that makes arena
    adoption a drop-in replacement for the flatten/prepare hop."""
    np = pytest.importorskip("numpy")
    _wave_native()
    from hotstuff_tpu.crypto.async_service import (
        DEFAULT_WAVE_BUCKETS,
        ZeroCopyIngest,
        flatten_claims,
        make_pad_claim,
    )

    rng = random.Random(0xF042)
    pad = make_pad_claim()
    ing = ZeroCopyIngest(capacity=DEFAULT_WAVE_BUCKETS[-1], ring_depth=3)
    for bucket in DEFAULT_WAVE_BUCKETS:
        for n in (bucket, max(1, bucket - 3)):
            pairs = [_raw_vote_frame(rng) for _ in range(n)]
            for frame, _ in pairs:
                assert ing.note_vote_frame(frame)
            claims = [c for _, c in pairs]
            wave = ing.try_adopt(claims, DEFAULT_WAVE_BUCKETS)
            assert wave is not None, (bucket, n)
            assert wave.n == n and wave.rows == bucket
            digests, pks, sigs, spans = flatten_claims(claims)
            assert spans == [(i, i + 1) for i in range(n)]
            dig_v = np.frombuffer(wave.dig, np.uint8).reshape(bucket, 32)
            pk_v = np.frombuffer(wave.pk, np.uint8).reshape(bucket, 32)
            sig_v = np.frombuffer(wave.sig, np.uint8).reshape(bucket, 64)
            for i in range(n):
                assert dig_v[i].tobytes() == digests[i]
                assert pk_v[i].tobytes() == pks[i]
                assert sig_v[i].tobytes() == sigs[i]
            for i in range(n, bucket):
                assert dig_v[i].tobytes() == pad[1]
                assert pk_v[i].tobytes() == pad[2]
                assert sig_v[i].tobytes() == pad[3]
            wave.release()
    counters = ing.counters()
    assert counters["zero_copy_waves"] == 2 * len(DEFAULT_WAVE_BUCKETS)
    assert counters["fallback_waves"] == 0
