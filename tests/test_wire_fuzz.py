"""Wire-codec robustness: random and mutated frames must never crash the
decoder — only ``SerializationError`` (or a clean decode) is acceptable.

The reference has no fuzzing at all (SURVEY §4 lists it as a gap); the
receiver dispatch feeds raw unauthenticated TCP frames into
``decode_message``, so "any byte string produces either a message or a
clean error" is a load-bearing property for liveness under garbage.
"""

from __future__ import annotations

import random

from hotstuff_tpu.consensus.errors import SerializationError
from hotstuff_tpu.consensus.messages import MAX_BLOCK_PAYLOADS
from hotstuff_tpu.consensus.wire import (
    decode_message,
    encode_propose,
    encode_sync_request,
    encode_tc,
    encode_timeout,
    encode_vote,
)
from hotstuff_tpu.crypto import Digest

from .common import chain, keys, qc_for_block, signed_timeout, signed_vote


def _decode_must_not_crash(data: bytes) -> None:
    try:
        decode_message(data)
    except SerializationError:
        pass  # the only acceptable failure mode


def test_random_frames_never_crash():
    rng = random.Random(0xF022)
    for _ in range(2_000):
        n = rng.randrange(0, 200)
        _decode_must_not_crash(rng.randbytes(n))


def test_tag_prefixed_random_frames_never_crash():
    """Valid tags followed by garbage exercise each decoder's depths."""
    rng = random.Random(0xF023)
    for tag in range(8):  # includes unknown tags
        for _ in range(500):
            body = rng.randbytes(rng.randrange(0, 400))
            _decode_must_not_crash(bytes([tag]) + body)


def test_mutated_valid_frames_never_crash():
    """Single-byte mutations and truncations of genuine messages — the
    most reachable malformed inputs for a Byzantine peer."""
    rng = random.Random(0xF024)
    blocks = chain(3)
    pk, sk = keys()[0]
    frames = [
        encode_propose(blocks[-1]),
        encode_vote(signed_vote(blocks[1], pk, sk)),
        encode_timeout(signed_timeout(qc_for_block(blocks[1]), 5, pk, sk)),
        encode_sync_request(Digest.of(b"missing"), pk),
    ]
    from hotstuff_tpu.consensus.messages import TC, timeout_digest
    from hotstuff_tpu.crypto import Signature

    tc = TC(
        round=5,
        votes=[
            (p, Signature.new(timeout_digest(5, 0), s), 0)
            for p, s in keys()[:3]
        ],
    )
    frames.append(encode_tc(tc))

    for frame in frames:
        decode_message(frame)  # sanity: the originals decode
        for _ in range(300):
            buf = bytearray(frame)
            pos = rng.randrange(len(buf))
            buf[pos] ^= 1 << rng.randrange(8)
            _decode_must_not_crash(bytes(buf))
        for cut in range(0, len(frame), max(1, len(frame) // 40)):
            _decode_must_not_crash(frame[:cut])
            _decode_must_not_crash(frame + frame[:cut])  # trailing junk


def test_length_field_extremes_never_crash_or_overallocate():
    """Huge declared counts/lengths must be rejected by caps, not
    attempted as allocations."""
    import struct

    # Propose frame claiming 2^32-1 payloads
    from hotstuff_tpu.utils.codec import Encoder

    enc = Encoder().u8(0)
    blocks = chain(2)
    blocks[-1].qc.encode(enc)
    enc.flag(False)
    from hotstuff_tpu.consensus.messages import encode_pk

    encode_pk(enc, blocks[-1].author)
    enc.u64(blocks[-1].round)
    enc.u32(0xFFFFFFFF)  # payload count
    _decode_must_not_crash(enc.finish())
    # vote whose pk length prefix is absurd
    frame = bytes([1]) + b"\x00" * 32 + struct.pack("<Q", 1) + struct.pack(
        "<I", 1 << 30
    )
    _decode_must_not_crash(frame)
    # block payload count just over the protocol cap decodes (the cap is
    # a VERIFY-time rule) or errors cleanly — never crashes
    assert MAX_BLOCK_PAYLOADS == 512
