"""Wire-codec robustness: random and mutated frames must never crash the
decoder — only ``SerializationError`` (or a clean decode) is acceptable.

The reference has no fuzzing at all (SURVEY §4 lists it as a gap); the
receiver dispatch feeds raw unauthenticated TCP frames into
``decode_message``, so "any byte string produces either a message or a
clean error" is a load-bearing property for liveness under garbage.
"""

from __future__ import annotations

import random

import pytest

from hotstuff_tpu.consensus.errors import SerializationError
from hotstuff_tpu.consensus.messages import MAX_BLOCK_PAYLOADS
from hotstuff_tpu.consensus.wire import (
    decode_message,
    encode_propose,
    encode_sync_request,
    encode_tc,
    encode_timeout,
    encode_vote,
)
from hotstuff_tpu.crypto import Digest, Signature

from .common import (
    chain,
    keys,
    qc_for_block,
    secret_for,
    signed_timeout,
    signed_vote,
)


def _decode_must_not_crash(data: bytes) -> None:
    try:
        decode_message(data)
    except SerializationError:
        pass  # the only acceptable failure mode


def test_random_frames_never_crash():
    rng = random.Random(0xF022)
    for _ in range(2_000):
        n = rng.randrange(0, 200)
        _decode_must_not_crash(rng.randbytes(n))


def test_tag_prefixed_random_frames_never_crash():
    """Valid tags followed by garbage exercise each decoder's depths."""
    rng = random.Random(0xF023)
    for tag in range(8):  # includes unknown tags
        for _ in range(500):
            body = rng.randbytes(rng.randrange(0, 400))
            _decode_must_not_crash(bytes([tag]) + body)


def test_mutated_valid_frames_never_crash():
    """Single-byte mutations and truncations of genuine messages — the
    most reachable malformed inputs for a Byzantine peer."""
    rng = random.Random(0xF024)
    blocks = chain(3)
    pk, sk = keys()[0]
    frames = [
        encode_propose(blocks[-1]),
        encode_vote(signed_vote(blocks[1], pk, sk)),
        encode_timeout(signed_timeout(qc_for_block(blocks[1]), 5, pk, sk)),
        encode_sync_request(Digest.of(b"missing"), pk),
    ]
    from hotstuff_tpu.consensus.messages import TC, timeout_digest
    from hotstuff_tpu.crypto import Signature

    tc = TC(
        round=5,
        votes=[
            (p, Signature.new(timeout_digest(5, 0), s), 0)
            for p, s in keys()[:3]
        ],
    )
    frames.append(encode_tc(tc))

    for frame in frames:
        decode_message(frame)  # sanity: the originals decode
        for _ in range(300):
            buf = bytearray(frame)
            pos = rng.randrange(len(buf))
            buf[pos] ^= 1 << rng.randrange(8)
            _decode_must_not_crash(bytes(buf))
        for cut in range(0, len(frame), max(1, len(frame) // 40)):
            _decode_must_not_crash(frame[:cut])
            _decode_must_not_crash(frame + frame[:cut])  # trailing junk


def test_length_field_extremes_never_crash_or_overallocate():
    """Huge declared counts/lengths must be rejected by caps, not
    attempted as allocations."""
    import struct

    # Propose frame claiming 2^32-1 payloads
    from hotstuff_tpu.utils.codec import Encoder

    enc = Encoder().u8(0)
    blocks = chain(2)
    blocks[-1].qc.encode(enc)
    enc.flag(False)
    from hotstuff_tpu.consensus.messages import encode_pk

    encode_pk(enc, blocks[-1].author)
    enc.u64(blocks[-1].round)
    enc.u32(0xFFFFFFFF)  # payload count
    _decode_must_not_crash(enc.finish())
    # vote whose pk length prefix is absurd
    frame = bytes([1]) + b"\x00" * 32 + struct.pack("<Q", 1) + struct.pack(
        "<I", 1 << 30
    )
    _decode_must_not_crash(frame)
    # block payload count just over the protocol cap decodes (the cap is
    # a VERIFY-time rule) or errors cleanly — never crashes
    assert MAX_BLOCK_PAYLOADS == 512


def test_adversarial_well_formed_frames_decode_then_fail_verify():
    """Frames an adversary-plane node actually emits (faults/adversary.py)
    are WELL-FORMED on the wire — they must decode cleanly and be killed
    by verification (``ConsensusError``), never by the codec and never by
    an unhandled crash.  This is a different threat than random mutation:
    every byte here is chosen by a protocol-aware attacker."""
    import time

    from hotstuff_tpu.consensus.errors import ConsensusError
    from hotstuff_tpu.crypto.service import CpuVerifier
    from hotstuff_tpu.faults.adversary import AdversaryPlane

    from .common import committee, signed_block

    base = 9_900
    com = committee(base)
    verifier = CpuVerifier()
    plane = AdversaryPlane(
        {
            "name": "byz-forge-qc",
            "seed": 7,
            "epoch_unix": time.time(),
            "nodes": {f"127.0.0.1:{base + i}": i for i in range(4)},
            "adversary": [{"policy": "forge-qc", "node": 0, "at": 0.0}],
        },
        ("127.0.0.1", base),
    )
    pairs = keys()
    blocks = chain(3)

    # 1. Forged QC smuggled inside an otherwise-genuine timeout: passes
    #    check_weight (real authors, quorum-many), decode round-trips,
    #    verification rejects the garbage signatures.
    forged = plane.forged_qc(com, blocks[1].round)
    forged.check_weight(com)
    pk, sk = pairs[0]
    frame = encode_timeout(signed_timeout(forged, 5, pk, sk))
    _, timeout = decode_message(frame)
    with pytest.raises(ConsensusError):
        timeout.verify(com, verifier)

    # 2. The forged QC as a block's parent certificate.
    author, secret = pairs[2 % 4]
    bad_block = signed_block(author, secret, 2, qc=forged)
    _, decoded = decode_message(encode_propose(bad_block))
    assert decoded.digest() == bad_block.digest()
    with pytest.raises(ConsensusError):
        decoded.verify(com, verifier)

    # 3. A vote whose signature was produced by a DIFFERENT committee
    #    member (signature spoofing a peer): structurally perfect, fails
    #    only on crypto.
    spoofed = signed_vote(blocks[1], pairs[1][0], pairs[2][1])
    _, vote = decode_message(encode_vote(spoofed))
    with pytest.raises(ConsensusError):
        vote.verify(com, verifier)

    # 4. The equivocating twin of a committed block, genuinely signed by
    #    its author: decodes AND verifies — only the safety rule (not the
    #    codec or crypto) can reject it, which is exactly why the
    #    invariant checker needs attribution.
    shadow = plane.shadow_block(blocks[1])
    shadow.signature = Signature.new(
        shadow.digest(), secret_for(shadow.author)
    )
    _, twin = decode_message(encode_propose(shadow))
    twin.verify(com, verifier)
    assert twin.digest() != blocks[1].digest()
    assert twin.round == blocks[1].round and twin.author == blocks[1].author

    # 5. Mutations of the adversarial frames still never crash the codec.
    rng = random.Random(0xF025)
    for f in (frame, encode_propose(bad_block), encode_vote(spoofed)):
        for _ in range(200):
            buf = bytearray(f)
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            _decode_must_not_crash(bytes(buf))
