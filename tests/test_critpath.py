"""Commit critical-path engine tests (ISSUE 17).

Covers the tentpole (hotstuff_tpu/telemetry/critpath.py) end to end on
fixture journals with hand-computable arithmetic: causal-chain
reconstruction and exact attribution sums on an honest committee,
clock-skew recovery, graceful degradation when edges are missing
(residual lands in ``unattributed`` — never fabricated), the qc.form ->
qc adoption fallback, crash-restart merge dedup by (node, seq) plus the
no-silent-caps dropped counter flowing into journal coverage, the
attribution-diff regression gate (share growth fails, shrink passes,
noise floor holds), the ``crit_regime_shift`` detector (pure and wired
through HealthMonitor), on-node ``rolling_attribution``, the Perfetto
critical-path track, and sim-plane determinism (same seed => identical
attribution document).
"""

from __future__ import annotations

import json

import pytest

from benchmark.traces import TraceSet, load_journals
from hotstuff_tpu.telemetry import critpath
from hotstuff_tpu.telemetry.critpath import (
    analyze,
    classify_regime,
    diff,
    render,
    rolling_attribution,
)
from hotstuff_tpu.telemetry.health import HealthMonitor, crit_regime_shift
from hotstuff_tpu.telemetry.taxonomy import CRITPATH_REGIMES, CRITPATH_STAGES

MS = 1_000_000  # ns per ms


# ---- fixture journals ------------------------------------------------------


def _committee_journals(n_rounds: int = 5, skews: dict | None = None):
    """Four nodes (A leads every round), symmetric per-pair delays so
    clock-offset estimation is EXACT, rounds pipelined every 15 ms.

    Per round r (ms offsets from that round's propose instant):

        propose at A               +0
        recv.propose   B +4, C +5, D +6     (pair delays 4/5/6 ms)
        vote.send      B +6, C +7, D +8     (2 ms local verify+sign)
        recv.vote at A B +10, C +12, D +14  (same pair delay back)
        qc.form at A   +13

    Quorum is 3, so the chain binds on C: net.propose 5, vote.local 2,
    net.vote 5, agg.form 1 per round.  B_r commits once QC(r+1) forms
    at +28; the slowest committer is D at +31.  With the 12 ms median
    producer wait the per-commit attribution sums EXACTLY to the
    measured total:

        ingest.wait 12 + net.propose 10 + vote.local 4 + net.vote 10
        + agg.form 2 + lead.handoff 2 + commit.exec 3 = 43 ms

    ``skews`` (node -> ns added to every wall stamp) simulates clock
    skew; monotonic stamps stay true, like real per-node clocks.
    """
    skews = skews or {}
    t0 = 1_000_000 * MS
    period = 15 * MS
    out: dict[str, list[dict]] = {"A": [], "B": [], "C": [], "D": []}
    delay = {"B": 4, "C": 5, "D": 6}

    def rec(node: str, e: str, r: int, d: str, p: str = "", at: int = 0):
        out[node].append(
            {"e": e, "r": r, "d": d, "p": p, "m": at,
             "w": at + skews.get(node, 0)}
        )

    # leader payload waits: median 12 ms -> the per-commit ingest estimate
    for i, wait in enumerate((11, 12, 13)):
        pd = f"PAY{i}000000000000"[:16]
        rec("A", "recv.producer", 0, pd, "client", t0 + i * MS)
        rec("A", "payload.first", 1, pd, "", t0 + i * MS + wait * MS)

    digests = {}
    for r in range(1, n_rounds + 1):
        d = f"blk{r:02d}0000000000000"[:16]
        digests[r] = d
        tr = t0 + r * period
        rec("A", "propose", r, d, at=tr)
        for name, dl in delay.items():
            rec(name, "recv.propose", r, d, "A", at=tr + dl * MS)
            rec(name, "vote.send", r, d, "A", at=tr + (dl + 2) * MS)
            rec("A", "recv.vote", r, d, name, at=tr + (2 * dl + 2) * MS)
        rec("A", "qc.form", r, d, at=tr + 13 * MS)
    # B_r commits once QC(r+1) forms (2-chain): +28 relative to its propose
    for r in range(1, n_rounds):
        d = digests[r]
        tr = t0 + r * period
        for name, dt_ms in (("A", 29.0), ("B", 30.0), ("C", 30.5), ("D", 31.0)):
            rec(name, "commit", r, d, at=tr + int(dt_ms * MS))
    return out


EXPECTED_STAGES = {
    "ingest.wait": 12.0,
    "net.propose": 10.0,
    "vote.local": 4.0,
    "net.vote": 10.0,
    "agg.form": 2.0,
    "lead.handoff": 2.0,
    "commit.exec": 3.0,
}


# ---- honest-chain reconstruction -------------------------------------------


def test_honest_chain_attribution_sums_exactly():
    """With every edge journaled the chain is contiguous: the stage sum
    equals the measured commit latency and coverage is exactly 1."""
    report = analyze(TraceSet(_committee_journals()))
    assert len(report.commits) == 4
    for c in report.commits:
        assert c.node == "D"  # slowest committer ends the path
        assert c.total_ms == pytest.approx(43.0, abs=1e-6)
        assert c.coverage == pytest.approx(1.0, abs=1e-9)
        assert sum(c.stages.values()) == pytest.approx(c.total_ms, abs=1e-6)
        for stage, ms in EXPECTED_STAGES.items():
            assert c.stages[stage] == pytest.approx(ms, abs=1e-6), stage
        assert c.dominant == "ingest.wait"
        assert all(s.stage in CRITPATH_STAGES for s in c.segments)
    # the network group (10 + 10 + 3) outweighs ingest (12), verify (4)
    # and aggregation (2 + 2) even though no single network stage wins
    assert report.regime == "network-bound"
    assert report.coverage == pytest.approx(1.0, abs=1e-9)
    assert report.journal_coverage == 1.0 and report.dropped_records == 0


def test_attribution_document_shape():
    report = analyze(TraceSet(_committee_journals()))
    att = report.attribution()
    assert att["commits"] == 4
    assert att["p50_ms"] == pytest.approx(43.0, abs=1e-3)
    assert att["coverage_pct"] == pytest.approx(100.0)
    assert att["journal_coverage_pct"] == pytest.approx(100.0)
    assert att["regime"] == "network-bound"
    assert att["dominant"] == {"ingest.wait": 4}
    assert "unattributed" not in att["stages"]
    shares = {s: e["share"] for s, e in att["stages"].items()}
    assert sum(shares.values()) == pytest.approx(1.0, abs=0.01)
    for stage, ms in EXPECTED_STAGES.items():
        assert shares[stage] == pytest.approx(ms / 43.0, abs=1e-3), stage
        assert att["stages"][stage]["p50_ms"] == pytest.approx(ms, abs=1e-3)
    # documents roundtrip through JSON (the --diff gate reads files)
    assert json.loads(json.dumps(att)) == att


def test_skewed_clocks_recovered():
    """Tens of ms of per-node wall skew (vs a 43 ms commit) must not
    move the attribution: symmetric pair delays make the median-based
    offset estimate exact."""
    honest = analyze(TraceSet(_committee_journals()))
    skewed = analyze(
        TraceSet(
            _committee_journals(
                skews={"B": 50 * MS, "C": -20 * MS, "D": 35 * MS}
            )
        )
    )
    assert len(skewed.commits) == len(honest.commits)
    for stage, total in honest.stage_totals.items():
        assert skewed.stage_totals[stage] == pytest.approx(
            total, abs=1e-6
        ), stage
    assert skewed.regime == honest.regime == "network-bound"


def test_missing_vote_edges_degrade_to_unattributed():
    """Dropping every vote.send loses vote.local + net.vote: the engine
    must not crash and must not fabricate — the 14 ms gap lands in the
    residual, which outweighs every single stage, so the per-commit
    dominant is honestly 'unattributed'."""
    journals = {
        n: [r for r in recs if r["e"] != "vote.send"]
        for n, recs in _committee_journals().items()
    }
    report = analyze(TraceSet(journals))
    assert len(report.commits) == 4
    assert "vote.local" not in report.stage_totals
    assert "net.vote" not in report.stage_totals
    for c in report.commits:
        # ingest 12 + net.propose 10 + agg 2 + handoff 2 + exec 3 = 29/43
        assert c.coverage == pytest.approx(29.0 / 43.0, abs=1e-6)
        assert c.dominant == "unattributed"
    assert report.attribution()["dominant"] == {"unattributed": 4}
    # network group (13) still edges out ingest (12) on attributed ms
    assert report.regime == "network-bound"


def test_qc_adoption_fallback_when_qc_form_missing():
    """Without the aggregator's qc.form edge the first high-QC adoption
    anchors the round instead — the chain still closes end to end."""
    journals = _committee_journals()
    journals["A"] = [r for r in journals["A"] if r["e"] != "qc.form"]
    for r in range(1, 6):
        d = f"blk{r:02d}0000000000000"[:16]
        tr = 1_000_000 * MS + r * 15 * MS
        journals["A"].append(
            {"e": "qc", "r": r, "d": d, "p": "", "m": tr + 13 * MS + MS // 2,
             "w": tr + 13 * MS + MS // 2}
        )
    report = analyze(TraceSet(journals))
    assert len(report.commits) == 4
    for c in report.commits:
        assert c.stages["agg.form"] == pytest.approx(3.0, abs=1e-6)
        assert c.stages["lead.handoff"] == pytest.approx(1.5, abs=1e-6)
        assert c.stages["commit.exec"] == pytest.approx(2.5, abs=1e-6)
        assert c.coverage == pytest.approx(1.0, abs=1e-9)


def test_commit_before_propose_skipped():
    """Irrecoverable clock damage (a commit wall-stamped before its own
    propose) skips that block only — never a crash, never a negative
    path."""
    journals = _committee_journals()
    for recs in journals.values():
        for r in recs:
            if r["e"] == "commit" and r["r"] == 2:
                r["w"] -= 40 * MS
    report = analyze(TraceSet(journals))
    assert len(report.commits) == 3
    assert all(c.round != 2 for c in report.commits)
    assert all(c.total_ms > 0 for c in report.commits)


# ---- journal merge accounting (crash-restart overlap, dropped rings) ------


def test_merge_dedup_by_node_seq(tmp_path):
    """A crash-restarted node replays seqs already persisted (a torn
    tail hides the true max): the merge dedups by (node, seq), first
    occurrence wins, and the ring's cumulative drop counter survives
    into the stats."""
    seg1 = tmp_path / "nodeX-000001.jsonl"
    seg2 = tmp_path / "nodeX-000002.jsonl"
    with open(seg1, "w") as f:
        f.write(json.dumps({"e": "meta", "n": "X", "tot": 5, "drop": 0}) + "\n")
        for s in range(1, 6):
            f.write(json.dumps(
                {"e": "commit", "r": s, "d": f"d{s:015d}"[:16],
                 "m": s * MS, "w": s * MS, "s": s}) + "\n")
    with open(seg2, "w") as f:
        f.write(json.dumps({"e": "meta", "n": "X", "tot": 8, "drop": 3}) + "\n")
        for s in range(4, 9):  # 4 and 5 replayed after the restart
            f.write(json.dumps(
                {"e": "commit", "r": s + 100, "d": f"d{s:015d}"[:16],
                 "m": s * MS, "w": s * MS, "s": s}) + "\n")
    stats: dict = {}
    journals = load_journals(str(tmp_path), stats)
    assert list(journals) == ["X"]
    assert [r["s"] for r in journals["X"]] == list(range(1, 9))
    # first occurrence wins: seqs 4/5 keep the pre-crash rounds
    rounds = {r["s"]: r["r"] for r in journals["X"]}
    assert rounds[4] == 4 and rounds[5] == 5 and rounds[6] == 106
    assert stats["overlap"] == 2
    assert stats["loaded"] == 8 and stats["dropped"] == 3
    ts = TraceSet(journals, merge_stats=stats)
    assert ts.journal_coverage() == pytest.approx(8.0 / 11.0)


def test_dropped_records_flow_into_report_and_render():
    """The no-silent-caps contract: ring drops shrink the journal
    coverage figure and are NAMED in the + CRITPATH block."""
    ts = TraceSet(
        _committee_journals(),
        merge_stats={"loaded": 300, "dropped": 100, "overlap": 7},
    )
    report = analyze(ts)
    assert report.dropped_records == 100
    assert report.journal_coverage == pytest.approx(0.75)
    assert report.attribution()["journal_coverage_pct"] == pytest.approx(75.0)
    text = render(report)
    assert "+ CRITPATH" in text
    assert "Journal coverage: 75%" in text
    assert "100 records rotated away" in text
    assert "regime: network-bound" in text
    assert "ingest.wait" in text and "Slowest edges:" in text
    # the merge accounting also surfaces in the cross-node summary
    summary = ts.summary()
    assert "7 replayed record(s) deduped" in summary
    assert "Journal ring dropped 100" in summary


# ---- regime classification -------------------------------------------------


def test_classify_regime_groups_and_unknown():
    assert classify_regime({}) == "unknown"
    assert classify_regime({"net.propose": 0.0}) == "unknown"
    assert classify_regime({"vote.local": 5.0, "agg.form": 4.0}) == (
        "verify-bound"
    )
    # group SUM wins, not the single biggest stage
    assert classify_regime(
        {"ingest.wait": 6.0, "net.propose": 4.0, "commit.exec": 3.0}
    ) == "network-bound"
    assert set(CRITPATH_REGIMES) == {
        "ingest-bound", "network-bound", "verify-bound", "aggregation-bound",
    }


# ---- Perfetto critical-path track ------------------------------------------


def test_chrome_trace_critical_path_track():
    ts = TraceSet(_committee_journals())
    report = analyze(ts)
    doc = ts.chrome_trace(critpath=report)
    tracks = [
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("name") == "process_name"
    ]
    assert "critical path" in tracks
    slices = [e for e in doc["traceEvents"] if e.get("cat") == "critpath"]
    # per commit: 4 anchored hops per chained round + handoff + exec =
    # 10 (the derived ingest.wait estimate has no wall anchors)
    assert len(slices) == 4 * 10
    assert {e["name"] for e in slices} == {
        "net.propose", "vote.local", "net.vote", "agg.form",
        "lead.handoff", "commit.exec",
    }
    for e in slices:
        assert e["dur"] >= 1.0 and e["ts"] >= 0.0
        assert e["args"]["digest"].startswith("blk")
    # without a report no critical-path track appears
    plain = ts.chrome_trace()
    assert not any(e.get("cat") == "critpath" for e in plain["traceEvents"])


# ---- attribution diff (the regression gate) --------------------------------


def _att_doc(**shares) -> dict:
    return {"stages": {s: {"share": v} for s, v in shares.items()}}


def test_diff_share_growth_fails_shrink_passes():
    ref = _att_doc(**{"net.propose": 0.40, "vote.local": 0.30})
    assert diff(ref, ref) == []
    grown = _att_doc(**{"net.propose": 0.56, "vote.local": 0.14})
    fails = diff(grown, ref)
    assert len(fails) == 1
    assert "critpath.net.propose.share" in fails[0]
    assert "+16.0pp" in fails[0]
    # shrinking (or holding) every share never fails
    shrunk = _att_doc(**{"net.propose": 0.30, "vote.local": 0.30})
    assert diff(shrunk, ref) == []


def test_diff_catches_shape_drift_at_constant_scalar():
    """The gate's reason to exist: identical p50, different shape."""
    ref = analyze(TraceSet(_committee_journals())).attribution()
    planted = json.loads(json.dumps(ref))
    # pretend the reference spent 16pp less in ingest.wait than we do now
    planted["stages"]["ingest.wait"]["share"] -= 0.16
    fails = diff(ref, planted)
    assert fails and "critpath.ingest.wait.share" in fails[0]
    assert diff(ref, ref) == []


def test_diff_new_stage_counts_as_growth_from_zero():
    ref = _att_doc(**{"net.propose": 0.50})
    cur = _att_doc(**{"net.propose": 0.35, "commit.exec": 0.15})
    fails = diff(cur, ref)
    assert len(fails) == 1 and "commit.exec" in fails[0]


def test_diff_noise_floor_and_tolerance_knob():
    # both sides under min_share: ignored even at a tiny tolerance
    tiny = diff(
        _att_doc(**{"agg.form": 0.015, "net.propose": 0.5}),
        _att_doc(**{"agg.form": 0.001, "net.propose": 0.5}),
        share_pp=0.5,
    )
    assert tiny == []
    # the same 1.4pp growth ABOVE the floor fails at that tolerance
    real = diff(
        _att_doc(**{"agg.form": 0.044, "net.propose": 0.5}),
        _att_doc(**{"agg.form": 0.030, "net.propose": 0.5}),
        share_pp=0.5,
    )
    assert len(real) == 1 and "agg.form" in real[0]


def test_diff_skip_if_missing():
    doc = _att_doc(**{"net.propose": 0.9})
    assert diff({}, doc) == []
    assert diff(doc, {}) == []
    assert diff(None, doc) == []
    assert diff({"stages": {}}, doc) == []


# ---- crit_regime_shift detector --------------------------------------------


def test_regime_shift_fires_only_when_settled():
    n, v = "network-bound", "verify-bound"
    assert crit_regime_shift([n] * 3) is None  # not enough history
    assert crit_regime_shift([n] * 8) is None  # no shift
    assert crit_regime_shift([n] * 4 + [v]) is None  # one-tick flap
    assert crit_regime_shift([n] * 4 + [v, n, v]) is None  # flapping
    inc = crit_regime_shift([n] * 4 + [v] * 3, node="n2")
    assert inc is not None and inc.kind == "crit_regime_shift"
    assert inc.severity == "warn" and inc.node == "n2"
    assert "network-bound -> verify-bound" in inc.detail


def test_regime_shift_filters_unknown_and_honors_confirm():
    n, i = "network-bound", "ingest-bound"
    # unknown/empty ticks are not evidence either way
    seq = [n, "unknown", n, "", n, n, i, "unknown", i, i]
    inc = crit_regime_shift(seq)
    assert inc is not None and "network-bound -> ingest-bound" in inc.detail
    assert crit_regime_shift(["unknown", "", "unknown"]) is None
    assert crit_regime_shift([n, i], confirm=1) is not None
    assert crit_regime_shift([i, i], confirm=1) is None


def test_monitor_ticks_rolling_attribution_into_detector():
    """HealthMonitor wiring: the attribution callback feeds the regime
    window, last_attribution backs the DOMINANT-STAGE watch column, and
    a settled shift opens a crit_regime_shift incident."""

    class FakeTel:
        journal = None

        def snapshot(self):
            return {"trace": {"commits": 5, "tc_advances": 0,
                              "last_commit_round": 9}}

    feed = (["network-bound"] * 4 + ["verify-bound"] * 3)
    atts = iter(
        {"regime": r, "dominant": "vote.local", "samples": 8} for r in feed
    )
    # a huge timeout keeps leader_stall's cold-start guard shut: this
    # test isolates the attribution path
    mon = HealthMonitor(
        FakeTel(), "n0", timeout_s=100.0, attribution_fn=lambda: next(atts)
    )
    fired = []
    for t in range(len(feed)):
        fired = mon.tick(float(t))
    assert [i.kind for i in fired] == ["crit_regime_shift"]
    assert "crit_regime_shift" in {i.kind for i in mon.open_incidents()}
    assert mon.last_attribution["regime"] == "verify-bound"
    assert mon.last_attribution["dominant"] == "vote.local"


def test_monitor_survives_attribution_failure():
    class FakeTel:
        journal = None

        def snapshot(self):
            return {}

    def boom():
        raise RuntimeError("no samples yet")

    mon = HealthMonitor(FakeTel(), "n0", timeout_s=100.0, attribution_fn=boom)
    for t in range(4):
        assert isinstance(mon.tick(float(t)), list)
    assert mon.last_attribution is None


# ---- on-node rolling attribution -------------------------------------------


def _trace_entry(pv=None, vq=None, qc=None, total=10.0):
    e = {"propose_to_commit_ms": total}
    if pv is not None:
        e["propose_to_vote_ms"] = pv
    if vq is not None:
        e["vote_to_qc_ms"] = vq
    if qc is not None:
        e["qc_to_commit_ms"] = qc
    return e


def test_rolling_attribution_needs_samples():
    entries = [_trace_entry(pv=8.0, vq=2.0, qc=3.0)] * 3
    assert rolling_attribution(entries) is None  # below the floor
    assert rolling_attribution(None) is None
    assert rolling_attribution([]) is None
    # entries without a commit measurement don't count toward the floor
    padded = entries + [{"round": 7}, {"round": 8}]
    assert rolling_attribution(padded) is None
    # commit totals alone (no edge breakdown) classify nothing
    assert rolling_attribution([{"propose_to_commit_ms": 9.0}] * 6) is None


def test_rolling_attribution_maps_edges_to_regimes():
    att = rolling_attribution([_trace_entry(pv=8.0, vq=2.0, qc=3.0)] * 5)
    assert att["samples"] == 5
    assert att["dominant"] == "propose_to_vote"
    assert att["regime"] == "verify-bound"
    assert att["edges_ms"] == {
        "propose_to_vote": 8.0, "vote_to_qc": 2.0, "qc_to_commit": 3.0,
    }
    slow_chain = rolling_attribution(
        [_trace_entry(pv=2.0, vq=1.0, qc=9.0)] * 4
    )
    assert slow_chain["regime"] == "network-bound"
    assert set(critpath.LOCAL_EDGE_REGIME.values()) <= (
        set(CRITPATH_REGIMES) | {"unknown"}
    )


# ---- sim-plane determinism -------------------------------------------------


def test_sim_attribution_deterministic(tmp_path):
    """Same seed => byte-identical journals => identical attribution
    document on the verdict (virtual clocks stamp the journals)."""
    from hotstuff_tpu.sim import draw_schedule, run_schedule

    schedule = draw_schedule(1, nodes=4)
    a = run_schedule(schedule, workdir=str(tmp_path / "a"))
    b = run_schedule(schedule, workdir=str(tmp_path / "b"))
    assert a.ok and b.ok
    assert a.attribution is not None
    assert a.attribution == b.attribution
    att = a.attribution
    assert att["commits"] > 0
    assert set(att) >= {
        "commits", "p50_ms", "p99_ms", "coverage_pct",
        "journal_coverage_pct", "regime", "stages", "dominant",
    }
    assert att["regime"] in set(CRITPATH_REGIMES) | {"unknown"}
    assert att["coverage_pct"] > 50.0
    assert att["stages"]  # at least one stage attributed
