"""Wire-level flow accounting tests (telemetry/flows.py, ISSUE 19).

Three contracts:

- **tag parity** — the accountant's first-byte -> class map is pinned
  against the LIVE wire constants (consensus/wire.py), so a tag
  renumbering is a test failure instead of a silently-mislabelled flow;
- **exact byte accounting** — across a fuzz corpus of frames driven
  through the real asyncio senders and a real Receiver (and through the
  native reactor when it is built), accounted bytes equal the exact
  encoded frame length, ``FRAME_OVERHEAD + len(payload)`` each;
- **determinism** — a same-seed sim double-run produces byte-identical
  per-node flow tables (runs entirely in virtual time, no ``slow``
  marker).
"""

from __future__ import annotations

import asyncio
import json
import random

import pytest

from hotstuff_tpu.network import Receiver, ReliableSender, SimpleSender
from hotstuff_tpu.telemetry.flows import (
    FRAME_OVERHEAD,
    FlowAccounting,
    frame_class,
)
from hotstuff_tpu.telemetry.taxonomy import FLOW_CLASSES

from .common import async_test, fresh_base_port


def _fuzz_corpus(seed: int, n: int = 64) -> list[bytes]:
    """Frames with every known tag byte plus unknown tags and an empty
    frame — sizes spread across the framing small/large paths."""
    rng = random.Random(seed)
    corpus: list[bytes] = [b""]
    tags = list(range(12)) + [0x41, 0xA2, 0xA3, 0x7F, 0xFF]
    for i in range(n - 1):
        tag = tags[i % len(tags)]
        body = rng.randbytes(rng.choice([0, 1, 37, 512, 4096]))
        corpus.append(bytes([tag]) + body)
    return corpus


def _wire_cost(corpus) -> int:
    return sum(FRAME_OVERHEAD + len(p) for p in corpus)


# ---- tag taxonomy parity ----------------------------------------------


def test_frame_class_pins_live_wire_tags():
    """Every class assignment mirrors the wire constants it claims to
    mirror — drift in consensus/wire.py must break HERE, not in a
    dashboard."""
    from hotstuff_tpu.consensus import wire

    assert frame_class(bytes([wire.TAG_PROPOSE])) == "propose"
    assert frame_class(bytes([wire.TAG_VOTE])) == "vote"
    assert frame_class(bytes([wire.TAG_TIMEOUT])) == "timeout"
    assert frame_class(bytes([wire.TAG_TC])) == "tc"
    assert frame_class(bytes([wire.TAG_SYNC_REQUEST])) == "sync-req"
    assert frame_class(bytes([wire.TAG_PRODUCER])) == "producer-v1"
    assert frame_class(bytes([wire.TAG_PRODUCER_V2])) == "producer-v2"
    # the whole state-transfer family folds into one class
    for tag in (
        wire.TAG_STATE_REQUEST,
        wire.TAG_STATE_MANIFEST,
        wire.TAG_STATE_CHUNK,
        wire.TAG_STATE_READ,
        wire.STATE_VALUE_TAG,
    ):
        assert frame_class(bytes([tag])) == "state-sync"
    assert frame_class(bytes([wire.TAG_RECONFIG])) == "reconfig"
    assert frame_class(wire.ACK) == "ack"
    assert frame_class(bytes([wire.INGEST_ACK_TAG])) == "ingest-ack"
    # unknown tags and the empty frame land in "other", never dropped
    assert frame_class(b"\x7f junk") == "other"
    assert frame_class(b"") == "other"


def test_every_class_is_registered_in_the_taxonomy():
    corpus = _fuzz_corpus(0xF040, 128)
    for payload in corpus:
        assert frame_class(payload) in FLOW_CLASSES


# ---- accountant unit behaviour ----------------------------------------


def test_amplification_is_wire_over_logical():
    acc = FlowAccounting("n0", enabled=True)
    frame = bytes([0]) + b"p" * 96  # propose
    acc.logical(frame)  # ONE broadcast call...
    for peer in ("a", "b", "c"):
        acc.tx(peer, frame)  # ...fanned out to 3 peers
    assert acc.amplification() == {"propose": 3.0}
    # a retransmit inflates wire amp AND the separate retx ledger
    acc.tx("a", frame, retx=True)
    assert acc.amplification()["propose"] == pytest.approx(4.0)
    assert acc.retx_bytes() == FRAME_OVERHEAD + len(frame)


def test_snapshot_topk_elides_with_explicit_counter(monkeypatch):
    monkeypatch.setenv("HOTSTUFF_NET_TOPK", "3")
    acc = FlowAccounting("n0", enabled=True)
    # 10 peers, strictly decreasing byte totals so top-K is stable
    for i in range(10):
        acc.tx(f"peer-{i}", bytes([1]) + b"v" * (100 - i))
    snap = acc.snapshot()
    assert len(snap["peers"]) == 3
    assert snap["peers_elided"] == 7
    assert list(snap["peers"]) == ["peer-0", "peer-1", "peer-2"]
    # eliding peers never elides bytes: totals stay exact
    assert snap["tx_bytes"] == acc.tx_bytes()
    # TOPK=0 disables the cap outright
    monkeypatch.setenv("HOTSTUFF_NET_TOPK", "0")
    full = FlowAccounting("n1", enabled=True)
    for i in range(10):
        full.tx(f"peer-{i}", b"\x01x")
    assert len(full.snapshot()["peers"]) == 10
    assert full.snapshot()["peers_elided"] == 0


def test_disabled_accounting_is_inert():
    acc = FlowAccounting("n0", enabled=False)
    acc.tx("a", b"\x00data")
    acc.rx("a", b"\x01data")
    acc.logical(b"\x00data")
    assert acc.snapshot() == {"enabled": False}
    assert acc.table() == {"flows": {}, "logical": {}}


# ---- exact byte accounting through the real transports ----------------


class _CollectHandler:
    def __init__(self, expect: int):
        self.frames: list[bytes] = []
        self.expect = expect
        self.done = asyncio.Event()

    async def dispatch(self, writer, message: bytes) -> None:
        self.frames.append(message)
        await writer.send(b"Ack")
        if len(self.frames) >= self.expect:
            self.done.set()


@async_test
async def test_simple_sender_accounts_exact_frame_bytes():
    corpus = _fuzz_corpus(0xF041)
    port = fresh_base_port()
    rx_acc = FlowAccounting("rx", enabled=True)
    tx_acc = FlowAccounting("tx", enabled=True)
    handler = _CollectHandler(len(corpus))
    recv = Receiver("127.0.0.1", port, handler, flows=rx_acc)
    await recv.spawn()
    sender = SimpleSender(flows=tx_acc)
    for payload in corpus:
        await sender.send(("127.0.0.1", port), payload)
    await asyncio.wait_for(handler.done.wait(), timeout=10.0)

    expected = _wire_cost(corpus)
    assert tx_acc.tx_bytes() == expected
    assert rx_acc.rx_bytes() == expected
    # the receiver's ACK replies are charged on ITS tx side, one frame
    # of b"Ack" per dispatch
    assert rx_acc.tx_bytes() == len(corpus) * (FRAME_OVERHEAD + 3)
    # per-class split loses nothing: class totals sum to the totals
    split = tx_acc.class_totals()
    assert sum(c["tx_bytes"] for c in split.values()) == expected
    assert sum(c["tx_frames"] for c in split.values()) == len(corpus)
    sender.close()
    await recv.shutdown()


@async_test
async def test_reliable_sender_accounts_exact_frame_bytes():
    corpus = _fuzz_corpus(0xF042, 32)
    port = fresh_base_port()
    rx_acc = FlowAccounting("rx", enabled=True)
    tx_acc = FlowAccounting("tx", enabled=True)
    handler = _CollectHandler(len(corpus))
    recv = Receiver("127.0.0.1", port, handler, flows=rx_acc)
    await recv.spawn()
    sender = ReliableSender(flows=tx_acc)
    handles = [
        await sender.send(("127.0.0.1", port), payload) for payload in corpus
    ]
    await asyncio.wait_for(asyncio.gather(*handles), timeout=10.0)

    expected = _wire_cost(corpus)
    assert tx_acc.tx_bytes() == expected
    assert rx_acc.rx_bytes() == expected
    # every ACK resolved first-try on a clean localhost link: the
    # retransmit ledger must read exactly zero
    assert tx_acc.retx_bytes() == 0
    assert all(r[3] == 0 for r in tx_acc.table()["flows"].values())
    sender.close()
    await recv.shutdown()


@async_test
async def test_native_reactor_loopback_matches_python_ledger():
    """Native sender -> native receiver: the Python-side flow ledger and
    the C++ reactor's own counters agree on every byte (both sides
    include the length prefix)."""
    native = pytest.importorskip("hotstuff_tpu.network.native")

    corpus = _fuzz_corpus(0xF043, 24)
    port = fresh_base_port()
    rx_acc = FlowAccounting("rx", enabled=True)
    tx_acc = FlowAccounting("tx", enabled=True)
    # the empty frame is charged on arrival but swallowed before
    # dispatch (b"" doubles as the isolate-window sentinel), so the
    # handler sees one frame fewer than the wire carried
    dispatched = sum(1 for p in corpus if p)
    handler = _CollectHandler(dispatched)
    recv = native.NativeReceiver("127.0.0.1", port, handler, flows=rx_acc)
    await recv.spawn()
    reactor = native.Reactor.shared()
    before = reactor.counters()

    sender = native.NativeSimpleSender(flows=tx_acc)
    for payload in corpus:
        await sender.send(("127.0.0.1", port), payload)
    await asyncio.wait_for(handler.done.wait(), timeout=10.0)

    expected = _wire_cost(corpus)
    assert tx_acc.tx_bytes() == expected
    assert rx_acc.rx_bytes() == expected

    # reactor ground truth: both directions of this loopback ran through
    # the one shared reactor, so its cumulative deltas cover our frames
    # plus the receiver's ACK replies — nothing else ran native here
    after = reactor.counters()
    acks = rx_acc.tx_bytes()
    assert after["tx_bytes"] - before["tx_bytes"] == expected + acks
    assert (
        after["tx_frames"] - before["tx_frames"]
        == len(corpus) + dispatched
    )
    assert after["rx_bytes"] - before["rx_bytes"] >= expected
    sender.close()
    await recv.shutdown()


# ---- sim determinism: byte-identical flow tables ----------------------


def test_same_seed_sim_runs_produce_byte_identical_flow_tables(tmp_path):
    from hotstuff_tpu.sim import draw_schedule, run_schedule

    schedule = draw_schedule(3, nodes=4, profile="honest")
    a = run_schedule(schedule, workdir=str(tmp_path / "a"))
    b = run_schedule(schedule, workdir=str(tmp_path / "b"))
    assert a.ok and b.ok
    assert a.flows and set(a.flows) == set(b.flows)
    assert json.dumps(a.flows, sort_keys=True) == json.dumps(
        b.flows, sort_keys=True
    )
    # the tables carry real consensus traffic, classed and non-empty
    wire = sum(
        row[0]
        for tables in a.flows.values()
        for t in tables
        for row in t["flows"].values()
    )
    assert wire > 0
    classes = {
        key.rsplit("|", 2)[2]
        for tables in a.flows.values()
        for t in tables
        for key in t["flows"]
    }
    assert {"propose", "vote"} <= classes <= set(FLOW_CLASSES)
