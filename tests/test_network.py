"""Network tests — ports of the reference's receiver/sender tests
(network/src/tests/*.rs): listener fixtures assert what lands on the wire;
the reliable `retry` case sends before any listener exists and asserts
delivery after one appears."""

import asyncio

import pytest

from hotstuff_tpu.network import (
    Receiver,
    ReliableSender,
    SimpleSender,
    read_frame,
    send_frame,
)

BASE_PORT = 24100


async def listener(port: int, expected: bytes, reply: bytes = b"Ack"):
    """One-shot fake peer (reference tests/common.rs:182-198): accept one
    connection, assert the first frame, reply, return the frame."""
    got = asyncio.get_running_loop().create_future()

    async def handle(reader, writer):
        frame = await read_frame(reader)
        await send_frame(writer, reply)
        if not got.done():
            got.set_result(frame)

    server = await asyncio.start_server(handle, "127.0.0.1", port)
    try:
        frame = await asyncio.wait_for(got, 5)
        assert frame == expected
        return frame
    finally:
        # no wait_closed(): senders hold their persistent connection open,
        # and 3.12's wait_closed blocks until every peer connection dies
        server.close()


class EchoHandler:
    def __init__(self):
        self.received = []

    async def dispatch(self, writer, message):
        self.received.append(message)
        await writer.send(b"Ack")


@pytest.mark.parametrize("payload", [b"hello", b"x" * 100_000])
def test_receiver_dispatches_and_acks(payload):
    async def body():
        port = BASE_PORT + 0
        handler = EchoHandler()
        rx = Receiver("127.0.0.1", port, handler)
        await rx.spawn()
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await send_frame(writer, payload)
        ack = await asyncio.wait_for(read_frame(reader), 5)
        assert ack == b"Ack"
        assert handler.received == [payload]
        writer.close()
        await rx.shutdown()

    asyncio.run(body())


def test_simple_sender():
    async def body():
        port = BASE_PORT + 1
        task = asyncio.create_task(listener(port, b"ping"))
        await asyncio.sleep(0.1)
        sender = SimpleSender()
        await sender.send(("127.0.0.1", port), b"ping")
        await asyncio.wait_for(task, 5)
        sender.close()

    asyncio.run(body())


def test_simple_broadcast():
    async def body():
        ports = [BASE_PORT + 2 + i for i in range(3)]
        tasks = [asyncio.create_task(listener(p, b"all")) for p in ports]
        await asyncio.sleep(0.1)
        sender = SimpleSender()
        await sender.broadcast([("127.0.0.1", p) for p in ports], b"all")
        await asyncio.wait_for(asyncio.gather(*tasks), 5)
        sender.close()

    asyncio.run(body())


def test_reliable_send_resolves_with_ack():
    async def body():
        port = BASE_PORT + 10
        task = asyncio.create_task(listener(port, b"important", reply=b"OK"))
        await asyncio.sleep(0.1)
        sender = ReliableSender()
        handle = await sender.send(("127.0.0.1", port), b"important")
        ack = await asyncio.wait_for(handle, 5)
        assert ack == b"OK"
        await asyncio.wait_for(task, 5)
        sender.close()

    asyncio.run(body())


def test_reliable_retry_before_listener_exists():
    """Reference reliable_sender_tests.rs:50-67: send with nobody listening,
    then start the listener — backoff reconnect must deliver it."""

    async def body():
        port = BASE_PORT + 11
        sender = ReliableSender()
        handle = await sender.send(("127.0.0.1", port), b"late delivery")
        await asyncio.sleep(0.4)  # let a connect attempt fail
        assert not handle.done()
        task = asyncio.create_task(listener(port, b"late delivery"))
        ack = await asyncio.wait_for(handle, 10)
        assert ack == b"Ack"
        await asyncio.wait_for(task, 5)
        sender.close()

    asyncio.run(body())


def test_reliable_broadcast_quorum_wait():
    """The proposer's pattern: broadcast, then await 2f+1 ACK handles."""

    async def body():
        ports = [BASE_PORT + 20 + i for i in range(3)]
        tasks = [asyncio.create_task(listener(p, b"block")) for p in ports]
        await asyncio.sleep(0.1)
        sender = ReliableSender()
        handles = await sender.broadcast(
            [("127.0.0.1", p) for p in ports], b"block"
        )
        done = 0
        for fut in asyncio.as_completed(handles, timeout=5):
            await fut
            done += 1
            if done >= 2:  # 2f+1 with f=0 committee of 3 → just exercise wait
                break
        assert done == 2
        await asyncio.wait_for(asyncio.gather(*tasks), 5)
        sender.close()

    asyncio.run(body())


def test_reliable_retransmits_unacked_on_reconnect():
    """Connection dies after receiving (not ACKing) a frame; the message must
    be retransmitted on the next connection."""

    async def body():
        port = BASE_PORT + 30
        first_conn = asyncio.get_running_loop().create_future()

        async def rude_handler(reader, writer):
            # read the frame, then slam the door without ACKing
            await read_frame(reader)
            writer.close()
            if not first_conn.done():
                first_conn.set_result(None)

        rude = await asyncio.start_server(rude_handler, "127.0.0.1", port)
        sender = ReliableSender()
        handle = await sender.send(("127.0.0.1", port), b"retry me")
        await asyncio.wait_for(first_conn, 5)
        rude.close()
        await rude.wait_closed()
        # now a polite listener takes over the port
        task = asyncio.create_task(listener(port, b"retry me"))
        ack = await asyncio.wait_for(handle, 10)
        assert ack == b"Ack"
        await asyncio.wait_for(task, 5)
        sender.close()

    asyncio.run(body())


def test_network_error_taxonomy():
    """Typed connect/listen/send/receive/ACK errors (reference
    network/src/error.rs:6-25): classifiable, address-carrying, and
    OSError-compatible so existing raw-tuple handlers keep working."""
    from hotstuff_tpu.network import (
        AckError,
        ConnectError,
        ListenError,
        NetworkError,
    )
    from hotstuff_tpu.network.errors import classify

    err = classify(ConnectionRefusedError(111, "refused"), "connect",
                   ("10.0.0.1", 9999))
    assert isinstance(err, ConnectError)
    assert isinstance(err, NetworkError)
    assert isinstance(err, OSError)  # raw-tuple handlers still catch it
    assert "10.0.0.1:9999" in str(err)
    assert isinstance(classify(OSError(), "ack"), AckError)
    assert isinstance(classify(OSError(), "listen"), ListenError)


def test_listen_failure_is_typed():
    """Binding a port twice raises the taxonomy's ListenError."""
    from hotstuff_tpu.network import ListenError

    async def body():
        port = BASE_PORT + 90

        class NullHandler:
            async def dispatch(self, writer, message):
                pass

        a = Receiver("127.0.0.1", port, NullHandler())
        await a.spawn()
        b = Receiver("127.0.0.1", port, NullHandler())
        with pytest.raises(ListenError):
            await b.spawn()
        await a.shutdown()

    asyncio.run(body())


def test_simple_sender_bounded_pool_evicts_idle():
    """max_conns bounds the persistent-connection pool: sending to more
    peers than the cap evicts idle LRU connections (and only idle ones),
    while every message still arrives (r5: an unbounded pool wedged the
    256-node in-process committee against the process fd limit)."""

    async def body():
        base = BASE_PORT + 60
        n = 5
        payload = b"bounded"
        listeners = [
            asyncio.ensure_future(listener(base + i, payload))
            for i in range(n)
        ]
        await asyncio.sleep(0.05)
        sender = SimpleSender(max_conns=2)
        for i in range(n):
            await sender.send(("127.0.0.1", base + i), payload)
            await asyncio.sleep(0.05)  # let the connection drain to idle
        await asyncio.wait_for(asyncio.gather(*listeners), timeout=5)
        assert len(sender._connections) <= 2
        sender.close()

    asyncio.run(body())


def test_reliable_sender_bounded_pool_keeps_acks():
    """ReliableSender's bound only evicts fully-ACKed idle connections:
    a capped broadcast still returns one resolving ACK future per peer."""

    async def body():
        base = BASE_PORT + 80
        n = 4
        payload = b"capped-reliable"
        listeners = [
            asyncio.ensure_future(listener(base + i, payload))
            for i in range(n)
        ]
        await asyncio.sleep(0.05)
        sender = ReliableSender(max_conns=2)
        handlers = await sender.broadcast(
            [("127.0.0.1", base + i) for i in range(n)], payload
        )
        acks = await asyncio.wait_for(asyncio.gather(*handlers), timeout=5)
        assert acks == [b"Ack"] * n
        await asyncio.gather(*listeners)
        # pool shrinks back to the cap once everything is ACKed
        for _ in range(50):
            sender._evict_idle(2)
            if len(sender._connections) <= 2:
                break
            await asyncio.sleep(0.02)
        assert len(sender._connections) <= 2
        sender.close()

    asyncio.run(body())
