"""Deterministic simulation plane (hotstuff_tpu/sim, docs/SIM.md).

Covers the virtual-time loop, the determinism contract (same seed ⇒
byte-identical journal), seeded crash-point injection with torn-WAL
recovery, shrinker convergence on a planted safety bug, the committed
regression seed corpus (tests/data/sim_seeds.json), and the virtual-time
port of the crash-restart-under-partition e2e — everything here runs in
virtual time, so no ``slow`` marker anywhere in this file.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import pytest

from hotstuff_tpu.sim import (
    SimDeadlock,
    SimLoop,
    VirtualClock,
    draw_schedule,
    run_schedule,
    shrink,
)
from hotstuff_tpu.sim.schedule import SCHEDULE_VERSION

CORPUS = os.path.join(os.path.dirname(__file__), "data", "sim_seeds.json")


# ---- virtual loop -----------------------------------------------------


def test_virtual_loop_sleeps_cost_no_wall_time():
    """An hour of virtual sleeping must finish in well under a second:
    the loop's clock jumps to the next timer whenever the run queue is
    empty."""
    loop = SimLoop()
    clock = VirtualClock(loop)

    async def nap():
        for _ in range(60):
            await asyncio.sleep(60.0)
        return clock.monotonic()

    t0 = time.monotonic()
    try:
        virtual = loop.run_until_complete(nap())
    finally:
        loop.close()
    assert virtual >= 3600.0
    assert time.monotonic() - t0 < 5.0


def test_virtual_loop_detects_deadlock():
    """A wait with no timer to jump to is a deadlock, not a hang."""
    loop = SimLoop()
    try:
        with pytest.raises(SimDeadlock):
            loop.run_until_complete(loop.create_future())
    finally:
        loop.close()


# ---- determinism contract ---------------------------------------------


def test_same_seed_byte_identical_journal(tmp_path):
    """The whole run — verdict fields AND the merged journal bytes — is
    a pure function of the schedule."""
    schedule = draw_schedule(3, nodes=4)
    a = run_schedule(schedule, workdir=str(tmp_path / "a"))
    b = run_schedule(schedule, workdir=str(tmp_path / "b"))
    assert a.ok and b.ok
    assert a.journal_digest == b.journal_digest
    assert (a.commits, a.all_ok, a.safety_ok) == (
        b.commits,
        b.all_ok,
        b.safety_ok,
    )
    ja = (tmp_path / "a" / "journal.jsonl").read_bytes()
    jb = (tmp_path / "b" / "journal.jsonl").read_bytes()
    assert ja == jb and ja


def test_draw_schedule_is_pure():
    assert draw_schedule(7, nodes=4) == draw_schedule(7, nodes=4)
    assert draw_schedule(7, nodes=4) != draw_schedule(8, nodes=4)


# ---- crash-point injection --------------------------------------------


def test_crash_injection_torn_tail_recovery(tmp_path):
    """A mid-run crash leaves a torn WAL tail (complete header, missing
    body); the restart must recover through WAL replay + state-sync and
    the committee must still pass every invariant."""
    schedule = {
        "version": SCHEDULE_VERSION,
        "seed": 12345,
        "nodes": 4,
        "duration_s": 9.0,
        "profile": "honest",
        "events": [
            {
                "kind": "crash",
                "node": 2,
                "at": 2.0,
                "restart_at": 4.0,
                "torn_bytes": 33,
            }
        ],
    }
    verdict = run_schedule(schedule, workdir=str(tmp_path))
    assert verdict.ok, verdict.failures
    assert verdict.commits > 0
    # the torn tail really landed and recovery really ran: the journal
    # records both halves of the injected crash
    journal = (tmp_path / "journal.jsonl").read_text()
    assert "node 2 crashed (torn tail 33B)" in journal
    assert "node 2 restarted" in journal


# ---- shrinker ----------------------------------------------------------


def test_shrinker_converges_on_planted_safety_bug():
    """Plant a collusion event inside an otherwise-honest schedule: the
    run must FAIL (profile 'honest' tolerates no divergence), and the
    shrinker must strip the innocent link noise down to exactly the
    planted event."""
    schedule = draw_schedule(48, nodes=4)  # honest, several link events
    assert schedule["profile"] == "honest"
    planted = {
        "kind": "byz",
        "policy": "collude",
        "nodes": [0, 1],
        "at": 1.0,
        "until": None,
    }
    schedule["events"] = schedule["events"] + [planted]
    verdict = run_schedule(schedule)
    assert not verdict.ok
    assert not verdict.safety_ok
    minimal = shrink(schedule)
    assert minimal["events"] == [planted]
    # the minimal schedule still reproduces, and removing the planted
    # event really is what makes it pass again
    assert not run_schedule(minimal).ok
    clean = dict(minimal, events=[])
    assert run_schedule(clean).ok


# ---- regression corpus ------------------------------------------------


def _corpus():
    with open(CORPUS) as f:
        corpus = json.load(f)
    assert corpus["version"] == SCHEDULE_VERSION, (
        "sim_seeds.json predates a schedule-format bump: re-derive the "
        "corpus expectations"
    )
    return corpus


@pytest.mark.parametrize(
    "entry", _corpus()["entries"], ids=lambda e: f"seed-{e['seed']}"
)
def test_regression_corpus(entry):
    """Every seed that ever produced an invariant failure during the sim
    plane's development, replayed against today's tree.  Entries with an
    inline ``schedule`` were promoted by the guided adversary search
    (docs/FAULTS.md): those must replay to the SAME verdict, the same
    threat set, and a byte-identical journal digest."""
    if "schedule" in entry:
        schedule = entry["schedule"]
        assert schedule["profile"] == entry["profile"]
        verdict = run_schedule(schedule)
        assert verdict.ok == entry["ok"], (entry["note"], verdict.failures)
        assert list(verdict.threats) == list(entry.get("threats", [])), (
            entry["note"],
            verdict.threats,
        )
        assert verdict.journal_digest == entry["journal_digest"], (
            entry["note"],
            "journal digest diverged from the promoted counterexample",
        )
        return
    schedule = draw_schedule(entry["seed"], nodes=_corpus()["nodes"])
    assert schedule["profile"] == entry["profile"]
    verdict = run_schedule(schedule)
    assert verdict.ok == entry["ok"], (entry["note"], verdict.failures)


# ---- ported e2e: crash + restart under partition ----------------------


def test_crash_restart_under_partition(tmp_path):
    """Virtual-time port of the subprocess e2e in
    tests/test_crash_rejoin_e2e.py (~150 s real time there): a crash
    INSIDE a split-brain window, and a rejoin inside a SECOND partition
    that isolates node 1 — the restarted node 3 must recover from its
    torn store via the reachable peers {0, 2} and its return restores
    the quorum.  Same fault geometry, same invariant stack, no ``slow``
    marker."""
    schedule = {
        "version": SCHEDULE_VERSION,
        "seed": 11,
        "nodes": 4,
        "duration_s": 12.0,
        "profile": "honest",
        "events": [
            # split-brain 0,1|2,3; node 3 crashes just as it bites,
            # leaving 2|1 — no quorum anywhere until the heal
            {
                "kind": "partition",
                "groups": [[0, 1], [2, 3]],
                "at": 1.5,
                "until": 3.5,
            },
            {
                "kind": "crash",
                "node": 3,
                "at": 1.6,
                "restart_at": 5.0,
                "torn_bytes": 24,
            },
            # second window: node 1 drops off while node 3 is still
            # down ({0,2} alone are below quorum); node 3 restarts
            # INSIDE this window and must resync from {0, 2}
            {
                "kind": "partition",
                "groups": [[0, 2, 3], [1]],
                "at": 4.5,
                "until": 7.5,
            },
        ],
    }
    verdict = run_schedule(schedule, workdir=str(tmp_path))
    assert verdict.ok, verdict.failures
    assert verdict.all_ok and verdict.safety_ok
    assert verdict.commits > 0
    journal = (tmp_path / "journal.jsonl").read_text()
    assert "node 3 crashed (torn tail 24B)" in journal
    assert "node 3 restarted" in journal
    # commits resumed after the last heal (t=7.5): liveness-after-heal
    # is part of check_run, but assert the rejoined node specifically
    # committed in its second lifetime
    node3 = (tmp_path / "logs" / "node-3.log").read_text()
    assert "Committed block" in node3
