"""Byzantine adversary plane (hotstuff_tpu/faults/adversary.py).

Unit tier: determinism from one seed, schedule gating, per-policy attack
math (shadow branch, equivocation targets, forged certificates), and the
checker layer (attribution + trusted-subset quorum re-check).

E2E tier: a live in-process 4-committee with the adversary plane armed
through the production ``HOTSTUFF_ADVERSARY`` knob — an equivocating
leader cannot stop the honest committee committing consistently, a
withholding node costs rounds but not safety, and a colluding pair
produces a real divergent history the invariant checker FAILs and
attributes.
"""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from benchmark.invariants import (
    adversaries_from_spec,
    attribute_violations,
    byz_activity_from_logs,
    check_run,
    check_safety,
    trusted_subset_recheck,
)
from hotstuff_tpu.faults.adversary import (
    POLICIES,
    AdversaryPlane,
    AdversaryRule,
    expand_adversary,
)
from hotstuff_tpu.faults.scenarios import SCENARIOS, build, last_heal

from .common import async_test, committee, fresh_base_port, keys
from .test_consensus_e2e import _feed_producers, _shutdown, _spawn_committee


def _spec(policy="equivocate", nodes=0, at=0.0, until=None, seed=3,
          base=9_900, n=4):
    return {
        "name": f"byz-{policy}",
        "seed": seed,
        "epoch_unix": time.time(),
        "nodes": {f"127.0.0.1:{base + i}": i for i in range(n)},
        "adversary": [
            {"policy": policy, "node": nodes, "at": at, "until": until}
        ],
    }


# ---- determinism ------------------------------------------------------------


def test_same_seed_same_attack_stream():
    """Two planes built from the same spec on the same slot draw
    identical randomness: forged certificates, shadow payloads, and the
    rng stream itself are replayable from (seed, node index) alone."""
    spec = _spec("forge-qc")
    com = committee(9_900)
    a = AdversaryPlane(spec, ("127.0.0.1", 9_900))
    b = AdversaryPlane(spec, ("127.0.0.1", 9_900))
    for rnd in (1, 2, 17):
        qa, qb = a.forged_qc(com, rnd), b.forged_qc(com, rnd)
        assert qa.hash == qb.hash
        assert [pk for pk, _ in qa.votes] == [pk for pk, _ in qb.votes]
        assert [s.to_bytes() for _, s in qa.votes] == [
            s.to_bytes() for _, s in qb.votes
        ]
        assert a.shadow_payloads(rnd) == b.shadow_payloads(rnd)
    # a different slot (or seed) diverges
    c = AdversaryPlane(_spec("forge-qc", nodes=1), ("127.0.0.1", 9_901))
    assert [s.to_bytes() for _, s in c.forged_qc(com, 1).votes] != [
        s.to_bytes() for _, s in a.forged_qc(com, 1).votes
    ]
    d = AdversaryPlane(_spec("forge-qc", seed=4), ("127.0.0.1", 9_900))
    assert d.shadow_payloads(1) != a.shadow_payloads(1)


def test_forged_qc_passes_weight_but_fails_verification():
    """The forged certificate is the whole point of the forge-qc policy:
    structurally a quorum (real authors, 2f+1 stake, no reuse) so it
    survives check_weight, with garbage signatures so honest
    verification must reject it."""
    from hotstuff_tpu.consensus.errors import ConsensusError
    from hotstuff_tpu.crypto.service import CpuVerifier

    com = committee(9_910)
    plane = AdversaryPlane(_spec("forge-qc", base=9_910), ("127.0.0.1", 9_910))
    qc = plane.forged_qc(com, 5)
    qc.check_weight(com)  # must NOT raise
    with pytest.raises(ConsensusError):
        qc.verify(com, CpuVerifier())


# ---- scheduling -------------------------------------------------------------


def test_schedule_gating_and_selection():
    spec = _spec("withhold", at=2.0, until=6.0)
    epoch = spec["epoch_unix"]
    plane = AdversaryPlane(spec, ("127.0.0.1", 9_900))
    assert plane.enabled
    assert not plane.active("withhold", now=epoch + 1.9)
    assert plane.active("withhold", now=epoch + 2.0)
    assert plane.active("withhold", now=epoch + 5.9)
    assert not plane.active("withhold", now=epoch + 6.0)
    # other policies never fire from this rule
    assert not plane.active("equivocate", now=epoch + 3.0)
    # a node the spec does not name is inert forever
    honest = AdversaryPlane(spec, ("127.0.0.1", 9_901))
    assert not honest.enabled
    assert not honest.active("withhold", now=epoch + 3.0)
    # window edges feed the adversary clock in order
    assert plane.window_edges() == [(2.0, "open", "withhold"),
                                    (6.0, "close", "withhold")]


def test_collude_implies_equivocate_and_double_vote():
    spec = _spec("collude", nodes=[0, 1], at=1.0)
    epoch = spec["epoch_unix"]
    plane = AdversaryPlane(spec, ("127.0.0.1", 9_901))
    for policy in ("collude", "equivocate", "double-vote"):
        assert plane.active(policy, now=epoch + 1.5), policy
    assert not plane.active("withhold", now=epoch + 1.5)
    assert plane.colluders == [0, 1]
    # shadow committer = highest-indexed colluder, deterministically
    assert plane.is_shadow_committer
    assert not AdversaryPlane(spec, ("127.0.0.1", 9_900)).is_shadow_committer


def test_unknown_policy_rejected():
    with pytest.raises(ValueError):
        AdversaryRule("bribe", 0)
    with pytest.raises(ValueError):
        expand_adversary({"adversary": [{"policy": "nope", "node": 0}]})


def test_canned_byz_scenarios_registered():
    for name in ("byz-equivocate", "byz-forge-qc", "byz-withhold",
                 "byz-collude"):
        assert name in SCENARIOS
        spec = build(name, nodes=4, seed=11)
        assert spec["adversary"], name
        for rule in expand_adversary(spec):
            assert rule.policy in POLICIES
    # only withhold impairs liveness; open-ended windows push the last
    # heal to infinity so the checker treats liveness as n/a
    import math

    assert last_heal(build("byz-withhold", nodes=4, seed=0)) == 12.0
    assert last_heal(build("byz-equivocate", nodes=4, seed=0)) == 0.0
    assert not math.isinf(last_heal(build("byz-collude", nodes=4, seed=0)))
    assert build("byz-collude", nodes=4, seed=0)["quorum_mode"] == (
        "trusted-subset"
    )


# ---- attack math ------------------------------------------------------------


def test_shadow_branch_agrees_across_colluders_without_communication():
    """Both colluders derive the same conflicting twin for a received
    block from (seed, round) alone — the block digest excludes the
    signature, so no coordination round-trip is needed."""
    from .common import signed_block

    spec = _spec("collude", nodes=[0, 1])
    a = AdversaryPlane(spec, ("127.0.0.1", 9_900))
    b = AdversaryPlane(spec, ("127.0.0.1", 9_901))
    pk, sk = keys()[2]
    block = signed_block(pk, sk, 7)
    sa, sb = a.shadow_block(block), b.shadow_block(block)
    assert sa.digest() == sb.digest()
    assert sa.digest() != block.digest()
    assert sa.round == block.round and sa.author == block.author


def test_equivocation_targets():
    com = committee(9_920)
    fixture = keys()
    self_name = fixture[0][0]
    pairs = com.broadcast_addresses(self_name)
    # solo equivocator: deterministic first half of the peer set
    solo = AdversaryPlane(_spec("equivocate", base=9_920),
                          ("127.0.0.1", 9_920))
    targets = solo.equivocation_targets(pairs)
    assert targets == sorted(pairs, key=lambda p: str(p[0]))[: len(pairs) // 2]
    # colluding equivocator: only fellow colluders see the shadow block
    spec = _spec("collude", nodes=[0, 1], base=9_920)
    plane = AdversaryPlane(spec, ("127.0.0.1", 9_920))
    plane.bind(com, self_name)
    targets = plane.equivocation_targets(pairs)
    assert [nm for nm, _ in targets] == [fixture[1][0]]


# ---- checker layer ----------------------------------------------------------


def test_attribution_names_adversaries_and_trusted_subset_recovers():
    spec = _spec("collude", nodes=[0, 1])
    advs = adversaries_from_spec(spec, {0: "aa11", 1: "bb22"})
    assert set(advs) == {"node-0", "node-1"}
    commits = {
        "node-0": [(1.0, 4, "MAIN")],
        "node-1": [(1.0, 4, "SHADOW")],
        "node-2": [(1.0, 4, "MAIN")],
        "node-3": [(1.0, 4, "MAIN")],
    }
    ok, viol = check_safety(commits)
    assert not ok
    attributed = attribute_violations(viol, advs)
    assert "node-1" in attributed[0] and "collude" in attributed[0]
    assert "bb22" in attributed[0]
    # TEE-style trusted-subset quorum: discard the adversarial
    # histories and the survivors agree
    t_ok, t_viol = trusted_subset_recheck(commits, set(advs))
    assert t_ok, t_viol


def test_check_run_fails_collusion_and_renders_byz_block(tmp_path):
    """The full log-scrape path: a shadow-committing colluder makes the
    run FAIL on full history, with the violation attributed and the
    trusted-subset recheck PASSing in the rendered + BYZ block."""
    epoch = time.time() - 30.0
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(epoch + 5.0))
    line = f"[{stamp}.000Z] node INFO: Committed block {{r}} -> {{d}}\n"
    logs = tmp_path / "logs"
    logs.mkdir()
    for i in range(4):
        digest = "SHADOW9" if i == 1 else "MAIN447"
        content = line.format(r=3, d=digest)
        content += "byz equivocate round 3 -> SHADOW9 | x (1 peers)\n" if i < 2 else ""
        (logs / f"node-{i}.log").write_text(content)
    (logs / "node-3.log").write_text(
        (logs / "node-3.log").read_text()
        + "qc reject: invalid certificate in timeout from x round 2\n"
        + "second digest cell paid by y\n"
    )
    spec = build("byz-collude", nodes=4, seed=0)
    ok, block = check_run(str(logs), spec, epoch,
                          authorities={0: "aa11", 1: "bb22"})
    assert not ok
    assert "+ BYZ:" in block
    assert "FAIL" in block
    assert "[adversary:" in block and "bb22" in block
    assert "Trusted-subset quorum (adversaries excluded): PASS" in block
    activity = byz_activity_from_logs(str(logs))
    assert activity["node-0"].get("equivocate") == 1
    assert activity["node-3"] == {"qc_reject": 1, "vote_conflict": 1}


# ---- e2e: the plane on a live committee -------------------------------------


def _arm(monkeypatch, base, policy, nodes, at=0.5, until=None, seed=5):
    spec = _spec(policy, nodes=nodes, at=at, until=until, seed=seed,
                 base=base)
    monkeypatch.setenv("HOTSTUFF_ADVERSARY", json.dumps(spec))
    return spec


async def _consistent_chains(nodes, per_node=4, timeout=40.0):
    chains = []
    for _, commit_q, _ in nodes:
        committed = []
        while len(committed) < per_node:
            b = await asyncio.wait_for(commit_q.get(), timeout=timeout)
            if b.round > 0:
                committed.append(b)
        chains.append(committed)
    digests = [[b.digest() for b in chain] for chain in chains]
    common_len = min(len(d) for d in digests)
    for d in digests[1:]:
        assert d[:common_len] == digests[0][:common_len]
    return chains


@async_test
async def test_equivocating_leader_commits_within_deadline(
    tmp_path, monkeypatch
):
    """The production knob end to end: node 0 equivocates every time it
    leads, yet the honest committee keeps committing a consistent chain
    — and the plane actually attacked (counted equivocations)."""
    base = fresh_base_port()
    _arm(monkeypatch, base, "equivocate", 0, at=0.0)
    nodes = await _spawn_committee(tmp_path, base, range(4),
                                   timeout_delay=1_000)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    try:
        await _consistent_chains(nodes, per_node=4)
        plane = nodes[0][0].core.adversary
        assert plane is not None and plane.enabled
        deadline = time.time() + 20.0
        while plane.counts["byz_equivocations"] == 0 and time.time() < deadline:
            await asyncio.sleep(0.25)
        assert plane.counts["byz_equivocations"] > 0
        assert nodes[1][0].core.adversary is None  # honest slots stay clean
    finally:
        await _shutdown(nodes, feeder)


@async_test
async def test_withholding_node_costs_rounds_not_safety(
    tmp_path, monkeypatch
):
    """Withhold: node 0 receives but never votes inside its window; the
    3-of-4 honest quorum keeps committing, and the attacker counted the
    votes it swallowed."""
    base = fresh_base_port()
    _arm(monkeypatch, base, "withhold", 0, at=0.0, until=None)
    nodes = await _spawn_committee(tmp_path, base, range(4),
                                   timeout_delay=800)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    try:
        await _consistent_chains(nodes, per_node=3)
        plane = nodes[0][0].core.adversary
        assert plane is not None
        assert plane.counts["byz_votes_withheld"] > 0
    finally:
        await _shutdown(nodes, feeder)


@async_test
async def test_double_vote_parks_on_honest_aggregator(tmp_path, monkeypatch):
    """Double-vote: the attacker's conflicting vote reaches an honest
    next leader whose aggregator must park it as a second paid digest
    cell — surfaced as the vote_conflicts defense counter."""
    base = fresh_base_port()
    _arm(monkeypatch, base, "double-vote", 0, at=0.0)
    nodes = await _spawn_committee(tmp_path, base, range(4),
                                   timeout_delay=1_000)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    try:
        await _consistent_chains(nodes, per_node=4)
        plane = nodes[0][0].core.adversary
        assert plane is not None
        deadline = time.time() + 20.0
        while plane.counts["byz_double_votes"] == 0 and time.time() < deadline:
            await asyncio.sleep(0.25)
        assert plane.counts["byz_double_votes"] > 0
        conflicts = sum(
            stack.core.aggregator.vote_conflicts
            for stack, _, _ in nodes[1:]
        )
        assert conflicts > 0, "no honest aggregator parked the double vote"
    finally:
        await _shutdown(nodes, feeder)


@async_test
async def test_colluding_pair_produces_attributable_divergence(
    tmp_path, monkeypatch
):
    """Collude e2e: nodes 0+1 run the shadow-branch suite; the shadow
    committer (node 1) reports shadow digests for colluder-authored
    commits, so the commit streams REALLY diverge — exactly what the
    safety checker must catch and pin on the colluders."""
    base = fresh_base_port()
    _arm(monkeypatch, base, "collude", [0, 1], at=0.0, seed=9)
    nodes = await _spawn_committee(tmp_path, base, range(4),
                                   timeout_delay=1_000)
    feeder = asyncio.ensure_future(_feed_producers(nodes))
    records: dict[str, list[tuple[float, int, str]]] = {
        f"node-{i}": [] for i in range(4)
    }

    async def collect(i, commit_q):
        while True:
            block = await commit_q.get()
            plane = nodes[i][0].core.adversary
            digest = block.digest()
            # commit queues carry the true blocks; mirror the shadow
            # committer's LOG view (core._commit), which is what the
            # checker scrapes in production
            if (
                plane is not None
                and plane.is_shadow_committer
                and block.author in plane.colluder_names
            ):
                digest = plane.shadow_block(block).digest()
            records[f"node-{i}"].append((time.time(), block.round, str(digest)))

    collectors = [
        asyncio.ensure_future(collect(i, commit_q))
        for i, (_, commit_q, _) in enumerate(nodes)
    ]
    try:
        shadow_plane = nodes[1][0].core.adversary
        assert shadow_plane is not None and shadow_plane.is_shadow_committer
        deadline = time.time() + 45.0
        diverged = False
        while time.time() < deadline:
            ok, viol = check_safety(records)
            if not ok:
                diverged = True
                break
            await asyncio.sleep(0.5)
        assert diverged, "colluders never produced a divergent history"
        advs = adversaries_from_spec(
            {"adversary": [{"policy": "collude", "nodes": [0, 1]}]}
        )
        attributed = attribute_violations(viol, advs)
        assert any("collude" in v for v in attributed)
        # the honest majority still agrees once colluders are discarded
        t_ok, t_viol = trusted_subset_recheck(records, {"node-0", "node-1"})
        assert t_ok, t_viol
    finally:
        for c in collectors:
            c.cancel()
        await _shutdown(nodes, feeder)
