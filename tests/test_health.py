"""Live fleet health-plane tests (ISSUE 13).

Covers the tentpole pieces — the ``/delta`` wire format
(flatten/DeltaStream/DeltaDecoder), every online anomaly detector as a
pure function over fixture windows (fires / does not fire / boundary),
the bounded campaign recorder, the per-node HealthMonitor incident
lifecycle (open/close hysteresis, journal edges, log lines), and the
scraper side (NodeFeed resync, FleetWatcher STALE handling, dashboard
rendering) — plus two slow end-to-end runs: leader-stall under the
canned ``leader-isolation`` chaos scenario and shed-storm under an
open-loop producer past admission capacity.
"""

from __future__ import annotations

import asyncio
import glob
import json
import os

import pytest

from hotstuff_tpu import telemetry
from hotstuff_tpu.telemetry.health import (
    CAMPAIGN_SUFFIX,
    CLEAR_AFTER,
    DELTA_HISTORY,
    HEALTH_EDGE_PREFIX,
    HEALTH_KINDS,
    CampaignRecorder,
    DeltaDecoder,
    DeltaStream,
    HealthMonitor,
    Incident,
    Window,
    commit_collapse,
    flatten,
    leader_stall,
    rate,
    root_divergence,
    shed_storm,
    straggler,
    view_change_storm,
)
from hotstuff_tpu.telemetry.taxonomy import (
    HEALTH_PREFIX,
    is_registered_edge,
)

from .common import async_test, committee, fresh_base_port, keys


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    """Telemetry state is process-global: every test starts disabled
    with an empty registry and leaves it that way."""
    monkeypatch.delenv("HOTSTUFF_TELEMETRY", raising=False)
    monkeypatch.delenv("HOTSTUFF_METRICS_PORT", raising=False)
    monkeypatch.delenv("HOTSTUFF_HEALTH", raising=False)
    monkeypatch.delenv("HOTSTUFF_JOURNAL", raising=False)
    monkeypatch.delenv("HOTSTUFF_JOURNAL_DIR", raising=False)
    telemetry.reset()
    yield
    telemetry.reset()


# ---- taxonomy contract -----------------------------------------------------


def test_health_edges_are_taxonomy_registered():
    """Every incident kind journals a registered ``health.*`` edge —
    the PR 12 lint gate must accept the whole dynamic family."""
    assert HEALTH_EDGE_PREFIX == HEALTH_PREFIX
    for kind in HEALTH_KINDS:
        assert is_registered_edge(f"health.{kind}")


# ---- delta-frame wire format ----------------------------------------------


def test_flatten_nested_lists_and_dropped_leaves():
    doc = {
        "a": {"b": 1, "c": {"d": "x"}},
        "lst": [10, {"k": True}],
        "none": None,
        "obj": object(),
        "f": 2.5,
    }
    assert flatten(doc) == {
        "a.b": 1,
        "a.c.d": "x",
        "lst.0": 10,
        "lst.1.k": True,
        "f": 2.5,
    }


def test_delta_stream_full_then_deltas():
    s = DeltaStream()
    doc = {"n": {"x": 1, "y": 2}}
    first = s.frame(doc, since=-1)
    assert first == {"seq": 1, "full": {"n.x": 1, "n.y": 2}}

    # unchanged state: seq does not advance, the delta is empty
    again = s.frame(doc, since=first["seq"])
    assert again == {"seq": 1, "base": 1, "set": {}, "del": []}

    # one key changed, one removed: the delta is O(changed)
    delta = s.frame({"n": {"x": 5}}, since=1)
    assert delta["seq"] == 2
    assert delta["base"] == 1
    assert delta["set"] == {"n.x": 5}
    assert delta["del"] == ["n.y"]


def test_delta_stream_unknown_since_serves_full():
    s = DeltaStream()
    s.frame({"a": 1}, since=-1)
    # a since the server never issued (ahead of seq) falls back to full
    frame = s.frame({"a": 2}, since=99)
    assert "full" in frame and frame["full"] == {"a": 2}


def test_delta_stream_history_overflow_serves_full():
    s = DeltaStream(history=DELTA_HISTORY)
    s.frame({"v": 0}, since=-1)
    for i in range(1, DELTA_HISTORY + 2):
        s.frame({"v": i}, since=-1)
    # seq 1 has fallen off the history ring: full frame, not a bad delta
    frame = s.frame({"v": 999}, since=1)
    assert "full" in frame


def test_delta_decoder_roundtrip_and_gap_resync():
    s = DeltaStream()
    d = DeltaDecoder()
    state = d.apply(s.frame({"a": 1, "b": 2}, since=d.since))
    assert state == {"a": 1, "b": 2}
    state = d.apply(s.frame({"a": 1, "c": 3}, since=d.since))
    assert state == {"a": 1, "c": 3}
    assert d.resyncs == 0

    # a delta against a base we do not hold: drop state, request full
    out = d.apply({"seq": 9, "base": 7, "set": {"x": 1}, "del": []})
    assert out is None
    assert d.resyncs == 1
    assert d.since == -1
    assert d.state == {}
    # the follow-up full frame recovers cleanly
    assert d.apply(s.frame({"a": 1, "c": 3}, since=d.since)) == {
        "a": 1,
        "c": 3,
    }


# ---- windows ---------------------------------------------------------------


def test_window_trims_by_span_and_capacity():
    w = Window(span_s=5.0, capacity=4)
    for t in range(10):
        w.push(float(t), float(t))
    # capacity 4 wins over the 5 s span here
    assert len(w) == 4
    assert w.samples()[0] == (6.0, 6.0)
    w2 = Window(span_s=2.0, capacity=100)
    for t in range(10):
        w2.push(float(t), 0.0)
    assert all(9.0 - t <= 2.0 for t, _ in w2.samples())


def test_rate_needs_two_samples_spanning_time():
    assert rate([]) is None
    assert rate([(0.0, 1.0)]) is None
    assert rate([(1.0, 0.0), (1.0, 5.0)]) is None
    assert rate([(0.0, 0.0), (4.0, 8.0)]) == pytest.approx(2.0)


# ---- detectors: leader stall ----------------------------------------------


def test_leader_stall_cold_start_never_fires():
    # window covers less than k x timeout: no verdict even with no
    # progress at all
    samples = [(0.0, 5.0), (1.0, 5.0), (2.0, 5.0)]
    assert leader_stall(samples, now=2.5, timeout_s=1.0, k=3.0) is None


def test_leader_stall_progressing_never_fires():
    samples = [(float(t), float(t)) for t in range(10)]
    assert leader_stall(samples, now=9.0, timeout_s=1.0, k=3.0) is None


def test_leader_stall_fires_past_threshold_with_boundary():
    samples = [(0.0, 5.0), (1.0, 6.0)] + [
        (float(t), 6.0) for t in range(2, 10)
    ]
    # last advance at t=1, horizon 3 s: stalled 2.9 s at now=3.9 -> no
    assert leader_stall(samples, now=3.9, timeout_s=1.0, k=3.0) is None
    # exactly at the boundary it fires (stalled == horizon)
    inc = leader_stall(samples, now=4.0, timeout_s=1.0, k=3.0, node="n2")
    assert inc is not None
    assert inc.kind == "leader_stall"
    assert inc.severity == "crit"
    assert inc.node == "n2"
    assert inc.value == pytest.approx(3.0)


def test_leader_stall_empty_window():
    assert leader_stall([], now=100.0, timeout_s=1.0) is None


# ---- detectors: view-change storm -----------------------------------------


def test_view_storm_first_rate_seeds_baseline():
    inc, ewma = view_change_storm([(0.0, 0.0), (10.0, 5.0)], None)
    assert inc is None
    assert ewma == pytest.approx(0.5)


def test_view_storm_quiet_ticks_update_ewma():
    inc, ewma = view_change_storm(
        [(0.0, 0.0), (10.0, 10.0)], baseline_ewma=1.0, alpha=0.3
    )
    assert inc is None
    # rate 1.0 == baseline: EWMA absorbs it unchanged
    assert ewma == pytest.approx(1.0)


def test_view_storm_fires_and_freezes_baseline():
    # rate 5/s vs baseline 1/s (> 4x): fires, baseline NOT updated (a
    # storm must not normalize itself)
    inc, ewma = view_change_storm(
        [(0.0, 0.0), (2.0, 10.0)], baseline_ewma=1.0
    )
    assert inc is not None
    assert inc.kind == "view_storm"
    assert inc.severity == "warn"
    assert inc.value == pytest.approx(5.0)
    assert ewma == pytest.approx(1.0)


def test_view_storm_min_rate_floors_trigger():
    # 0.4/s is >4x a 0.01 baseline but under the absolute floor
    inc, _ = view_change_storm(
        [(0.0, 0.0), (10.0, 4.0)], baseline_ewma=0.01, min_rate=0.5
    )
    assert inc is None


# ---- detectors: commit collapse -------------------------------------------


def test_commit_collapse_needs_four_samples():
    assert commit_collapse([(0.0, 0.0), (1.0, 0.0), (2.0, 0.0)]) is None


def test_commit_collapse_steady_rate_never_fires():
    samples = [(float(t), 10.0 * t) for t in range(10)]
    assert commit_collapse(samples) is None


def test_commit_collapse_fires_on_collapse():
    # 10/s for the first half, flat after the midpoint
    samples = [(float(t), 10.0 * min(t, 5)) for t in range(11)]
    inc = commit_collapse(samples, node="n0")
    assert inc is not None
    assert inc.kind == "commit_collapse"
    assert inc.severity == "crit"
    assert inc.node == "n0"


def test_commit_collapse_quiet_baseline_never_fires():
    # an idle committee (0.1/s) going fully idle is not a collapse
    samples = [(float(t * 10), min(t, 5) * 1.0) for t in range(11)]
    assert commit_collapse(samples, min_baseline_rate=1.0) is None


# ---- detectors: straggler --------------------------------------------------


def test_straggler_fires_on_round_lag():
    rounds = {
        "n0": (10.0, 100.0),
        "n1": (10.0, 99.0),
        "n2": (10.0, 80.0),
    }
    out = straggler(rounds, {}, now=10.0, lag_rounds=16.0)
    assert [i.node for i in out] == ["n2"]
    assert out[0].kind == "straggler"
    assert out[0].value == pytest.approx(20.0)


def test_straggler_stale_samples_excluded():
    # n2's sample is 20 s old: the STALE column's problem, not a lag
    rounds = {
        "n0": (10.0, 100.0),
        "n1": (10.0, 99.0),
        "n2": (-10.0, 10.0),
    }
    assert straggler(rounds, {}, now=10.0) == []


def test_straggler_clock_offset_keeps_skewed_node_fresh():
    # n2's clock runs 20 s behind: its sample time looks ancient but
    # the offset correction keeps it in the fresh set
    rounds = {
        "n0": (10.0, 100.0),
        "n1": (10.0, 99.0),
        "n2": (-10.0, 10.0),
    }
    out = straggler(rounds, {"n2": -20.0}, now=10.0)
    assert [i.node for i in out] == ["n2"]


def test_straggler_needs_two_fresh_nodes():
    assert straggler({"n0": (10.0, 100.0)}, {}, now=10.0) == []


# ---- detectors: shed storm -------------------------------------------------


def test_shed_storm_fires_on_rate_and_total():
    inc = shed_storm([(0.0, 0.0), (2.0, 100.0)], node="n3")
    assert inc is not None
    assert inc.kind == "shed_storm"
    assert inc.node == "n3"
    assert inc.value == pytest.approx(50.0)


def test_shed_storm_min_shed_suppresses_edge_burst():
    # 8 sheds over 0.1 s is a 80/s rate but under the absolute minimum
    inc = shed_storm([(0.0, 0.0), (0.1, 8.0)], min_shed=10)
    assert inc is None
    assert shed_storm([(0.0, 0.0), (10.0, 50.0)], rate_threshold=20.0) is None


# ---- detectors: root divergence -------------------------------------------


def test_root_divergence_agreement_is_quiet():
    roots = {"n0": (7, "aa"), "n1": (7, "aa"), "n2": (6, "bb")}
    assert root_divergence(roots) == []


def test_root_divergence_fires_once_per_version():
    roots = {
        "n0": (7, "a" * 32),
        "n1": (7, "b" * 32),
        "n2": (7, "a" * 32),
    }
    out = root_divergence(roots)
    assert len(out) == 1
    inc = out[0]
    assert inc.kind == "root_divergence"
    assert inc.severity == "crit"
    assert inc.node == ""  # fleet-wide
    assert "version 7" in inc.detail
    assert "n0,n2" in inc.detail
    assert inc.value == pytest.approx(7.0)


def test_root_divergence_different_versions_not_compared():
    # a lagging node at an older version is NOT divergence
    roots = {"n0": (7, "aa"), "n1": (6, "bb")}
    assert root_divergence(roots) == []


# ---- campaign recorder -----------------------------------------------------


def test_campaign_recorder_interval_gate_and_bound(tmp_path):
    rec = CampaignRecorder("n0", interval_s=1.0, capacity=8)
    assert rec.sample(0.0, {"round": 1})
    assert not rec.sample(0.5, {"round": 2})  # gate closed
    assert rec.sample(1.0, {"round": 3})
    assert len(rec) == 2
    for t in range(2, 50):
        rec.sample(float(t), {"round": t})
    assert len(rec) == 8  # ring bound holds


def test_campaign_recorder_persist_roundtrip(tmp_path):
    path = str(tmp_path / f"n0{CAMPAIGN_SUFFIX}")
    rec = CampaignRecorder("n0", path=path, interval_s=1.0)
    rec.sample(1.0, {"round": 4, "commits": 10.0})
    rec.sample(2.0, {"round": 5, "commits": 12.0})
    assert rec.persist() == path
    doc = CampaignRecorder.load(path)
    assert doc["node"] == "n0"
    assert doc["interval_s"] == 1.0
    assert [s["round"] for s in doc["samples"]] == [4, 5]
    # the journal loader must never pick the campaign up: its glob is
    # *.jsonl and the suffix is .json
    assert not glob.glob(str(tmp_path / "*.jsonl"))
    assert not path.endswith(".jsonl")


def test_campaign_recorder_no_path_is_a_noop():
    rec = CampaignRecorder("n0")
    rec.sample(0.0, {"round": 1})
    assert rec.persist() is None


# ---- health monitor --------------------------------------------------------


class FakeJournal:
    def __init__(self):
        self.records = []

    def record(self, event, round_=0, digest=None, peer="", dur_ns=None):
        self.records.append((event, round_, peer))


class FakeTel:
    """A snapshot-bearing telemetry stand-in the monitor samples."""

    def __init__(self):
        self.journal = FakeJournal()
        self.doc = {
            "trace": {"commits": 0, "tc_advances": 0, "last_commit_round": 0},
            "ingest": {"shed_total": 0, "last_credit": 64},
            "state": {"version": 0},
        }

    def snapshot(self):
        return json.loads(json.dumps(self.doc))


class FakeLogger:
    def __init__(self):
        self.lines = []

    def info(self, msg, *args):
        self.lines.append(msg % args)

    warning = info


def test_monitor_shed_storm_open_close_hysteresis():
    tel = FakeTel()
    logger = FakeLogger()
    # a huge timeout keeps leader_stall's cold-start guard shut for the
    # whole fixture run: this test isolates the shed path
    mon = HealthMonitor(tel, "n0", timeout_s=100.0, logger=logger)

    mon.tick(0.0)
    tel.doc["ingest"]["shed_total"] = 60  # 60/s over 1 s
    tel.doc["trace"]["last_commit_round"] = 9
    fired = mon.tick(1.0)
    assert [i.kind for i in fired] == ["shed_storm"]
    assert [i.kind for i in mon.open_incidents()] == ["shed_storm"]
    assert tel.journal.records == [("health.shed_storm", 9, "open")]
    assert any(
        '"kind": "shed_storm"' in ln and '"phase": "open"' in ln
        for ln in logger.lines
    )

    # still firing: no duplicate open edge
    tel.doc["ingest"]["shed_total"] = 120
    mon.tick(2.0)
    assert len(tel.journal.records) == 1

    # shed flattens: the incident survives CLEAR_AFTER-1 quiet ticks,
    # then closes exactly once
    for t in range(3, 3 + CLEAR_AFTER + 2):
        mon.tick(float(t + 60))  # jump past the window so rate drops
    assert mon.open_incidents() == []
    assert tel.journal.records[-1] == ("health.shed_storm", 9, "close")
    assert (
        sum(1 for e, _, p in tel.journal.records if p == "close") == 1
    )


def test_monitor_leader_stall_fires_on_frozen_commits():
    tel = FakeTel()
    tel.doc["trace"]["commits"] = 5
    mon = HealthMonitor(tel, "n1", timeout_s=1.0, logger=FakeLogger())
    for t in range(4):
        mon.tick(float(t))
    assert "leader_stall" in {i.kind for i in mon.open_incidents()}


def test_monitor_campaign_samples_and_close_persists(tmp_path):
    path = str(tmp_path / f"n0{CAMPAIGN_SUFFIX}")
    tel = FakeTel()
    mon = HealthMonitor(
        tel, "n0", timeout_s=100.0, campaign_path=path, logger=FakeLogger()
    )
    tel.doc["trace"]["commits"] = 7
    tel.doc["state"]["version"] = 3
    for t in range(5):
        mon.tick(float(t))
    assert len(mon.recorder) == 5
    mon.close()
    doc = CampaignRecorder.load(path)
    assert doc["samples"][-1]["commits"] == 7.0
    assert doc["samples"][-1]["version"] == 3
    assert set(doc["samples"][0]) >= {
        "t", "round", "commits", "tcs", "shed", "credit", "version",
        "incidents",
    }


def test_monitor_survives_empty_snapshot():
    class EmptyTel:
        journal = None

        def snapshot(self):
            return {}

    mon = HealthMonitor(EmptyTel(), "n0", timeout_s=1.0, logger=FakeLogger())
    for t in range(6):
        assert isinstance(mon.tick(float(t)), list)


# ---- scraper side: NodeFeed / FleetWatcher / render ------------------------


class FakeNode:
    """An in-memory /delta server: a DeltaStream over a mutable doc."""

    def __init__(self, name):
        self.name = name
        self.stream = DeltaStream()
        self.sections = {
            "trace": {"commits": 0, "last_commit_round": 0},
            "ingest": {"last_credit": 64, "shed_total": 0},
            "state": {"version": 0, "root": "r0", "last_round": 0},
            "metrics": {"hotstuff_core_round": 0},
        }
        self.down = False

    def handle(self, url, timeout_s=None):
        if self.down:
            raise OSError("connection refused")
        since = int(url.rsplit("since=", 1)[1])
        return self.stream.frame({self.name: self.sections}, since)


def _fleet(n=2):
    nodes = {f"n{i}": FakeNode(f"n{i}") for i in range(n)}

    def opener(url, timeout_s=None):
        host = url.split("//", 1)[1].split(":", 1)[0]
        return nodes[host].handle(url, timeout_s)

    targets = [
        {"index": i, "name": f"n{i}", "key": i, "host": f"n{i}", "port": 1}
        for i in range(n)
    ]
    order = [f"n{i}" for i in range(n)]
    return nodes, targets, order, opener


def test_node_feed_polls_deltas_and_resyncs_on_gap():
    node = FakeNode("n0")
    from benchmark.watch import NodeFeed

    # one injected delta whose base the decoder does not hold (a
    # restarted/confused server): poll must absorb it as a resync, not
    # a wrong merge
    bogus = {"inject": None}

    def opener(url, timeout_s=None):
        frame = bogus.pop("inject", None)
        if frame is not None:
            return frame
        return node.handle(url, timeout_s)

    feed = NodeFeed("n0", "http://n0:1", opener=opener)
    state = feed.poll()
    assert state["n0.trace.commits"] == 0
    node.sections["trace"]["commits"] = 5
    state = feed.poll()
    assert state["n0.trace.commits"] == 5
    assert feed.decoder.resyncs == 0

    bogus["inject"] = {"seq": 99, "base": 98, "set": {"x": 1}, "del": []}
    state = feed.poll()
    assert state is not None  # the same poll re-pulled a full frame
    assert state["n0.trace.commits"] == 5
    assert "x" not in state
    assert feed.decoder.resyncs == 1
    assert not feed.stale


def test_node_feed_goes_stale_and_recovers():
    from benchmark.watch import STALE_AFTER, NodeFeed

    node = FakeNode("n0")
    node.down = True
    feed = NodeFeed("n0", "http://n0:1", opener=node.handle)
    for _ in range(STALE_AFTER):
        assert feed.poll() is None
    assert feed.stale
    node.down = False
    assert feed.poll() is not None
    assert not feed.stale


def test_fleet_watcher_renders_rows_and_marks_stale():
    from benchmark.watch import FleetWatcher, render

    nodes, targets, order, opener = _fleet(2)
    nodes["n0"].sections["metrics"]["hotstuff_core_round"] = 8
    nodes["n1"].sections["metrics"]["hotstuff_core_round"] = 8
    watcher = FleetWatcher(targets, order, timeout_s=1.0, opener=opener)
    try:
        view = watcher.tick(0.0)
        assert view["head"] == 8.0
        assert view["leader"] == order[8 % 2]
        text = render(view)
        assert "NODE" in text and "ROUND" in text
        assert "STALE" not in text
        assert "*" in text  # leader marker

        # n1 dies: three missed polls flip its status column
        nodes["n1"].down = True
        from benchmark.watch import STALE_AFTER

        for t in range(1, STALE_AFTER + 1):
            view = watcher.tick(float(t))
        rows = {v["name"]: v for v in view["nodes"]}
        assert rows["n1"]["stale"] is True
        assert rows["n0"]["stale"] is False
        text = render(view)
        assert "STALE" in text
        # the dead node still shows its last known round
        assert rows["n1"]["round"] == 8
    finally:
        watcher.close()


def test_fleet_watcher_detects_root_divergence_live():
    from benchmark.watch import FleetWatcher

    nodes, targets, order, opener = _fleet(2)
    for n in nodes.values():
        n.sections["state"]["version"] = 4
    nodes["n0"].sections["state"]["root"] = "a" * 32
    nodes["n1"].sections["state"]["root"] = "b" * 32
    watcher = FleetWatcher(targets, order, timeout_s=1.0, opener=opener)
    try:
        view = watcher.tick(0.0)
        kinds = {i.kind for i in view["incidents"]}
        assert "root_divergence" in kinds
        assert ("root_divergence", "") in view["open"]
        # still diverging: the incident stays open, no duplicate record
        watcher.tick(1.0)
        assert len(watcher.incidents) == 1
    finally:
        watcher.close()


def test_fleet_watcher_leader_stall_attribution():
    from benchmark.watch import FleetWatcher

    nodes, targets, order, opener = _fleet(2)
    for n in nodes.values():
        n.sections["metrics"]["hotstuff_core_round"] = 4
        n.sections["trace"]["commits"] = 10
    watcher = FleetWatcher(
        targets, order, timeout_s=0.5, stall_k=3.0, opener=opener
    )
    try:
        leader = order[4 % 2]
        for t in range(5):  # commits frozen for > 1.5 s
            view = watcher.tick(float(t))
        kinds = {(i.kind, i.node) for i in view["incidents"]}
        assert ("leader_stall", leader) in kinds
    finally:
        watcher.close()


def test_fleet_watcher_surfaces_node_reported_alerts():
    """A node's own HealthMonitor exposes its open incidents in the
    snapshot's ``health`` section; the watcher must lift them into the
    live incident feed with the detector's severity."""
    from benchmark.watch import FleetWatcher

    nodes, targets, order, opener = _fleet(2)
    nodes["n1"].sections["health"] = {"open": ["leader_stall"]}
    watcher = FleetWatcher(targets, order, timeout_s=1.0, opener=opener)
    try:
        view = watcher.tick(0.0)
        by_kind = {(i.kind, i.node): i for i in view["incidents"]}
        assert ("leader_stall", "n1") in by_kind
        assert by_kind[("leader_stall", "n1")].severity == "crit"
        assert ("leader_stall", "n1") in view["open"]
        # the node clears it: the open set empties next tick
        nodes["n1"].sections["health"] = {"open": []}
        view = watcher.tick(1.0)
        assert ("leader_stall", "n1") not in view["open"]
    finally:
        watcher.close()


def test_run_watch_once_renders_and_returns_view():
    from benchmark.watch import FleetWatcher, run_watch

    nodes, targets, order, opener = _fleet(2)

    class FakeClock:
        def __init__(self):
            self.t = 100.0

        def time(self):
            return self.t

        def sleep(self, s):
            self.t += s

    out: list = []
    watcher = FleetWatcher(targets, order, timeout_s=1.0, opener=opener)
    view = run_watch(
        watcher, once=True, out=out.append, clock=FakeClock()
    )
    assert view["nodes"]
    assert out and "NODE" in out[0]
    # a single tick has no rate window yet: the column shows "-"
    assert " - " in out[0] or "-" in out[0]


def test_node_view_extracts_metrics_with_fallbacks():
    from benchmark.watch import node_view

    flat = flatten(
        {
            "n0": {
                "trace": {
                    "commits": 12,
                    "edges": {"propose_to_commit": {"p50_ms": 4.5}},
                },
                "ingest": {"last_credit": 32, "shed_total": 2},
                "state": {"version": 3, "root": "abc", "last_round": 9},
                "metrics": {
                    "hotstuff_verify_route{route=device}": 7,
                    "hotstuff_verify_route{route=cpu}": 1,
                },
            }
        }
    )
    v = node_view("n0", flat)
    assert v["round"] == 9  # falls back to state.last_round
    assert v["commits"] == 12
    assert v["credit"] == 32
    assert v["p50_ms"] == 4.5  # falls back to the trace edge summary
    assert v["route"] == (7, 0, 1)
    assert v["version"] == 3 and v["root"] == "abc"


# ---- end to end: leader-isolation trips leader-stall (slow tier) -----------


@pytest.mark.slow
def test_leader_stall_fires_under_leader_isolation(tmp_path, monkeypatch):
    """The canned ``leader-isolation`` chaos scenario with the health
    plane on: the isolated node's commit progress freezes for longer
    than k x timeout, so a ``leader_stall`` incident must appear in the
    ``+ HEALTH`` SUMMARY block, in the journal as ``health.*`` edges,
    and as the Perfetto incidents track."""
    from benchmark.chaos import ChaosBench
    from benchmark.traces import TraceSet, load_journals, merge_campaigns
    from benchmark.utils import PathMaker

    monkeypatch.chdir(tmp_path)
    bench = ChaosBench(
        scenario="leader-isolation",
        seed=7,
        nodes=4,
        rate=400,
        duration=10.0,  # extended automatically past last heal
        timeout_delay=1_000,
        transport="asyncio",
        journal=True,
        health=True,
    )
    parser = bench.run()
    assert parser.has_window(), "no commits at all"

    # the SUMMARY surface
    assert parser.health_nodes == 4, "health monitors never announced"
    text = parser.result()
    assert "+ HEALTH" in text
    assert "leader_stall" in text, text
    assert "SLO burn" in text

    # the journal surface: health.* edges pair into incident spans and
    # land on the dedicated Perfetto incidents track
    journals = load_journals(PathMaker.journals_path())
    assert journals, "journal mode produced no journals"
    ts = TraceSet(journals)
    stall_spans = [s for s in ts.health_spans if s[1] == "leader_stall"]
    assert stall_spans, f"no leader_stall spans in {ts.health_spans}"
    doc = ts.chrome_trace()
    names = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert "incidents" in names
    assert any(
        e.get("cat") == "health" for e in doc["traceEvents"]
    ), "no incident slices emitted"

    # the campaign surface: the run outlives PERSIST_EVERY ticks, so
    # every node left a bounded ring beside its journal
    campaigns = glob.glob(
        os.path.join(PathMaker.journals_path(), f"*{CAMPAIGN_SUFFIX}")
    )
    assert campaigns, "no campaign rings persisted"
    report = merge_campaigns(
        PathMaker.journals_path(), str(tmp_path / "campaign.json")
    )
    assert report is not None
    merged = json.loads(open(report).read())
    assert merged["nodes"]
    for node in merged["nodes"]:
        assert merged["coverage"][node]["samples"] > 0


# ---- end to end: shed-storm at saturation (slow tier) ----------------------


@pytest.mark.slow
@async_test
async def test_shed_storm_fires_at_saturation(tmp_path, monkeypatch):
    """An open-loop producer past admission capacity (the exact failure
    the credit plane exists to absorb): typed BUSY sheds climb fast and
    the node's own HealthMonitor must raise ``shed_storm`` — while the
    proposer buffer still never silently drops."""
    from hotstuff_tpu.consensus import Consensus, Parameters
    from hotstuff_tpu.consensus.wire import (
        MAX_PRODUCER_BATCH,
        encode_producer_batch,
    )
    from hotstuff_tpu.crypto import Digest, SignatureService
    from hotstuff_tpu.network.framing import read_frame, write_frame
    from hotstuff_tpu.store import Store

    # a buffer this small saturates in well under a second at the
    # open-loop rate below; the low watermark makes sheds typed BUSY
    monkeypatch.setenv("HOTSTUFF_MAX_PENDING", "200")
    monkeypatch.setenv("HOTSTUFF_INGEST_WATERMARK", "0.5")
    telemetry.enable()

    base = fresh_base_port()
    com = committee(base)
    nodes = []
    for i in range(4):
        name, secret = keys()[i]
        store = Store(str(tmp_path / f"db_{i}"))
        commit_q: asyncio.Queue = asyncio.Queue()
        stack = await Consensus.spawn(
            name,
            com,
            Parameters(timeout_delay=2_000, sync_retry_delay=5_000),
            SignatureService(secret),
            store,
            commit_q,
            bind_host="127.0.0.1",
            telemetry=telemetry.for_node(f"n{i}"),
        )
        nodes.append((stack, commit_q, store))

    async def drain(q: asyncio.Queue):
        while True:
            await q.get()

    drains = [asyncio.ensure_future(drain(q)) for _, q, _ in nodes]
    loop = asyncio.get_running_loop()
    tel0 = telemetry.for_node("n0")
    # a huge timeout keeps leader_stall quiet; this test is about sheds
    mon = HealthMonitor(tel0, "n0", timeout_s=60.0, logger=FakeLogger())
    sink = None
    writer = None
    try:
        mon.tick(loop.time())

        reader, writer = await asyncio.open_connection("127.0.0.1", base)

        async def discard():
            while True:
                await read_frame(reader)

        sink = asyncio.ensure_future(discard())

        # ~2x+ admission capacity, credits deliberately ignored: 40
        # batches x 128 unique payloads against a 200-slot buffer
        seq = 0
        for _ in range(40):
            items = []
            for _ in range(min(128, MAX_PRODUCER_BATCH)):
                body = seq.to_bytes(8, "big") + b"x" * 56
                items.append((Digest.of(body), body))
                seq += 1
            write_frame(writer, encode_producer_batch(items))
            await writer.drain()
            await asyncio.sleep(0.02)

        fired: list = []
        deadline = loop.time() + 10.0
        while loop.time() < deadline:
            await asyncio.sleep(0.5)
            fired.extend(mon.tick(loop.time()))
            if any(i.kind == "shed_storm" for i in fired):
                break
        kinds = {i.kind for i in fired}
        snap = tel0.snapshot()
        assert "shed_storm" in kinds, (
            f"no shed_storm under open-loop saturation; fired={kinds}, "
            f"ingest={snap.get('ingest')}"
        )
        assert snap["ingest"]["shed_total"] >= 10

        # admission control absorbed the storm: nothing silently lost
        stack0 = nodes[0][0]
        assert stack0.proposer.drop_newest == 0
        assert len(stack0.proposer.pending) <= stack0.proposer.max_pending
    finally:
        if sink is not None:
            sink.cancel()
        if writer is not None:
            writer.close()
        for t in drains:
            t.cancel()
        for stack, _, _ in nodes:
            await stack.shutdown()
        for _, _, store in nodes:
            store.close()
