"""Node-layer tests: config round-trips, CLI keygen, full node boot with a
client driving the producer path end-to-end.
"""

import asyncio
import os

from hotstuff_tpu.consensus import Committee, Parameters
from hotstuff_tpu.node import (
    Secret,
    read_committee,
    read_parameters,
    write_committee,
    write_parameters,
)
from hotstuff_tpu.node.client import run_client
from hotstuff_tpu.node.main import main as node_main
from hotstuff_tpu.node.node import Node

from .common import async_test, fresh_base_port, keys


def test_secret_roundtrip(tmp_path):
    path = str(tmp_path / "node.json")
    secret = Secret.new()
    secret.write(path)
    again = Secret.read(path)
    assert again.name == secret.name
    assert again.secret.to_bytes() == secret.secret.to_bytes()
    # keypair files must not be world-readable
    assert os.stat(path).st_mode & 0o077 == 0


def test_committee_and_parameters_roundtrip(tmp_path):
    com_path = str(tmp_path / "committee.json")
    par_path = str(tmp_path / "parameters.json")
    committee = Committee.new(
        [(pk, 1, ("127.0.0.1", 7000 + i)) for i, (pk, _) in enumerate(keys())]
    )
    write_committee(committee, com_path)
    again = read_committee(com_path)
    assert again.authorities.keys() == committee.authorities.keys()
    assert again.quorum_threshold() == committee.quorum_threshold()

    write_parameters(Parameters(timeout_delay=1234), par_path)
    assert read_parameters(par_path).timeout_delay == 1234


def test_cli_keys(tmp_path):
    path = str(tmp_path / "k.json")
    assert node_main(["keys", "--filename", path]) == 0
    assert Secret.read(path).name is not None


@async_test
async def test_node_boot_and_client_commits(tmp_path):
    """Boot a full 4-node committee via Node.new and drive it with the
    producer-path client; every node commits."""
    base = fresh_base_port()
    com_path = str(tmp_path / "committee.json")
    committee = Committee.new(
        [(pk, 1, ("127.0.0.1", base + i)) for i, (pk, _) in enumerate(keys())]
    )
    write_committee(committee, com_path)
    par_path = str(tmp_path / "parameters.json")
    write_parameters(Parameters(timeout_delay=1_000, sync_retry_delay=5_000), par_path)

    nodes = []
    for i, (pk, sk) in enumerate(keys()):
        key_path = str(tmp_path / f"node_{i}.json")
        Secret(pk, sk).write(key_path)
        node = await Node.new(
            committee_file=com_path,
            key_file=key_path,
            store_path=str(tmp_path / f"db_{i}"),
            parameters_file=par_path,
            bind_host="127.0.0.1",
        )
        nodes.append(node)

    addresses = [a.address for a in committee.authorities.values()]
    client = asyncio.ensure_future(
        run_client(addresses, rate=100, duration=15.0, warmup=0.0)
    )
    try:
        for node in nodes:
            committed = await asyncio.wait_for(node.commit.get(), timeout=15.0)
            while committed.round == 0:
                committed = await asyncio.wait_for(node.commit.get(), timeout=15.0)
            assert committed.round >= 1
    finally:
        client.cancel()
        for node in nodes:
            await node.shutdown()


def test_lazy_device_verifier_routes_without_jax():
    """Small batches route to CPU without materializing the device
    backend (importing jax costs seconds per node process — the lazy
    wrapper exists so small committees never pay it)."""
    import sys

    from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
    from hotstuff_tpu.node.node import LazyDeviceVerifier

    v = LazyDeviceVerifier("tpu")
    pk, sk = generate_keypair(b"\x11" * 32, 3)
    d = Digest.of(b"lazy-verifier probe")
    sig = Signature.new(d, sk)

    assert v.verify_one(d, pk, sig)
    assert v.verify_shared_msg(d, [(pk, sig)] * 3)
    assert v.verify_many(
        [d.to_bytes()] * 2, [pk.to_bytes()] * 2, [sig.to_bytes()] * 2
    ) == [True, True]
    # the device backend was never constructed for sub-threshold batches
    assert v._device is None
    # precompute is deferred, not lost
    v.precompute([pk.to_bytes()])
    assert v._precomputed and v._device is None


@async_test
async def test_client_conn_connect_is_cancellation_safe(monkeypatch):
    """ADVICE r2 (client.py try_reconnect): the fd-leak race is the
    cancel landing AT the ``await open_connection`` when the open has
    already completed — the task machinery drops the (reader, writer)
    result.  Reproduce it deterministically: let the inner open task
    complete, cancel the connect task before its wakeup is processed,
    and assert the orphaned transport is closed."""
    closed = []

    class FakeWriter:
        def close(self):
            closed.append(True)

    async def fake_open_connection(*a, **k):
        return object(), FakeWriter()

    monkeypatch.setattr(asyncio, "open_connection", fake_open_connection)
    from hotstuff_tpu.node.client import _NodeConn

    conn = _NodeConn(("127.0.0.1", 1))
    task = asyncio.ensure_future(conn.connect())
    await asyncio.sleep(0)  # connect() starts, suspends on open_task
    await asyncio.sleep(0)  # open_task completes; connect wakeup queued
    task.cancel()  # delivered at the await: the completed result orphans
    try:
        await task
    except asyncio.CancelledError:
        pass
    await asyncio.sleep(0)  # let the reaper done-callback run
    assert closed == [True]
    assert conn.writer is None and not conn.alive


def test_client_rejects_sub_counter_size(tmp_path):
    """Advisor r4: 0 < --size < 8 would silently send 8-byte bodies (the
    uniqueness counter) while the harness reports BPS from the requested
    size — the client refuses the misreporting configuration."""
    import pytest

    from hotstuff_tpu.node.client import main as client_main

    com_path = str(tmp_path / "committee.json")
    committee = Committee.new(
        [(pk, 1, ("127.0.0.1", 9900 + i)) for i, (pk, _) in enumerate(keys())]
    )
    write_committee(committee, com_path)
    for bad in (1, 7):
        with pytest.raises(SystemExit):
            client_main(
                ["--committee", com_path, "--size", str(bad), "--duration", "0"]
            )
